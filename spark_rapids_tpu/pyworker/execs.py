"""Pandas-UDF physical execs over Arrow IPC worker processes.

Reference analog (SURVEY.md §2d "Pandas/Python execs (×7)"):
``GpuArrowEvalPythonExec`` (658 LoC), ``GpuMapInPandasExec``,
``GpuFlatMapGroupsInPandasExec``, ``GpuFlatMapCoGroupsInPandasExec``,
``GpuAggregateInPandasExec``, ``GpuWindowInPandasExec`` under
``sql-plugin/.../execution/python/``.  Shared plumbing:
``RebatchingRoundoffIterator`` (match the UDF's requested batch rows) and
``BatchQueue`` (pair inputs with worker outputs)
(GpuArrowEvalPythonExec.scala:58,178).

These are host-currency execs (pyarrow tables in/out).  The device path
is the transitions the planner already inserts: a TPU subtree ends in
DeviceToHostExec, the exec streams Arrow IPC to the worker — the same
wire the reference puts directly on the socket from device memory
(Table.writeArrowIPCChunked, GpuArrowEvalPythonExec.scala:422-435) — and
the next TPU subtree re-uploads.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.exec.base import PhysicalPlan, timed
from spark_rapids_tpu.expr import eval_cpu, ir
from spark_rapids_tpu.plan.logical import Field, Schema
from spark_rapids_tpu.pyworker.pool import borrowed_worker


# ---------------------------------------------------------------------------
# Shared plumbing
# ---------------------------------------------------------------------------

class RebatchingRoundoffIterator:
    """Re-slice an input stream into batches of exactly ``target_rows``
    (except the final remainder) —
    GpuArrowEvalPythonExec.scala:58 RebatchingRoundoffIterator."""

    def __init__(self, it: Iterator[pa.Table], target_rows: int):
        self._it = it
        self.target_rows = max(int(target_rows), 1)
        self._pending: List[pa.Table] = []
        self._pending_rows = 0

    def __iter__(self):
        return self

    def __next__(self) -> pa.Table:
        while self._pending_rows < self.target_rows:
            try:
                t = next(self._it)
            except StopIteration:
                if self._pending_rows == 0:
                    raise
                out = pa.concat_tables(self._pending)
                self._pending, self._pending_rows = [], 0
                return out
            if t.num_rows:
                self._pending.append(t)
                self._pending_rows += t.num_rows
        whole = pa.concat_tables(self._pending)
        out = whole.slice(0, self.target_rows)
        rest = whole.slice(self.target_rows)
        self._pending = [rest] if rest.num_rows else []
        self._pending_rows = rest.num_rows
        return out


class BatchQueue:
    """Pairs each input batch with the worker's output for it
    (GpuArrowEvalPythonExec.scala:178)."""

    def __init__(self):
        self._q: List[pa.Table] = []

    def push(self, t: pa.Table) -> None:
        self._q.append(t)

    def pop_pair(self, result: pa.Table) -> Tuple[pa.Table, pa.Table]:
        inp = self._q.pop(0)
        if inp.num_rows != result.num_rows:
            raise ValueError(
                f"python worker returned {result.num_rows} rows for a "
                f"{inp.num_rows}-row batch")
        return inp, result


def _cast_result(col: pa.ChunkedArray | pa.Array,
                 want: dt.DType) -> pa.Array:
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    target = want.to_arrow()
    if arr.type != target:
        arr = arr.cast(target)
    return arr


def _schema_to_arrow(schema: Schema) -> pa.Schema:
    return pa.schema([pa.field(f.name, f.dtype.to_arrow(), f.nullable)
                      for f in schema.fields])


def _conform(t: pa.Table, schema: Schema) -> pa.Table:
    """Cast/rename a worker result to the declared output schema."""
    if t.num_columns != len(schema):
        raise ValueError(
            f"python worker returned {t.num_columns} columns, declared "
            f"schema has {len(schema)}")
    cols = [_cast_result(t.column(i), f.dtype)
            for i, f in enumerate(schema.fields)]
    return pa.table(dict(zip(schema.names, cols)),
                    schema=_schema_to_arrow(schema))


def _eval_args(args: Sequence[ir.Expression], t: pa.Table) -> pa.Table:
    cols = {}
    for i, e in enumerate(args):
        v = eval_cpu.evaluate(e, t)
        cols[f"_a{i}"] = eval_cpu.to_arrow_array(v)
    return pa.table(cols) if cols else t.select([])


# ---------------------------------------------------------------------------
# ArrowEvalPython: scalar pandas UDFs inside projections
# ---------------------------------------------------------------------------

class CpuArrowEvalPythonExec(PhysicalPlan):
    """GpuArrowEvalPythonExec analog: evaluates vectorized PythonUDFs via
    the worker, emitting child output + one column per UDF."""

    def __init__(self, child: PhysicalPlan,
                 udfs: List[Tuple[str, ir.PythonUDF]],
                 batch_rows: int = 10_000):
        super().__init__()
        self.children = (child,)
        self.udfs = udfs
        self.batch_rows = batch_rows
        base = child.schema
        self._schema = Schema(
            list(base.fields) +
            [Field(name, u.return_type, True) for name, u in udfs])

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        import contextlib

        def eval_one(w, u, t: pa.Table) -> pa.Array:
            args = _eval_args(list(u.children), t)
            res = w.run_table(args)
            if res.num_rows != t.num_rows:
                raise ValueError(
                    f"python worker returned {res.num_rows} rows for a "
                    f"{t.num_rows}-row batch")
            return _cast_result(res.column(0), u.return_type)

        def run(it) -> Iterator[pa.Table]:
            rebatch = RebatchingRoundoffIterator(it, self.batch_rows)
            with contextlib.ExitStack() as stack:
                # single-UDF fast path holds one worker for the whole
                # partition (no per-batch handshake); multiple UDFs borrow
                # per batch so fan-out can never exceed the pool permits
                hoisted = None
                if len(self.udfs) == 1:
                    hoisted = stack.enter_context(
                        borrowed_worker("series", self.udfs[0][1].func))
                for t in rebatch:
                    merged = t
                    for name, u in self.udfs:
                        if hoisted is not None:
                            col = eval_one(hoisted, u, t)
                        else:
                            with borrowed_worker("series", u.func) as w:
                                col = eval_one(w, u, t)
                        merged = merged.append_column(
                            pa.field(name, col.type, True), col)
                    self.metrics.num_output_rows += merged.num_rows
                    self.metrics.add_batches()
                    yield merged
        return [run(it) for it in self.children[0].execute()]


# ---------------------------------------------------------------------------
# MapInPandas
# ---------------------------------------------------------------------------

class CpuMapInPandasExec(PhysicalPlan):
    """GpuMapInPandasExec analog: fn(pdf) -> pdf per batch."""

    def __init__(self, child: PhysicalPlan, fn: Callable, schema: Schema,
                 batch_rows: int = 10_000):
        super().__init__()
        self.children = (child,)
        self.fn = fn
        self._schema = schema
        self.batch_rows = batch_rows

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        def run(it) -> Iterator[pa.Table]:
            rebatch = RebatchingRoundoffIterator(it, self.batch_rows)
            with borrowed_worker("table", self.fn) as w:
                for t in rebatch:
                    out = _conform(w.run_table(t), self._schema)
                    self.metrics.num_output_rows += out.num_rows
                    self.metrics.add_batches()
                    yield out
        return [run(it) for it in self.children[0].execute()]


# ---------------------------------------------------------------------------
# Grouped execs
# ---------------------------------------------------------------------------

def _collect_partition(it: Iterator[pa.Table]) -> Optional[pa.Table]:
    parts = [t for t in it if t.num_rows]
    if not parts:
        return None
    return pa.concat_tables(parts)


class _NanKey:
    """Canonical NaN grouping key: Spark groups all NaNs together, but
    float('nan') != float('nan') breaks dict/set matching across cogroup
    sides — so NaN keys are frozen to this singleton for matching and
    thawed back to NaN for output."""

    __slots__ = ()

    def __eq__(self, other):
        return isinstance(other, _NanKey)

    def __hash__(self):
        return 0x7FF8

    def __repr__(self):
        return "NaN"


_NAN_KEY = _NanKey()


def _freeze_key_val(v):
    if isinstance(v, float) and np.isnan(v):
        return _NAN_KEY
    return v


def _thaw_key_val(v):
    return float("nan") if isinstance(v, _NanKey) else v


def _key_sort_token(v):
    """Total order over frozen key values incl. None/NaN (nulls last,
    NaN after numbers — Spark ordering)."""
    if v is None:
        return (2, 0, "")
    if isinstance(v, _NanKey):
        return (1, 0, "")
    return (0, 0, v)


def _group_slices(t: pa.Table, keys: Sequence[str]
                  ) -> Iterator[Tuple[tuple, pa.Table]]:
    """Stable group iteration: sort by keys, emit contiguous slices.

    Keys come from ``to_pylist`` (None preserved — no pandas NaN coercion
    of null integer keys) and are frozen via ``_freeze_key_val``."""
    import pyarrow.compute as pc
    # group contiguity only needs nulls sorted together; placement is
    # irrelevant, so the deprecated null_placement option is not used
    idx = pc.sort_indices(t, sort_keys=[(k, "ascending") for k in keys])
    s = t.take(idx)
    key_cols = [[_freeze_key_val(v) for v in s.column(k).to_pylist()]
                for k in keys]
    n = s.num_rows
    start = 0
    for i in range(1, n + 1):
        if i == n or any(
                not _key_eq(col[i], col[i - 1]) for col in key_cols):
            key = tuple(col[start] for col in key_cols)
            yield key, s.slice(start, i - start)
            start = i


def _key_eq(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    return a == b


class CpuFlatMapGroupsInPandasExec(PhysicalPlan):
    """GpuFlatMapGroupsInPandasExec analog: fn(group_pdf) -> pdf."""

    def __init__(self, child: PhysicalPlan, keys: List[str], fn: Callable,
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.keys = keys
        self.fn = fn
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        def run() -> Iterator[pa.Table]:
            parts = []
            for it in self.children[0].execute():
                g = _collect_partition(it)
                if g is not None:
                    parts.append(g)
            if not parts:
                return
            whole = pa.concat_tables(parts)
            outs = []
            with borrowed_worker("table", self.fn) as w:
                for _key, grp in _group_slices(whole, self.keys):
                    outs.append(_conform(w.run_table(grp), self._schema))
            if outs:
                out = pa.concat_tables(outs)
                self.metrics.num_output_rows += out.num_rows
                self.metrics.add_batches()
                yield out
        return [run()]


class CpuFlatMapCoGroupsInPandasExec(PhysicalPlan):
    """GpuFlatMapCoGroupsInPandasExec analog:
    fn(left_group_pdf, right_group_pdf) -> pdf over the co-grouped keys."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 left_keys: List[str], right_keys: List[str], fn: Callable,
                 schema: Schema):
        super().__init__()
        self.children = (left, right)
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.fn = fn
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        def run() -> Iterator[pa.Table]:
            sides = []
            for child, keys in ((self.children[0], self.left_keys),
                                (self.children[1], self.right_keys)):
                parts = []
                for it in child.execute():
                    g = _collect_partition(it)
                    if g is not None:
                        parts.append(g)
                groups = {}
                if parts:
                    whole = pa.concat_tables(parts)
                    for key, grp in _group_slices(whole, keys):
                        groups[key] = grp
                    empty = whole.slice(0, 0)
                else:
                    # PySpark passes an EMPTY frame for the missing side,
                    # never skips the group
                    empty = _schema_to_arrow(child.schema).empty_table()
                sides.append((groups, empty))
            (lgroups, lempty), (rgroups, rempty) = sides
            all_keys = sorted(set(lgroups) | set(rgroups),
                              key=lambda k: tuple(_key_sort_token(v)
                                                  for v in k))
            outs = []
            with borrowed_worker("cogroup", self.fn) as w:
                for key in all_keys:
                    lt = lgroups.get(key, lempty)
                    rt = rgroups.get(key, rempty)
                    outs.append(_conform(w.run_cogroup(lt, rt),
                                         self._schema))
            if outs:
                out = pa.concat_tables(outs)
                self.metrics.num_output_rows += out.num_rows
                self.metrics.add_batches()
                yield out
        return [run()]


class CpuAggregateInPandasExec(PhysicalPlan):
    """GpuAggregateInPandasExec analog: fn(*series) -> scalar per group;
    output = group keys + result column."""

    def __init__(self, child: PhysicalPlan, keys: List[str], fn: Callable,
                 args: List[ir.Expression], out_field: Field):
        super().__init__()
        self.children = (child,)
        self.keys = keys
        self.fn = fn
        self.args = args
        self.out_field = out_field
        base = child.schema
        self._schema = Schema(
            [base.field(k) for k in keys] + [out_field])

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        def run() -> Iterator[pa.Table]:
            parts = []
            for it in self.children[0].execute():
                g = _collect_partition(it)
                if g is not None:
                    parts.append(g)
            if not parts:
                return
            whole = pa.concat_tables(parts)
            key_rows: List[tuple] = []
            results: List = []
            with borrowed_worker("agg_series", self.fn) as w:
                for key, grp in _group_slices(whole, self.keys):
                    args = _eval_args(self.args, grp)
                    res = w.run_table(args)
                    key_rows.append(key)
                    results.append(res.column(0)[0].as_py())
            cols = {}
            for i, k in enumerate(self.keys):
                f = self._schema.field(k)
                cols[k] = pa.array([_thaw_key_val(r[i]) for r in key_rows],
                                   type=f.dtype.to_arrow())
            cols[self.out_field.name] = pa.array(
                results, type=self.out_field.dtype.to_arrow())
            out = pa.table(cols, schema=_schema_to_arrow(self._schema))
            self.metrics.num_output_rows += out.num_rows
            self.metrics.add_batches()
            yield out
        return [run()]


class CpuWindowInPandasExec(PhysicalPlan):
    """GpuWindowInPandasExec analog, unbounded-frame case: fn(*series)
    evaluated once per partition, broadcast to every row (the reference
    computes pandas window UDFs over whole partitions the same way for
    unbounded frames, WindowInPandasExec)."""

    def __init__(self, child: PhysicalPlan, part_keys: List[str],
                 fn: Callable, args: List[ir.Expression], out_field: Field):
        super().__init__()
        self.children = (child,)
        self.part_keys = part_keys
        self.fn = fn
        self.args = args
        self.out_field = out_field
        base = child.schema
        self._schema = Schema(list(base.fields) + [out_field])

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        def run() -> Iterator[pa.Table]:
            parts = []
            for it in self.children[0].execute():
                g = _collect_partition(it)
                if g is not None:
                    parts.append(g)
            if not parts:
                return
            whole = pa.concat_tables(parts)
            outs = []
            with borrowed_worker("agg_series", self.fn) as w:
                for _key, grp in _group_slices(whole, self.part_keys):
                    args = _eval_args(self.args, grp)
                    res = w.run_table(args).column(0)[0].as_py()
                    col = pa.array([res] * grp.num_rows,
                                   type=self.out_field.dtype.to_arrow())
                    outs.append(grp.append_column(
                        pa.field(self.out_field.name, col.type, True), col))
            out = pa.concat_tables(outs)
            self.metrics.num_output_rows += out.num_rows
            self.metrics.add_batches()
            yield out
        return [run()]
