"""In-memory table scan over cached parquet blobs (df.cache()).

Reference: ``ParquetCachedBatchSerializer`` stores each cached batch as a
device-encoded parquet blob (``compressColumnarBatchWithParquet``,
shims/spark310/.../ParquetCachedBatchSerializer.scala:333) and
``GpuInMemoryTableScanExec`` (GpuInMemoryTableScanExec.scala:115) decodes
them back on device, with a CPU iterator fallback.  Here:

  * materialization runs the child plan through the full override
    pipeline once and parquet-encodes each output partition (host Arrow
    encode — the documented delta),
  * ``TpuInMemoryTableScanExec`` decodes blobs straight into HBM via the
    same device parquet decoder as file scans (per-column host fallback
    included),
  * ``CpuInMemoryTableScanExec`` is the pure-CPU read used when the TPU
    plan is disabled or the scan is kill-switched off.
"""

from __future__ import annotations

import io
from typing import Iterator, List

import pyarrow as pa
import pyarrow.parquet as papq

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.exec.base import PhysicalPlan, TpuExec, timed
from spark_rapids_tpu.mem.device import tpu_semaphore
from spark_rapids_tpu.plan.logical import CachedRelation, Schema


def materialize(relation: CachedRelation, conf) -> None:
    """Build the cache: run the child plan once, encode each partition
    as one parquet blob (single row group, so device decode sees the
    same page layout as a file scan).

    When the child plan ends on device, batches are encoded by the
    DEVICE parquet encoder (reference:
    ParquetCachedBatchSerializer.scala:333
    compressColumnarBatchWithParquet encodes cached batches on GPU);
    otherwise host Arrow encodes."""
    if relation.materialized:
        return
    relation._blob_keys = None   # content digests memoized per build
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    from spark_rapids_tpu.plan.planner import plan_cpu
    from spark_rapids_tpu.exec.cpu import concat_tables

    cpu_plan = plan_cpu(relation.children[0], conf)
    result = TpuOverrides.apply(cpu_plan, conf)
    from spark_rapids_tpu.exec.cpu import _empty_table
    codec = str(conf.get(cfg.CACHE_COMPRESSION))
    relation.device_encoded = False

    from spark_rapids_tpu.exec.tpu_basic import DeviceToHostExec
    from spark_rapids_tpu.io import parquet_encode as pqe
    if (conf.get(cfg.CACHE_DEVICE_ENCODE) and
            isinstance(result.plan, DeviceToHostExec) and
            pqe.supported(result.plan.schema.fields) and
            codec in ("snappy", "zstd", "none", "uncompressed")):
        from spark_rapids_tpu.columnar.batch import concat_batches
        blobs: List[bytes] = []
        for it in result.plan.children[0].execute():
            batches = [b for b in it if int(b.num_rows)]
            if batches:
                whole = concat_batches(batches) if len(batches) > 1 \
                    else batches[0]
                blobs.append(pqe.encode_batch(whole, codec=codec))
            else:
                buf = io.BytesIO()
                papq.write_table(_empty_table(relation.schema), buf,
                                 compression=codec)
                blobs.append(buf.getvalue())
        if blobs:
            relation.blobs = blobs
            relation.device_encoded = True
            return

    blobs = []
    for it in result.plan.execute():
        tables = [t for t in it]
        # empty partitions cache as empty blobs so the cached relation
        # keeps the child's partition count (spark_partition_id /
        # monotonically_increasing_id stay cache-transparent)
        t = concat_tables(tables, result.plan.schema) if tables \
            else _empty_table(relation.schema)
        buf = io.BytesIO()
        papq.write_table(t, buf, compression=codec,
                         row_group_size=max(t.num_rows, 1))
        blobs.append(buf.getvalue())
    if not blobs:
        t = _empty_table(relation.schema)
        buf = io.BytesIO()
        papq.write_table(t, buf, compression=codec)
        blobs.append(buf.getvalue())
    relation.blobs = blobs


class CpuInMemoryTableScanExec(PhysicalPlan):
    """Host-side cached read (InMemoryTableScan CPU fallback analog)."""

    is_tpu = False

    def __init__(self, relation: CachedRelation, conf):
        super().__init__()
        self.relation = relation
        self.conf = conf

    @property
    def schema(self) -> Schema:
        return self.relation.schema

    def execute(self):
        materialize(self.relation, self.conf)

        def part(blob: bytes) -> Iterator[pa.Table]:
            yield papq.read_table(io.BytesIO(blob))

        return [part(b) for b in self.relation.blobs]

    def simple_string(self) -> str:
        return (f"CpuInMemoryTableScanExec("
                f"partitions={len(self.relation.blobs or [])})")


class TpuInMemoryTableScanExec(TpuExec):
    """Device-decoding cached read (GpuInMemoryTableScanExec analog)."""

    def __init__(self, relation: CachedRelation, conf):
        super().__init__()
        self.relation = relation
        self.conf = conf
        self.metrics.extra["fallbackColumns"] = 0

    @property
    def schema(self) -> Schema:
        return self.relation.schema

    def execute(self):
        from spark_rapids_tpu.io import device_parquet as devpq
        from spark_rapids_tpu.io import scan_cache as sc
        materialize(self.relation, self.conf)
        schema = self.schema
        # blob decodes reuse the scan-plan cache (content-keyed): a
        # re-collected cached relation skips the page walks.  Digests
        # memoize on the relation — blobs are immutable, so K collects
        # must not pay K full-blob sha1 passes
        keys = getattr(self.relation, "_blob_keys", None)
        if keys is None or len(keys) != len(self.relation.blobs):
            keys = [sc.blob_key(b) for b in self.relation.blobs]
            self.relation._blob_keys = keys

        def part(blob: bytes, skey):
            pf = sc.blob_footer(blob)
            if not sc.enabled():
                skey = None
            for rg in range(pf.metadata.num_row_groups):
                with tpu_semaphore(self.metrics):
                    with timed(self.metrics, "cache.decode"):
                        batch, fallbacks = devpq.decode_row_group(
                            blob, rg, schema, parquet_file=pf,
                            source_key=skey, metrics=self.metrics)
                    self.metrics.extra["fallbackColumns"] += \
                        len(fallbacks)
                    self.metrics.add_rows(batch.num_rows)
                    self.metrics.add_batches()
                    yield batch

        return [part(b, k) for b, k in zip(self.relation.blobs, keys)]

    def simple_string(self) -> str:
        return (f"TpuInMemoryTableScanExec("
                f"partitions={len(self.relation.blobs or [])})")
