"""Whole-stage fused exec: one kernel for a collapsed Project/Filter chain.

The per-node execution model pays one jitted dispatch per exec per batch
(~72 ms each on the tunneled runtime, PERF.md) and materializes full
padded intermediate columns in HBM between every Project/Filter.
``TpuFusedStageExec`` is the engine's whole-stage-codegen analog
(reference: Spark's WholeStageCodegenExec; the reference plugin's tiered
project / combined filter-project, basicPhysicalOperators.scala): the
planner pass in :mod:`spark_rapids_tpu.plan.fusion` collapses a maximal
chain of dispatch-only execs into one node whose single cached kernel

  1. evaluates the AND-combination of every filter condition in the
     chain (each rewritten over the stage INPUT schema, so conditions
     from different chain depths compose without materializing the
     columns between them),
  2. performs at most ONE stream compaction, and
  3. evaluates the composed output projection — a fused filter->project
     pays zero intermediate materialization.  Projection and compaction
     order per stage by WIDTH: compaction costs one full-capacity
     scatter per column (the engine's dominant compaction cost, see the
     ``agg.fusedFilter`` rationale in config.py), so when the composed
     output is narrower than the stage input the kernel projects first
     and compacts only the output columns; otherwise it compacts the
     input first.  Both orders are sound — every fusable expression is
     row-wise, so evaluating it on rows the filter drops is harmless
     (see below) and ``compact``'s keep-mask applies unchanged on
     either side of the projection.

Rewriting upper-chain expressions over the stage input is sound because
every fusable expression is row-wise and position-independent (the
fusion pass bars MonotonicallyIncreasingID / Rand from chains — their
values depend on row position, which compaction changes); evaluating a
condition on rows a lower filter would have dropped is harmless under
the engine's total-function semantics (x/0 is NULL, never a fault), and
AND is commutative, so the combined keep-set is exactly the chain's.

A stage whose composed projection is pure column selection (every
output a BoundReference, no condition) runs in **passthrough** mode:
zero dispatches, host-side column pick/rename only — the common
``prune_columns`` select below a sort/window stops costing a kernel
launch entirely.

Input-buffer donation (``sql.fusion.donateInputs``, stamped per-plan
by ``TpuOverrides.apply`` as ``_donate_enabled`` on every node): when
the producing exec is known not to retain its yielded batches, the
stage (and the plain project/filter execs) jits with ``donate_argnums``
so XLA reuses the input batch's HBM for the output — deep chains stop
holding two copies of every intermediate.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.exec.base import PhysicalPlan, TpuExec, timed
from spark_rapids_tpu.expr import eval_tpu, ir
from spark_rapids_tpu.plan.logical import Schema

_warn_filter_installed = False


def _install_donation_warn_filter() -> None:
    """jax warns per-compile when a donated buffer's shape has no
    output to reuse it for (e.g. a string column whose max_len bucket
    changed); partial reuse is exactly the intent, so the warning is
    noise — but only processes that actually build a donating kernel
    should mutate the global warnings filter (an import side effect
    would suppress it for the user's own unrelated jax code too)."""
    global _warn_filter_installed
    if not _warn_filter_installed:
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        _warn_filter_installed = True

# producers whose yielded batches are fresh per batch and never
# re-served (caches, broadcast builds and shuffle catalogs may alias
# buffers they hand out — donating those would corrupt a later read)
_DONATE_SAFE_PRODUCERS = frozenset({
    "HostToDeviceExec", "TpuProjectExec", "TpuFilterExec",
    "TpuFusedStageExec", "TpuRangeExec", "TpuParquetScanExec",
    "TpuOrcScanExec", "TpuCsvScanExec",
})


def _persistent_cache_active() -> bool:
    """Is a persistent XLA compilation cache dir configured?  Donation
    used to AUTO-DISARM while one was (an executable RELOADED from the
    cache mis-applies the donate_argnums aliasing table on jax 0.4.37 —
    identity-shaped outputs read the WRONG donated input buffer;
    minimal repro: jit ``lambda ai, af, p: (ai + 0, af * 1.0, ...)``
    with ``donate_argnums=(0,)``; run 2 of 2 processes returns ``af``'s
    bits inside the ``ai + 0`` output — pinned by
    tests/test_fusion.test_donation_persistent_cache_repro).  Donation
    now stays armed: donating kernels compile inside
    ``kernel_cache._no_persistent_cache`` — never written to nor
    reloaded from the cache — so steady state gets donation AND warm
    compiles for every other program.  This predicate remains as the
    guard's (and the regression tests') one definition of "a cache dir
    is configured"."""
    try:
        import jax
        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return True  # unknown state: assume a cache could be active


def donate_ok(child: PhysicalPlan, enabled: bool) -> bool:
    """May a consumer donate the batches ``child`` yields?

    ``enabled`` is the consumer's PLAN-STAMPED donation flag
    (``sql.fusion.donateInputs``, stamped on every node by
    ``TpuOverrides.apply``): each session's plans carry their own
    setting, so a later session with a different conf cannot flip an
    earlier session's behavior, and plan fragments shipped to executor
    processes (shuffle/executor_proc.py) honor the driver's conf with
    no pickled-conf side channel.  An un-stamped plan (hand-built in a
    test) never donates.

    A passthrough fused stage forwards its child's column buffers BY
    REFERENCE (zero-dispatch host-side pick), so the donation decision
    must look through it to the transitive producer — a pure select
    over a cache/shuffle read must not launder those aliased buffers
    into the donate-safe set.  A passthrough that DUPLICATES a column
    (select(a, a.alias(a2))) yields the same device array as two batch
    leaves; donating that batch is an XLA error ("attempt to donate the
    same buffer twice"), so it bars donation outright.  Only the
    host-side passthrough pick can introduce such leaf aliasing: a
    KERNEL-produced batch never does — XLA's copy-insertion guarantees
    entry-computation output leaves are distinct buffers even when two
    outputs compute the same value (checked empirically on this jax:
    jit(lambda x: (x*2, x*2)) returns distinct buffer pointers)."""
    if not enabled:
        return False
    while isinstance(child, TpuFusedStageExec) and child.is_passthrough:
        ords = [e.ordinal for e in child.out_exprs]
        if len(set(ords)) < len(ords):
            return False
        child = child.children[0]
    # shared-scan multicast (io/scan_share): a fused parquet scan with
    # sharing enabled may hand the SAME decoded batch to several
    # queries and retains it in the multicast window — donating such a
    # batch would invalidate every other holder's copy.  The bar used
    # to be static (any shared-capable scan barred every batch); it is
    # now per-batch: the scan stamps each yielded batch with its share
    # entry and ``dispatch`` donates only after ``ScanShare.try_steal``
    # proves this pipeline is the batch's sole holder — solo scans
    # recover donation, genuinely multicast batches stay barred.
    return type(child).__name__ in _DONATE_SAFE_PRODUCERS


def batch_donate_ok(b: DeviceBatch, reg) -> bool:
    """Per-batch half of the donation decision (see donate_ok): True
    unless ``b`` is a shared-scan batch some other query holds (or may
    yet claim from the retention window)."""
    e = getattr(b, "_scan_share_entry", None)
    if e is None:
        return True
    from spark_rapids_tpu.io import scan_share
    share = scan_share.peek_share()
    if share is not None and share.try_steal(e):
        reg.inc("fusion.donationsRecovered")
        return True
    reg.inc("fusion.donationsBarred")
    return False


def rows_detached(b: DeviceBatch) -> DeviceBatch:
    """Shallow copy whose ``num_rows`` leaf is a dummy zero — the
    donated argument to a kernel.  The real count rides as a separate
    NON-donated argument: producers lazily buffer their output's
    ``num_rows`` device scalar in ``Metrics._rows_pending`` (exec/base
    ``add_rows``), and XLA invalidates every leaf of a donated pytree,
    so donating the count would leave the metric pointing at a deleted
    array (resolution then raises, or silently loses the per-node row
    counts in the query profile)."""
    d = DeviceBatch(b.names, b.columns, 0)
    d._capacity = b._capacity  # zero-column batches can't derive it
    return d


def rows_arg(nr):
    """The real row count as the kernel's non-donated argument,
    coerced to the dtype ``DeviceBatch.tree_flatten`` uses for host
    ints so traces are shape-stable."""
    return jnp.int32(nr) if isinstance(nr, int) else nr


def canonical_names(n: int) -> List[str]:
    """Positional output names baked into cached kernels; the exec
    restamps its real schema names host-side after each dispatch, so
    aliasing cannot fragment the compile cache (satellite: kernel-cache
    key hygiene)."""
    return [f"_c{i}" for i in range(n)]


def build_kernel(exec_obj, key, impl_factory, donate: bool):
    """Kernel memoized on ``exec_obj._kernel`` with the donate flag
    folded into both the cache key and the rebuild guard — shared by
    TpuProjectExec / TpuFilterExec / TpuFusedStageExec so donation
    semantics live in ONE place.  The donate decision reads LIVE state
    (the persistent-cache check can flip between runs) but the handle
    is memoized, so rebuild when the flag flipped between two
    executions of the same instance: a stale donating kernel fed an
    un-detached batch would invalidate buffers the caller still treats
    as live.  Donating kernels skip the HBM-OOM retry wrapper (the
    retry would replay already-consumed buffers) and compile OUTSIDE
    the persistent XLA cache (``persistent_cache=False`` — reloaded
    donating executables mis-apply the aliasing table on jax 0.4.37;
    see kernel_cache._no_persistent_cache)."""
    if exec_obj._kernel is None or \
            getattr(exec_obj, "_kernel_donate", None) is not donate:
        from spark_rapids_tpu.exec import kernel_cache as kc
        if donate:
            _install_donation_warn_filter()
        exec_obj._kernel = kc.get_kernel(
            key + (donate,), impl_factory, oom_retry=not donate,
            persistent_cache=not donate,
            **({"donate_argnums": (0,)} if donate else {}))
        exec_obj._kernel_donate = donate
    return exec_obj._kernel


def dispatch(exec_obj, label: str, donate: bool, reg,
             b: DeviceBatch, pid: int, offset: int,
             key=None, impl_factory=None):
    """One per-batch kernel launch with the donation calling convention
    (detached row count as a separate non-donated arg), the
    shape-erased ABI (kernel_abi.erase: canonical positional names,
    bucketed hints, capacity/width padded to tier — the caller restamps
    its real schema names after), and donation bookkeeping.  The erased
    view shares the input's buffers unless padding engaged, so donation
    still releases the producer's HBM.

    When ``key``/``impl_factory`` are passed and the static decision
    allowed donation, the refcount-aware shared-scan gate runs per
    batch: a batch another query holds dispatches through the
    non-donating twin kernel (one cache lookup), everything else keeps
    its donation."""
    from spark_rapids_tpu.exec import kernel_abi
    if donate and key is not None:
        donate = batch_donate_ok(b, reg)
        build_kernel(exec_obj, key, impl_factory, donate)
    eb = kernel_abi.erase(b)
    nr = b.num_rows
    with timed(exec_obj.metrics, label):
        out = exec_obj._kernel(
            rows_detached(eb) if donate else eb,
            rows_arg(nr), jnp.int32(pid), jnp.int64(offset))
    if donate:
        exec_obj.metrics.add_extra("fusion.donatedBatches", 1)
        reg.inc("fusion.donatedDispatches")
    return out


class TpuFusedStageExec(TpuExec):
    """One collapsed Project/Filter chain (see module docstring)."""

    def __init__(self, child: PhysicalPlan,
                 out_exprs: Sequence[ir.Expression], schema: Schema,
                 condition: Optional[ir.Expression] = None,
                 fused: Sequence[str] = ()):
        super().__init__()
        self.children = (child,)
        self.out_exprs = list(out_exprs)
        self._schema = schema
        self.condition = condition
        # display names of the execs this stage replaced (top-down)
        self.fused = tuple(fused)
        self._kernel = None

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def is_passthrough(self) -> bool:
        """Pure column selection: no condition and every output a plain
        BoundReference — runs with ZERO kernel dispatches."""
        return self.condition is None and all(
            isinstance(e, ir.BoundReference) for e in self.out_exprs)

    def n_fused(self) -> int:
        return len(self.fused)

    def simple_string(self) -> str:
        mode = "passthrough" if self.is_passthrough else (
            "filter+project" if self.condition is not None else "project")
        return (f"TpuFusedStageExec({mode}, fused={len(self.fused)}: "
                f"{'+'.join(self.fused)})")

    # ------------------------------------------------------------------
    def _impl(self, batch: DeviceBatch, nr, pid, offset) -> DeviceBatch:
        from spark_rapids_tpu.exec import context
        from spark_rapids_tpu.exec.tpu_basic import compact
        # nr is the real row count, passed OUTSIDE the (possibly
        # donated) batch pytree — see rows_detached
        batch.num_rows = nr
        with context.task_context(pid, offset):
            keep = None
            if self.condition is not None:
                v = eval_tpu.evaluate(self.condition, batch)
                keep = v.data.astype(jnp.bool_) & v.validity
                if len(self.out_exprs) >= len(batch.columns):
                    batch = compact(batch, keep)
                    keep = None
            cols = [eval_tpu.evaluate(e, batch).to_column()
                    for e in self.out_exprs]
        out = DeviceBatch(canonical_names(len(cols)), cols,
                          batch.num_rows)
        return compact(out, keep) if keep is not None else out

    def _execute_passthrough(self):
        from spark_rapids_tpu.obs import registry as obsreg
        names = self._schema.names
        ords = [e.ordinal for e in self.out_exprs]
        saved = len(self.fused)

        def run(it):
            reg = obsreg.get_registry()
            for b in it:
                with timed(self.metrics, "fused.passthrough"):
                    out = DeviceBatch(names, [b.columns[i] for i in ords],
                                      b.num_rows)
                e = getattr(b, "_scan_share_entry", None)
                if e is not None:
                    # column buffers are forwarded by reference: the
                    # share stamp must survive for the downstream
                    # donation gate
                    out._scan_share_entry = e
                reg.inc("fusion.dispatchesSaved", saved)
                self.metrics.add_batches()
                self.metrics.add_rows(out.num_rows)
                yield out
        return [run(it) for it in self.children[0].execute()]

    def execute(self):
        if self.is_passthrough:
            return self._execute_passthrough()
        import functools
        import types
        from spark_rapids_tpu.exec import kernel_cache as kc
        from spark_rapids_tpu.obs import registry as obsreg
        donate = donate_ok(self.children[0],
                           getattr(self, "_donate_enabled", False))
        # detach from self: the cached closure must not pin the exec
        # instance (and through it the whole child plan subtree)
        shim = types.SimpleNamespace(out_exprs=self.out_exprs,
                                     condition=self.condition)
        key = ("fused_stage", kc.exprs_sig(self.out_exprs),
               kc.expr_sig(self.condition))
        factory = lambda: functools.partial(type(self)._impl, shim)  # noqa: E731
        build_kernel(self, key, factory, donate)

        names = self._schema.names
        # dispatches saved per batch: the chain would have cost one
        # dispatch per fused exec, the stage costs one
        saved = max(0, len(self.fused) - 1)

        def run(pid, it):
            reg = obsreg.get_registry()
            for b in it:
                out = dispatch(self, "fused.eval", donate, reg,
                               b, pid, 0, key=key,
                               impl_factory=factory)
                out = DeviceBatch(names, out.columns, out.num_rows)
                if saved:
                    reg.inc("fusion.dispatchesSaved", saved)
                self.metrics.add_batches()
                self.metrics.add_rows(out.num_rows)
                yield out
        return [run(pid, it) for pid, it in
                enumerate(self.children[0].execute())]
