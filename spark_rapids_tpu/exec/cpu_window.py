"""CPU window exec — the oracle/fallback for window functions.

Deliberately a direct row-loop interpretation of SQL window semantics
(partition slices, peer groups, frame bounds), independent of the TPU
path's segmented-scan formulation, so parity tests cross-check two very
different algorithms (same philosophy as eval_cpu vs eval_tpu).
"""

from __future__ import annotations

import functools
import math
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.cpu import _gather_single
from spark_rapids_tpu.expr import eval_cpu, ir
from spark_rapids_tpu.plan.logical import Schema


def _vals(v: eval_cpu.CpuVal) -> List[Any]:
    out = []
    for i in range(len(v.data)):
        out.append(v.data[i] if v.valid[i] else None)
    return out


def _cmp_scalar(a, b, asc: bool, nulls_first: bool) -> int:
    def rank(x):
        if x is None:
            return (0 if nulls_first else 2, 0)
        if isinstance(x, float) and math.isnan(x):
            return (1, 1)
        return (1, 0)
    ra, rb = rank(a), rank(b)
    if ra[0] != rb[0]:
        return -1 if ra[0] < rb[0] else 1
    if ra[0] == 1:  # both non-null
        if ra[1] != rb[1]:  # NaN greatest within values
            c = -1 if ra[1] < rb[1] else 1
        elif a == b:
            c = 0
        else:
            c = -1 if a < b else 1
        return c if asc else -c
    return 0


def _order_cmp(keys_a, keys_b, dirs) -> int:
    for (a, b), (asc, nf) in zip(zip(keys_a, keys_b), dirs):
        c = _cmp_scalar(a, b, asc, nf)
        if c != 0:
            return c
    return 0


class CpuWindowExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan,
                 window_exprs: Sequence[ir.WindowExpression],
                 out_names: Sequence[str], schema: Schema,
                 partitionwise: bool = False):
        super().__init__()
        self.children = (child,)
        self.window_exprs = list(window_exprs)
        self.out_names = list(out_names)
        self._schema = schema
        # partitionwise: each child partition evaluates independently —
        # the planner hashed-exchanged on the PARTITION BY keys, so
        # every window group is colocated in one partition
        self.partitionwise = partitionwise

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        if self.partitionwise:
            from spark_rapids_tpu.exec.cpu import concat_tables
            return [self._run_one(
                lambda it=it: concat_tables(list(it),
                                            self.children[0].schema))
                for it in self.children[0].execute()]
        return [self._run_one(
            lambda: _gather_single(self.children[0],
                                   self.children[0].schema))]

    def _run_one(self, get_table):
        def run():
            t = get_table()
            n = t.num_rows
            result_cols = {name: None for name in self.out_names}
            final_order = list(range(n))

            # group exprs sharing (partition, order) into one pass
            groups = {}
            for name, we in zip(self.out_names, self.window_exprs):
                sig = (tuple(e.sql() for e in we.partition_exprs),
                       tuple(e.sql() for e in we.order_exprs),
                       we.order_dirs)
                groups.setdefault(sig, []).append((name, we))

            for (_, _, dirs), items in groups.items():
                we0 = items[0][1]
                pvals = [_vals(eval_cpu.evaluate(e, t))
                         for e in we0.partition_exprs]
                ovals = [_vals(eval_cpu.evaluate(e, t))
                         for e in we0.order_exprs]

                def key_of(i):
                    return tuple(p[i] for p in pvals), \
                        tuple(o[i] for o in ovals)

                def cmp(i, j):
                    pa_, oa = key_of(i)
                    pb, ob = key_of(j)
                    c = _order_cmp(pa_, pb, [(True, True)] * len(pa_))
                    if c != 0:
                        return c
                    return _order_cmp(oa, ob, dirs or ())

                order = sorted(range(n), key=functools.cmp_to_key(cmp))
                final_order = order

                # partition slices and peer groups in sorted space
                parts: List[Tuple[int, int]] = []
                ps = 0
                for i in range(1, n + 1):
                    if i == n or _order_cmp(
                            key_of(order[i])[0], key_of(order[ps])[0],
                            [(True, True)] * len(pvals)) != 0:
                        parts.append((ps, i))
                        ps = i

                for name, we in items:
                    out_sorted = self._compute(we, t, order, parts, dirs)
                    col = [None] * n
                    for si, orig in enumerate(order):
                        col[orig] = out_sorted[si]
                    result_cols[name] = col

            # emit in last pass's sorted order (Spark emits sorted)
            arrays = [t.column(i).take(pa.array(final_order))
                      for i in range(t.num_columns)]
            for name, we in zip(self.out_names, self.window_exprs):
                vals = [result_cols[name][orig] for orig in final_order]
                arrays.append(pa.array(vals, type=we.dtype.to_arrow()))
            yield pa.Table.from_arrays(
                arrays, names=list(t.column_names) + self.out_names)
        return run()

    # ------------------------------------------------------------------
    def _compute(self, we: ir.WindowExpression, t, order, parts, dirs):
        n = len(order)
        fn = we.function
        frame = we.frame
        self._range_dirs = we.order_dirs
        ovals = [_vals(eval_cpu.evaluate(e, t)) for e in we.order_exprs]

        def peers(ps, pe, i):
            """peer range [qs, qe) of sorted index i within [ps, pe)."""
            def same(a, b):
                return _order_cmp(
                    tuple(o[order[a]] for o in ovals),
                    tuple(o[order[b]] for o in ovals), dirs or ()) == 0
            qs = i
            while qs > ps and same(qs - 1, i):
                qs -= 1
            qe = i + 1
            while qe < pe and same(qe, i):
                qe += 1
            return qs, qe

        out = [None] * n
        if isinstance(fn, (ir.RowNumber, ir.Rank, ir.DenseRank)):
            for ps, pe in parts:
                dense = 0
                for i in range(ps, pe):
                    qs, qe = peers(ps, pe, i)
                    if i == qs:
                        dense += 1
                    if isinstance(fn, ir.RowNumber):
                        out[i] = i - ps + 1
                    elif isinstance(fn, ir.Rank):
                        out[i] = qs - ps + 1
                    else:
                        out[i] = dense
            return out

        if isinstance(fn, (ir.Lead, ir.Lag)):
            src = _vals(eval_cpu.evaluate(fn.children[0], t))
            off = fn.offset if isinstance(fn, ir.Lead) else -fn.offset
            for ps, pe in parts:
                for i in range(ps, pe):
                    j = i + off
                    if ps <= j < pe:
                        out[i] = src[order[j]]
                    else:
                        out[i] = fn.default
            return out

        if isinstance(fn, ir.AggregateExpression):
            src = _vals(eval_cpu.evaluate(fn.child, t)) \
                if fn.child is not None else [1] * t.num_rows
            for ps, pe in parts:
                # partition-level range-scan stats are row-independent:
                # hoist them out of the per-row loop (O(n) not O(n^2))
                stats = self._range_stats(frame, ps, pe, ovals, order)
                for i in range(ps, pe):
                    a, b = self._bounds(frame, ps, pe, i, peers, ovals,
                                        order, stats)
                    window = [src[order[j]] for j in range(a, b + 1)] \
                        if b >= a else []
                    out[i] = _agg_py(fn, window)
            return out

        raise NotImplementedError(type(fn).__name__)

    def _range_stats(self, frame, ps, pe, ovals, order):
        """Row-independent per-partition stats for finite numeric RANGE
        frames: normalized order values plus null/NaN run boundaries.

        Spark's frame scans (Sliding/Unbounded*WindowFunctionFrame): the
        comparator treats a null order key as -inf when nulls sort first
        and +inf when they sort last, and NaN as above every finite
        value; value-bounded sides exclude the null runs (or degenerate
        TO the run that ranks past the bound), while an unbounded side
        reaches the partition bound.
        """
        if frame.kind != "range" or not ovals or \
                (frame.start is None and frame.end in (None, 0)):
            return None
        ascending = True
        if getattr(self, "_range_dirs", None):
            ascending = self._range_dirs[0][0]
        # normalize to ascending w-space exactly like the TPU path
        # (tpu_window's `w = -w` for DESC) so the monotonic scans are
        # direction-agnostic
        raw = [ovals[0][order[j]] for j in range(ps, pe)]
        wvals = [None if x is None
                 else (x if isinstance(x, float) and math.isnan(x)
                       else (x if ascending else -x))
                 for x in raw]
        nulls_first = bool(wvals) and wvals[0] is None
        nleading = 0
        while nleading < len(wvals) and wvals[nleading] is None:
            nleading += 1
        ntrailing = 0
        while ntrailing < len(wvals) - nleading and \
                wvals[-1 - ntrailing] is None:
            ntrailing += 1
        if not nulls_first:
            nleading = 0
        else:
            ntrailing = 0
        vlo, vhi = ps + nleading, pe - 1 - ntrailing
        # NaN rows rank above every finite value, so after normalization
        # the NaN run sits at the high end of the value run under ASC and
        # at the low (physical-start) end under DESC
        nnan = sum(1 for j in range(vlo, vhi + 1)
                   if isinstance(wvals[j - ps], float)
                   and math.isnan(wvals[j - ps]))
        if ascending:
            flo, fhi = vlo, vhi - nnan
        else:
            flo, fhi = vlo + nnan, vhi
        return (ascending, wvals, nleading, ntrailing, nnan, flo, fhi)

    def _bounds(self, frame, ps, pe, i, peers, ovals, order, stats=None):
        if frame.kind == "rows":
            a = ps if frame.start is None else max(ps, i + frame.start)
            b = pe - 1 if frame.end is None else min(pe - 1, i + frame.end)
            return a, b
        # range
        if frame.start is None and frame.end == 0:
            qs, qe = peers(ps, pe, i)
            return ps, qe - 1
        if frame.start is None and frame.end is None:
            return ps, pe - 1
        # numeric range offsets over a single order column
        v = ovals[0][order[i]]
        if v is None or (isinstance(v, float) and math.isnan(v)):
            # null/NaN current row: its peers on value-bounded sides, the
            # partition bound on unbounded sides (Spark's bound
            # comparators: null+offset is null and NaN+offset is NaN,
            # which compare equal to the row's own key and outside every
            # finite value run)
            qs, qe = peers(ps, pe, i)
            a = ps if frame.start is None else qs
            b = pe - 1 if frame.end is None else qe - 1
            return a, b
        if stats is None:
            stats = self._range_stats(frame, ps, pe, ovals, order)
        ascending, wvals, nleading, ntrailing, nnan, flo, fhi = stats
        w = v if ascending else -v
        lo = w + frame.start if frame.start is not None else None
        hi = w + frame.end if frame.end is not None else None

        if frame.start is None:
            a = ps
        else:
            if ascending and nnan:
                a = fhi + 1        # NaN run satisfies >= any finite bound
            else:
                a = pe - ntrailing  # trailing null run (pe when none)
            for j in range(flo, fhi + 1):
                if wvals[j - ps] >= lo:
                    a = j
                    break
        if frame.end is None:
            b = pe - 1
        else:
            if not ascending and nnan:
                b = flo - 1        # NaN run (in w-space) precedes finites
            else:
                b = ps + nleading - 1  # leading null run (ps-1 when none)
            for j in range(fhi, flo - 1, -1):
                if wvals[j - ps] <= hi:
                    b = j
                    break
        return a, b


def _agg_py(fn: ir.AggregateExpression, window: List[Any]):
    non_null = [v for v in window if v is not None and
                not (isinstance(v, float) and math.isnan(v))]
    nans = [v for v in window if isinstance(v, float) and math.isnan(v)]
    if isinstance(fn, ir.Count):
        if fn.child is None:
            return len(window)
        return len(non_null) + len(nans)
    if isinstance(fn, ir.Sum):
        vals = non_null + nans
        if not vals:
            return None
        if all(isinstance(v, (int, np.integer)) for v in vals):
            # numpy-scalar sum() wraps with a RuntimeWarning and its
            # behavior shifted across numpy versions; Spark's long SUM
            # wraps silently (Java long add, non-ANSI).  Sum exactly in
            # Python ints, then wrap to int64 explicitly so the oracle
            # has pinned overflow semantics.
            s = sum(int(v) for v in vals)
            return np.int64(((s + (1 << 63)) & ((1 << 64) - 1))
                            - (1 << 63))
        return sum(vals)
    if isinstance(fn, ir.Average):
        # Spark averages in double space (no integral overflow)
        vals = [float(v) for v in non_null] + nans
        return (sum(vals) / len(vals)) if vals else None
    if isinstance(fn, ir.Min):
        if nans and not non_null:
            return float("nan")
        return min(non_null) if non_null else None
    if isinstance(fn, ir.Max):
        if nans:
            return float("nan")
        return max(non_null) if non_null else None
    raise NotImplementedError(type(fn).__name__)
