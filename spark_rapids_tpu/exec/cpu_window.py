"""CPU window exec — the oracle/fallback for window functions.

Deliberately a direct row-loop interpretation of SQL window semantics
(partition slices, peer groups, frame bounds), independent of the TPU
path's segmented-scan formulation, so parity tests cross-check two very
different algorithms (same philosophy as eval_cpu vs eval_tpu).
"""

from __future__ import annotations

import bisect as _bisect
import functools
import math
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.cpu import _gather_single
from spark_rapids_tpu.expr import eval_cpu, ir
from spark_rapids_tpu.plan.logical import Schema


def _vals(v: eval_cpu.CpuVal) -> List[Any]:
    out = []
    for i in range(len(v.data)):
        out.append(v.data[i] if v.valid[i] else None)
    return out


def _cmp_scalar(a, b, asc: bool, nulls_first: bool) -> int:
    def rank(x):
        if x is None:
            return (0 if nulls_first else 2, 0)
        if isinstance(x, float) and math.isnan(x):
            return (1, 1)
        return (1, 0)
    ra, rb = rank(a), rank(b)
    if ra[0] != rb[0]:
        return -1 if ra[0] < rb[0] else 1
    if ra[0] == 1:  # both non-null
        if ra[1] != rb[1]:  # NaN greatest within values
            c = -1 if ra[1] < rb[1] else 1
        elif ra[1] == 1:
            c = 0   # NaN == NaN (Double.compare semantics)
        elif a == b:
            c = 0
        else:
            c = -1 if a < b else 1
        return c if asc else -c
    return 0


def _order_cmp(keys_a, keys_b, dirs) -> int:
    for (a, b), (asc, nf) in zip(zip(keys_a, keys_b), dirs):
        c = _cmp_scalar(a, b, asc, nf)
        if c != 0:
            return c
    return 0


def _fast_order_and_parts(pvals, plists, ovals, olists, dirs, n):
    """Vectorized ordering + partition boundaries via Arrow's stable
    multi-key sort — semantics identical to the _order_cmp comparator
    (per-key null flag columns give per-key null placement; Arrow sorts
    NaN greatest among values, the same rank _cmp_scalar assigns).

    The comparator path is O(n log n) PYTHON comparisons — minutes at
    millions of rows — and stays as the fallback for value types Arrow
    cannot sort.  Returns (order ndarray, parts [(start, end)]).
    """
    import pyarrow.compute as pc
    all_vals = list(zip(pvals, plists, [(True, True)] * len(pvals))) + \
        list(zip(ovals, olists, list(dirs or ())))
    if not all_vals:
        return np.arange(n, dtype=np.int64), [(0, n)]
    cols = {}
    keys = []
    for i, (cv, vlist, (asc, nf)) in enumerate(all_vals):
        valid = np.asarray(cv.valid, dtype=bool)
        flag = np.where(valid, 1, 0) if nf else np.where(valid, 0, 1)
        cols[f"f{i}"] = pa.array(flag.astype(np.int8))
        arr = pa.array(vlist)               # None-mapped values
        cols[f"v{i}"] = arr
        d = "ascending" if asc else "descending"
        keys.append((f"f{i}", "ascending"))
        if pa.types.is_floating(arr.type):
            # Spark ranks NaN greatest among values in BOTH directions;
            # Arrow sorts NaN after values regardless of direction, so
            # the NaN rank rides its own direction-following key
            data = np.asarray(cv.data, dtype=np.float64)
            cols[f"g{i}"] = pa.array(
                (valid & np.isnan(data)).astype(np.int8))
            keys.append((f"g{i}", d))
        keys.append((f"v{i}", d))
    table = pa.table(cols)
    order = pc.sort_indices(table, sort_keys=keys).to_numpy(
        zero_copy_only=False).astype(np.int64)

    # partition boundaries: adjacent sorted rows differ in any
    # partition key (flag catches null-vs-value; NaN==NaN for floats)
    flags_diff = np.zeros(n, dtype=bool)
    if n:
        flags_diff[0] = True
    for i in range(len(pvals)):
        fl = np.asarray(cols[f"f{i}"])[order]
        flags_diff[1:] |= fl[1:] != fl[:-1]
        filled = pc.fill_null(
            cols[f"v{i}"],
            _null_fill_for(table.schema.field(f"v{i}").type))
        vv = filled.to_numpy(zero_copy_only=False)[order]
        neq = vv[1:] != vv[:-1]
        if vv.dtype.kind == "f":
            neq &= ~(np.isnan(vv[1:].astype(np.float64)) &
                     np.isnan(vv[:-1].astype(np.float64)))
        flags_diff[1:] |= neq
    starts = np.flatnonzero(flags_diff)
    parts = [(int(s), int(e)) for s, e in
             zip(starts, list(starts[1:]) + [n])]
    return order, parts


def _null_fill_for(t: pa.DataType):
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return ""
    if pa.types.is_boolean(t):
        return False
    if pa.types.is_floating(t):
        return 0.0
    if pa.types.is_null(t):
        raise TypeError("all-null key: comparator fallback")
    return 0


class CpuWindowExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan,
                 window_exprs: Sequence[ir.WindowExpression],
                 out_names: Sequence[str], schema: Schema,
                 partitionwise: bool = False):
        super().__init__()
        self.children = (child,)
        self.window_exprs = list(window_exprs)
        self.out_names = list(out_names)
        self._schema = schema
        # partitionwise: each child partition evaluates independently —
        # the planner hashed-exchanged on the PARTITION BY keys, so
        # every window group is colocated in one partition
        self.partitionwise = partitionwise

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        if self.partitionwise:
            from spark_rapids_tpu.exec.cpu import concat_tables
            return [self._run_one(
                lambda it=it: concat_tables(list(it),
                                            self.children[0].schema))
                for it in self.children[0].execute()]
        return [self._run_one(
            lambda: _gather_single(self.children[0],
                                   self.children[0].schema))]

    def _run_one(self, get_table):
        def run():
            t = get_table()
            n = t.num_rows
            result_cols = {name: None for name in self.out_names}
            final_order = list(range(n))

            # group exprs sharing (partition, order) into one pass
            groups = {}
            for name, we in zip(self.out_names, self.window_exprs):
                sig = (tuple(e.sql() for e in we.partition_exprs),
                       tuple(e.sql() for e in we.order_exprs),
                       we.order_dirs)
                groups.setdefault(sig, []).append((name, we))

            for (_, _, dirs), items in groups.items():
                we0 = items[0][1]
                pcv = [eval_cpu.evaluate(e, t)
                       for e in we0.partition_exprs]
                ocv = [eval_cpu.evaluate(e, t) for e in we0.order_exprs]
                pvals = [_vals(v) for v in pcv]
                ovals = [_vals(v) for v in ocv]

                def key_of(i):
                    return tuple(p[i] for p in pvals), \
                        tuple(o[i] for o in ovals)

                try:
                    order, parts = _fast_order_and_parts(
                        pcv, pvals, ocv, ovals, dirs, n)
                    order = list(order)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                        TypeError):
                    def cmp(i, j):
                        pa_, oa = key_of(i)
                        pb, ob = key_of(j)
                        c = _order_cmp(pa_, pb,
                                       [(True, True)] * len(pa_))
                        if c != 0:
                            return c
                        return _order_cmp(oa, ob, dirs or ())

                    order = sorted(range(n),
                                   key=functools.cmp_to_key(cmp))
                    parts = []
                    ps = 0
                    for i in range(1, n + 1):
                        if i == n or _order_cmp(
                                key_of(order[i])[0],
                                key_of(order[ps])[0],
                                [(True, True)] * len(pvals)) != 0:
                            parts.append((ps, i))
                            ps = i
                final_order = order

                for name, we in items:
                    out_sorted = self._compute(we, t, order, parts, dirs)
                    col = [None] * n
                    for si, orig in enumerate(order):
                        col[orig] = out_sorted[si]
                    result_cols[name] = col

            # emit in last pass's sorted order (Spark emits sorted)
            arrays = [t.column(i).take(pa.array(final_order))
                      for i in range(t.num_columns)]
            for name, we in zip(self.out_names, self.window_exprs):
                vals = [result_cols[name][orig] for orig in final_order]
                arrays.append(pa.array(vals, type=we.dtype.to_arrow()))
            yield pa.Table.from_arrays(
                arrays, names=list(t.column_names) + self.out_names)
        return run()

    # ------------------------------------------------------------------
    def _compute(self, we: ir.WindowExpression, t, order, parts, dirs):
        n = len(order)
        fn = we.function
        frame = we.frame
        self._range_dirs = we.order_dirs
        ovals = [_vals(eval_cpu.evaluate(e, t)) for e in we.order_exprs]

        # peer groups once per spec (the per-row while-loop scan was
        # O(n * peer_size)): one adjacent comparison per sorted row
        qs_arr = np.zeros(n, dtype=np.int64)
        qe_arr = np.zeros(n, dtype=np.int64)
        for ps, pe in parts:
            gs = ps
            for i in range(ps + 1, pe + 1):
                if i == pe or _order_cmp(
                        tuple(o[order[i]] for o in ovals),
                        tuple(o[order[i - 1]] for o in ovals),
                        dirs or ()) != 0:
                    qs_arr[gs:i] = gs
                    qe_arr[gs:i] = i
                    gs = i

        def peers(ps, pe, i):
            """peer range [qs, qe) of sorted index i within [ps, pe)."""
            return int(qs_arr[i]), int(qe_arr[i])

        out = [None] * n
        if isinstance(fn, (ir.RowNumber, ir.Rank, ir.DenseRank)):
            for ps, pe in parts:
                dense = 0
                for i in range(ps, pe):
                    qs, qe = peers(ps, pe, i)
                    if i == qs:
                        dense += 1
                    if isinstance(fn, ir.RowNumber):
                        out[i] = i - ps + 1
                    elif isinstance(fn, ir.Rank):
                        out[i] = qs - ps + 1
                    else:
                        out[i] = dense
            return out

        if isinstance(fn, (ir.Lead, ir.Lag)):
            src = _vals(eval_cpu.evaluate(fn.children[0], t))
            off = fn.offset if isinstance(fn, ir.Lead) else -fn.offset
            for ps, pe in parts:
                for i in range(ps, pe):
                    j = i + off
                    if ps <= j < pe:
                        out[i] = src[order[j]]
                    else:
                        out[i] = fn.default
            return out

        if isinstance(fn, ir.AggregateExpression):
            a_arr = np.empty(n, dtype=np.int64)
            b_arr = np.empty(n, dtype=np.int64)
            for ps, pe in parts:
                # partition-level range-scan stats are row-independent:
                # hoist them out of the per-row loop (O(n) not O(n^2))
                stats = self._range_stats(frame, ps, pe, ovals, order)
                for i in range(ps, pe):
                    a_arr[i], b_arr[i] = self._bounds(
                        frame, ps, pe, i, peers, ovals, order, stats)
            cv = eval_cpu.evaluate(fn.child, t) \
                if fn.child is not None else None
            res = _agg_windows(fn, cv, order, a_arr, b_arr)
            if res is not None:
                return res
            # fallback (non-numeric sources): per-row materialization
            src = _vals(eval_cpu.evaluate(fn.child, t)) \
                if fn.child is not None else [1] * t.num_rows
            for i in range(n):
                a, b = int(a_arr[i]), int(b_arr[i])
                window = [src[order[j]] for j in range(a, b + 1)] \
                    if b >= a else []
                out[i] = _agg_py(fn, window)
            return out

        raise NotImplementedError(type(fn).__name__)

    def _range_stats(self, frame, ps, pe, ovals, order):
        """Row-independent per-partition stats for finite numeric RANGE
        frames: normalized order values plus null/NaN run boundaries.

        Spark's frame scans (Sliding/Unbounded*WindowFunctionFrame): the
        comparator treats a null order key as -inf when nulls sort first
        and +inf when they sort last, and NaN as above every finite
        value; value-bounded sides exclude the null runs (or degenerate
        TO the run that ranks past the bound), while an unbounded side
        reaches the partition bound.
        """
        if frame.kind != "range" or not ovals or \
                (frame.start is None and frame.end in (None, 0)):
            return None
        ascending = True
        if getattr(self, "_range_dirs", None):
            ascending = self._range_dirs[0][0]
        # normalize to ascending w-space exactly like the TPU path
        # (tpu_window's `w = -w` for DESC) so the monotonic scans are
        # direction-agnostic
        raw = [ovals[0][order[j]] for j in range(ps, pe)]
        wvals = [None if x is None
                 else (x if isinstance(x, float) and math.isnan(x)
                       else (x if ascending else -x))
                 for x in raw]
        nulls_first = bool(wvals) and wvals[0] is None
        nleading = 0
        while nleading < len(wvals) and wvals[nleading] is None:
            nleading += 1
        ntrailing = 0
        while ntrailing < len(wvals) - nleading and \
                wvals[-1 - ntrailing] is None:
            ntrailing += 1
        if not nulls_first:
            nleading = 0
        else:
            ntrailing = 0
        vlo, vhi = ps + nleading, pe - 1 - ntrailing
        # NaN rows rank above every finite value, so after normalization
        # the NaN run sits at the high end of the value run under ASC and
        # at the low (physical-start) end under DESC
        nnan = sum(1 for j in range(vlo, vhi + 1)
                   if isinstance(wvals[j - ps], float)
                   and math.isnan(wvals[j - ps]))
        if ascending:
            flo, fhi = vlo, vhi - nnan
        else:
            flo, fhi = vlo + nnan, vhi
        return (ascending, wvals, nleading, ntrailing, nnan, flo, fhi)

    def _bounds(self, frame, ps, pe, i, peers, ovals, order, stats=None):
        if frame.kind == "rows":
            a = ps if frame.start is None else max(ps, i + frame.start)
            b = pe - 1 if frame.end is None else min(pe - 1, i + frame.end)
            return a, b
        # range
        if frame.start is None and frame.end == 0:
            qs, qe = peers(ps, pe, i)
            return ps, qe - 1
        if frame.start is None and frame.end is None:
            return ps, pe - 1
        # numeric range offsets over a single order column
        v = ovals[0][order[i]]
        if v is None or (isinstance(v, float) and math.isnan(v)):
            # null/NaN current row: its peers on value-bounded sides, the
            # partition bound on unbounded sides (Spark's bound
            # comparators: null+offset is null and NaN+offset is NaN,
            # which compare equal to the row's own key and outside every
            # finite value run)
            qs, qe = peers(ps, pe, i)
            a = ps if frame.start is None else qs
            b = pe - 1 if frame.end is None else qe - 1
            return a, b
        if stats is None:
            stats = self._range_stats(frame, ps, pe, ovals, order)
        ascending, wvals, nleading, ntrailing, nnan, flo, fhi = stats
        w = v if ascending else -v
        lo = w + frame.start if frame.start is not None else None
        hi = w + frame.end if frame.end is not None else None

        # the finite run [flo, fhi] is ascending in w-space, so the
        # first >= lo / last <= hi rows bisect in O(log) instead of the
        # former O(partition) linear scan per row
        if frame.start is None:
            a = ps
        else:
            if ascending and nnan:
                a = fhi + 1        # NaN run satisfies >= any finite bound
            else:
                a = pe - ntrailing  # trailing null run (pe when none)
            if flo <= fhi:
                j = ps + _bisect.bisect_left(wvals, lo, flo - ps,
                                             fhi - ps + 1)
                if j <= fhi:
                    a = j
        if frame.end is None:
            b = pe - 1
        else:
            if not ascending and nnan:
                b = flo - 1        # NaN run (in w-space) precedes finites
            else:
                b = ps + nleading - 1  # leading null run (ps-1 when none)
            if flo <= fhi:
                j = ps + _bisect.bisect_right(wvals, hi, flo - ps,
                                              fhi - ps + 1) - 1
                if j >= flo:
                    b = j
        return a, b


def _agg_windows(fn: ir.AggregateExpression, cv, order,
                 a_arr: np.ndarray, b_arr: np.ndarray):
    """Vectorized per-row window aggregation over [a, b] bounds —
    identical results to _agg_py (wrapping i64 sums, Spark NaN/null
    ranking) computed with prefix sums and ufunc.reduceat instead of
    materializing every window (the old path was O(rows x frame) in
    Python).  Returns None for source types it does not cover (the
    caller falls back to _agg_py)."""
    n = a_arr.shape[0]
    empty = b_arr < a_arr
    if cv is None:                       # COUNT(*)
        if not isinstance(fn, ir.Count):
            return None
        ln = np.where(empty, 0, b_arr - a_arr + 1)
        return [int(v) for v in ln]
    data0 = np.asarray(cv.data)
    if data0.dtype.kind not in "iufb":
        return None
    order_np = np.asarray(order, dtype=np.int64)
    data = data0[order_np]
    valid = np.asarray(cv.valid, dtype=bool)[order_np]
    is_f = data.dtype.kind == "f"
    nanm = (np.isnan(data) & valid) if is_f else np.zeros(n, bool)
    finite = valid & ~nanm

    aa = np.where(empty, 0, a_arr)
    bb1 = np.where(empty, 1, b_arr + 1)

    def pdiff(x32):
        p = np.concatenate([[0], np.cumsum(x32.astype(np.int64))])
        return np.where(empty, 0, p[bb1] - p[aa])

    def win_reduce(x, ufunc, fill):
        xpad = np.concatenate([x, np.asarray([fill], dtype=x.dtype)])
        idx = np.empty(2 * n, dtype=np.int64)
        idx[0::2] = aa
        idx[1::2] = bb1
        if n == 0:
            return np.asarray([], dtype=x.dtype)
        r = ufunc.reduceat(xpad, idx)[0::2]
        return np.where(empty, fill, r)

    cnt_valid = pdiff(valid)
    if isinstance(fn, ir.Count):
        return [int(v) for v in cnt_valid]
    if isinstance(fn, ir.Sum):
        if is_f:
            x = np.where(valid, data.astype(np.float64), 0.0)
            s = win_reduce(x, np.add, 0.0)
            return [float(v) if c else None
                    for v, c in zip(s, cnt_valid)]
        with np.errstate(over="ignore"):
            x = np.where(valid, data.astype(np.int64), 0)
            p = np.concatenate([[0], np.cumsum(x)])
            s = np.where(empty, 0, p[bb1] - p[aa])
        return [np.int64(v) if c else None
                for v, c in zip(s, cnt_valid)]
    if isinstance(fn, ir.Average):
        x = np.where(valid, data.astype(np.float64), 0.0)
        s = win_reduce(x, np.add, 0.0)
        return [(float(v) / int(c)) if c else None
                for v, c in zip(s, cnt_valid)]
    if isinstance(fn, (ir.Min, ir.Max)):
        is_min = isinstance(fn, ir.Min)
        cnt_fin = pdiff(finite)
        cnt_nan = pdiff(nanm)
        if is_f:
            fill = np.inf if is_min else -np.inf
            x = np.where(finite, data.astype(np.float64), fill)
            m = win_reduce(x, np.minimum if is_min else np.maximum,
                           fill)
            out = []
            for v, cf, cn in zip(m, cnt_fin, cnt_nan):
                if (cn and not is_min) or (cn and is_min and not cf):
                    out.append(float("nan"))
                elif cf:
                    out.append(float(v))
                else:
                    out.append(None)
            return out
        info = np.iinfo(np.int64)
        fill = info.max if is_min else info.min
        x = np.where(finite, data.astype(np.int64), fill)
        m = win_reduce(x, np.minimum if is_min else np.maximum, fill)
        if data.dtype.kind == "b":
            return [bool(v) if c else None
                    for v, c in zip(m, cnt_fin)]
        return [data0.dtype.type(v) if c else None
                for v, c in zip(m, cnt_fin)]
    return None


def _agg_py(fn: ir.AggregateExpression, window: List[Any]):
    non_null = [v for v in window if v is not None and
                not (isinstance(v, float) and math.isnan(v))]
    nans = [v for v in window if isinstance(v, float) and math.isnan(v)]
    if isinstance(fn, ir.Count):
        if fn.child is None:
            return len(window)
        return len(non_null) + len(nans)
    if isinstance(fn, ir.Sum):
        vals = non_null + nans
        if not vals:
            return None
        if all(isinstance(v, (int, np.integer)) for v in vals):
            # numpy-scalar sum() wraps with a RuntimeWarning and its
            # behavior shifted across numpy versions; Spark's long SUM
            # wraps silently (Java long add, non-ANSI).  Sum exactly in
            # Python ints, then wrap to int64 explicitly so the oracle
            # has pinned overflow semantics.
            s = sum(int(v) for v in vals)
            return np.int64(((s + (1 << 63)) & ((1 << 64) - 1))
                            - (1 << 63))
        return sum(vals)
    if isinstance(fn, ir.Average):
        # Spark averages in double space (no integral overflow)
        vals = [float(v) for v in non_null] + nans
        return (sum(vals) / len(vals)) if vals else None
    if isinstance(fn, ir.Min):
        if nans and not non_null:
            return float("nan")
        return min(non_null) if non_null else None
    if isinstance(fn, ir.Max):
        if nans:
            return float("nan")
        return max(non_null) if non_null else None
    raise NotImplementedError(type(fn).__name__)
