"""Adaptive (AQE-analog) shuffle reads for join exchanges.

Reference: ``GpuCustomShuffleReaderExec`` (GpuCustomShuffleReaderExec.scala:38)
serves the coalesced/skewed partition specs Spark's AQE derived from map
output statistics.  Here the engine computes them itself, with Spark's
scoping rules:

  * only planner-inserted join exchanges participate — a user's
    ``df.repartition(n, ...)`` fixed the partition count explicitly and is
    exempt (Spark's REPARTITION_BY_NUM exemption);
  * both join sides share ONE spec list computed from the combined
    per-partition sizes, so the join's co-partitioning contract survives
    (Spark's ShufflePartitionsUtil.coalescePartitions over multiple map
    output statistics);
  * a skewed partition (side bytes > skewedPartitionFactor × median and
    > the absolute threshold) is split by rows into advisory-sized chunks
    while the other side's matching partition is replicated per chunk
    (OptimizeSkewedJoin's PartialReducerPartitionSpec).  Sides are only
    split where the join type allows it: the left for
    inner/left/semi/anti, the right for inner/right, neither for full
    outer.
  * ``minPartitionNum`` constrains only coalescing, never skew splitting.

Trade-off vs the reference: specs need both sides' sizes, so the
coordinator materializes every reduce partition before the first read
(AQE reads map statistics instead; our exchange does not persist
host-side stats for the device transport).  To keep that from pinning
HBM on large joins, every buffered partition batch is registered in the
spill catalog (when enabled) so the device store can evict it to
host/disk under pressure, exactly like the hash aggregate's buffered
partials.  Partition buffers are refcounted and released as the last
spec referencing them drains.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar.batch import (DeviceBatch, bucket_rows,
                                             concat_batches)
from spark_rapids_tpu.exec.base import PhysicalPlan, TpuExec, timed
from spark_rapids_tpu.plan.logical import Schema
from spark_rapids_tpu.shuffle.exchange import slice_span


@dataclass(frozen=True)
class CoalescedSpec:
    """Output partition = input partitions [start, end)."""
    start: int
    end: int


@dataclass(frozen=True)
class SkewSplitSpec:
    """Output partition = rows [row_start, row_end) of input partition."""
    partition: int
    row_start: int
    row_end: int


def skewed_indices(sizes: Sequence[int], factor: int, threshold: int
                   ) -> Set[int]:
    nonzero = sorted(s for s in sizes if s > 0)
    if not nonzero:
        return set()
    median = nonzero[len(nonzero) // 2]
    cut = max(factor * median, threshold)
    return {i for i, s in enumerate(sizes) if s > cut}


def coalesce_runs(sizes: Sequence[int], advisory: int,
                  skew: Set[int]) -> List:
    """Greedy contiguous coalescing up to ``advisory`` bytes; indices in
    ``skew`` become standalone ``("skew", i)`` markers.  Returns a list of
    CoalescedSpec | ("skew", i)."""
    specs: List = []
    run_start: Optional[int] = None
    run_bytes = 0

    def flush(end: int) -> None:
        nonlocal run_start, run_bytes
        if run_start is not None and end > run_start:
            specs.append(CoalescedSpec(run_start, end))
        run_start, run_bytes = None, 0

    for i, s in enumerate(sizes):
        if i in skew:
            flush(i)
            specs.append(("skew", i))
            continue
        if run_start is None:
            run_start = i
        run_bytes += s
        if run_bytes >= advisory:
            flush(i + 1)
    flush(len(sizes))
    return specs


def _row_chunks(rows: int, size: int, advisory: int
                ) -> List[Tuple[int, int]]:
    n_chunks = max(2, -(-size // advisory))
    chunk = max(1, -(-rows // n_chunks))
    return [(st, min(st + chunk, rows))
            for st in range(0, max(rows, 1), chunk)]


def plan_join_specs(lsizes: Sequence[int], rsizes: Sequence[int],
                    lrows: Sequence[int], rrows: Sequence[int],
                    how: str, advisory: int, factor: int, threshold: int,
                    min_parts: int) -> List[Tuple]:
    """One shared spec list for both join sides.

    Returns [(left_spec, right_spec), ...]; coalesced specs are identical
    on both sides, skew entries pair row chunks of the split side with a
    replica of the other side's whole partition."""
    lskew = skewed_indices(lsizes, factor, threshold) \
        if how in ("inner", "left", "semi", "anti") else set()
    rskew = skewed_indices(rsizes, factor, threshold) \
        if how in ("inner", "right") else set()
    skew = lskew | rskew
    combined = [a + b for a, b in zip(lsizes, rsizes)]
    runs = coalesce_runs(combined, advisory, skew)

    def expand(runs_list) -> List[Tuple]:
        out: List[Tuple] = []
        for sp in runs_list:
            if isinstance(sp, CoalescedSpec):
                out.append((sp, sp))
                continue
            _, i = sp
            lchunks = _row_chunks(lrows[i], lsizes[i], advisory) \
                if i in lskew else [(0, lrows[i])]
            rchunks = _row_chunks(rrows[i], rsizes[i], advisory) \
                if i in rskew else [(0, rrows[i])]
            for ls, le in lchunks:
                for rs, re in rchunks:
                    out.append((SkewSplitSpec(i, ls, le),
                                SkewSplitSpec(i, rs, re)))
        return out

    specs = expand(runs)
    if len(specs) < min_parts:
        # minPartitionNum limits coalescing only: retry without it
        identity = []
        for sp in runs:
            if isinstance(sp, CoalescedSpec):
                identity.extend(CoalescedSpec(p, p + 1)
                                for p in range(sp.start, sp.end))
            else:
                identity.append(sp)
        specs = expand(identity)
    return specs


class _JoinAdaptiveState:
    """Shared coordinator: pulls both exchanges once, plans one spec
    list, hands per-side views their batches.  Buffers are spillable
    (registered in the spill catalog when enabled), refcounted per
    (side, partition), and dropped when the last referencing spec
    drains."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, how: str,
                 conf_obj):
        import threading
        self._lock = threading.Lock()
        self.children = (left, right)
        self.how = how
        self.advisory = int(conf_obj.get(
            cfg.ADAPTIVE_ADVISORY_PARTITION_SIZE))
        self.factor = int(conf_obj.get(cfg.ADAPTIVE_SKEW_FACTOR))
        self.threshold = int(conf_obj.get(cfg.ADAPTIVE_SKEW_THRESHOLD))
        self.min_parts = int(conf_obj.get(cfg.ADAPTIVE_MIN_PARTITION_NUM))
        self.specs: Optional[List[Tuple]] = None
        # handles with .get()/.close() (SpillableBatch/PlainBatchHandle)
        self.batches: List[List[List]] = [[], []]
        self._refs: List[Dict[int, int]] = [{}, {}]

    # join fragments ship to executor processes (transport='process');
    # the lock and any pulled device buffers are process-local
    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_lock", None)
        d["specs"] = None
        d["batches"] = [[], []]
        d["_refs"] = [{}, {}]
        return d

    def __setstate__(self, d):
        import threading
        self.__dict__.update(d)
        self._lock = threading.Lock()

    def ensure(self) -> None:
        with self._lock:
            return self._ensure_locked()

    def _ensure_locked(self) -> None:
        if self.specs is not None:
            return
        from spark_rapids_tpu.mem.spill import register_or_hold
        per_side_sizes = []
        per_side_rows = []
        for side, child in enumerate(self.children):
            # ICI-plane reducers hand out batches committed to their
            # owning mesh device; the adaptive reader re-slices them
            # across partitions, so colocate at pull time (the cost the
            # reference's AQE pays as remote map-output fetches)
            colocate = getattr(child, "transport", None) in ("ici",
                                                             "ici_ring")
            tgt = jax.devices()[0] if colocate else None
            sizes: List[int] = []
            rows: List[int] = []
            handles: List[List] = []
            for it in child.execute():
                bs = [b for b in it]
                if colocate:
                    bs = [b if tgt in b.columns[0].data.devices()
                          else jax.device_put(b, tgt) for b in bs]
                # effective bytes = occupancy-scaled: capacity padding
                # (ICI shards all share the mesh-shard capacity; buckets
                # pad up to 2x) must not mask real size skew
                sizes.append(sum(
                    int(b.nbytes() * (int(b.num_rows) /
                                      max(int(b.capacity), 1)))
                    for b in bs))
                rows.append(sum(int(b.num_rows) for b in bs))
                handles.append([register_or_hold(b) for b in bs])
            self.batches[side] = handles
            per_side_sizes.append(sizes)
            per_side_rows.append(rows)
        self.specs = plan_join_specs(
            per_side_sizes[0], per_side_sizes[1],
            per_side_rows[0], per_side_rows[1],
            self.how, self.advisory, self.factor, self.threshold,
            self.min_parts)
        # pre-concat partitions that skew chunks will row-slice, and
        # count references so buffers free as readers drain
        for side in (0, 1):
            refs: Dict[int, int] = {}
            for sp in (s[side] for s in self.specs):
                if isinstance(sp, SkewSplitSpec):
                    refs[sp.partition] = refs.get(sp.partition, 0) + 1
                else:
                    for p in range(sp.start, sp.end):
                        refs[p] = refs.get(p, 0) + 1
            self._refs[side] = refs
            skew_parts = {sp[side].partition for sp in self.specs
                          if isinstance(sp[side], SkewSplitSpec)}
            for p in skew_parts:
                hs = self.batches[side][p]
                if len(hs) > 1:
                    merged = concat_batches([h.get() for h in hs])
                    for h in hs:
                        h.close()
                    self.batches[side][p] = [register_or_hold(merged)]

    def release(self, side: int, parts) -> None:
        # partition readers run concurrently under the task thread pool
        with self._lock:
            for p in parts:
                self._refs[side][p] -= 1
                if self._refs[side][p] == 0:
                    for h in self.batches[side][p]:
                        h.close()
                    self.batches[side][p] = []


class TpuAdaptiveJoinReaderExec(TpuExec):
    """One join side's view of the shared coordinated specs (the
    CustomShuffleReader node that appears in explain output)."""

    def __init__(self, state: _JoinAdaptiveState, side: int,
                 child: PhysicalPlan, conf_obj):
        super().__init__()
        self.state = state
        self.side = side
        self.children = (child,)
        self.min_bucket = conf_obj.get(cfg.MIN_BUCKET_ROWS)
        self._kernels = {}

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def simple_string(self) -> str:
        n = len(self.state.specs) if self.state.specs is not None else "?"
        return f"TpuAdaptiveJoinReaderExec(side={self.side}, specs={n})"

    def _row_slice(self, batch: DeviceBatch, start: int, count: int
                   ) -> DeviceBatch:
        from spark_rapids_tpu.exec import kernel_cache as kc
        cap = bucket_rows(count, self.min_bucket)
        key = ("exch_slice", cap, batch.schema_key())
        if key not in self._kernels:
            self._kernels[key] = kc.get_kernel(
                key, lambda: lambda b, o, c: slice_span(b, o, c, cap))
        return self._kernels[key](batch,
                                  jnp.asarray(start, dtype=jnp.int32),
                                  jnp.asarray(count, dtype=jnp.int32))

    def execute(self):
        self.state.ensure()
        side = self.side
        batches = self.state.batches[side]

        def reader(spec) -> Iterator[DeviceBatch]:
            if isinstance(spec, CoalescedSpec):
                group = [h.get() for p in range(spec.start, spec.end)
                         for h in batches[p]]
                if group:
                    with timed(self.metrics, "adaptive.coalesce"):
                        out = group[0] if len(group) == 1 \
                            else concat_batches(group)
                    self.metrics.add_rows(out.num_rows)
                    self.metrics.add_batches()
                    self.state.release(side, range(spec.start, spec.end))
                    yield out
                else:
                    self.state.release(side, range(spec.start, spec.end))
            else:
                hs = batches[spec.partition]
                count = spec.row_end - spec.row_start
                if hs and count > 0:
                    first = hs[0].get()
                    with timed(self.metrics, "adaptive.split"):
                        # a replica spec spanning the whole partition
                        # (the non-split side) reuses the batch as-is
                        if spec.row_start == 0 and \
                                count == int(first.num_rows):
                            out = first
                        else:
                            out = self._row_slice(first, spec.row_start,
                                                  count)
                    self.metrics.add_rows(out.num_rows)
                    self.metrics.add_batches()
                    self.state.release(side, [spec.partition])
                    yield out
                else:
                    self.state.release(side, [spec.partition])

        return [reader(sp[side]) for sp in self.state.specs]


class _JoinSkewState:
    """Shared coordinator for runtime hot-bucket splitting at the
    map-output tracker (tentpole half of OptimizeSkewedJoin).

    Unlike :class:`_JoinAdaptiveState` (which materializes every reduce
    partition to size them), this consults the per-bucket byte totals
    the exchanges' map-output trackers aggregated as maps completed —
    blocks are still per-(map, bucket) when the split decision lands.
    A probe-side bucket over ``skew.bucketFactor`` × the nonzero median
    (and over ``minBucketBytes``) is split into S contiguous row chunks
    while the matching build-side bucket is shared across all S
    sub-partitions: counted as a broadcast when it is under
    ``broadcastThresholdBytes``, a replication otherwise (in-process the
    mechanism is one refcounted buffer either way; the distinction
    tracks which plan Spark would have picked).  Non-hot buckets stream
    straight from the held-back map output with no extra materialization.

    The probe side is the one the join type lets us split without
    duplicating preserved rows: the left for inner/left/semi/anti, the
    right for how='right' (its unmatched rows land in exactly one
    chunk; the replicated side only ever emits matched rows).  Full
    outer is ineligible and falls through to the adaptive reader."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, how: str,
                 conf_obj):
        import threading
        self._lock = threading.Lock()
        self.children = (left, right)
        self.how = how
        self.probe = 1 if how == "right" else 0
        self.factor = float(conf_obj.get(cfg.JOIN_SKEW_FACTOR))
        self.min_bucket_bytes = int(conf_obj.get(
            cfg.JOIN_SKEW_MIN_BUCKET_BYTES))
        self.max_splits = int(conf_obj.get(cfg.JOIN_SKEW_MAX_SPLITS))
        self.broadcast_threshold = int(conf_obj.get(
            cfg.JOIN_SKEW_BROADCAST_THRESHOLD))
        self.specs: Optional[List[Tuple]] = None
        self.outs: List = [None, None]       # per-side SkewMapOutput
        # hot partition -> refcounted concat handle, per side
        self.handles: List[Dict[int, object]] = [{}, {}]
        self._refs: List[Dict[int, int]] = [{}, {}]
        # hot partition -> [(row_start, row_count), ...] probe chunks
        self.chunks: Dict[int, List[Tuple[int, int]]] = {}

    # skew wraps in-process transports only, but fragment shipping may
    # still pickle the plan: the lock and pulled buffers are process-local
    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_lock", None)
        d["specs"] = None
        d["outs"] = [None, None]
        d["handles"] = [{}, {}]
        d["_refs"] = [{}, {}]
        d["chunks"] = {}
        return d

    def __setstate__(self, d):
        import threading
        self.__dict__.update(d)
        self._lock = threading.Lock()

    def ensure(self) -> None:
        with self._lock:
            if self.specs is not None:
                return
            self._plan_locked()

    def _plan_locked(self) -> None:
        from spark_rapids_tpu.mem.spill import register_or_hold
        from spark_rapids_tpu.obs import registry as obsreg
        from spark_rapids_tpu.obs.recorder import record_event
        outs = [c.skew_map_side() for c in self.children]
        self.outs = outs
        probe, build = self.probe, 1 - self.probe
        totals = outs[probe].totals
        rows = outs[probe].row_counts
        nonzero = sorted(s for s in totals if s > 0)
        median = nonzero[len(nonzero) // 2] if nonzero else 0
        cut = max(self.factor * median, self.min_bucket_bytes)
        hot = {p for p, s in enumerate(totals)
               if median and s > cut and rows[p] >= 2}
        reg = obsreg.get_registry()
        specs: List[Tuple] = []
        for p in range(len(totals)):
            if p not in hot:
                specs.append(("plain", p))
                continue
            n_splits = min(self.max_splits, rows[p],
                           max(2, -(-totals[p] // max(median, 1))))
            chunk = max(1, -(-rows[p] // n_splits))
            self.chunks[p] = [(st, min(chunk, rows[p] - st))
                              for st in range(0, rows[p], chunk)]
            n_splits = len(self.chunks[p])
            for side in (probe, build):
                bs = outs[side].fetch(p)
                merged = bs[0] if len(bs) == 1 else \
                    (concat_batches(bs) if bs else None)
                if merged is not None:
                    self.handles[side][p] = register_or_hold(merged)
                self._refs[side][p] = n_splits
            bcast = outs[build].totals[p] <= self.broadcast_threshold
            reg.inc_many(
                ("shuffle.skew.detected", 1),
                ("shuffle.skew.splits", n_splits),
                (("shuffle.skew.broadcasts" if bcast
                  else "shuffle.skew.replications"), 1))
            record_event("shuffle.bucketSplit", partition=p,
                         bucket_bytes=int(totals[p]),
                         median_bytes=int(median), splits=n_splits,
                         build_bytes=int(outs[build].totals[p]),
                         mode="broadcast" if bcast else "replicate")
            specs.extend(("split", p, j, n_splits)
                         for j in range(n_splits))
        self.specs = specs

    def release(self, side: int, p: int) -> None:
        # sub-partition readers run concurrently under the task pool
        with self._lock:
            self._refs[side][p] -= 1
            if self._refs[side][p] == 0:
                h = self.handles[side].pop(p, None)
                if h is not None:
                    h.close()


class TpuSkewJoinReaderExec(TpuExec):
    """One join side's view of the skew-split fetch plan (the
    CustomShuffleReader node of the skew half; shows in explain)."""

    def __init__(self, state: _JoinSkewState, side: int,
                 child: PhysicalPlan, conf_obj):
        super().__init__()
        self.state = state
        self.side = side
        self.children = (child,)
        self.min_bucket = conf_obj.get(cfg.MIN_BUCKET_ROWS)
        self._kernels = {}

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def simple_string(self) -> str:
        n = len(self.state.specs) if self.state.specs is not None else "?"
        return f"TpuSkewJoinReaderExec(side={self.side}, specs={n})"

    def _row_slice(self, batch: DeviceBatch, start: int, count: int
                   ) -> DeviceBatch:
        from spark_rapids_tpu.exec import kernel_cache as kc
        cap = bucket_rows(count, self.min_bucket)
        key = ("exch_slice", cap, batch.schema_key())
        if key not in self._kernels:
            self._kernels[key] = kc.get_kernel(
                key, lambda: lambda b, o, c: slice_span(b, o, c, cap))
        return self._kernels[key](batch,
                                  jnp.asarray(start, dtype=jnp.int32),
                                  jnp.asarray(count, dtype=jnp.int32))

    def execute(self):
        self.state.ensure()
        side = self.side
        is_probe = side == self.state.probe
        out = self.state.outs[side]

        def plain(p: int) -> Iterator[DeviceBatch]:
            for b in out.fetch(p):
                self.metrics.add_rows(b.num_rows)
                self.metrics.add_batches()
                yield b

        def split(p: int, j: int) -> Iterator[DeviceBatch]:
            try:
                h = self.state.handles[side].get(p)
                if h is None:
                    return
                whole = h.get()
                if is_probe:
                    start, count = self.state.chunks[p][j]
                    if count <= 0:
                        return
                    with timed(self.metrics, "skew.split"):
                        b = whole if count == int(whole.num_rows) \
                            else self._row_slice(whole, start, count)
                else:
                    # replicated/broadcast build bucket: every probe
                    # chunk joins against the same shared buffer
                    b = whole
                self.metrics.add_rows(b.num_rows)
                self.metrics.add_batches()
                yield b
            finally:
                self.state.release(side, p)

        return [plain(sp[1]) if sp[0] == "plain" else split(sp[1], sp[2])
                for sp in self.state.specs]


def wrap_join_children(left: PhysicalPlan, right: PhysicalPlan, how: str,
                       conf_obj) -> Tuple[PhysicalPlan, PhysicalPlan]:
    """Wrap a shuffled join's two exchange children in coordinated
    adaptive (or skew-splitting) readers — no-op unless both children
    are hash exchanges and the respective knob is enabled."""
    from spark_rapids_tpu.shuffle.exchange import (HashPartitioning,
                                                   TpuShuffleExchangeExec)
    eligible = (isinstance(left, TpuShuffleExchangeExec)
                and isinstance(right, TpuShuffleExchangeExec)
                and isinstance(left.partitioning, HashPartitioning)
                and isinstance(right.partitioning, HashPartitioning)
                and left.partitioning.num_partitions
                == right.partitioning.num_partitions)
    # skew splitting takes over the skew half of the adaptive reader for
    # eligible joins; ineligible shapes (full outer, shipped transports)
    # fall through to the adaptive reader
    if (eligible and conf_obj.get(cfg.JOIN_SKEW_ENABLED)
            and how in ("inner", "left", "right", "semi", "anti")
            and left.transport in ("local", "device")
            and right.transport in ("local", "device")):
        state = _JoinSkewState(left, right, how, conf_obj)
        return (TpuSkewJoinReaderExec(state, 0, left, conf_obj),
                TpuSkewJoinReaderExec(state, 1, right, conf_obj))
    if not conf_obj.get(cfg.ADAPTIVE_ENABLED):
        return left, right
    if not eligible:
        return left, right
    state = _JoinAdaptiveState(left, right, how, conf_obj)
    return (TpuAdaptiveJoinReaderExec(state, 0, left, conf_obj),
            TpuAdaptiveJoinReaderExec(state, 1, right, conf_obj))
