"""Out-of-core grace hash-join partitioning.

Reference analog: the GPU-joins-on-Hadoop partitioned hash join
(arXiv:1904.11201) grafted onto this engine's spill tiers — when a
join's per-partition build side exceeds ``join.buildSideBudgetBytes``,
both sides are hash-partitioned into 2^k *grace partitions* with a
murmur seed decorrelated from the exchange's bucketing (seed 42), every
partition slice is parked in the spill catalog at the coldest priority
(``GRACE_JOIN_PARTITION_PRIORITY``) and proactively demoted off-device,
then each grace partition is re-streamed and joined alone through the
unchanged ``_join_pair`` machinery.  A partition still over budget
recurses with the next level's seed; a partition that cannot shrink (one
hot key hashes to one bucket under every seed) falls back to streaming
the probe side chunk-by-chunk against the oversized build partition —
always correct, always terminating.

Bit-identity: grace partitioning only changes WHICH (build, probe-batch)
pairs ``_join_pair`` sees and in what order — each probe row still meets
exactly the build rows sharing its key (hash partitioning is exact on
the promoted, normalized key columns), so the output differs from the
unpartitioned run only in batch assembly order, which every consumer
already tolerates (and tests sort-normalize).

In-flight state is leak-free and pressure-aware: a ``GraceJoinState``
tracks every live partition handle, registers as a pressure spiller so
``handle_memory_pressure`` can reach in-flight join state, and a
``finally`` drains the catalog on any exit — including a mid-join
cancel that closes the generator.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, bucket_rows,
                                             concat_batches)
from spark_rapids_tpu.exec import sortkeys

_MAX_PARTS_LOG2 = 5          # 32-way cap per level (matches the conf doc)


def _level_seed(level: int) -> int:
    """Per-recursion-level murmur seed, deliberately != 42: rows arrive
    already routed by the exchange's seed-42 hash, and re-splitting with
    that seed would park an entire partition in one grace bucket."""
    s = (0x7F4A7C15 + level * 0x9E3779B9) & 0xFFFFFFFF
    return s - (1 << 32) if s >= (1 << 31) else s


def resolve_oocore(conf_obj) -> Optional[dict]:
    """Resolve the ``join.*`` out-of-core knobs into the stamp dict the
    planner attaches to a shuffled-join exec (``_oocore``); ``None``
    disables the budget check entirely (the one-knob revert — and the
    default for hand-built execs that never get stamped)."""
    if not conf_obj.get(cfg.JOIN_OOCORE_ENABLED):
        return None
    budget = int(conf_obj.get(cfg.JOIN_BUILD_BUDGET))
    if budget < 0:
        return None
    if budget == 0:
        # admission-machinery derivation: one admitted query's fair
        # share of the scheduler budget (sched/service.py's own
        # default chain: explicit conf > HBM pool > 8 GiB)
        base = int(conf_obj.get(cfg.SCHED_MEMORY_BUDGET) or 0)
        if base <= 0:
            try:
                from spark_rapids_tpu.mem.device import TpuDeviceManager
                base = int(TpuDeviceManager.get().hbm_budget)
            except Exception:
                base = 0
        if base <= 0:
            base = 8 << 30
        budget = max(1, base // max(1, int(conf_obj.get(
            cfg.SCHED_MAX_CONCURRENT))))
    return {
        "budget": budget,
        "parts_log2": max(0, int(conf_obj.get(
            cfg.JOIN_OOCORE_PARTITIONS_LOG2))),
        "max_recursion": max(0, int(conf_obj.get(
            cfg.JOIN_OOCORE_MAX_RECURSION))),
    }


def _fanout(build_bytes: int, oocore: dict, level: int) -> int:
    """2^k grace partitions: the smallest k whose expected per-partition
    build size fits the budget (explicit partitionsLog2 pins level 0)."""
    if level == 0 and oocore["parts_log2"] > 0:
        return 1 << min(oocore["parts_log2"], _MAX_PARTS_LOG2)
    k = 1
    while (build_bytes >> k) > oocore["budget"] and k < _MAX_PARTS_LOG2:
        k += 1
    return 1 << k


def promoted_key_dtypes(exec_obj) -> List[Optional[dt.DType]]:
    """The common promoted dtype per key position, or None for keys
    that hash as-is (strings; already-equal dtypes).

    Both sides MUST cast to the promoted dtype BEFORE hashing:
    ``_hash_int`` and ``_hash_long`` disagree for the same value at
    different widths, so an int32 key on one side and int64 on the
    other would route equal keys to different grace partitions."""
    lsch = exec_obj.children[0].schema
    rsch = exec_obj.children[1].schema
    out: List[Optional[dt.DType]] = []
    for lk, rk in zip(exec_obj.left_keys, exec_obj.right_keys):
        a, b = lsch.field(lk).dtype, rsch.field(rk).dtype
        if a.is_string or b.is_string or a == b:
            out.append(None)
        else:
            out.append(dt.promote(a, b))
    return out


def _grace_key_colval(batch: DeviceBatch, name: str,
                      tgt: Optional[dt.DType]):
    from spark_rapids_tpu.exec.tpu_aggregate import normalize_key
    from spark_rapids_tpu.expr.eval_tpu import ColVal
    c = batch.column(name)
    v = normalize_key(ColVal(c.dtype, c.data, c.validity, c.lengths,
                             vbits=c.vbits, nonnull=c.nonnull))
    if tgt is not None and v.dtype != tgt:
        v = normalize_key(ColVal(tgt, v.data.astype(tgt.to_np()),
                                 v.validity))
    return v


def split_batch(kernels: dict, batch: DeviceBatch,
                key_names: Sequence[str],
                key_dtypes: Sequence[Optional[dt.DType]],
                seed: int, n_parts: int,
                min_bucket: int = 16) -> List[Optional[DeviceBatch]]:
    """Hash-partition one device batch into ``n_parts`` sub-batches by
    the salted murmur of its (promoted, normalized) key columns.

    Same kernel split as the exchange's map side: a per-schema target
    kernel (seed is a traced operand, so one program serves every
    recursion level), the SHARED per-capacity partition-order sort
    (sortkeys.shared_partition_order — never embed an argsort in a
    per-schema jit), a per-schema apply kernel, then per-count bucketed
    slice kernels.  Returns one batch (or None when empty) per
    partition."""
    from spark_rapids_tpu.exec import kernel_cache as kc
    from spark_rapids_tpu.expr.eval_tpu import hash_colval
    from spark_rapids_tpu.shuffle.exchange import slice_span
    knames = tuple(key_names)
    kdts = tuple(None if d is None else d.id for d in key_dtypes)
    tkey = ("grace_target", n_parts, knames, kdts, batch.schema_key())
    if tkey not in kernels:
        kn, kd = list(key_names), list(key_dtypes)

        def targets(b, sd):
            h = jnp.full((b.capacity,), jnp.int32(0)) + sd
            for nm, td in zip(kn, kd):
                h = hash_colval(_grace_key_colval(b, nm, td), h)
            m = h % np.int32(n_parts)
            t = jnp.where(m < 0, m + n_parts, m).astype(jnp.int32)
            return jnp.where(b.row_mask(), t, jnp.int32(n_parts))
        kernels[tkey] = kc.get_kernel(tkey, lambda: targets)
    t = kernels[tkey](batch, jnp.asarray(seed, dtype=jnp.int32))
    order = sortkeys.shared_partition_order(t)
    akey = ("grace_apply", n_parts, batch.schema_key())
    if akey not in kernels:
        def apply_order(b, tt, o):
            counts = jnp.zeros((n_parts,), dtype=jnp.int32).at[tt].add(
                (tt < n_parts).astype(jnp.int32), mode="drop")
            exists = b.row_mask()
            cols = [c.gather(o, jnp.take(exists, o)) for c in b.columns]
            return DeviceBatch(b.names, cols, b.num_rows), counts
        kernels[akey] = kc.get_kernel(akey, lambda: apply_order)
    reordered, counts = kernels[akey](batch, t, order)
    counts = np.asarray(counts)
    out: List[Optional[DeviceBatch]] = [None] * n_parts
    off = 0
    for p in range(n_parts):
        c = int(counts[p])
        if c:
            out_cap = bucket_rows(c, min_bucket)
            skey = ("grace_slice", out_cap, reordered.schema_key())
            if skey not in kernels:
                kernels[skey] = kc.get_kernel(
                    skey, lambda oc=out_cap:
                    lambda b, o, cc: slice_span(b, o, cc, oc))
            out[p] = kernels[skey](reordered,
                                   jnp.asarray(off, dtype=jnp.int32),
                                   jnp.asarray(c, dtype=jnp.int32))
        off += c
    return out


class GraceJoinState:
    """Every live grace-partition handle of one in-flight join.

    Registered as a pressure spiller so ``handle_memory_pressure``
    reaches parked join state (the caller's generator holds the strong
    reference; the spill module only keeps a weakref).  ``close_all``
    is the cancel/error drain — after it, the join owns zero catalog
    entries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict = {}          # id(handle) -> handle

    def track(self, handle) -> None:
        with self._lock:
            self._handles[id(handle)] = handle

    def untrack(self, handle) -> None:
        with self._lock:
            self._handles.pop(id(handle), None)

    def pressure_spill(self, bytes_needed: int) -> int:
        from spark_rapids_tpu.mem.spill import StorageTier
        with self._lock:
            handles = list(self._handles.values())
        freed = 0
        for h in handles:
            if freed >= bytes_needed:
                break
            try:
                if h.tier == StorageTier.DEVICE:
                    freed += h.spill()
            except Exception:
                pass      # racing close; the tracker sweep is advisory
        return freed

    def close_all(self) -> None:
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
        for h in handles:
            try:
                h.close()
            except Exception:
                pass


class _Part:
    """One parked partition slice: spill handle + host-known stats (the
    handle's batch may be off-device, so sizes are captured at park
    time, never re-measured)."""

    __slots__ = ("handle", "nbytes", "rows")

    def __init__(self, handle, nbytes: int, rows: int):
        self.handle = handle
        self.nbytes = nbytes
        self.rows = rows


def _park(state: GraceJoinState, batch: DeviceBatch) -> _Part:
    """Register one partition slice at the coldest spill priority and
    proactively demote it off-device: grace partitions are by
    definition not being joined right now, device residency stays
    bounded by the one partition in flight, and the later ``get()``
    unspill is the counter-visible proof of the re-stream."""
    from spark_rapids_tpu.mem import spill as sp
    nb, rows = int(batch.nbytes()), int(batch.num_rows)
    h = sp.register_or_hold(batch,
                            priority=sp.GRACE_JOIN_PARTITION_PRIORITY)
    state.track(h)
    h.spill()
    return _Part(h, nb, rows)


def _unpark(state: GraceJoinState, part: _Part) -> DeviceBatch:
    b = part.handle.get()
    state.untrack(part.handle)
    part.handle.close()
    return b


def _materialize(state: GraceJoinState, parts: List[_Part],
                 count_spilled: bool = False) -> Optional[DeviceBatch]:
    from spark_rapids_tpu.obs import registry as obsreg
    from spark_rapids_tpu.mem.spill import StorageTier
    if not parts:
        return None
    if count_spilled:
        spilled = sum(p.nbytes for p in parts
                      if p.handle.tier != StorageTier.DEVICE)
        if spilled:
            obsreg.get_registry().inc("join.grace.spilledBuildBytes",
                                      spilled)
    return concat_batches([_unpark(state, p) for p in parts])


def _close_parts(state: GraceJoinState, parts: List[_Part]) -> None:
    for p in parts:
        state.untrack(p.handle)
        p.handle.close()


def _split_parts(exec_obj, state: GraceJoinState, parts: List[_Part],
                 key_names, key_dtypes, seed: int,
                 n_parts: int) -> List[List[_Part]]:
    """Re-partition parked slices into ``n_parts`` child partitions
    (recursion step): each slice is re-streamed, split with the new
    level's seed, and its children parked; the parent handle closes."""
    out: List[List[_Part]] = [[] for _ in range(n_parts)]
    for p in parts:
        b = _unpark(state, p)
        for i, s in enumerate(split_batch(exec_obj._kernels, b,
                                          key_names, key_dtypes, seed,
                                          n_parts)):
            if s is not None:
                out[i].append(_park(state, s))
    return out


def _empty_side(exec_obj, side: int) -> DeviceBatch:
    from spark_rapids_tpu.exec.tpu_join import _empty_like
    return _empty_like(exec_obj.children[side].schema)


def _run_level(exec_obj, state: GraceJoinState, build: List[_Part],
               probe: List[_Part], level: int, oocore: dict,
               key_dtypes, build_is_left: bool,
               gathered: bool) -> Iterator[DeviceBatch]:
    """Join ONE grace partition: recurse while over budget and
    shrinking, else re-stream and join through the unchanged
    ``_join_pair`` (streamed mode probes chunk-by-chunk — the fallback
    for an unsplittable hot key is this same loop)."""
    from spark_rapids_tpu.mem import spill as sp
    from spark_rapids_tpu.obs import recorder as obsrec
    from spark_rapids_tpu.obs import registry as obsreg
    reg = obsreg.get_registry()
    how = exec_obj.how
    if not build and not probe:
        return
    build_bytes = sum(p.nbytes for p in build)
    over = build_bytes > oocore["budget"]
    bkeys = exec_obj.left_keys if build_is_left else exec_obj.right_keys
    pkeys = exec_obj.right_keys if build_is_left else exec_obj.left_keys
    if over and level < oocore["max_recursion"]:
        n_child = _fanout(build_bytes, oocore, level)
        seed = _level_seed(level + 1)
        child_b = _split_parts(exec_obj, state, build, bkeys,
                               key_dtypes, seed, n_child)
        nonempty = sum(1 for part in child_b if part)
        if nonempty >= 2:
            # progress: every child partition is strictly smaller
            reg.gauge_max("join.grace.maxRecursionDepth", level + 1)
            reg.inc("join.grace.partitions", n_child)
            obsrec.record_event("join.graceRecurse", level=level + 1,
                                partitions=n_child,
                                buildBytes=build_bytes,
                                budget=oocore["budget"])
            child_p = _split_parts(exec_obj, state, probe, pkeys,
                                   key_dtypes, seed, n_child)
            for i in range(n_child):
                yield from _run_level(exec_obj, state, child_b[i],
                                      child_p[i], level + 1, oocore,
                                      key_dtypes, build_is_left,
                                      gathered)
            return
        # one hot key: re-hashing cannot shrink this partition under
        # ANY seed — stop recursing and fall back below (the children
        # all landed in one bucket; they ARE the partition)
        build = [p for part in child_b for p in part]
        reg.inc("join.grace.fallbacks")
        obsrec.record_event("join.graceFallback", level=level,
                            buildBytes=build_bytes,
                            budget=oocore["budget"], reason="noShrink")
    elif over:
        reg.inc("join.grace.fallbacks")
        obsrec.record_event("join.graceFallback", level=level,
                            buildBytes=build_bytes,
                            budget=oocore["budget"],
                            reason="maxRecursion")

    reg.inc("join.grace.restreams")
    if gathered:
        # right/full: unmatched-build emission needs the whole stream
        # side of the partition, so the pair joins as two single
        # batches (partition key-disjointness makes the per-partition
        # union exact: every row is in exactly one partition, so each
        # unmatched row is emitted exactly once)
        b = _materialize(state, build, count_spilled=True)
        s = _materialize(state, probe)
        if b is None and s is None:
            return
        if build_is_left:
            lb, rb = b, s
        else:
            lb, rb = s, b
        lb = lb if lb is not None else _empty_side(exec_obj, 0)
        rb = rb if rb is not None else _empty_side(exec_obj, 1)
        yield from exec_obj._join_pair(lb, rb)
        return
    # streamed (inner/left/semi/anti, build = right): probe handles
    # re-stream one at a time against the held build partition
    b = _materialize(state, build, count_spilled=True)
    if b is None:
        if how in ("inner", "semi"):
            _close_parts(state, probe)
            return
        b = _empty_side(exec_obj, 1)
    with sp.register_or_hold(b) as rh:
        for p in probe:
            pb = _unpark(state, p)
            if not int(pb.num_rows):
                continue
            yield from exec_obj._join_pair(pb, rh.get())


def grace_join(exec_obj, probe_input, build_batches: List[DeviceBatch],
               build_bytes: int, oocore: dict, build_is_left: bool,
               gathered: bool) -> Iterator[DeviceBatch]:
    """Top-level grace join for one co-partitioned partition pair.

    ``probe_input`` is an iterable of stream-side device batches (the
    raw partition iterator in streamed mode — never concatenated);
    ``build_batches`` the already-collected build side that measured
    over budget.  Yields joined batches; all partition state drains
    through the spill catalog on any exit, including generator close
    (mid-join cancel)."""
    from spark_rapids_tpu.mem import spill as sp
    from spark_rapids_tpu.obs import recorder as obsrec
    from spark_rapids_tpu.obs import registry as obsreg
    reg = obsreg.get_registry()
    state = GraceJoinState()
    sp.register_pressure_spiller(state)
    n_parts = _fanout(build_bytes, oocore, 0)
    key_dtypes = promoted_key_dtypes(exec_obj)
    bkeys = exec_obj.left_keys if build_is_left else exec_obj.right_keys
    pkeys = exec_obj.right_keys if build_is_left else exec_obj.left_keys
    seed = _level_seed(0)
    reg.inc_many(("join.grace.activations", 1),
                 ("join.grace.partitions", n_parts))
    obsrec.record_event("join.graceActivated", how=exec_obj.how,
                        buildBytes=build_bytes, budget=oocore["budget"],
                        partitions=n_parts)
    exec_obj.metrics.add_extra("join.gracePartitions", n_parts)
    try:
        build_parts: List[List[_Part]] = [[] for _ in range(n_parts)]
        for b in build_batches:
            for i, s in enumerate(split_batch(
                    exec_obj._kernels, b, bkeys, key_dtypes, seed,
                    n_parts)):
                if s is not None:
                    build_parts[i].append(_park(state, s))
        del build_batches
        probe_parts: List[List[_Part]] = [[] for _ in range(n_parts)]
        for pb in probe_input:
            if not int(pb.num_rows):
                continue
            for i, s in enumerate(split_batch(
                    exec_obj._kernels, pb, pkeys, key_dtypes, seed,
                    n_parts)):
                if s is not None:
                    probe_parts[i].append(_park(state, s))
        for i in range(n_parts):
            yield from _run_level(exec_obj, state, build_parts[i],
                                  probe_parts[i], 0, oocore,
                                  key_dtypes, build_is_left, gathered)
    finally:
        state.close_all()
