"""Basic TPU execs: transitions, project, filter, range, union, limit,
coalesce, expand.

Reference analogs:
  * HostToDeviceExec / DeviceToHostExec — GpuRowToColumnarExec /
    GpuColumnarToRowExec / HostColumnarToGpu (reference:
    GpuRowToColumnarExec.scala:430-736, GpuColumnarToRowExec.scala:38-306)
  * TpuProjectExec / TpuFilterExec — basicPhysicalOperators.scala:64,132
  * TpuRangeExec — basicPhysicalOperators.scala:187 (ColumnVector.sequence)
  * TpuUnionExec / TpuCoalesceExec — basicPhysicalOperators.scala:308,346
  * TpuLocalLimit/GlobalLimit — limit.scala
  * TpuCoalesceBatchesExec — GpuCoalesceBatches.scala:40-711
  * TpuExpandExec — GpuExpandExec.scala:67

Each exec jit-compiles its kernel once per (schema, capacity-bucket); the
bucketed static shapes bound XLA recompiles (SURVEY.md §7 hard part #1).
The filter's "mask -> stable argsort -> gather" compaction is the XLA
equivalent of cudf's stream-compaction ``Table.filter``.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             bucket_rows, concat_batches,
                                             from_arrow, to_arrow)
from spark_rapids_tpu.exec.base import (CoalesceGoal, PhysicalPlan,
                                        RequireSingleBatch, TargetSize,
                                        TpuExec, timed)
from spark_rapids_tpu.exec.cpu import concat_tables, _empty_table
from spark_rapids_tpu.expr import eval_tpu, ir
from spark_rapids_tpu.mem.device import tpu_semaphore
from spark_rapids_tpu.plan.logical import Field, Schema


class HostToDeviceExec(TpuExec):
    """Upload host Arrow batches into padded DeviceBatches.

    String-outlier guard (VERDICT r2 weak #4): the padded byte-matrix
    costs capacity x max_len bytes, so ONE long string inflates every
    row of its batch.  When the padded string payload would exceed the
    conf budget, the incoming table SPLITS into row slices — each
    slice re-measures its own max_len, so the rows around the outlier
    pay its width while the rest of the batch stays narrow (the
    offsets+bytes rationale of cudf, GpuColumnVector.java:40, adapted
    to static shapes)."""

    def __init__(self, child: PhysicalPlan, min_bucket: int = 16,
                 string_budget: int = 256 << 20):
        super().__init__()
        self.children = (child,)
        self.min_bucket = min_bucket
        self.string_budget = string_budget

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _split_for_strings(self, t):
        import pyarrow.compute as pc
        from spark_rapids_tpu.columnar.batch import (_bucket_strlen,
                                                     bucket_rows)
        if t.num_rows <= self.min_bucket:
            return [t]
        padded = 0
        for col, field_ in zip(t.columns, t.schema):
            if pa.types.is_string(field_.type) or \
                    pa.types.is_large_string(field_.type):
                ml = pc.max(pc.binary_length(col)).as_py() or 0
                padded += _bucket_strlen(int(ml)) * \
                    bucket_rows(t.num_rows, self.min_bucket)
        if padded <= self.string_budget:
            return [t]
        half = t.num_rows // 2
        return (self._split_for_strings(t.slice(0, half)) +
                self._split_for_strings(t.slice(half)))

    def execute(self):
        def run(it):
            for t in it:
                for piece in self._split_for_strings(t):
                    with tpu_semaphore(self.metrics):
                        with timed(self.metrics, "transition.upload"):
                            b = from_arrow(piece, self.min_bucket)
                        self.metrics.num_output_rows += piece.num_rows
                        self.metrics.add_batches()
                        yield b
        return [run(it) for it in self.children[0].execute()]


class DeviceToHostExec(PhysicalPlan):
    """Download DeviceBatches to host Arrow (the terminal transition,
    GpuBringBackToHost analog)."""

    def __init__(self, child: PhysicalPlan):
        super().__init__()
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        def run(it):
            for b in it:
                yield to_arrow(b)
        return [run(it) for it in self.children[0].execute()]


class TpuProjectExec(TpuExec):
    def __init__(self, child: PhysicalPlan, exprs: Sequence[ir.Expression],
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.exprs = list(exprs)
        self._schema = schema
        self._kernel = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def _impl(self, batch: DeviceBatch, nr, pid, offset) -> DeviceBatch:
        from spark_rapids_tpu.exec import context
        from spark_rapids_tpu.exec.fused_stage import canonical_names
        # pid/offset are tracers here: one compiled kernel serves every
        # partition (partition-dependent exprs read them via the context).
        # nr is the real row count, passed OUTSIDE the (possibly donated)
        # batch pytree — see fused_stage.rows_detached.
        # Output names are POSITIONAL placeholders: the kernel-cache key
        # carries no column names (identical projections under different
        # aliases share one compile) and execute() restamps the real
        # schema names host-side.
        batch.num_rows = nr
        with context.task_context(pid, offset):
            cols = [eval_tpu.evaluate(e, batch).to_column()
                    for e in self.exprs]
        return DeviceBatch(canonical_names(len(cols)), cols,
                           batch.num_rows)

    def execute(self):
        import functools
        import types
        from spark_rapids_tpu.exec import fused_stage as fs
        from spark_rapids_tpu.exec import kernel_cache as kc
        from spark_rapids_tpu.obs import registry as obsreg
        donate = fs.donate_ok(self.children[0],
                              getattr(self, "_donate_enabled", False))
        # detach from self: the cached closure must not pin the exec
        # instance (and through it the whole child plan subtree)
        shim = types.SimpleNamespace(exprs=self.exprs)
        key = ("project", kc.exprs_sig(self.exprs))
        factory = lambda: functools.partial(type(self)._impl, shim)  # noqa: E731
        fs.build_kernel(self, key, factory, donate)

        needs_ctx = any(
            ir.collect(e, lambda n: isinstance(
                n, (ir.SparkPartitionID, ir.MonotonicallyIncreasingID)))
            for e in self.exprs)
        names = self._schema.names

        def run(pid, it):
            reg = obsreg.get_registry()
            offset = 0
            for b in it:
                if needs_ctx:
                    # row-offset tracking costs one host sync per batch;
                    # only pay it when a partition-dependent expr exists
                    # (read BEFORE dispatch — donation consumes b)
                    nr = int(b.num_rows)
                out = fs.dispatch(self, "project.eval", donate, reg,
                                  b, pid, offset, key=key,
                                  impl_factory=factory)
                out = DeviceBatch(names, out.columns, out.num_rows)
                if needs_ctx:
                    offset += nr
                self.metrics.add_batches()
                yield out
        return [run(pid, it) for pid, it in
                enumerate(self.children[0].execute())]


def compact(batch: DeviceBatch, keep: jnp.ndarray) -> DeviceBatch:
    """Stream compaction: stable-partition kept rows to the front.

    XLA formulation of cudf's boolean-mask ``Table.filter``: cumsum the
    mask for destination slots, then SCATTER kept rows (dropped rows
    scatter out of bounds).  No sort — XLA sort compiles are minutes-
    scale on TPU at SQL batch sizes, scatter is milliseconds."""
    from spark_rapids_tpu.columnar.batch import compact_arrays
    cap = batch.capacity
    keep = keep & batch.row_mask()
    count = jnp.sum(keep.astype(jnp.int32))
    dest = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1, cap)
    cols = [DeviceColumn(c.dtype, *compact_arrays(
        keep, dest, c.data, c.validity, c.lengths, c.elem_validity))
        for c in batch.columns]
    return DeviceBatch(batch.names, cols, count)


class TpuFilterExec(TpuExec):
    def __init__(self, child: PhysicalPlan, condition: ir.Expression):
        super().__init__()
        self.children = (child,)
        self.condition = condition
        self._kernel = None

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _impl(self, batch: DeviceBatch, nr, pid, offset) -> DeviceBatch:
        from spark_rapids_tpu.exec import context
        # a standalone filter must see the task context too: a
        # partition-dependent condition (spark_partition_id(),
        # monotonically_increasing_id()) otherwise evaluates against
        # the context DEFAULT (0, 0) inside the jitted kernel and
        # silently keeps/drops the wrong rows on every partition
        batch.num_rows = nr
        with context.task_context(pid, offset):
            v = eval_tpu.evaluate(self.condition, batch)
        return compact(batch, v.data.astype(jnp.bool_) & v.validity)

    def execute(self):
        import functools
        import types
        from spark_rapids_tpu.exec import fused_stage as fs
        from spark_rapids_tpu.exec import kernel_cache as kc
        from spark_rapids_tpu.obs import registry as obsreg
        donate = fs.donate_ok(self.children[0],
                              getattr(self, "_donate_enabled", False))
        shim = types.SimpleNamespace(condition=self.condition)
        key = ("filter", kc.expr_sig(self.condition))
        factory = lambda: functools.partial(type(self)._impl, shim)  # noqa: E731
        fs.build_kernel(self, key, factory, donate)

        needs_ctx = bool(ir.collect(
            self.condition, lambda n: isinstance(
                n, (ir.SparkPartitionID, ir.MonotonicallyIncreasingID))))
        names = self.schema.names

        def run(pid, it):
            reg = obsreg.get_registry()
            offset = 0
            for b in it:
                if needs_ctx:
                    # offset accumulates INPUT rows (the condition sees
                    # pre-compaction positions); host sync only on the
                    # partition-dependent path, read BEFORE dispatch
                    nr = int(b.num_rows)
                out = fs.dispatch(self, "filter.eval", donate, reg,
                                  b, pid, offset, key=key,
                                  impl_factory=factory)
                # the kernel's compact keeps the (ABI-erased) input
                # names; restamp the real schema host-side
                out = DeviceBatch(names, out.columns, out.num_rows)
                if needs_ctx:
                    offset += nr
                yield out
        return [run(pid, it) for pid, it in
                enumerate(self.children[0].execute())]


class TpuRangeExec(TpuExec):
    def __init__(self, start: int, end: int, step: int, num_partitions: int,
                 max_batch_rows: int = 1 << 22):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)
        self.max_batch_rows = max_batch_rows
        self._schema = Schema([Field("id", dt.INT64, False)])

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        total = max(0, -(-(self.end - self.start) // self.step)
                    if self.step != 0 else 0)
        per = (total + self.num_partitions - 1) // self.num_partitions or 1

        def part(i):
            lo = min(i * per, total)
            hi = min(lo + per, total)
            for off in range(lo, max(hi, lo + 1), self.max_batch_rows):
                n = min(self.max_batch_rows, hi - off)
                if n <= 0 and off != lo:
                    break
                n = max(n, 0)
                cap = bucket_rows(n)
                first = self.start + off * self.step
                data = first + jnp.arange(cap, dtype=jnp.int64) * self.step
                valid = jnp.arange(cap) < n
                data = jnp.where(valid, data, 0)
                col = DeviceColumn(dt.INT64, data, valid, None)
                yield DeviceBatch(["id"], [col], n)
                if hi == lo:
                    break
        return [part(i) for i in range(self.num_partitions)]


class TpuUnionExec(TpuExec):
    def __init__(self, children: Sequence[PhysicalPlan]):
        super().__init__()
        self.children = tuple(children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        parts = []
        for c in self.children:
            # unify column names to the union schema
            names = self.schema.names

            def run(it, names=names):
                for b in it:
                    yield DeviceBatch(names, b.columns, b.num_rows)
            for it in c.execute():
                parts.append(run(it))
        return parts


class TpuGlobalLimitExec(TpuExec):
    def __init__(self, child: PhysicalPlan, n: int):
        super().__init__()
        self.children = (child,)
        self.n = n

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        def run():
            remaining = self.n
            for it in self.children[0].execute():
                for b in it:
                    if remaining <= 0:
                        return
                    rows = int(b.num_rows)
                    take = min(remaining, rows)
                    remaining -= take
                    if take == rows:
                        yield b
                    else:
                        yield DeviceBatch(b.names, b.columns, take)
        return [run()]


class TpuCoalesceBatchesExec(TpuExec):
    """Goal-driven batch concatenation (GpuCoalesceBatches analog)."""

    def __init__(self, child: PhysicalPlan, goal: CoalesceGoal):
        super().__init__()
        self.children = (child,)
        self.goal = goal

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _emit(self, pending: List[DeviceBatch]) -> Optional[DeviceBatch]:
        if not pending:
            return None
        out = concat_batches(pending)
        return out

    def execute(self):
        target = self.goal.bytes if isinstance(self.goal, TargetSize) \
            else None

        def run(it):
            pending: List[DeviceBatch] = []
            pending_bytes = 0
            for b in it:
                if int(b.num_rows) == 0 and pending:
                    continue
                pending.append(b)
                pending_bytes += b.nbytes()
                if target is not None and pending_bytes >= target:
                    out = self._emit(pending)
                    pending, pending_bytes = [], 0
                    if out is not None:
                        self.metrics.add_batches()
                        yield out
            out = self._emit(pending)
            if out is not None:
                self.metrics.add_batches()
                yield out
        if isinstance(self.goal, RequireSingleBatch):
            # single batch across ALL partitions
            def run_all():
                batches: List[DeviceBatch] = []
                for it in self.children[0].execute():
                    batches.extend(it)
                if not batches:
                    return
                yield concat_batches(batches)
            return [run_all()]
        return [run(it) for it in self.children[0].execute()]


class TpuExpandExec(TpuExec):
    def __init__(self, child: PhysicalPlan,
                 projections: Sequence[Sequence[ir.Expression]],
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.projections = projections
        self._schema = schema
        self._kernels = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        if self._kernels is None:
            from spark_rapids_tpu.exec import kernel_cache as kc
            from spark_rapids_tpu.exec.fused_stage import canonical_names

            def mk(proj):
                n_out = len(proj)

                def impl(batch):
                    cols = [eval_tpu.evaluate(e, batch).to_column()
                            for e in proj]
                    # positional output names (the erased-ABI/PR-4
                    # scheme); run() restamps the real schema
                    return DeviceBatch(canonical_names(n_out), cols,
                                       batch.num_rows)
                return kc.get_kernel(
                    ("expand", kc.exprs_sig(proj)), lambda: impl)
            self._kernels = [mk(p) for p in self.projections]

        names = self._schema.names

        def run(it):
            from spark_rapids_tpu.exec import kernel_abi
            for b in it:
                eb = kernel_abi.erase(b)
                for k in self._kernels:
                    out = k(eb)
                    yield DeviceBatch(names, out.columns, out.num_rows)
        return [run(it) for it in self.children[0].execute()]
