"""TPU hash-aggregate exec.

Analog of ``GpuHashAggregateExec`` (reference: aggregate.scala:302-997):
per-batch *update* aggregation, buffered partial results, concat, *merge*
aggregation, then a final projection — the exact three-phase flow of the
reference (see comments at aggregate.scala:326-421), with cudf's
``Table.groupBy.aggregate`` replaced by a TPU-friendly sort-based segmented
reduction:

  1. encode grouping keys to total-order uint64 keys (exec/sortkeys.py)
  2. one stable ``jnp.lexsort`` brings equal keys adjacent
  3. group boundaries -> segment ids; ``jax.ops.segment_{sum,min,max}``
     computes every aggregate in fixed-shape space
  4. group count is the only host sync (the new batch's num_rows)

Aggregate functions follow the reference's update/merge pair structure
(reference: AggregateFunctions.scala:531 — each ``CudfAggregate`` declares
updateAggregate and mergeAggregate).  NaN/-0.0 key canonicalization matches
Spark's NormalizeFloatingNumbers semantics (parity-critical).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             bucket_rows, concat_batches)
from spark_rapids_tpu.exec.base import PhysicalPlan, TpuExec, timed
from spark_rapids_tpu.exec import scans, sortkeys
from spark_rapids_tpu.expr import eval_tpu, ir
from spark_rapids_tpu.expr.eval_tpu import ColVal
from spark_rapids_tpu.plan.logical import Schema

_BIG = np.int64(1 << 62)

# capacity ladder engages only when cap/4 reaches this rung size: below
# it the second lax.cond branch's compile time would dominate
# small-batch suites (tests may lower it to cover both branches)
_LADDER_MIN_RUNG = 1 << 18


@dataclass
class _SortedCtx:
    """Sorted-space grouping context shared by all aggregate updates in
    one kernel.

    Rows are ordered by grouping key (stable LSD radix over packed
    digits, sortkeys.radix_order_digits) so equal keys are adjacent and
    every segment reduction becomes SCATTER-FREE dense work: a masked
    take into sorted order, a cumsum or segmented associative scan, and
    one gather at group-end positions.  Measured on the bench chip,
    dynamic scatter-adds run ~7x slower than gathers (~290 ms vs ~40 ms
    per 4M elements), which made the round-3 scatter-based
    segment_sum formulation the whole aggregate cost."""

    order: jnp.ndarray        # sorted row order (original indices)
    new: jnp.ndarray          # sorted space: row starts a new group
    gid_sorted: jnp.ndarray   # group id per sorted row
    start_pos: jnp.ndarray    # [cap] sorted-space first row of group g
    end_pos: jnp.ndarray      # [cap] sorted-space last row of group g
    sorted_mask: jnp.ndarray  # sorted-space "row exists"
    cap: int
    row_mask: jnp.ndarray     # original-space "row exists"
    n_groups: jnp.ndarray     # scalar
    # narrow fast path: the fully-packed sorted u32 key, and (when the
    # single key is invertibly encoded) its (vbits, nullable, dtype)
    # layout — lets gather_group_keys reconstruct representative keys
    # arithmetically instead of through original-row gathers
    sorted_key: Optional[jnp.ndarray] = None
    key_inverse: Optional[Tuple] = None
    # kernel backend for the segment reductions ('xla' | 'pallas'):
    # per-REDUCTION selection with fallback — see kernels/segreduce.py
    backend: str = "xla"
    # tile budget pinned by the enclosing kernel's cache key (None =
    # the live kernel.pallas.tileBytes knob): the segreduce gather
    # plans its source tiles from THIS value, so a concurrent session
    # reconfiguring the knob between key computation and trace cannot
    # cache a kernel whose geometry disagrees with its key
    tile_bytes: "Optional[int]" = None

    # -- scatter-free segment reductions -------------------------------
    #
    # Cost discipline (all numbers measured on the bench chip, see
    # PERF.md): gathers dominate — ~7.6 ms per 1M u32/i32/f64 lookups
    # and 3x that for x64-emulated i64 — so every reduction pre-masks
    # in ORIGINAL row space (dense elementwise, ~1 ms per 4M) and pays
    # exactly ONE value gather into sorted space; i64 end-position
    # gathers are narrowed to i32 whenever a vbits hint bounds the sum.
    #
    # Under ``kernel.backend=pallas`` the gather and the segmented scan
    # fuse into ONE single-pass Pallas kernel (kernels/segreduce.py):
    # the sorted copy and the standalone scan array never materialize.
    # Each reduction selects independently; unsupported shapes/dtypes
    # keep the XLA chain below (per-kernel fallback, never the whole
    # aggregate).
    def take_sorted(self, x: jnp.ndarray) -> jnp.ndarray:
        return jnp.take(x, self.order, axis=0)

    def _pallas_op(self, op, dtype, ndim: int = 1) -> Optional[str]:
        """op-key when this reduction runs the Pallas kernel, else
        None (selection + hit/fallback accounting happen here, at
        trace time of the enclosing cached aggregate kernel)."""
        from spark_rapids_tpu.kernels import backend as kb
        from spark_rapids_tpu.kernels import segreduce as kseg
        name = kseg.op_name(op)
        ok, reason = kseg.supported(self.cap, dtype, name, ndim)
        bk = kb.choose("agg.segreduce", self.backend, ok,
                       reason or "unsupported")
        return name if bk == kb.PALLAS else None

    def seg_sum(self, x: jnp.ndarray, mask: jnp.ndarray,
                out_np=None, narrow_bits: Optional[int] = None
                ) -> jnp.ndarray:
        """Per-group sum over rows where mask (both original space).

        ``x`` stays in its input dtype through the gather (narrow
        gathers are 3x cheaper than emulated-i64 ones) and widens to
        ``out_np`` after.  Integers use global cumsum + end-position
        differences (exact under two's-complement wraparound); a
        ``narrow_bits`` hint with narrow_bits+log2(cap) <= 31 keeps the
        whole chain in native i32.  Floats use the segmented scan: a
        global float cumsum would leak +/-inf and rounding error across
        group boundaries through the differences.  (The Pallas path
        computes every variant as a fused gather+segmented-add — equal
        to the cumsum-difference formulation exactly, ints being exact
        under wraparound, and bit-identical for floats by the shared
        block structure.)"""
        from spark_rapids_tpu.kernels import segreduce as kseg
        out_np = out_np or x.dtype
        if jnp.issubdtype(jnp.dtype(out_np), jnp.floating):
            # cast before the gather: f64 gathers are native-cheap while
            # i64 ones pay the pair emulation (and per-element casts
            # commute with the gather)
            xm = jnp.where(mask, x.astype(out_np),
                           jnp.zeros((), out_np))
            if self._pallas_op(jnp.add, out_np):
                s = kseg.gather_seg_scan(xm, self.order, self.new,
                                         "add", 0,
                                         tile_bytes=self.tile_bytes)
                return jnp.take(s, self.end_pos)
            return jnp.take(
                scans.seg_scan(jnp.add, self.new,
                               self.take_sorted(xm), 0), self.end_pos)
        narrow = (narrow_bits is not None and
                  narrow_bits + max(self.cap - 1, 1).bit_length() <= 31)
        if narrow:
            xm = jnp.where(mask, x, jnp.zeros((), x.dtype)
                           ).astype(jnp.int32)
            if self._pallas_op(jnp.add, jnp.int32):
                s = kseg.gather_seg_scan(xm, self.order, self.new,
                                         "add", 0,
                                         tile_bytes=self.tile_bytes)
                return jnp.take(s, self.end_pos).astype(out_np)
            c = jnp.cumsum(self.take_sorted(xm))
        else:
            xm = jnp.where(mask, x, jnp.zeros((), x.dtype))
            if self._pallas_op(jnp.add, out_np):
                s = kseg.gather_seg_scan(xm, self.order, self.new,
                                         "add", 0, scan_np=out_np,
                                         tile_bytes=self.tile_bytes)
                return jnp.take(s, self.end_pos)
            c = scans.cumsum(self.take_sorted(xm).astype(out_np))
        ce = jnp.take(c, self.end_pos)
        return (ce - jnp.concatenate([ce[:1] * 0, ce[:-1]])
                ).astype(out_np)

    def seg_count(self, mask: jnp.ndarray) -> jnp.ndarray:
        # counts fit int32 (cap < 2^31): the native 32-bit cumsum skips
        # the blocked 64-bit scan entirely; widen at the end
        from spark_rapids_tpu.kernels import segreduce as kseg
        if mask is self.row_mask:   # COUNT(*): already have it sorted
            xs = self.sorted_mask.astype(jnp.int32)
            if self._pallas_op(jnp.add, jnp.int32):
                s = kseg.seg_scan_sorted(self.new, xs, "add", 0)
                return jnp.take(s, self.end_pos).astype(jnp.int64)
        else:
            if self._pallas_op(jnp.add, jnp.int32):
                s = kseg.gather_seg_scan(mask, self.order, self.new,
                                         "add", 0, scan_np=jnp.int32,
                                         tile_bytes=self.tile_bytes)
                return jnp.take(s, self.end_pos).astype(jnp.int64)
            xs = self.take_sorted(mask).astype(jnp.int32)
        c = jnp.cumsum(xs)
        ce = jnp.take(c, self.end_pos)
        return (ce - jnp.concatenate([ce[:1] * 0, ce[:-1]])
                ).astype(jnp.int64)

    def seg_scan_reduce(self, x_sorted: jnp.ndarray, op,
                        identity) -> jnp.ndarray:
        """Segmented reduce via associative scan over sorted rows; the
        caller pre-fills excluded rows with op's identity (also passed
        here so the capacity-blocked scan can pad with it)."""
        from spark_rapids_tpu.kernels import segreduce as kseg
        name = self._pallas_op(op, x_sorted.dtype, x_sorted.ndim)
        if name:
            s = kseg.seg_scan_sorted(self.new, x_sorted, name, identity)
        else:
            s = scans.seg_scan(op, self.new, x_sorted, identity)
        return jnp.take(s, self.end_pos)

    def seg_min_of(self, x: jnp.ndarray, mask: jnp.ndarray,
                   fill) -> jnp.ndarray:
        return self._seg_extreme(x, mask, fill, jnp.minimum, "min")

    def seg_max_of(self, x: jnp.ndarray, mask: jnp.ndarray,
                   fill) -> jnp.ndarray:
        return self._seg_extreme(x, mask, fill, jnp.maximum, "max")

    def _seg_extreme(self, x, mask, fill, op, name) -> jnp.ndarray:
        from spark_rapids_tpu.kernels import segreduce as kseg
        xm = jnp.where(mask, x, jnp.asarray(fill, dtype=x.dtype))
        if self._pallas_op(op, x.dtype, xm.ndim):
            s = kseg.gather_seg_scan(xm, self.order, self.new, name,
                                     fill, tile_bytes=self.tile_bytes)
            return jnp.take(s, self.end_pos)
        return jnp.take(
            scans.seg_scan(op, self.new, self.take_sorted(xm), fill),
            self.end_pos)


class _AggSpec:
    """update/merge/finalize triple for one aggregate function."""

    n_buffers = 1

    def __init__(self, agg: ir.AggregateExpression):
        self.agg = agg

    def update(self, v: Optional[ColVal], ctx: _SortedCtx
               ) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
        raise NotImplementedError

    def merge(self, bufs: List[DeviceColumn], ctx: _SortedCtx
              ) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
        raise NotImplementedError

    def finalize(self, bufs: List[DeviceColumn]) -> ColVal:
        raise NotImplementedError

    def buffer_dtypes(self) -> List[dt.DType]:
        raise NotImplementedError


class _CountSpec(_AggSpec):
    def buffer_dtypes(self):
        return [dt.INT64]

    def update(self, v, ctx):
        if v is None or v.nonnull:  # COUNT(*) / provably null-free
            mask = ctx.row_mask
        else:
            mask = v.validity & ctx.row_mask
        c = ctx.seg_count(mask)
        return [(c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def merge(self, bufs, ctx):
        c = ctx.seg_sum(bufs[0].data, ctx.row_mask)
        return [(c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def finalize(self, bufs):
        return ColVal(dt.INT64, bufs[0].data,
                      jnp.ones_like(bufs[0].validity))


class _SumSpec(_AggSpec):
    n_buffers = 2  # sum, valid-input count

    def buffer_dtypes(self):
        return [self.agg.dtype, dt.INT64]

    def _sum(self, data, validity, ctx, narrow_bits=None):
        tgt = self.agg.dtype.to_np()
        mask = validity if validity is ctx.row_mask \
            else validity & ctx.row_mask
        s = ctx.seg_sum(data, mask, out_np=tgt, narrow_bits=narrow_bits)
        c = ctx.seg_count(mask)
        return [(s, c > 0), (c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def update(self, v, ctx):
        return self._sum(v.data,
                         ctx.row_mask if v.nonnull else v.validity,
                         ctx, narrow_bits=sortkeys.narrow_int_bits(v))

    def merge(self, bufs, ctx):
        tgt = self.agg.dtype.to_np()
        s = ctx.seg_sum(bufs[0].data, bufs[0].validity & ctx.row_mask,
                        out_np=tgt)
        c = ctx.seg_sum(bufs[1].data, ctx.row_mask, out_np=np.int64)
        return [(s, c > 0), (c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def finalize(self, bufs):
        return ColVal(self.agg.dtype, bufs[0].data, bufs[0].validity)


class _MinMaxSpec(_AggSpec):
    def __init__(self, agg, is_min: bool):
        super().__init__(agg)
        self.is_min = is_min

    def buffer_dtypes(self):
        return [self.agg.dtype]

    def _reduce_string(self, data, validity, lengths, ctx):
        """String min/max: word-wise segmented tie-break — per uint64
        key word (most significant first), keep the rows matching the
        group's extreme, then pick the first survivor.  All segmented
        steps are scan+gather (scatter-free); cudf's GpuMin/GpuMax are
        type-generic (reference: AggregateFunctions.scala:531)."""
        considered = validity & ctx.row_mask
        sv = ColVal(self.agg.dtype, data, considered, lengths)
        words = sortkeys.encode_keys(sv, True, nulls_first=False)[1:]
        cand_s = ctx.take_sorted(considered)
        umax = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        for w in words:
            wv_s = ctx.take_sorted(w if self.is_min else ~w)
            best = ctx.seg_scan_reduce(
                jnp.where(cand_s, wv_s, umax), jnp.minimum, umax)
            cand_s = cand_s & (wv_s == jnp.take(best, ctx.gid_sorted))
        i = jnp.arange(ctx.cap, dtype=jnp.int64)
        win = ctx.seg_scan_reduce(jnp.where(cand_s, i, _BIG),
                                  jnp.minimum, _BIG)
        found = ctx.seg_count(considered) > 0
        orig = jnp.take(ctx.order, jnp.clip(win, 0, ctx.cap - 1))
        val = jnp.where(found[:, None], jnp.take(data, orig, axis=0), 0)
        lens = jnp.where(found, jnp.take(lengths, orig), 0)
        return [(val, found, lens)]

    def _reduce(self, data, validity, lengths, ctx):
        d = self.agg.dtype
        tgt = d.to_np()
        considered = validity if validity is ctx.row_mask \
            else validity & ctx.row_mask
        if d.is_string:
            return self._reduce_string(data, validity, lengths, ctx)
        if d.is_floating:
            isnan = jnp.isnan(data)
            non_nan = considered & ~isnan
            fill = np.array(np.inf if self.is_min else -np.inf, dtype=tgt)
            red = ctx.seg_min_of(data, non_nan, fill) if self.is_min \
                else ctx.seg_max_of(data, non_nan, fill)
            has_non_nan = ctx.seg_count(non_nan) > 0
            has_nan = ctx.seg_count(considered & isnan) > 0
            has_any = has_non_nan | has_nan
            nan = np.array(np.nan, dtype=tgt)
            if self.is_min:
                # Spark: NaN is greatest -> min prefers non-NaN
                val = jnp.where(has_non_nan, red, nan)
            else:
                # max: any NaN wins
                val = jnp.where(has_nan, nan, red)
            return [(jnp.where(has_any, val, 0), has_any)]
        if d.is_bool:
            x = data.astype(jnp.int32)
            red = ctx.seg_min_of(x, considered, 1) if self.is_min \
                else ctx.seg_max_of(x, considered, 0)
            has = ctx.seg_count(considered) > 0
            return [(red.astype(bool) & has, has)]
        info = np.iinfo(tgt)
        x = data.astype(tgt)
        red = ctx.seg_min_of(x, considered, info.max) if self.is_min \
            else ctx.seg_max_of(x, considered, info.min)
        has = ctx.seg_count(considered) > 0
        return [(jnp.where(has, red, 0), has)]

    def update(self, v, ctx):
        return self._reduce(v.data,
                            ctx.row_mask if v.nonnull else v.validity,
                            v.lengths, ctx)

    def merge(self, bufs, ctx):
        return self._reduce(bufs[0].data, bufs[0].validity,
                            bufs[0].lengths, ctx)

    def finalize(self, bufs):
        return ColVal(self.agg.dtype, bufs[0].data, bufs[0].validity,
                      bufs[0].lengths)


class _AverageSpec(_AggSpec):
    n_buffers = 2  # sum f64, count i64

    def buffer_dtypes(self):
        return [dt.FLOAT64, dt.INT64]

    def update(self, v, ctx):
        considered = ctx.row_mask if v.nonnull \
            else v.validity & ctx.row_mask
        s = ctx.seg_sum(v.data, considered, out_np=np.float64)
        c = ctx.seg_count(considered)
        ones = jnp.ones((ctx.cap,), dtype=jnp.bool_)
        return [(s, ones), (c, ones)]

    def merge(self, bufs, ctx):
        s = ctx.seg_sum(bufs[0].data, ctx.row_mask, out_np=np.float64)
        c = ctx.seg_sum(bufs[1].data, ctx.row_mask, out_np=np.int64)
        ones = jnp.ones((ctx.cap,), dtype=jnp.bool_)
        return [(s, ones), (c, ones)]

    def finalize(self, bufs):
        c = bufs[1].data
        nz = c > 0
        avg = jnp.where(nz, bufs[0].data / jnp.where(nz, c, 1), 0.0)
        return ColVal(dt.FLOAT64, avg, nz)


class _FirstLastSpec(_AggSpec):
    n_buffers = 2  # value, found-flag

    def __init__(self, agg, is_first: bool):
        super().__init__(agg)
        self.is_first = is_first
        self.ignore_nulls = agg.ignore_nulls

    def buffer_dtypes(self):
        return [self.agg.dtype, dt.BOOL]

    def _pick(self, data, validity, lengths, considered, ctx):
        """In sorted space, pick first/last considered row per group.

        Stable radix sort preserves input order within a group, so
        'first in sorted order' == 'first in input/partial order'.
        """
        i = jnp.arange(ctx.cap, dtype=jnp.int64)
        considered_s = ctx.take_sorted(considered)
        if self.is_first:
            win = ctx.seg_scan_reduce(
                jnp.where(considered_s, i, _BIG), jnp.minimum, _BIG)
            found = win < _BIG
        else:
            win = ctx.seg_scan_reduce(
                jnp.where(considered_s, i, jnp.int64(-1)), jnp.maximum,
                jnp.int64(-1))
            found = win >= 0
        j = jnp.clip(win, 0, ctx.cap - 1)
        orig = jnp.take(ctx.order, j)  # original row index of the winner
        val = jnp.take(data, orig, axis=0)
        vvalid = jnp.take(validity, orig) & found
        if data.ndim == 2:
            val = jnp.where(found[:, None], val, 0)
        else:
            val = jnp.where(found, val, 0)
        if lengths is not None:
            lens = jnp.where(found, jnp.take(lengths, orig), 0)
            return [(val, vvalid, lens), (found, jnp.ones_like(found))]
        return [(val, vvalid), (found, jnp.ones_like(found))]

    def update(self, v, ctx):
        considered = ctx.row_mask & (v.validity if self.ignore_nulls
                                     else jnp.ones_like(v.validity))
        return self._pick(v.data, v.validity, v.lengths, considered, ctx)

    def merge(self, bufs, ctx):
        considered = ctx.row_mask & bufs[1].data.astype(bool)
        if self.ignore_nulls:
            considered = considered & bufs[0].validity
        return self._pick(bufs[0].data, bufs[0].validity, bufs[0].lengths,
                          considered, ctx)

    def finalize(self, bufs):
        return ColVal(self.agg.dtype, bufs[0].data, bufs[0].validity,
                      bufs[0].lengths)


def make_spec(agg: ir.AggregateExpression) -> _AggSpec:
    if isinstance(agg, ir.Count):
        return _CountSpec(agg)
    if isinstance(agg, ir.Sum):
        return _SumSpec(agg)
    if isinstance(agg, ir.Min):
        return _MinMaxSpec(agg, True)
    if isinstance(agg, ir.Max):
        return _MinMaxSpec(agg, False)
    if isinstance(agg, ir.Average):
        return _AverageSpec(agg)
    if isinstance(agg, ir.First):
        return _FirstLastSpec(agg, True)
    if isinstance(agg, ir.Last):
        return _FirstLastSpec(agg, False)
    raise NotImplementedError(type(agg).__name__)


# ---------------------------------------------------------------------------
# Pure kernel functions (shared by the exec and the ICI distributed path)
# ---------------------------------------------------------------------------

def normalize_key(v: ColVal) -> ColVal:
    """NaN/-0.0 canonicalization for grouping keys (Spark
    NormalizeFloatingNumbers semantics)."""
    if v.dtype.is_floating:
        x = jnp.where(jnp.isnan(v.data),
                      jnp.array(np.nan, dtype=v.data.dtype), v.data)
        x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
        return ColVal(v.dtype, x, v.validity, v.lengths)
    return v


def sorted_group_ctx(key_vals: List[ColVal],
                     batch: DeviceBatch,
                     backend: str = "xla",
                     tile_bytes=None) -> _SortedCtx:
    """Batch-shaped wrapper over _group_ctx (rows are prefix-dense:
    row i exists iff i < num_rows)."""
    return _group_ctx(key_vals, batch.capacity, batch.num_rows,
                      backend=backend, tile_bytes=tile_bytes)


def _group_ctx(key_vals: List[ColVal], cap: int, n_rows,
               backend: str = "xla", tile_bytes=None) -> _SortedCtx:
    """Group rows by key: stable LSD radix sort over bit-packed key
    digits brings equal keys adjacent, boundaries mark group starts, and
    every downstream reduction is scan+gather (see _SortedCtx).

    The radix formulation (sortkeys.radix_order_digits) compiles ONE
    single-key u32 sort for any key arity — the catastrophic multi-
    operand XLA sort compile (20-180 s measured) that forced round 3's
    hash-probe grouping is gone, and so are that path's per-iteration
    scatter rounds."""
    row_mask = jnp.arange(cap) < n_rows
    i32 = jnp.arange(cap, dtype=jnp.int32)
    if not key_vals:
        # global aggregation: one group holding every selected row (no
        # sort needed; the single segment spans the whole capacity so a
        # fused-filter mask with gaps still sums correctly)
        end = jnp.full((cap,), 0, jnp.int32).at[0].set(cap - 1)
        return _SortedCtx(
            order=i32, new=(i32 == 0), gid_sorted=jnp.zeros_like(i32),
            start_pos=jnp.zeros((cap,), jnp.int32), end_pos=end,
            sorted_mask=row_mask, cap=cap, row_mask=row_mask,
            n_groups=jnp.int32(1), backend=backend,
            tile_bytes=tile_bytes)

    fields = [(1, (~row_mask).astype(jnp.uint64))]  # padding sorts last
    total_bits = 1
    eff_nullables = []
    for ki, v in enumerate(key_vals):
        # drop the null flag only on the propagated no-null hint —
        # schema nullability is metadata and can be stale (a falsely
        # non-nullable key would group null rows with the zero value)
        nullable = not v.nonnull
        eff_nullables.append(nullable)
        kf = sortkeys.encode_fields(v, True, True, nullable=nullable)
        fields.extend(kf)
        total_bits += sum(w for w, _ in kf)
    digits = sortkeys.fields_to_digits(fields)

    if digits.shape[0] == 1:
        # narrow-key fast path (vbits hints pack every key + null flags
        # + the padding bit into one u32): ONE direct stable pair sort,
        # and because the padding flag is the MSB of the key itself,
        # sorted_mask and group boundaries come from the sorted keys —
        # zero digit gathers (measured: each 1M-row digit gather costs
        # as much as 5 pair sorts)
        ks, order = jax.lax.sort(
            (digits[0], i32), num_keys=1, is_stable=True)
        sorted_mask = (ks >> jnp.uint32(total_bits - 1)) == 0
        new = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]])
        new = new & sorted_mask
        sorted_key_u32 = ks
    else:
        order = sortkeys.radix_order_digits(digits)
        sorted_mask = jnp.take(row_mask, order)
        new = i32 == 0
        for di in range(digits.shape[0]):
            ds = jnp.take(digits[di], order)
            new = new | jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), ds[1:] != ds[:-1]])
        new = new & sorted_mask
        sorted_key_u32 = None
    gid_sorted = jnp.cumsum(new.astype(jnp.int32)) - 1
    gid_sorted = jnp.maximum(gid_sorted, 0)
    n_groups = jnp.sum(new.astype(jnp.int32))

    nxt_real = jnp.concatenate([sorted_mask[1:],
                                jnp.zeros((1,), jnp.bool_)])
    nxt_new = jnp.concatenate([new[1:], jnp.ones((1,), jnp.bool_)])
    is_end = sorted_mask & (nxt_new | ~nxt_real)
    # unique-index set-scatters (cheap, unlike add/min/max scatters)
    start_pos = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(new, gid_sorted, cap)].set(i32, mode="drop")
    end_pos = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(is_end, gid_sorted, cap)].set(i32, mode="drop")
    key_inverse = None
    if sorted_key_u32 is not None and len(key_vals) == 1:
        v0 = key_vals[0]
        vb = sortkeys.narrow_int_bits(v0)
        if vb is not None:
            key_inverse = (vb, eff_nullables[0], v0.dtype, v0.vbits)
    return _SortedCtx(tile_bytes=tile_bytes,
                      order=order, new=new, gid_sorted=gid_sorted,
                      start_pos=start_pos, end_pos=end_pos,
                      sorted_mask=sorted_mask, cap=cap,
                      row_mask=row_mask, n_groups=n_groups,
                      sorted_key=sorted_key_u32, key_inverse=key_inverse,
                      backend=backend)


def gather_group_keys(key_vals: List[ColVal],
                      ctx: _SortedCtx) -> List[DeviceColumn]:
    """Representative key row per group (first sorted row)."""
    if not key_vals:
        return []
    group_exists = jnp.arange(ctx.cap) < ctx.n_groups
    if ctx.key_inverse is not None:
        # single narrow int key: unbias the packed sorted key at group
        # starts — one u32 gather replaces the order gather + per-key
        # data/validity gathers (the data gather is 3x a u32 gather for
        # int64 keys under x64 pair emulation)
        vb, nullable, kdt, kvbits = ctx.key_inverse
        kg = jnp.take(ctx.sorted_key, ctx.start_pos)
        value = (kg & jnp.uint32((1 << vb) - 1)).astype(jnp.int64) - \
            jnp.int64(1 << (vb - 1))
        valid = group_exists
        if nullable:
            valid = valid & (((kg >> jnp.uint32(vb)) & 1) == 1)
        data = jnp.where(valid, value, 0).astype(kdt.to_np())
        return [DeviceColumn(kdt, data, valid, vbits=kvbits,
                             nonnull=not nullable)]
    orig = jnp.take(ctx.order, ctx.start_pos)
    return [v.to_column().gather(orig, group_exists) for v in key_vals]


def _append_buffers(cols, names, bufs_per_spec, specs, ctx):
    for ai, (spec, bufs) in enumerate(zip(specs, bufs_per_spec)):
        for bi, (buf, bdt) in enumerate(zip(bufs, spec.buffer_dtypes())):
            data, valid = buf[0], buf[1]
            lengths = buf[2] if len(buf) > 2 else None
            group_exists = jnp.arange(ctx.cap) < ctx.n_groups
            cols.append(DeviceColumn(
                bdt, jnp.where(group_exists, data.astype(bdt.to_np()), 0)
                if data.ndim == 1 else data,
                valid & group_exists,
                jnp.where(group_exists, lengths, 0)
                if lengths is not None else None))
            names.append(f"__a{ai}_{bi}")


def _slice_batch(batch: DeviceBatch, n2: int) -> DeviceBatch:
    cols = [DeviceColumn(
        c.dtype, c.data[:n2], c.validity[:n2],
        None if c.lengths is None else c.lengths[:n2],
        None if c.elem_validity is None else c.elem_validity[:n2],
        c.vbits, c.nonnull)
        for c in batch.columns]
    return DeviceBatch(batch.names, cols, batch.num_rows)


def _pad_batch(batch: DeviceBatch, cap: int) -> DeviceBatch:
    def pad(a):
        if a is None or a.shape[0] >= cap:
            return a
        return jnp.concatenate(
            [a, jnp.zeros((cap - a.shape[0],) + a.shape[1:], a.dtype)])
    cols = [DeviceColumn(c.dtype, pad(c.data), pad(c.validity),
                         pad(c.lengths), pad(c.elem_validity),
                         c.vbits, c.nonnull)
            for c in batch.columns]
    return DeviceBatch(batch.names, cols, batch.num_rows)


def _laddered(batch: DeviceBatch, fn):
    """Capacity ladder: when the batch's live rows fit in cap/4 (the
    common case after a selective filter), run the whole aggregation at
    that statically smaller shape — every sort pass, gather and scan
    scales with capacity, not live rows.  Host-known row counts pick
    the rung in Python; traced counts pick via one lax.cond (both
    branches compile once, outputs padded back to cap)."""
    cap = batch.capacity
    rung = cap // 4
    # engage only at real-workload scale: the second branch doubles the
    # kernel's compile time, which would dominate small-batch suites
    if rung < _LADDER_MIN_RUNG:
        return fn(batch)
    nr = batch.num_rows
    if isinstance(nr, (int, np.integer)):
        if int(nr) <= rung:
            return _pad_batch(fn(_slice_batch(batch, rung)), cap)
        return fn(batch)
    # traced counts pick via one lax.cond: both branches compile once
    # (safe since exec/scans.py keeps 64-bit scans out of the
    # pathological in-control-flow cumsum lowering), outputs pad back
    # to cap
    return jax.lax.cond(
        nr <= rung,
        lambda: _pad_batch(fn(_slice_batch(batch, rung)), cap),
        lambda: fn(batch))


def _gather_val(v: ColVal, sel: jnp.ndarray,
                live: jnp.ndarray) -> ColVal:
    """Gather a value vector through a selected-row index map (the
    fused-filter permutation compact); rows beyond the live count zero
    out.  Hint-driven narrowing: i64 gathers cost 3x an i32 one under
    the pair emulation, so vbits<=32 data gathers through an i32 view
    and widens after; nonnull columns skip the validity gather (sel
    maps live outputs to live source rows)."""
    vb = sortkeys.narrow_int_bits(v)
    if (vb is not None and vb <= 32 and v.data.ndim == 1 and
            np.dtype(v.dtype.to_np()).itemsize == 8):
        data = jnp.take(v.data.astype(jnp.int32), sel
                        ).astype(v.data.dtype)
    else:
        data = jnp.take(v.data, sel, axis=0)
    data = jnp.where(live if data.ndim == 1 else live[:, None], data,
                     jnp.zeros((), data.dtype))
    validity = live if v.nonnull else jnp.take(v.validity, sel) & live
    lengths = None if v.lengths is None else \
        jnp.where(live, jnp.take(v.lengths, sel), 0)
    ev = None if v.elem_validity is None else \
        jnp.take(v.elem_validity, sel, axis=0) & live[:, None]
    return ColVal(v.dtype, data, validity, lengths, ev, vbits=v.vbits,
                  nonnull=v.nonnull)


def update_aggregate(batch: DeviceBatch,
                     groupings: Sequence[ir.Expression],
                     aggregates: Sequence[ir.AggregateExpression],
                     specs: Sequence[_AggSpec],
                     condition: Optional[ir.Expression] = None,
                     backend: str = "xla",
                     tile_bytes=None) -> DeviceBatch:
    """Per-batch update phase: groupBy().aggregate(updateAggs) analog.

    ``condition`` is a fused pre-filter (Filter directly under the
    aggregate): the filter compacts ONLY the evaluated key/agg value
    vectors (tpu_basic.compact would move every batch column), and the
    prefix-dense survivors let the capacity ladder run the sort-based
    grouping at a rung sized to the SELECTED rows — for the q6 bench's
    25%-selective filter that is cap/4 for every sort pass, gather and
    scan."""
    def run(kv, av, cap2, nr, sel_s=None, full_mask=None):
        """One grouped update at capacity cap2.  In the fused-filter
        path ``av`` stays in ORIGINAL row space: the sorted-space value
        gather composes the selection map with the sort order
        (sel∘order -> original rows), so each value vector pays ONE
        rung-sized gather total instead of a rung compact + a sorted
        gather."""
        from dataclasses import replace as _dc_replace
        ctx = _group_ctx(kv, cap2, nr, backend=backend,
                         tile_bytes=tile_bytes)
        cols = gather_group_keys(kv, ctx)
        names = [f"__k{i}" for i in range(len(cols))]
        vctx = ctx
        if sel_s is not None:
            vctx = _dc_replace(ctx, order=jnp.take(sel_s, ctx.order),
                               row_mask=full_mask)
        bufs_per_spec = [spec.update(v, vctx)
                         for v, spec in zip(av, specs)]
        _append_buffers(cols, names, bufs_per_spec, specs, ctx)
        return DeviceBatch(names, cols, ctx.n_groups)

    def eval_vals(b: DeviceBatch):
        kv = [normalize_key(eval_tpu.evaluate(g, b))
              for g in groupings]
        av = [eval_tpu.evaluate(a.child, b)
              if a.child is not None else None for a in aggregates]
        return kv, av

    if condition is None:
        # batch-shaped ladder: expression evaluation itself runs at the
        # rung when live rows fit (strings/regex children are per-row
        # elementwise work worth 4x)
        def run_batch(b: DeviceBatch) -> DeviceBatch:
            kv, av = eval_vals(b)
            return run(kv, av, b.capacity, b.num_rows)
        return _laddered(batch, run_batch)

    # fused filter: the condition must see every row, so evaluate at
    # full capacity — then compact the PERMUTATION, not the data: one
    # int32 scatter builds the selected-row index map, and every value
    # vector gathers through it at the ladder rung (gathers at rung
    # cost ~1/4 of full-capacity scatters per vector; measured, the
    # per-vector scatter compact was ~310 ms of the 668 ms q6 pipeline)
    key_vals, agg_vals = eval_vals(batch)
    cap = batch.capacity
    cv = eval_tpu.evaluate(condition, batch)
    keep = cv.data.astype(jnp.bool_) & cv.validity & batch.row_mask()
    n_rows = jnp.sum(keep.astype(jnp.int32))
    # selected-row index map via ONE single-operand u32 sort (surviving
    # row positions ascend, so the sort is the stable compaction);
    # measured ~3x cheaper than the full-capacity scatter it replaces
    pos = jnp.where(keep, jnp.arange(cap, dtype=jnp.uint32),
                    jnp.uint32(0xFFFFFFFF))
    sel = jnp.sort(pos).astype(jnp.int32)

    def gather_keys(cap2):
        s = sel[:cap2]
        live = jnp.arange(cap2) < n_rows
        return [_gather_val(v, s, live) for v in key_vals], s

    rung = cap // 4
    if rung < _LADDER_MIN_RUNG:
        kv, s = gather_keys(cap)
        return run(kv, agg_vals, cap, n_rows, s, keep)

    def small():
        kv, s = gather_keys(rung)
        return _pad_batch(run(kv, agg_vals, rung, n_rows, s, keep), cap)

    def big():
        kv, s = gather_keys(cap)
        return run(kv, agg_vals, cap, n_rows, s, keep)

    return jax.lax.cond(n_rows <= rung, small, big)


def merge_aggregate(batch: DeviceBatch, n_keys: int,
                    specs: Sequence[_AggSpec],
                    backend: str = "xla",
                    tile_bytes=None) -> DeviceBatch:
    """Merge phase over concatenated partials: mergeAggs analog."""
    def run(b: DeviceBatch) -> DeviceBatch:
        key_cols = b.columns[:n_keys]
        key_vals = [ColVal(c.dtype, c.data, c.validity, c.lengths,
                            vbits=c.vbits, nonnull=c.nonnull)
                    for c in key_cols]
        ctx = sorted_group_ctx(key_vals, b, backend=backend,
                               tile_bytes=tile_bytes)
        cols = gather_group_keys(key_vals, ctx)
        names = list(b.names[:n_keys])
        bufs_per_spec = []
        off = n_keys
        for spec in specs:
            bufs = b.columns[off:off + spec.n_buffers]
            off += spec.n_buffers
            bufs_per_spec.append(spec.merge(bufs, ctx))
        _append_buffers(cols, names, bufs_per_spec, specs, ctx)
        return DeviceBatch(names, cols, ctx.n_groups)
    return _laddered(batch, run)


def finalize_aggregate(batch: DeviceBatch, n_keys: int,
                       specs: Sequence[_AggSpec],
                       out_names: Sequence[str]) -> DeviceBatch:
    """Final projection from buffer columns to output columns."""
    cols = list(batch.columns[:n_keys])
    off = n_keys
    for spec in specs:
        bufs = batch.columns[off:off + spec.n_buffers]
        off += spec.n_buffers
        cols.append(spec.finalize(bufs).to_column())
    return DeviceBatch(list(out_names), cols, batch.num_rows)


class TpuHashAggregateExec(TpuExec):
    def __init__(self, child: PhysicalPlan,
                 groupings: Sequence[ir.Expression],
                 aggregates: Sequence[ir.AggregateExpression],
                 schema: Schema, per_partition: bool = False):
        super().__init__()
        self.children = (child,)
        self.groupings = list(groupings)
        self.aggregates = list(aggregates)
        self.specs = [make_spec(a) for a in self.aggregates]
        self._schema = schema
        # per_partition: aggregate each child partition independently
        # (the distributed plan shape over a hash exchange on the keys)
        self.per_partition = per_partition
        # a Filter that sat directly below this aggregate, fused in by
        # the overrides post-pass: rows failing it are MASKED instead
        # of compacted (compact costs one full-capacity gather per
        # column; the sort-based grouping is capacity-proportional
        # either way)
        self.fused_condition: Optional[ir.Expression] = None
        # execs the whole-stage fusion pass inlined into this
        # aggregate's prologue (plan/fusion.py R2)
        self.fused_prologue_execs: int = 0
        # the subset of those that are REAL savings vs the fusion-off
        # baseline: a lone filter directly under the aggregate is
        # absorbed by the legacy _fuse_filters_into_aggregates post-pass
        # either way, so counting it would overstate fusion's benefit
        self.fused_prologue_saved: int = 0
        self._update_kernel = None
        self._merge_kernel = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        if self.fused_condition is not None:
            return (f"TpuHashAggregateExec(fusedFilter="
                    f"{self.fused_condition.sql()})")
        return "TpuHashAggregateExec"

    def _update_impl(self, batch: DeviceBatch) -> DeviceBatch:
        return update_aggregate(batch, self.groupings, self.aggregates,
                                self.specs, self.fused_condition,
                                backend=getattr(self, "backend", "xla"),
                                tile_bytes=getattr(self, "tile_bytes",
                                                   None))

    def _merge_impl(self, batch: DeviceBatch) -> DeviceBatch:
        return merge_aggregate(batch, len(self.groupings), self.specs,
                               backend=getattr(self, "backend", "xla"),
                               tile_bytes=getattr(self, "tile_bytes",
                                                  None))

    def _final_impl(self, batch: DeviceBatch) -> DeviceBatch:
        return finalize_aggregate(batch, len(self.groupings), self.specs,
                                  self._schema.names)

    # ------------------------------------------------------------------
    def execute(self):
        if self._update_kernel is None:
            import functools
            import types
            from spark_rapids_tpu.exec import kernel_cache as kc
            from spark_rapids_tpu.kernels import backend as kb
            # segment-reduction kernel backend: the plan-stamped
            # kernel.backend (falling back to the process default for
            # hand-built plans).  Folded into the cache keys — the two
            # backends are two executables — and passed to get_kernel
            # so dispatches attribute as kernel.dispatches.agg_*.<bk>
            bk = kb.resolve(getattr(self, "_kernel_backend", None))
            # interpret mode rides the key for pallas-built kernels so
            # flipping kernel.pallas.interpret can't serve stale
            # interpreter-mode executables from the process cache
            # update/merge kernels never read the output schema names
            # (they emit static __k*/__a* buffer names); only agg_final
            # bakes the real names in — so names ride ONLY its key, and
            # the same aggregation under different output aliases
            # shares the expensive update/merge sorts (shape-erased ABI)
            # the tile budget rides the key too: it shapes the grids of
            # the embedded segreduce kernels (kernels/tiling.py).  Read
            # ONCE here and threaded through the shim to trace time, so
            # a concurrent session reconfiguring the knob between key
            # computation and first trace cannot cache a kernel whose
            # tile geometry disagrees with its key.
            tb = kb.tile_bytes() if bk == kb.PALLAS else None
            sig = (kc.exprs_sig(self.groupings),
                   kc.exprs_sig(self.aggregates), bk,
                   kb.interpret() if bk == kb.PALLAS else None, tb)
            # only the UPDATE kernel evaluates the fused condition;
            # merge/final kernels are identical across filters and must
            # share one compile (aggregate sorts cost ~17-20 s each)
            usig = sig + (kc.expr_sig(self.fused_condition)
                          if self.fused_condition is not None else None,)
            shim = types.SimpleNamespace(
                groupings=self.groupings, aggregates=self.aggregates,
                specs=self.specs, _schema=self._schema,
                fused_condition=self.fused_condition, backend=bk,
                tile_bytes=tb)
            cls = type(self)
            self._update_kernel = kc.get_kernel(
                ("agg_update", usig),
                lambda: functools.partial(cls._update_impl, shim),
                backend=bk)
            self._merge_kernel = kc.get_kernel(
                ("agg_merge", sig),
                lambda: functools.partial(cls._merge_impl, shim),
                backend=bk)
            self._final_kernel = kc.get_kernel(
                ("agg_final", sig, tuple(self._schema.names)),
                lambda: functools.partial(cls._final_impl, shim))

        # incremental-maintenance stamp (exec/incremental.py, threaded
        # through the planner): "retained" is a host table of merged
        # partial state from a previous run to fold into THIS run's
        # merge, "sink" captures this run's merged partials (pre-
        # finalize) for the next delta.  Never honored per_partition:
        # each partition merges independently there, so seeding every
        # partition with the retained state would multiply it in.
        inc = getattr(self, "_incremental", None)
        if inc is not None and self.per_partition:
            inc = None

        def run(its):
            from spark_rapids_tpu.exec import kernel_abi
            from spark_rapids_tpu.mem.spill import register_or_hold
            from spark_rapids_tpu.obs import registry as obsreg
            reg = obsreg.get_registry()
            # buffered partials stay spillable between update and merge
            # (reference: aggregate.scala buffers partial results;
            # SpillableColumnarBatch keeps them evictable)
            partials: List = []
            n_updates = 0
            if inc is not None and inc.get("retained") is not None:
                # the retained state merges FIRST, preserving the
                # old-batches-then-new-batches partial order a full
                # recompute would have produced
                from spark_rapids_tpu.columnar.batch import from_arrow
                retained_b = from_arrow(inc["retained"])
                reg.inc("incremental.retainedRows",
                        int(retained_b.num_rows))
                partials.append(register_or_hold(retained_b))
            try:
                for it in its:
                    for b in it:
                        # skip empty batches only when the count is
                        # already host-side: forcing a device sync here
                        # would serialize the whole pipeline per batch
                        nr = b.num_rows
                        if isinstance(nr, (int, np.integer)) \
                                and nr == 0 and self.groupings:
                            continue
                        # shape-erased ABI: the update kernel reads
                        # columns by ordinal only (groupings/aggregates
                        # are BoundReference trees) and emits its own
                        # static __k*/__a* buffer names, so the input
                        # erases with no restamp needed
                        with timed(self.metrics, "agg.update"):
                            partial = self._update_kernel(
                                kernel_abi.erase(b))
                        if self.fused_prologue_saved:
                            reg.inc("fusion.dispatchesSaved",
                                    self.fused_prologue_saved)
                        n_updates += 1
                        if inc is not None and inc.get("delta"):
                            # a delta-restricted scan's update batches
                            # ARE the delta cost — the serve-tier
                            # counter and the per-query profile section
                            # both read this
                            reg.inc("incremental.deltaBatches")
                            reg.inc("serve.incremental.deltaBatches")
                        partials.append(register_or_hold(partial))
                if not partials:
                    if self.groupings:
                        return  # grouped agg over empty input -> no rows
                    # global agg over empty -> one row (count=0, sum=null)
                    empty = _make_empty_buffer_batch(self)
                    if inc is not None and inc.get("sink") is not None:
                        from spark_rapids_tpu.columnar.batch import \
                            to_arrow
                        inc["sink"].table = to_arrow(empty)
                        inc["sink"].update_batches = n_updates
                    yield self._final_kernel(empty)
                    return
                if len(partials) == 1:
                    merged = partials[0].get()
                else:
                    whole = concat_batches([p.get() for p in partials])
                    with timed(self.metrics, "agg.merge"):
                        merged = self._merge_kernel(whole)
                if inc is not None and inc.get("sink") is not None:
                    # freeze the pre-finalize merged state host-side:
                    # the next append-only drift merges forward from
                    # this instead of rescanning the whole dataset.
                    # The host conversion syncs once at the END of the
                    # pipeline (finalize is the only dispatch left).
                    from spark_rapids_tpu.columnar.batch import to_arrow
                    with timed(self.metrics, "agg.partialCapture"):
                        inc["sink"].table = to_arrow(merged)
                        inc["sink"].update_batches = n_updates
                    reg.inc("incremental.partialsCaptured")
                out = self._final_kernel(merged)
                self.metrics.add_rows(out.num_rows)
                yield out
            finally:
                for p in partials:
                    p.close()

        if self.per_partition:
            return [run([it]) for it in self.children[0].execute()]
        return [run(self.children[0].execute())]


def _make_empty_buffer_batch(exec_: TpuHashAggregateExec) -> DeviceBatch:
    """Buffer-layout batch for a global aggregate over zero rows."""
    cap = 16
    cols, names = [], []
    for ai, spec in enumerate(exec_.specs):
        for bi, bdt in enumerate(spec.buffer_dtypes()):
            if bdt.is_string:
                cols.append(DeviceColumn(
                    bdt, jnp.zeros((cap, 1), dtype=jnp.uint8),
                    jnp.zeros((cap,), dtype=jnp.bool_),
                    jnp.zeros((cap,), dtype=jnp.int32)))
                names.append(f"__a{ai}_{bi}")
                continue
            data = jnp.zeros((cap,), dtype=bdt.to_np())
            # count buffers are valid-0; value buffers are null
            valid = jnp.zeros((cap,), dtype=jnp.bool_)
            if bdt == dt.INT64 and isinstance(
                    exec_.specs[ai], (_CountSpec, _SumSpec, _AverageSpec)) \
                    and bi == (0 if isinstance(exec_.specs[ai], _CountSpec)
                               else 1):
                valid = jnp.zeros((cap,), dtype=jnp.bool_).at[0].set(True)
            cols.append(DeviceColumn(bdt, data, valid, None))
            names.append(f"__a{ai}_{bi}")
    return DeviceBatch(names, cols, 1)
