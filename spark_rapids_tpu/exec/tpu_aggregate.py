"""TPU hash-aggregate exec.

Analog of ``GpuHashAggregateExec`` (reference: aggregate.scala:302-997):
per-batch *update* aggregation, buffered partial results, concat, *merge*
aggregation, then a final projection — the exact three-phase flow of the
reference (see comments at aggregate.scala:326-421), with cudf's
``Table.groupBy.aggregate`` replaced by a TPU-friendly sort-based segmented
reduction:

  1. encode grouping keys to total-order uint64 keys (exec/sortkeys.py)
  2. one stable ``jnp.lexsort`` brings equal keys adjacent
  3. group boundaries -> segment ids; ``jax.ops.segment_{sum,min,max}``
     computes every aggregate in fixed-shape space
  4. group count is the only host sync (the new batch's num_rows)

Aggregate functions follow the reference's update/merge pair structure
(reference: AggregateFunctions.scala:531 — each ``CudfAggregate`` declares
updateAggregate and mergeAggregate).  NaN/-0.0 key canonicalization matches
Spark's NormalizeFloatingNumbers semantics (parity-critical).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             bucket_rows, concat_batches)
from spark_rapids_tpu.exec.base import PhysicalPlan, TpuExec, timed
from spark_rapids_tpu.exec import sortkeys
from spark_rapids_tpu.expr import eval_tpu, ir
from spark_rapids_tpu.expr.eval_tpu import ColVal
from spark_rapids_tpu.plan.logical import Schema

_BIG = np.int64(1 << 62)


@dataclass
class _SortedCtx:
    """Sorted-space context shared by all aggregate updates in one kernel."""

    order: jnp.ndarray        # sorted row order (original indices)
    seg_sorted: jnp.ndarray   # group id per sorted row
    seg_orig: jnp.ndarray     # group id per original row
    cap: int
    row_mask: jnp.ndarray     # original-space "row exists"
    n_groups: jnp.ndarray     # scalar


def _seg_sum(x, seg, cap):
    return jax.ops.segment_sum(x, seg, num_segments=cap)


def _seg_min(x, seg, cap):
    return jax.ops.segment_min(x, seg, num_segments=cap)


def _seg_max(x, seg, cap):
    return jax.ops.segment_max(x, seg, num_segments=cap)


class _AggSpec:
    """update/merge/finalize triple for one aggregate function."""

    n_buffers = 1

    def __init__(self, agg: ir.AggregateExpression):
        self.agg = agg

    def update(self, v: Optional[ColVal], ctx: _SortedCtx
               ) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
        raise NotImplementedError

    def merge(self, bufs: List[DeviceColumn], ctx: _SortedCtx
              ) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
        raise NotImplementedError

    def finalize(self, bufs: List[DeviceColumn]) -> ColVal:
        raise NotImplementedError

    def buffer_dtypes(self) -> List[dt.DType]:
        raise NotImplementedError


class _CountSpec(_AggSpec):
    def buffer_dtypes(self):
        return [dt.INT64]

    def update(self, v, ctx):
        if v is None:  # COUNT(*)
            ones = ctx.row_mask.astype(jnp.int64)
        else:
            ones = (v.validity & ctx.row_mask).astype(jnp.int64)
        c = _seg_sum(ones, ctx.seg_orig, ctx.cap)
        return [(c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def merge(self, bufs, ctx):
        c = _seg_sum(jnp.where(ctx.row_mask, bufs[0].data, 0),
                     ctx.seg_orig, ctx.cap)
        return [(c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def finalize(self, bufs):
        return ColVal(dt.INT64, bufs[0].data,
                      jnp.ones_like(bufs[0].validity))


class _SumSpec(_AggSpec):
    n_buffers = 2  # sum, valid-input count

    def buffer_dtypes(self):
        return [self.agg.dtype, dt.INT64]

    def _sum(self, data, validity, ctx):
        tgt = self.agg.dtype.to_np()
        x = jnp.where(validity & ctx.row_mask, data.astype(tgt), 0)
        s = _seg_sum(x, ctx.seg_orig, ctx.cap)
        c = _seg_sum((validity & ctx.row_mask).astype(jnp.int64),
                     ctx.seg_orig, ctx.cap)
        return [(s, c > 0), (c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def update(self, v, ctx):
        return self._sum(v.data, v.validity, ctx)

    def merge(self, bufs, ctx):
        tgt = self.agg.dtype.to_np()
        x = jnp.where(bufs[0].validity & ctx.row_mask,
                      bufs[0].data.astype(tgt), 0)
        s = _seg_sum(x, ctx.seg_orig, ctx.cap)
        c = _seg_sum(jnp.where(ctx.row_mask, bufs[1].data, 0),
                     ctx.seg_orig, ctx.cap)
        return [(s, c > 0), (c, jnp.ones((ctx.cap,), dtype=jnp.bool_))]

    def finalize(self, bufs):
        return ColVal(self.agg.dtype, bufs[0].data, bufs[0].validity)


class _MinMaxSpec(_AggSpec):
    def __init__(self, agg, is_min: bool):
        super().__init__(agg)
        self.is_min = is_min

    def buffer_dtypes(self):
        return [self.agg.dtype]

    def _reduce_string(self, data, validity, lengths, ctx):
        """String min/max: word-wise segmented tie-break — per uint64
        key word (most significant first), keep the rows matching the
        group's extreme, then pick the first survivor.  No sort (XLA
        sort compiles are minutes-scale); W segment-mins instead.
        cudf's GpuMin/GpuMax are type-generic (reference:
        AggregateFunctions.scala:531)."""
        considered = validity & ctx.row_mask
        sv = ColVal(self.agg.dtype, data, considered, lengths)
        words = sortkeys.encode_keys(sv, True, nulls_first=False)[1:]
        cand = considered
        umax = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        for w in words:
            wv = w if self.is_min else ~w
            best = _seg_min(jnp.where(cand, wv, umax), ctx.seg_orig,
                            ctx.cap)
            cand = cand & (wv == jnp.take(best, ctx.seg_orig))
        pos = jnp.where(cand, jnp.arange(ctx.cap, dtype=jnp.int64),
                        _BIG)
        win = _seg_min(pos, ctx.seg_orig, ctx.cap)
        found = _seg_sum(considered.astype(jnp.int32), ctx.seg_orig,
                         ctx.cap) > 0
        orig = jnp.clip(win, 0, ctx.cap - 1)
        val = jnp.where(found[:, None], jnp.take(data, orig, axis=0), 0)
        lens = jnp.where(found, jnp.take(lengths, orig), 0)
        return [(val, found, lens)]

    def _reduce(self, data, validity, lengths, ctx):
        d = self.agg.dtype
        tgt = d.to_np()
        considered = validity & ctx.row_mask
        if d.is_string:
            return self._reduce_string(data, validity, lengths, ctx)
        if d.is_floating:
            isnan = jnp.isnan(data)
            non_nan = considered & ~isnan
            fill = np.array(np.inf if self.is_min else -np.inf, dtype=tgt)
            x = jnp.where(non_nan, data, fill)
            red = _seg_min(x, ctx.seg_orig, ctx.cap) if self.is_min \
                else _seg_max(x, ctx.seg_orig, ctx.cap)
            has_non_nan = _seg_sum(non_nan.astype(jnp.int32),
                                   ctx.seg_orig, ctx.cap) > 0
            has_nan = _seg_sum((considered & isnan).astype(jnp.int32),
                               ctx.seg_orig, ctx.cap) > 0
            has_any = has_non_nan | has_nan
            nan = np.array(np.nan, dtype=tgt)
            if self.is_min:
                # Spark: NaN is greatest -> min prefers non-NaN
                val = jnp.where(has_non_nan, red, nan)
            else:
                # max: any NaN wins
                val = jnp.where(has_nan, nan, red)
            return [(jnp.where(has_any, val, 0), has_any)]
        if d.is_bool:
            x = jnp.where(considered, data,
                          jnp.array(not self.is_min, dtype=bool))
            red = _seg_min(x.astype(jnp.int32), ctx.seg_orig, ctx.cap) \
                if self.is_min else _seg_max(x.astype(jnp.int32),
                                             ctx.seg_orig, ctx.cap)
            has = _seg_sum(considered.astype(jnp.int32),
                           ctx.seg_orig, ctx.cap) > 0
            return [(red.astype(bool) & has, has)]
        info = np.iinfo(tgt)
        fill = np.array(info.max if self.is_min else info.min, dtype=tgt)
        x = jnp.where(considered, data.astype(tgt), fill)
        red = _seg_min(x, ctx.seg_orig, ctx.cap) if self.is_min \
            else _seg_max(x, ctx.seg_orig, ctx.cap)
        has = _seg_sum(considered.astype(jnp.int32), ctx.seg_orig,
                       ctx.cap) > 0
        return [(jnp.where(has, red, 0), has)]

    def update(self, v, ctx):
        return self._reduce(v.data, v.validity, v.lengths, ctx)

    def merge(self, bufs, ctx):
        return self._reduce(bufs[0].data, bufs[0].validity,
                            bufs[0].lengths, ctx)

    def finalize(self, bufs):
        return ColVal(self.agg.dtype, bufs[0].data, bufs[0].validity,
                      bufs[0].lengths)


class _AverageSpec(_AggSpec):
    n_buffers = 2  # sum f64, count i64

    def buffer_dtypes(self):
        return [dt.FLOAT64, dt.INT64]

    def update(self, v, ctx):
        considered = v.validity & ctx.row_mask
        x = jnp.where(considered, v.data.astype(jnp.float64), 0.0)
        s = _seg_sum(x, ctx.seg_orig, ctx.cap)
        c = _seg_sum(considered.astype(jnp.int64), ctx.seg_orig, ctx.cap)
        ones = jnp.ones((ctx.cap,), dtype=jnp.bool_)
        return [(s, ones), (c, ones)]

    def merge(self, bufs, ctx):
        s = _seg_sum(jnp.where(ctx.row_mask, bufs[0].data, 0.0),
                     ctx.seg_orig, ctx.cap)
        c = _seg_sum(jnp.where(ctx.row_mask, bufs[1].data, 0),
                     ctx.seg_orig, ctx.cap)
        ones = jnp.ones((ctx.cap,), dtype=jnp.bool_)
        return [(s, ones), (c, ones)]

    def finalize(self, bufs):
        c = bufs[1].data
        nz = c > 0
        avg = jnp.where(nz, bufs[0].data / jnp.where(nz, c, 1), 0.0)
        return ColVal(dt.FLOAT64, avg, nz)


class _FirstLastSpec(_AggSpec):
    n_buffers = 2  # value, found-flag

    def __init__(self, agg, is_first: bool):
        super().__init__(agg)
        self.is_first = is_first
        self.ignore_nulls = agg.ignore_nulls

    def buffer_dtypes(self):
        return [self.agg.dtype, dt.BOOL]

    def _pick(self, data, validity, lengths, considered, ctx):
        """In sorted space, pick first/last considered row per group.

        Stable lexsort preserves input order within a group, so 'first in
        sorted order' == 'first in input/partial order'.
        """
        i = jnp.arange(ctx.cap, dtype=jnp.int64)
        considered_s = jnp.take(considered, ctx.order)
        if self.is_first:
            pos = jnp.where(considered_s, i, _BIG)
            win = _seg_min(pos, ctx.seg_sorted, ctx.cap)
            found = win < _BIG
        else:
            pos = jnp.where(considered_s, i, -1)
            win = _seg_max(pos, ctx.seg_sorted, ctx.cap)
            found = win >= 0
        j = jnp.clip(win, 0, ctx.cap - 1)
        orig = jnp.take(ctx.order, j)  # original row index of the winner
        val = jnp.take(data, orig, axis=0)
        vvalid = jnp.take(validity, orig) & found
        if data.ndim == 2:
            val = jnp.where(found[:, None], val, 0)
        else:
            val = jnp.where(found, val, 0)
        if lengths is not None:
            lens = jnp.where(found, jnp.take(lengths, orig), 0)
            return [(val, vvalid, lens), (found, jnp.ones_like(found))]
        return [(val, vvalid), (found, jnp.ones_like(found))]

    def update(self, v, ctx):
        considered = ctx.row_mask & (v.validity if self.ignore_nulls
                                     else jnp.ones_like(v.validity))
        return self._pick(v.data, v.validity, v.lengths, considered, ctx)

    def merge(self, bufs, ctx):
        considered = ctx.row_mask & bufs[1].data.astype(bool)
        if self.ignore_nulls:
            considered = considered & bufs[0].validity
        return self._pick(bufs[0].data, bufs[0].validity, bufs[0].lengths,
                          considered, ctx)

    def finalize(self, bufs):
        return ColVal(self.agg.dtype, bufs[0].data, bufs[0].validity,
                      bufs[0].lengths)


def make_spec(agg: ir.AggregateExpression) -> _AggSpec:
    if isinstance(agg, ir.Count):
        return _CountSpec(agg)
    if isinstance(agg, ir.Sum):
        return _SumSpec(agg)
    if isinstance(agg, ir.Min):
        return _MinMaxSpec(agg, True)
    if isinstance(agg, ir.Max):
        return _MinMaxSpec(agg, False)
    if isinstance(agg, ir.Average):
        return _AverageSpec(agg)
    if isinstance(agg, ir.First):
        return _FirstLastSpec(agg, True)
    if isinstance(agg, ir.Last):
        return _FirstLastSpec(agg, False)
    raise NotImplementedError(type(agg).__name__)


# ---------------------------------------------------------------------------
# Pure kernel functions (shared by the exec and the ICI distributed path)
# ---------------------------------------------------------------------------

def normalize_key(v: ColVal) -> ColVal:
    """NaN/-0.0 canonicalization for grouping keys (Spark
    NormalizeFloatingNumbers semantics)."""
    if v.dtype.is_floating:
        x = jnp.where(jnp.isnan(v.data),
                      jnp.array(np.nan, dtype=v.data.dtype), v.data)
        x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
        return ColVal(v.dtype, x, v.validity, v.lengths)
    return v


def sorted_group_ctx(key_vals: List[ColVal],
                     batch: DeviceBatch) -> _SortedCtx:
    """Group rows by key WITHOUT sorting: open-addressing hash build.

    XLA ``sort`` compiles catastrophically slowly on TPU (the bitonic
    network unrolls ~log^2(n) stages; measured 20-180 s per sort compile
    at SQL batch sizes), so the aggregate groups via a scatter-based
    linear-probing hash table instead — the literal "hash aggregate" of
    the reference (GpuHashAggregateExec; cudf hash groupby).  Group ids
    come out dense in [0, n_groups); first/last semantics use original
    row order (ctx.order is the identity), which matches the stable-sort
    contract the specs were written against."""
    cap = batch.capacity
    row_mask = batch.row_mask()
    if not key_vals:
        # global aggregation: one group holding every row
        zeros = jnp.zeros((cap,), dtype=jnp.int32)
        return _SortedCtx(order=jnp.arange(cap), seg_sorted=zeros,
                          seg_orig=zeros, cap=cap, row_mask=row_mask,
                          n_groups=jnp.int32(1))
    words_l: List[jnp.ndarray] = []
    for v in key_vals:
        words_l.extend(sortkeys.encode_keys(v, True, True))
    seg, n_groups = sortkeys.hash_group_ids(words_l, row_mask)
    order = jnp.arange(cap)
    return _SortedCtx(order=order, seg_sorted=seg,
                      seg_orig=seg, cap=cap, row_mask=row_mask,
                      n_groups=n_groups)


def gather_group_keys(key_vals: List[ColVal],
                      ctx: _SortedCtx) -> List[DeviceColumn]:
    """Representative key row per group (first sorted row)."""
    if not key_vals:
        return []
    i = jnp.arange(ctx.cap, dtype=jnp.int64)
    first_sorted_pos = _seg_min(i, ctx.seg_sorted, ctx.cap)
    j = jnp.clip(first_sorted_pos, 0, ctx.cap - 1)
    orig = jnp.take(ctx.order, j)
    group_exists = jnp.arange(ctx.cap) < ctx.n_groups
    return [v.to_column().gather(orig, group_exists) for v in key_vals]


def _append_buffers(cols, names, bufs_per_spec, specs, ctx):
    for ai, (spec, bufs) in enumerate(zip(specs, bufs_per_spec)):
        for bi, (buf, bdt) in enumerate(zip(bufs, spec.buffer_dtypes())):
            data, valid = buf[0], buf[1]
            lengths = buf[2] if len(buf) > 2 else None
            group_exists = jnp.arange(ctx.cap) < ctx.n_groups
            cols.append(DeviceColumn(
                bdt, jnp.where(group_exists, data.astype(bdt.to_np()), 0)
                if data.ndim == 1 else data,
                valid & group_exists,
                jnp.where(group_exists, lengths, 0)
                if lengths is not None else None))
            names.append(f"__a{ai}_{bi}")


def update_aggregate(batch: DeviceBatch,
                     groupings: Sequence[ir.Expression],
                     aggregates: Sequence[ir.AggregateExpression],
                     specs: Sequence[_AggSpec]) -> DeviceBatch:
    """Per-batch update phase: groupBy().aggregate(updateAggs) analog."""
    key_vals = [normalize_key(eval_tpu.evaluate(g, batch))
                for g in groupings]
    ctx = sorted_group_ctx(key_vals, batch)
    cols = gather_group_keys(key_vals, ctx)
    names = [f"__k{i}" for i in range(len(cols))]
    bufs_per_spec = []
    for agg, spec in zip(aggregates, specs):
        v = eval_tpu.evaluate(agg.child, batch) \
            if agg.child is not None else None
        bufs_per_spec.append(spec.update(v, ctx))
    _append_buffers(cols, names, bufs_per_spec, specs, ctx)
    return DeviceBatch(names, cols, ctx.n_groups)


def merge_aggregate(batch: DeviceBatch, n_keys: int,
                    specs: Sequence[_AggSpec]) -> DeviceBatch:
    """Merge phase over concatenated partials: mergeAggs analog."""
    key_cols = batch.columns[:n_keys]
    key_vals = [ColVal(c.dtype, c.data, c.validity, c.lengths)
                for c in key_cols]
    ctx = sorted_group_ctx(key_vals, batch)
    cols = gather_group_keys(key_vals, ctx)
    names = list(batch.names[:n_keys])
    bufs_per_spec = []
    off = n_keys
    for spec in specs:
        bufs = batch.columns[off:off + spec.n_buffers]
        off += spec.n_buffers
        bufs_per_spec.append(spec.merge(bufs, ctx))
    _append_buffers(cols, names, bufs_per_spec, specs, ctx)
    return DeviceBatch(names, cols, ctx.n_groups)


def finalize_aggregate(batch: DeviceBatch, n_keys: int,
                       specs: Sequence[_AggSpec],
                       out_names: Sequence[str]) -> DeviceBatch:
    """Final projection from buffer columns to output columns."""
    cols = list(batch.columns[:n_keys])
    off = n_keys
    for spec in specs:
        bufs = batch.columns[off:off + spec.n_buffers]
        off += spec.n_buffers
        cols.append(spec.finalize(bufs).to_column())
    return DeviceBatch(list(out_names), cols, batch.num_rows)


class TpuHashAggregateExec(TpuExec):
    def __init__(self, child: PhysicalPlan,
                 groupings: Sequence[ir.Expression],
                 aggregates: Sequence[ir.AggregateExpression],
                 schema: Schema, per_partition: bool = False):
        super().__init__()
        self.children = (child,)
        self.groupings = list(groupings)
        self.aggregates = list(aggregates)
        self.specs = [make_spec(a) for a in self.aggregates]
        self._schema = schema
        # per_partition: aggregate each child partition independently
        # (the distributed plan shape over a hash exchange on the keys)
        self.per_partition = per_partition
        self._update_kernel = None
        self._merge_kernel = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def _update_impl(self, batch: DeviceBatch) -> DeviceBatch:
        return update_aggregate(batch, self.groupings, self.aggregates,
                                self.specs)

    def _merge_impl(self, batch: DeviceBatch) -> DeviceBatch:
        return merge_aggregate(batch, len(self.groupings), self.specs)

    def _final_impl(self, batch: DeviceBatch) -> DeviceBatch:
        return finalize_aggregate(batch, len(self.groupings), self.specs,
                                  self._schema.names)

    # ------------------------------------------------------------------
    def execute(self):
        if self._update_kernel is None:
            import functools
            import types
            from spark_rapids_tpu.exec import kernel_cache as kc
            sig = (kc.exprs_sig(self.groupings),
                   kc.exprs_sig(self.aggregates),
                   tuple(self._schema.names))
            shim = types.SimpleNamespace(
                groupings=self.groupings, aggregates=self.aggregates,
                specs=self.specs, _schema=self._schema)
            cls = type(self)
            self._update_kernel = kc.get_kernel(
                ("agg_update", sig),
                lambda: functools.partial(cls._update_impl, shim))
            self._merge_kernel = kc.get_kernel(
                ("agg_merge", sig),
                lambda: functools.partial(cls._merge_impl, shim))
            self._final_kernel = kc.get_kernel(
                ("agg_final", sig),
                lambda: functools.partial(cls._final_impl, shim))

        def run(its):
            from spark_rapids_tpu.mem.spill import register_or_hold
            # buffered partials stay spillable between update and merge
            # (reference: aggregate.scala buffers partial results;
            # SpillableColumnarBatch keeps them evictable)
            partials: List = []
            try:
                for it in its:
                    for b in it:
                        # skip empty batches only when the count is
                        # already host-side: forcing a device sync here
                        # would serialize the whole pipeline per batch
                        nr = b.num_rows
                        if isinstance(nr, (int, np.integer)) \
                                and nr == 0 and self.groupings:
                            continue
                        with timed(self.metrics):
                            partial = self._update_kernel(b)
                        partials.append(register_or_hold(partial))
                if not partials:
                    if self.groupings:
                        return  # grouped agg over empty input -> no rows
                    # global agg over empty -> one row (count=0, sum=null)
                    empty = _make_empty_buffer_batch(self)
                    yield self._final_kernel(empty)
                    return
                if len(partials) == 1:
                    merged = partials[0].get()
                else:
                    whole = concat_batches([p.get() for p in partials])
                    with timed(self.metrics):
                        merged = self._merge_kernel(whole)
                out = self._final_kernel(merged)
                self.metrics.add_rows(out.num_rows)
                yield out
            finally:
                for p in partials:
                    p.close()

        if self.per_partition:
            return [run([it]) for it in self.children[0].execute()]
        return [run(self.children[0].execute())]


def _make_empty_buffer_batch(exec_: TpuHashAggregateExec) -> DeviceBatch:
    """Buffer-layout batch for a global aggregate over zero rows."""
    cap = 16
    cols, names = [], []
    for ai, spec in enumerate(exec_.specs):
        for bi, bdt in enumerate(spec.buffer_dtypes()):
            if bdt.is_string:
                cols.append(DeviceColumn(
                    bdt, jnp.zeros((cap, 1), dtype=jnp.uint8),
                    jnp.zeros((cap,), dtype=jnp.bool_),
                    jnp.zeros((cap,), dtype=jnp.int32)))
                names.append(f"__a{ai}_{bi}")
                continue
            data = jnp.zeros((cap,), dtype=bdt.to_np())
            # count buffers are valid-0; value buffers are null
            valid = jnp.zeros((cap,), dtype=jnp.bool_)
            if bdt == dt.INT64 and isinstance(
                    exec_.specs[ai], (_CountSpec, _SumSpec, _AverageSpec)) \
                    and bi == (0 if isinstance(exec_.specs[ai], _CountSpec)
                               else 1):
                valid = jnp.zeros((cap,), dtype=jnp.bool_).at[0].set(True)
            cols.append(DeviceColumn(bdt, data, valid, None))
            names.append(f"__a{ai}_{bi}")
    return DeviceBatch(names, cols, 1)
