"""Total-order sort-key encoding for device columns.

cudf's ``Table.orderBy``/``groupBy`` sort natively on any column type
(reference: GpuSortExec.scala:51-265, aggregate.scala).  XLA has only numeric
sorts, so every column is *encoded* into one or more unsigned integer keys
whose ascending numeric order equals the column's SQL order:

  * ints/dates/timestamps: sign-bit flip -> uint64
  * floats: IEEE total-order transform (negatives bit-flipped), after
    canonicalizing NaN and -0.0 (Spark: NaN greatest, NaN==NaN, -0.0==0.0)
  * bools: 0/1
  * strings: bytes packed big-endian into uint64 words (exact lexicographic,
    zero-padded) + length tiebreaker
  * nulls: a leading 0/1 key implementing NULLS FIRST/LAST
  * descending: bitwise complement of each key

``jnp.lexsort`` over the resulting key stack is then an exact multi-column
SQL sort.  The same encoding gives grouping adjacency for the sort-based
hash-aggregate and the sort-merge join.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr.eval_tpu import ColVal, f64_bits

_SIGN64 = np.uint64(0x8000000000000000)


def _int_key(data: jnp.ndarray) -> jnp.ndarray:
    # two's-complement wrap (convert, not bitcast: TPU x64 emulation has
    # no 64-bit bitcast-convert) then sign-bit flip
    u = data.astype(jnp.int64).astype(jnp.uint64)
    return u ^ _SIGN64


def _float_key(data: jnp.ndarray, is32: bool) -> jnp.ndarray:
    x = data
    # canonicalize: -0.0 -> 0.0, NaN -> canonical quiet NaN (positive)
    x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
    x = jnp.where(jnp.isnan(x), jnp.array(np.nan, dtype=x.dtype), x)
    if is32:
        bits = x.view(jnp.int32).astype(jnp.int64)
        bits = bits << 32  # keep ordering in the top bits
        u = bits.astype(jnp.uint64)
        neg = bits < 0
        return jnp.where(neg, ~u, u ^ _SIGN64)
    # float64: arithmetic IEEE bit reconstruction (no 64-bit bitcast)
    u = f64_bits(x)
    neg = (u & _SIGN64) != 0
    return jnp.where(neg, ~u, u ^ _SIGN64)


def encode_keys(v: ColVal, ascending: bool = True,
                nulls_first: bool = True) -> List[jnp.ndarray]:
    """Encode one column into uint64 keys, most-significant first."""
    keys: List[jnp.ndarray] = []
    null_key = jnp.where(v.validity,
                         jnp.uint64(1 if nulls_first else 0),
                         jnp.uint64(0 if nulls_first else 1))
    keys.append(null_key)

    d = v.dtype
    if d.is_string:
        w = v.data.shape[1]
        for word_start in range(0, w, 8):
            word = jnp.zeros(v.data.shape[0], dtype=jnp.uint64)
            for k in range(8):
                j = word_start + k
                if j < w:
                    byte = v.data[:, j].astype(jnp.uint64)
                    word = word | (byte << (8 * (7 - k)))
            keys.append(word)
        keys.append(v.lengths.astype(jnp.uint64))
    elif d.is_floating:
        keys.append(_float_key(v.data, d.id == dt.TypeId.FLOAT32))
    elif d.is_bool:
        keys.append(v.data.astype(jnp.uint64))
    else:
        keys.append(_int_key(v.data))

    if not ascending:
        keys = [keys[0]] + [~k for k in keys[1:]]
        # null placement key already encodes nulls_first; invert only values
    # null rows: zero out value keys so equal nulls tie deterministically
    for i in range(1, len(keys)):
        keys[i] = jnp.where(v.validity, keys[i], jnp.uint64(0))
    return keys


def narrow_int_bits(v: ColVal) -> Optional[int]:
    """Effective bit width encode_fields uses for an integer-backed
    column (dtype width capped by the vbits range hint), or None for
    non-integer / full-width columns.  Callers use it to decide narrow
    fast paths (single-digit sorts, i32 segment sums, key inversion)."""
    d = v.dtype
    if d.is_string or d.is_floating or d.is_bool:
        return None
    npd = np.dtype(d.to_np())
    if not np.issubdtype(npd, np.integer):
        return None
    vb = min(getattr(v, "vbits", None) or 64, npd.itemsize * 8)
    return vb if vb < 64 else None


def encode_fields(v: ColVal, ascending: bool = True,
                  nulls_first: bool = True, nullable: bool = True
                  ) -> List[Tuple[int, jnp.ndarray]]:
    """Encode one column as BIT-WIDTH-AWARE key fields, most significant
    first: (width_bits, uint64 values masked to width).

    The u64-word encoding (encode_keys) spends a full 64-bit word on
    every key — a 1-bit null flag costs the same radix passes as an
    int64.  Fields pack to their true width (bool=1, int32/float32/
    date=32, int64/float64=64 split into two 32-bit halves, string
    length=16), so fields_to_digits can chop the concatenated bitstring
    into ~2x fewer u32 radix digits.  Schema-non-nullable columns skip
    the null field entirely."""
    fields: List[Tuple[int, jnp.ndarray]] = []
    if nullable:
        nk = jnp.where(v.validity,
                       jnp.uint64(1 if nulls_first else 0),
                       jnp.uint64(0 if nulls_first else 1))
        fields.append((1, nk))

    def split64(u: jnp.ndarray) -> List[Tuple[int, jnp.ndarray]]:
        return [(32, (u >> jnp.uint64(32)) & jnp.uint64(0xFFFFFFFF)),
                (32, u & jnp.uint64(0xFFFFFFFF))]

    d = v.dtype
    vals: List[Tuple[int, jnp.ndarray]] = []
    if d.is_string:
        w = v.data.shape[1]
        for word_start in range(0, w, 4):
            word = jnp.zeros(v.data.shape[0], dtype=jnp.uint64)
            for k in range(4):
                j = word_start + k
                if j < w:
                    byte = v.data[:, j].astype(jnp.uint64)
                    word = word | (byte << jnp.uint64(8 * (3 - k)))
            vals.append((32, word))
        vals.append((16, v.lengths.astype(jnp.uint64) &
                     jnp.uint64(0xFFFF)))
    elif d.is_floating:
        if d.id == dt.TypeId.FLOAT32:
            x = v.data
            x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
            x = jnp.where(jnp.isnan(x), jnp.array(np.nan, x.dtype), x)
            bits = x.view(jnp.int32)
            u = bits.astype(jnp.int64).astype(jnp.uint64) & \
                jnp.uint64(0xFFFFFFFF)
            neg = bits < 0
            key = jnp.where(neg, (~u) & jnp.uint64(0xFFFFFFFF),
                            u ^ jnp.uint64(0x80000000))
            vals.append((32, key))
        else:
            vals.extend(split64(_float_key(v.data, False)))
    elif d.is_bool:
        vals.append((1, v.data.astype(jnp.uint64)))
    else:
        npd = np.dtype(d.to_np())
        vb = v.vbits if getattr(v, "vbits", None) else None
        # the dtype's own width is a free static bound (int16 fits 16)
        vb = min(vb or 64, npd.itemsize * 8)
        if vb < 64:
            # static range hint (DeviceColumn.vbits): all valid values
            # fit signed vb bits, so the biased value (v + 2^(vb-1))
            # is an order-preserving unsigned vb-bit key — fewer radix
            # digits than the full-width encoding
            biased = (v.data.astype(jnp.int64) +
                      jnp.int64(1 << (vb - 1))).astype(jnp.uint64)
            if vb <= 32:
                vals.append((vb, biased))
            else:
                vals.append((vb - 32, biased >> jnp.uint64(32)))
                vals.append((32, biased & jnp.uint64(0xFFFFFFFF)))
        else:
            vals.extend(split64(_int_key(v.data)))

    if not ascending:
        vals = [(w, (~k) & ((jnp.uint64(1) << jnp.uint64(w)) -
                            jnp.uint64(1))) for w, k in vals]
    # null rows: zero value fields so equal nulls tie deterministically
    vals = [(w, jnp.where(v.validity, k, jnp.uint64(0)))
            for w, k in vals]
    return fields + vals


def fields_to_digits(fields: List[Tuple[int, jnp.ndarray]],
                     ) -> jnp.ndarray:
    """Concatenate MSB-first bit fields and chop the bitstring into u32
    radix digits, LEAST significant digit first — the direct input to
    radix_order_digits.  Every field must be <= 32 bits (encode_fields
    guarantees it)."""
    digits: List[jnp.ndarray] = []
    cur = None
    cur_bits = 0
    for w, vals in reversed(fields):   # least-significant field first
        assert w <= 32, w
        v = vals & ((jnp.uint64(1) << jnp.uint64(w)) - jnp.uint64(1))
        if cur is None:
            cur = jnp.zeros_like(v)
        cur = cur | (v << jnp.uint64(cur_bits))
        cur_bits += w
        while cur_bits >= 32:
            digits.append((cur & jnp.uint64(0xFFFFFFFF)
                           ).astype(jnp.uint32))
            cur = cur >> jnp.uint64(32)
            cur_bits -= 32
    assert cur is not None, "fields_to_digits needs at least one field"
    if cur_bits or not digits:
        digits.append((cur & jnp.uint64(0xFFFFFFFF)
                       ).astype(jnp.uint32))
    return jnp.stack(digits)           # [d, cap], LSB digit first


def radix_order_digits(digits: jnp.ndarray) -> jnp.ndarray:
    """Stable order from [d, cap] u32 digits (least significant digit
    FIRST) via LSD radix passes — one cheap single-key sort in a scan,
    any key arity (see radix_order)."""
    cap = digits.shape[1]
    perm0 = jnp.arange(cap, dtype=jnp.int32)

    def body(perm, digit):
        dk = jnp.take(digit, perm)
        _, perm2 = jax.lax.sort((dk, perm), num_keys=1, is_stable=True)
        return perm2, None

    perm, _ = jax.lax.scan(body, perm0, digits)
    return perm


def radix_order(wm: jnp.ndarray) -> jnp.ndarray:
    """Stable lexicographic order of a [m, cap] uint64 word matrix
    (row 0 most significant) via LSD radix over u32 half-words.

    XLA lowers a multi-operand lexsort into ONE sorting network whose
    comparator grows with arity — compile cost explodes (measured: ~9 s
    for 1-op u32, ~100 s for 3-op u64, minutes beyond).  LSD radix
    needs only a single-key stable sort applied per digit; wrapping it
    in ``lax.scan`` compiles the sort ONCE regardless of word count, so
    any ORDER BY arity costs one cheap compile.  Stability of each pass
    makes the final order exactly the multi-key lexicographic order."""
    m, _cap = wm.shape
    parts = []
    for i in range(m - 1, -1, -1):          # least-significant first
        parts.append(wm[i].astype(jnp.uint32))
        parts.append((wm[i] >> jnp.uint64(32)).astype(jnp.uint32))
    return radix_order_digits(jnp.stack(parts))   # [2m, cap] uint32


def lexsort_indices(key_groups: List[List[jnp.ndarray]],
                    row_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable sort indices; padding rows always sort to the end.

    key_groups: per sort column (primary first), the encode_keys output.
    """
    return radix_order(stack_sort_words(key_groups, row_mask))


def group_boundaries(key_groups: List[List[jnp.ndarray]],
                     order: jnp.ndarray,
                     row_mask: jnp.ndarray) -> jnp.ndarray:
    """After sorting with `order`, mark rows that start a new key group.

    Null keys compare equal (SQL GROUP BY semantics).  Padding rows always
    start their own group so they never merge into the last real group.
    """
    n = order.shape[0]
    sorted_mask = jnp.take(row_mask, order)
    new_group = jnp.zeros((n,), dtype=jnp.bool_).at[0].set(True)
    for group in key_groups:
        for k in group:
            ks = jnp.take(k, order)
            diff = jnp.concatenate(
                [jnp.ones((1,), dtype=jnp.bool_), ks[1:] != ks[:-1]])
            new_group = new_group | diff
    prev_mask = jnp.concatenate(
        [jnp.ones((1,), dtype=jnp.bool_), sorted_mask[:-1]])
    new_group = new_group | (sorted_mask != prev_mask)
    return new_group


# ---------------------------------------------------------------------------
# Shared standalone sort kernels
# ---------------------------------------------------------------------------
#
# XLA ``sort`` unrolls a ~log^2(n)-stage network on TPU; a single sort
# compile at SQL batch sizes costs 10-180 s (measured).  Embedding a sort
# in every exec's fused kernel therefore recompiles that cost per
# (operator, schema, bucket).  Instead, the sort itself lives in a
# STANDALONE jitted kernel keyed only on (word count, capacity), shared
# by every sort/window/exchange/range in the process and reused from the
# persistent compile cache across processes.  Callers split their work
# into (encode keys) -> shared sort -> (apply order), each side cheap to
# compile.

def stack_sort_words(key_groups: List[List[jnp.ndarray]],
                     row_mask: jnp.ndarray) -> jnp.ndarray:
    """[m, cap] uint64 word matrix, most-significant first, with the
    padding key leading so padding rows always sort last."""
    flat: List[jnp.ndarray] = []
    for group in key_groups:
        flat.extend(group)
    pad_key = (~row_mask).astype(jnp.uint64)
    return jnp.stack([pad_key] + flat)


def stack_sort_digits(field_groups: List[List[Tuple[int, jnp.ndarray]]],
                      row_mask: jnp.ndarray) -> jnp.ndarray:
    """Bit-width-aware u32 digit matrix for a full sort spec: the
    padding flag leads (so padding rows always sort last), then each
    column's encode_fields output in priority order.  Narrow fields
    (vbits hints, dtype widths, 1-bit null flags) pack densely, so the
    digit count — and with it the number of radix passes and digit
    gathers — is typically 2-3x smaller than the u64-word encoding."""
    fields: List[Tuple[int, jnp.ndarray]] = [
        (1, (~row_mask).astype(jnp.uint64))]
    for g in field_groups:
        fields.extend(g)
    return fields_to_digits(fields)


def _digit_sort_impl(digits: jnp.ndarray) -> jnp.ndarray:
    if digits.shape[0] == 1:
        # everything fits one u32: a single direct stable pair sort
        _, perm = jax.lax.sort(
            (digits[0], jnp.arange(digits.shape[1], dtype=jnp.int32)),
            num_keys=1, is_stable=True)
        return perm
    return radix_order_digits(digits)


def shared_digit_sort(digits: jnp.ndarray) -> jnp.ndarray:
    """Stable order for a [d, cap] u32 digit matrix (LSB digit first)
    via the shared per-(d, cap) kernel."""
    from spark_rapids_tpu.exec import kernel_cache as kc
    d, cap = int(digits.shape[0]), int(digits.shape[1])
    fn = kc.get_kernel(("shared_digit_sort", d, cap),
                       lambda: _digit_sort_impl)
    return fn(digits)


def digit_boundaries(digits: jnp.ndarray, order: jnp.ndarray,
                     row_mask: jnp.ndarray) -> jnp.ndarray:
    """After sorting with ``order``, mark rows whose key differs from
    the previous row's (group starts) — the digits analog of
    group_boundaries.  Padding rows always start their own group."""
    n = order.shape[0]
    sorted_mask = jnp.take(row_mask, order)
    new_group = jnp.zeros((n,), dtype=jnp.bool_).at[0].set(True)
    for di in range(digits.shape[0]):
        ds = jnp.take(digits[di], order)
        new_group = new_group | jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), ds[1:] != ds[:-1]])
    prev_mask = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_mask[:-1]])
    return new_group | (sorted_mask != prev_mask)


def shared_lexsort(wm: jnp.ndarray) -> jnp.ndarray:
    """Stable sort order for a [m, cap] word matrix via the shared
    per-(m, cap) kernel.  The kernel body is the LSD radix scan, whose
    compile cost is one single-key sort for ANY m (see radix_order)."""
    from spark_rapids_tpu.exec import kernel_cache as kc
    m, cap = int(wm.shape[0]), int(wm.shape[1])
    fn = kc.get_kernel(("shared_lexsort4", m, cap),
                       lambda: radix_order)
    return fn(wm)


def _shared_partition_order_impl(targets: jnp.ndarray) -> jnp.ndarray:
    """Stable order grouping rows by small non-negative target id: one
    single-operand u64 sort of (target << 32 | row)."""
    cap = targets.shape[0]
    iota = jnp.arange(cap, dtype=jnp.uint64)
    key = (targets.astype(jnp.uint64) << jnp.uint64(32)) | iota
    skey = jnp.sort(key)
    return (skey & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)


def shared_partition_order(targets: jnp.ndarray) -> jnp.ndarray:
    """Stable grouping order for int32 targets in [0, 2^31); shared
    kernel keyed on capacity only."""
    from spark_rapids_tpu.exec import kernel_cache as kc
    cap = int(targets.shape[0])
    fn = kc.get_kernel(("shared_partition_order", cap),
                       lambda: _shared_partition_order_impl)
    return fn(targets)


def hash_group_ids(words: List[jnp.ndarray], row_mask: jnp.ndarray):
    """Dense group ids for equal-key rows WITHOUT sorting: linear-probe
    hash build with scatter claims (the cudf hash-groupby analog).

    Returns (seg, n_groups): seg[i] in [0, n_groups) for real rows —
    equal keys share an id — and cap-1 for padding rows (safe: padding
    implies n_groups < cap).  Ids are dense but hash-ordered."""
    import jax
    cap = int(row_mask.shape[0])
    wm = jnp.stack(words)                      # [W, cap] uint64
    W = wm.shape[0]
    h = jnp.full((cap,), 2166136261, dtype=jnp.uint32)
    for i in range(W):
        for part in (wm[i].astype(jnp.uint32),
                     (wm[i] >> jnp.uint64(32)).astype(jnp.uint32)):
            h = (h ^ part) * jnp.uint32(16777619)
    # the probe wraparound is a bitmask, so the table size must be a
    # power of two regardless of the (configurable) batch capacity
    T = 1
    while T < 2 * cap:
        T <<= 1
    tmask = jnp.int32(T - 1)
    row_idx = jnp.arange(cap, dtype=jnp.int32)
    MAXI = jnp.int32(np.iinfo(np.int32).max)
    slot0 = jnp.where(row_mask, (h & tmask.astype(jnp.uint32))
                      .astype(jnp.int32), 0)
    init = (slot0, ~row_mask, jnp.full((T,), -1, dtype=jnp.int32))

    def cond(c):
        return jnp.any(~c[1])

    def body(c):
        slot, resolved, owner = c
        unres = ~resolved
        own = jnp.take(owner, slot)
        cand = jnp.where(unres & (own < 0), row_idx, MAXI)
        claimed = jnp.full((T,), MAXI, dtype=jnp.int32
                           ).at[slot].min(cand, mode="drop")
        owner = jnp.where((owner < 0) & (claimed < MAXI), claimed,
                          owner)
        own2 = jnp.take(owner, slot)
        ref = jnp.clip(own2, 0, cap - 1)
        eq = own2 >= 0
        for i in range(W):
            eq = eq & (wm[i] == jnp.take(wm[i], ref))
        done = (own2 == row_idx) | eq
        resolved2 = resolved | (unres & done)
        slot2 = jnp.where(resolved2, slot, (slot + 1) & tmask)
        return slot2, resolved2, owner

    slot, _, owner = jax.lax.while_loop(cond, body, init)
    used = owner >= 0
    dense = jnp.cumsum(used.astype(jnp.int32)) - 1
    n_groups = jnp.sum(used.astype(jnp.int32))
    seg = jnp.where(row_mask, jnp.take(dense, slot),
                    jnp.int32(cap - 1))
    return seg, n_groups
