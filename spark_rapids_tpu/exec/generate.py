"""Generate execs: explode/posexplode over array columns.

Reference analog: ``GpuGenerateExec`` (reference: GpuGenerateExec.scala:101
— per-row list explode via cudf).  On TPU the data-dependent output size
uses the same two-pass count-then-emit pattern as the join: per-row
emission counts -> inclusive cumsum -> searchsorted maps each output slot
back to its source row and element ordinal, all masked gathers at a static
bucketed capacity.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             bucket_rows)
from spark_rapids_tpu.exec.base import PhysicalPlan, TpuExec, timed
from spark_rapids_tpu.expr import eval_cpu, eval_tpu, ir
from spark_rapids_tpu.plan.logical import Schema


class CpuGenerateExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, generator: ir.Generator,
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.generator = generator
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        gen = self.generator
        outer = gen.outer
        with_pos = isinstance(gen, ir.PosExplode)
        el = gen.children[0].dtype.element

        def run(it) -> Iterator[pa.Table]:
            for t in it:
                v = eval_cpu.evaluate(gen.children[0], t)
                n = t.num_rows
                counts = np.zeros(n, dtype=np.int64)
                for i in range(n):
                    c = len(v.data[i]) if v.valid[i] else 0
                    counts[i] = max(c, 1) if outer else c
                row_idx = np.repeat(np.arange(n), counts)
                base = t.take(pa.array(row_idx))
                pos: List[Optional[int]] = []
                elems: List = []
                for i in range(n):
                    lst = v.data[i] if v.valid[i] else None
                    c = len(lst) if lst is not None else 0
                    if c == 0:
                        if outer:
                            pos.append(None)
                            elems.append(None)
                        continue
                    for j in range(c):
                        pos.append(j)
                        elems.append(lst[j])
                arrays = list(base.columns)
                names = list(base.column_names)
                if with_pos:
                    arrays.append(pa.array(pos, type=pa.int32()))
                    names.append(self._schema.names[len(names)])
                arrays.append(pa.array(elems, type=el.to_arrow()))
                names.append(self._schema.names[len(names)])
                out = pa.Table.from_arrays(arrays, names=names)
                self.metrics.num_output_rows += out.num_rows
                yield out

        return [run(it) for it in self.children[0].execute()]


def _generate_kernel(batch: DeviceBatch, gen: ir.Generator, out_cap: int,
                     schema: Schema, with_pos: bool, outer: bool
                     ) -> DeviceBatch:
    v = eval_tpu.evaluate(gen.children[0], batch)
    counts = jnp.where(v.validity, v.lengths, 0).astype(jnp.int64)
    if outer:
        counts = jnp.where(batch.row_mask(), jnp.maximum(counts, 1), 0)
    incl = jnp.cumsum(counts)
    total = incl[-1]

    k = jnp.arange(out_cap, dtype=jnp.int64)
    r = jnp.searchsorted(incl, k, side="right")
    r = jnp.clip(r, 0, batch.capacity - 1)
    j = k - (jnp.take(incl, r) - jnp.take(counts, r))
    valid_out = k < total

    cols = [c.gather(r, valid_out) for c in batch.columns]
    names = list(batch.names)

    eff_len = jnp.where(v.validity, v.lengths, 0)
    if with_pos:
        # outer rows emitted for an empty/null array carry null pos
        from_empty = jnp.take(eff_len, r) == 0
        pos_valid = valid_out & ~from_empty if outer else valid_out
        pos = jnp.where(pos_valid, j, 0).astype(jnp.int32)
        cols.append(DeviceColumn(dt.INT32, pos, pos_valid))
        names.append(schema.names[len(names)])

    max_len = v.data.shape[1]
    jj = jnp.clip(j, 0, max_len - 1).astype(jnp.int32)
    elem_rows = jnp.take(v.data, r, axis=0)
    elem = jnp.take_along_axis(elem_rows, jj[:, None], axis=1)[:, 0]
    ev = jnp.take(v.elem_validity, r, axis=0) \
        if v.elem_validity is not None else \
        jnp.ones(elem_rows.shape, dtype=jnp.bool_)
    elem_ok = jnp.take_along_axis(ev, jj[:, None], axis=1)[:, 0]
    in_list = j < jnp.take(eff_len, r)
    elem_valid = valid_out & in_list & elem_ok
    el = gen.children[0].dtype.element
    cols.append(DeviceColumn(
        el, jnp.where(elem_valid, elem, 0).astype(el.to_np()), elem_valid))
    names.append(schema.names[len(names)])
    return DeviceBatch(names, cols, total)


class TpuGenerateExec(TpuExec):
    """Two-pass explode: count on device (one scalar sync), emit at the
    bucketed static capacity."""

    def __init__(self, child: PhysicalPlan, generator: ir.Generator,
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.generator = generator
        self._schema = schema
        self._kernels = {}

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        gen = self.generator
        with_pos = isinstance(gen, ir.PosExplode)
        outer = gen.outer

        def count_fn(b):
            v = eval_tpu.evaluate(gen.children[0], b)
            counts = jnp.where(v.validity, v.lengths, 0).astype(jnp.int64)
            if outer:
                counts = jnp.where(b.row_mask(), jnp.maximum(counts, 1), 0)
            return jnp.sum(counts)

        def run(it) -> Iterator[DeviceBatch]:
            from spark_rapids_tpu.exec import kernel_cache as kc
            gsig = kc.expr_sig(gen)
            for b in it:
                ckey = ("gen_count", gsig, outer, b.schema_key())
                if ckey not in self._kernels:
                    self._kernels[ckey] = kc.get_kernel(
                        ckey, lambda: count_fn)
                with timed(self.metrics, "generate.count"):
                    total = int(self._kernels[ckey](b))
                out_cap = bucket_rows(total)
                ekey = ("gen_emit", gsig, out_cap, with_pos, outer,
                        tuple(self._schema.names), b.schema_key())
                if ekey not in self._kernels:
                    self._kernels[ekey] = kc.get_kernel(
                        ekey, lambda: lambda bb: _generate_kernel(
                            bb, gen, out_cap, self._schema, with_pos,
                            outer))
                with timed(self.metrics, "generate.emit"):
                    out = self._kernels[ekey](b)
                self.metrics.add_rows(out.num_rows)
                self.metrics.add_batches()
                yield out

        return [run(it) for it in self.children[0].execute()]
