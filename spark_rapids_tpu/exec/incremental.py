"""Incremental query maintenance: delta scans + retained aggregate
partials.

PR 7's serving result cache is all-or-nothing: entries key on
``io/scan_cache.source_stamps``, so appending ONE file to a watched
dataset invalidates the whole entry and the next hit re-pays the full
scan + aggregate.  This module turns that cache into a *delta-
maintained* one for the plan shape dashboards actually repeat — a
deterministic aggregate over stampable parquet sources:

  * alongside each cacheable aggregate result, the **pre-final merged
    partial state** is retained (the ``_AggSpec`` update/merge/finalize
    triple already makes aggregate state mergeable —
    exec/tpu_aggregate.py) in the same byte-budget LRU the results live
    in (``serve.resultCache.maxBytes``), keyed by plan digest + the
    per-file stamp set;
  * on a lookup whose stamp set drifted by **pure append** (every old
    file's (path, mtime_ns, size) stamp unchanged, new files added —
    ``io/scan_cache.classify_stamp_delta``), the SAME plan re-runs its
    update phase over only the delta files (a ``file_subset``
    restriction threaded through the scan node), ``merge_aggregate``
    folds the retained partials in, and finalize produces the full
    result — recompute cost proportional to the delta, not the
    dataset;
  * any other drift (rewrite, shrink, delete, mtime-only touch, or a
    file moving mid-refresh) falls back to the full recompute, which
    stays the bit-identical correctness oracle
    (``serve.incremental.enabled`` is the one-knob revert, the
    ``sql.fusion.enabled`` pattern);
  * a low-priority background refresher (``serve.incremental.
    refreshMs``, the sched/precompile idle-wait idiom) polls stamps
    and delta-refreshes retained entries off the serving path, so
    interactive hits stay warm instead of paying the delta on first
    touch.

Watched datasets: ``read.parquet(dir)`` expands the directory eagerly,
so the scan records its original ``source_roots`` and the maintenance
path re-expands them at lookup time — a file appended to the directory
appears as a new path in the stamp set (and invalidates/delta-refreshes
the entry) instead of being silently invisible to the frozen file list.

Eligibility (reported explain-style by :func:`explain`): the root
chain (Sort/Limit/Project allowed on top) must end at ONE Aggregate
whose functions are all decomposable (count/sum/min/max/avg, no
DISTINCT — First/Last are arrival-order-dependent), over a
Filter/Project chain on ONE parquet FileScan — no joins, no nested
aggregates, no nondeterministic expressions, no distributed two-stage
aggregate (per-partition partials have no single retained state).

Registry counters (→ /metrics): ``serve.incremental.hits`` /
``deltaFiles`` / ``deltaBatches`` / ``fullFallbacks[.reason]`` /
``refreshRuns`` / ``ineligible.<reason>``; the per-query profile gains
an always-present ``incremental`` section.
"""

from __future__ import annotations

import copy
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.obs import registry as _obsreg
from spark_rapids_tpu.plan import logical as lp

# partial-state entries ride the serving result cache (byte accounting
# against serve.resultCache.maxBytes comes for free) under a namespaced
# digest; the marker names keep them from ever colliding with a real
# result's (digest, output-names) pair
PARTIAL_SUFFIX = "#partial"
PARTIAL_NAMES = ("__incremental_partial__",)

_DECOMPOSABLE = (ir.Count, ir.Sum, ir.Min, ir.Max, ir.Average)

# root-chain nodes allowed ABOVE the maintained aggregate: they are
# deterministic row-wise/order transforms of the finalized output, so
# re-running them over a delta-merged aggregate is exactly re-running
# them over the full recompute's aggregate
_ABOVE_AGG = (lp.Sort, lp.Limit, lp.Project)
_BELOW_AGG = (lp.Filter, lp.Project)


# ---------------------------------------------------------------------------
# Eligibility
# ---------------------------------------------------------------------------

def _unalias(e: ir.Expression) -> ir.Expression:
    return e.children[0] if isinstance(e, ir.Alias) else e


def _root_aggregate(plan: lp.LogicalPlan) -> Optional[lp.Aggregate]:
    node = plan
    while isinstance(node, _ABOVE_AGG):
        node = node.children[0]
    return node if isinstance(node, lp.Aggregate) else None


def _scan_below(agg: lp.Aggregate):
    node = agg.children[0]
    while isinstance(node, _BELOW_AGG):
        node = node.children[0]
    return node


def eligibility(plan: lp.LogicalPlan,
                conf=None) -> Tuple[bool, str]:
    """(eligible, reason) for delta maintenance of ``plan`` (module
    docstring).  ``reason`` is ``"eligible"`` on success, else the
    explain-style slug also used for the
    ``serve.incremental.ineligible.<reason>`` counter."""
    agg = _root_aggregate(plan)
    if agg is None:
        return False, "non_agg_root"
    for a in agg.aggregates:
        fn = _unalias(a)
        if not isinstance(fn, _DECOMPOSABLE) or \
                getattr(fn, "distinct", False):
            return False, "non_decomposable_function"
    below = _scan_below(agg)
    if isinstance(below, lp.Join):
        return False, "join"
    if isinstance(below, lp.Aggregate):
        # nested aggregate (incl. the DISTINCT double-agg rewrite):
        # the inner dedup state is not mergeable across delta runs
        return False, "non_decomposable_function"
    if not isinstance(below, lp.FileScan):
        return False, "non_scan_subtree"
    if below.fmt != "parquet":
        return False, "non_parquet_source"
    from spark_rapids_tpu.plan import digest as pdig
    for node in pdig.walk(plan):
        for e in pdig.iter_node_exprs(node):
            if ir.collect(e, lambda x: type(x).__name__
                          in pdig._NONDETERMINISTIC_EXPRS):
                return False, "nondeterminism"
    if conf is not None and agg.groupings:
        # the planner's two-stage shape merges partials PER PARTITION
        # behind a hash exchange — there is no single merged partial
        # to retain (planner.plan_cpu two_stage condition, mirrored)
        if conf.get(cfg.AGG_EXCHANGE) or \
                str(conf.get(cfg.SHUFFLE_TRANSPORT)) in (
                    "ici", "ici_ring", "process"):
            return False, "distributed_agg"
    return True, "eligible"


def explain(plan: lp.LogicalPlan, conf=None) -> List[str]:
    """Explain-style eligibility report (DataFrame.explain idiom)."""
    ok, reason = eligibility(plan, conf)
    if ok:
        agg = _root_aggregate(plan)
        scan = _scan_below(agg)
        return [
            "incremental maintenance: ELIGIBLE",
            f"  aggregate: {len(agg.groupings)} grouping(s), "
            f"{len(agg.aggregates)} decomposable function(s)",
            f"  sources: {len(scan.paths)} parquet file(s)"
            + (" (watched roots)" if scan.options.get("source_roots")
               else ""),
        ]
    return [f"incremental maintenance: INELIGIBLE ({reason})"]


# ---------------------------------------------------------------------------
# Watched-dataset expansion + stamps
# ---------------------------------------------------------------------------

def current_files(scan: lp.FileScan) -> Tuple[List[str], List[dict]]:
    """(files, part_values) the scan resolves to RIGHT NOW: the
    recorded ``source_roots`` re-expanded when present (so appended
    files appear), else the frozen snapshot taken at read() time."""
    roots = scan.options.get("source_roots")
    if not roots:
        return (list(scan.paths),
                list(scan.options.get("part_values") or []))
    from spark_rapids_tpu.io.readers import expand_paths
    return expand_paths(scan.fmt, list(roots))


def current_stamps(plan: lp.LogicalPlan):
    """Current source stamps for a plan — ``scan_cache.source_stamps``
    over the *live* expansion of every FileScan (None when any source
    can't be stamped, matching the source_stamps contract)."""
    from spark_rapids_tpu.io import scan_cache as sc
    from spark_rapids_tpu.plan import digest as pdig
    paths: List[str] = []
    for node in pdig.walk(plan):
        if isinstance(node, lp.FileScan):
            files, _ = current_files(node)
            paths.extend(files)
    return sc.source_stamps(sorted(set(paths)))


def files_from_stamps(scan: lp.FileScan, stamps
                      ) -> Tuple[List[str], List[dict]]:
    """(files, part_values) for the maintained scan, derived from an
    already-computed stamp set instead of a second directory
    expansion — the serving path stamps the sources once per lookup
    and reuses that sweep here (eligible plans have exactly ONE
    FileScan, so the stamp set's paths ARE this scan's live file
    list).  Partition values re-derive through the same
    ``readers.dir_part_values`` parser ``expand_paths`` uses."""
    import os as _os
    from spark_rapids_tpu.io.readers import dir_part_values
    roots = [_os.path.abspath(r)
             for r in (scan.options.get("source_roots") or [])]
    if not roots:
        return (list(scan.paths),
                list(scan.options.get("part_values") or []))
    files = [s[1] for s in stamps]
    pvs = []
    for f in files:
        pv: dict = {}
        for r in roots:
            if _os.path.isdir(r) and \
                    _os.path.abspath(f).startswith(r + _os.sep):
                pv = dir_part_values(r, f)
                break
        pvs.append(pv)
    return files, pvs


# ---------------------------------------------------------------------------
# Plan cloning + stamping
# ---------------------------------------------------------------------------

class PartialSink:
    """Capture slot the aggregate exec fills with the merged partial
    state (as a host Arrow table of the static __k*/__a* buffer
    columns) just before finalize — exec/tpu_aggregate.py honors it
    through the ``_incremental`` plan stamp."""

    __slots__ = ("table", "update_batches")

    def __init__(self):
        self.table = None
        self.update_batches = 0


def _refreshed_scan(scan: lp.FileScan, files: List[str],
                    part_values: List[dict],
                    file_subset=None) -> lp.FileScan:
    """Shallow clone of ``scan`` re-pinned to the live file list, with
    an optional ``file_subset`` restriction (delta scans).  The subset
    rides ``options`` so it participates in the plan digest and both
    scan execs (device + CPU fallback) honor it."""
    new = copy.copy(scan)
    new.paths = list(files)
    opts = dict(scan.options)
    opts["part_values"] = list(part_values)
    if file_subset is not None:
        opts["file_subset"] = tuple(sorted(
            os.path.abspath(p) for p in file_subset))
    else:
        opts.pop("file_subset", None)
    new.options = opts
    return new


def clone_stamped(plan: lp.LogicalPlan, files: List[str],
                  part_values: List[dict],
                  sink: Optional[PartialSink] = None,
                  retained=None, delta_files=None,
                  is_delta: bool = False) -> lp.LogicalPlan:
    """Clone the (linear, eligibility-checked) plan chain with the scan
    re-pinned/restricted and the aggregate stamped for partial capture
    and retained-state merge.  The original plan is never mutated —
    stamps ride private attrs the plan digest skips, except the file
    subset which rides scan options (it changes result content, so it
    must change the digest)."""

    def rec(node: lp.LogicalPlan) -> lp.LogicalPlan:
        if isinstance(node, lp.FileScan):
            return _refreshed_scan(node, files, part_values,
                                   file_subset=delta_files)
        c = copy.copy(node)
        c.children = tuple(rec(ch) for ch in node.children)
        if isinstance(node, lp.Aggregate):
            c._incremental = {"sink": sink, "retained": retained,
                              "delta": bool(is_delta)}
        return c

    return rec(plan)


def repin_plan(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """Clone with every watched FileScan re-expanded to the live file
    list (no aggregate stamps): the full-recompute path over the
    CURRENT dataset snapshot, so a result cached under live stamps was
    really computed over the files those stamps describe."""

    def rec(node: lp.LogicalPlan) -> lp.LogicalPlan:
        if isinstance(node, lp.FileScan):
            files, pvs = current_files(node)
            if list(files) == list(node.paths) and \
                    "file_subset" not in node.options:
                return node
            return _refreshed_scan(node, files, pvs)
        if not node.children:
            return node
        kids = tuple(rec(ch) for ch in node.children)
        if all(k is o for k, o in zip(kids, node.children)):
            return node
        c = copy.copy(node)
        c.children = kids
        return c

    return rec(plan)


# ---------------------------------------------------------------------------
# The maintainer
# ---------------------------------------------------------------------------

class _RunCtx:
    """What one maintained run needs at completion time."""

    __slots__ = ("mode", "cache_key", "names", "stamps",
                 "retained_stamps", "sink", "plan", "delta_paths")

    def __init__(self, mode: str, cache_key: str, names, stamps,
                 retained_stamps, sink: Optional[PartialSink],
                 plan: lp.LogicalPlan, delta_paths=()):
        self.mode = mode                  # "capture" | "delta"
        self.cache_key = cache_key
        self.names = tuple(names)
        self.stamps = stamps              # expected post-run stamp set
        self.retained_stamps = retained_stamps
        self.sink = sink
        self.plan = plan                  # ORIGINAL logical plan
        self.delta_paths = tuple(delta_paths)


class IncrementalMaintainer:
    """Serving-tier incremental maintenance (module docstring).

    One per ServeServer.  ``prepare`` is called on every result-cache
    miss of a cacheable plan and decides full-capture vs delta;
    ``finish`` commits results + partials under verified stamps and
    owns the mid-stream-drift fallback.  ``refresh_once``/the
    background thread keep tracked entries warm off the serving path.
    """

    def __init__(self, session):
        self._session = session
        conf = session.conf
        self.enabled = bool(conf.get(cfg.SERVE_INCREMENTAL_ENABLED))
        self.refresh_ms = int(conf.get(cfg.SERVE_INCREMENTAL_REFRESH_MS))
        self.max_tracked = max(
            1, int(conf.get(cfg.SERVE_INCREMENTAL_MAX_TRACKED)))
        # (cache_key, names) -> {"plan": original logical plan}
        self._tracked: "OrderedDict[Tuple, Dict[str, Any]]" = \
            OrderedDict()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.enabled and self.refresh_ms > 0:
            self._thread = threading.Thread(
                target=self._refresh_loop, name="serve-incremental",
                daemon=True)
            self._thread.start()

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self) -> None:
        self._stop.set()

    def tracked_keys(self) -> List[Tuple]:
        with self._lock:
            return list(self._tracked)

    # -- serving-path hooks -------------------------------------------------
    def prepare(self, plan: lp.LogicalPlan, cache_key: str, names,
                stamps, is_refresh: bool = False):
        """On a result-cache miss for a cacheable plan: returns
        ``(plan_to_submit, ctx)``.  ``ctx`` None means plain full run
        (ineligible or maintenance off) — the caller keeps its legacy
        insert path; otherwise the caller MUST route the completed
        table through :meth:`finish` with this ctx and skip its own
        insert."""
        from spark_rapids_tpu.io import scan_cache as sc
        from spark_rapids_tpu.serve import result_cache
        reg = _obsreg.get_registry()
        if not self.enabled or stamps is None:
            return repin_plan(plan), None
        ok, reason = eligibility(plan, self._session.conf)
        if not ok:
            reg.inc(f"serve.incremental.ineligible.{reason}")
            return repin_plan(plan), None
        agg = _root_aggregate(plan)
        scan = _scan_below(agg)
        # reuse the caller's stamp sweep as the live file list rather
        # than paying a second directory expansion on the serving path
        files, pvs = files_from_stamps(scan, stamps)
        retained = result_cache.lookup_latest(
            cache_key + PARTIAL_SUFFIX, PARTIAL_NAMES)
        if retained is not None:
            old_stamps, ptable = retained
            delta = sc.classify_stamp_delta(old_stamps, stamps)
            if delta.kind == "append":
                sink = PartialSink()
                dplan = clone_stamped(
                    plan, files, pvs, sink=sink, retained=ptable,
                    delta_files=delta.appended, is_delta=True)
                if not is_refresh:
                    reg.inc("serve.incremental.hits")
                reg.inc("serve.incremental.deltaFiles",
                        len(delta.appended))
                return dplan, _RunCtx(
                    "delta", cache_key, names, stamps, old_stamps,
                    sink, plan, delta.appended)
            if delta.kind != "unchanged":
                reg.inc("serve.incremental.fullFallbacks")
                reg.inc(f"serve.incremental.fullFallbacks.{delta.kind}")
        # first sight of this (digest, names) under these stamps — or a
        # non-append drift: full run, capturing partials for next time
        sink = PartialSink()
        cplan = clone_stamped(plan, files, pvs, sink=sink)
        return cplan, _RunCtx("capture", cache_key, names, stamps,
                              None, sink, plan)

    def finish(self, ctx: _RunCtx, table):
        """Commit one maintained run.  Returns the table to stream —
        usually ``table`` itself; a delta run whose OLD files moved
        mid-refresh is torn (its retained partials were stale) and is
        replaced by a synchronous full recompute."""
        from spark_rapids_tpu.io import scan_cache as sc
        reg = _obsreg.get_registry()
        post = current_stamps(ctx.plan)
        if ctx.mode == "delta":
            if ctx.sink is None or ctx.sink.table is None:
                # the aggregate that ran never filled the sink — the
                # _incremental stamp was NOT honored (the plan landed
                # on CpuHashAggregateExec, a per_partition shape, or a
                # future planner path that drops the stamp) while the
                # scan's file_subset restriction WAS: the computed
                # table covers only the delta files.  Eligibility is a
                # prediction; this is the ground truth of what
                # executed — never stream it, recompute fully.
                reg.inc("serve.incremental.fullFallbacks")
                reg.inc("serve.incremental.fullFallbacks.unhonored")
                return self._recompute_full(ctx)
            if post != ctx.stamps:
                d2 = sc.classify_stamp_delta(ctx.retained_stamps,
                                             post or ())
                reg.inc("serve.incremental.fullFallbacks")
                if post is not None and d2.kind in ("append",
                                                    "unchanged"):
                    # delta arrived mid-refresh on top of pure appends:
                    # the computed result is a coherent snapshot (each
                    # file was read through one consistent footer), it
                    # just can't be frozen under any stamp we observed
                    reg.inc("serve.incremental."
                            "fullFallbacks.midStreamAppend")
                    return table
                # an OLD file was rewritten/deleted mid-refresh: the
                # retained partials this run merged were stale — the
                # result may correspond to NO dataset snapshot.  Never
                # stream it; recompute fully.
                reg.inc("serve.incremental."
                        "fullFallbacks.midStreamDrift")
                return self._recompute_full(ctx)
            self._commit(ctx, table)
            return table
        # capture: freeze result + partial only under held stamps (the
        # serve pre/post-stamp pin, extended to the partial state)
        if post == ctx.stamps:
            self._commit(ctx, table)
        return table

    # -- internals ----------------------------------------------------------
    def _commit(self, ctx: _RunCtx, table) -> None:
        from spark_rapids_tpu.serve import result_cache
        reg = _obsreg.get_registry()
        result_cache.insert(ctx.cache_key, ctx.names, ctx.stamps, table)
        if ctx.sink is not None and ctx.sink.table is not None:
            if result_cache.insert(ctx.cache_key + PARTIAL_SUFFIX,
                                   PARTIAL_NAMES, ctx.stamps,
                                   ctx.sink.table):
                reg.inc("serve.incremental.partialsRetained")
        with self._lock:
            key = (ctx.cache_key, ctx.names)
            self._tracked[key] = {"plan": ctx.plan}
            self._tracked.move_to_end(key)
            while len(self._tracked) > self.max_tracked:
                self._tracked.popitem(last=False)

    def _recompute_full(self, ctx: _RunCtx):
        fut = self._session._query_service.submit(repin_plan(ctx.plan))
        return fut.result()

    # -- background refresher ----------------------------------------------
    def _busy(self) -> bool:
        """Live (queued or running) queries — the signal the refresher
        yields to (the sched/precompile low-priority contract)."""
        try:
            return self._session._query_service.has_live_queries()
        except Exception:
            return False

    def _yield_to_serving(self) -> None:
        import time
        while not self._stop.is_set() and self._busy():
            time.sleep(max(self.refresh_ms, 5) / 1e3)

    def _refresh_loop(self) -> None:
        period = max(self.refresh_ms, 1) / 1e3
        while not self._stop.wait(period):
            try:
                self.refresh_once()
            except Exception:
                pass

    def refresh_once(self) -> int:
        """One refresher sweep: delta-refresh every tracked entry whose
        sources drifted by pure append.  Returns how many entries were
        refreshed.  Public so tests and the CI gate can drive a sweep
        deterministically."""
        from spark_rapids_tpu.serve import result_cache
        reg = _obsreg.get_registry()
        with self._lock:
            items = list(self._tracked.items())
        ran = 0
        for (cache_key, names), ent in items:
            if self._stop.is_set():
                break
            self._yield_to_serving()
            plan = ent["plan"]
            stamps = current_stamps(plan)
            if stamps is None:
                continue
            latest = result_cache.lookup_latest(cache_key, names)
            if latest is not None and latest[0] == stamps:
                continue                  # still warm
            sub, ctx = self.prepare(plan, cache_key, names, stamps,
                                    is_refresh=True)
            if ctx is None or ctx.mode != "delta":
                # non-append drift (or evicted partial): the next
                # client query pays the full recompute; the refresher
                # never burns a full dataset pass in the background
                continue
            try:
                fut = self._session._query_service.submit(
                    sub, priority=-1)
                self.finish(ctx, fut.result())
                reg.inc("serve.incremental.refreshRuns")
                ran += 1
            except Exception:
                pass
        return ran
