"""Per-task evaluation context: partition id + running row offset.

Analog of the TaskContext the reference's GpuSparkPartitionID /
GpuMonotonicallyIncreasingID read (reference: GpuSparkPartitionID.scala,
GpuMonotonicallyIncreasingID.scala).

CPU execs set concrete ints.  TPU execs set *tracers* inside their jitted
kernel (the kernel takes pid/offset as traced arguments), so one compiled
kernel serves every partition — the context var only ever holds values for
the duration of a single evaluate() call.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Tuple

_CTX: contextvars.ContextVar[Tuple[Any, Any]] = contextvars.ContextVar(
    "spark_rapids_tpu_eval_ctx", default=(0, 0))


def get() -> Tuple[Any, Any]:
    """(partition_id, row_offset) — ints on CPU, possibly tracers on TPU."""
    return _CTX.get()


@contextlib.contextmanager
def task_context(partition_id, row_offset):
    token = _CTX.set((partition_id, row_offset))
    try:
        yield
    finally:
        _CTX.reset(token)


# file-scan scope for input_file_name() (reference: GpuInputFileBlock.scala
# reads InputFileBlockHolder; scans set it per file)
_FILE_CTX: contextvars.ContextVar[str] = contextvars.ContextVar(
    "spark_rapids_tpu_input_file", default="")


def input_file() -> str:
    return _FILE_CTX.get()


@contextlib.contextmanager
def file_scope(path: str):
    token = _FILE_CTX.set(path)
    try:
        yield
    finally:
        _FILE_CTX.reset(token)
