"""Per-task evaluation context: partition id + running row offset.

Analog of the TaskContext the reference's GpuSparkPartitionID /
GpuMonotonicallyIncreasingID read (reference: GpuSparkPartitionID.scala,
GpuMonotonicallyIncreasingID.scala).

CPU execs set concrete ints.  TPU execs set *tracers* inside their jitted
kernel (the kernel takes pid/offset as traced arguments), so one compiled
kernel serves every partition — the context var only ever holds values for
the duration of a single evaluate() call.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Any, Tuple

_CTX: contextvars.ContextVar[Tuple[Any, Any]] = contextvars.ContextVar(
    "spark_rapids_tpu_eval_ctx", default=(0, 0))


def get() -> Tuple[Any, Any]:
    """(partition_id, row_offset) — ints on CPU, possibly tracers on TPU."""
    return _CTX.get()


@contextlib.contextmanager
def task_context(partition_id, row_offset):
    token = _CTX.set((partition_id, row_offset))
    try:
        yield
    finally:
        _CTX.reset(token)


# file-scan slot for input_file_name() (reference: GpuInputFileBlock.scala
# reads InputFileBlockHolder; scans set it per file).  A thread-local
# last-writer-wins slot set immediately before each batch is yielded —
# NOT a generator-scoped contextvar, whose set/reset straddles yield
# suspensions and so mis-attributes paths when two scans are consumed
# interleaved (e.g. both sides of a join).  Thread-local matches Spark's
# per-task InputFileBlockHolder: when partitions execute on a thread
# pool, each task thread sees only its own scan's path.
_FILE_SLOT = threading.local()


def input_file() -> str:
    return getattr(_FILE_SLOT, "path", "")


def set_input_file(path: str) -> None:
    """Mark `path` as the source of the batch about to be yielded.

    Scans must call ``set_input_file("")`` when exhausted, and the
    session clears the slot at query start, so a later query that reads
    no files sees Spark's empty-string default rather than a stale path.
    """
    _FILE_SLOT.path = path
