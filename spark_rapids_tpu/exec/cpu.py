"""CPU physical execs — the engine's "stock Spark" execution path.

In the reference, unsupported operators stay as Spark's own CPU execs
(reference: RapidsMeta.scala:605-624 convertIfNeeded keeps original nodes).
We are standalone, so these execs play that role: a complete, independent
columnar CPU engine over pyarrow, used (a) as the fallback target for
anything the TPU path can't run, and (b) as the oracle side of the parity
test harness (reference: SparkQueryCompareTestSuite).

Batch currency: ``pyarrow.Table``.
"""

from __future__ import annotations

import itertools
from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr import eval_cpu, ir
from spark_rapids_tpu.plan.logical import Field, Schema, SortOrder
from spark_rapids_tpu.exec.base import PhysicalPlan


def _empty_table(schema: Schema) -> pa.Table:
    return pa.Table.from_arrays(
        [pa.array([], type=f.dtype.to_arrow()) for f in schema.fields],
        names=schema.names)


def concat_tables(tables: List[pa.Table], schema: Schema) -> pa.Table:
    if not tables:
        return _empty_table(schema)
    if len(tables) == 1:
        return tables[0]
    # no schema promotion: batches of one plan share a schema, and joins
    # legitimately produce duplicate column names that unification rejects
    return pa.concat_tables(tables)


class CpuScanExec(PhysicalPlan):
    def __init__(self, table: pa.Table, num_partitions: int = 1,
                 max_batch_rows: int = 1 << 20):
        super().__init__()
        self.table = table
        self.num_partitions = max(1, num_partitions)
        self.max_batch_rows = max_batch_rows
        self._schema = Schema.from_arrow(table.schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self) -> List[Iterator[pa.Table]]:
        n = self.table.num_rows
        per = (n + self.num_partitions - 1) // self.num_partitions or 1

        def part(i: int) -> Iterator[pa.Table]:
            lo = min(i * per, n)
            hi = min(lo + per, n)
            chunk = self.table.slice(lo, hi - lo)
            for off in range(0, max(chunk.num_rows, 1), self.max_batch_rows):
                yield chunk.slice(off, self.max_batch_rows)
                if chunk.num_rows == 0:
                    break
        return [part(i) for i in range(self.num_partitions)]

    def simple_string(self) -> str:
        return f"CpuScanExec(rows={self.table.num_rows})"


class CpuRangeExec(PhysicalPlan):
    def __init__(self, start: int, end: int, step: int, num_partitions: int):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)
        self._schema = Schema([Field("id", dt.INT64, False)])

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self) -> List[Iterator[pa.Table]]:
        vals = np.arange(self.start, self.end, self.step, dtype=np.int64)
        per = (len(vals) + self.num_partitions - 1) // self.num_partitions or 1

        def part(i):
            chunk = vals[i * per:(i + 1) * per]
            yield pa.Table.from_arrays([pa.array(chunk)], names=["id"])
        return [part(i) for i in range(self.num_partitions)]


class CpuProjectExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, exprs: Sequence[ir.Expression],
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.exprs = list(exprs)
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        from spark_rapids_tpu.exec import context

        def run(pid, it):
            offset = 0
            for t in it:
                with context.task_context(pid, offset):
                    arrays = [eval_cpu.to_arrow_array(
                        eval_cpu.evaluate(e, t)) for e in self.exprs]
                offset += t.num_rows
                yield pa.Table.from_arrays(arrays, names=self._schema.names)
        return [run(pid, it) for pid, it in
                enumerate(self.children[0].execute())]


class CpuFilterExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, condition: ir.Expression):
        super().__init__()
        self.children = (child,)
        self.condition = condition

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        def run(it):
            for t in it:
                v = eval_cpu.evaluate(self.condition, t)
                mask = v.data.astype(bool) & v.valid
                yield t.filter(pa.array(mask))
        return [run(it) for it in self.children[0].execute()]


class CpuUnionExec(PhysicalPlan):
    def __init__(self, children: Sequence[PhysicalPlan]):
        super().__init__()
        self.children = tuple(children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        parts: List[Iterator[pa.Table]] = []
        for c in self.children:
            parts.extend(c.execute())
        return parts


class CpuLimitExec(PhysicalPlan):
    """Global limit: concatenates partitions in order and takes n rows."""

    def __init__(self, child: PhysicalPlan, n: int):
        super().__init__()
        self.children = (child,)
        self.n = n

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        def run():
            remaining = self.n
            for it in self.children[0].execute():
                for t in it:
                    if remaining <= 0:
                        return
                    take = min(remaining, t.num_rows)
                    remaining -= take
                    yield t.slice(0, take)
        return [run()]


def _gather_single(child: PhysicalPlan, schema: Schema) -> pa.Table:
    tables = []
    for it in child.execute():
        tables.extend(list(it))
    return concat_tables(tables, schema)


class CpuSortExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder],
                 partitionwise: bool = False):
        super().__init__()
        self.children = (child,)
        self.orders = list(orders)
        # partitionwise: each child partition sorts independently (the
        # planner put a range exchange below, so partition-ordered
        # concatenation is the total order)
        self.partitionwise = partitionwise

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute(self):
        if self.partitionwise:
            return [self._run_one(
                lambda it=it: concat_tables(list(it), self.schema))
                for it in self.children[0].execute()]
        return [self._run_one(
            lambda: _gather_single(self.children[0], self.schema))]

    def _run_one(self, get_table):
        def run():
            t = get_table()
            key_names = []
            key_arrays = []
            sort_keys = []
            for i, o in enumerate(self.orders):
                name = f"__sort_{i}"
                v = eval_cpu.evaluate(o.expr, t)
                if v.dtype.is_floating:
                    # Spark total order: -inf < ... < +inf < NaN, and
                    # -0.0 == 0.0.  Arrow's sort groups NaN with nulls,
                    # so sort on the sign-flipped IEEE bit key instead
                    # (same transform as the device sortkeys encoder).
                    x = v.data.astype(np.float64)
                    x = np.where(np.isnan(x), np.nan, x)   # canonical NaN
                    x = np.where(x == 0.0, 0.0, x)         # -0.0 -> 0.0
                    u = x.view(np.uint64)
                    sign = np.uint64(1) << np.uint64(63)
                    ukey = np.where(u >> np.uint64(63) == 1, ~u,
                                    u | sign)
                    key = (ukey ^ sign).view(np.int64)
                    v = eval_cpu.CpuVal(dt.INT64, key, v.valid)
                key_names.append(name)
                key_arrays.append(eval_cpu.to_arrow_array(v))
                sort_keys.append((name, "ascending" if o.ascending
                                  else "descending"))
            keyed = t
            for n_, a in zip(key_names, key_arrays):
                keyed = keyed.append_column(n_, a)
            # Spark: nulls_first default matches ascending; arrow option is
            # global so sort per-key from least significant using stable sort
            idx = np.arange(t.num_rows)
            for (name, order), o in zip(reversed(sort_keys),
                                        reversed(self.orders)):
                col = keyed.column(name).combine_chunks()
                sub = col.take(pa.array(idx))
                order_idx = pc.sort_indices(
                    sub, sort_keys=[("", order)],
                    null_placement="at_start" if o.nulls_first_resolved
                    else "at_end")
                idx = idx[np.asarray(order_idx)]
            yield t.take(pa.array(idx))
        return run()


_AGG_MAP = {
    ir.Sum: "sum",
    ir.Min: "min",
    ir.Max: "max",
    ir.Average: "mean",
}


class CpuHashAggregateExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan,
                 groupings: Sequence[ir.Expression],
                 aggregates: Sequence[ir.Expression],
                 schema: Schema, per_partition: bool = False):
        super().__init__()
        self.children = (child,)
        self.groupings = list(groupings)
        self.aggregates = list(aggregates)
        self._schema = schema
        # per_partition: each child partition aggregates independently
        # (correct when the child is hash-partitioned on the grouping
        # keys — the distributed plan shape, see planner two-stage agg)
        self.per_partition = per_partition

    @property
    def schema(self) -> Schema:
        return self._schema

    def _agg_arrays(self, t: pa.Table) -> pa.Table:
        """Project grouping keys and agg inputs with temp names."""
        arrays, names = [], []
        for i, g in enumerate(self.groupings):
            arrays.append(eval_cpu.to_arrow_array(eval_cpu.evaluate(g, t)))
            names.append(f"__k{i}")
        for i, a in enumerate(self.aggregates):
            child = a.child
            if child is None:
                col = pa.array(np.ones(t.num_rows, dtype=np.int64))
            else:
                col = eval_cpu.to_arrow_array(eval_cpu.evaluate(child, t))
            arrays.append(col)
            names.append(f"__a{i}")
        return pa.Table.from_arrays(arrays, names=names)

    @staticmethod
    def _hashable(v):
        """Nested value -> hashable group key (NaN==NaN, -0.0==0.0)."""
        if isinstance(v, list):
            return tuple(CpuHashAggregateExec._hashable(x) for x in v)
        if isinstance(v, tuple):
            return tuple(CpuHashAggregateExec._hashable(x) for x in v)
        if isinstance(v, float):
            if v != v:
                return "__NaN__"
            if v == 0.0:
                return 0.0
        return v

    def execute(self):
        if self.per_partition:
            def run_part(it):
                t = concat_tables(list(it), self.children[0].schema)
                out = self._agg_one(t)
                if out.num_rows:
                    yield out
            return [run_part(it) for it in self.children[0].execute()]

        def run():
            t = _gather_single(self.children[0], self.children[0].schema)
            yield self._agg_one(t)
        return [run()]

    def _agg_one(self, t: pa.Table) -> pa.Table:
        proj = self._agg_arrays(t)
        key_names = [f"__k{i}" for i in range(len(self.groupings))]

        # arrow group_by cannot key on nested types; substitute a dense
        # surrogate id per distinct nested value, map back afterwards
        # (Spark supports grouping on arrays)
        nested_originals = {}
        for i, g in enumerate(self.groupings):
            if g.dtype is None or not g.dtype.is_nested:
                continue
            cname = f"__k{i}"
            arr = proj.column(cname)
            py = arr.to_pylist()
            seen, originals = {}, []
            sur = np.empty(len(py), dtype=np.int64)
            for r, v in enumerate(py):
                k = self._hashable(v)
                if k not in seen:
                    seen[k] = len(seen)
                    originals.append(v)
                sur[r] = seen[k]
            proj = proj.set_column(
                proj.column_names.index(cname), cname, pa.array(sur))
            nested_originals[i] = (originals, arr.type)
        aggs = []
        out_names_in_result = []
        count_modes = {}
        # Spark float ordering: NaN is GREATEST (max -> NaN if any NaN;
        # min -> NaN only when every non-null value is NaN).  Arrow's
        # min/max skip NaN, so strip NaNs to null and carry a per-group
        # NaN count to patch the results after the aggregation.
        nan_fix = {}
        for i, a in enumerate(self.aggregates):
            if isinstance(a, (ir.Min, ir.Max)) and \
                    a.dtype is not None and a.dtype.is_floating:
                cname = f"__a{i}"
                c = proj.column(cname).combine_chunks()
                isn = pc.fill_null(pc.is_nan(c), False)
                clean = pc.if_else(isn, pa.scalar(None, c.type), c)
                proj = proj.set_column(
                    proj.column_names.index(cname), cname, clean)
                proj = proj.append_column(
                    f"{cname}__nan", pc.cast(isn, pa.int64()))
                nan_fix[i] = isinstance(a, ir.Min)
        for i, a in enumerate(self.aggregates):
            if isinstance(a, ir.Count):
                mode = "all" if a.child is None else "only_valid"
                count_modes[f"__a{i}"] = mode
                aggs.append((f"__a{i}", "count",
                             pc.CountOptions(mode=mode)))
                out_names_in_result.append(f"__a{i}_count")
            elif isinstance(a, ir.First):
                aggs.append((f"__a{i}", "first", pc.ScalarAggregateOptions(
                    skip_nulls=a.ignore_nulls)))
                out_names_in_result.append(f"__a{i}_first")
            elif isinstance(a, ir.Last):
                aggs.append((f"__a{i}", "last", pc.ScalarAggregateOptions(
                    skip_nulls=a.ignore_nulls)))
                out_names_in_result.append(f"__a{i}_last")
            else:
                fn = _AGG_MAP[type(a)]
                aggs.append((f"__a{i}", fn))
                out_names_in_result.append(f"__a{i}_{fn}")
        for i in nan_fix:
            aggs.append((f"__a{i}__nan", "sum"))
            out_names_in_result.append(f"__a{i}__nan_sum")

        if key_names:
            res = proj.group_by(key_names, use_threads=False).aggregate(
                aggs)
        else:
            # global aggregation (always exactly one output row)
            cols, names2 = [], []
            for (col_name, fn, *opt), oname in zip(aggs,
                                                   out_names_in_result):
                c = proj.column(col_name).combine_chunks()
                options = opt[0] if opt else None
                if fn == "count":
                    val = pc.count(c, mode=count_modes.get(
                        col_name, "only_valid"))
                elif fn == "first":
                    cc = c.drop_null() if (options and
                                           options.skip_nulls) else c
                    val = cc[0] if len(cc) else pa.scalar(None, c.type)
                elif fn == "last":
                    cc = c.drop_null() if (options and
                                           options.skip_nulls) else c
                    val = cc[-1] if len(cc) else pa.scalar(None, c.type)
                else:
                    val = getattr(pc, fn)(c)
                cols.append(pa.array([val.as_py()],
                                     type=getattr(val, "type", None)))
                names2.append(oname)
            res = pa.Table.from_arrays(cols, names=names2)

        # patch Spark NaN ordering into float min/max results
        for i, is_min in nan_fix.items():
            fn = "min" if is_min else "max"
            base_name = f"__a{i}_{fn}"
            base = res.column(base_name).combine_chunks()
            has_nan = pc.greater(
                pc.coalesce(res.column(f"__a{i}__nan_sum"),
                            pa.scalar(0, pa.int64())),
                pa.scalar(0, pa.int64()))
            if is_min:
                # NaN is greatest: min -> NaN only when every non-null
                # value in the group was NaN (clean min came up null)
                cond = pc.and_(pc.is_null(base), has_nan)
            else:
                cond = has_nan
            fixed = pc.if_else(cond, pa.scalar(float("nan"), base.type),
                               base)
            res = res.set_column(
                res.column_names.index(base_name), base_name, fixed)

        # assemble final output: keys then aggs with target dtypes
        out_arrays = []
        for i in range(len(self.groupings)):
            if not key_names:
                out_arrays.append(None)
                continue
            kcol = res.column(f"__k{i}")
            if i in nested_originals:
                originals, ktype = nested_originals[i]
                ids = kcol.to_pylist()
                kcol = pa.chunked_array([pa.array(
                    [originals[s] for s in ids], type=ktype)])
            out_arrays.append(kcol)
        for i, a in enumerate(self.aggregates):
            col = res.column(out_names_in_result[i])
            tgt = self._schema.fields[len(self.groupings) + i].dtype
            col = col.cast(tgt.to_arrow())
            out_arrays.append(col)
        arrays = [a for a in out_arrays if a is not None]
        return pa.Table.from_arrays(arrays, names=self._schema.names)


class CpuExpandExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan,
                 projections: Sequence[Sequence[ir.Expression]],
                 schema: Schema):
        super().__init__()
        self.children = (child,)
        self.projections = projections
        self._schema = schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def execute(self):
        def run(it):
            for t in it:
                for proj in self.projections:
                    arrays = [eval_cpu.to_arrow_array(
                        eval_cpu.evaluate(e, t)) for e in proj]
                    yield pa.Table.from_arrays(arrays,
                                               names=self._schema.names)
        return [run(it) for it in self.children[0].execute()]


def _cast_join_keys(t: pa.Table, keys: List[str], dtypes) -> pa.Table:
    for k, d in zip(keys, dtypes):
        i = t.column_names.index(k)
        col = t.column(k)
        if col.type != d.to_arrow():
            t = t.set_column(i, k, col.cast(d.to_arrow()))
    return t


def _normalize_float_join_keys(t: pa.Table, keys: List[str]
                               ) -> Tuple[pa.Table, List[str]]:
    """Replace float key columns with canonicalized bit-pattern columns."""
    out_keys = []
    for k in keys:
        col = t.column(k).combine_chunks()
        if pa.types.is_floating(col.type):
            mask = np.asarray(col.is_null())
            vals = col.fill_null(0.0).to_numpy(zero_copy_only=False)
            vals = np.where(vals == 0.0, 0.0, vals)
            vals = np.where(np.isnan(vals), np.nan, vals)  # canonical NaN
            if col.type == pa.float32():
                bits = vals.astype(np.float32).view(np.int32)
            else:
                bits = vals.astype(np.float64).view(np.int64)
            name = f"{k}__bits"
            t = t.append_column(name, pa.array(bits, mask=mask))
            out_keys.append(name)
        else:
            out_keys.append(k)
    return t, out_keys


class CpuJoinExec(PhysicalPlan):
    """Hash join via pyarrow Table.join (+ cross join by replication)."""

    _HOW_MAP = {
        "inner": "inner",
        "left": "left outer",
        "right": "right outer",
        "full": "full outer",
        "semi": "left semi",
        "anti": "left anti",
    }

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 how: str, condition: Optional[ir.Expression],
                 schema: Schema, key_dtypes: Optional[List] = None):
        super().__init__()
        self.children = (left, right)
        self.left_keys, self.right_keys = list(left_keys), list(right_keys)
        self.how = how
        self.condition = condition
        self._schema = schema
        self.key_dtypes = key_dtypes

    @property
    def schema(self) -> Schema:
        return self._schema

    def _exec_cross(self, lt: pa.Table, rt: pa.Table) -> pa.Table:
        li = np.repeat(np.arange(lt.num_rows), rt.num_rows)
        ri = np.tile(np.arange(rt.num_rows), lt.num_rows)
        left = lt.take(pa.array(li))
        right = rt.take(pa.array(ri))
        arrays = list(left.columns) + list(right.columns)
        return pa.Table.from_arrays(arrays, names=self._schema.names)

    def execute(self):
        def run():
            lt = _gather_single(self.children[0], self.children[0].schema)
            rt = _gather_single(self.children[1], self.children[1].schema)
            if self.how == "cross":
                out = self._exec_cross(lt, rt)
            else:
                # rename to positional names to avoid collisions; duplicate
                # right keys so they survive arrow's key coalescing
                ln = [f"__l{i}" for i in range(lt.num_columns)]
                rn = [f"__r{i}" for i in range(rt.num_columns)]
                lt2 = lt.rename_columns(ln)
                rt2 = rt.rename_columns(rn)
                lk = [f"__l{lt.column_names.index(k)}" for k in self.left_keys]
                rk = [f"__r{rt.column_names.index(k)}" for k in
                      self.right_keys]
                # promote mismatched numeric key pairs to the common type
                # (Spark's implicit cast before key comparison)
                if self.key_dtypes is not None:
                    lt2 = _cast_join_keys(lt2, lk, self.key_dtypes)
                    rt2 = _cast_join_keys(rt2, rk, self.key_dtypes)
                # Spark joins NaN==NaN and -0.0==0.0 (NormalizeFloatingNumbers);
                # arrow's join does not, so float keys join on canonical bits
                lt2, lk = _normalize_float_join_keys(lt2, lk)
                rt2, rk = _normalize_float_join_keys(rt2, rk)
                joined = lt2.join(
                    rt2, keys=lk, right_keys=rk,
                    join_type=self._HOW_MAP[self.how],
                    coalesce_keys=False, use_threads=False)
                if self.how in ("semi", "anti"):
                    out = pa.Table.from_arrays(
                        [joined.column(n) for n in ln],
                        names=self._schema.names)
                else:
                    out = pa.Table.from_arrays(
                        [joined.column(n) for n in ln + rn],
                        names=self._schema.names)
            if self.condition is not None:
                v = eval_cpu.evaluate(self.condition, out)
                out = out.filter(pa.array(v.data.astype(bool) & v.valid))
            yield out
        return [run()]


class CpuShuffledHashJoinExec(CpuJoinExec):
    """Equi-join planned with both sides exchanged on their keys
    (ShuffledHashJoinExec analog; SortMergeJoin is replaced by this,
    reference: shims/spark300/.../GpuSortMergeJoinExec.scala)."""


class CpuBroadcastHashJoinExec(CpuJoinExec):
    """Equi-join with one side small enough to broadcast (reference:
    GpuBroadcastHashJoinExec).  build_side in {"left", "right"}."""

    def __init__(self, *args, build_side: str = "right", **kwargs):
        super().__init__(*args, **kwargs)
        self.build_side = build_side


class CpuBroadcastNestedLoopJoinExec(CpuJoinExec):
    """Cross join (+ condition) with a broadcast side (reference:
    GpuBroadcastNestedLoopJoinExec.scala:311)."""

    def __init__(self, *args, build_side: str = "right", **kwargs):
        super().__init__(*args, **kwargs)
        self.build_side = build_side


class CpuCartesianProductExec(CpuJoinExec):
    """Partition-pairwise cross join (reference:
    GpuCartesianProductExec.scala:304)."""
