"""Shape-erased kernel ABI: the dispatch-boundary contract that bounds
the compile bill.

The TPC-DS-99 compile bill — 2,639 distinct (kernel, shape) programs
(PERF.md) — is breadth, not any single runaway kernel: jax.jit compiles
one program per (pytree structure, argument shapes/dtypes), and the
engine's DeviceBatch pytree leaks THREE kinds of query-specific detail
into that identity that never change a kernel's semantics:

  1. **Column names.**  ``DeviceBatch.tree_flatten`` carries the name
     tuple as treedef aux data, so two batches with identical layouts
     but different schemas trace two programs — even though every
     expression reads columns by ordinal (``BoundReference.ordinal``)
     and PR 4 already made kernel OUTPUT names positional.  The erased
     ABI extends that to inputs: batches are renamed to canonical
     positional ``_c0.._cn`` before dispatch and the exec restamps its
     real schema host-side after (the "positional dtype-class
     arguments" of the ABI).

  2. **Value-range hints.**  ``DeviceColumn.vbits`` rides the treedef
     in 7 buckets (8..56); the narrow fast paths it unlocks only branch
     on coarse thresholds (<=16 single-digit sorts, <=32 i32 gathers/
     segment sums, <64 packed radix fields), so the precise buckets buy
     nothing but program churn.  The ABI re-buckets hints to
     {16, 32, 56} at the dispatch boundary (a WEAKER bound is always
     sound — vbits is an upper bound on value magnitude).

  3. **Shape spread.**  Row capacities and string/list widths bucket to
     every power of two; the ABI quantizes both ladders to every
     ``2**stride``-th rung (default stride 2: capacities 16, 64, 256,
     1024, ... and widths 1, 4, 16, 64, ...).  Batches are BORN at tier
     capacities (``columnar.batch.bucket_rows`` delegates here), and
     ``pad_to_tier`` pads stragglers (hand-built batches, batches born
     under a different conf) host-side at dispatch — padding rows keep
     the batch contract (validity False, data zeroed) and ``num_rows``
     is untouched, so slicing back is the existing logical-length read
     every kernel already performs via ``row_mask()``.

Every tier value is a SUBSET of the legacy power-of-two ladder and
every bucketed hint is a weakening of a legacy bucket, so the erased
ABI introduces no shape class the kernels have not always handled —
it only collapses many classes into fewer.

Batched multi-column signatures: kernels that treat a batch purely as
a column container (pack/download-compact/concat in columnar/batch.py)
key on :func:`layout_key` — the positional (dtype, width, validity
layout) sequence — instead of the schema, so any two batches with the
same physical layout share one program regardless of column names.

Decimal note: the engine's dtype set has no decimal (GpuOverrides
parity — decimals fall back to CPU at planning); when decimal columns
land they are specified to ride the same integer-backed vbits buckets
(scale static in the expression signature, precision bucketed like
vbits), so the tier tables here are already their contract.

Configuration is process-wide, last session wins (the obs configure
idiom): ``kernel.abi.enabled`` master switch, ``kernel.abi.tierStride``
/ ``kernel.abi.widthStride`` for the two shape ladders,
``kernel.abi.bucketHints`` for hint re-bucketing.  This module is an
import leaf below columnar/batch (which imports it for the tier
ladders); it imports the batch types lazily inside functions.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

_enabled = True          # kernel.abi.enabled default
_tier_stride = 2         # capacity ladder: every 2**stride-th pow2 rung
_width_stride = 2        # string/list max_len ladder
_bucket_hints = True     # re-bucket vbits at the ABI boundary

# the ABI hint buckets: chosen so every narrow fast path keeps its
# branch — <=16 single-digit sort / direct-bin groups, <=32 i32
# gather + segment sums, <=56 packed radix fields under 64 bits
ABI_VBIT_BUCKETS = (16, 32, 56)

# canonical positional input names (PR 4 introduced the same scheme for
# kernel OUTPUT names; the erased ABI applies it to inputs too).  The
# prefix matches fused_stage.canonical_names so an erased batch fed
# through a chain of erased kernels is a fixed point.
_CANON = [f"_c{i}" for i in range(64)]


def configure(conf) -> None:
    """Session-init hook (api/session.py).  Last session wins."""
    global _enabled, _tier_stride, _width_stride, _bucket_hints
    from spark_rapids_tpu import config as cfg
    _enabled = bool(conf.get(cfg.KERNEL_ABI_ENABLED))
    _tier_stride = max(1, int(conf.get(cfg.KERNEL_ABI_TIER_STRIDE)))
    _width_stride = max(1, int(conf.get(cfg.KERNEL_ABI_WIDTH_STRIDE)))
    _bucket_hints = bool(conf.get(cfg.KERNEL_ABI_BUCKET_HINTS))


def is_enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# tier ladders (pure int math; see docs/kernels.md for the tier table)
# ---------------------------------------------------------------------------

def tier_rows(n: int, min_bucket: int = 16) -> int:
    """Smallest capacity tier >= max(n, min_bucket): power-of-two
    rungs restricted to every ``tierStride``-th step of ONE canonical
    ladder anchored at 1 (stride 2: 1, 4, 16, 64, 256, ...).  All
    tiers are powers of two, so the result is always a legacy-valid
    capacity.

    ``min_bucket`` is a FLOOR, not a ladder anchor: a caller-specific
    anchor (bucket_rows(n, 32)) would mint an offset ladder (32, 128,
    512, ...) that ``erase``'s canonical quantization never matches —
    every dispatch of every batch born there would pay a full-batch
    host pad.  Rounding the floor up to the canonical rung instead
    (32 -> 64) keeps all capacities on one ladder; returning a larger
    floor is always valid."""
    if not _enabled:
        cap = max(int(min_bucket), 1)
        n = max(int(n), 1)
        while cap < n:
            cap <<= 1
        return cap
    cap = 1
    lo = max(int(n), int(min_bucket), 1)
    step = 1 << _tier_stride
    while cap < lo:
        cap *= step
    return cap


def tier_strlen(n: int) -> int:
    """String/list width tier >= n (ladder 1, 4, 16, 64, ... under the
    default widthStride=2; legacy pow2 when the ABI is disabled)."""
    if n <= 0:
        return 1
    cap = 1
    step = 1 << (_width_stride if _enabled else 1)
    while cap < n:
        cap *= step
    return cap


def is_tier(cap: int, min_bucket: int = 16) -> bool:
    return cap == tier_rows(cap, min_bucket=min(min_bucket, cap))


def bucket_vbits(vb: Optional[int]) -> Optional[int]:
    """ABI hint bucket for a precise vbits value (weaker bound, always
    sound); identity when the ABI or hint bucketing is off."""
    if vb is None or not (_enabled and _bucket_hints):
        return vb
    for b in ABI_VBIT_BUCKETS:
        if vb <= b:
            return b
    return None


def canonical_input_names(n: int) -> List[str]:
    if n <= len(_CANON):
        return _CANON[:n]
    return _CANON + [f"_c{i}" for i in range(len(_CANON), n)]


# ---------------------------------------------------------------------------
# batch erasure at the dispatch boundary
# ---------------------------------------------------------------------------

def _erase_column(c, strip_hints: bool = False):
    """Hint-bucketed (or, for kernels that never read hints,
    hint-stripped) view of one column — shares every buffer."""
    from dataclasses import replace
    if strip_hints:
        if c.vbits is None and not c.nonnull:
            return c
        return replace(c, vbits=None, nonnull=False)
    vb = bucket_vbits(c.vbits)
    if vb == c.vbits:
        return c
    return replace(c, vbits=vb)


def _pad_column(c, cap: int, width: Optional[int]):
    """Pad one column's buffers to ``cap`` rows (and 2-D payloads to
    ``width``) with the batch contract's zeros/False — host-side eager
    ops, dispatched outside any jit trace."""
    import jax.numpy as jnp

    def pad(a, w=None):
        if a is None:
            return None
        grow_rows = cap - a.shape[0]
        grow_w = 0 if (w is None or a.ndim < 2) else w - a.shape[1]
        if grow_rows <= 0 and grow_w <= 0:
            return a
        spec = [(0, max(grow_rows, 0))] + \
            [(0, max(grow_w, 0))] * (a.ndim - 1)
        return jnp.pad(a, spec)

    from dataclasses import replace
    return replace(c, data=pad(c.data, width), validity=pad(c.validity),
                   lengths=pad(c.lengths),
                   elem_validity=pad(c.elem_validity, width))


def erase(batch, pad: bool = True, strip_hints: bool = False):
    """The shape-erased view of a batch for kernel dispatch: canonical
    positional names, ABI-bucketed hints, and (``pad=True``) capacity /
    var-len widths padded up to their tiers.  Shares the input's
    buffers whenever no padding is needed (the overwhelmingly common
    case — batches are born at tier shapes); ``num_rows`` (host int or
    traced scalar) passes through untouched, so the logical row count
    — the slice-back half of pad/slice — is exactly the ``row_mask()``
    contract every kernel already honors.

    Callers that rely on input names surviving the kernel (filter's
    compact keeps batch names) must restamp their real schema after
    dispatch; project/fused-stage already do.

    ``pad=False`` is for kernels whose HOST-side epilogue reads the
    original buffer shapes back (the pack/download path): names and
    hints erase, shapes stay.  ``strip_hints=True`` removes hints
    outright instead of bucketing them — only for kernels that never
    read vbits/nonnull (pack: pure buffer concatenation), where even a
    bucketed hint on the treedef would re-trace an identical
    program."""
    if not _enabled:
        return batch
    from spark_rapids_tpu.columnar.batch import DeviceBatch
    cols = [_erase_column(c, strip_hints) for c in batch.columns]
    if pad and cols:
        cap = tier_rows(batch.capacity, min_bucket=min(16, batch.capacity))
        widths = [tier_strlen(c.max_len) if c.dtype.has_lengths else None
                  for c in cols]
        if cap != batch.capacity or any(
                w is not None and w != c.max_len
                for w, c in zip(widths, cols)):
            cols = [_pad_column(c, cap, w)
                    for c, w in zip(cols, widths)]
    out = DeviceBatch.__new__(DeviceBatch)
    out.names = canonical_input_names(len(cols))
    out.columns = cols
    out.num_rows = batch.num_rows
    out._capacity = cols[0].capacity if cols else batch._capacity
    return out


def layout_key(batch) -> Tuple:
    """Positional physical-layout signature of a batch — the
    schema-erased replacement for ``DeviceBatch.schema_key()`` in
    kernel-cache keys of column-container kernels (pack, download
    compact, no-sync concat): per column (dtype, var-len width,
    has-elem-validity) plus the capacity.  No names — any two batches
    with this layout share one program."""
    return (batch._capacity,
            tuple((c.dtype.name,
                   c.max_len if c.dtype.has_lengths else 0,
                   c.elem_validity is not None)
                  for c in batch.columns))


def erased_key(batch) -> Any:
    """``layout_key`` under the ABI, the legacy named ``schema_key``
    otherwise (so flipping ``kernel.abi.enabled`` between sessions of
    one process cannot serve a kernel traced under the other ABI)."""
    if _enabled:
        return ("abi", layout_key(batch))
    return (batch.schema_key(),
            tuple(c.elem_validity is not None for c in batch.columns))
