"""Physical plan contracts.

Analog of ``trait GpuExec extends SparkPlan`` (reference: GpuExec.scala:58-102:
``supportsColumnar=true``, ``doExecuteColumnar(): RDD[ColumnarBatch]``, and the
batching contracts ``coalesceAfter``/``childrenCoalesceGoal``/``outputBatching``)
plus the CoalesceGoal machinery (reference: GpuCoalesceBatches.scala:94-130).

Execution model: ``execute()`` returns one Python iterator per partition.
CPU execs yield ``pyarrow.Table`` batches; TPU execs yield ``DeviceBatch``.
The planner guarantees the currencies never mix without an explicit
transition exec (HostToDeviceExec / DeviceToHostExec — the
GpuRowToColumnar/GpuColumnarToRow analogs).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.plan.logical import Schema


# ---------------------------------------------------------------------------
# Coalesce goals (reference: GpuCoalesceBatches.scala:94-130)
# ---------------------------------------------------------------------------

class CoalesceGoal:
    pass


@dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    bytes: int


class RequireSingleBatch(CoalesceGoal):
    """Operator needs its whole input in one batch (total sort, hash-join
    build side, final agg without partials; reference: GpuSortExec.scala:76)."""


REQUIRE_SINGLE_BATCH = RequireSingleBatch()


# ---------------------------------------------------------------------------
# Metrics (reference: GpuMetricNames, GpuExec.scala:27-56)
# ---------------------------------------------------------------------------

@dataclass
class Metrics:
    _rows_host: int = 0
    num_output_batches: int = 0
    total_time_ns: int = 0
    peak_dev_memory: int = 0
    extra: Dict[str, float] = field(default_factory=dict)
    _rows_pending: list = field(default_factory=list)
    _rows_lock: Any = field(default_factory=threading.Lock)

    def add_rows(self, nr) -> None:
        """Count output rows WITHOUT forcing a device sync: traced/device
        counts buffer and resolve lazily when the metric is read (a
        mid-pipeline int() would serialize the whole async pipeline —
        and on remote-device runtimes a single early read-back degrades
        every later dispatch).  Thread-safe: partition iterators of one
        exec run concurrently under the task pool."""
        with self._rows_lock:
            if isinstance(nr, int):
                self._rows_host += nr
            else:
                self._rows_pending.append(nr)

    def add_batches(self, n: int = 1) -> None:
        """Locked batch-count increment: partition iterators run
        concurrently under the task pool, so a bare ``+=`` loses counts
        to read-modify-write races."""
        with self._rows_lock:
            self.num_output_batches += n

    def add_extra(self, key: str, n: float) -> None:
        with self._rows_lock:
            self.extra[key] = self.extra.get(key, 0) + n

    @property
    def num_output_rows(self) -> int:
        with self._rows_lock:
            if self._rows_pending:
                self._rows_host += sum(int(x)
                                       for x in self._rows_pending)
                self._rows_pending.clear()
            return self._rows_host

    # plans ship to executor processes (shuffle/executor_proc.py); the
    # lock is process-local state and pending device scalars must be
    # resolved before crossing the boundary
    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_rows_lock", None)
        if d.get("_rows_pending"):
            d["_rows_host"] = self.num_output_rows
            d["_rows_pending"] = []
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._rows_lock = threading.Lock()
        if not hasattr(self, "_rows_pending"):
            self._rows_pending = []

    @num_output_rows.setter
    def num_output_rows(self, v) -> None:
        with self._rows_lock:
            self._rows_pending.clear()
            self._rows_host = int(v)


class PhysicalPlan:
    """Base physical node."""

    children: Tuple["PhysicalPlan", ...] = ()

    def __init__(self):
        self.metrics = Metrics()

    def __getstate__(self):
        # plan fragments ship to executor processes
        # (shuffle/executor_proc.py); jitted-kernel caches (any _kernel*
        # attribute) are process-local and must not travel
        d = dict(self.__dict__)
        for k, v in list(d.items()):
            if k.startswith("_") and "kernel" in k:
                d[k] = {} if isinstance(v, dict) else None
        return d

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def is_tpu(self) -> bool:
        return False

    def execute(self) -> List[Iterator[Any]]:
        """One iterator of batches per partition."""
        raise NotImplementedError

    # batching contracts -----------------------------------------------------
    def children_coalesce_goal(self) -> List[Optional[CoalesceGoal]]:
        return [None] * len(self.children)

    def output_batching(self) -> Optional[CoalesceGoal]:
        return None

    # display ---------------------------------------------------------------
    def simple_string(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [f"{pad}{'*' if self.is_tpu else ' '}{self.simple_string()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def foreach(self, fn) -> None:
        fn(self)
        for c in self.children:
            c.foreach(fn)


class TpuExec(PhysicalPlan):
    """Marker base for device-side execs (GpuExec analog)."""

    @property
    def is_tpu(self) -> bool:
        return True


def timed(metrics: Metrics):
    class _T:
        def __enter__(self):
            self.t0 = time.perf_counter_ns()
            return self

        def __exit__(self, *a):
            metrics.total_time_ns += time.perf_counter_ns() - self.t0
    return _T()


def timed_extra(metrics: Metrics, key: str):
    """Time a sub-phase into ``Metrics.extra[key]`` (seconds) WITHOUT
    touching total_time_ns — for phases that overlap the operator's
    main timing (scan host prep / upload running on a prefetch thread
    while the consumer's ``timed`` covers the dispatch)."""
    class _T:
        def __enter__(self):
            self.t0 = time.perf_counter_ns()
            return self

        def __exit__(self, *a):
            metrics.add_extra(
                key, (time.perf_counter_ns() - self.t0) / 1e9)
    return _T()
