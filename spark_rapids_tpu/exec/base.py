"""Physical plan contracts.

Analog of ``trait GpuExec extends SparkPlan`` (reference: GpuExec.scala:58-102:
``supportsColumnar=true``, ``doExecuteColumnar(): RDD[ColumnarBatch]``, and the
batching contracts ``coalesceAfter``/``childrenCoalesceGoal``/``outputBatching``)
plus the CoalesceGoal machinery (reference: GpuCoalesceBatches.scala:94-130).

Execution model: ``execute()`` returns one Python iterator per partition.
CPU execs yield ``pyarrow.Table`` batches; TPU execs yield ``DeviceBatch``.
The planner guarantees the currencies never mix without an explicit
transition exec (HostToDeviceExec / DeviceToHostExec — the
GpuRowToColumnar/GpuColumnarToRow analogs).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.obs import trace as _trace
from spark_rapids_tpu.plan.logical import Schema
from spark_rapids_tpu.sched import cancel as _cancel


# ---------------------------------------------------------------------------
# Coalesce goals (reference: GpuCoalesceBatches.scala:94-130)
# ---------------------------------------------------------------------------

class CoalesceGoal:
    pass


@dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    bytes: int


class RequireSingleBatch(CoalesceGoal):
    """Operator needs its whole input in one batch (total sort, hash-join
    build side, final agg without partials; reference: GpuSortExec.scala:76)."""


REQUIRE_SINGLE_BATCH = RequireSingleBatch()


# ---------------------------------------------------------------------------
# Metrics (reference: GpuMetricNames, GpuExec.scala:27-56)
#
# Unit contract: every time-valued metric is NANOSECONDS internally —
# ``total_time_ns`` and every ``extra`` key written by ``timed_extra``
# (keys end in "Time"/"Ns" by convention).  Seconds exist only at
# report time, via the explicit ``total_time_s`` / ``extra_s``
# conversions (and the QueryProfile's ``*_s`` rendering).
# ---------------------------------------------------------------------------

@dataclass
class Metrics:
    _rows_host: int = 0
    num_output_batches: int = 0
    total_time_ns: int = 0
    peak_dev_memory: int = 0
    extra: Dict[str, float] = field(default_factory=dict)
    _rows_pending: list = field(default_factory=list)
    _rows_lock: Any = field(default_factory=threading.Lock)

    def add_rows(self, nr) -> None:
        """Count output rows WITHOUT forcing a device sync: traced/device
        counts buffer and resolve lazily when the metric is read (a
        mid-pipeline int() would serialize the whole async pipeline —
        and on remote-device runtimes a single early read-back degrades
        every later dispatch).  Thread-safe: partition iterators of one
        exec run concurrently under the task pool."""
        with self._rows_lock:
            if isinstance(nr, int):
                self._rows_host += nr
            else:
                self._rows_pending.append(nr)

    def add_batches(self, n: int = 1) -> None:
        """Locked batch-count increment: partition iterators run
        concurrently under the task pool, so a bare ``+=`` loses counts
        to read-modify-write races."""
        with self._rows_lock:
            self.num_output_batches += n

    def add_extra(self, key: str, n: float) -> None:
        with self._rows_lock:
            self.extra[key] = self.extra.get(key, 0) + n

    def add_time_ns(self, ns: int) -> None:
        """Locked total_time_ns accumulation (partition iterators run
        concurrently under the task pool)."""
        with self._rows_lock:
            self.total_time_ns += ns

    def max_peak(self, v: int) -> None:
        """Locked high-water update of peak_dev_memory (concurrent
        executor-reply merges race an unlocked read-modify-write)."""
        with self._rows_lock:
            if v > self.peak_dev_memory:
                self.peak_dev_memory = v

    @property
    def total_time_s(self) -> float:
        """Report-time seconds conversion (ns internally)."""
        return self.total_time_ns / 1e9

    def extra_s(self, key: str) -> float:
        """Report-time seconds view of a time-valued ``extra`` entry
        (``timed_extra`` accumulates nanoseconds)."""
        return self.extra.get(key, 0) / 1e9

    @property
    def num_output_rows(self) -> int:
        with self._rows_lock:
            if self._rows_pending:
                self._rows_host += sum(int(x)
                                       for x in self._rows_pending)
                self._rows_pending.clear()
            return self._rows_host

    # plans ship to executor processes (shuffle/executor_proc.py); the
    # lock is process-local state and pending device scalars must be
    # resolved before crossing the boundary
    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_rows_lock", None)
        if d.get("_rows_pending"):
            d["_rows_host"] = self.num_output_rows
            d["_rows_pending"] = []
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._rows_lock = threading.Lock()
        if not hasattr(self, "_rows_pending"):
            self._rows_pending = []

    @num_output_rows.setter
    def num_output_rows(self, v) -> None:
        with self._rows_lock:
            self._rows_pending.clear()
            self._rows_host = int(v)


class PhysicalPlan:
    """Base physical node."""

    children: Tuple["PhysicalPlan", ...] = ()

    def __init__(self):
        self.metrics = Metrics()

    def __getstate__(self):
        # plan fragments ship to executor processes
        # (shuffle/executor_proc.py); jitted-kernel caches (any _kernel*
        # attribute) are process-local and must not travel
        d = dict(self.__dict__)
        for k, v in list(d.items()):
            if k.startswith("_") and "kernel" in k:
                d[k] = {} if isinstance(v, dict) else None
        return d

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def is_tpu(self) -> bool:
        return False

    def execute(self) -> List[Iterator[Any]]:
        """One iterator of batches per partition."""
        raise NotImplementedError

    # batching contracts -----------------------------------------------------
    def children_coalesce_goal(self) -> List[Optional[CoalesceGoal]]:
        return [None] * len(self.children)

    def output_batching(self) -> Optional[CoalesceGoal]:
        return None

    # display ---------------------------------------------------------------
    def simple_string(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [f"{pad}{'*' if self.is_tpu else ' '}{self.simple_string()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def foreach(self, fn) -> None:
        fn(self)
        for c in self.children:
            c.foreach(fn)


class TpuExec(PhysicalPlan):
    """Marker base for device-side execs (GpuExec analog)."""

    @property
    def is_tpu(self) -> bool:
        return True


class _Timed:
    """Accumulates elapsed ns into ``metrics.total_time_ns`` and, when
    tracing is enabled and a span name was given, records the interval
    as a span (obs/trace.py; the disabled path costs one bool check).

    Entry doubles as the engine's per-batch cooperative cancellation
    checkpoint: every exec's batch loop opens ``timed`` around its
    device work, so a fired CancelToken (sched/cancel.py) unwinds the
    query here at batch granularity — one thread-local read + one bool
    check when no cancellation is pending."""

    __slots__ = ("metrics", "name", "t0")

    def __init__(self, metrics: Metrics, name: Optional[str]):
        self.metrics = metrics
        self.name = name

    def __enter__(self):
        _cancel.check_current()
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        dur = time.perf_counter_ns() - self.t0
        self.metrics.add_time_ns(dur)
        if self.name is not None:
            _trace.record(self.name, self.t0, dur)


def timed(metrics: Metrics, name: Optional[str] = None):
    return _Timed(metrics, name)


class _TimedExtra:
    __slots__ = ("metrics", "key", "t0")

    def __init__(self, metrics: Metrics, key: str):
        self.metrics = metrics
        self.key = key

    def __enter__(self):
        _cancel.check_current()   # prefetch-thread batch checkpoint
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        dur = time.perf_counter_ns() - self.t0
        self.metrics.add_extra(self.key, dur)
        _trace.record(self.key, self.t0, dur)


def timed_extra(metrics: Metrics, key: str):
    """Time a sub-phase into ``Metrics.extra[key]`` (NANOSECONDS; read
    back in seconds via ``Metrics.extra_s``) WITHOUT touching
    total_time_ns — for phases that overlap the operator's main timing
    (scan host prep / upload running on a prefetch thread while the
    consumer's ``timed`` covers the dispatch).  Also recorded as a span
    named ``key`` when tracing is enabled."""
    return _TimedExtra(metrics, key)


# ---------------------------------------------------------------------------
# Executor-side metrics round trip (shuffle/executor_proc.py ships plan
# fragments whose Metrics would otherwise never return to the driver)
# ---------------------------------------------------------------------------

def collect_plan_metrics(plan: PhysicalPlan) -> List[dict]:
    """Flatten a plan tree's Metrics in pre-order (``foreach`` order).
    The pre-order index IS the plan node id: the driver's tree and the
    executor's unpickled copy share the structure, so index + class
    name key the merge."""
    out: List[dict] = []

    def one(n: PhysicalPlan) -> None:
        m = n.metrics
        out.append({
            "name": type(n).__name__,
            "rows": int(m.num_output_rows),
            "batches": int(m.num_output_batches),
            "time_ns": int(m.total_time_ns),
            "peak_dev_memory": int(m.peak_dev_memory),
            "extra": {k: v for k, v in m.extra.items()
                      if isinstance(v, (int, float))},
        })
    plan.foreach(one)
    return out


def merge_plan_metrics(plan: PhysicalPlan,
                       recorded: Optional[List[dict]],
                       skip_root: bool = False) -> None:
    """Merge executor-side metrics back into the driver-side tree
    (keyed by pre-order node id + class name; a shape mismatch drops
    the payload rather than corrupting driver metrics).  Additive, so
    every executor's share of a map stage accumulates.

    ``skip_root``: leave the root node untouched — the process-shuffle
    driver already times the whole map stage on its own exchange node,
    so merging the executor copy's exchange-node time on top would
    double-count the same work."""
    if not recorded:
        return
    nodes: List[PhysicalPlan] = []
    plan.foreach(nodes.append)
    if len(nodes) != len(recorded):
        return
    for i, (n, r) in enumerate(zip(nodes, recorded)):
        if (skip_root and i == 0) or r.get("name") != type(n).__name__:
            continue
        m = n.metrics
        if r.get("rows"):
            m.add_rows(int(r["rows"]))
        if r.get("batches"):
            m.add_batches(int(r["batches"]))
        if r.get("time_ns"):
            m.add_time_ns(int(r["time_ns"]))
        if r.get("peak_dev_memory"):
            m.max_peak(int(r["peak_dev_memory"]))
        for k, v in (r.get("extra") or {}).items():
            m.add_extra(k, v)
