"""Process-wide compiled-kernel cache.

The engine plans a FRESH exec tree for every ``collect()`` (the reference
does too — Spark re-plans each action), so per-instance ``jax.jit``
handles would recompile identical kernels on every query.  This cache
keys jitted callables on a canonical (operator, expression-tree,
parameter) signature so the XLA compile cost is paid once per
(operator, schema, batch-bucket) per process — the compile-cache
contract of SURVEY.md §7 ("XLA computations compiled per (operator,
schema, batch-bucket)").

jax.jit itself re-traces per input shape bucket under one cached handle,
so batch capacity does not belong in the key.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable

import jax

from spark_rapids_tpu.expr import ir

_MAX_ENTRIES = 1024
_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_LOCK = threading.Lock()
# objects keyed by identity in _value_sig; pinned so CPython can't hand
# their address to a different value while a cache key references it
_ID_PINNED: dict = {}


# output-name attributes cannot change a compiled program: a
# BoundReference reads by ordinal and an Alias only labels its child,
# so identical projections under different aliases must share one
# compile (kernels that DO emit names either take them from the input
# batch at runtime or carry an explicit name tuple in their cache key)
_NAME_ATTRS = ("ref_name", "alias", "attr_name")


def expr_sig(e) -> Any:
    """Canonical hashable signature of an expression tree (class, dtype,
    scalar params, children) — the kernel-cache key component for any
    closed-over expression.  Canonical: ordinals and dtypes only, never
    column/alias names."""
    if e is None:
        return None
    if isinstance(e, ir.Expression):
        parts = [type(e).__name__,
                 e.dtype.name if e.dtype is not None else "?",
                 bool(e.nullable)]
        for k in sorted(e.__dict__):
            if k in ("children", "dtype", "nullable") or k in _NAME_ATTRS:
                continue
            parts.append((k, _value_sig(e.__dict__[k])))
        parts.append(tuple(expr_sig(c) for c in e.children))
        return tuple(parts)
    return _value_sig(e)


def _value_sig(v) -> Any:
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_value_sig(x) for x in v)
    if isinstance(v, ir.Expression):
        return expr_sig(v)
    import numpy as _np
    if isinstance(v, _np.ndarray):
        # repr() truncates large arrays ('...') so two big IN-lists could
        # collide; hash the full buffer instead.
        import hashlib
        return ("ndarray", str(v.dtype), v.shape,
                hashlib.sha1(_np.ascontiguousarray(v).tobytes())
                .hexdigest())
    if hasattr(v, "name") and not callable(v):  # DType-like
        return getattr(v, "name")
    if callable(v):
        # UDF payloads etc. — unique per object, no cross-instance reuse
        return ("callable", id(v))
    d = getattr(v, "__dict__", None)
    if d is not None:  # WindowFrame / SortOrder-like value objects
        return (type(v).__name__,) + tuple(
            (k, _value_sig(x)) for k, x in sorted(d.items()))
    # unknown opaque object: content hash when picklable; identity as a
    # last resort — with the object PINNED so its address can't be
    # recycled into a different value aliasing this cache key
    try:
        import hashlib
        import pickle
        return ("pickle", type(v).__name__,
                hashlib.sha1(pickle.dumps(v)).hexdigest())
    except Exception:
        _ID_PINNED.setdefault(id(v), v)
        return ("id", type(v).__name__, id(v))


def exprs_sig(exprs) -> Any:
    return tuple(expr_sig(e) for e in exprs)


# -- compile-bill instrumentation (PERF.md "compile bill") ------------------
# When SRT_COMPILE_LOG is set, every kernel call whose (key, arg-shape)
# combination is new is timed and recorded — jax.jit compiles lazily per
# shape bucket, so the first call's wall is trace+compile (+ one async
# dispatch, negligible on the tunneled runtime).  dump_compile_log()
# returns [(kernel key repr, shape sig repr, seconds)].
import os as _os
import time as _time

COMPILE_LOG_ENABLED = bool(_os.environ.get("SRT_COMPILE_LOG"))
_COMPILE_LOG: list = []


def _shape_sig(args, kwargs):
    # the treedef rides the signature as the OBJECT (hashable, eq by
    # structure) — repr'ing it per dispatch would dominate the
    # always-on compile observatory's per-call cost
    def leaf_sig(x):
        shp = getattr(x, "shape", None)
        dty = getattr(x, "dtype", None)
        return (tuple(shp), str(dty)) if shp is not None else repr(x)[:32]
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef, tuple(leaf_sig(x) for x in leaves))


class _ShapeSeen:
    """Per-wrapper first-call-per-shape detector (jax.jit retraces per
    ``_shape_sig`` bucket) — the ONE implementation shared by all
    kernel-call wrappers so their notion of "first call" cannot drift.
    Two protocols, chosen by what the wrapper's semantics require:
    ``claim`` marks-and-returns-first atomically (recording wrappers —
    fire at most once per shape even under races); ``peek``/``mark``
    split the check from the commit for guards whose SAFETY depends on
    a shape not counting as warm until it was actually handled
    (_no_persistent_cache)."""

    def __init__(self):
        self._seen = set()
        self._lock = threading.Lock()

    def claim(self, sig) -> bool:
        """True exactly once per sig (atomic check-and-mark)."""
        with self._lock:
            if sig in self._seen:
                return False
            self._seen.add(sig)
            return True

    def peek(self, sig) -> bool:
        with self._lock:
            return sig in self._seen

    def mark(self, sig) -> None:
        with self._lock:
            self._seen.add(sig)


def _instrument(key, fn):
    seen = _ShapeSeen()

    def wrapped(*args, **kwargs):
        sig = _shape_sig(args, kwargs)
        if not seen.claim(sig):
            return fn(*args, **kwargs)
        t0 = _time.perf_counter()
        out = fn(*args, **kwargs)
        dt_ = _time.perf_counter() - t0
        with _LOCK:
            _COMPILE_LOG.append((repr(key)[:160], repr(sig[1])[:120],
                                 dt_))
        return out
    return wrapped


def dump_compile_log() -> list:
    with _LOCK:
        return list(_COMPILE_LOG)


def _replay_payload(inner: Callable, jit_kwargs: dict,
                    args, kwargs) -> "str | None":
    """Pickle (traceable, jit kwargs, abstract argument shapes) into a
    base64 replay payload for the precompile corpus — everything the
    AOT precompile service (sched/precompile.py) needs to re-lower and
    re-compile this exact program in a fresh process, with no data, no
    plan, no session.  Arguments map to ``jax.ShapeDtypeStruct`` leaves
    (static kwargs — ints routed through ``static_argnames`` — stay
    concrete).  Traceables are usually picklable (module functions, or
    ``functools.partial`` over a class method + an expression-holding
    shim — the same things the executor protocol already ships); ones
    that are not return None and the program is recorded without a
    payload (counted as skipped at replay time)."""
    import base64
    import pickle
    import zlib

    def to_sds(x):
        shp = getattr(x, "shape", None)
        dty = getattr(x, "dtype", None)
        if shp is None or dty is None:
            return x
        return jax.ShapeDtypeStruct(tuple(shp), dty)
    try:
        sds = jax.tree_util.tree_map(to_sds, (args, kwargs))
        raw = pickle.dumps({"fn": inner, "jit": jit_kwargs,
                            "args": sds[0], "kwargs": sds[1]},
                           protocol=pickle.HIGHEST_PROTOCOL)
        if len(raw) > (2 << 20):
            return None          # pathological payload: skip, don't bloat
        return base64.b64encode(zlib.compress(raw, 6)).decode("ascii")
    except Exception:
        return None


def load_replay_payload(payload: str):
    """Inverse of :func:`_replay_payload` (the precompile service's
    decode half; lives here so the pickle format has one owner)."""
    import base64
    import pickle
    import zlib
    return pickle.loads(zlib.decompress(base64.b64decode(payload)))


def _observe_compiles(key: Any, fn: Callable, backend: str = None,
                      replay_src=None) -> Callable:
    """Compile-observatory wrapper (obs/compile.py): the first call of
    each (key, arg-shape) program is where jax.jit traces + compiles
    (or reloads from the persistent XLA cache), so that call is timed
    and recorded as a CompileEvent with its cache tier, backend, and
    the triggering query's id + plan digest.  Wraps the jitted callable
    DIRECTLY (inside the OOM/dispatch-counter wrappers) so the measured
    wall is the compile, not the counters; an OOM-retry replay of the
    same shape is by definition not a first call and never re-records.

    Installed only when the observatory is enabled at BUILD time
    (get_kernel): a disabled process pays nothing at all.  Once
    installed, the wrapper tracks first calls even through a
    mid-process disable (``record_compile`` itself no-ops then) — so a
    later re-enable cannot misreport an already-compiled shape's next
    dispatch as a microsecond 'fresh compile'.  Kernels BUILT while
    disabled stay unobserved for their lifetime."""
    from spark_rapids_tpu.obs import compile as obscompile
    fam = _family(key)
    bk = backend or ("pallas" if "pallas" in str(key) else "xla")
    seen = _ShapeSeen()

    def wrapped(*args, **kwargs):
        sig = _shape_sig(args, kwargs)
        if not seen.claim(sig):
            return fn(*args, **kwargs)
        probe = obscompile.probe_begin()
        t0 = _time.perf_counter_ns()
        try:
            return fn(*args, **kwargs)
        finally:
            # record in finally: a first call that compiles and THEN
            # raises (HBM OOM mid-execution) still paid the compile —
            # the OOM-retry replay is warm and would never re-record,
            # so skipping here would lose the event entirely
            dur = _time.perf_counter_ns() - t0
            replay = None
            if replay_src is not None and obscompile.corpus_path() \
                    and obscompile.corpus_replay_enabled():
                replay = _replay_payload(replay_src[0], replay_src[1],
                                         args, kwargs)
            obscompile.record_compile(
                key=key, family=fam, backend=bk, leaves=sig[1],
                t0_ns=t0, dur_ns=dur,
                tier=obscompile.classify_tier(probe),
                replay=replay)
            if COMPILE_LOG_ENABLED:
                # the legacy SRT_COMPILE_LOG ledger shares this
                # wrapper's first-call detection (one _shape_sig per
                # dispatch, not two); _instrument only installs for
                # kernels built while the observatory is disabled
                with _LOCK:
                    _COMPILE_LOG.append((repr(key)[:160],
                                         repr(sig[1])[:120],
                                         dur / 1e9))
    return wrapped


# serializes persistent-cache flips across threads: the flip window is
# process-global jax config, so donating compiles take turns
_PC_FLIP_LOCK = threading.Lock()
_no_persist_noted = False


def _no_persistent_cache(fn):
    """Compile wrapper for kernels BARRED from the persistent XLA
    compilation cache — donating kernels, on jax 0.4.37: an executable
    RELOADED from the persistent cache mis-applies the donate_argnums
    aliasing table (same-shaped outputs read the WRONG donated input
    buffer; minimal repro pinned by
    tests/test_fusion.test_donation_persistent_cache_repro).  Fresh
    compiles are always correct, so the durable workaround is to keep
    such programs out of the cache entirely — never written, never
    reloadable — by compiling their first (shape) call inside a window
    where the cache dir is unset and the latched cache object is reset
    (jax consults the dir at cache-init, not per compile; flipping the
    enable flag alone does not stop writes — probed on 0.4.37).

    The window is serialized by a process lock; a concurrent compile of
    a NON-donating kernel on another thread during the window loses
    persistence for that one program (correctness unaffected — it
    simply compiles fresh again next process).  Steady state therefore
    gets donation AND warm compiles: every non-donating program warms
    from the persistent cache, donating programs pay one fresh compile
    per process, bounded by the (small) donating-kernel inventory.

    A shape is marked warm only AFTER its guarded call returns: a
    pre-marked shape would let (a) a concurrent first dispatch of the
    same shape, or (b) the retry after a guarded call that raised
    (HBM OOM), take the unguarded fast path while the program is still
    uncompiled — compiling it with the cache armed and WRITING the
    donating executable into the cache this guard exists to keep it
    out of.  Concurrent first callers instead serialize on the flip
    lock; by the time the loser's call runs, jax's in-memory cache is
    warm and no compile (hence no write) happens."""
    seen = _ShapeSeen()

    def run(*args, **kwargs):
        sig = _shape_sig(args, kwargs)
        if seen.peek(sig):
            return fn(*args, **kwargs)
        global _no_persist_noted
        if not _no_persist_noted:
            _no_persist_noted = True
            import logging
            logging.getLogger("spark_rapids_tpu.fusion").info(
                "donating kernels compile outside the persistent XLA "
                "cache (jax 0.4.37 reload mis-applies donate_argnums "
                "aliasing — see exec/kernel_cache._no_persistent_cache)")
        from spark_rapids_tpu.obs import registry as _obsreg
        from jax._src import compilation_cache as _cc
        with _PC_FLIP_LOCK:
            prev = None
            try:
                prev = jax.config.jax_compilation_cache_dir
            except Exception:
                pass
            if prev:
                jax.config.update("jax_compilation_cache_dir", None)
                _cc.reset_cache()
            try:
                out = fn(*args, **kwargs)
            finally:
                if prev:
                    jax.config.update("jax_compilation_cache_dir", prev)
                    _cc.reset_cache()
                _obsreg.get_registry().inc(
                    "kernel.cache.noPersistCompiles")
        seen.mark(sig)
        return out
    return run


def _with_oom_recovery(fn):
    """Retry a kernel dispatch once after an HBM-exhaustion error, with
    the spill catalog's synchronous device-tier eviction in between (the
    RMM onAllocFailure retry loop, DeviceMemoryEventHandler.scala:42-70,
    restructured for an allocator the engine doesn't own)."""
    def run(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            from spark_rapids_tpu.mem import spill as _spill
            if not _spill.hbm_oom_recover(e):
                raise
            return fn(*args, **kwargs)
    return run


def _family(key: Any) -> str:
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        return key[0]
    return "other"


def _count_dispatches(key: Any, fn: Callable,
                      backend: str = None) -> Callable:
    """Per-call registry counters: ``kernel.dispatches`` is the ground
    truth the fusion layer's dispatch-reduction claims are measured
    against (bench.py / tests assert the fused-vs-unfused delta on it;
    one lock bump per ~72 ms dispatch is noise).

    Backend-aware call sites additionally tag the family counter with
    the backend this executable was BUILT under
    (``kernel.dispatches.<family>.<pallas|xla>``).  Note the exact
    semantics: a ``.pallas``-tagged dispatch ran an executable built
    with the pallas backend REQUESTED — individual reductions inside
    it may still have fallen back per kernel; read it together with
    the selection counters ``kernel.backend.pallas.hits/.fallbacks``
    (kernels/backend.py) to see whether pallas kernels actually
    engaged inside."""
    from spark_rapids_tpu.obs import accounting as _acct
    from spark_rapids_tpu.obs import registry as _obsreg
    fam = _family(key)
    pairs = [("kernel.dispatches", 1), (f"kernel.dispatches.{fam}", 1)]
    if backend:
        pairs.append((f"kernel.dispatches.{fam}.{backend}", 1))
    pairs = tuple(pairs)

    def wrapped(*args, **kwargs):
        _obsreg.get_registry().inc_many(*pairs)
        # ledger: every dispatch bills the owning tenant with the SAME
        # n as the global counter — the CI exactness gate's invariant
        _acct.charge("kernel.dispatches", 1)
        return fn(*args, **kwargs)
    return wrapped


def get_kernel(key: Any, builder: Callable[[], Callable],
               oom_retry: bool = True, backend: str = None,
               persistent_cache: bool = True,
               **jit_kwargs) -> Callable:
    """Return the cached jitted kernel for ``key``, building+jitting via
    ``builder`` on first use (LRU-bounded).

    ``oom_retry=False`` skips the HBM-OOM retry wrapper — required when
    the kernel donates input buffers (a retry would replay arguments
    the failed dispatch may already have consumed).  Call sites that
    donate must fold the donation into ``key``: the same signature
    jitted with and without ``donate_argnums`` is two executables.

    ``backend`` tags this kernel's per-dispatch family counter with the
    kernel backend ('pallas'/'xla') at backend-aware call sites; the
    backend must already be folded into ``key`` by the caller (two
    backends are two executables).

    Cache-tier counters (the compile-observatory split): an in-memory
    hit here bumps ``kernel.cache.memHits`` (``kernel.cache.hits`` is
    its documented legacy alias, key granularity); a miss invokes the
    builder (``kernel.cache.misses``, distinct KEYS built), after which
    each first (key, shape) call classifies as ``kernel.cache.compiles``
    (fresh XLA compile) or ``kernel.cache.persistentHits`` (persistent-
    cache reload) via obs/compile.py — note the granularity: one key
    can lazily compile several shape-bucket programs, so misses is not
    the sum of the two program-tier counters.

    ``persistent_cache=False`` bars this kernel's programs from the
    persistent XLA compilation cache (see ``_no_persistent_cache``) —
    required for donating kernels on jax 0.4.37, where reloaded
    executables mis-apply the donation aliasing table.  Such programs
    also record no precompile replay payload: an AOT replay would
    re-write them into the cache the guard exists to keep them out
    of."""
    from spark_rapids_tpu.obs import registry as _obsreg
    fam = _family(key)
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _CACHE.move_to_end(key)
            _obsreg.get_registry().inc_many(
                ("kernel.cache.hits", 1),
                (f"kernel.cache.hits.{fam}", 1),
                ("kernel.cache.memHits", 1))
            return fn
    _obsreg.get_registry().inc_many(
        ("kernel.cache.misses", 1), (f"kernel.cache.misses.{fam}", 1))
    inner = builder()
    fn = jax.jit(inner, **jit_kwargs)
    if not persistent_cache:
        fn = _no_persistent_cache(fn)
    from spark_rapids_tpu.obs import compile as _obscompile
    observed = _obscompile.is_enabled()
    if observed:
        fn = _observe_compiles(
            key, fn, backend,
            replay_src=(inner, jit_kwargs) if persistent_cache
            else None)
    if oom_retry:
        fn = _with_oom_recovery(fn)
    fn = _count_dispatches(key, fn, backend)
    if COMPILE_LOG_ENABLED and not observed:
        # legacy SRT_COMPILE_LOG path for observatory-disabled builds;
        # observed kernels feed _COMPILE_LOG from _observe_compiles
        fn = _instrument(key, fn)
    with _LOCK:
        cur = _CACHE.setdefault(key, fn)
        if len(_CACHE) > _MAX_ENTRIES:
            _CACHE.popitem(last=False)
    return cur


# -- tile-plan memo (kernels/tiling.py) -------------------------------------
# Grid shapes of the streaming Pallas tiler are pure functions of
# (kernel family, buffer shapes, tileBytes, block caps) but computing
# one walks the pow2 ladders and reads config — per-dispatch host cost
# the hot path should not re-pay.  Plans memoize here, alongside the
# kernels they shape, with their own hit/miss counters
# (kernel.tilePlan.hits/misses).  Bounded like _CACHE; a plan is a tiny
# frozen dataclass so the bound is about key hygiene, not memory.
_TILE_PLANS: "OrderedDict[Any, Any]" = OrderedDict()


def tile_plan(key: Any, builder: Callable[[], Any]) -> Any:
    """Return the memoized tile plan for ``key``, computing it via
    ``builder`` on first use.  ``key`` must capture everything the plan
    depends on (family, shapes, block caps, tileBytes, interpret) —
    kernels/tiling.py owns that contract."""
    from spark_rapids_tpu.obs import registry as _obsreg
    with _LOCK:
        plan = _TILE_PLANS.get(key)
        if plan is not None:
            _TILE_PLANS.move_to_end(key)
            _obsreg.get_registry().inc("kernel.tilePlan.hits")
            return plan
    _obsreg.get_registry().inc("kernel.tilePlan.misses")
    plan = builder()
    with _LOCK:
        cur = _TILE_PLANS.setdefault(key, plan)
        if len(_TILE_PLANS) > _MAX_ENTRIES:
            _TILE_PLANS.popitem(last=False)
    return cur


def clear() -> None:
    _CACHE.clear()
    _TILE_PLANS.clear()
    _ID_PINNED.clear()


def clear_compile_state() -> None:
    """Drop every cached executable (this cache + jax's internal ones)
    so their memory mappings release; the persistent compile cache
    makes re-loading cheap."""
    import gc

    import jax
    clear()
    jax.clear_caches()
    gc.collect()


_maps_calls = 0
_maps_guard_disabled = False


def _count_maps() -> int:
    with open("/proc/self/maps", "rb") as f:
        return f.read().count(b"\n")


def maybe_clear_for_map_pressure(threshold: int = 40000,
                                 every: int = 16,
                                 force_check: bool = False) -> bool:
    """Executor-longevity guard: every loaded XLA executable costs
    memory mappings, and a long-lived process compiling many queries
    would hit ``vm.max_map_count`` (65530) and SIGSEGV — round 2's
    reproducible suite-killer.  Samples /proc/self/maps every ``every``
    calls (the scan itself costs ~ms) and clears cached executables
    past ``threshold``; if clearing doesn't actually reduce the count
    (mappings owned by something else), the guard disables itself
    instead of thrashing recompiles.  (The reference gets this bound
    for free from the JVM's code-cache management.)"""
    global _maps_calls, _maps_guard_disabled
    if _maps_guard_disabled:
        return False
    _maps_calls += 1
    if not force_check and _maps_calls % every:
        return False
    try:
        n = _count_maps()
    except OSError:
        _maps_guard_disabled = True
        return False
    if n <= threshold:
        return False
    clear_compile_state()
    try:
        if _count_maps() > 0.9 * threshold:
            _maps_guard_disabled = True
    except OSError:
        _maps_guard_disabled = True
    return True
