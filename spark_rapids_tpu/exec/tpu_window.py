"""TPU window exec.

Analog of ``GpuWindowExec``/``GpuWindowExpression`` (reference:
GpuWindowExec.scala:92, GpuWindowExpression.scala:171-834 — cudf
``groupBy.aggregateWindows`` for row frames and
``aggregateWindowsOverTimeRanges`` for range frames; fns:
count/sum/min/max/row_number/lead/lag).

TPU formulation: one total-order lexsort by (partition keys, order keys)
turns every window primitive into segment arithmetic over sorted rows —
partition/peer boundaries from key-change detection, ranking functions
from positions, frame aggregates from prefix sums (sum/count/avg over
arbitrary row frames via prefix differences), segmented associative
scans (running min/max), and a log-stride sparse table for bounded-start
min/max frames (O(1) per row: min/max is idempotent, so two overlapping
power-of-two blocks cover any range exactly — the cudf rolling-window
analog of GpuWindowExpression.scala:233-269 `aggregateWindows`).  This
is the "segmented scan kernels" plan of SURVEY.md §2d.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn, \
    concat_batches
from spark_rapids_tpu.exec import scans, sortkeys
from spark_rapids_tpu.exec.base import (PhysicalPlan, REQUIRE_SINGLE_BATCH,
                                        TpuExec, timed)
from spark_rapids_tpu.exec.tpu_aggregate import normalize_key
from spark_rapids_tpu.expr import eval_tpu, ir
from spark_rapids_tpu.expr.eval_tpu import ColVal
from spark_rapids_tpu.plan.logical import Schema


def _seg_scan(op, x, seg, identity):
    """Segmented inclusive scan over partition ids.

    Delegates to exec/scans.seg_scan (boundary-flag formulation) whose
    capacity-blocked form keeps wide (8-byte) dtypes compilable at any
    size — a full-capacity ``lax.associative_scan`` over i64/f64 is a
    minutes-scale XLA compile at 4M (PERF.md)."""
    flags = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                             seg[1:] != seg[:-1]])
    return scans.seg_scan(op, flags, x, identity)


def _boundaries_to_seg(new_flag: jnp.ndarray) -> jnp.ndarray:
    return jnp.cumsum(new_flag.astype(jnp.int32)) - 1


def _win_fields(v, asc, nf):
    # null field dropped only on the propagated no-null hint (schema
    # nullability alone is metadata and can be stale)
    return sortkeys.encode_fields(v, asc, nf, nullable=not v.nonnull)


class _WinCtx:
    """Sorted-space context for one (partition, order) spec."""

    def __init__(self, batch: DeviceBatch,
                 part_exprs, order_exprs, order_dirs, order=None):
        cap = batch.capacity
        self.cap = cap
        row_mask = batch.row_mask()
        pvals = [normalize_key(eval_tpu.evaluate(e, batch))
                 for e in part_exprs]
        ovals = [normalize_key(eval_tpu.evaluate(e, batch))
                 for e in order_exprs]
        pfields = [_win_fields(v, True, True) for v in pvals]
        ofields = [_win_fields(v, asc, nf)
                   for v, (asc, nf) in zip(ovals, order_dirs)]
        full_digits = sortkeys.stack_sort_digits(pfields + ofields,
                                                 row_mask)
        # the sort order is normally computed OUTSIDE this (jitted)
        # kernel via sortkeys.shared_digit_sort — embedding the sort
        # here would recompile a minutes-scale XLA sort per window spec
        self.order = order if order is not None else \
            sortkeys._digit_sort_impl(full_digits)
        base = jnp.zeros((cap,), jnp.bool_).at[0].set(True)
        sorted_mask = jnp.take(row_mask, self.order)
        mask_edge = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_),
             sorted_mask[1:] != sorted_mask[:-1]])
        if pfields:
            pdigits = sortkeys.fields_to_digits(
                [f for g in pfields for f in g])
            new_part = sortkeys.digit_boundaries(pdigits, self.order,
                                                 row_mask)
        else:
            new_part = base | mask_edge
        new_peer = sortkeys.digit_boundaries(full_digits, self.order,
                                             row_mask)
        self.part_seg = _boundaries_to_seg(new_part)
        self.peer_seg = _boundaries_to_seg(new_peer)
        self.new_peer = new_peer
        # i32 positions: i64 segment min/max scatters cost ~14x under
        # the pair emulation (PERF.md)
        pos = jnp.arange(cap, dtype=jnp.int32)
        self.pos = pos
        self.part_start = jnp.take(
            jax.ops.segment_min(pos, self.part_seg, num_segments=cap),
            self.part_seg)
        self.part_end = jnp.take(
            jax.ops.segment_max(pos, self.part_seg, num_segments=cap),
            self.part_seg)
        self.peer_start = jnp.take(
            jax.ops.segment_min(pos, self.peer_seg, num_segments=cap),
            self.peer_seg)
        self.peer_end = jnp.take(
            jax.ops.segment_max(pos, self.peer_seg, num_segments=cap),
            self.peer_seg)
        self.sorted_exists = jnp.take(row_mask, self.order)
        # finite RANGE frames need the (single) order value in sorted
        # space plus its direction/null placement
        self.order_dirs = tuple(order_dirs)
        self.order_vals = ovals

    def sorted_val(self, v: ColVal) -> ColVal:
        c = v.to_column().gather(self.order, self.sorted_exists)
        return ColVal(c.dtype, c.data, c.validity, c.lengths,
                      vbits=c.vbits)


def _seg_searchsorted(vals: jnp.ndarray, lo0: jnp.ndarray,
                      hi0: jnp.ndarray, target: jnp.ndarray,
                      left: bool) -> jnp.ndarray:
    """Vectorized per-row binary search of ``target`` within the sorted
    slice [lo0, hi0] of ``vals`` (inclusive positions).  Returns the
    insertion point (bisect_left/bisect_right semantics)."""
    cap = vals.shape[0]
    lo = lo0.astype(jnp.int64)
    hi = hi0.astype(jnp.int64) + 1
    steps = int(np.ceil(np.log2(cap + 1))) + 1

    def body(_, lh):
        lo, hi = lh
        active = lo < hi
        mid = (lo + hi) // 2
        v = jnp.take(vals, jnp.clip(mid, 0, cap - 1))
        go_right = (v < target) if left else (v <= target)
        new_lo = jnp.where(go_right, mid + 1, lo)
        new_hi = jnp.where(go_right, hi, mid)
        return (jnp.where(active, new_lo, lo),
                jnp.where(active, new_hi, hi))

    lo, hi = lax.fori_loop(0, steps, body, (lo, hi))
    return lo


def _sat_add(w: jnp.ndarray, off: int) -> jnp.ndarray:
    """w + off with int64 saturation (full-range order values must not
    wrap)."""
    if w.dtype == jnp.float64:
        return w + off
    t = w + np.int64(off)
    if off > 0:
        return jnp.where(t < w, np.iinfo(np.int64).max, t)
    if off < 0:
        return jnp.where(t > w, np.iinfo(np.int64).min, t)
    return t


def _range_frame_bounds(ctx: _WinCtx, frame: ir.WindowFrame):
    """Finite RANGE offsets (reference analog: cudf
    aggregateWindowsOverTimeRanges, GpuWindowExpression.scala:233-269):
    row i's frame = partition rows whose order value lies within
    [v_i + start, v_i + end] along the sort direction, via a segmented
    binary search over the sorted order values.

    Nulls (and NaN for float keys) sort into contiguous runs at one end
    of the partition; the search is restricted to the plain-value run,
    and a null/NaN current row frames over its peer group on finite
    sides and the partition bound on unbounded sides (Spark semantics).
    """
    v = ctx.sorted_val(ctx.order_vals[0])
    asc, nulls_first = ctx.order_dirs[0]
    use_float = v.dtype.is_floating
    w = v.data.astype(jnp.float64 if use_float else jnp.int64)
    if not asc:
        w = -w   # descending sort == ascending on the negation
    exists = ctx.sorted_exists
    is_null = ~v.validity & exists
    if use_float:
        is_nan = jnp.isnan(w) & ~is_null & exists
        w = jnp.where(is_nan, 0.0, w)   # value unused once excluded
    else:
        is_nan = jnp.zeros_like(is_null)
    special = is_null | is_nan

    # per-partition counts -> bounds of the plain-value run in sorted
    # sequence (nulls at the nulls_first/last end; NaN at the largest-
    # value end, which after desc negation is the sequence start)
    def pcount(mask):
        c = jax.ops.segment_sum(mask.astype(jnp.int64), ctx.part_seg,
                                num_segments=ctx.cap)
        return jnp.take(c, ctx.part_seg)

    nulls = pcount(is_null)
    nans = pcount(is_nan)
    lo = ctx.part_start + jnp.where(nulls_first, nulls, 0) + \
        jnp.where(asc, 0, nans)
    hi = ctx.part_end - jnp.where(nulls_first, 0, nulls) - \
        jnp.where(asc, nans, 0)

    start, end = frame.start, frame.end
    a = ctx.part_start if start is None else jnp.maximum(
        _seg_searchsorted(w, lo, hi, _sat_add(w, start), left=True), lo)
    b = ctx.part_end if end is None else jnp.minimum(
        _seg_searchsorted(w, lo, hi, _sat_add(w, end), left=False) - 1,
        hi)
    # null/NaN current rows: peer group on finite sides
    if start is not None:
        a = jnp.where(special, ctx.peer_start, a)
    if end is not None:
        b = jnp.where(special, ctx.peer_end, b)
    return a, b


def _frame_bounds(ctx: _WinCtx, frame: ir.WindowFrame):
    """Inclusive sorted-position bounds [a, b] per row."""
    if frame.kind == "rows":
        # host-side saturation: offsets are Python ints (Spark longs);
        # ctx.pos is i32 and an offset beyond +-cap clamps to the same
        # partition bound as the unclamped value would
        def sat(off):
            return max(min(int(off), ctx.cap), -ctx.cap)
        a = ctx.part_start if frame.start is None else \
            jnp.maximum(ctx.part_start, ctx.pos + sat(frame.start))
        b = ctx.part_end if frame.end is None else \
            jnp.minimum(ctx.part_end, ctx.pos + sat(frame.end))
        return a, b
    if frame.start is None and frame.end == 0:
        return ctx.part_start, ctx.peer_end
    if frame.start is None and frame.end is None:
        return ctx.part_start, ctx.part_end
    return _range_frame_bounds(ctx, frame)


def _prefix(x: jnp.ndarray) -> jnp.ndarray:
    # scans.cumsum blocks wide (8-byte) dtypes: a bare i64/f64
    # jnp.cumsum inside any control flow trips the 19.09M scoped-VMEM
    # pair lowering on TPU (PERF.md / exec/scans.py)
    return jnp.concatenate([jnp.zeros((1,), x.dtype), scans.cumsum(x)])


def _log_table(op, x: jnp.ndarray, pad, levels: int) -> list:
    """Log-stride table: level ``lvl`` holds op over x[i : i+2^lvl],
    padded past the end with ``pad`` (the op's identity)."""
    cap = x.shape[0]
    tables = [x]
    for lvl in range(1, levels):
        half = 1 << (lvl - 1)
        prev = tables[-1]
        tail = jnp.full((min(half, cap),), pad, prev.dtype)
        shifted = jnp.concatenate([prev[half:], tail])[:cap]
        tables.append(op(prev, shifted))
    return tables


def _range_sum(x: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray
               ) -> jnp.ndarray:
    """Subtraction-free per-row range sum over inclusive [a, b].

    A prefix-sum difference cancels catastrophically when huge values
    surround a small frame (|P| ~ 1e19 swallows a frame sum of 1.0), so
    float frames instead decompose each range into O(log cap) power-of-
    two blocks from a sparse table of pairwise partial sums — every
    block is *added*, never subtracted, so the error stays relative to
    the true frame sum.  This is the exact-per-frame evaluation the
    reference gets from cudf's rolling-window kernels
    (GpuWindowExpression.scala:233-269).
    """
    cap = x.shape[0]
    levels = max(int(np.ceil(np.log2(cap))), 0) + 1 if cap > 1 else 1
    tables = _log_table(jnp.add, x, 0, levels)
    end = b.astype(jnp.int64) + 1
    p = a.astype(jnp.int64)
    acc = jnp.zeros(a.shape, x.dtype)
    for lvl in range(levels - 1, -1, -1):
        size = 1 << lvl
        take = p + size <= end
        val = jnp.take(tables[lvl], jnp.clip(p, 0, cap - 1))
        acc = acc + jnp.where(take, val, 0)
        p = jnp.where(take, p + size, p)
    return acc


def _range_minmax(op, x: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray,
                  fill, max_len: Optional[int] = None) -> jnp.ndarray:
    """Per-row range min/max over inclusive sorted positions [a, b].

    Log-stride sparse table: level ``lvl`` holds op over x[i : i+2^lvl].
    Because min/max is idempotent, any range [a, b] is covered EXACTLY by
    the two (overlapping) blocks [a, a+2^k-1] and [b-2^k+1, b] with
    k = floor(log2(len)) — one gather pair per row, no log-length loop at
    query time.  ``max_len`` caps the table depth when the frame spec
    statically bounds the range length (ROWS k PRECEDING .. m FOLLOWING).
    Frame bounds are pre-clamped to partition bounds, and both query
    blocks lie inside [a, b], so the table may span partitions freely.
    """
    cap = x.shape[0]
    limit = cap if max_len is None else max(min(max_len, cap), 1)
    levels = int(np.floor(np.log2(limit))) + 1 if limit > 1 else 1
    flat = jnp.reshape(jnp.stack(_log_table(op, x, fill, levels)), (-1,))
    ln = jnp.maximum((b - a + 1).astype(jnp.int32), 1)
    k = jnp.minimum(31 - lax.clz(ln), levels - 1)
    size = jnp.left_shift(jnp.int32(1), k)
    base = k * jnp.int32(cap)
    lo = base + jnp.clip(a, 0, cap - 1).astype(jnp.int32)
    hi = base + jnp.clip(b + 1 - size, 0, cap - 1).astype(jnp.int32)
    return op(jnp.take(flat, lo), jnp.take(flat, hi))


def _window_agg(fn: ir.AggregateExpression, ctx: _WinCtx,
                frame: ir.WindowFrame, batch: DeviceBatch) -> ColVal:
    if fn.child is not None:
        v = ctx.sorted_val(eval_tpu.evaluate(fn.child, batch))
        valid = v.validity & ctx.sorted_exists
        data = v.data
    else:
        valid = ctx.sorted_exists
        data = jnp.ones((ctx.cap,), dtype=jnp.int64)
    a, b = _frame_bounds(ctx, frame)
    a = jnp.clip(a, 0, ctx.cap - 1)
    b = jnp.clip(b, -1, ctx.cap - 1)

    nonempty = b >= a

    if isinstance(fn, ir.Count):
        # counts fit i32 (cap < 2^31): native cumsum + narrow gathers
        P = _prefix(valid.astype(jnp.int32))
        out = (jnp.take(P, b + 1) - jnp.take(P, a)).astype(jnp.int64)
        out = jnp.where(nonempty, out, 0)  # empty frame -> count 0
        return ColVal(dt.INT64, out, jnp.ones((ctx.cap,), jnp.bool_))

    if isinstance(fn, (ir.Sum, ir.Average)):
        tgt = jnp.float64 if (fn.dtype.is_floating or
                              isinstance(fn, ir.Average)) else jnp.int64
        is_float = fn.dtype.is_floating or isinstance(fn, ir.Average)
        x = jnp.where(valid, data.astype(tgt), 0)
        if is_float and data.dtype.kind == "f":
            # a NaN would poison every downstream prefix difference;
            # sum the non-NaN part and re-inject NaN per frame
            isnan = jnp.isnan(data) & valid
            x = jnp.where(isnan, 0.0, x)
            nanP = _prefix(isnan.astype(jnp.int32))
            frame_has_nan = (jnp.take(nanP, b + 1) - jnp.take(nanP, a)) > 0
        else:
            frame_has_nan = jnp.zeros((ctx.cap,), dtype=jnp.bool_)
        if is_float:
            s = _range_sum(x, a, b)
        else:
            P = _prefix(x)
            s = jnp.take(P, b + 1) - jnp.take(P, a)
        cnt = _prefix(valid.astype(jnp.int32))
        c = jnp.maximum((jnp.take(cnt, b + 1) -
                         jnp.take(cnt, a)).astype(jnp.int64), 0)
        c = jnp.where(nonempty, c, 0)
        if is_float:
            s = jnp.where(frame_has_nan, jnp.float64(np.nan), s)
        if isinstance(fn, ir.Average):
            nz = c > 0
            return ColVal(dt.FLOAT64,
                          jnp.where(nz, s / jnp.where(nz, c, 1), 0.0), nz)
        return ColVal(fn.dtype, s.astype(fn.dtype.to_np()), c > 0)

    if isinstance(fn, (ir.Min, ir.Max)):
        # prefix frames (a == part_start): running segmented scan indexed
        # at b.  Bounded-start frames: sparse-table range query (cudf
        # rolling-window analog, GpuWindowExpression.scala:233-269).
        is_min = isinstance(fn, ir.Min)
        d = fn.dtype
        tgt = d.to_np()
        bounded = frame.start is not None
        max_len = None
        if bounded and frame.kind == "rows" and frame.end is not None:
            max_len = int(frame.end) - int(frame.start) + 1

        def agg_at_b(op, x, fill):
            if not bounded:
                return jnp.take(_seg_scan(op, x, ctx.part_seg, fill), b)
            if frame.end is None:
                # b == part_end: suffix running scan (O(cap), no table)
                suf = _seg_scan(op, x[::-1], ctx.part_seg[::-1], fill)
                return jnp.take(suf[::-1], a)
            return _range_minmax(op, x, a, b, fill, max_len)

        def any_at_b(mask):
            if bounded:
                P = _prefix(mask.astype(jnp.int32))
                return (jnp.take(P, b + 1) - jnp.take(P, a)) > 0
            return jnp.take(
                _seg_scan(jnp.logical_or, mask, ctx.part_seg, False), b)

        if d.is_floating:
            isnan = jnp.isnan(data)
            fill = np.array(np.inf if is_min else -np.inf, dtype=tgt)
            x = jnp.where(valid & ~isnan, data.astype(tgt), fill)
            run_b = agg_at_b(jnp.minimum if is_min else jnp.maximum, x,
                             fill)
            nonnan_b = any_at_b(valid & ~isnan)
            nan_b = any_at_b(valid & isnan)
            nanv = np.array(np.nan, dtype=tgt)
            if is_min:
                val = jnp.where(nonnan_b, run_b, nanv)
            else:
                val = jnp.where(nan_b, nanv, run_b)
            has = nonnan_b | nan_b
            return ColVal(d, jnp.where(has, val, 0), has & (b >= a))
        if d.is_bool:
            # identity of AND (min) is True, of OR (max) is False
            x = jnp.where(valid, data, is_min)
            run_b = agg_at_b(
                jnp.logical_and if is_min else jnp.logical_or, x, is_min)
            return ColVal(d, run_b, any_at_b(valid) & (b >= a))
        info = np.iinfo(tgt)
        fill = np.array(info.max if is_min else info.min, dtype=tgt)
        x = jnp.where(valid, data.astype(tgt), fill)
        out = agg_at_b(jnp.minimum if is_min else jnp.maximum, x, fill)
        has = any_at_b(valid) & (b >= a)
        return ColVal(d, jnp.where(has, out, 0), has)

    raise NotImplementedError(type(fn).__name__)


def _window_value(we: ir.WindowExpression, ctx: _WinCtx,
                  batch: DeviceBatch) -> ColVal:
    fn = we.function
    if isinstance(fn, ir.RowNumber):
        out = (ctx.pos - ctx.part_start + 1).astype(jnp.int32)
        return ColVal(dt.INT32, out, ctx.sorted_exists)
    if isinstance(fn, ir.Rank):
        out = (ctx.peer_start - ctx.part_start + 1).astype(jnp.int32)
        return ColVal(dt.INT32, out, ctx.sorted_exists)
    if isinstance(fn, ir.DenseRank):
        c = jnp.cumsum(ctx.new_peer.astype(jnp.int32))
        out = c - jnp.take(c, jnp.clip(ctx.part_start, 0, ctx.cap - 1)) + 1
        return ColVal(dt.INT32, out.astype(jnp.int32), ctx.sorted_exists)
    if isinstance(fn, (ir.Lead, ir.Lag)):
        src = ctx.sorted_val(eval_tpu.evaluate(fn.children[0], batch))
        off = fn.offset if isinstance(fn, ir.Lead) else -fn.offset
        tgt = ctx.pos + off
        in_part = (tgt >= ctx.part_start) & (tgt <= ctx.part_end)
        j = jnp.clip(tgt, 0, ctx.cap - 1)
        col = src.to_column().gather(j, in_part & ctx.sorted_exists)
        if fn.default is not None:
            dflt = eval_tpu._const(batch, fn.default, src.dtype)
            use_d = ~in_part & ctx.sorted_exists
            if src.dtype.is_string:
                w = max(col.data.shape[1], dflt.data.shape[1])
                cd = jnp.pad(col.data, ((0, 0), (0, w - col.data.shape[1])))
                dd = jnp.pad(dflt.data,
                             ((0, 0), (0, w - dflt.data.shape[1])))
                data = jnp.where(use_d[:, None], dd, cd)
                lengths = jnp.where(use_d, dflt.lengths, col.lengths)
                return ColVal(src.dtype, data,
                              jnp.where(use_d, dflt.validity, col.validity),
                              lengths)
            data = jnp.where(use_d, dflt.data, col.data)
            return ColVal(src.dtype, data,
                          jnp.where(use_d, dflt.validity, col.validity))
        return ColVal(src.dtype, col.data, col.validity, col.lengths)
    if isinstance(fn, ir.AggregateExpression):
        return _window_agg(fn, ctx, we.frame, batch)
    raise NotImplementedError(type(fn).__name__)


class TpuWindowExec(TpuExec):
    def __init__(self, child: PhysicalPlan,
                 window_exprs: Sequence[ir.WindowExpression],
                 out_names: Sequence[str], schema: Schema,
                 partitionwise: bool = False):
        super().__init__()
        self.children = (child,)
        self.window_exprs = list(window_exprs)
        self.out_names = list(out_names)
        self._schema = schema
        # partitionwise: the planner hash-exchanged on the PARTITION BY
        # keys (rides the ICI plane under transport=ici/ici_ring), so
        # each child partition holds whole window groups and evaluates
        # independently
        self.partitionwise = partitionwise
        self._kernel = None

    @property
    def schema(self) -> Schema:
        return self._schema

    def children_coalesce_goal(self):
        return [REQUIRE_SINGLE_BATCH]

    @staticmethod
    def _spec_groups(out_names, window_exprs):
        """Window exprs grouped by shared (partition, order) spec, in a
        deterministic order."""
        groups = {}
        order = []
        for name, we in zip(out_names, window_exprs):
            sig = (tuple(e.sql() for e in we.partition_exprs),
                   tuple(e.sql() for e in we.order_exprs), we.order_dirs)
            if sig not in groups:
                groups[sig] = []
                order.append(sig)
            groups[sig].append((name, we))
        return [groups[sig] for sig in order]

    def _keys_impl(self, gi: int, batch: DeviceBatch) -> jnp.ndarray:
        we0 = self._spec_groups(self.out_names, self.window_exprs)[gi][0][1]
        pvals = [normalize_key(eval_tpu.evaluate(e, batch))
                 for e in we0.partition_exprs]
        ovals = [normalize_key(eval_tpu.evaluate(e, batch))
                 for e in we0.order_exprs]
        pfields = [_win_fields(v, True, True) for v in pvals]
        ofields = [_win_fields(v, asc, nf)
                   for v, (asc, nf) in zip(ovals, we0.order_dirs)]
        return sortkeys.stack_sort_digits(pfields + ofields,
                                          batch.row_mask())

    def _impl(self, batch: DeviceBatch, orders) -> DeviceBatch:
        spec_groups = self._spec_groups(self.out_names,
                                        self.window_exprs)
        new_cols = {}
        last_order = None
        for gi, items in enumerate(spec_groups):
            we0 = items[0][1]
            ctx = _WinCtx(batch, we0.partition_exprs, we0.order_exprs,
                          we0.order_dirs, order=orders[gi])
            last_order = ctx
            for name, we in items:
                v = _window_value(we, ctx, batch)
                # scatter back to original row order
                inv = jnp.zeros((ctx.cap,), dtype=jnp.int32).at[
                    ctx.order].set(jnp.arange(ctx.cap, dtype=jnp.int32))
                col = v.to_column().gather(inv, batch.row_mask())
                new_cols[name] = col
        # emit in the last spec's sorted order (Spark emits sorted)
        ctx = last_order
        cols = [c.gather(ctx.order, ctx.sorted_exists)
                for c in batch.columns]
        for name in self.out_names:
            c = new_cols[name]
            cols.append(c.gather(ctx.order, ctx.sorted_exists))
        return DeviceBatch(list(batch.names) + self.out_names, cols,
                           batch.num_rows)

    def execute(self):
        import functools
        import types
        from spark_rapids_tpu.exec import kernel_cache as kc
        shim = types.SimpleNamespace(window_exprs=self.window_exprs,
                                     out_names=self.out_names,
                                     _schema=self._schema,
                                     _spec_groups=type(self)._spec_groups)
        cls = type(self)
        sig = (kc.exprs_sig(self.window_exprs), tuple(self.out_names))
        n_groups = len(self._spec_groups(self.out_names,
                                         self.window_exprs))
        keys_kernels = [
            kc.get_kernel(("win_keys", sig, gi),
                          lambda gi=gi: functools.partial(
                              cls._keys_impl, shim, gi))
            for gi in range(n_groups)]
        apply_kernel = kc.get_kernel(
            ("window_apply", sig),
            lambda: functools.partial(cls._impl, shim))

        def run(iters):
            batches: List[DeviceBatch] = []
            for it in iters:
                batches.extend(it)
            if not batches:
                return
            whole = concat_batches(batches)
            with timed(self.metrics, "window.eval"):
                orders = tuple(
                    sortkeys.shared_digit_sort(k(whole))
                    for k in keys_kernels)
                out = apply_kernel(whole, orders)
            self.metrics.add_rows(out.num_rows)
            yield out
        if self.partitionwise:
            return [run([it]) for it in self.children[0].execute()]
        return [run(self.children[0].execute())]
