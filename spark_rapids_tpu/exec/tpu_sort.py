"""TPU sort exec.

Analog of ``GpuSortExec``/``GpuColumnarBatchSorter`` (reference:
GpuSortExec.scala:51-265 — ``Table.orderBy`` on a single coalesced batch with
``RequireSingleBatch`` for total sort, GpuSortExec.scala:76).  The cudf
orderBy becomes: encode each sort column into total-order uint64 keys
(exec/sortkeys.py), one ``jnp.lexsort``, then a row gather.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, concat_batches
from spark_rapids_tpu.exec.base import (PhysicalPlan, REQUIRE_SINGLE_BATCH,
                                        TpuExec, timed)
from spark_rapids_tpu.exec import sortkeys
from spark_rapids_tpu.expr import eval_tpu
from spark_rapids_tpu.plan.logical import Schema, SortOrder


def _field_groups(batch: DeviceBatch, orders: Sequence[SortOrder]):
    groups = []
    for o in orders:
        v = eval_tpu.evaluate(o.expr, batch)
        # trust only the PROPAGATED no-null hint for dropping the null
        # field: schema nullability can be stale (it is metadata; the
        # hint is derived from the actual upload/scan)
        groups.append(sortkeys.encode_fields(
            v, o.ascending, o.nulls_first_resolved,
            nullable=not v.nonnull))
    return groups


class TpuSortExec(TpuExec):
    """Total sort: requires its whole input as one batch (like the
    reference's out-of-core-less sort; spill integration comes via the
    coalesce/spill framework)."""

    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder],
                 partitionwise: bool = False):
        super().__init__()
        self.children = (child,)
        self.orders = list(orders)
        # partitionwise: sort each child partition independently — the
        # planner placed a range exchange below, so partition-ordered
        # concatenation IS the total order (distributed ORDER BY; the
        # exchange rides the ICI plane under transport=ici/ici_ring)
        self.partitionwise = partitionwise
        self._kernel = None

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def children_coalesce_goal(self):
        return [REQUIRE_SINGLE_BATCH]

    def _keys_impl(self, batch: DeviceBatch) -> jnp.ndarray:
        return sortkeys.stack_sort_digits(
            _field_groups(batch, self.orders), batch.row_mask())

    @staticmethod
    def _apply_impl(batch: DeviceBatch,
                    order: jnp.ndarray) -> DeviceBatch:
        valid = jnp.arange(batch.capacity) < batch.num_rows
        cols = [c.gather(order, valid) for c in batch.columns]
        return DeviceBatch(batch.names, cols, batch.num_rows)

    def execute(self):
        # The sort itself runs in sortkeys.shared_lexsort — a standalone
        # kernel keyed (words, cap) shared by every sort in the process
        # (XLA sort compiles are minutes-scale; see sortkeys.py).  Only
        # the cheap encode/apply kernels are schema-specific.
        import functools
        import types
        from spark_rapids_tpu.exec import kernel_cache as kc
        shim = types.SimpleNamespace(orders=self.orders)
        keys_kernel = kc.get_kernel(
            ("sort_keys", tuple((kc.expr_sig(o.expr), o.ascending,
                                 o.nulls_first_resolved)
                                for o in self.orders)),
            lambda: functools.partial(type(self)._keys_impl, shim))

        def run(iters):
            from spark_rapids_tpu.mem.spill import register_or_hold
            # RequireSingleBatch coalesce is a pressure point: every
            # input batch buffers until the concat.  Register each with
            # the spill catalog so accumulated input stays evictable
            # (reference: GpuSortExec's input via SpillableColumnarBatch,
            # SpillableColumnarBatch.scala:169)
            handles: List = []
            for it in iters:
                for b in it:
                    handles.append(register_or_hold(b))
            if not handles:
                return
            try:
                whole = concat_batches([h.get() for h in handles])
            finally:
                for h in handles:
                    h.close()
            with timed(self.metrics, "sort.exec"):
                # shape-erased ABI: ONE erased view feeds both the
                # key-encode and the apply gather (order indices are
                # positions in the erased capacity), names restamped
                # host-side after
                from spark_rapids_tpu.exec import kernel_abi
                ew = kernel_abi.erase(whole)
                digits = keys_kernel(ew)
                order = sortkeys.shared_digit_sort(digits)
                apply_kernel = kc.get_kernel(
                    ("sort_apply", kernel_abi.erased_key(ew)),
                    lambda: type(self)._apply_impl)
                out = apply_kernel(ew, order)
                out = DeviceBatch(whole.names, out.columns,
                                  out.num_rows)
            self.metrics.add_rows(out.num_rows)
            yield out
        if self.partitionwise:
            return [run([it]) for it in self.children[0].execute()]
        return [run(self.children[0].execute())]
