"""TPU equi-join execs.

Reference analog: ``GpuShuffledHashJoinExec``/``GpuBroadcastHashJoinExec``
build one hash table from the build side and probe per stream batch via
``Table.onColumns(keys).innerJoin/leftJoin/fullJoin`` (reference:
shims/spark300/.../GpuHashJoin.scala:193-326); SortMergeJoin is *replaced by*
the shuffled hash join (reference: shims/spark300/.../GpuSortMergeJoinExec.scala).

On TPU, the hash table becomes a sort: both sides' keys are encoded into
total-order words (exec/sortkeys.py), one stable lexsort of the combined
rows groups equal keys together with build rows ahead of stream rows, and
segment arithmetic yields each stream row's contiguous build-match range.
The data-dependent output size (SURVEY.md §7 hard part #1) is handled with
the two-pass count-then-emit pattern: pass 1 computes the exact match
count (one scalar host sync), the host picks a power-of-two output bucket,
pass 2 re-runs the (cached) emit kernel at that static capacity.

SQL semantics: null join keys never match (a key group shares one null
pattern, so null-key groups are simply masked); float keys are normalized
(NaN==NaN, -0.0==0.0) to match Spark's NormalizeFloatingNumbers behavior.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _combined_hints, bucket_rows,
                                             concat_batches)
from spark_rapids_tpu.exec import scans, sortkeys
from spark_rapids_tpu.exec.base import (PhysicalPlan, REQUIRE_SINGLE_BATCH,
                                        TpuExec, timed)
from spark_rapids_tpu.exec.tpu_basic import compact
from spark_rapids_tpu.exec.tpu_aggregate import normalize_key
from spark_rapids_tpu.expr import eval_tpu, ir
from spark_rapids_tpu.expr.eval_tpu import ColVal
from spark_rapids_tpu.plan.logical import Schema

_BIG = np.int64(1 << 62)
_BIG32 = np.int32(np.iinfo(np.int32).max)  # > any position (cap-1)


def _gather(child: PhysicalPlan) -> Optional[DeviceBatch]:
    """Coalesce all of a child's partitions into one batch (build-side
    RequireSingleBatch, reference: GpuHashJoin build side).

    Each arriving batch registers with the spill catalog so the
    accumulating build side stays evictable until the concat
    (reference: build side held as LazySpillableColumnarBatch,
    GpuHashJoin.scala / SpillableColumnarBatch.scala:169)."""
    from spark_rapids_tpu.mem.spill import register_or_hold
    handles = []
    for it in child.execute():
        for b in it:
            handles.append(register_or_hold(b))
    if not handles:
        return None
    try:
        return concat_batches([h.get() for h in handles])
    finally:
        for h in handles:
            h.close()


def _canon_side(batch: DeviceBatch, prefix: str) -> DeviceBatch:
    """Shape-erased ABI at the join dispatch boundary (the PR 12 erase
    extended into the join ``emit`` family): bucket value-range hints
    to the coarse ABI table and pad stragglers to capacity tiers
    (``kernel_abi.erase``), then rename positionally with the side's
    static ``__l*``/``__r*`` prefix — the join kernels reference key
    columns by those canonical names, and keeping the two sides'
    prefixes distinct means the emitted build+stream column set never
    carries duplicate names.  Joins that differ only in schema names
    or precise value ranges share one program; the renamed-join-schema
    rerun test pins zero new programs."""
    from spark_rapids_tpu.exec import kernel_abi
    names = [f"{prefix}{i}" for i in range(batch.num_cols)]
    eb = kernel_abi.erase(batch)
    return DeviceBatch(names, eb.columns, eb.num_rows)


def _side_key(batch: DeviceBatch):
    """Erased cache-key component for one (already canonical) side —
    layout only under the ABI, the legacy named schema_key otherwise
    (so flipping kernel.abi.enabled between sessions cannot serve a
    kernel traced under the other ABI)."""
    from spark_rapids_tpu.exec import kernel_abi
    return kernel_abi.erased_key(batch)


def _key_vals(batch: DeviceBatch, key_names: Sequence[str]) -> List[ColVal]:
    out = []
    for k in key_names:
        c = batch.column(k)
        out.append(normalize_key(ColVal(c.dtype, c.data, c.validity,
                                        c.lengths, vbits=c.vbits,
                                        nonnull=c.nonnull)))
    return out


def _concat_colvals(a: ColVal, b: ColVal) -> ColVal:
    """Concatenate two key columns (for the combined build+stream space).

    Mismatched numeric key pairs are promoted to the common type before
    comparison (Spark's implicit cast), never truncated to one side's type.
    """
    if a.dtype.is_string:
        wa, wb = a.data.shape[1], b.data.shape[1]
        w = max(wa, wb)
        da = jnp.pad(a.data, ((0, 0), (0, w - wa)))
        db = jnp.pad(b.data, ((0, 0), (0, w - wb)))
        return ColVal(a.dtype, jnp.concatenate([da, db]),
                      jnp.concatenate([a.validity, b.validity]),
                      jnp.concatenate([a.lengths, b.lengths]))
    out_dt = a.dtype if a.dtype == b.dtype else dt.promote(a.dtype, b.dtype)
    tgt = out_dt.to_np()
    vb, nn = _combined_hints([a, b])
    merged = ColVal(out_dt,
                    jnp.concatenate([a.data.astype(tgt),
                                     b.data.astype(tgt)]),
                    jnp.concatenate([a.validity, b.validity]),
                    vbits=vb, nonnull=nn)
    # re-normalize: an int->float promotion can introduce nothing new, but
    # float inputs promoted from float32 need canonical NaN/-0.0 again
    return normalize_key(merged)


def _narrow_key_codes(combined, pad: int):
    """Equality-preserving per-row key code for narrow hinted keys.

    When every join key is integer-backed with a vbits range hint and
    the biased fields + null flags pack into 62 bits, the combined code
    itself IS the group value — equal keys share a code — so the
    hash-grouping while_loop (linear-probe scatter claims over a 2x
    table, the joins' dominant pre-sort cost) is skipped entirely.
    None -> caller falls back to hash_group_ids."""
    fields = []
    total = 0
    for v in combined:
        vb = sortkeys.narrow_int_bits(v)
        if vb is None or vb > 32:
            return None
        kf = sortkeys.encode_fields(v, True, True, nullable=True)
        fields.extend(kf)
        total += sum(w for w, _ in kf)
    if not fields or total > 62:         # code << 1 | side fits u64
        return None
    code = None
    for w, vals in fields:               # MSB-first fold
        code = vals if code is None else \
            (code << jnp.uint64(w)) | vals
    return jnp.pad(code, (0, pad))


def _join_sort_key(build: DeviceBatch, stream: DeviceBatch,
                   build_keys: Sequence[str],
                   stream_keys: Sequence[str], seg0=None):
    """(combined keys, exists, side, hash group ids, packed sort key)
    for the combined build+stream row space.

    Equal-key adjacency WITHOUT a multi-word lexsort: hash-group the
    combined keys (scatter build, compile-cheap), then the caller sorts
    ONE u64 word of (group id, side) — XLA sort compile cost scales with
    operand count, and at SQL batch sizes a multi-word lexsort compiles
    for minutes."""
    cap_b, cap_s = build.capacity, stream.capacity
    # pad the combined space to a power-of-two capacity so the shared
    # sort kernel is keyed on a handful of buckets, not on every
    # (cap_b + cap_s) sum the suite produces
    cap2 = bucket_rows(cap_b + cap_s)
    pad = cap2 - (cap_b + cap_s)
    bk = _key_vals(build, build_keys)
    sk = _key_vals(stream, stream_keys)
    combined = [_concat_colvals(b, s) for b, s in zip(bk, sk)]
    exists = jnp.pad(jnp.concatenate([build.row_mask(),
                                      stream.row_mask()]), (0, pad))
    side = jnp.pad(jnp.concatenate([
        jnp.zeros((cap_b,), dtype=jnp.uint64),
        jnp.ones((cap_s,), dtype=jnp.uint64)]), (0, pad))
    if seg0 is None:
        seg0 = _narrow_key_codes(combined, pad)
    if seg0 is None:
        key_groups = [sortkeys.encode_keys(v, True, True)
                      for v in combined]
        words = [jnp.pad(w, (0, pad)) for g in key_groups for w in g]
        seg0, _ = sortkeys.hash_group_ids(words, exists)
    packed = (seg0.astype(jnp.uint64) << jnp.uint64(1)) | side
    packed = jnp.where(exists, packed, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    null_key = jnp.zeros((cap_b + cap_s,), dtype=jnp.bool_)
    for v in combined:
        null_key = null_key | ~v.validity
    null_key = jnp.pad(null_key, (0, pad))
    return null_key, exists, side, seg0, packed


class _JoinCtx:
    """Combined sorted space over build+stream rows."""

    def __init__(self, build: DeviceBatch, stream: DeviceBatch,
                 build_keys: Sequence[str], stream_keys: Sequence[str],
                 order=None, seg0=None):
        self.cap_b = build.capacity
        self.cap_s = stream.capacity
        null_key, exists, side, seg0, packed = _join_sort_key(
            build, stream, build_keys, stream_keys, seg0=seg0)
        cap = int(packed.shape[0])   # bucketed combined capacity
        self.cap = cap

        # the stable sort of the packed key normally runs OUTSIDE this
        # (jitted) kernel via sortkeys.shared_lexsort — embedding it
        # would recompile a minutes-scale XLA sort per join schema
        if order is None:
            order = jnp.lexsort((packed,))  # stable
        seg_sorted_raw = jnp.take(seg0, order)
        exists_sorted = jnp.take(exists, order)
        new_group = jnp.concatenate(
            [jnp.ones((1,), dtype=jnp.bool_),
             (seg_sorted_raw[1:] != seg_sorted_raw[:-1]) |
             (exists_sorted[1:] != exists_sorted[:-1])])
        seg = jnp.cumsum(new_group.astype(jnp.int32)) - 1

        self.order = order
        self.seg = seg
        sorted_exists = jnp.take(exists, order)
        sorted_side = jnp.take(side, order)
        self.sorted_null_key = jnp.take(null_key, order)
        self.is_build = sorted_exists & (sorted_side == 0)
        self.is_stream = sorted_exists & (sorted_side == 1)
        # counts/positions fit i32 (cap < 2^31), and every per-group
        # reduction is SCATTER-FREE sorted-space work (cumsum diffs +
        # one set-scatter of group end positions + a segmented i32
        # min-scan) — segment_sum/min scatter-adds at full capacity
        # measured ~100 ms each per 4M rows (PERF.md)
        pos = jnp.arange(cap, dtype=jnp.int32)
        nxt_new = jnp.concatenate([new_group[1:],
                                   jnp.ones((1,), jnp.bool_)])
        end_pos = jnp.zeros((cap,), jnp.int32).at[
            jnp.where(nxt_new, seg, cap)].set(pos, mode="drop")

        def per_group_count(mask):
            c = jnp.cumsum(mask.astype(jnp.int32))
            ce = jnp.take(c, end_pos)
            return ce - jnp.concatenate([ce[:1] * 0, ce[:-1]])

        match_build = self.is_build & ~self.sorted_null_key
        self.b_count = per_group_count(match_build)
        run_min = scans.seg_scan(
            jnp.minimum, new_group,
            jnp.where(match_build, pos, _BIG32), _BIG32)
        self.build_start = jnp.take(run_min, end_pos)
        match_stream = self.is_stream & ~self.sorted_null_key
        self.s_count = per_group_count(match_stream)

        # per sorted-row match count (stream rows only)
        self.m = jnp.where(self.is_stream & ~self.sorted_null_key,
                           jnp.take(self.b_count, seg), 0)


def _pairs_layout(ctx: _JoinCtx, outer: bool, with_incl: bool = True):
    """Per-sorted-row emission count + inclusive cumsum (i32: the emit
    kernel only runs after the host has checked the i64 total fits)."""
    m_out = ctx.m
    if outer:
        m_out = jnp.where(ctx.is_stream, jnp.maximum(ctx.m, 1), 0)
    else:
        m_out = jnp.where(ctx.is_stream, ctx.m, 0)
    incl = jnp.cumsum(m_out) if with_incl else None
    return m_out, incl


def _count_kernel(build, stream, order, seg0, build_keys, stream_keys,
                  how):
    ctx = _JoinCtx(build, stream, build_keys, stream_keys, order=order,
                   seg0=seg0)
    outer = how in ("left", "right", "full")
    m_out, _ = _pairs_layout(ctx, outer, with_incl=False)
    # the TRUE pair total needs i64: per-row counts fit i32 but a
    # many-to-many join's total is bounded by cap_b*cap_s, not cap.
    # A plain i64 reduction is safe anywhere (only i64 *scans* trip the
    # scoped-VMEM lowering); the host refuses totals past the i32 range
    # before the emit kernel's i32 cumsum ever sees them.
    total = jnp.sum(m_out, dtype=jnp.int64)
    if how == "full":
        unmatched_build = ctx.is_build & \
            (jnp.take(ctx.s_count, ctx.seg) == 0)
        total = total + jnp.sum(unmatched_build, dtype=jnp.int64)
    return total


def _emit_kernel(build, stream, order, seg0, build_keys, stream_keys,
                 how, out_cap,
                 build_names, stream_names, build_first_in_output):
    """Pass 2: materialize the joined batch at static capacity out_cap."""
    ctx = _JoinCtx(build, stream, build_keys, stream_keys, order=order,
                   seg0=seg0)
    outer = how in ("left", "right", "full")
    m_out, incl = _pairs_layout(ctx, outer)
    total_pairs = incl[-1]

    k = jnp.arange(out_cap, dtype=jnp.int32)
    # slot -> sorted stream row: scatter each emitting row's index at
    # its first output slot, forward-fill with a running max (row
    # indices ascend along slots).  Replaces searchsorted, whose
    # log2(cap) binary-search gathers per slot cost ~300 ms at 2M
    starts = incl - m_out
    has = m_out > 0
    marks = jnp.zeros((out_cap,), jnp.int32).at[
        jnp.where(has, starts, out_cap)].max(
        jnp.arange(ctx.cap, dtype=jnp.int32), mode="drop")
    r = jax.lax.cummax(marks)
    r = jnp.clip(r, 0, ctx.cap - 1)
    j = k - jnp.take(starts, r)
    valid_pair = k < total_pairs

    stream_orig = jnp.take(ctx.order, r) - ctx.cap_b
    stream_orig = jnp.clip(stream_orig, 0, ctx.cap_s - 1)
    has_match = jnp.take(ctx.m, r) > 0
    bpos = jnp.clip(jnp.take(ctx.build_start, jnp.take(ctx.seg, r)) + j,
                    0, ctx.cap - 1)
    build_orig = jnp.clip(jnp.take(ctx.order, bpos), 0, ctx.cap_b - 1)

    stream_valid = valid_pair
    build_valid = valid_pair & has_match

    if how == "full":
        # append unmatched build rows after the pairs (rank->row map via
        # cumsum+scatter, no sort)
        unmatched = ctx.is_build & (jnp.take(ctx.s_count, ctx.seg) == 0)
        u_count = jnp.sum(unmatched.astype(jnp.int32), dtype=jnp.int32)
        u_dest = jnp.where(
            unmatched, jnp.cumsum(unmatched.astype(jnp.int32)) - 1,
            ctx.cap)
        u_order = jnp.zeros((ctx.cap,), dtype=jnp.int32).at[u_dest].set(
            jnp.arange(ctx.cap, dtype=jnp.int32), mode="drop")
        tail_idx = jnp.clip(k - total_pairs, 0, ctx.cap - 1)
        in_tail = (k >= total_pairs) & (k < total_pairs + u_count)
        tail_sorted_pos = jnp.take(u_order, tail_idx)
        tail_build_orig = jnp.clip(
            jnp.take(ctx.order, tail_sorted_pos), 0, ctx.cap_b - 1)
        build_orig = jnp.where(in_tail, tail_build_orig, build_orig)
        build_valid = build_valid | in_tail
        stream_valid = valid_pair  # tail rows have null stream side
        total_out = total_pairs + u_count
    else:
        total_out = total_pairs

    s_cols = [c.gather(stream_orig, stream_valid) for c in stream.columns]
    b_cols = [c.gather(build_orig, build_valid) for c in build.columns]
    if build_first_in_output:
        names = list(build_names) + list(stream_names)
        cols = b_cols + s_cols
    else:
        names = list(stream_names) + list(build_names)
        cols = s_cols + b_cols
    return DeviceBatch(names, cols, total_out)


def _semi_kernel(build, stream, order, seg0, build_keys, stream_keys,
                 anti: bool):
    ctx = _JoinCtx(build, stream, build_keys, stream_keys, order=order,
                   seg0=seg0)
    # scatter per-sorted-row match count back to original stream rows
    m_orig = jnp.zeros((ctx.cap,), dtype=jnp.int32).at[ctx.order].set(ctx.m)
    m_stream = m_orig[ctx.cap_b:ctx.cap_b + ctx.cap_s]
    keep = (m_stream == 0) if anti else (m_stream > 0)
    return compact(stream, keep)



# ---------------------------------------------------------------------------
# Direct-address probe path (narrow keys)
# ---------------------------------------------------------------------------
#
# When every join key is integer-backed with a narrow vbits range hint,
# the biased key fields pack into one u32 code and the hash table of
# cudf's hash join (GpuHashJoin.scala:193-326) becomes a DENSE
# direct-address table: one i32 scatter per build row, ONE gather per
# stream row to find its match range.  This removes the combined-space
# sort entirely — the sort-merge path's dominant cost is the (cap_b +
# cap_s)-sized sort plus ~10 bookkeeping gathers per row; the probe path
# pays 1-2 table gathers per stream row and per-output-column gathers
# only.  Falls back to the sort path for wide/float/string keys or full
# outer joins.

_PROBE_MAX_BITS = 22    # direct table <= 4M entries (2 x 16 MiB i32)


def _probe_code_bits(build: DeviceBatch, stream: DeviceBatch,
                     build_keys: Sequence[str],
                     stream_keys: Sequence[str]) -> Optional[int]:
    """Static (host-side) width of the packed direct-address code, or
    None when the narrow encoding does not apply.  Mirrors the field
    widths `_narrow_key_codes` produces (encode_fields with
    nullable=True: 1 null bit + vbits value bits per key)."""
    total = 0
    for kb, ks in zip(build_keys, stream_keys):
        b, s = build.column(kb), stream.column(ks)
        for c in (b, s):
            if c.dtype.is_string or c.dtype.is_floating or \
                    c.dtype.is_bool or c.dtype.is_nested or \
                    c.dtype.is_temporal:
                return None
        out_dt = b.dtype if b.dtype == s.dtype \
            else dt.promote(b.dtype, s.dtype)
        if not out_dt.is_numeric or out_dt.is_floating:
            return None
        vb, _nn = _combined_hints([b, s])
        npd = np.dtype(out_dt.to_np())
        vb = min(vb or 64, npd.itemsize * 8)
        if vb > 32 or vb >= 64:
            return None
        total += vb + 1                     # null flag + biased value
    return total if total else None


def _probe_tables(build: DeviceBatch, stream: DeviceBatch,
                  build_keys: Sequence[str], stream_keys: Sequence[str],
                  bits: int):
    """Shared probe-side prologue: per-side u32 codes, valid masks, and
    the dense per-code build count table."""
    bk = _key_vals(build, build_keys)
    sk = _key_vals(stream, stream_keys)
    combined = [_concat_colvals(b, s) for b, s in zip(bk, sk)]
    code = _narrow_key_codes(combined, 0)
    null_key = jnp.zeros((code.shape[0],), dtype=jnp.bool_)
    for v in combined:
        null_key = null_key | ~v.validity
    cap_b = build.capacity
    code = code.astype(jnp.uint32)
    T = 1 << bits
    bcode = code[:cap_b].astype(jnp.int32)
    scode = code[cap_b:].astype(jnp.int32)
    bvalid = build.row_mask() & ~null_key[:cap_b]
    svalid = stream.row_mask() & ~null_key[cap_b:]
    cnt = jnp.zeros((T,), jnp.int32).at[
        jnp.where(bvalid, bcode, T)].add(1, mode="drop")
    m = jnp.where(svalid, jnp.take(cnt, scode), 0)
    return bcode, scode, bvalid, svalid, cnt, m


def _probe_count_kernel(build, stream, build_keys, stream_keys, how,
                        bits):
    """(total output rows i64, max per-stream-row match count i32)."""
    _, _, _, _, _, m = _probe_tables(build, stream, build_keys,
                                     stream_keys, bits)
    m_out = jnp.where(stream.row_mask(), jnp.maximum(m, 1), 0) \
        if how == "left" else m
    return jnp.sum(m_out, dtype=jnp.int64), jnp.max(m)


def _probe_emit_unique_kernel(build, stream, build_keys, stream_keys,
                              how, out_cap, build_names, stream_names,
                              build_first_in_output, bits):
    """Emit when every build key is unique (max match count <= 1): the
    dense table maps code -> build row directly, output rows are stream
    rows (left: in place; inner: compacted), no expansion machinery."""
    bcode, scode, bvalid, svalid, _cnt, _m = _probe_tables(
        build, stream, build_keys, stream_keys, bits)
    T = 1 << bits
    cap_b, cap_s = build.capacity, stream.capacity
    # row+1 sentinel table: 0 = no build row, ONE gather gives both the
    # match flag and the row
    rows1 = jnp.zeros((T,), jnp.int32).at[
        jnp.where(bvalid, bcode, T)].set(
        jnp.arange(cap_b, dtype=jnp.int32) + 1, mode="drop")
    hit = jnp.where(svalid, jnp.take(rows1, scode), 0)
    matched = hit > 0
    build_row = jnp.clip(hit - 1, 0, cap_b - 1)

    if how in ("left", "inner_inplace"):
        # inner_inplace: the host saw total == stream rows (FK join,
        # every stream row matched) — output rows ARE the stream rows,
        # so skip the compaction and all stream-column gathers
        s_cols = list(stream.columns)
        b_cols = [c.gather(build_row, matched) for c in build.columns]
        total_out = stream.num_rows
    else:
        keep = matched
        # stable compaction of (stream cols, gathered build cols) to
        # out_cap (cumsum destinations + scatter, the compact() idiom)
        count = jnp.sum(keep.astype(jnp.int32))
        dest = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1,
                         out_cap)
        src = jnp.zeros((out_cap,), jnp.int32).at[dest].set(
            jnp.arange(cap_s, dtype=jnp.int32), mode="drop")
        out_valid = jnp.arange(out_cap, dtype=jnp.int32) < count
        s_cols = [c.gather(src, out_valid) for c in stream.columns]
        br = jnp.take(build_row, src)
        b_cols = [c.gather(br, out_valid) for c in build.columns]
        total_out = count
    if build_first_in_output:
        names = list(build_names) + list(stream_names)
        cols = b_cols + s_cols
    else:
        names = list(stream_names) + list(build_names)
        cols = s_cols + b_cols
    return DeviceBatch(names, cols, total_out)


def _probe_emit_dup_kernel(build, stream, border, build_keys,
                           stream_keys, how, out_cap, build_names,
                           stream_names, build_first_in_output, bits):
    """Emit with duplicated build keys: build rows grouped by code via
    the (small) build-side sort ``border``, match ranges from the dense
    start/count tables, output expansion via cumsum + set-scatter +
    cummax forward fill (no combined-space sort)."""
    bcode, scode, bvalid, svalid, cnt, m = _probe_tables(
        build, stream, build_keys, stream_keys, bits)
    T = 1 << bits
    cap_b, cap_s = build.capacity, stream.capacity
    starts_tbl = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(cnt)[:-1]])
    # build rows grouped by code: border sorts (invalid-last) bcode
    grouped_rows = border                  # sorted build row ids
    st = jnp.where(svalid, jnp.take(starts_tbl, scode), 0)

    m_out = jnp.where(stream.row_mask(), jnp.maximum(m, 1), 0) \
        if how == "left" else m
    incl = jnp.cumsum(m_out)
    total_out = incl[-1]
    starts_out = incl - m_out
    has = m_out > 0
    k = jnp.arange(out_cap, dtype=jnp.int32)
    marks = jnp.zeros((out_cap,), jnp.int32).at[
        jnp.where(has, starts_out, out_cap)].max(
        jnp.arange(cap_s, dtype=jnp.int32), mode="drop")
    r = jnp.clip(jax.lax.cummax(marks), 0, cap_s - 1)
    j = k - jnp.take(starts_out, r)
    valid_pair = k < total_out
    has_match = jnp.take(m, r) > 0
    bpos = jnp.clip(jnp.take(st, r) + j, 0, cap_b - 1)
    build_row = jnp.clip(jnp.take(grouped_rows, bpos), 0, cap_b - 1)
    s_cols = [c.gather(r, valid_pair) for c in stream.columns]
    b_cols = [c.gather(build_row, valid_pair & has_match)
              for c in build.columns]
    if build_first_in_output:
        names = list(build_names) + list(stream_names)
        cols = b_cols + s_cols
    else:
        names = list(stream_names) + list(build_names)
        cols = s_cols + b_cols
    return DeviceBatch(names, cols, total_out)


def _probe_semi_kernel(build, stream, build_keys, stream_keys, anti,
                       bits):
    _, _, _, _, _, m = _probe_tables(build, stream, build_keys,
                                     stream_keys, bits)
    keep = (m == 0) if anti else (m > 0)
    return compact(stream, keep & stream.row_mask())


class _BroadcastBuildMixin:
    """Caches the one-time gather of the broadcast (build) side."""

    def _init_build(self, build_side: str) -> None:
        self.build_side = build_side
        self._built = None
        self._build_done = False
        import threading
        self._build_lock = threading.Lock()

    def _build(self):
        # concurrent stream partitions must gather the build side once;
        # the cached copy is held through the whole probe phase, so it
        # stays registered with the spill catalog and is rematerialized
        # per probe (reference: broadcast build kept as
        # SpillableColumnarBatch, GpuBroadcastExchangeExec)
        from spark_rapids_tpu.mem.spill import register_or_hold
        with self._build_lock:
            if not self._build_done:
                side = 1 if self.build_side == "right" else 0
                built = _gather(self.children[side])
                self._built = None if built is None \
                    else register_or_hold(built)
                self._build_done = True
        return None if self._built is None else self._built.get()


class _HashJoinBase(TpuExec):
    """Shared probe machinery for shuffled and broadcast hash joins."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 how: str, condition: Optional[ir.Expression],
                 schema: Schema):
        super().__init__()
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.how = how
        self.condition = condition
        self._schema = schema
        self._kernels = {}

    @property
    def schema(self) -> Schema:
        return self._schema

    def _sort_order(self, build: DeviceBatch, stream: DeviceBatch,
                    bkeys, skeys) -> jnp.ndarray:
        """Combined-space sort order via the SHARED per-capacity sort
        kernel (the expensive compile), fed by a cheap per-schema pack
        kernel."""
        from spark_rapids_tpu.exec import kernel_cache as kc
        pkey = ("join_pack", tuple(bkeys), tuple(skeys),
                _side_key(build), _side_key(stream))
        if pkey not in self._kernels:
            self._kernels[pkey] = kc.get_kernel(
                pkey, lambda: lambda b, s: _join_sort_key(
                    b, s, bkeys, skeys)[3:5])
        seg0, packed = self._kernels[pkey](build, stream)
        order = sortkeys.shared_lexsort(jnp.reshape(packed, (1, -1)))
        return order, seg0

    def _probe_pair(self, build: DeviceBatch, stream: DeviceBatch,
                    bkeys, skeys, emit_how: str, build_first: bool,
                    bits: int):
        """Direct-address probe join (narrow keys): count -> host picks
        the unique or duplicated-build-key emit variant."""
        from spark_rapids_tpu.exec import kernel_cache as kc
        sig = (bits, emit_how, tuple(bkeys), tuple(skeys),
               _side_key(build), _side_key(stream))
        ckey = ("probe_count",) + sig
        if ckey not in self._kernels:
            self._kernels[ckey] = kc.get_kernel(
                ckey, lambda: lambda b, s: _probe_count_kernel(
                    b, s, bkeys, skeys, emit_how, bits))
        with timed(self.metrics, "join.probeCount"):
            total, maxm = self._kernels[ckey](build, stream)
            total, maxm = int(total), int(maxm)
        if total >= (1 << 31):
            raise MemoryError(
                f"join output of {total} rows exceeds the single-batch "
                f"2^31 limit; repartition the inputs")
        if maxm <= 1:
            emit_variant = emit_how
            if emit_how == "inner" and \
                    isinstance(stream.num_rows, (int, np.integer)) and \
                    total == int(stream.num_rows):
                emit_variant = "inner_inplace"   # FK join: all rows match
            out_cap = bucket_rows(stream.capacity) if emit_variant != "inner" \
                else bucket_rows(total)
            ekey = ("probe_emit_u", emit_variant, out_cap,
                    build_first) + sig
            if ekey not in self._kernels:
                self._kernels[ekey] = kc.get_kernel(
                    ekey, lambda: lambda b, s: _probe_emit_unique_kernel(
                        b, s, bkeys, skeys, emit_variant, out_cap,
                        build.names, stream.names, build_first, bits))
            with timed(self.metrics, "join.probeEmit"):
                out = self._kernels[ekey](build, stream)
        else:
            out_cap = bucket_rows(total)
            pkey = ("probe_bpack",) + sig
            if pkey not in self._kernels:
                def bpack(b, s):
                    bcode, _, bvalid, _, _, _ = _probe_tables(
                        b, s, bkeys, skeys, bits)
                    key = jnp.where(bvalid, bcode.astype(jnp.uint64),
                                    jnp.uint64(0xFFFFFFFF))
                    return jnp.reshape(key, (1, -1))
                self._kernels[pkey] = kc.get_kernel(pkey,
                                                    lambda: bpack)
            ekey = ("probe_emit_d", out_cap, build_first) + sig
            if ekey not in self._kernels:
                self._kernels[ekey] = kc.get_kernel(
                    ekey, lambda: lambda b, s, o: _probe_emit_dup_kernel(
                        b, s, o, bkeys, skeys, emit_how, out_cap,
                        build.names, stream.names, build_first, bits))
            with timed(self.metrics, "join.probeEmit"):
                border = sortkeys.shared_lexsort(
                    self._kernels[pkey](build, stream))
                out = self._kernels[ekey](build, stream, border)
        out = DeviceBatch(self._schema.names, out.columns, out.num_rows)
        if self.condition is not None:
            v = eval_tpu.evaluate(self.condition, out)
            out = compact(out, v.data.astype(jnp.bool_) & v.validity)
        self.metrics.add_rows(out.num_rows)
        self.metrics.add_batches()
        yield out

    def _join_pair(self, left: DeviceBatch, right: DeviceBatch,
                   build_side: str = "right"):
        """Join two single batches; yields 0 or 1 output batches."""
        how = self.how
        # canonicalize both sides at the dispatch boundary: positional
        # __l*/__r* names (dodges duplicate-name lookups AND erases the
        # user schema from the kernel identity) + ABI hint bucketing /
        # tier padding (_canon_side)
        lkeys = [f"__l{left.names.index(k)}" for k in self.left_keys]
        rkeys = [f"__r{right.names.index(k)}" for k in self.right_keys]
        left = _canon_side(left, "__l")
        right = _canon_side(right, "__r")

        if how in ("semi", "anti"):
            from spark_rapids_tpu.exec import kernel_cache as kc
            bits = _probe_code_bits(right, left, rkeys, lkeys)
            if bits is not None and bits <= _PROBE_MAX_BITS:
                key = ("probe_semi", how, bits, tuple(lkeys),
                       tuple(rkeys), _side_key(left),
                       _side_key(right))
                if key not in self._kernels:
                    self._kernels[key] = kc.get_kernel(
                        key, lambda: lambda b, s: _probe_semi_kernel(
                            b, s, rkeys, lkeys, how == "anti", bits))
                with timed(self.metrics, "join.semi"):
                    out = self._kernels[key](right, left)
            else:
                key = ("semi", how, tuple(lkeys), tuple(rkeys),
                       _side_key(left), _side_key(right))
                if key not in self._kernels:
                    self._kernels[key] = kc.get_kernel(
                        key, lambda: lambda b, s, o, g: _semi_kernel(
                            b, s, o, g, rkeys, lkeys, how == "anti"))
                with timed(self.metrics, "join.semi"):
                    order, seg0 = self._sort_order(right, left, rkeys,
                                                   lkeys)
                    out = self._kernels[key](right, left, order, seg0)
            self.metrics.add_rows(out.num_rows)
            self.metrics.add_batches()
            yield DeviceBatch(self._schema.names, out.columns,
                              out.num_rows)
            return

        if build_side == "left" or how == "right":
            # right outer == left outer with sides swapped
            build, stream = left, right
            bkeys, skeys = lkeys, rkeys
            emit_how = "left" if how == "right" else how
            build_first = True
        else:
            build, stream = right, left
            bkeys, skeys = rkeys, lkeys
            emit_how = how
            build_first = False

        from spark_rapids_tpu.exec import kernel_cache as kc
        bits = _probe_code_bits(build, stream, bkeys, skeys)
        if bits is not None and bits <= _PROBE_MAX_BITS and \
                emit_how in ("inner", "left"):
            yield from self._probe_pair(build, stream, bkeys, skeys,
                                        emit_how, build_first, bits)
            return
        ckey = ("count", emit_how, tuple(bkeys), tuple(skeys),
                _side_key(build), _side_key(stream))
        if ckey not in self._kernels:
            self._kernels[ckey] = kc.get_kernel(
                ckey, lambda: lambda b, s, o, g: _count_kernel(
                    b, s, o, g, bkeys, skeys, emit_how))
        with timed(self.metrics, "join.count"):
            order, seg0 = self._sort_order(build, stream, bkeys, skeys)
            total = int(self._kernels[ckey](build, stream, order,
                                            seg0))
        if total >= (1 << 31):
            # the emit kernel's per-row layout runs in i32 (i64 chains
            # are 3-14x slower under the pair emulation); a >2^31-row
            # single join output cannot be materialized as one batch
            # anyway — fail loudly instead of wrapping silently
            raise MemoryError(
                f"join output of {total} rows exceeds the single-batch "
                f"2^31 limit; repartition the inputs")
        out_cap = bucket_rows(total)
        ekey = ("emit", emit_how, out_cap, tuple(bkeys), tuple(skeys),
                build_first, _side_key(build), _side_key(stream))
        if ekey not in self._kernels:
            self._kernels[ekey] = kc.get_kernel(
                ekey, lambda: lambda b, s, o, g: _emit_kernel(
                    b, s, o, g, bkeys, skeys, emit_how, out_cap,
                    build.names, stream.names, build_first))
        with timed(self.metrics, "join.emit"):
            out = self._kernels[ekey](build, stream, order, seg0)
        out = DeviceBatch(self._schema.names, out.columns, out.num_rows)
        if self.condition is not None:
            v = eval_tpu.evaluate(self.condition, out)
            out = compact(out, v.data.astype(jnp.bool_) & v.validity)
        self.metrics.add_rows(out.num_rows)
        self.metrics.add_batches()
        yield out


def _gather_partition(it) -> Optional[DeviceBatch]:
    batches = [b for b in it if int(b.num_rows)]
    return concat_batches(batches) if batches else None


class TpuShuffledHashJoinExec(_HashJoinBase):
    """Equi-join over co-partitioned children (hash exchanges inserted by
    the planner); each partition pair joins independently with the build
    partition coalesced to one batch, like the reference's build side
    (GpuHashJoin build on single coalesced batch).  Also accepts
    single-partition children (the degenerate pre-exchange shape)."""

    def execute(self):
        lits = self.children[0].execute()
        rits = self.children[1].execute()
        assert len(lits) == len(rits), \
            f"join children not co-partitioned: {len(lits)} vs {len(rits)}"
        # planner-stamped out-of-core resolution (join_partition.
        # resolve_oocore); unstamped execs — hand-built tests, the
        # knob off — keep today's unconditional gather exactly
        oocore = getattr(self, "_oocore", None)

        def run_streamed(lit, rit):
            """inner/left/semi/anti: build side coalesced once, STREAM
            side probes per batch (reference: GpuHashJoin.scala:193-326
            streams the probe side) — the stream partition is never
            concatenated into one giant batch.  Cost note: each probe
            batch re-groups the combined build+batch key space (the
            sort-based formulation has no persistent hash table);
            coalesce goals keep probe batches per partition few.
            """
            from spark_rapids_tpu.mem.spill import register_or_hold
            if oocore is not None:
                rbs = [b for b in rit if int(b.num_rows)]
                build_bytes = sum(int(b.nbytes()) for b in rbs)
                if rbs and build_bytes > oocore["budget"]:
                    from spark_rapids_tpu.exec import join_partition
                    yield from join_partition.grace_join(
                        self, lit, rbs, build_bytes, oocore,
                        build_is_left=False, gathered=False)
                    return
                right = concat_batches(rbs) if rbs else None
            else:
                right = _gather_partition(rit)
            if right is None:
                if self.how == "inner":
                    # nothing can match — but the stream iterator must
                    # still drain: AQE readers release their
                    # spill-catalog claims inside the generator body
                    for _ in lit:
                        pass
                    return
                right = _empty_like(self.children[1].schema)
            # the build partition is held across the whole stream probe
            # loop — keep it spillable between probe batches
            with register_or_hold(right) as rh:
                for lb in lit:
                    if not int(lb.num_rows):
                        continue
                    yield from self._join_pair(lb, rh.get())

        def run_gathered(lit, rit):
            """right/full: unmatched-build emission needs every stream
            batch, so the pair joins as two single batches."""
            if oocore is not None:
                lbs = [b for b in lit if int(b.num_rows)]
                rbs = [b for b in rit if int(b.num_rows)]
                # _join_pair's build-side resolution: right-outer
                # builds on the LEFT (swapped-sides left outer), full
                # builds on the right
                build_is_left = self.how == "right"
                bbs = lbs if build_is_left else rbs
                build_bytes = sum(int(b.nbytes()) for b in bbs)
                if bbs and build_bytes > oocore["budget"]:
                    from spark_rapids_tpu.exec import join_partition
                    yield from join_partition.grace_join(
                        self, rbs if build_is_left else lbs, bbs,
                        build_bytes, oocore,
                        build_is_left=build_is_left, gathered=True)
                    return
                left = concat_batches(lbs) if lbs else None
                right = concat_batches(rbs) if rbs else None
            else:
                left = _gather_partition(lit)
                right = _gather_partition(rit)
            if left is None or right is None:
                if left is not None or right is not None:
                    left = left if left is not None else \
                        _empty_like(self.children[0].schema)
                    right = right if right is not None else \
                        _empty_like(self.children[1].schema)
                else:
                    return
            yield from self._join_pair(left, right)

        run = run_gathered if self.how in ("right", "full") \
            else run_streamed
        return [run(l, r) for l, r in zip(lits, rits)]


class TpuBroadcastHashJoinExec(_BroadcastBuildMixin, _HashJoinBase):
    """Equi-join with the build side broadcast: gathered once across all
    its partitions, then probed per stream batch so the stream side stays
    partitioned (reference: GpuBroadcastHashJoinExec — broadcast host
    batch -> device once per task, then probe per batch)."""

    def __init__(self, *args, build_side: str = "right",
                 transport: str = "local"):
        super().__init__(*args)
        self._init_build(build_side)
        # 'ici': replicate the build side over the device mesh with one
        # mesh broadcast so each stream shard joins against its LOCAL
        # copy (GpuBroadcastExchangeExec analog) instead of depending on
        # a single in-process batch
        self.transport = transport
        self._bcast_map = None
        import threading
        self._bcast_lock = threading.Lock()

    def _build_broadcast(self):
        built = self._build()   # takes _build_lock itself
        with self._bcast_lock:
            if self._bcast_map is None:
                from spark_rapids_tpu.shuffle import ici
                if built is None:
                    self._bcast_map = {}
                elif self.transport == "ici_ring":
                    # point-to-point plane: ppermute ring rotation
                    self._bcast_map = ici.ring_broadcast_batch(built)
                    self.metrics.extra["ici_ring_hops"] = \
                        max(len(self._bcast_map) - 1, 0)
                else:
                    self._bcast_map = ici.broadcast_batch(built)
                    self.metrics.extra["ici_broadcast_devices"] = \
                        len(self._bcast_map)
        return self._bcast_map

    def _build_for(self, stream_batch: DeviceBatch):
        """The build-side copy colocated with this stream batch."""
        if self.transport not in ("ici", "ici_ring"):
            return self._build()
        bmap = self._build_broadcast()
        if not bmap:
            return None
        if stream_batch.columns:
            devs = stream_batch.columns[0].data.devices()
            for d in devs:
                if d in bmap:
                    return bmap[d]
        return next(iter(bmap.values()))

    def execute(self):
        stream_side = 0 if self.build_side == "right" else 1
        sits = self.children[stream_side].execute()

        def run(sit):
            # materialize (and for ICI, broadcast) the build side BEFORE
            # pulling any stream batch: stream scans hold the TPU
            # semaphore across their yield, and the build side's own
            # scan acquiring it then would deadlock the task pool
            if self.transport in ("ici", "ici_ring"):
                self._build_broadcast()
            else:
                self._build()
            for sb in sit:
                if not int(sb.num_rows):
                    continue
                build = self._build_for(sb)
                b = build if build is not None else \
                    _empty_like(self.children[1 - stream_side].schema)
                if self.build_side == "right":
                    yield from self._join_pair(sb, b, "right")
                else:
                    yield from self._join_pair(b, sb, "left")

        return [run(it) for it in sits]


def _empty_like(schema: Schema) -> DeviceBatch:
    """A 0-row device batch (for outer joins against an empty side)."""
    from spark_rapids_tpu.columnar.batch import from_arrow
    import pyarrow as pa
    t = pa.Table.from_arrays(
        [pa.array([], type=f.dtype.to_arrow()) for f in schema.fields],
        names=schema.names)
    return from_arrow(t)


class _NestedLoopBase(TpuExec):
    """Shared cross-product kernel (Table.crossJoin + filter analog)."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 condition: Optional[ir.Expression], schema: Schema):
        super().__init__()
        self.children = (left, right)
        self.condition = condition
        self._schema = schema
        self._kernels = {}

    @property
    def schema(self) -> Schema:
        return self._schema

    def _cross_pair(self, left: DeviceBatch, right: DeviceBatch):
        nl, nr = int(left.num_rows), int(right.num_rows)
        if nl == 0 or nr == 0:
            return
        from spark_rapids_tpu.exec import kernel_cache as kc
        # same dispatch-boundary canonicalization as the hash joins:
        # the kernel builds its output with positional names (the
        # condition reads by ordinal), the real schema restamps after
        left = _canon_side(left, "__l")
        right = _canon_side(right, "__r")
        n_out = left.num_cols + right.num_cols
        out_cap = bucket_rows(nl * nr)
        key = ("cross", out_cap, kc.expr_sig(self.condition),
               _side_key(left), _side_key(right))
        if key not in self._kernels:
            def impl(l, r):
                total = l.num_rows * r.num_rows
                k = jnp.arange(out_cap, dtype=jnp.int64)
                li = jnp.clip(k // jnp.maximum(r.num_rows, 1), 0,
                              l.capacity - 1)
                ri = jnp.clip(k % jnp.maximum(r.num_rows, 1), 0,
                              r.capacity - 1)
                valid = k < total
                cols = [c.gather(li, valid) for c in l.columns] + \
                    [c.gather(ri, valid) for c in r.columns]
                out = DeviceBatch([f"_c{i}" for i in range(n_out)],
                                  cols, total)
                if self.condition is not None:
                    v = eval_tpu.evaluate(self.condition, out)
                    out = compact(out, v.data.astype(jnp.bool_) &
                                  v.validity)
                return out
            self._kernels[key] = kc.get_kernel(key, lambda: impl)
        with timed(self.metrics, "join.nestedLoop"):
            out = self._kernels[key](left, right)
        out = DeviceBatch(self._schema.names, out.columns, out.num_rows)
        self.metrics.add_rows(out.num_rows)
        self.metrics.add_batches()
        yield out


class TpuBroadcastNestedLoopJoinExec(_BroadcastBuildMixin, _NestedLoopBase):
    """Cross join (+ optional condition) with one side broadcast
    (reference: GpuBroadcastNestedLoopJoinExec.scala:311).  The stream
    side keeps its partitioning; the build side is gathered once."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 condition: Optional[ir.Expression], schema: Schema,
                 build_side: str = "right"):
        super().__init__(left, right, condition, schema)
        self._init_build(build_side)

    def execute(self):
        stream_side = 0 if self.build_side == "right" else 1
        sits = self.children[stream_side].execute()

        def run(sit):
            build = self._build()
            if build is None:
                return
            for sb in sit:
                if not int(sb.num_rows):
                    continue
                if stream_side == 0:
                    yield from self._cross_pair(sb, build)
                else:
                    yield from self._cross_pair(build, sb)

        return [run(it) for it in sits]


class TpuCartesianProductExec(_NestedLoopBase):
    """Partition-pairwise cross join: output partition (i, j) crosses left
    partition i with right partition j (reference:
    GpuCartesianProductExec.scala:304 — pairwise cross join with
    serialized-batch RDD)."""

    def execute(self):
        lits = self.children[0].execute()
        rits = self.children[1].execute()
        # right partitions are iterated once per left partition: gather
        # each right partition lazily and cache (the serialized-batch
        # broadcast-to-all-pairs role)
        rcache: dict = {}

        def right_batch(j: int, rit) -> Optional[DeviceBatch]:
            if j not in rcache:
                rcache[j] = _gather_partition(rit)
            return rcache[j]

        def run(i, lit, j, rit):
            left = _gather_partition(lit) if (i, "l") not in rcache else \
                rcache[(i, "l")]
            rcache[(i, "l")] = left
            right = right_batch(j, rit)
            if left is None or right is None:
                return
            yield from self._cross_pair(left, right)

        return [run(i, lit, j, rit)
                for i, lit in enumerate(lits)
                for j, rit in enumerate(rits)]
