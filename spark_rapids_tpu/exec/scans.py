"""Capacity-safe prefix scans for wide (8-byte) dtypes.

TPU emulates 64-bit integers (and x64 floats) as pairs of 32-bit
lanes, and both stock prefix-scan formulations break at capacity
(every number below measured on the bench chip):

- ``jnp.cumsum`` lowers to a pair reduce-window that requests a FIXED
  ~19.09 MiB scoped-VMEM allocation whenever it sits inside ANY
  control flow (lax.scan/cond/fori_loop bodies) — even a 32k-element
  int64 cumsum inside a scan body fails against the 16 MiB scoped
  limit, while the same op at top level compiles.
- ``lax.associative_scan`` compiles in every context, but at full
  capacity its log2(n) split recursion explodes compile time
  (4M int64: 1107 s).

The blocked form threads the needle: a ``lax.scan`` over fixed-size
blocks whose body runs ONE block-sized ``associative_scan`` and
carries the running prefix — 4M int64 compiles in ~1.5 s and scoped
VMEM stays ~block-sized.

Reference analog: none needed — cudf's prefix scans run on a GPU whose
scratch is not a compile-time-bounded scoped space; this module is the
TPU formulation of the same segmented-reduction building block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_BLOCK = 1 << 15          # per-step scan length


def _to_blocks(x: jnp.ndarray, fill) -> jnp.ndarray:
    n = x.shape[0]
    g = -(-n // _BLOCK)
    pad = g * _BLOCK - n
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((pad,), fill, dtype=x.dtype)])
    return x.reshape(g, _BLOCK)


def cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive 1-D cumsum safe for wide dtypes in any context."""
    if x.dtype.itemsize < 8:
        return jnp.cumsum(x)
    n = x.shape[0]
    if n <= _BLOCK:
        return jax.lax.associative_scan(jnp.add, x)

    def body(carry, row):
        s = jax.lax.associative_scan(jnp.add, row) + carry
        return s[-1], s

    _, rows = jax.lax.scan(body, jnp.zeros((), x.dtype),
                           _to_blocks(x, 0))
    return rows.reshape(-1)[:n]


def seg_scan(op, flags: jnp.ndarray, vals: jnp.ndarray, identity
             ) -> jnp.ndarray:
    """Inclusive SEGMENTED scan: within each run started where ``flags``
    is True, accumulate ``vals`` with the associative ``op`` (whose
    identity element is ``identity`` — callers pre-fill excluded
    positions with it, and block padding uses it).  The value at a
    segment's last position is the segment reduction."""

    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))

    n = vals.shape[0]
    if vals.dtype.itemsize < 8 or n <= _BLOCK:
        _f, s = jax.lax.associative_scan(combine, (flags, vals))
        return s
    fb_ = _to_blocks(flags, True)          # padding starts a new run
    vb_ = _to_blocks(vals, identity)

    def body(carry, xs):
        pf, pv = jax.lax.associative_scan(combine, xs)
        cf = jnp.broadcast_to(carry[0], pf.shape)
        cv = jnp.broadcast_to(carry[1], pv.shape)
        of, ov = combine((cf, cv), (pf, pv))
        return (of[-1], ov[-1]), ov

    init = (jnp.zeros((), jnp.bool_),
            jnp.full((), identity, vals.dtype))
    _, rows = jax.lax.scan(body, init, (fb_, vb_))
    return rows.reshape(-1)[:n]
