"""Capacity-safe prefix scans for wide (8-byte) dtypes, plus the
pipelined scan prefetcher (bounded look-ahead host prep for file
scans — see ScanPrefetcher below).

TPU emulates 64-bit integers (and x64 floats) as pairs of 32-bit
lanes, and both stock prefix-scan formulations break at capacity
(every number below measured on the bench chip):

- ``jnp.cumsum`` lowers to a pair reduce-window that requests a FIXED
  ~19.09 MiB scoped-VMEM allocation whenever it sits inside ANY
  control flow (lax.scan/cond/fori_loop bodies) — even a 32k-element
  int64 cumsum inside a scan body fails against the 16 MiB scoped
  limit, while the same op at top level compiles.
- ``lax.associative_scan`` compiles in every context, but at full
  capacity its log2(n) split recursion explodes compile time
  (4M int64: 1107 s).

The blocked form threads the needle: a ``lax.scan`` over fixed-size
blocks whose body runs ONE block-sized ``associative_scan`` and
carries the running prefix — 4M int64 compiles in ~1.5 s and scoped
VMEM stays ~block-sized.

Reference analog: none needed — cudf's prefix scans run on a GPU whose
scratch is not a compile-time-bounded scoped space; this module is the
TPU formulation of the same segmented-reduction building block.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.obs import trace as obstrace
from spark_rapids_tpu.sched import cancel as _cancel

_BLOCK = 1 << 15          # per-step scan length


@dataclass(frozen=True)
class PrefetchKeys:
    """Span/registry names one ScanPrefetcher instance emits under.

    The prefetcher started life scan-only; the shuffle pipeline reuses
    it (the exchange's bounded look-ahead over reduce partitions) with
    its own name set — ``shuffle.pipeline.prefetch``/``stall`` spans
    and ``shuffle.pipeline.stalls``/``overlapNs`` counters — so traces
    and /metrics keep the two pipelines distinguishable."""

    span_prefetch: str = "scan.prefetch"
    span_stall: str = "scan.prefetchStall"
    prefetch_ns: str = "scan.prefetchNs"
    stalls: str = "scan.prefetchStalls"
    stall_ns: str = "scan.prefetchStallNs"
    overlap_ns: str = "scan.prefetchOverlapNs"
    cat: str = "scan"


SHUFFLE_PIPELINE_KEYS = PrefetchKeys(
    span_prefetch="shuffle.pipeline.prefetch",
    span_stall="shuffle.pipeline.stall",
    prefetch_ns="shuffle.pipeline.prefetchNs",
    stalls="shuffle.pipeline.stalls",
    stall_ns="shuffle.pipeline.stallNs",
    overlap_ns="shuffle.pipeline.overlapNs",
    cat="shuffle")


class ScanPrefetcher:
    """Bounded look-ahead runner for scan host prep.

    Given one thunk per scan batch (each performing host-side prep +
    device upload — e.g. ``io/parquet_fused.prepare_fused`` — and NO
    device->host read, per PERF.md's no-mid-stream-read discipline),
    runs up to ``depth`` of them ahead of the consumer on a small
    thread pool, so batch k+1's footer/page walks and packed-page
    uploads overlap batch k's dispatch-only device decode.

    ``get(i)`` returns thunk i's result exactly once, blocking if it
    isn't ready (counted into ``metrics.extra['scan.prefetchStalls']``
    — a stall means the consumer outran the prepared window).
    Consumers may arrive out of order (partition iterators drain on a
    task pool); an index past the submitted window forces submission
    so no ``get`` can deadlock.  A thunk's exception is re-raised at
    its ``get``.  In-flight prepared-but-unconsumed batches — and so
    the held host artifacts and uploaded page buffers — are bounded by
    ``max(depth, concurrent consumers)``: the forced submissions mean
    a task pool wider than ``depth`` raises the bound to its own
    width (the engine's pool is ``concurrentTpuTasks``, default 2).

    Abandonment safety: if the consumer never drains every index (an
    error mid-query, a short-circuiting collect), ``close()`` — also
    wired as a GC finalizer — cancels undispatched thunks and runs
    ``cleanup`` on every prepared-but-unconsumed result (e.g. closing
    file handles), then shuts the pool down."""

    def __init__(self, thunks: Sequence[Callable[[], object]],
                 depth: int, metrics=None,
                 cleanup: Optional[Callable[[object], None]] = None,
                 labels: Optional[Sequence[str]] = None,
                 keys: Optional[PrefetchKeys] = None,
                 thread_name: str = "scan-prefetch"):
        import concurrent.futures as cf
        import weakref
        self._thunks: List[Callable[[], object]] = list(thunks)
        # per-thunk source labels (file/row-group ids) so stall spans
        # name WHAT stalled — an anonymous stall count makes prefetch
        # tuning guesswork
        self._labels: List[str] = list(labels or ())
        self._depth = max(1, int(depth))
        self._metrics = metrics
        self._keys = keys or PrefetchKeys()
        self._lock = threading.Lock()
        self._futures = {}
        # per-thunk prefetch wall (ns), consumed by get()'s overlap
        # accounting: background work that completed before (or ran
        # beyond) the consumer's arrival is genuinely overlapped time
        self._durs = {}
        self._next = 0
        self._consumed = 0
        self._parts_done = 0
        self._pool: Optional[object] = None
        # cancellation: capture the submitting query's token here (the
        # prefetch pool's threads don't inherit thread-locals) and
        # install it around every thunk — a cancelled query stops
        # prepping/uploading look-ahead batches at the next checkpoint
        self._token = _cancel.current()
        if self._thunks:
            self._pool = cf.ThreadPoolExecutor(
                max_workers=self._depth,
                thread_name_prefix=thread_name)
            # args must not reference self (that would pin it forever)
            self._finalizer = weakref.finalize(
                self, ScanPrefetcher._close_impl, self._lock,
                self._futures, self._pool, cleanup)
            with self._lock:
                self._fill_locked()

    def _span_args(self, i: int) -> dict:
        args = {"batch": i}
        if i < len(self._labels):
            args["src"] = self._labels[i]
        return args

    def _run_thunk(self, i: int):
        """Thunk wrapper: the thread inherits the query's CancelToken,
        and the prefetch work itself shows up in the trace (prep+upload
        of batch i on the prefetch thread) and in the registry's
        prefetch histogram."""
        t0 = time.perf_counter_ns()
        try:
            with _cancel.install(self._token):
                _cancel.check_current()
                return self._thunks[i]()
        finally:
            dur = time.perf_counter_ns() - t0
            with self._lock:
                self._durs[i] = dur
            obstrace.record(self._keys.span_prefetch, t0, dur,
                            cat=self._keys.cat,
                            args=self._span_args(i))
            obsreg.get_registry().observe(self._keys.prefetch_ns, dur)

    def _fill_locked(self) -> None:
        while (self._next < len(self._thunks) and
               len(self._futures) < self._depth):
            i = self._next
            self._next += 1
            self._futures[i] = self._pool.submit(self._run_thunk, i)

    def part_done(self) -> None:
        """Consumer-side completion mark, called once per index from
        the partition iterator's ``finally`` (success OR failure).
        Once every consumer has finished, prepared-but-unconsumed
        results are released deterministically — without waiting for
        the GC finalizer — covering queries that die mid-drain."""
        with self._lock:
            self._parts_done += 1
            done = self._parts_done >= len(self._thunks)
        if done:
            self.close()

    def get(self, i: int):
        _cancel.check_current()   # don't block on a cancelled query
        with self._lock:
            # out-of-order consumer past the window: submit through i
            while self._next <= i:
                j = self._next
                self._next += 1
                self._futures[j] = self._pool.submit(self._run_thunk, j)
            fut = self._futures.pop(i)
        stalled = not fut.done()
        t0 = 0
        if stalled:
            # the consumer outran the prepared window: a stall, timed
            # so the profile shows where the pipeline starved (same
            # name in Metrics.extra and the registry: PrefetchKeys
            # owns it once)
            if self._metrics is not None:
                self._metrics.add_extra(self._keys.stalls, 1)
            obsreg.get_registry().inc(self._keys.stalls)
            t0 = time.perf_counter_ns()
        try:
            return fut.result()
        finally:
            stall_ns = 0
            if stalled:
                stall_ns = time.perf_counter_ns() - t0
                # the stall span names its source (path#rg), so a trace
                # shows WHICH batch the consumer starved on
                obstrace.record(self._keys.span_stall, t0, stall_ns,
                                cat=self._keys.cat,
                                args=self._span_args(i))
                obsreg.get_registry().inc(self._keys.stall_ns, stall_ns)
            with self._lock:
                self._consumed += 1
                dur = self._durs.pop(i, 0)
                self._fill_locked()
                if self._consumed >= len(self._thunks):
                    self._pool.shutdown(wait=False)
            # overlapped time = background prefetch wall the consumer
            # did NOT wait out: a thunk that was ready at get() overlaps
            # in full; a stalled get overlaps only the head start.  This
            # is the pipeline's headline (overlapNs == 0 means the
            # look-ahead bought nothing).
            overlap = dur - stall_ns
            if overlap > 0:
                obsreg.get_registry().inc(self._keys.overlap_ns, overlap)

    @staticmethod
    def _close_impl(lock, futures, pool, cleanup) -> None:
        with lock:
            pending = list(futures.values())
            futures.clear()
        for fut in pending:
            if not fut.cancel() and cleanup is not None:
                try:
                    cleanup(fut.result())
                except Exception:
                    pass   # the thunk itself failed: nothing to clean
        pool.shutdown(wait=False)

    def close(self) -> None:
        """Release prepared-but-unconsumed results (idempotent)."""
        if self._pool is not None:
            self._finalizer()


def _to_blocks(x: jnp.ndarray, fill) -> jnp.ndarray:
    n = x.shape[0]
    g = -(-n // _BLOCK)
    pad = g * _BLOCK - n
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((pad,), fill, dtype=x.dtype)])
    return x.reshape(g, _BLOCK)


def cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive 1-D cumsum safe for wide dtypes in any context."""
    if x.dtype.itemsize < 8:
        return jnp.cumsum(x)
    n = x.shape[0]
    if n <= _BLOCK:
        return jax.lax.associative_scan(jnp.add, x)

    def body(carry, row):
        s = jax.lax.associative_scan(jnp.add, row) + carry
        return s[-1], s

    _, rows = jax.lax.scan(body, jnp.zeros((), x.dtype),
                           _to_blocks(x, 0))
    return rows.reshape(-1)[:n]


def seg_scan(op, flags: jnp.ndarray, vals: jnp.ndarray, identity
             ) -> jnp.ndarray:
    """Inclusive SEGMENTED scan: within each run started where ``flags``
    is True, accumulate ``vals`` with the associative ``op`` (whose
    identity element is ``identity`` — callers pre-fill excluded
    positions with it, and block padding uses it).  The value at a
    segment's last position is the segment reduction."""

    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))

    n = vals.shape[0]
    if vals.dtype.itemsize < 8 or n <= _BLOCK:
        _f, s = jax.lax.associative_scan(combine, (flags, vals))
        return s
    fb_ = _to_blocks(flags, True)          # padding starts a new run
    vb_ = _to_blocks(vals, identity)

    def body(carry, xs):
        pf, pv = jax.lax.associative_scan(combine, xs)
        cf = jnp.broadcast_to(carry[0], pf.shape)
        cv = jnp.broadcast_to(carry[1], pv.shape)
        of, ov = combine((cf, cv), (pf, pv))
        return (of[-1], ov[-1]), ov

    init = (jnp.zeros((), jnp.bool_),
            jnp.full((), identity, vals.dtype))
    _, rows = jax.lax.scan(body, init, (fb_, vb_))
    return rows.reshape(-1)[:n]
