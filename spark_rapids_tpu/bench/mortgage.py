"""MortgageLike: the mortgage-ETL benchmark (fannie-mae-style data).

Reference analog: integration_tests/.../tests/mortgage/MortgageSpark.scala
— performance + acquisition tables, per-loan delinquency aggregation, a
12-month explode/re-aggregate, seller-name normalization join, and the
final acquisition/performance feature join; plus the simple-aggregate
benchmark queries.  Original DataFrame re-expression over dbgen-lite
data (the reference reads real CSV dumps; data shape, not data, is the
point here).
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F


_SELLERS = ["WITMER LLC", "witmer llc", "Witmer Financial",
            "ACME BANK", "Acme Bank NA", "acme",
            "FIRST UNITED", "First United Corp"]
_CANON = {"WITMER LLC": "Witmer", "witmer llc": "Witmer",
          "Witmer Financial": "Witmer", "ACME BANK": "Acme",
          "Acme Bank NA": "Acme", "acme": "Acme",
          "FIRST UNITED": "FirstUnited",
          "First United Corp": "FirstUnited"}


def generate(sf: float = 0.001, seed: int = 0) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)
    n_loans = max(200, int(500_000 * sf))
    quarters = [f"{y}Q{q}" for y in (2000, 2001) for q in range(1, 5)]

    loan_q = rng.integers(0, len(quarters), n_loans)
    acq = pa.table({
        "loan_id": pa.array(np.arange(1, n_loans + 1, dtype=np.int64)),
        "quarter": [quarters[i] for i in loan_q],
        "seller_name": rng.choice(_SELLERS, n_loans).tolist(),
        "orig_channel": rng.choice(["R", "B", "C"], n_loans).tolist(),
        "orig_interest_rate": np.round(rng.uniform(2.0, 9.0, n_loans), 3),
        "orig_upb": pa.array(
            (rng.integers(30, 800, n_loans) * 1000).astype(np.int64)),
        "orig_loan_term": pa.array(
            rng.choice([180, 240, 360], n_loans).astype(np.int32)),
        "dti": pa.array(rng.uniform(5, 60, n_loans),
                        mask=rng.random(n_loans) < 0.05),
        "borrower_credit_score": pa.array(
            rng.integers(450, 850, n_loans).astype(np.int32),
            mask=rng.random(n_loans) < 0.03),
        "first_home_buyer": rng.choice(["Y", "N", "U"],
                                       n_loans).tolist(),
    })

    # performance: ~18 monthly rows per loan with a random delinquency
    # walk; upb amortizes toward zero
    rows_per = 18
    n_perf = n_loans * rows_per
    loan_ids = np.repeat(np.arange(1, n_loans + 1, dtype=np.int64),
                         rows_per)
    month_idx = np.tile(np.arange(rows_per), n_loans)
    base = _dt.date(2000, 1, 1)
    dates = [base + _dt.timedelta(days=int(30.4 * m)) for m in month_idx]
    status = np.maximum(
        0, rng.integers(-6, 4, n_perf) + (month_idx // 6)).astype(
        np.int32)
    upb0 = np.repeat(
        (rng.integers(30, 800, n_loans) * 1000).astype(np.float64),
        rows_per)
    upb = np.round(upb0 * (1 - month_idx / (rows_per * 2.0)), 2)
    upb = np.where(rng.random(n_perf) < 0.02, 0.0, upb)
    perf = pa.table({
        "loan_id": pa.array(loan_ids),
        "quarter": [quarters[loan_q[i - 1]] for i in loan_ids],
        "monthly_reporting_period": pa.array(dates, type=pa.date32()),
        "current_actual_upb": upb,
        "current_loan_delinquency_status": pa.array(status),
        "servicer": rng.choice(_SELLERS, n_perf).tolist(),
        "interest_rate": np.round(rng.uniform(2.0, 9.0, n_perf), 3),
        "loan_age": pa.array(month_idx.astype(np.int32)),
    })
    return {"perf": perf, "acq": acq}


def setup(session, tables: Dict[str, pa.Table]):
    return {k: session.create_dataframe(v, num_partitions=4)
            for k, v in tables.items()}


def name_mapping(session):
    """Seller-name normalization lookup (NameMapping analog)."""
    return session.create_dataframe(pa.table({
        "from_seller_name": list(_CANON.keys()),
        "to_seller_name": list(_CANON.values()),
    }))


def performance_delinquency(t):
    """Per-(quarter, loan) delinquency features + the 12-month window
    re-aggregation (CreatePerformanceDelinquency analog: conditional
    when-aggregates, explode over 12 month offsets, floor/pmod month
    bucketing, left join back)."""
    df = (t["perf"]
          .with_column("period_month",
                       F.month(col("monthly_reporting_period")))
          .with_column("period_year",
                       F.year(col("monthly_reporting_period"))))
    agg = (df.select(
        col("quarter"), col("loan_id"),
        col("current_loan_delinquency_status").alias("status"),
        F.when(col("current_loan_delinquency_status") >= lit(1),
               col("monthly_reporting_period")).otherwise(lit(None))
        .alias("d30"),
        F.when(col("current_loan_delinquency_status") >= lit(3),
               col("monthly_reporting_period")).otherwise(lit(None))
        .alias("d90"),
        F.when(col("current_loan_delinquency_status") >= lit(6),
               col("monthly_reporting_period")).otherwise(lit(None))
        .alias("d180"))
        .group_by("quarter", "loan_id")
        .agg(F.max("status").alias("delinquency_12"),
             F.min("d30").alias("delinquency_30"),
             F.min("d90").alias("delinquency_90"),
             F.min("d180").alias("delinquency_180"))
        .select(col("quarter").alias("a_quarter"),
                col("loan_id").alias("a_loan_id"),
                (col("delinquency_12") >= lit(1)).alias("ever_30"),
                (col("delinquency_12") >= lit(3)).alias("ever_90"),
                (col("delinquency_12") >= lit(6)).alias("ever_180"),
                col("delinquency_30"), col("delinquency_90"),
                col("delinquency_180")))

    joined = (df.select(col("quarter"), col("loan_id"),
                        col("current_loan_delinquency_status")
                        .alias("delinquency_12"),
                        col("current_actual_upb").alias("upb_12"),
                        col("period_month").alias("timestamp_month"),
                        col("period_year").alias("timestamp_year"))
              .join(agg, (col("loan_id") == col("a_loan_id"))
                    & (col("quarter") == col("a_quarter")), how="left"))

    months = 12
    month_y = F.explode(F.array(*[lit(i) for i in range(months)]))
    exploded = joined.with_column("month_y", month_y)
    mody = ((col("timestamp_year") * lit(12) + col("timestamp_month"))
            - lit(24000) - col("month_y"))
    bucketed = (exploded
                .with_column("josh_mody_n",
                             F.floor(mody.cast("double")
                                     / lit(float(months))))
                .group_by("quarter", "loan_id", "josh_mody_n",
                          "ever_30", "ever_90", "ever_180", "month_y")
                .agg(F.max("delinquency_12").alias("max_d12"),
                     F.min("upb_12").alias("min_upb_12")))
    ts_base = (lit(24000)
               + (col("josh_mody_n") * lit(months)).cast("bigint")
               + col("month_y"))
    return (bucketed
            .with_column("timestamp_year",
                         F.floor((ts_base - lit(1)).cast("double")
                                 / lit(12.0)).cast("bigint"))
            .with_column("timestamp_month_tmp",
                         F.pmod(ts_base, lit(12)))
            .with_column("timestamp_month",
                         F.when(col("timestamp_month_tmp") == lit(0),
                                lit(12))
                         .otherwise(col("timestamp_month_tmp")))
            .with_column("delinquency_12",
                         (col("max_d12") > lit(3)).cast("int")
                         + (col("min_upb_12") == lit(0.0)).cast("int"))
            .select("quarter", "loan_id", "timestamp_year",
                    "timestamp_month", "delinquency_12", "ever_30",
                    "ever_90", "ever_180"))


def acquisition(t, session):
    """Acquisition cleanup + seller-name normalization join."""
    return (t["acq"]
            .join(name_mapping(session),
                  col("seller_name") == col("from_seller_name"),
                  how="left")
            .select(col("loan_id").alias("q_loan_id"),
                    col("quarter").alias("q_quarter"),
                    F.coalesce(col("to_seller_name"),
                               col("seller_name")).alias("seller"),
                    col("orig_channel"), col("orig_interest_rate"),
                    col("orig_upb"), col("orig_loan_term"), col("dti"),
                    col("borrower_credit_score"),
                    col("first_home_buyer")))


def run(t, session):
    """The full mortgage ETL: delinquency features joined to cleaned
    acquisition records (CleanAcquisitionPrime analog)."""
    perf = performance_delinquency(t)
    acq = acquisition(t, session)
    return (perf.join(acq, (col("loan_id") == col("q_loan_id"))
                      & (col("quarter") == col("q_quarter")))
            .select("loan_id", "quarter", "timestamp_year",
                    "timestamp_month", "delinquency_12", "ever_30",
                    "ever_90", "ever_180", "seller", "orig_channel",
                    "orig_interest_rate", "orig_upb", "dti",
                    "borrower_credit_score", "first_home_buyer"))


def simple_aggregates(t):
    """Per-quarter portfolio stats (Benchmarks SimpleAggregates
    analog)."""
    loans = (t["perf"].select("quarter", "loan_id").distinct()
             .group_by("quarter").agg(F.count("*").alias("loans"))
             .select(col("quarter").alias("l_quarter"), col("loans")))
    stats = (t["perf"]
             .group_by("quarter")
             .agg(F.avg("interest_rate").alias("avg_rate"),
                  F.sum("current_actual_upb").alias("total_upb"),
                  F.max("current_loan_delinquency_status")
                  .alias("worst_status")))
    return (stats.join(loans, col("quarter") == col("l_quarter"))
            .select("quarter", "loans", "avg_rate", "total_upb",
                    "worst_status")
            .sort("quarter"))


def delinquency_rate(t):
    """Share of ever-90-delinquent loans per quarter."""
    per_loan = (t["perf"]
                .group_by("quarter", "loan_id")
                .agg(F.max("current_loan_delinquency_status")
                     .alias("worst")))
    return (per_loan.group_by("quarter")
            .agg(F.count("*").alias("loans"),
                 F.sum(F.when(col("worst") >= lit(3), lit(1))
                       .otherwise(lit(0))).alias("ever_90"))
            .select(col("quarter"), col("loans"), col("ever_90"),
                    (col("ever_90").cast("double")
                     / col("loans").cast("double")).alias("rate"))
            .sort("quarter"))
