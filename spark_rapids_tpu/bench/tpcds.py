"""TpcdsLike: star schema, dbgen-lite generator, representative queries.

Reference analog: ``integration_tests/.../tests/tpcds/TpcdsLikeSpark.scala``
— like the reference's "Like" suites, the data is not audited dsdgen output
and results are not comparable to official TPC-DS numbers; the queries
exercise the reporting-class operator mix (star joins over date_dim/item/
store/demographics, grouped aggregates, CASE, top-k sorts, window
functions) that dominates the 99-query set.

Queries included (classic single-star reporting subset): q3, q7, q19,
q42, q52, q55, q68-lite, q73, q96, q98 — expressed in the DataFrame API;
q98 exercises windowed revenue ratios.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

TPCDS_TABLES = [
    "date_dim", "time_dim", "item", "store", "customer",
    "customer_address", "customer_demographics",
    "household_demographics", "promotion", "store_sales",
]

_CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
               "Shoes", "Sports", "Women", "Men", "Children"]
_CLASSES = ["class01", "class02", "class03", "class04", "class05"]
_CITIES = ["Midway", "Fairview", "Oakland", "Riverside", "Centerville",
           "Pleasant Hill", "Bunker Hill", "Five Points"]
_STATES = ["CA", "TX", "NY", "WA", "GA", "OH", "IL", "TN"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]


def generate(sf: float = 0.001, seed: int = 0) -> Dict[str, pa.Table]:
    """dbgen-lite star schema at scale factor ``sf``."""
    rng = np.random.default_rng(seed)
    t: Dict[str, pa.Table] = {}

    # -- date_dim: 1998-01-01 .. 2002-12-31, sk = index + 1 ---------------
    start = _dt.date(1998, 1, 1)
    n_days = (_dt.date(2002, 12, 31) - start).days + 1
    days = [start + _dt.timedelta(days=i) for i in range(n_days)]
    t["date_dim"] = pa.table({
        "d_date_sk": pa.array(np.arange(1, n_days + 1, dtype=np.int64)),
        "d_date": pa.array(days, type=pa.date32()),
        "d_year": pa.array(np.array([d.year for d in days],
                                    dtype=np.int32)),
        "d_moy": pa.array(np.array([d.month for d in days],
                                   dtype=np.int32)),
        "d_dom": pa.array(np.array([d.day for d in days],
                                   dtype=np.int32)),
        "d_dow": pa.array(np.array([d.weekday() for d in days],
                                   dtype=np.int32)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in days],
                                   dtype=np.int32)),
    })

    t["time_dim"] = pa.table({
        "t_time_sk": pa.array(np.arange(1, 86401, dtype=np.int64)),
        "t_hour": pa.array((np.arange(86400) // 3600).astype(np.int32)),
        "t_minute": pa.array(((np.arange(86400) % 3600) // 60)
                             .astype(np.int32)),
    })

    ni = max(100, int(18_000 * sf * 10))
    brand_id = rng.integers(1, 1000, ni).astype(np.int32)
    cat_id = rng.integers(0, len(_CATEGORIES), ni)
    manu = rng.integers(1, 1000, ni).astype(np.int32)
    t["item"] = pa.table({
        "i_item_sk": pa.array(np.arange(1, ni + 1, dtype=np.int64)),
        "i_item_id": [f"ITEM{i:012d}" for i in range(1, ni + 1)],
        "i_item_desc": [f"desc of item {i}" for i in range(1, ni + 1)],
        "i_brand_id": pa.array(brand_id),
        "i_brand": [f"brand#{b}" for b in brand_id],
        "i_category_id": pa.array(cat_id.astype(np.int32) + 1),
        "i_category": [_CATEGORIES[c] for c in cat_id],
        "i_class_id": pa.array(
            rng.integers(1, len(_CLASSES) + 1, ni).astype(np.int32)),
        "i_class": rng.choice(_CLASSES, ni).tolist(),
        "i_manufact_id": pa.array(manu),
        # 1..30 (spec uses 1..100) so point filters like q55's
        # i_manager_id = 28 select rows even at tiny scale factors
        "i_manager_id": pa.array(
            rng.integers(1, 31, ni).astype(np.int32)),
        "i_current_price": np.round(rng.uniform(0.1, 100.0, ni), 2),
    })

    ns = max(6, int(12 * sf * 100))
    t["store"] = pa.table({
        "s_store_sk": pa.array(np.arange(1, ns + 1, dtype=np.int64)),
        "s_store_id": [f"STORE{i:06d}" for i in range(1, ns + 1)],
        "s_store_name": [f"store-{i}" for i in range(1, ns + 1)],
        "s_city": rng.choice(_CITIES, ns).tolist(),
        "s_state": rng.choice(_STATES, ns).tolist(),
        "s_zip": [f"{z:05d}" for z in rng.integers(10000, 99999, ns)],
        "s_number_employees": pa.array(
            rng.integers(200, 301, ns).astype(np.int32)),
    })

    ncd = 1000
    t["customer_demographics"] = pa.table({
        "cd_demo_sk": pa.array(np.arange(1, ncd + 1, dtype=np.int64)),
        "cd_gender": rng.choice(["M", "F"], ncd).tolist(),
        "cd_marital_status": rng.choice(
            ["M", "S", "D", "W", "U"], ncd).tolist(),
        "cd_education_status": rng.choice(_EDUCATION, ncd).tolist(),
    })

    nhd = 720
    t["household_demographics"] = pa.table({
        "hd_demo_sk": pa.array(np.arange(1, nhd + 1, dtype=np.int64)),
        "hd_dep_count": pa.array(
            rng.integers(0, 10, nhd).astype(np.int32)),
        "hd_vehicle_count": pa.array(
            rng.integers(-1, 5, nhd).astype(np.int32)),
        "hd_buy_potential": rng.choice(_BUY_POTENTIAL, nhd).tolist(),
    })

    nca = max(50, int(50_000 * sf * 10))
    t["customer_address"] = pa.table({
        "ca_address_sk": pa.array(np.arange(1, nca + 1, dtype=np.int64)),
        "ca_city": rng.choice(_CITIES, nca).tolist(),
        "ca_state": rng.choice(_STATES, nca).tolist(),
        "ca_zip": [f"{z:05d}" for z in rng.integers(10000, 99999, nca)],
        "ca_country": ["United States"] * nca,
    })

    nc = max(100, int(100_000 * sf * 10))
    t["customer"] = pa.table({
        "c_customer_sk": pa.array(np.arange(1, nc + 1, dtype=np.int64)),
        "c_customer_id": [f"CUST{i:012d}" for i in range(1, nc + 1)],
        "c_current_addr_sk": pa.array(
            rng.integers(1, nca + 1, nc).astype(np.int64)),
        "c_current_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, nc).astype(np.int64)),
        "c_current_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, nc).astype(np.int64)),
        "c_first_name": [f"First{i % 977}" for i in range(nc)],
        "c_last_name": [f"Last{i % 653}" for i in range(nc)],
    })

    npromo = 30
    t["promotion"] = pa.table({
        "p_promo_sk": pa.array(np.arange(1, npromo + 1, dtype=np.int64)),
        "p_channel_email": rng.choice(["Y", "N"], npromo,
                                      p=[0.15, 0.85]).tolist(),
        "p_channel_event": rng.choice(["Y", "N"], npromo,
                                      p=[0.15, 0.85]).tolist(),
    })

    nss = max(2000, int(2_880_000 * sf))
    qty = rng.integers(1, 101, nss).astype(np.int32)
    list_price = np.round(rng.uniform(1.0, 200.0, nss), 2)
    sales_price = np.round(list_price * rng.uniform(0.2, 1.0, nss), 2)
    coupon = np.where(rng.random(nss) < 0.1,
                      np.round(sales_price * qty * 0.1, 2), 0.0)
    ext_sales = np.round(sales_price * qty, 2)
    wholesale = np.round(list_price * 0.6, 2)
    t["store_sales"] = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(1, n_days + 1, nss).astype(np.int64)),
        "ss_sold_time_sk": pa.array(
            rng.integers(1, 86401, nss).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(1, ni + 1, nss).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(1, nc + 1, nss).astype(np.int64)),
        "ss_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, nss).astype(np.int64)),
        "ss_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, nss).astype(np.int64)),
        "ss_addr_sk": pa.array(
            rng.integers(1, nca + 1, nss).astype(np.int64)),
        "ss_store_sk": pa.array(
            rng.integers(1, ns + 1, nss).astype(np.int64)),
        "ss_promo_sk": pa.array(
            rng.integers(1, npromo + 1, nss).astype(np.int64)),
        "ss_ticket_number": pa.array(
            rng.integers(1, nss // 3 + 2, nss).astype(np.int64)),
        "ss_quantity": pa.array(qty),
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_ext_sales_price": ext_sales,
        "ss_ext_discount_amt": coupon,
        "ss_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "ss_coupon_amt": coupon,
        "ss_net_profit": np.round(ext_sales - wholesale * qty - coupon,
                                  2),
    })
    return t


def setup(session, tables: Dict[str, pa.Table]):
    return {name: session.create_dataframe(tbl)
            for name, tbl in tables.items()}


# ---------------------------------------------------------------------------
# Queries (validation parameters from the spec templates, simplified to
# this schema subset)
# ---------------------------------------------------------------------------

def q3(t):
    """Brand revenue for manufacturer 1..100 subset in month 11 by year."""
    return (t["date_dim"].filter(col("d_moy") == lit(11))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"].filter(col("i_manufact_id") <= lit(100)),
                  col("ss_item_sk") == col("i_item_sk"))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .select(col("d_year"), col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), col("sum_agg"))
            .sort(col("d_year").asc(), col("sum_agg").desc(),
                  col("brand_id").asc())
            .limit(100))


def q7(t):
    """Item averages for a demographic slice with promo filter."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")))
    promo = t["promotion"].filter(
        (col("p_channel_email") == lit("N"))
        | (col("p_channel_event") == lit("N")))
    return (t["store_sales"]
            .join(cd, col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(promo, col("ss_promo_sk") == col("p_promo_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .group_by("i_item_id")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q19(t):
    """Brand revenue where customer and store are in different zips."""
    return (t["date_dim"].filter((col("d_moy") == lit(11))
                                 & (col("d_year") == lit(1999)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"].filter(col("i_manager_id") <= lit(20)),
                  col("ss_item_sk") == col("i_item_sk"))
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .filter(F.substring(col("ca_zip"), 1, 5)
                    != F.substring(col("s_zip"), 1, 5))
            .group_by("i_brand", "i_brand_id", "i_manufact_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand").alias("brand"),
                    col("i_brand_id").alias("brand_id"),
                    col("i_manufact_id"), col("ext_price"))
            .sort(col("ext_price").desc(), col("brand_id").asc(),
                  col("i_manufact_id").asc())
            .limit(100))


def q42(t):
    """Category revenue for one month/year."""
    return (t["date_dim"].filter((col("d_moy") == lit(11))
                                 & (col("d_year") == lit(2000)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .group_by("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("total"))
            .sort(col("total").desc(), col("d_year").asc(),
                  col("i_category_id").asc(), col("i_category").asc())
            .limit(100))


def q52(t):
    """Brand revenue for one month/year (q42 over brand)."""
    return (t["date_dim"].filter((col("d_moy") == lit(12))
                                 & (col("d_year") == lit(1998)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("d_year"), col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), col("ext_price"))
            .sort(col("d_year").asc(), col("ext_price").desc(),
                  col("brand_id").asc())
            .limit(100))


def q55(t):
    """Brand revenue for one manager's items in one month."""
    return (t["date_dim"].filter((col("d_moy") == lit(11))
                                 & (col("d_year") == lit(1999)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"].filter(col("i_manager_id") == lit(28)),
                  col("ss_item_sk") == col("i_item_sk"))
            .group_by("i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), col("ext_price"))
            .sort(col("ext_price").desc(), col("brand_id").asc())
            .limit(100))


def q68(t):
    """Per-ticket extended-price/ discount/ tax rollup for two cities
    (lite: no tax column, grouped on ticket + customer + city)."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(4))
        | (col("hd_vehicle_count") == lit(3)))
    return (t["store_sales"]
            .join(t["date_dim"].filter(
                col("d_year").isin(1999, 2000)
                & (col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"].filter(
                col("s_city").isin("Midway", "Fairview")),
                col("ss_store_sk") == col("s_store_sk"))
            .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(t["customer_address"],
                  col("ss_addr_sk") == col("ca_address_sk"))
            .group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
            .agg(F.sum("ss_ext_sales_price").alias("extended_price"),
                 F.sum("ss_ext_discount_amt").alias("extended_discount"))
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .select("c_last_name", "c_first_name", "ca_city",
                    "ss_ticket_number", "extended_price",
                    "extended_discount")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(t):
    """Ticket counts per household bucket, 1..5 items per ticket."""
    hd = t["household_demographics"].filter(
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > lit(0)))
    counts = (t["store_sales"]
              .join(t["date_dim"].filter(
                  (col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                  & col("d_year").isin(1999, 2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["store"].filter(
                  col("s_number_employees") >= lit(200)),
                  col("ss_store_sk") == col("s_store_sk"))
              .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
              .group_by("ss_ticket_number", "ss_customer_sk")
              .agg(F.count("*").alias("cnt"))
              .filter((col("cnt") >= lit(1)) & (col("cnt") <= lit(5))))
    return (counts
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .select("c_last_name", "c_first_name", "ss_ticket_number",
                    "cnt")
            .sort(col("cnt").desc(), col("c_last_name").asc())
            .limit(100))


def q96(t):
    """Sales count in a time window for busy households."""
    return (t["store_sales"]
            .join(t["time_dim"].filter((col("t_hour") == lit(20))
                                       & (col("t_minute") >= lit(30))),
                  col("ss_sold_time_sk") == col("t_time_sk"))
            .join(t["household_demographics"].filter(
                col("hd_dep_count") == lit(7)),
                col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(t["store"].filter(col("s_store_name") != lit("")),
                  col("ss_store_sk") == col("s_store_sk"))
            .agg(F.count("*").alias("cnt")))


def q98(t):
    """Item revenue + share of its class's revenue (window)."""
    base = (t["store_sales"]
            .join(t["item"].filter(
                col("i_category").isin("Sports", "Books", "Home")),
                col("ss_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(
                (col("d_date") >= lit(_dt.date(1999, 2, 22)))
                & (col("d_date") <= lit(_dt.date(1999, 3, 24)))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .group_by("i_item_id", "i_item_desc", "i_category",
                      "i_class", "i_current_price")
            .agg(F.sum("ss_ext_sales_price").alias("itemrevenue")))
    return (base.select(
                col("i_item_id"), col("i_item_desc"), col("i_category"),
                col("i_class"), col("i_current_price"),
                col("itemrevenue"),
                (col("itemrevenue") * lit(100.0)
                 / F.sum(col("itemrevenue")).over(
                     Window.partition_by("i_class"))).alias(
                     "revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio"))


QUERIES = {"q3": q3, "q7": q7, "q19": q19, "q42": q42, "q52": q52,
           "q55": q55, "q68": q68, "q73": q73, "q96": q96, "q98": q98}
