"""TpcdsLike: star schema, dbgen-lite generator, representative queries.

Reference analog: ``integration_tests/.../tests/tpcds/TpcdsLikeSpark.scala``
— like the reference's "Like" suites, the data is not audited dsdgen output
and results are not comparable to official TPC-DS numbers; the queries
exercise the reporting-class operator mix (star joins over date_dim/item/
store/demographics, grouped aggregates, CASE, top-k sorts, window
functions) that dominates the 99-query set.

Queries included (classic single-star reporting subset): q3, q7, q19,
q42, q52, q55, q68-lite, q73, q96, q98 — expressed in the DataFrame API;
q98 exercises windowed revenue ratios.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

TPCDS_TABLES = [
    "date_dim", "time_dim", "item", "store", "customer",
    "customer_address", "customer_demographics",
    "household_demographics", "promotion", "store_sales",
    "store_returns", "catalog_sales", "catalog_returns", "web_sales",
    "web_returns", "inventory", "warehouse", "ship_mode", "reason",
    "call_center", "catalog_page", "web_site", "web_page", "income_band",
]

_CATEGORIES = ["Books", "Electronics", "Home", "Jewelry", "Music",
               "Shoes", "Sports", "Women", "Men", "Children"]
_CLASSES = ["class01", "class02", "class03", "class04", "class05"]
_CITIES = ["Midway", "Fairview", "Oakland", "Riverside", "Centerville",
           "Pleasant Hill", "Bunker Hill", "Five Points"]
_COUNTIES = ["Williamson County", "Ziebach County", "Walker County",
             "Daviess County", "Barrow County", "Luce County",
             "Richland County", "Bronx County"]
_COUNTRIES = ["United States", "Canada", "Mexico", "Germany", "Japan",
              "Brazil", "India", "France"]
_STATES = ["CA", "TX", "NY", "WA", "GA", "OH", "IL", "TN"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]


def generate(sf: float = 0.001, seed: int = 0) -> Dict[str, pa.Table]:
    """dbgen-lite star schema at scale factor ``sf``."""
    rng = np.random.default_rng(seed)
    t: Dict[str, pa.Table] = {}

    # -- date_dim: 1998-01-01 .. 2002-12-31, sk = index + 1 ---------------
    start = _dt.date(1998, 1, 1)
    n_days = (_dt.date(2002, 12, 31) - start).days + 1
    days = [start + _dt.timedelta(days=i) for i in range(n_days)]
    epoch_week = (start - _dt.date(1995, 1, 2)).days // 7
    day_names = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
                 "Saturday", "Sunday"]
    t["date_dim"] = pa.table({
        "d_date_sk": pa.array(np.arange(1, n_days + 1, dtype=np.int64)),
        "d_date": pa.array(days, type=pa.date32()),
        "d_year": pa.array(np.array([d.year for d in days],
                                    dtype=np.int32)),
        "d_moy": pa.array(np.array([d.month for d in days],
                                   dtype=np.int32)),
        "d_dom": pa.array(np.array([d.day for d in days],
                                   dtype=np.int32)),
        "d_dow": pa.array(np.array([d.weekday() for d in days],
                                   dtype=np.int32)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in days],
                                   dtype=np.int32)),
        "d_week_seq": pa.array(np.array(
            [epoch_week + (d - start).days // 7 for d in days],
            dtype=np.int32)),
        "d_month_seq": pa.array(np.array(
            [(d.year - 1990) * 12 + d.month - 1 for d in days],
            dtype=np.int32)),
        "d_day_name": [day_names[d.weekday()] for d in days],
        "d_quarter_name": [f"{d.year}Q{(d.month - 1) // 3 + 1}"
                           for d in days],
    })

    meal = np.full(86400, "", dtype=object)
    hours = np.arange(86400) // 3600
    meal[(hours >= 6) & (hours < 9)] = "breakfast"
    meal[(hours >= 17) & (hours < 21)] = "dinner"
    t["time_dim"] = pa.table({
        "t_time_sk": pa.array(np.arange(1, 86401, dtype=np.int64)),
        "t_time": pa.array(np.arange(86400).astype(np.int32)),
        "t_hour": pa.array(hours.astype(np.int32)),
        "t_minute": pa.array(((np.arange(86400) % 3600) // 60)
                             .astype(np.int32)),
        "t_meal_time": meal.tolist(),
    })

    ni = max(100, int(18_000 * sf * 10))
    brand_id = rng.integers(1, 1000, ni).astype(np.int32)
    cat_id = rng.integers(0, len(_CATEGORIES), ni)
    manu = rng.integers(1, 1000, ni).astype(np.int32)
    t["item"] = pa.table({
        "i_item_sk": pa.array(np.arange(1, ni + 1, dtype=np.int64)),
        "i_item_id": [f"ITEM{i:012d}" for i in range(1, ni + 1)],
        "i_item_desc": [f"desc of item {i}" for i in range(1, ni + 1)],
        "i_brand_id": pa.array(brand_id),
        "i_brand": [f"brand#{b}" for b in brand_id],
        "i_category_id": pa.array(cat_id.astype(np.int32) + 1),
        "i_category": [_CATEGORIES[c] for c in cat_id],
        "i_class_id": pa.array(
            rng.integers(1, len(_CLASSES) + 1, ni).astype(np.int32)),
        "i_class": rng.choice(_CLASSES, ni).tolist(),
        "i_manufact_id": pa.array(manu),
        "i_manufact": [f"manufact#{m}" for m in manu],
        # 1..30 (spec uses 1..100) so point filters like q55's
        # i_manager_id = 28 select rows even at tiny scale factors
        "i_manager_id": pa.array(
            rng.integers(1, 31, ni).astype(np.int32)),
        "i_current_price": np.round(rng.uniform(0.1, 100.0, ni), 2),
        "i_wholesale_cost": np.round(rng.uniform(0.1, 80.0, ni), 2),
        "i_size": rng.choice(["small", "medium", "large", "extra large",
                              "economy", "N/A", "petite"], ni).tolist(),
        "i_color": rng.choice(["red", "blue", "green", "white", "black",
                               "ivory", "almond", "navy", "plum",
                               "indian", "khaki"], ni).tolist(),
        "i_units": rng.choice(["Each", "Dozen", "Case", "Pound", "Ton",
                               "Oz", "Pallet"], ni).tolist(),
        "i_product_name": [f"product{i}" for i in range(1, ni + 1)],
    })

    ns = max(6, int(12 * sf * 100))
    t["store"] = pa.table({
        "s_store_sk": pa.array(np.arange(1, ns + 1, dtype=np.int64)),
        "s_store_id": [f"STORE{i:06d}" for i in range(1, ns + 1)],
        "s_store_name": [f"store-{i}" for i in range(1, ns + 1)],
        "s_city": rng.choice(_CITIES, ns).tolist(),
        "s_county": rng.choice(_COUNTIES, ns).tolist(),
        "s_state": rng.choice(_STATES, ns).tolist(),
        "s_zip": [f"{z:05d}" for z in rng.integers(10000, 99999, ns)],
        "s_number_employees": pa.array(
            rng.integers(200, 301, ns).astype(np.int32)),
        "s_floor_space": pa.array(
            rng.integers(5_000_000, 10_000_000, ns).astype(np.int32)),
        "s_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], ns),
        "s_market_id": pa.array(rng.integers(1, 11, ns).astype(np.int32)),
        "s_company_name": ["Unknown"] * ns,
    })

    ncd = 1000
    t["customer_demographics"] = pa.table({
        "cd_demo_sk": pa.array(np.arange(1, ncd + 1, dtype=np.int64)),
        "cd_gender": rng.choice(["M", "F"], ncd).tolist(),
        "cd_marital_status": rng.choice(
            ["M", "S", "D", "W", "U"], ncd).tolist(),
        "cd_education_status": rng.choice(_EDUCATION, ncd).tolist(),
        "cd_purchase_estimate": pa.array(
            (rng.integers(1, 21, ncd) * 500).astype(np.int32)),
        "cd_credit_rating": rng.choice(
            ["Good", "Low Risk", "High Risk", "Unknown"], ncd).tolist(),
        "cd_dep_count": pa.array(rng.integers(0, 7, ncd).astype(np.int32)),
        "cd_dep_employed_count": pa.array(
            rng.integers(0, 7, ncd).astype(np.int32)),
        "cd_dep_college_count": pa.array(
            rng.integers(0, 7, ncd).astype(np.int32)),
    })

    nib = 20
    t["income_band"] = pa.table({
        "ib_income_band_sk": pa.array(np.arange(1, nib + 1,
                                                dtype=np.int64)),
        "ib_lower_bound": pa.array(
            (np.arange(nib) * 10000).astype(np.int32)),
        "ib_upper_bound": pa.array(
            ((np.arange(nib) + 1) * 10000).astype(np.int32)),
    })

    nhd = 720
    t["household_demographics"] = pa.table({
        "hd_demo_sk": pa.array(np.arange(1, nhd + 1, dtype=np.int64)),
        "hd_income_band_sk": pa.array(
            rng.integers(1, nib + 1, nhd).astype(np.int64)),
        "hd_dep_count": pa.array(
            rng.integers(0, 10, nhd).astype(np.int32)),
        "hd_vehicle_count": pa.array(
            rng.integers(-1, 5, nhd).astype(np.int32)),
        "hd_buy_potential": rng.choice(_BUY_POTENTIAL, nhd).tolist(),
    })

    nca = max(50, int(50_000 * sf * 10))
    t["customer_address"] = pa.table({
        "ca_address_sk": pa.array(np.arange(1, nca + 1, dtype=np.int64)),
        "ca_city": rng.choice(_CITIES, nca).tolist(),
        "ca_county": rng.choice(_COUNTIES, nca).tolist(),
        "ca_state": rng.choice(_STATES, nca).tolist(),
        "ca_zip": [f"{z:05d}" for z in rng.integers(10000, 99999, nca)],
        "ca_country": ["United States"] * nca,
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], nca),
        "ca_location_type": rng.choice(
            ["condo", "apartment", "single family"], nca).tolist(),
    })

    nc = max(100, int(100_000 * sf * 10))
    t["customer"] = pa.table({
        "c_customer_sk": pa.array(np.arange(1, nc + 1, dtype=np.int64)),
        "c_customer_id": [f"CUST{i:012d}" for i in range(1, nc + 1)],
        "c_current_addr_sk": pa.array(
            rng.integers(1, nca + 1, nc).astype(np.int64)),
        "c_current_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, nc).astype(np.int64)),
        "c_current_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, nc).astype(np.int64)),
        "c_first_name": [f"First{i % 977}" for i in range(nc)],
        "c_last_name": [f"Last{i % 653}" for i in range(nc)],
        "c_preferred_cust_flag": rng.choice(["Y", "N"], nc).tolist(),
        "c_birth_year": pa.array(
            rng.integers(1924, 1993, nc).astype(np.int32)),
        "c_birth_month": pa.array(
            rng.integers(1, 13, nc).astype(np.int32)),
        "c_birth_day": pa.array(
            rng.integers(1, 29, nc).astype(np.int32)),
        "c_birth_country": rng.choice(_COUNTRIES, nc).tolist(),
        "c_salutation": rng.choice(
            ["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"], nc).tolist(),
        "c_email_address": [f"c{i}@example.com" for i in range(nc)],
        "c_first_sales_date_sk": pa.array(
            rng.integers(1, n_days + 1, nc).astype(np.int64)),
        "c_first_shipto_date_sk": pa.array(
            rng.integers(1, n_days + 1, nc).astype(np.int64)),
    })

    npromo = 30
    t["promotion"] = pa.table({
        "p_promo_sk": pa.array(np.arange(1, npromo + 1, dtype=np.int64)),
        "p_promo_id": [f"PROMO{i:08d}" for i in range(1, npromo + 1)],
        "p_promo_name": [f"promo-{i}" for i in range(1, npromo + 1)],
        "p_channel_email": rng.choice(["Y", "N"], npromo,
                                      p=[0.15, 0.85]).tolist(),
        "p_channel_event": rng.choice(["Y", "N"], npromo,
                                      p=[0.15, 0.85]).tolist(),
        "p_channel_dmail": rng.choice(["Y", "N"], npromo,
                                      p=[0.5, 0.5]).tolist(),
        "p_channel_tv": rng.choice(["Y", "N"], npromo,
                                   p=[0.15, 0.85]).tolist(),
    })

    nss = max(2000, int(2_880_000 * sf))
    qty = rng.integers(1, 101, nss).astype(np.int32)
    list_price = np.round(rng.uniform(1.0, 200.0, nss), 2)
    sales_price = np.round(list_price * rng.uniform(0.2, 1.0, nss), 2)
    coupon = np.where(rng.random(nss) < 0.1,
                      np.round(sales_price * qty * 0.1, 2), 0.0)
    ext_sales = np.round(sales_price * qty, 2)
    wholesale = np.round(list_price * 0.6, 2)
    t["store_sales"] = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(1, n_days + 1, nss).astype(np.int64)),
        "ss_sold_time_sk": pa.array(
            rng.integers(1, 86401, nss).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(1, ni + 1, nss).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(1, nc + 1, nss).astype(np.int64)),
        "ss_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, nss).astype(np.int64)),
        "ss_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, nss).astype(np.int64)),
        # ~4% null addresses (q76-class queries probe null fk buckets)
        "ss_addr_sk": pa.array(
            rng.integers(1, nca + 1, nss).astype(np.int64),
            mask=rng.random(nss) < 0.04),
        "ss_store_sk": pa.array(
            rng.integers(1, ns + 1, nss).astype(np.int64)),
        "ss_promo_sk": pa.array(
            rng.integers(1, npromo + 1, nss).astype(np.int64)),
        "ss_ticket_number": pa.array(
            rng.integers(1, nss // 3 + 2, nss).astype(np.int64)),
        "ss_quantity": pa.array(qty),
        "ss_wholesale_cost": wholesale,
        "ss_list_price": list_price,
        "ss_sales_price": sales_price,
        "ss_ext_sales_price": ext_sales,
        "ss_ext_list_price": np.round(list_price * qty, 2),
        "ss_ext_discount_amt": coupon,
        "ss_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "ss_ext_tax": np.round(ext_sales * 0.08, 2),
        "ss_coupon_amt": coupon,
        "ss_net_paid": np.round(ext_sales - coupon, 2),
        "ss_net_profit": np.round(ext_sales - wholesale * qty - coupon,
                                  2),
    })

    # -- store_returns: ~10% of store_sales rows, correlated on
    # (ticket, item, customer) so returns join back to their sale --------
    nsr = max(200, nss // 10)
    ridx = rng.choice(nss, nsr, replace=False)
    r_qty = np.minimum(qty[ridx],
                       rng.integers(1, 101, nsr).astype(np.int32))
    r_amt = np.round(sales_price[ridx] * r_qty, 2)
    ss = t["store_sales"]
    t["store_returns"] = pa.table({
        "sr_returned_date_sk": pa.array(np.minimum(
            np.asarray(ss.column("ss_sold_date_sk"))[ridx]
            + rng.integers(1, 60, nsr), n_days).astype(np.int64)),
        "sr_return_time_sk": pa.array(
            rng.integers(1, 86401, nsr).astype(np.int64)),
        "sr_item_sk": pa.array(
            np.asarray(ss.column("ss_item_sk"))[ridx]),
        "sr_customer_sk": pa.array(
            np.asarray(ss.column("ss_customer_sk"))[ridx]),
        "sr_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, nsr).astype(np.int64)),
        "sr_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, nsr).astype(np.int64)),
        "sr_addr_sk": pa.array(
            rng.integers(1, nca + 1, nsr).astype(np.int64)),
        "sr_store_sk": pa.array(
            np.asarray(ss.column("ss_store_sk"))[ridx]),
        "sr_reason_sk": pa.array(
            rng.integers(1, 36, nsr).astype(np.int64)),
        "sr_ticket_number": pa.array(
            np.asarray(ss.column("ss_ticket_number"))[ridx]),
        "sr_return_quantity": pa.array(r_qty),
        "sr_return_amt": r_amt,
        "sr_return_tax": np.round(r_amt * 0.08, 2),
        "sr_return_amt_inc_tax": np.round(r_amt * 1.08, 2),
        "sr_fee": np.round(rng.uniform(0.5, 100.0, nsr), 2),
        "sr_return_ship_cost": np.round(rng.uniform(0, 30.0, nsr), 2),
        "sr_refunded_cash": np.round(r_amt * 0.7, 2),
        "sr_reversed_charge": np.round(r_amt * 0.2, 2),
        "sr_store_credit": np.round(r_amt * 0.1, 2),
        "sr_net_loss": np.round(r_amt * 0.1
                                + rng.uniform(0.5, 50.0, nsr), 2),
    })

    # -- catalog channel --------------------------------------------------
    ncc = 6
    t["call_center"] = pa.table({
        "cc_call_center_sk": pa.array(np.arange(1, ncc + 1,
                                                dtype=np.int64)),
        "cc_call_center_id": [f"CC{i:06d}" for i in range(1, ncc + 1)],
        "cc_name": [f"call center {i}" for i in range(1, ncc + 1)],
        "cc_manager": [f"Manager{i}" for i in range(1, ncc + 1)],
        "cc_county": rng.choice(_COUNTIES, ncc).tolist(),
    })

    ncp = 100
    t["catalog_page"] = pa.table({
        "cp_catalog_page_sk": pa.array(np.arange(1, ncp + 1,
                                                 dtype=np.int64)),
        "cp_catalog_page_id": [f"CP{i:08d}" for i in range(1, ncp + 1)],
    })

    nwh = 5
    t["warehouse"] = pa.table({
        "w_warehouse_sk": pa.array(np.arange(1, nwh + 1, dtype=np.int64)),
        "w_warehouse_name": [f"Warehouse {i}" for i in range(1, nwh + 1)],
        "w_warehouse_sq_ft": pa.array(
            rng.integers(50_000, 1_000_000, nwh).astype(np.int32)),
        "w_city": rng.choice(_CITIES, nwh).tolist(),
        "w_county": rng.choice(_COUNTIES, nwh).tolist(),
        "w_state": rng.choice(_STATES, nwh).tolist(),
        "w_country": ["United States"] * nwh,
    })

    nsm = 20
    t["ship_mode"] = pa.table({
        "sm_ship_mode_sk": pa.array(np.arange(1, nsm + 1,
                                              dtype=np.int64)),
        "sm_type": rng.choice(["EXPRESS", "NEXT DAY", "OVERNIGHT",
                               "REGULAR", "TWO DAY", "LIBRARY"],
                              nsm).tolist(),
        "sm_carrier": rng.choice(["UPS", "FEDEX", "AIRBORNE", "USPS",
                                  "DHL", "TBS"], nsm).tolist(),
        "sm_code": rng.choice(["AIR", "SURFACE", "SEA"], nsm).tolist(),
    })

    nreason = 35
    t["reason"] = pa.table({
        "r_reason_sk": pa.array(np.arange(1, nreason + 1,
                                          dtype=np.int64)),
        "r_reason_desc": [f"reason {i}" for i in range(1, nreason + 1)],
    })

    def _sales_channel(prefix: str, nrows: int, order_div: int,
                       extra: Dict[str, pa.Array]) -> pa.Table:
        """Shared generator for catalog_sales/web_sales columns."""
        q2 = rng.integers(1, 101, nrows).astype(np.int32)
        lp2 = np.round(rng.uniform(1.0, 200.0, nrows), 2)
        sp2 = np.round(lp2 * rng.uniform(0.2, 1.0, nrows), 2)
        ws2 = np.round(lp2 * 0.6, 2)
        ext2 = np.round(sp2 * q2, 2)
        disc = np.where(rng.random(nrows) < 0.1,
                        np.round(ext2 * 0.1, 2), 0.0)
        sold = rng.integers(1, n_days + 1, nrows).astype(np.int64)
        cols = {
            f"{prefix}_sold_date_sk": pa.array(sold),
            f"{prefix}_sold_time_sk": pa.array(
                rng.integers(1, 86401, nrows).astype(np.int64)),
            f"{prefix}_ship_date_sk": pa.array(np.minimum(
                sold + rng.integers(1, 121, nrows), n_days)
                .astype(np.int64)),
            f"{prefix}_item_sk": pa.array(
                rng.integers(1, ni + 1, nrows).astype(np.int64)),
            f"{prefix}_order_number": pa.array(
                rng.integers(1, nrows // order_div + 2, nrows)
                .astype(np.int64)),
            f"{prefix}_quantity": pa.array(q2),
            f"{prefix}_wholesale_cost": ws2,
            f"{prefix}_list_price": lp2,
            f"{prefix}_sales_price": sp2,
            f"{prefix}_ext_discount_amt": disc,
            f"{prefix}_ext_sales_price": ext2,
            f"{prefix}_ext_wholesale_cost": np.round(ws2 * q2, 2),
            f"{prefix}_ext_list_price": np.round(lp2 * q2, 2),
            f"{prefix}_ext_ship_cost": np.round(
                rng.uniform(0, 25.0, nrows) * q2, 2),
            f"{prefix}_net_paid": np.round(ext2 - disc, 2),
            f"{prefix}_net_profit": np.round(ext2 - ws2 * q2 - disc, 2),
            f"{prefix}_coupon_amt": disc,
            f"{prefix}_promo_sk": pa.array(
                rng.integers(1, npromo + 1, nrows).astype(np.int64)),
            f"{prefix}_warehouse_sk": pa.array(
                rng.integers(1, nwh + 1, nrows).astype(np.int64)),
            f"{prefix}_ship_mode_sk": pa.array(
                rng.integers(1, nsm + 1, nrows).astype(np.int64)),
        }
        cols.update(extra)
        return pa.table(cols)

    ncs = max(1500, int(1_440_000 * sf))
    t["catalog_sales"] = _sales_channel("cs", ncs, 4, {
        "cs_bill_customer_sk": pa.array(
            rng.integers(1, nc + 1, ncs).astype(np.int64)),
        "cs_bill_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, ncs).astype(np.int64)),
        "cs_bill_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, ncs).astype(np.int64)),
        "cs_bill_addr_sk": pa.array(
            rng.integers(1, nca + 1, ncs).astype(np.int64)),
        "cs_ship_customer_sk": pa.array(
            rng.integers(1, nc + 1, ncs).astype(np.int64)),
        "cs_ship_addr_sk": pa.array(
            rng.integers(1, nca + 1, ncs).astype(np.int64),
            mask=rng.random(ncs) < 0.04),
        "cs_call_center_sk": pa.array(
            rng.integers(1, ncc + 1, ncs).astype(np.int64)),
        "cs_catalog_page_sk": pa.array(
            rng.integers(1, ncp + 1, ncs).astype(np.int64)),
    })

    nws = max(1000, int(720_000 * sf))
    t["web_sales"] = _sales_channel("ws", nws, 4, {
        "ws_bill_customer_sk": pa.array(
            rng.integers(1, nc + 1, nws).astype(np.int64)),
        "ws_bill_cdemo_sk": pa.array(
            rng.integers(1, ncd + 1, nws).astype(np.int64)),
        "ws_bill_hdemo_sk": pa.array(
            rng.integers(1, nhd + 1, nws).astype(np.int64)),
        "ws_bill_addr_sk": pa.array(
            rng.integers(1, nca + 1, nws).astype(np.int64)),
        "ws_ship_customer_sk": pa.array(
            rng.integers(1, nc + 1, nws).astype(np.int64),
            mask=rng.random(nws) < 0.04),
        "ws_ship_addr_sk": pa.array(
            rng.integers(1, nca + 1, nws).astype(np.int64)),
        "ws_web_site_sk": pa.array(
            rng.integers(1, 13, nws).astype(np.int64)),
        "ws_web_page_sk": pa.array(
            rng.integers(1, 61, nws).astype(np.int64)),
    })

    def _returns(prefix: str, sales: pa.Table, sprefix: str,
                 extra_fn) -> pa.Table:
        nr = max(150, sales.num_rows // 10)
        idx = rng.choice(sales.num_rows, nr, replace=False)
        rq = np.minimum(np.asarray(sales.column(f"{sprefix}_quantity"))[idx],
                        rng.integers(1, 101, nr).astype(np.int32))
        ra = np.round(
            np.asarray(sales.column(f"{sprefix}_sales_price"))[idx] * rq, 2)
        cols = {
            f"{prefix}_returned_date_sk": pa.array(np.minimum(
                np.asarray(sales.column(f"{sprefix}_sold_date_sk"))[idx]
                + rng.integers(1, 60, nr), n_days).astype(np.int64)),
            f"{prefix}_item_sk": pa.array(
                np.asarray(sales.column(f"{sprefix}_item_sk"))[idx]),
            f"{prefix}_order_number": pa.array(
                np.asarray(sales.column(f"{sprefix}_order_number"))[idx]),
            f"{prefix}_return_quantity": pa.array(rq),
            f"{prefix}_reason_sk": pa.array(
                rng.integers(1, nreason + 1, nr).astype(np.int64)),
            f"{prefix}_refunded_cash": np.round(ra * 0.7, 2),
            f"{prefix}_reversed_charge": np.round(ra * 0.2, 2),
            f"{prefix}_net_loss": np.round(
                ra * 0.1 + rng.uniform(0.5, 50.0, nr), 2),
            f"{prefix}_fee": np.round(rng.uniform(0.5, 100.0, nr), 2),
        }
        cols.update(extra_fn(idx, nr, ra))
        return pa.table(cols)

    # correlate ~1/3 of catalog orders with store-returned (customer,
    # item) pairs so cross-channel repurchase chains (q17/q25/q29/q64)
    # select rows even at tiny scale factors
    sr_cust = np.asarray(t["store_returns"].column("sr_customer_sk"))
    sr_item = np.asarray(t["store_returns"].column("sr_item_sk"))
    n_corr = min(nsr, ncs // 3)
    corr_rows = rng.choice(ncs, n_corr, replace=False)
    pick = rng.integers(0, nsr, n_corr)
    cs_tbl = t["catalog_sales"]
    bill = np.asarray(cs_tbl.column("cs_bill_customer_sk")).copy()
    citem = np.asarray(cs_tbl.column("cs_item_sk")).copy()
    bill[corr_rows] = sr_cust[pick]
    citem[corr_rows] = sr_item[pick]
    cs_tbl = cs_tbl.set_column(
        cs_tbl.column_names.index("cs_bill_customer_sk"),
        "cs_bill_customer_sk", pa.array(bill))
    t["catalog_sales"] = cs_tbl.set_column(
        cs_tbl.column_names.index("cs_item_sk"), "cs_item_sk",
        pa.array(citem))

    t["catalog_returns"] = _returns("cr", t["catalog_sales"], "cs",
        lambda idx, nr, ra: {
            "cr_return_amount": ra,
            "cr_return_amt_inc_tax": np.round(ra * 1.08, 2),
            "cr_returning_customer_sk": pa.array(
                rng.integers(1, nc + 1, nr).astype(np.int64)),
            "cr_refunded_customer_sk": pa.array(np.asarray(
                t["catalog_sales"].column("cs_bill_customer_sk"))[idx]),
            "cr_call_center_sk": pa.array(
                rng.integers(1, ncc + 1, nr).astype(np.int64)),
            "cr_catalog_page_sk": pa.array(
                rng.integers(1, ncp + 1, nr).astype(np.int64)),
            "cr_warehouse_sk": pa.array(
                rng.integers(1, nwh + 1, nr).astype(np.int64)),
            "cr_store_credit": np.round(ra * 0.1, 2),
        })

    t["web_returns"] = _returns("wr", t["web_sales"], "ws",
        lambda idx, nr, ra: {
            "wr_return_amt": ra,
            "wr_return_amt_inc_tax": np.round(ra * 1.08, 2),
            "wr_returning_customer_sk": pa.array(
                rng.integers(1, nc + 1, nr).astype(np.int64)),
            "wr_refunded_customer_sk": pa.array(np.asarray(
                t["web_sales"].column("ws_bill_customer_sk"))[idx]),
            "wr_refunded_cdemo_sk": pa.array(
                rng.integers(1, ncd + 1, nr).astype(np.int64)),
            "wr_returning_cdemo_sk": pa.array(
                rng.integers(1, ncd + 1, nr).astype(np.int64)),
            "wr_refunded_addr_sk": pa.array(
                rng.integers(1, nca + 1, nr).astype(np.int64)),
            "wr_web_page_sk": pa.array(
                rng.integers(1, 61, nr).astype(np.int64)),
        })

    nwsite = 12
    t["web_site"] = pa.table({
        "web_site_sk": pa.array(np.arange(1, nwsite + 1,
                                          dtype=np.int64)),
        "web_site_id": [f"WEB{i:06d}" for i in range(1, nwsite + 1)],
        "web_name": [f"site-{i}" for i in range(1, nwsite + 1)],
        "web_company_name": rng.choice(["pri", "able", "ese", "anti",
                                        "cally"], nwsite).tolist(),
    })

    nwp = 60
    t["web_page"] = pa.table({
        "wp_web_page_sk": pa.array(np.arange(1, nwp + 1,
                                             dtype=np.int64)),
        "wp_char_count": pa.array(
            rng.integers(100, 8000, nwp).astype(np.int32)),
    })

    # -- inventory: weekly snapshots (every 7th date) ---------------------
    inv_dates = np.arange(1, n_days + 1, 7, dtype=np.int64)
    inv_items = np.arange(1, ni + 1, dtype=np.int64)
    n_inv = len(inv_dates) * nwh
    # one row per (week, warehouse) x a sampled item subset bounds size
    items_per = min(ni, max(20, int(200 * sf * 100)))
    di, wi = np.meshgrid(inv_dates, np.arange(1, nwh + 1,
                                              dtype=np.int64))
    di, wi = di.ravel(), wi.ravel()
    reps = len(di)
    inv_item = rng.choice(inv_items, (reps, items_per))
    t["inventory"] = pa.table({
        "inv_date_sk": pa.array(np.repeat(di, items_per)),
        "inv_warehouse_sk": pa.array(np.repeat(wi, items_per)),
        "inv_item_sk": pa.array(inv_item.ravel()),
        "inv_quantity_on_hand": pa.array(
            rng.integers(0, 1000, reps * items_per).astype(np.int32)),
    })
    return t


def setup(session, tables: Dict[str, pa.Table]):
    return {name: session.create_dataframe(tbl)
            for name, tbl in tables.items()}


# ---------------------------------------------------------------------------
# Queries (validation parameters from the spec templates, simplified to
# this schema subset)
# ---------------------------------------------------------------------------

def q3(t):
    """Brand revenue for manufacturer 1..100 subset in month 11 by year."""
    return (t["date_dim"].filter(col("d_moy") == lit(11))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"].filter(col("i_manufact_id") <= lit(100)),
                  col("ss_item_sk") == col("i_item_sk"))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("sum_agg"))
            .select(col("d_year"), col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), col("sum_agg"))
            .sort(col("d_year").asc(), col("sum_agg").desc(),
                  col("brand_id").asc())
            .limit(100))


def q7(t):
    """Item averages for a demographic slice with promo filter."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")))
    promo = t["promotion"].filter(
        (col("p_channel_email") == lit("N"))
        | (col("p_channel_event") == lit("N")))
    return (t["store_sales"]
            .join(cd, col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(promo, col("ss_promo_sk") == col("p_promo_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .group_by("i_item_id")
            .agg(F.avg("ss_quantity").alias("agg1"),
                 F.avg("ss_list_price").alias("agg2"),
                 F.avg("ss_coupon_amt").alias("agg3"),
                 F.avg("ss_sales_price").alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q19(t):
    """Brand revenue where customer and store are in different zips."""
    return (t["date_dim"].filter((col("d_moy") == lit(11))
                                 & (col("d_year") == lit(1999)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"].filter(col("i_manager_id") <= lit(20)),
                  col("ss_item_sk") == col("i_item_sk"))
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .filter(F.substring(col("ca_zip"), 1, 5)
                    != F.substring(col("s_zip"), 1, 5))
            .group_by("i_brand", "i_brand_id", "i_manufact_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand").alias("brand"),
                    col("i_brand_id").alias("brand_id"),
                    col("i_manufact_id"), col("ext_price"))
            .sort(col("ext_price").desc(), col("brand_id").asc(),
                  col("i_manufact_id").asc())
            .limit(100))


def q42(t):
    """Category revenue for one month/year."""
    return (t["date_dim"].filter((col("d_moy") == lit(11))
                                 & (col("d_year") == lit(2000)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .group_by("d_year", "i_category_id", "i_category")
            .agg(F.sum("ss_ext_sales_price").alias("total"))
            .sort(col("total").desc(), col("d_year").asc(),
                  col("i_category_id").asc(), col("i_category").asc())
            .limit(100))


def q52(t):
    """Brand revenue for one month/year (q42 over brand)."""
    return (t["date_dim"].filter((col("d_moy") == lit(12))
                                 & (col("d_year") == lit(1998)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("d_year"), col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), col("ext_price"))
            .sort(col("d_year").asc(), col("ext_price").desc(),
                  col("brand_id").asc())
            .limit(100))


def q55(t):
    """Brand revenue for one manager's items in one month."""
    return (t["date_dim"].filter((col("d_moy") == lit(11))
                                 & (col("d_year") == lit(1999)))
            .join(t["store_sales"],
                  col("d_date_sk") == col("ss_sold_date_sk"))
            .join(t["item"].filter(col("i_manager_id") == lit(28)),
                  col("ss_item_sk") == col("i_item_sk"))
            .group_by("i_brand", "i_brand_id")
            .agg(F.sum("ss_ext_sales_price").alias("ext_price"))
            .select(col("i_brand_id").alias("brand_id"),
                    col("i_brand").alias("brand"), col("ext_price"))
            .sort(col("ext_price").desc(), col("brand_id").asc())
            .limit(100))


def q68(t):
    """Per-ticket extended-price/ discount/ tax rollup for two cities
    (lite: no tax column, grouped on ticket + customer + city)."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(4))
        | (col("hd_vehicle_count") == lit(3)))
    return (t["store_sales"]
            .join(t["date_dim"].filter(
                col("d_year").isin(1999, 2000)
                & (col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"].filter(
                col("s_city").isin("Midway", "Fairview")),
                col("ss_store_sk") == col("s_store_sk"))
            .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(t["customer_address"],
                  col("ss_addr_sk") == col("ca_address_sk"))
            .group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
            .agg(F.sum("ss_ext_sales_price").alias("extended_price"),
                 F.sum("ss_ext_discount_amt").alias("extended_discount"))
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .select("c_last_name", "c_first_name", "ca_city",
                    "ss_ticket_number", "extended_price",
                    "extended_discount")
            .sort("c_last_name", "ss_ticket_number")
            .limit(100))


def q73(t):
    """Ticket counts per household bucket, 1..5 items per ticket."""
    hd = t["household_demographics"].filter(
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > lit(0)))
    counts = (t["store_sales"]
              .join(t["date_dim"].filter(
                  (col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                  & col("d_year").isin(1999, 2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["store"].filter(
                  col("s_number_employees") >= lit(200)),
                  col("ss_store_sk") == col("s_store_sk"))
              .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
              .group_by("ss_ticket_number", "ss_customer_sk")
              .agg(F.count("*").alias("cnt"))
              .filter((col("cnt") >= lit(1)) & (col("cnt") <= lit(5))))
    return (counts
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .select("c_last_name", "c_first_name", "ss_ticket_number",
                    "cnt")
            .sort(col("cnt").desc(), col("c_last_name").asc())
            .limit(100))


def q96(t):
    """Sales count in a time window for busy households."""
    return (t["store_sales"]
            .join(t["time_dim"].filter((col("t_hour") == lit(20))
                                       & (col("t_minute") >= lit(30))),
                  col("ss_sold_time_sk") == col("t_time_sk"))
            .join(t["household_demographics"].filter(
                col("hd_dep_count") == lit(7)),
                col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(t["store"].filter(col("s_store_name") != lit("")),
                  col("ss_store_sk") == col("s_store_sk"))
            .agg(F.count("*").alias("cnt")))


def q98(t):
    """Item revenue + share of its class's revenue (window)."""
    base = (t["store_sales"]
            .join(t["item"].filter(
                col("i_category").isin("Sports", "Books", "Home")),
                col("ss_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(
                (col("d_date") >= lit(_dt.date(1999, 2, 22)))
                & (col("d_date") <= lit(_dt.date(1999, 3, 24)))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .group_by("i_item_id", "i_item_desc", "i_category",
                      "i_class", "i_current_price")
            .agg(F.sum("ss_ext_sales_price").alias("itemrevenue")))
    return (base.select(
                col("i_item_id"), col("i_item_desc"), col("i_category"),
                col("i_class"), col("i_current_price"),
                col("itemrevenue"),
                (col("itemrevenue") * lit(100.0)
                 / F.sum(col("itemrevenue")).over(
                     Window.partition_by("i_class"))).alias(
                     "revenueratio"))
            .sort("i_category", "i_class", "i_item_id", "i_item_desc",
                  "revenueratio"))


QUERIES = {"q3": q3, "q7": q7, "q19": q19, "q42": q42, "q52": q52,
           "q55": q55, "q68": q68, "q73": q73, "q96": q96, "q98": q98}


def _collect_extended():
    """Merge q1-q99 from the three query modules (all 99 present)."""
    from spark_rapids_tpu.bench import (tpcds_queries_a,
                                        tpcds_queries_b,
                                        tpcds_queries_c)
    for mod in (tpcds_queries_a, tpcds_queries_b, tpcds_queries_c):
        for name, fn in vars(mod).items():
            if name.startswith("q") and name[1:].isdigit():
                QUERIES.setdefault(name, fn)


_collect_extended()
assert len(QUERIES) == 99, f"expected 99 TPC-DS queries, {len(QUERIES)}"
