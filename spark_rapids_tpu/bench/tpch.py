"""TpchLike: TPC-H schema, dbgen-lite generator, and the 22 queries.

Reference analog: ``integration_tests/.../tests/tpch/TpchLikeSpark.scala``
(schema + the 22 queries as classes with ``apply(spark)``) — "Like" because,
as in the reference, the data is not audited dbgen output and the results
are not comparable to official TPC-H numbers; the queries exercise the same
operator mix (multi-way hash joins, aggregates, semi/anti joins, scalar
subqueries, like-filters, top-k sorts).

Deliberate deltas from spec dbgen, mirroring the engine's documented
incompatibilities: prices are float64 (no decimal — reference:
GpuOverrides.scala:459-504 also rejects DecimalType), and text columns are
seeded-random words rather than spec grammar text, with the substrings the
queries grep for ("green", "forest", "special ... requests",
"Customer ... Complaints") injected at spec-plausible rates.
"""

from __future__ import annotations

import datetime as dt
from typing import Dict, List

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F

# ---------------------------------------------------------------------------
# Schema (TPC-H spec §1.4; names kept verbatim so queries read like the spec)
# ---------------------------------------------------------------------------

TPCH_TABLES = ["region", "nation", "supplier", "part", "partsupp",
               "customer", "orders", "lineitem"]

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

# nation -> (nationkey, regionkey) per spec
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
             "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
               "5-LOW"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
              "TAKE BACK RETURN"]
_CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
               for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK",
                         "CAN", "DRUM"]]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_TYPES = [f"{a} {b} {c}" for a in _TYPE_S1 for b in _TYPE_S2
          for c in _TYPE_S3]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "black", "blanched", "blue", "blush", "brown", "burlywood",
           "burnished", "chartreuse", "chiffon", "chocolate", "coral",
           "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
           "dim", "dodger", "drab", "firebrick", "floral", "forest",
           "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
           "honeydew", "hot", "hunter", "indian", "ivory", "khaki",
           "lace", "lavender", "lawn", "lemon", "light", "lime", "linen"]
_WORDS = ["packages", "deposits", "accounts", "foxes", "ideas", "theodolites",
          "dependencies", "instructions", "excuses", "platelets",
          "requests", "asymptotes", "courts", "dolphins", "multipliers",
          "sauternes", "warthogs", "frets", "dinos", "attainments"]

_EPOCH = dt.date(1970, 1, 1)
_STARTDATE = dt.date(1992, 1, 1)
_CURRENTDATE = dt.date(1995, 6, 17)
_ENDDATE = dt.date(1998, 12, 31)


def _days(d: dt.date) -> int:
    return (d - _EPOCH).days


def _date_arr(days: np.ndarray) -> pa.Array:
    return pa.array(days.astype(np.int32), type=pa.date32())


def _money(rng, lo: float, hi: float, n: int) -> np.ndarray:
    return np.round(rng.uniform(lo, hi, n), 2)


def _text(rng, n: int, inject: str = "", rate: float = 0.0) -> List[str]:
    words = rng.choice(_WORDS, size=(n, 4))
    out = [" ".join(row) for row in words]
    if inject and rate > 0:
        hits = rng.random(n) < rate
        for i in np.flatnonzero(hits):
            out[i] = f"{out[i][:10]}{inject}{out[i][10:]}"
    return out


def _phone(keys: np.ndarray, rng) -> List[str]:
    a = rng.integers(100, 999, keys.shape[0])
    b = rng.integers(100, 999, keys.shape[0])
    c = rng.integers(1000, 9999, keys.shape[0])
    return [f"{10 + k}-{x}-{y}-{z}"
            for k, x, y, z in zip(keys, a, b, c)]


def scale_counts(sf: float) -> Dict[str, int]:
    return {
        "supplier": max(10, int(10_000 * sf)),
        "part": max(40, int(200_000 * sf)),
        "customer": max(60, int(150_000 * sf)),
        "orders": max(150, int(1_500_000 * sf)),
    }


_FAVORED_NATIONS = [2, 3, 6, 7, 20]  # BRAZIL CANADA FRANCE GERMANY SAUDI
_NATION_P = np.full(25, 0.6 / 20)
_NATION_P[_FAVORED_NATIONS] = 0.08


def generate(sf: float = 0.001, seed: int = 0) -> Dict[str, pa.Table]:
    """dbgen-lite: the 8 tables at scale factor ``sf`` as Arrow tables.

    The query-parameter nations are oversampled (so q5/q7/q8/q11/q20/q21
    select non-empty results even at tiny scale factors) and ~2% of orders
    are bulk orders whose line quantities clear q18's sum(qty) > 300."""
    rng = np.random.default_rng(seed)
    counts = scale_counts(sf)
    tables: Dict[str, pa.Table] = {}

    tables["region"] = pa.table({
        "r_regionkey": pa.array(range(5), type=pa.int32()),
        "r_name": _REGIONS,
        "r_comment": _text(rng, 5),
    })

    nk = np.arange(25, dtype=np.int32)
    tables["nation"] = pa.table({
        "n_nationkey": pa.array(nk),
        "n_name": [n for n, _ in _NATIONS],
        "n_regionkey": pa.array([r for _, r in _NATIONS],
                                type=pa.int32()),
        "n_comment": _text(rng, 25),
    })

    ns = counts["supplier"]
    s_nation = rng.choice(25, ns, p=_NATION_P).astype(np.int32)
    tables["supplier"] = pa.table({
        "s_suppkey": pa.array(np.arange(1, ns + 1, dtype=np.int64)),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, ns + 1)],
        "s_address": _text(rng, ns),
        "s_nationkey": pa.array(s_nation),
        "s_phone": _phone(s_nation, rng),
        "s_acctbal": _money(rng, -999.99, 9999.99, ns),
        # q16 greps 'Customer%Complaints'; spec rate is 5 per 10k
        "s_comment": _text(rng, ns, "Customer Complaints", 0.02),
    })

    npart = counts["part"]
    color1 = rng.choice(_COLORS, npart)
    color2 = rng.choice(_COLORS, npart)
    brand_m = rng.integers(1, 6, npart)
    brand_n = rng.integers(1, 6, npart)
    tables["part"] = pa.table({
        "p_partkey": pa.array(np.arange(1, npart + 1, dtype=np.int64)),
        "p_name": [f"{a} {b}" for a, b in zip(color1, color2)],
        "p_mfgr": [f"Manufacturer#{m}" for m in brand_m],
        "p_brand": [f"Brand#{m}{n}" for m, n in zip(brand_m, brand_n)],
        "p_type": rng.choice(_TYPES, npart).tolist(),
        "p_size": pa.array(rng.integers(1, 51, npart).astype(np.int32)),
        "p_container": rng.choice(_CONTAINERS, npart).tolist(),
        "p_retailprice": np.round(
            900.0 + (np.arange(1, npart + 1) % 1000) / 10.0
            + 100.0 * (np.arange(1, npart + 1) % 10), 2),
        "p_comment": _text(rng, npart),
    })

    # partsupp: each part stocked by 4 suppliers (spec formula)
    pk = np.repeat(np.arange(1, npart + 1, dtype=np.int64), 4)
    j = np.tile(np.arange(4, dtype=np.int64), npart)
    sk = 1 + (pk - 1 + j * (ns // 4 + 1)) % ns
    nps = pk.shape[0]
    tables["partsupp"] = pa.table({
        "ps_partkey": pa.array(pk),
        "ps_suppkey": pa.array(sk),
        "ps_availqty": pa.array(
            rng.integers(1, 10_000, nps).astype(np.int32)),
        "ps_supplycost": _money(rng, 1.0, 1000.0, nps),
        "ps_comment": _text(rng, nps),
    })

    nc = counts["customer"]
    c_nation = rng.choice(25, nc, p=_NATION_P).astype(np.int32)
    tables["customer"] = pa.table({
        "c_custkey": pa.array(np.arange(1, nc + 1, dtype=np.int64)),
        "c_name": [f"Customer#{i:09d}" for i in range(1, nc + 1)],
        "c_address": _text(rng, nc),
        "c_nationkey": pa.array(c_nation),
        "c_phone": _phone(c_nation, rng),
        "c_acctbal": _money(rng, -999.99, 9999.99, nc),
        "c_mktsegment": rng.choice(_SEGMENTS, nc).tolist(),
        "c_comment": _text(rng, nc),
    })

    no = counts["orders"]
    o_key = np.arange(1, no + 1, dtype=np.int64)
    # spec: only 2/3 of customers have orders
    o_cust = rng.integers(1, max(2, (nc * 2) // 3) + 1, no).astype(np.int64)
    o_days = rng.integers(_days(_STARTDATE),
                          _days(_ENDDATE) - 151, no)
    nlines = rng.integers(1, 8, no)
    is_bulk = rng.random(no) < 0.02
    nlines[is_bulk] = 7

    # lineitem built alongside orders so dates/keys are consistent
    l_order = np.repeat(o_key, nlines)
    l_odate = np.repeat(o_days, nlines)
    nl = l_order.shape[0]
    l_part = rng.integers(1, npart + 1, nl).astype(np.int64)
    l_j = rng.integers(0, 4, nl)
    l_supp = 1 + (l_part - 1 + l_j * (ns // 4 + 1)) % ns
    bulk = np.repeat(is_bulk, nlines)
    l_qty = np.where(bulk, rng.integers(45, 51, nl),
                     rng.integers(1, 51, nl)).astype(np.int32)
    retail = 900.0 + (l_part % 1000) / 10.0 + 100.0 * (l_part % 10)
    l_price = np.round(l_qty * retail / 10.0, 2)
    l_disc = np.round(rng.integers(0, 11, nl) / 100.0, 2)
    l_tax = np.round(rng.integers(0, 9, nl) / 100.0, 2)
    l_ship = l_odate + rng.integers(1, 122, nl)
    l_commit = l_odate + rng.integers(30, 91, nl)
    l_receipt = l_ship + rng.integers(1, 31, nl)
    shipped = l_receipt <= _days(_CURRENTDATE)
    l_rflag = np.where(shipped,
                       np.where(rng.random(nl) < 0.5, "R", "A"), "N")
    l_status = np.where(l_ship > _days(_CURRENTDATE), "O", "F")

    # order status from its lines (spec: F all-F, O all-O, else P)
    ends = np.cumsum(nlines)
    starts = ends - nlines
    n_open = np.add.reduceat((l_status == "O").astype(np.int64), starts)
    o_status = np.where(n_open == 0, "F",
                        np.where(n_open == nlines, "O", "P"))
    tot = np.round(l_price * (1.0 + l_tax) * (1.0 - l_disc), 2)
    o_total = np.round(np.add.reduceat(tot, starts), 2)

    tables["orders"] = pa.table({
        "o_orderkey": pa.array(o_key),
        "o_custkey": pa.array(o_cust),
        "o_orderstatus": o_status.tolist(),
        "o_totalprice": o_total,
        "o_orderdate": _date_arr(o_days),
        "o_orderpriority": rng.choice(_PRIORITIES, no).tolist(),
        "o_clerk": [f"Clerk#{i:09d}" for i in
                    rng.integers(1, max(2, int(1000 * sf)) + 1, no)],
        "o_shippriority": pa.array(np.zeros(no, dtype=np.int32)),
        # q13 greps o_comment NOT LIKE '%special%requests%'
        "o_comment": _text(rng, no, "special packages requests", 0.1),
    })

    tables["lineitem"] = pa.table({
        "l_orderkey": pa.array(l_order),
        "l_partkey": pa.array(l_part),
        "l_suppkey": pa.array(l_supp),
        "l_linenumber": pa.array(
            (np.arange(nl) - np.repeat(starts, nlines) + 1)
            .astype(np.int32)),
        "l_quantity": pa.array(l_qty.astype(np.float64)),
        "l_extendedprice": l_price,
        "l_discount": l_disc,
        "l_tax": l_tax,
        "l_returnflag": l_rflag.tolist(),
        "l_linestatus": l_status.tolist(),
        "l_shipdate": _date_arr(l_ship),
        "l_commitdate": _date_arr(l_commit),
        "l_receiptdate": _date_arr(l_receipt),
        "l_shipinstruct": rng.choice(_INSTRUCTS, nl).tolist(),
        "l_shipmode": rng.choice(_SHIPMODES, nl).tolist(),
        "l_comment": _text(rng, nl),
    })
    return tables


def setup(session, tables: Dict[str, pa.Table]):
    """Register generated tables; returns name -> DataFrame."""
    return {name: session.create_dataframe(t) for name, t in tables.items()}


def setup_from_dir(session, path: str):
    """Load a written TPC-H dataset (parquet dirs per table) — the
    reference's ``TpchLikeSpark.setupAllParquet`` analog."""
    return {name: session.read.parquet(f"{path}/{name}")
            for name in TPCH_TABLES}


def write_parquet(tables: Dict[str, pa.Table], path: str) -> None:
    import os
    import pyarrow.parquet as papq
    for name, t in tables.items():
        os.makedirs(f"{path}/{name}", exist_ok=True)
        papq.write_table(t, f"{path}/{name}/part-00000.parquet")


# ---------------------------------------------------------------------------
# The 22 queries (validation parameter values from TPC-H spec §2.4)
# ---------------------------------------------------------------------------

def _scalar(df, name):
    v = df.collect().column(name)[0].as_py()
    return 0.0 if v is None else v


def q1(t):
    l = t["lineitem"]
    disc = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (l.filter(col("l_shipdate") <= lit(dt.date(1998, 9, 2)))
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum("l_quantity").alias("sum_qty"),
                 F.sum("l_extendedprice").alias("sum_base_price"),
                 F.sum(disc).alias("sum_disc_price"),
                 F.sum(disc * (lit(1.0) + col("l_tax"))).alias("sum_charge"),
                 F.avg("l_quantity").alias("avg_qty"),
                 F.avg("l_extendedprice").alias("avg_price"),
                 F.avg("l_discount").alias("avg_disc"),
                 F.count("*").alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q2(t):
    eu = (t["partsupp"]
          .join(t["supplier"], col("ps_suppkey") == col("s_suppkey"))
          .join(t["nation"], col("s_nationkey") == col("n_nationkey"))
          .join(t["region"].filter(col("r_name") == lit("EUROPE")),
                col("n_regionkey") == col("r_regionkey")))
    min_cost = (eu.group_by("ps_partkey")
                .agg(F.min("ps_supplycost").alias("min_cost"))
                .select(col("ps_partkey").alias("mc_partkey"),
                        col("min_cost")))
    parts = t["part"].filter((col("p_size") == lit(15))
                             & col("p_type").endswith("BRASS"))
    return (eu.join(parts, col("ps_partkey") == col("p_partkey"))
            .join(min_cost, (col("ps_partkey") == col("mc_partkey"))
                  & (col("ps_supplycost") == col("min_cost")))
            .select("s_acctbal", "s_name", "n_name", "p_partkey",
                    "p_mfgr", "s_address", "s_phone", "s_comment")
            .sort(col("s_acctbal").desc(), col("n_name").asc(),
                  col("s_name").asc(), col("p_partkey").asc())
            .limit(100))


def q3(t):
    cutoff = dt.date(1995, 3, 15)
    return (t["customer"].filter(col("c_mktsegment") == lit("BUILDING"))
            .join(t["orders"].filter(col("o_orderdate") < lit(cutoff)),
                  col("c_custkey") == col("o_custkey"))
            .join(t["lineitem"].filter(col("l_shipdate") > lit(cutoff)),
                  col("o_orderkey") == col("l_orderkey"))
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue"))
            .select("l_orderkey", "revenue", "o_orderdate",
                    "o_shippriority")
            .sort(col("revenue").desc(), col("o_orderdate").asc())
            .limit(10))


def q4(t):
    late = t["lineitem"].filter(col("l_commitdate") < col("l_receiptdate"))
    return (t["orders"]
            .filter((col("o_orderdate") >= lit(dt.date(1993, 7, 1)))
                    & (col("o_orderdate") < lit(dt.date(1993, 10, 1))))
            .join(late, col("o_orderkey") == col("l_orderkey"), "semi")
            .group_by("o_orderpriority")
            .agg(F.count("*").alias("order_count"))
            .sort("o_orderpriority"))


def q5(t):
    return (t["customer"]
            .join(t["orders"]
                  .filter((col("o_orderdate") >= lit(dt.date(1994, 1, 1)))
                          & (col("o_orderdate")
                             < lit(dt.date(1995, 1, 1)))),
                  col("c_custkey") == col("o_custkey"))
            .join(t["lineitem"], col("o_orderkey") == col("l_orderkey"))
            .join(t["supplier"],
                  (col("l_suppkey") == col("s_suppkey"))
                  & (col("c_nationkey") == col("s_nationkey")))
            .join(t["nation"], col("s_nationkey") == col("n_nationkey"))
            .join(t["region"].filter(col("r_name") == lit("ASIA")),
                  col("n_regionkey") == col("r_regionkey"))
            .group_by("n_name")
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue"))
            .sort(col("revenue").desc()))


def q6(t):
    return (t["lineitem"]
            .filter((col("l_shipdate") >= lit(dt.date(1994, 1, 1)))
                    & (col("l_shipdate") < lit(dt.date(1995, 1, 1)))
                    & (col("l_discount") >= lit(0.05))
                    & (col("l_discount") <= lit(0.07))
                    & (col("l_quantity") < lit(24.0)))
            .agg(F.sum(col("l_extendedprice")
                       * col("l_discount")).alias("revenue")))


def q7(t):
    n1 = t["nation"].select(col("n_nationkey").alias("n1_key"),
                            col("n_name").alias("supp_nation"))
    n2 = t["nation"].select(col("n_nationkey").alias("n2_key"),
                            col("n_name").alias("cust_nation"))
    pair = ((col("supp_nation") == lit("FRANCE"))
            & (col("cust_nation") == lit("GERMANY"))) | \
           ((col("supp_nation") == lit("GERMANY"))
            & (col("cust_nation") == lit("FRANCE")))
    return (t["supplier"]
            .join(t["lineitem"]
                  .filter((col("l_shipdate") >= lit(dt.date(1995, 1, 1)))
                          & (col("l_shipdate")
                             <= lit(dt.date(1996, 12, 31)))),
                  col("s_suppkey") == col("l_suppkey"))
            .join(t["orders"], col("l_orderkey") == col("o_orderkey"))
            .join(t["customer"], col("o_custkey") == col("c_custkey"))
            .join(n1, col("s_nationkey") == col("n1_key"))
            .join(n2, col("c_nationkey") == col("n2_key"))
            .filter(pair)
            .select(col("supp_nation"), col("cust_nation"),
                    F.year(col("l_shipdate")).alias("l_year"),
                    (col("l_extendedprice")
                     * (lit(1.0) - col("l_discount"))).alias("volume"))
            .group_by("supp_nation", "cust_nation", "l_year")
            .agg(F.sum("volume").alias("revenue"))
            .sort("supp_nation", "cust_nation", "l_year"))


def q8(t):
    n1 = t["nation"].select(col("n_nationkey").alias("n1_key"),
                            col("n_regionkey").alias("n1_region"))
    n2 = t["nation"].select(col("n_nationkey").alias("n2_key"),
                            col("n_name").alias("supp_nation"))
    return (t["part"].filter(
                col("p_type") == lit("ECONOMY ANODIZED STEEL"))
            .join(t["lineitem"], col("p_partkey") == col("l_partkey"))
            .join(t["supplier"], col("l_suppkey") == col("s_suppkey"))
            .join(t["orders"]
                  .filter((col("o_orderdate") >= lit(dt.date(1995, 1, 1)))
                          & (col("o_orderdate")
                             <= lit(dt.date(1996, 12, 31)))),
                  col("l_orderkey") == col("o_orderkey"))
            .join(t["customer"], col("o_custkey") == col("c_custkey"))
            .join(n1, col("c_nationkey") == col("n1_key"))
            .join(t["region"].filter(col("r_name") == lit("AMERICA")),
                  col("n1_region") == col("r_regionkey"))
            .join(n2, col("s_nationkey") == col("n2_key"))
            .select(F.year(col("o_orderdate")).alias("o_year"),
                    (col("l_extendedprice")
                     * (lit(1.0) - col("l_discount"))).alias("volume"),
                    col("supp_nation"))
            .group_by("o_year")
            .agg((F.sum(F.when(col("supp_nation") == lit("BRAZIL"),
                               col("volume")).otherwise(lit(0.0)))
                  / F.sum("volume")).alias("mkt_share"))
            .sort("o_year"))


def q9(t):
    return (t["part"].filter(col("p_name").contains("green"))
            .join(t["lineitem"], col("p_partkey") == col("l_partkey"))
            .join(t["supplier"], col("l_suppkey") == col("s_suppkey"))
            .join(t["partsupp"],
                  (col("l_suppkey") == col("ps_suppkey"))
                  & (col("l_partkey") == col("ps_partkey")))
            .join(t["orders"], col("l_orderkey") == col("o_orderkey"))
            .join(t["nation"], col("s_nationkey") == col("n_nationkey"))
            .select(col("n_name").alias("nation"),
                    F.year(col("o_orderdate")).alias("o_year"),
                    (col("l_extendedprice")
                     * (lit(1.0) - col("l_discount"))
                     - col("ps_supplycost")
                     * col("l_quantity")).alias("amount"))
            .group_by("nation", "o_year")
            .agg(F.sum("amount").alias("sum_profit"))
            .sort(col("nation").asc(), col("o_year").desc()))


def q10(t):
    return (t["customer"]
            .join(t["orders"]
                  .filter((col("o_orderdate") >= lit(dt.date(1993, 10, 1)))
                          & (col("o_orderdate")
                             < lit(dt.date(1994, 1, 1)))),
                  col("c_custkey") == col("o_custkey"))
            .join(t["lineitem"].filter(col("l_returnflag") == lit("R")),
                  col("o_orderkey") == col("l_orderkey"))
            .join(t["nation"], col("c_nationkey") == col("n_nationkey"))
            .group_by("c_custkey", "c_name", "c_acctbal", "c_phone",
                      "n_name", "c_address", "c_comment")
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue"))
            .select("c_custkey", "c_name", "revenue", "c_acctbal",
                    "n_name", "c_address", "c_phone", "c_comment")
            .sort(col("revenue").desc(), col("c_custkey").asc())
            .limit(20))


def q11(t):
    de = (t["partsupp"]
          .join(t["supplier"], col("ps_suppkey") == col("s_suppkey"))
          .join(t["nation"].filter(col("n_name") == lit("GERMANY")),
                col("s_nationkey") == col("n_nationkey")))
    value = col("ps_supplycost") * col("ps_availqty")
    threshold = _scalar(
        de.agg(F.sum(value).alias("total")), "total") * 0.0001
    return (de.group_by("ps_partkey")
            .agg(F.sum(value).alias("value"))
            .filter(col("value") > lit(threshold))
            .sort(col("value").desc(), col("ps_partkey").asc()))


def q12(t):
    high = col("o_orderpriority").isin("1-URGENT", "2-HIGH")
    return (t["orders"]
            .join(t["lineitem"]
                  .filter(col("l_shipmode").isin("MAIL", "SHIP")
                          & (col("l_commitdate") < col("l_receiptdate"))
                          & (col("l_shipdate") < col("l_commitdate"))
                          & (col("l_receiptdate")
                             >= lit(dt.date(1994, 1, 1)))
                          & (col("l_receiptdate")
                             < lit(dt.date(1995, 1, 1)))),
                  col("o_orderkey") == col("l_orderkey"))
            .group_by("l_shipmode")
            .agg(F.sum(F.when(high, lit(1)).otherwise(lit(0)))
                 .alias("high_line_count"),
                 F.sum(F.when(~high, lit(1)).otherwise(lit(0)))
                 .alias("low_line_count"))
            .sort("l_shipmode"))


def q13(t):
    orders = t["orders"].filter(
        ~col("o_comment").like("%special%requests%"))
    return (t["customer"]
            .join(orders, col("c_custkey") == col("o_custkey"), "left")
            .group_by("c_custkey")
            .agg(F.count(col("o_orderkey")).alias("c_count"))
            .group_by("c_count")
            .agg(F.count("*").alias("custdist"))
            .sort(col("custdist").desc(), col("c_count").desc()))


def q14(t):
    disc = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["lineitem"]
            .filter((col("l_shipdate") >= lit(dt.date(1995, 9, 1)))
                    & (col("l_shipdate") < lit(dt.date(1995, 10, 1))))
            .join(t["part"], col("l_partkey") == col("p_partkey"))
            .agg((F.sum(F.when(col("p_type").startswith("PROMO"), disc)
                        .otherwise(lit(0.0)))
                  * lit(100.0) / F.sum(disc)).alias("promo_revenue")))


def q15(t):
    revenue = (t["lineitem"]
               .filter((col("l_shipdate") >= lit(dt.date(1996, 1, 1)))
                       & (col("l_shipdate") < lit(dt.date(1996, 4, 1))))
               .group_by("l_suppkey")
               .agg(F.sum(col("l_extendedprice")
                          * (lit(1.0) - col("l_discount")))
                    .alias("total_revenue"))
               .select(col("l_suppkey").alias("supplier_no"),
                       col("total_revenue")))
    top = _scalar(revenue.agg(F.max("total_revenue").alias("m")), "m")
    return (t["supplier"]
            .join(revenue.filter(col("total_revenue") >= lit(top)),
                  col("s_suppkey") == col("supplier_no"))
            .select("s_suppkey", "s_name", "s_address", "s_phone",
                    "total_revenue")
            .sort("s_suppkey"))


def q16(t):
    bad_supp = t["supplier"].filter(
        col("s_comment").like("%Customer%Complaints%"))
    ps = (t["partsupp"]
          .join(bad_supp, col("ps_suppkey") == col("s_suppkey"), "anti")
          .join(t["part"]
                .filter((col("p_brand") != lit("Brand#45"))
                        & ~col("p_type").startswith("MEDIUM POLISHED")
                        & col("p_size").isin(49, 14, 23, 45, 19, 3,
                                             36, 9)),
                col("ps_partkey") == col("p_partkey")))
    return (ps.select("p_brand", "p_type", "p_size", "ps_suppkey")
            .distinct()
            .group_by("p_brand", "p_type", "p_size")
            .agg(F.count("*").alias("supplier_cnt"))
            .sort(col("supplier_cnt").desc(), col("p_brand").asc(),
                  col("p_type").asc(), col("p_size").asc()))


def q17(t):
    threshold = (t["lineitem"]
                 .group_by("l_partkey")
                 .agg((F.avg("l_quantity") * lit(0.2)).alias("avg_qty"))
                 .select(col("l_partkey").alias("t_partkey"),
                         col("avg_qty")))
    return (t["lineitem"]
            .join(t["part"]
                  .filter((col("p_brand") == lit("Brand#23"))
                          & (col("p_container") == lit("MED BOX"))),
                  col("l_partkey") == col("p_partkey"))
            .join(threshold, col("l_partkey") == col("t_partkey"))
            .filter(col("l_quantity") < col("avg_qty"))
            .agg((F.sum("l_extendedprice") / lit(7.0))
                 .alias("avg_yearly")))


def q18(t):
    big = (t["lineitem"].group_by("l_orderkey")
           .agg(F.sum("l_quantity").alias("o_sum_qty"))
           .filter(col("o_sum_qty") > lit(300.0))
           .select(col("l_orderkey").alias("big_orderkey")))
    return (t["customer"]
            .join(t["orders"], col("c_custkey") == col("o_custkey"))
            .join(big, col("o_orderkey") == col("big_orderkey"), "semi")
            .join(t["lineitem"], col("o_orderkey") == col("l_orderkey"))
            .group_by("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                      "o_totalprice")
            .agg(F.sum("l_quantity").alias("sum_qty"))
            .sort(col("o_totalprice").desc(), col("o_orderdate").asc())
            .limit(100))


def q19(t):
    qty, size = col("l_quantity"), col("p_size")
    cond = (
        ((col("p_brand") == lit("Brand#12"))
         & col("p_container").isin("SM CASE", "SM BOX", "SM PACK",
                                   "SM PKG")
         & (qty >= lit(1.0)) & (qty <= lit(11.0))
         & (size >= lit(1)) & (size <= lit(5)))
        | ((col("p_brand") == lit("Brand#23"))
           & col("p_container").isin("MED BAG", "MED BOX", "MED PKG",
                                     "MED PACK")
           & (qty >= lit(10.0)) & (qty <= lit(20.0))
           & (size >= lit(1)) & (size <= lit(10)))
        | ((col("p_brand") == lit("Brand#34"))
           & col("p_container").isin("LG CASE", "LG BOX", "LG PACK",
                                     "LG PKG")
           & (qty >= lit(20.0)) & (qty <= lit(30.0))
           & (size >= lit(1)) & (size <= lit(15))))
    return (t["lineitem"]
            .filter(col("l_shipmode").isin("AIR", "REG AIR")
                    & (col("l_shipinstruct")
                       == lit("DELIVER IN PERSON")))
            .join(t["part"], col("l_partkey") == col("p_partkey"))
            .filter(cond)
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount")))
                 .alias("revenue")))


def q20(t):
    shipped = (t["lineitem"]
               .filter((col("l_shipdate") >= lit(dt.date(1994, 1, 1)))
                       & (col("l_shipdate") < lit(dt.date(1995, 1, 1))))
               .group_by("l_partkey", "l_suppkey")
               .agg((F.sum("l_quantity") * lit(0.5)).alias("half_qty"))
               .select(col("l_partkey").alias("sh_partkey"),
                       col("l_suppkey").alias("sh_suppkey"),
                       col("half_qty")))
    forest = t["part"].filter(col("p_name").startswith("forest"))
    excess = (t["partsupp"]
              .join(forest, col("ps_partkey") == col("p_partkey"), "semi")
              .join(shipped, (col("ps_partkey") == col("sh_partkey"))
                    & (col("ps_suppkey") == col("sh_suppkey")))
              .filter(col("ps_availqty").cast("double")
                      > col("half_qty"))
              .select(col("ps_suppkey").alias("ex_suppkey"))
              .distinct())
    return (t["supplier"]
            .join(t["nation"].filter(col("n_name") == lit("CANADA")),
                  col("s_nationkey") == col("n_nationkey"))
            .join(excess, col("s_suppkey") == col("ex_suppkey"), "semi")
            .select("s_name", "s_address")
            .sort("s_name"))


def q21(t):
    # per order: #distinct suppliers overall and #distinct late suppliers
    # (exists-other-supplier / not-exists-other-late-supplier rewrite)
    supp_cnt = (t["lineitem"].select("l_orderkey", "l_suppkey").distinct()
                .group_by("l_orderkey")
                .agg(F.count("*").alias("n_supps"))
                .select(col("l_orderkey").alias("sc_orderkey"),
                        col("n_supps")))
    late = t["lineitem"].filter(
        col("l_receiptdate") > col("l_commitdate"))
    late_cnt = (late.select("l_orderkey", "l_suppkey").distinct()
                .group_by("l_orderkey")
                .agg(F.count("*").alias("n_late_supps"))
                .select(col("l_orderkey").alias("lc_orderkey"),
                        col("n_late_supps")))
    return (t["supplier"]
            .join(late, col("s_suppkey") == col("l_suppkey"))
            .join(t["orders"].filter(col("o_orderstatus") == lit("F")),
                  col("l_orderkey") == col("o_orderkey"))
            .join(t["nation"].filter(
                col("n_name") == lit("SAUDI ARABIA")),
                col("s_nationkey") == col("n_nationkey"))
            .join(supp_cnt, col("l_orderkey") == col("sc_orderkey"))
            .join(late_cnt, col("l_orderkey") == col("lc_orderkey"))
            .filter((col("n_supps") > lit(1))
                    & (col("n_late_supps") == lit(1)))
            .group_by("s_name")
            .agg(F.count("*").alias("numwait"))
            .sort(col("numwait").desc(), col("s_name").asc())
            .limit(100))


def q22(t):
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = (t["customer"]
            .with_column("cntrycode",
                         F.substring(col("c_phone"), 1, 2))
            .filter(col("cntrycode").isin(*codes)))
    avg_bal = _scalar(
        cust.filter(col("c_acctbal") > lit(0.0))
        .agg(F.avg("c_acctbal").alias("a")), "a")
    return (cust.filter(col("c_acctbal") > lit(avg_bal))
            .join(t["orders"], col("c_custkey") == col("o_custkey"),
                  "anti")
            .group_by("cntrycode")
            .agg(F.count("*").alias("numcust"),
                 F.sum("c_acctbal").alias("totacctbal"))
            .sort("cntrycode"))


QUERIES = {f"q{i}": fn for i, fn in enumerate(
    [q1, q2, q3, q4, q5, q6, q7, q8, q9, q10, q11, q12, q13, q14, q15,
     q16, q17, q18, q19, q20, q21, q22], start=1)}


# SQL texts for the queries the SQL frontend's subset covers — run through
# session.sql() against registered views (the reference feeds Spark's
# parser the spec SQL; TpchLikeSpark.scala registers temp views the same
# way).
SQL_QUERIES = {
    "q1": """
      SELECT l_returnflag, l_linestatus,
             sum(l_quantity) AS sum_qty,
             sum(l_extendedprice) AS sum_base_price,
             sum(l_extendedprice * (1.0 - l_discount)) AS sum_disc_price,
             sum(l_extendedprice * (1.0 - l_discount) * (1.0 + l_tax))
               AS sum_charge,
             avg(l_quantity) AS avg_qty,
             avg(l_extendedprice) AS avg_price,
             avg(l_discount) AS avg_disc,
             count(*) AS count_order
      FROM lineitem
      WHERE l_shipdate <= DATE '1998-09-02'
      GROUP BY l_returnflag, l_linestatus
      ORDER BY l_returnflag, l_linestatus
    """,
    "q3": """
      SELECT l_orderkey,
             sum(l_extendedprice * (1.0 - l_discount)) AS revenue,
             o_orderdate, o_shippriority
      FROM customer c
      JOIN orders o ON c_custkey = o_custkey
      JOIN lineitem l ON o_orderkey = l_orderkey
      WHERE c_mktsegment = 'BUILDING'
        AND o_orderdate < DATE '1995-03-15'
        AND l_shipdate > DATE '1995-03-15'
      GROUP BY l_orderkey, o_orderdate, o_shippriority
      ORDER BY revenue DESC, o_orderdate
      LIMIT 10
    """,
    "q5": """
      SELECT n_name,
             sum(l_extendedprice * (1.0 - l_discount)) AS revenue
      FROM customer
      JOIN orders ON c_custkey = o_custkey
      JOIN lineitem ON o_orderkey = l_orderkey
      JOIN supplier ON l_suppkey = s_suppkey AND c_nationkey = s_nationkey
      JOIN nation ON s_nationkey = n_nationkey
      JOIN region ON n_regionkey = r_regionkey
      WHERE r_name = 'ASIA'
        AND o_orderdate >= DATE '1994-01-01'
        AND o_orderdate < DATE '1995-01-01'
      GROUP BY n_name
      ORDER BY revenue DESC
    """,
    "q6": """
      SELECT sum(l_extendedprice * l_discount) AS revenue
      FROM lineitem
      WHERE l_shipdate >= DATE '1994-01-01'
        AND l_shipdate < DATE '1995-01-01'
        AND l_discount BETWEEN 0.05 AND 0.07
        AND l_quantity < 24.0
    """,
    "q10": """
      SELECT c_custkey, c_name,
             sum(l_extendedprice * (1.0 - l_discount)) AS revenue,
             c_acctbal, n_name, c_address, c_phone, c_comment
      FROM customer
      JOIN orders ON c_custkey = o_custkey
      JOIN lineitem ON o_orderkey = l_orderkey
      JOIN nation ON c_nationkey = n_nationkey
      WHERE o_orderdate >= DATE '1993-10-01'
        AND o_orderdate < DATE '1994-01-01'
        AND l_returnflag = 'R'
      GROUP BY c_custkey, c_name, c_acctbal, c_phone, n_name,
               c_address, c_comment
      ORDER BY revenue DESC, c_custkey
      LIMIT 20
    """,
    "q12": """
      SELECT l_shipmode,
             sum(CASE WHEN o_orderpriority = '1-URGENT'
                        OR o_orderpriority = '2-HIGH'
                      THEN 1 ELSE 0 END) AS high_line_count,
             sum(CASE WHEN o_orderpriority <> '1-URGENT'
                       AND o_orderpriority <> '2-HIGH'
                      THEN 1 ELSE 0 END) AS low_line_count
      FROM orders
      JOIN lineitem ON o_orderkey = l_orderkey
      WHERE l_shipmode IN ('MAIL', 'SHIP')
        AND l_commitdate < l_receiptdate
        AND l_shipdate < l_commitdate
        AND l_receiptdate >= DATE '1994-01-01'
        AND l_receiptdate < DATE '1995-01-01'
      GROUP BY l_shipmode
      ORDER BY l_shipmode
    """,
    "q14": """
      SELECT sum(CASE WHEN p_type LIKE 'PROMO%'
                      THEN l_extendedprice * (1.0 - l_discount)
                      ELSE 0.0 END) * 100.0
             / sum(l_extendedprice * (1.0 - l_discount)) AS promo_revenue
      FROM lineitem
      JOIN part ON l_partkey = p_partkey
      WHERE l_shipdate >= DATE '1995-09-01'
        AND l_shipdate < DATE '1995-10-01'
    """,
    "q19": """
      SELECT sum(l_extendedprice * (1.0 - l_discount)) AS revenue
      FROM lineitem
      JOIN part ON p_partkey = l_partkey
      WHERE l_shipmode IN ('AIR', 'REG AIR')
        AND l_shipinstruct = 'DELIVER IN PERSON'
        AND ((p_brand = 'Brand#12'
              AND p_container IN ('SM CASE', 'SM BOX', 'SM PACK',
                                  'SM PKG')
              AND l_quantity BETWEEN 1.0 AND 11.0
              AND p_size BETWEEN 1 AND 5)
          OR (p_brand = 'Brand#23'
              AND p_container IN ('MED BAG', 'MED BOX', 'MED PKG',
                                  'MED PACK')
              AND l_quantity BETWEEN 10.0 AND 20.0
              AND p_size BETWEEN 1 AND 10)
          OR (p_brand = 'Brand#34'
              AND p_container IN ('LG CASE', 'LG BOX', 'LG PACK',
                                  'LG PKG')
              AND l_quantity BETWEEN 20.0 AND 30.0
              AND p_size BETWEEN 1 AND 15))
    """,
}


def setup_views(session, tables: Dict[str, pa.Table]) -> None:
    """Register the 8 tables as temp views for SQL_QUERIES."""
    for name, tbl in tables.items():
        session.create_dataframe(tbl).create_or_replace_temp_view(name)

