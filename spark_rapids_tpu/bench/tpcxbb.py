"""TpcxbbLike: big-data retail analytics suite (the SQL-able subset).

Reference analog: integration_tests/.../tests/tpcxbb/TpcxbbLikeSpark.scala
— the reference implements 19 of the 30 TPCx-BB queries and throws
UnsupportedOperation for the UDTF/python/NLP ones (q1-q4, q8, q10, q18,
q19, q27, q29, q30); this suite mirrors that scope with original
DataFrame-API re-expressions over the dbgen-lite schema (tpcds.py tables
plus the three TPCx-BB-specific tables below).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window
from spark_rapids_tpu.bench import tpcds as _tpcds

UNSUPPORTED = {  # same list the reference refuses (UDTF / python / NLP)
    "q1", "q2", "q3", "q4", "q8", "q10", "q18", "q19", "q27", "q29",
    "q30",
}


def generate(sf: float = 0.001, seed: int = 0) -> Dict[str, pa.Table]:
    """tpcds dbgen-lite tables + web_clickstreams / product_reviews /
    item_marketprices."""
    t = _tpcds.generate(sf, seed)
    rng = np.random.default_rng(seed + 77)
    n_days = t["date_dim"].num_rows
    ni = t["item"].num_rows
    nc = t["customer"].num_rows

    nwc = max(4000, int(5_000_000 * sf))
    # ~20% of clicks convert to a web sale (wcs_sales_sk non-null)
    sales_sk = rng.integers(1, t["web_sales"].num_rows + 1, nwc)
    t["web_clickstreams"] = pa.table({
        "wcs_click_date_sk": pa.array(
            rng.integers(1, n_days + 1, nwc).astype(np.int64)),
        "wcs_click_time_sk": pa.array(
            rng.integers(1, 86401, nwc).astype(np.int64)),
        "wcs_item_sk": pa.array(
            rng.integers(1, ni + 1, nwc).astype(np.int64)),
        "wcs_user_sk": pa.array(
            rng.integers(1, nc + 1, nwc).astype(np.int64),
            mask=rng.random(nwc) < 0.3),
        "wcs_sales_sk": pa.array(sales_sk.astype(np.int64),
                                 mask=rng.random(nwc) >= 0.2),
    })

    npr = max(300, int(60_000 * sf))
    words = ["great", "terrible", "fine", "excellent", "poor", "okay",
             "broken", "love", "hate", "works"]
    t["product_reviews"] = pa.table({
        "pr_review_sk": pa.array(np.arange(1, npr + 1, dtype=np.int64)),
        "pr_review_date_sk": pa.array(
            rng.integers(1, n_days + 1, npr).astype(np.int64)),
        "pr_item_sk": pa.array(
            rng.integers(1, ni + 1, npr).astype(np.int64)),
        "pr_user_sk": pa.array(
            rng.integers(1, nc + 1, npr).astype(np.int64)),
        "pr_review_rating": pa.array(
            rng.integers(1, 6, npr).astype(np.int32)),
        "pr_review_content": [
            " ".join(rng.choice(words, 5)) for _ in range(npr)],
    })

    nip = ni * 2
    start = rng.integers(1, n_days - 100, nip)
    t["item_marketprices"] = pa.table({
        "imp_sk": pa.array(np.arange(1, nip + 1, dtype=np.int64)),
        "imp_item_sk": pa.array(
            rng.integers(1, ni + 1, nip).astype(np.int64)),
        "imp_competitor_price": np.round(
            rng.uniform(0.1, 110.0, nip), 2),
        "imp_start_date_sk": pa.array(start.astype(np.int64)),
        "imp_end_date_sk": pa.array(
            (start + rng.integers(30, 100, nip)).astype(np.int64)),
    })
    return t


def setup(session, tables: Dict[str, pa.Table]):
    return {name: session.create_dataframe(tbl)
            for name, tbl in tables.items()}


def q5(t):
    """Per-customer category click interest + demographics (logistic
    regression feature prep)."""
    cats = ["Books", "Electronics", "Home", "Jewelry", "Sports"]
    clicks = (t["web_clickstreams"]
              .filter(~F.isnull(col("wcs_user_sk")))
              .join(t["item"], col("wcs_item_sk") == col("i_item_sk")))
    aggs = [F.sum(F.when(col("i_category") == lit(c), lit(1))
                  .otherwise(lit(0))).alias(f"clicks_in_{i + 1}")
            for i, c in enumerate(cats)]
    per_user = clicks.group_by("wcs_user_sk").agg(*aggs)
    return (per_user
            .join(t["customer"],
                  col("wcs_user_sk") == col("c_customer_sk"))
            .join(t["customer_demographics"],
                  col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .select(col("wcs_user_sk").alias("user_sk"),
                    F.when(col("cd_education_status").isin(
                        "College", "4 yr Degree", "Advanced Degree"),
                        lit(1)).otherwise(lit(0)).alias("college_ed"),
                    F.when(col("cd_gender") == lit("M"), lit(1))
                    .otherwise(lit(0)).alias("male"),
                    *[col(f"clicks_in_{i + 1}")
                      for i in range(len(cats))])
            .sort("user_sk")
            .limit(100))


def q6(t):
    """Customers whose web spend grew faster than store spend."""
    from spark_rapids_tpu.bench.tpcds_queries_a import _year_total
    s1 = _year_total(t, "s", True).select(
        col("c_customer_id").alias("id_s1"),
        col("year_total").alias("t_s1"))
    s2 = _year_total(t, "s", False).select(
        col("c_customer_id").alias("id_s2"),
        col("year_total").alias("t_s2"))
    w1 = _year_total(t, "w", True).select(
        col("c_customer_id").alias("id_w1"),
        col("year_total").alias("t_w1"))
    w2 = _year_total(t, "w", False).select(
        col("c_customer_id").alias("id_w2"),
        col("year_total").alias("t_w2"))
    return (s1.join(s2, col("id_s1") == col("id_s2"))
            .join(w1, col("id_s1") == col("id_w1"))
            .join(w2, col("id_s1") == col("id_w2"))
            .filter((col("t_w1") > lit(0.0)) & (col("t_s1") > lit(0.0)))
            .select(col("id_s1").alias("customer_id"),
                    (col("t_w2") / col("t_w1")).alias("web_ratio"),
                    (col("t_s2") / col("t_s1")).alias("store_ratio"))
            .filter(col("web_ratio") > col("store_ratio"))
            .sort(col("web_ratio").desc(), col("customer_id").asc())
            .limit(100))


def q7(t):
    """States with 10+ customers buying items priced >= 1.2x their
    category average in one month (pricey-item buyers)."""
    cat_avg = (t["item"].group_by("i_category")
               .agg((F.avg("i_current_price") * lit(1.2)).alias("thr"))
               .select(col("i_category").alias("avg_cat"), col("thr")))
    pricey = (t["item"]
              .join(cat_avg, col("i_category") == col("avg_cat"))
              .filter(col("i_current_price") > col("thr"))
              .select(col("i_item_sk").alias("pricey_sk")))
    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(pricey, col("ss_item_sk") == col("pricey_sk"),
                  how="leftsemi")
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by("ca_state")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(10))
            .sort(col("cnt").desc(), col("ca_state").asc())
            .limit(10))


def q9(t):
    """Store sales quantity sum under OR'd demographic x address
    conditions."""
    cd_ok = ((col("cd_marital_status") == lit("M"))
             & (col("cd_education_status") == lit("4 yr Degree"))
             & (col("ss_sales_price") >= lit(100.0))) | \
            ((col("cd_marital_status") == lit("S"))
             & (col("cd_education_status") == lit("Secondary"))
             & (col("ss_sales_price") >= lit(50.0))) | \
            ((col("cd_marital_status") == lit("W"))
             & (col("cd_education_status") == lit("Advanced Degree")))
    ca_ok = (col("ca_state").isin("TX", "OH", "CA")
             | col("ca_state").isin("WA", "NY", "GA"))
    return (t["store_sales"]
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .join(t["customer_demographics"],
                  col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["customer_address"],
                  col("ss_addr_sk") == col("ca_address_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(cd_ok & ca_ok)
            .agg(F.sum("ss_quantity").alias("total_quantity")))


def q11(t):
    """Correlation between review ratings and web sales per item."""
    sales = (t["web_sales"]
             .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                   col("ws_sold_date_sk") == col("d_date_sk"))
             .group_by("ws_item_sk")
             .agg(F.sum("ws_net_paid").alias("sales"))
             .select(col("ws_item_sk").alias("s_isk"), col("sales")))
    reviews = (t["product_reviews"]
               .group_by("pr_item_sk")
               .agg(F.avg(col("pr_review_rating").cast("double"))
                    .alias("avg_rating"),
                    F.count("*").alias("r_count")))
    j = sales.join(reviews, col("s_isk") == col("pr_item_sk"))
    # Pearson corr via moment sums (no corr() aggregate needed)
    x, y = col("avg_rating"), col("sales")
    m = j.agg(F.count("*").alias("n"), F.sum(x).alias("sx"),
              F.sum(y).alias("sy"), F.sum(x * y).alias("sxy"),
              F.sum(x * x).alias("sxx"), F.sum(y * y).alias("syy"))
    n = col("n").cast("double")
    num = n * col("sxy") - col("sx") * col("sy")
    den = F.sqrt(n * col("sxx") - col("sx") * col("sx")) * \
        F.sqrt(n * col("syy") - col("sy") * col("sy"))
    return m.select((num / den).alias("corr"))


def q12(t):
    """Customers who clicked an item category online then bought in
    store within 90 days."""
    clicks = (t["web_clickstreams"]
              .filter(~F.isnull(col("wcs_user_sk")))
              .join(t["item"].filter(col("i_category").isin(
                  "Books", "Electronics"))
                  .select(col("i_item_sk").alias("ci_sk")),
                  col("wcs_item_sk") == col("ci_sk"))
              .select(col("wcs_user_sk").alias("click_user"),
                      col("wcs_click_date_sk").alias("click_date")))
    buys = (t["store_sales"]
            .join(t["item"].filter(col("i_category").isin(
                "Books", "Electronics"))
                .select(col("i_item_sk").alias("bi_sk")),
                col("ss_item_sk") == col("bi_sk"))
            .select(col("ss_customer_sk").alias("buy_user"),
                    col("ss_sold_date_sk").alias("buy_date")))
    return (clicks.join(buys, (col("click_user") == col("buy_user"))
                        & (col("buy_date") > col("click_date"))
                        & (col("buy_date")
                           < col("click_date") + lit(90)))
            .select("click_user").distinct()
            .sort("click_user")
            .limit(100))


def q13(t):
    """Year-over-year sales growth ratio per customer, both channels
    (q6 sibling keeping both ratios)."""
    from spark_rapids_tpu.bench.tpcds_queries_a import _year_total
    s1 = _year_total(t, "s", True).select(
        col("c_customer_id").alias("id_s1"),
        col("year_total").alias("t_s1"))
    s2 = _year_total(t, "s", False).select(
        col("c_customer_id").alias("id_s2"),
        col("year_total").alias("t_s2"))
    w1 = _year_total(t, "w", True).select(
        col("c_customer_id").alias("id_w1"),
        col("year_total").alias("t_w1"))
    w2 = _year_total(t, "w", False).select(
        col("c_customer_id").alias("id_w2"),
        col("year_total").alias("t_w2"))
    return (s1.join(s2, col("id_s1") == col("id_s2"))
            .join(w1, col("id_s1") == col("id_w1"))
            .join(w2, col("id_s1") == col("id_w2"))
            .select(col("id_s1").alias("customer_id"),
                    (col("t_s2") / col("t_s1")).alias("storeratio"),
                    (col("t_w2") / col("t_w1")).alias("webratio"))
            .sort("customer_id")
            .limit(100))


def q14(t):
    """Ratio of evening to morning web sales (dinner/breakfast)."""
    am = (t["web_sales"]
          .join(t["time_dim"].filter((col("t_hour") >= lit(7))
                                     & (col("t_hour") <= lit(8)))
                .select(col("t_time_sk").alias("am_sk")),
                col("ws_sold_time_sk") == col("am_sk"))
          .agg(F.sum("ws_ext_sales_price").alias("am_sales")))
    pm = (t["web_sales"]
          .join(t["time_dim"].filter((col("t_hour") >= lit(19))
                                     & (col("t_hour") <= lit(20)))
                .select(col("t_time_sk").alias("pm_sk")),
                col("ws_sold_time_sk") == col("pm_sk"))
          .agg(F.sum("ws_ext_sales_price").alias("pm_sales")))
    return (pm.crossJoin(am)
            .select((col("pm_sales") / col("am_sales"))
                    .alias("pm_am_ratio")))


def q15(t):
    """Store categories with declining sales: per-category monthly
    regression slope via moment sums."""
    monthly = (t["store_sales"]
               .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                     col("ss_sold_date_sk") == col("d_date_sk"))
               .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
               .group_by("i_category_id", "d_moy")
               .agg(F.sum("ss_net_paid").alias("sales")))
    x = col("d_moy").cast("double")
    y = col("sales")
    m = (monthly.group_by("i_category_id")
         .agg(F.count("*").alias("cnt"), F.sum(x).alias("sx"),
              F.sum(y).alias("sy"), F.sum(x * y).alias("sxy"),
              F.sum(x * x).alias("sxx")))
    n = col("cnt").cast("double")
    slope = (n * col("sxy") - col("sx") * col("sy")) / \
        (n * col("sxx") - col("sx") * col("sx"))
    return (m.select(col("i_category_id"), slope.alias("slope"))
            .filter(col("slope") < lit(0.0))
            .sort("i_category_id"))


def q16(t):
    """Web sales net of returns around a pivot date per item/state
    (tpcds q40 shape on the web channel)."""
    import datetime as _dt
    pivot = lit(_dt.date(2001, 3, 16))
    wr = t["web_returns"].select(
        col("wr_order_number").alias("wr_o"),
        col("wr_item_sk").alias("wr_i"),
        col("wr_refunded_cash").alias("refund"))
    j = (t["web_sales"]
         .join(wr, (col("ws_order_number") == col("wr_o"))
               & (col("ws_item_sk") == col("wr_i")), how="left")
         .join(t["warehouse"],
               col("ws_warehouse_sk") == col("w_warehouse_sk"))
         .join(t["item"], col("ws_item_sk") == col("i_item_sk"))
         .join(t["date_dim"].filter(
             (col("d_date") >= lit(_dt.date(2001, 2, 14)))
             & (col("d_date") <= lit(_dt.date(2001, 4, 15)))),
             col("ws_sold_date_sk") == col("d_date_sk")))
    val = col("ws_sales_price") - F.coalesce(col("refund"), lit(0.0))
    return (j.group_by("w_state", "i_item_id")
            .agg(F.sum(F.when(col("d_date") < pivot, val)
                       .otherwise(lit(0.0))).alias("sales_before"),
                 F.sum(F.when(col("d_date") >= pivot, val)
                       .otherwise(lit(0.0))).alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q17(t):
    """Promotional to total store revenue ratio (tpcds q61 shape)."""
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_year") == lit(2001))
                                       & (col("d_moy") == lit(12))),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"].filter(col("i_category").isin(
                "Books", "Music")),
                col("ss_item_sk") == col("i_item_sk")))
    promos = (base.join(t["promotion"].filter(
        (col("p_channel_dmail") == lit("Y"))
        | (col("p_channel_email") == lit("Y"))
        | (col("p_channel_tv") == lit("Y"))),
        col("ss_promo_sk") == col("p_promo_sk"))
        .agg(F.sum("ss_ext_sales_price").alias("promotional")))
    total = base.agg(F.sum("ss_ext_sales_price").alias("total"))
    return (promos.crossJoin(total)
            .select(col("promotional"), col("total"),
                    (col("promotional") * lit(100.0) / col("total"))
                    .alias("promo_percent")))


def q20(t):
    """Customer return behavior features (clustering prep)."""
    sales = (t["store_sales"]
             .group_by("ss_customer_sk")
             .agg(F.count("*").alias("orders"),
                  F.sum("ss_net_paid").alias("spend")))
    rets = (t["store_returns"]
            .group_by("sr_customer_sk")
            .agg(F.count("*").alias("returns_"),
                 F.sum("sr_return_amt").alias("returned")))
    return (sales.join(rets,
                       col("ss_customer_sk") == col("sr_customer_sk"))
            .select(col("ss_customer_sk").alias("user_sk"),
                    (col("returns_").cast("double")
                     / col("orders").cast("double"))
                    .alias("order_ratio"),
                    (col("returned") / col("spend"))
                    .alias("amount_ratio"))
            .sort("user_sk")
            .limit(100))


def q21(t):
    """Items returned in store then re-bought via catalog within 6
    months (tpcds q29 shape)."""
    d1 = (t["date_dim"].filter((col("d_year") == lit(2001))
                               & (col("d_moy") <= lit(6)))
          .select(col("d_date_sk").alias("d1_sk")))
    d2 = (t["date_dim"].filter(col("d_year").isin(2001, 2002))
          .select(col("d_date_sk").alias("d2_sk")))
    return (t["store_sales"]
            .join(d1, col("ss_sold_date_sk") == col("d1_sk"))
            .join(t["store_returns"],
                  (col("ss_ticket_number") == col("sr_ticket_number"))
                  & (col("ss_item_sk") == col("sr_item_sk")))
            .join(d2, col("sr_returned_date_sk") == col("d2_sk"))
            .join(t["catalog_sales"],
                  (col("sr_customer_sk") == col("cs_bill_customer_sk"))
                  & (col("sr_item_sk") == col("cs_item_sk")))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name")
            .agg(F.sum("ss_quantity").alias("store_sales_quantity"),
                 F.sum("sr_return_quantity").alias("returns_quantity"),
                 F.sum("cs_quantity").alias("catalog_quantity"))
            .sort("i_item_id", "s_store_id")
            .limit(100))


def q22(t):
    """Inventory change around a price-change date (tpcds q21 shape)."""
    import datetime as _dt
    pivot = lit(_dt.date(2001, 5, 8))
    j = (t["inventory"]
         .join(t["warehouse"],
               col("inv_warehouse_sk") == col("w_warehouse_sk"))
         .join(t["item"].filter((col("i_current_price") >= lit(10.0))
                                & (col("i_current_price")
                                   <= lit(100.0))),
               col("inv_item_sk") == col("i_item_sk"))
         .join(t["date_dim"].filter(
             (col("d_date") >= lit(_dt.date(2001, 4, 8)))
             & (col("d_date") <= lit(_dt.date(2001, 6, 7)))),
             col("inv_date_sk") == col("d_date_sk")))
    g = (j.group_by("w_warehouse_name", "i_item_id")
         .agg(F.sum(F.when(col("d_date") < pivot,
                           col("inv_quantity_on_hand"))
                    .otherwise(lit(0))).alias("inv_before"),
              F.sum(F.when(col("d_date") >= pivot,
                           col("inv_quantity_on_hand"))
                    .otherwise(lit(0))).alias("inv_after")))
    ratio = col("inv_after").cast("double") / \
        col("inv_before").cast("double")
    return (g.filter((col("inv_before") > lit(0))
                     & (ratio >= lit(2.0 / 3.0))
                     & (ratio <= lit(3.0 / 2.0)))
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def q23(t):
    """Inventory coefficient-of-variation month pairs (tpcds q39
    shape)."""
    from spark_rapids_tpu.bench.tpcds_queries_b import q39
    return q39(t)


def q24(t):
    """Price elasticity: sales while a competitor price window was
    active vs outside it."""
    imp = (t["item_marketprices"]
           .select(col("imp_item_sk").alias("mp_isk"),
                   col("imp_start_date_sk").alias("mp_start"),
                   col("imp_end_date_sk").alias("mp_end")))
    ws = (t["web_sales"]
          .join(imp, col("ws_item_sk") == col("mp_isk"))
          .agg(F.sum(F.when((col("ws_sold_date_sk") >= col("mp_start"))
                            & (col("ws_sold_date_sk")
                               <= col("mp_end")),
                            col("ws_quantity")).otherwise(lit(0)))
               .alias("in_window"),
               F.sum(F.when((col("ws_sold_date_sk") < col("mp_start"))
                            | (col("ws_sold_date_sk")
                               > col("mp_end")),
                            col("ws_quantity")).otherwise(lit(0)))
               .alias("out_window")))
    return ws.select(
        col("in_window"), col("out_window"),
        (col("in_window").cast("double")
         / col("out_window").cast("double")).alias("cross_elasticity"))


def q25(t):
    """Customer recency/frequency/monetary features from both
    channels (segmentation prep)."""
    import datetime as _dt
    cutoff = lit(_dt.date(2002, 1, 2))
    ss = (t["store_sales"]
          .join(t["date_dim"],
                col("ss_sold_date_sk") == col("d_date_sk"))
          .group_by("ss_customer_sk")
          .agg(F.max("d_date").alias("last_store"),
               F.count("*").alias("store_orders"),
               F.sum("ss_net_paid").alias("store_amount")))
    ws = (t["web_sales"]
          .join(t["date_dim"].select(col("d_date_sk").alias("wd_sk"),
                                     col("d_date").alias("w_date")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .group_by("ws_bill_customer_sk")
          .agg(F.max("w_date").alias("last_web"),
               F.count("*").alias("web_orders"),
               F.sum("ws_net_paid").alias("web_amount")))
    return (ss.join(ws, col("ss_customer_sk")
                    == col("ws_bill_customer_sk"))
            .select(col("ss_customer_sk").alias("cid"),
                    F.when(col("last_store") > cutoff, lit(1))
                    .otherwise(lit(0)).alias("store_recent"),
                    F.when(col("last_web") > cutoff, lit(1))
                    .otherwise(lit(0)).alias("web_recent"),
                    (col("store_orders") + col("web_orders"))
                    .alias("frequency"),
                    (col("store_amount") + col("web_amount"))
                    .alias("totalspend"))
            .sort("cid")
            .limit(100))


def q26(t):
    """Per-customer per-class store spend (kmeans feature prep)."""
    classes = ["class01", "class02", "class03", "class04", "class05"]
    base = (t["store_sales"]
            .join(t["item"].filter(col("i_category") == lit("Books")),
                  col("ss_item_sk") == col("i_item_sk")))
    aggs = [F.sum(F.when(col("i_class") == lit(c),
                         col("ss_net_paid")).otherwise(lit(0.0)))
            .alias(f"sum{i + 1}") for i, c in enumerate(classes)]
    return (base.group_by("ss_customer_sk")
            .agg(F.count("*").alias("cnt"), *aggs)
            .filter(col("cnt") >= lit(2))
            .select(col("ss_customer_sk").alias("cid"),
                    *[col(f"sum{i + 1}") for i in range(len(classes))])
            .sort("cid")
            .limit(100))


def q28(t):
    """Sentiment-model train/test split prep over product reviews."""
    base = (t["product_reviews"]
            .filter(~F.isnull(col("pr_review_content")))
            .select(col("pr_review_sk"), col("pr_review_rating"),
                    col("pr_review_content"),
                    (col("pr_review_sk") % lit(10)).alias("bucket")))
    train = (base.filter(col("bucket") < lit(9))
             .select(col("pr_review_sk"), col("pr_review_rating"),
                     col("pr_review_content")))
    test = (base.filter(col("bucket") >= lit(9))
            .select(col("pr_review_sk"), col("pr_review_rating"),
                    col("pr_review_content")))
    tr = train.agg(F.count("*").alias("n_train"))
    te = test.agg(F.count("*").alias("n_test"))
    return tr.crossJoin(te)


QUERIES = {n: fn for n, fn in list(globals().items())
           if n.startswith("q") and n[1:].isdigit()}
