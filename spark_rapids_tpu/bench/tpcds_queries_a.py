"""TpcdsLike queries q1-q33 (DataFrame form).

Reference analog: integration_tests/.../tests/tpcds/TpcdsLikeSpark.scala
(the 99-query "Like" suite).  Queries are original DataFrame-API
re-expressions of the spec's intent over the dbgen-lite schema; SQL
subquery forms are rewritten with the standard planner rewrites:

  IN/EXISTS (subquery)   -> leftsemi join
  NOT IN / NOT EXISTS    -> leftanti join
  scalar subquery        -> crossJoin of a 1-row aggregate
  INTERSECT / EXCEPT     -> distinct + leftsemi / leftanti
  ROLLUP / GROUPING SETS -> UNION of per-level aggregates

q3/q7/q19/q42/q52/q55/q68/q73/q96/q98 live in tpcds.py.
"""

from __future__ import annotations

import datetime as _dt

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window


def _d(y, m, d):
    return lit(_dt.date(y, m, d))


def q1(t):
    """Customers returning more than 1.2x the store-average return."""
    ctr = (t["store_returns"]
           .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                 col("sr_returned_date_sk") == col("d_date_sk"))
           .group_by("sr_customer_sk", "sr_store_sk")
           .agg(F.sum("sr_return_amt").alias("ctr_total_return")))
    avg_ctr = (ctr.group_by("sr_store_sk")
               .agg((F.avg("ctr_total_return") * lit(1.2)).alias("thr"))
               .select(col("sr_store_sk").alias("avg_store_sk"),
                       col("thr")))
    return (ctr
            .join(avg_ctr, col("sr_store_sk") == col("avg_store_sk"))
            .filter(col("ctr_total_return") > col("thr"))
            .join(t["store"].filter(col("s_state").isin(
                "TN", "CA", "TX", "NY", "WA", "GA")),
                col("sr_store_sk") == col("s_store_sk"))
            .join(t["customer"],
                  col("sr_customer_sk") == col("c_customer_sk"))
            .select("c_customer_id")
            .sort("c_customer_id")
            .limit(100))


def q2(t):
    """Web+catalog weekly sales; year-over-year per-day ratios."""
    wscs = (t["web_sales"]
            .select(col("ws_sold_date_sk").alias("sold_date_sk"),
                    col("ws_ext_sales_price").alias("sales_price"))
            .union(t["catalog_sales"]
                   .select(col("cs_sold_date_sk").alias("sold_date_sk"),
                           col("cs_ext_sales_price")
                           .alias("sales_price"))))

    def day(nm):
        return F.sum(F.when(col("d_day_name") == lit(nm),
                            col("sales_price")).otherwise(lit(None)))

    wk = (wscs.join(t["date_dim"],
                    col("sold_date_sk") == col("d_date_sk"))
          .group_by("d_week_seq")
          .agg(day("Sunday").alias("sun_sales"),
               day("Monday").alias("mon_sales"),
               day("Tuesday").alias("tue_sales"),
               day("Wednesday").alias("wed_sales"),
               day("Thursday").alias("thu_sales"),
               day("Friday").alias("fri_sales"),
               day("Saturday").alias("sat_sales")))
    years = (t["date_dim"].select("d_week_seq", "d_year").distinct())
    y1 = (wk.join(years.filter(col("d_year") == lit(2001)),
                  on="d_week_seq")
          .select(col("d_week_seq").alias("w1"),
                  *[col(c).alias(c + "1")
                    for c in ["sun_sales", "mon_sales", "tue_sales",
                              "wed_sales", "thu_sales", "fri_sales",
                              "sat_sales"]]))
    y2 = (wk.join(years.filter(col("d_year") == lit(2002)),
                  on="d_week_seq")
          .select((col("d_week_seq") - lit(53)).alias("w2"),
                  *[col(c).alias(c + "2")
                    for c in ["sun_sales", "mon_sales", "tue_sales",
                              "wed_sales", "thu_sales", "fri_sales",
                              "sat_sales"]]))
    out = y1.join(y2, col("w1") == col("w2"))
    ratios = [(col(c + "1") / col(c + "2")).alias("r_" + c[:3])
              for c in ["sun_sales", "mon_sales", "tue_sales",
                        "wed_sales", "thu_sales", "fri_sales",
                        "sat_sales"]]
    return out.select(col("w1"), *ratios).sort("w1")


def _year_total(t, channel, first: bool):
    """q4/q11/q74 helper: per-customer period revenue for one channel.

    Like-delta: the spec compares two single years; dbgen-lite data is
    too sparse for a per-customer 6-way single-year chain, so period 1 =
    1998-2000 and period 2 = 2001-2002 keep the identical plan shape.
    """
    if channel == "s":
        sales, date_k, cust_k = "store_sales", "ss_sold_date_sk", \
            "ss_customer_sk"
        val = (col("ss_ext_list_price") - col("ss_ext_discount_amt"))
    elif channel == "c":
        sales, date_k, cust_k = "catalog_sales", "cs_sold_date_sk", \
            "cs_bill_customer_sk"
        val = (col("cs_ext_list_price") - col("cs_ext_discount_amt"))
    else:
        sales, date_k, cust_k = "web_sales", "ws_sold_date_sk", \
            "ws_bill_customer_sk"
        val = (col("ws_ext_list_price") - col("ws_ext_discount_amt"))
    dd = t["date_dim"].filter(col("d_year") <= lit(2000) if first
                              else col("d_year") > lit(2000))
    return (t[sales]
            .join(dd, col(date_k) == col("d_date_sk"))
            .join(t["customer"], col(cust_k) == col("c_customer_sk"))
            .group_by("c_customer_id")
            .agg(F.sum(val).alias("year_total"))
            .filter(col("year_total") > lit(0.0)))


def q4(t):
    """Customers whose catalog AND web growth outpaces store growth."""
    s1 = _year_total(t, "s", True).select(
        col("c_customer_id").alias("id_s1"),
        col("year_total").alias("t_s1"))
    s2 = _year_total(t, "s", False).select(
        col("c_customer_id").alias("id_s2"),
        col("year_total").alias("t_s2"))
    c1 = _year_total(t, "c", True).select(
        col("c_customer_id").alias("id_c1"),
        col("year_total").alias("t_c1"))
    c2 = _year_total(t, "c", False).select(
        col("c_customer_id").alias("id_c2"),
        col("year_total").alias("t_c2"))
    w1 = _year_total(t, "w", True).select(
        col("c_customer_id").alias("id_w1"),
        col("year_total").alias("t_w1"))
    w2 = _year_total(t, "w", False).select(
        col("c_customer_id").alias("id_w2"),
        col("year_total").alias("t_w2"))
    j = (s1.join(s2, col("id_s1") == col("id_s2"))
         .join(c1, col("id_s1") == col("id_c1"))
         .join(c2, col("id_s1") == col("id_c2"))
         .join(w1, col("id_s1") == col("id_w1"))
         .join(w2, col("id_s1") == col("id_w2")))
    return (j.filter((col("t_c2") / col("t_c1")
                      > col("t_s2") / col("t_s1"))
                     & (col("t_c2") / col("t_c1")
                        > col("t_w2") / col("t_w1")))
            .select(col("id_s1").alias("customer_id"))
            .sort("customer_id")
            .limit(100))


def q5(t):
    """Channel profit/loss rollup over sales + returns."""
    ss = (t["store_sales"]
          .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
          .group_by("s_store_id")
          .agg(F.sum("ss_ext_sales_price").alias("sales"),
               F.sum("ss_net_profit").alias("profit"))
          .select(lit("store channel").alias("channel"),
                  col("s_store_id").alias("id"), col("sales"),
                  col("profit")))
    cs = (t["catalog_sales"]
          .join(t["catalog_page"],
                col("cs_catalog_page_sk") == col("cp_catalog_page_sk"))
          .group_by("cp_catalog_page_id")
          .agg(F.sum("cs_ext_sales_price").alias("sales"),
               F.sum("cs_net_profit").alias("profit"))
          .select(lit("catalog channel").alias("channel"),
                  col("cp_catalog_page_id").alias("id"), col("sales"),
                  col("profit")))
    ws = (t["web_sales"]
          .join(t["web_site"],
                col("ws_web_site_sk") == col("web_site_sk"))
          .group_by("web_site_id")
          .agg(F.sum("ws_ext_sales_price").alias("sales"),
               F.sum("ws_net_profit").alias("profit"))
          .select(lit("web channel").alias("channel"),
                  col("web_site_id").alias("id"), col("sales"),
                  col("profit")))
    detail = ss.union(cs).union(ws)
    per_channel = (detail.group_by("channel")
                   .agg(F.sum("sales").alias("sales"),
                        F.sum("profit").alias("profit"))
                   .select(col("channel"), lit(None).cast("string")
                           .alias("id"), col("sales"), col("profit")))
    total = (detail.agg(F.sum("sales").alias("sales"),
                        F.sum("profit").alias("profit"))
             .select(lit(None).cast("string").alias("channel"),
                     lit(None).cast("string").alias("id"),
                     col("sales"), col("profit")))
    return (detail.union(per_channel).union(total)
            .sort(col("channel").asc_nulls_last(),
                  col("id").asc_nulls_last(), col("sales").desc())
            .limit(100))


def q6(t):
    """States with 10+ customers buying items priced >= 1.2x their
    category average in one month."""
    cat_avg = (t["item"].group_by("i_category")
               .agg((F.avg("i_current_price") * lit(1.2)).alias("thr"))
               .select(col("i_category").alias("avg_cat"), col("thr")))
    items = (t["item"]
             .join(cat_avg, col("i_category") == col("avg_cat"))
             .filter(col("i_current_price") > col("thr")))
    return (t["store_sales"]
            .join(t["date_dim"].filter((col("d_year") == lit(2000))
                                       & (col("d_moy") == lit(1))),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(items, col("ss_item_sk") == col("i_item_sk"))
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by("ca_state")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(10))
            .sort(col("cnt").asc(), col("ca_state").asc())
            .limit(100))


def q8(t):
    """Store net profit for stores in preferred-customer zip codes.

    The spec INTERSECTs a literal 400-zip list with zips that have >1
    preferred customers; Like version keeps the data-driven side (the
    INTERSECT-as-semi-join shape) since random zips rarely hit literals.
    """
    pref = (t["customer"].filter(col("c_preferred_cust_flag") == lit("Y"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by("ca_zip")
            .agg(F.count("*").alias("cnt"))
            .filter(col("cnt") > lit(1))
            .select(F.substring(col("ca_zip"), 1, 2).alias("zip2"))
            .distinct())
    return (t["store_sales"]
            .join(t["date_dim"].filter((col("d_qoy") == lit(2))
                                       & (col("d_year") == lit(1998))),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .with_column("s_zip2", F.substring(col("s_zip"), 1, 2))
            .join(pref, col("s_zip2") == col("zip2"), how="leftsemi")
            .group_by("s_store_name")
            .agg(F.sum("ss_net_profit").alias("profit"))
            .sort("s_store_name")
            .limit(100))


def q9(t):
    """Bucketed quantity statistics pivoted into one row."""
    buckets = [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)]
    aggs = []
    for i, (lo, hi) in enumerate(buckets, 1):
        in_b = (col("ss_quantity") >= lit(lo)) & \
            (col("ss_quantity") <= lit(hi))
        aggs.append(F.sum(F.when(in_b, lit(1)).otherwise(lit(0)))
                    .alias(f"cnt{i}"))
        aggs.append(F.avg(F.when(in_b, col("ss_ext_discount_amt"))
                          .otherwise(lit(None))).alias(f"avg_disc{i}"))
        aggs.append(F.avg(F.when(in_b, col("ss_net_paid"))
                          .otherwise(lit(None))).alias(f"avg_paid{i}"))
    stats = t["store_sales"].agg(*aggs)
    picks = []
    for i in range(1, 6):
        picks.append(F.when(col(f"cnt{i}") > lit(100),
                            col(f"avg_disc{i}"))
                     .otherwise(col(f"avg_paid{i}")).alias(f"bucket{i}"))
    return (t["reason"].filter(col("r_reason_sk") == lit(1))
            .crossJoin(stats)
            .select(*picks))


def q10(t):
    """Demographic counts for county customers active in any channel."""
    c = (t["customer"]
         .join(t["customer_address"].filter(
             col("ca_county").isin("Williamson County", "Ziebach County",
                                   "Walker County")),
             col("c_current_addr_sk") == col("ca_address_sk")))
    dd = t["date_dim"].filter((col("d_year") == lit(2000))
                              & (col("d_moy") >= lit(1))
                              & (col("d_moy") <= lit(4)))
    ss_c = (t["store_sales"]
            .join(dd.select("d_date_sk"),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .select(col("ss_customer_sk").alias("act_sk")))
    ws_c = (t["web_sales"]
            .join(dd.select(col("d_date_sk").alias("wd_sk")),
                  col("ws_sold_date_sk") == col("wd_sk"))
            .select(col("ws_bill_customer_sk").alias("act_sk")))
    cs_c = (t["catalog_sales"]
            .join(dd.select(col("d_date_sk").alias("cd_sk")),
                  col("cs_sold_date_sk") == col("cd_sk"))
            .select(col("cs_bill_customer_sk").alias("act_sk")))
    c = c.join(ss_c, col("c_customer_sk") == col("act_sk"),
               how="leftsemi")
    c = c.join(ws_c.union(cs_c), col("c_customer_sk") == col("act_sk"),
               how="leftsemi")
    return (c.join(t["customer_demographics"],
                   col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .group_by("cd_gender", "cd_marital_status",
                      "cd_education_status", "cd_purchase_estimate",
                      "cd_credit_rating")
            .agg(F.count("*").alias("cnt"))
            .sort("cd_gender", "cd_marital_status",
                  "cd_education_status", "cd_purchase_estimate",
                  "cd_credit_rating")
            .limit(100))


def q11(t):
    """Customers whose web growth outpaces store growth (2-channel q4)."""
    s1 = _year_total(t, "s", True).select(
        col("c_customer_id").alias("id_s1"),
        col("year_total").alias("t_s1"))
    s2 = _year_total(t, "s", False).select(
        col("c_customer_id").alias("id_s2"),
        col("year_total").alias("t_s2"))
    w1 = _year_total(t, "w", True).select(
        col("c_customer_id").alias("id_w1"),
        col("year_total").alias("t_w1"))
    w2 = _year_total(t, "w", False).select(
        col("c_customer_id").alias("id_w2"),
        col("year_total").alias("t_w2"))
    return (s1.join(s2, col("id_s1") == col("id_s2"))
            .join(w1, col("id_s1") == col("id_w1"))
            .join(w2, col("id_s1") == col("id_w2"))
            .filter(col("t_w2") / col("t_w1")
                    > col("t_s2") / col("t_s1"))
            .select(col("id_s1").alias("customer_id"))
            .sort("customer_id")
            .limit(100))


def q12(t):
    """Web item revenue + share of class revenue (q98 web version)."""
    base = (t["web_sales"]
            .join(t["item"].filter(
                col("i_category").isin("Sports", "Books", "Home")),
                col("ws_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(
                (col("d_date") >= _d(1999, 2, 22))
                & (col("d_date") <= _d(1999, 3, 24))),
                col("ws_sold_date_sk") == col("d_date_sk"))
            .group_by("i_item_id", "i_item_desc", "i_category",
                      "i_class", "i_current_price")
            .agg(F.sum("ws_ext_sales_price").alias("itemrevenue")))
    return (base.select(
        col("i_item_id"), col("i_item_desc"), col("i_category"),
        col("i_class"), col("i_current_price"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(
             Window.partition_by("i_class"))).alias("revenueratio"))
        .sort("i_category", "i_class", "i_item_id", "i_item_desc",
              "revenueratio")
        .limit(100))


def q13(t):
    """Averages under OR'd demographic x address conditions."""
    cd_ok = ((col("cd_marital_status") == lit("M"))
             & (col("cd_education_status") == lit("College"))
             & (col("ss_sales_price") >= lit(100.0))) | \
            ((col("cd_marital_status") == lit("S"))
             & (col("cd_education_status") == lit("Primary"))
             & (col("ss_sales_price") >= lit(50.0))) | \
            ((col("cd_marital_status") == lit("W"))
             & (col("cd_education_status") == lit("2 yr Degree")))
    ca_ok = (col("ca_state").isin("TX", "OH", "CA")
             | col("ca_state").isin("WA", "NY", "GA"))
    return (t["store_sales"]
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .join(t["customer_demographics"],
                  col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["household_demographics"],
                  col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(t["customer_address"],
                  col("ss_addr_sk") == col("ca_address_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(cd_ok & ca_ok)
            .agg(F.avg("ss_quantity").alias("avg_qty"),
                 F.avg("ss_ext_sales_price").alias("avg_esp"),
                 F.avg("ss_ext_wholesale_cost").alias("avg_ewc"),
                 F.sum("ss_ext_wholesale_cost").alias("sum_ewc")))


def q14(t):
    """Cross-channel items: brands sold in all three channels, per-channel
    sales above the all-channel average (iceberg lite)."""
    ss_b = (t["store_sales"]
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .select(col("i_brand_id").alias("b1")).distinct())
    cs_b = (t["catalog_sales"]
            .join(t["item"].select(col("i_item_sk").alias("ci_sk"),
                                   col("i_brand_id").alias("b2")),
                  col("cs_item_sk") == col("ci_sk"))
            .select("b2").distinct())
    ws_b = (t["web_sales"]
            .join(t["item"].select(col("i_item_sk").alias("wi_sk"),
                                   col("i_brand_id").alias("b3")),
                  col("ws_item_sk") == col("wi_sk"))
            .select("b3").distinct())
    cross = (ss_b.join(cs_b, col("b1") == col("b2"), how="leftsemi")
             .join(ws_b, col("b1") == col("b3"), how="leftsemi"))
    avg_sales = (t["store_sales"]
                 .select((col("ss_quantity") * col("ss_list_price"))
                         .alias("v"))
                 .union(t["catalog_sales"].select(
                     (col("cs_quantity") * col("cs_list_price"))
                     .alias("v")))
                 .union(t["web_sales"].select(
                     (col("ws_quantity") * col("ws_list_price"))
                     .alias("v")))
                 .agg(F.avg("v").alias("average_sales")))
    return (t["store_sales"]
            .join(t["date_dim"].filter((col("d_year") == lit(2001))
                                       & (col("d_moy") == lit(11))),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .join(cross, col("i_brand_id") == col("b1"),
                  how="leftsemi")
            .group_by("i_brand_id", "i_class_id", "i_category_id")
            .agg(F.sum(col("ss_quantity") * col("ss_list_price"))
                 .alias("sales"), F.count("*").alias("number_sales"))
            .crossJoin(avg_sales)
            .filter(col("sales") > col("average_sales"))
            .select(lit("store").alias("channel"), col("i_brand_id"),
                    col("i_class_id"), col("i_category_id"),
                    col("sales"), col("number_sales"))
            .sort("i_brand_id", "i_class_id", "i_category_id")
            .limit(100))


def q15(t):
    """Catalog sales by customer zip for qualifying geographies."""
    return (t["catalog_sales"]
            .join(t["customer"],
                  col("cs_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["date_dim"].filter((col("d_qoy") == lit(2))
                                       & (col("d_year") == lit(2001))),
                  col("cs_sold_date_sk") == col("d_date_sk"))
            .filter(F.substring(col("ca_zip"), 1, 2)
                    .isin("85", "86", "88", "89", "80", "81", "30", "31")
                    | col("ca_state").isin("CA", "WA", "GA")
                    | (col("cs_sales_price") > lit(500.0)))
            .group_by("ca_zip")
            .agg(F.sum("cs_sales_price").alias("total"))
            .sort("ca_zip")
            .limit(100))


def q16(t):
    """Catalog orders shipped from one state: multi-warehouse orders
    without returns (EXISTS/NOT EXISTS via semi/anti joins)."""
    cs1 = (t["catalog_sales"]
           .join(t["date_dim"].filter(
               (col("d_date") >= _d(2002, 2, 1))
               & (col("d_date") <= _d(2002, 4, 2))),
               col("cs_ship_date_sk") == col("d_date_sk"))
           .join(t["customer_address"].filter(
               col("ca_state") == lit("GA")),
               col("cs_ship_addr_sk") == col("ca_address_sk"))
           .join(t["call_center"],
                 col("cs_call_center_sk") == col("cc_call_center_sk")))
    # EXISTS (same order, different warehouse) -> orders spanning >1
    # distinct warehouse, then a plain semi join on the order number
    multi_wh = (t["catalog_sales"]
                .group_by("cs_order_number")
                .agg(F.count_distinct(col("cs_warehouse_sk"))
                     .alias("n_wh"))
                .filter(col("n_wh") > lit(1))
                .select(col("cs_order_number").alias("o2")))
    returned = t["catalog_returns"].select(
        col("cr_order_number").alias("ro"))
    base = (cs1
            .join(multi_wh, col("cs_order_number") == col("o2"),
                  how="leftsemi")
            .join(returned, col("cs_order_number") == col("ro"),
                  how="leftanti"))
    dist = (base.select("cs_order_number").distinct()
            .agg(F.count("*").alias("order_count")))
    return (base.agg(F.sum("cs_ext_ship_cost")
                     .alias("total_shipping_cost"),
                     F.sum("cs_net_profit").alias("total_net_profit"))
            .crossJoin(dist)
            .select("order_count", "total_shipping_cost",
                    "total_net_profit"))


def _stddev(sum_sq, sum_, cnt):
    """Sample stddev from (sum of squares, sum, count) aggregates."""
    n = cnt.cast("double")
    var = (sum_sq - sum_ * sum_ / n) / (n - lit(1.0))
    return F.sqrt(F.when(n > lit(1.0), var).otherwise(lit(None)))


def q17(t):
    """Store purchase/return/catalog-repurchase quantity stats."""
    d1 = (t["date_dim"].filter(col("d_quarter_name") == lit("2001Q1"))
          .select(col("d_date_sk").alias("d1_sk")))
    d2 = (t["date_dim"].filter(
        col("d_quarter_name").isin("2001Q1", "2001Q2", "2001Q3"))
        .select(col("d_date_sk").alias("d2_sk")))
    d3 = (t["date_dim"].filter(
        col("d_quarter_name").isin("2001Q1", "2001Q2", "2001Q3"))
        .select(col("d_date_sk").alias("d3_sk")))
    j = (t["store_sales"]
         .join(d1, col("ss_sold_date_sk") == col("d1_sk"))
         .join(t["store_returns"],
               (col("ss_ticket_number") == col("sr_ticket_number"))
               & (col("ss_item_sk") == col("sr_item_sk")))
         .join(d2, col("sr_returned_date_sk") == col("d2_sk"))
         .join(t["catalog_sales"],
               (col("sr_customer_sk") == col("cs_bill_customer_sk"))
               & (col("sr_item_sk") == col("cs_item_sk")))
         .join(d3, col("cs_sold_date_sk") == col("d3_sk"))
         .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
         .join(t["store"], col("ss_store_sk") == col("s_store_sk")))
    q = col("ss_quantity").cast("double")
    return (j.group_by("i_item_id", "i_item_desc", "s_state")
            .agg(F.count("*").alias("store_sales_quantitycount"),
                 F.avg("ss_quantity").alias("store_sales_quantityave"),
                 F.sum(q * q).alias("ssq2"),
                 F.sum(q).alias("ssq1"))
            .select(col("i_item_id"), col("i_item_desc"), col("s_state"),
                    col("store_sales_quantitycount"),
                    col("store_sales_quantityave"),
                    _stddev(col("ssq2"), col("ssq1"),
                            col("store_sales_quantitycount"))
                    .alias("store_sales_quantitystdev"))
            .sort("i_item_id", "i_item_desc", "s_state")
            .limit(100))


def q18(t):
    """Catalog averages by customer geography rollup."""
    base = (t["catalog_sales"]
            .join(t["customer_demographics"].filter(
                (col("cd_gender") == lit("F"))
                & (col("cd_education_status") == lit("Unknown"))),
                col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
            .join(t["customer"].filter(col("c_birth_month").isin(
                1, 6, 8, 9, 12, 2)),
                col("cs_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"].filter(
                col("ca_state").isin("CA", "NY", "TX", "OH", "WA")),
                col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(1998)),
                  col("cs_sold_date_sk") == col("d_date_sk")))

    def level(keys, names):
        sel = [col(k).alias(n) for k, n in zip(keys, names)]
        sel += [lit(None).cast("string").alias(n)
                for n in ["ca_country", "ca_state", "ca_county"]
                [len(keys):]]
        return (base.group_by(*keys).agg(
            F.avg(col("cs_quantity").cast("double")).alias("agg1"),
            F.avg(col("cs_list_price").cast("double")).alias("agg2"),
            F.avg(col("cs_coupon_amt").cast("double")).alias("agg3"),
            F.avg(col("cs_sales_price").cast("double")).alias("agg4"))
            .select(*sel, col("agg1"), col("agg2"), col("agg3"),
                    col("agg4"))) if keys else \
            (base.agg(
                F.avg(col("cs_quantity").cast("double")).alias("agg1"),
                F.avg(col("cs_list_price").cast("double")).alias("agg2"),
                F.avg(col("cs_coupon_amt").cast("double")).alias("agg3"),
                F.avg(col("cs_sales_price").cast("double"))
                .alias("agg4"))
             .select(lit(None).cast("string").alias("ca_country"),
                     lit(None).cast("string").alias("ca_state"),
                     lit(None).cast("string").alias("ca_county"),
                     col("agg1"), col("agg2"), col("agg3"),
                     col("agg4")))

    lvl3 = level(["ca_country", "ca_state", "ca_county"],
                 ["ca_country", "ca_state", "ca_county"])
    lvl2 = level(["ca_country", "ca_state"], ["ca_country", "ca_state"])
    lvl1 = level(["ca_country"], ["ca_country"])
    lvl0 = level([], [])
    return (lvl3.union(lvl2).union(lvl1).union(lvl0)
            .sort(col("ca_country").asc_nulls_last(),
                  col("ca_state").asc_nulls_last(),
                  col("ca_county").asc_nulls_last())
            .limit(100))


def q20(t):
    """Catalog item revenue + class share (q98 catalog version)."""
    base = (t["catalog_sales"]
            .join(t["item"].filter(
                col("i_category").isin("Sports", "Books", "Home")),
                col("cs_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(
                (col("d_date") >= _d(1999, 2, 22))
                & (col("d_date") <= _d(1999, 3, 24))),
                col("cs_sold_date_sk") == col("d_date_sk"))
            .group_by("i_item_id", "i_item_desc", "i_category",
                      "i_class", "i_current_price")
            .agg(F.sum("cs_ext_sales_price").alias("itemrevenue")))
    return (base.select(
        col("i_item_id"), col("i_item_desc"), col("i_category"),
        col("i_class"), col("i_current_price"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(
             Window.partition_by("i_class"))).alias("revenueratio"))
        .sort("i_category", "i_class", "i_item_id", "i_item_desc",
              "revenueratio")
        .limit(100))


def q21(t):
    """Inventory level change around a date per warehouse/item."""
    pivot = _d(2000, 3, 11)
    j = (t["inventory"]
         .join(t["warehouse"],
               col("inv_warehouse_sk") == col("w_warehouse_sk"))
         .join(t["item"], col("inv_item_sk") == col("i_item_sk"))
         .join(t["date_dim"].filter(
             (col("d_date") >= _d(2000, 2, 10))
             & (col("d_date") <= _d(2000, 4, 10))),
             col("inv_date_sk") == col("d_date_sk")))
    g = (j.group_by("w_warehouse_name", "i_item_id")
         .agg(F.sum(F.when(col("d_date") < pivot,
                           col("inv_quantity_on_hand"))
                    .otherwise(lit(0))).alias("inv_before"),
              F.sum(F.when(col("d_date") >= pivot,
                           col("inv_quantity_on_hand"))
                    .otherwise(lit(0))).alias("inv_after")))
    ratio = col("inv_after").cast("double") / \
        col("inv_before").cast("double")
    return (g.filter((col("inv_before") > lit(0))
                     & (ratio >= lit(2.0 / 3.0))
                     & (ratio <= lit(3.0 / 2.0)))
            .sort("w_warehouse_name", "i_item_id")
            .limit(100))


def q22(t):
    """Average inventory quantity rollup over the item hierarchy."""
    base = (t["inventory"]
            .join(t["date_dim"].filter(
                (col("d_month_seq") >= lit(120))
                & (col("d_month_seq") <= lit(131))),
                col("inv_date_sk") == col("d_date_sk"))
            .join(t["item"], col("inv_item_sk") == col("i_item_sk")))

    def level(keys):
        names = ["i_product_name", "i_brand", "i_class", "i_category"]
        sel = [col(k) for k in keys] + \
            [lit(None).cast("string").alias(n) for n in names[len(keys):]]
        if keys:
            return (base.group_by(*keys)
                    .agg(F.avg("inv_quantity_on_hand").alias("qoh"))
                    .select(*sel, col("qoh")))
        return (base.agg(F.avg("inv_quantity_on_hand").alias("qoh"))
                .select(*sel, col("qoh")))

    return (level(["i_product_name", "i_brand", "i_class", "i_category"])
            .union(level(["i_product_name", "i_brand", "i_class"]))
            .union(level(["i_product_name", "i_brand"]))
            .union(level(["i_product_name"]))
            .union(level([]))
            .sort(col("qoh").asc(),
                  col("i_product_name").asc_nulls_last(),
                  col("i_brand").asc_nulls_last(),
                  col("i_class").asc_nulls_last(),
                  col("i_category").asc_nulls_last())
            .limit(100))


def q23(t):
    """Best customers buying frequent items (iceberg lite)."""
    frequent = (t["store_sales"]
                .join(t["date_dim"].filter(
                    col("d_year").isin(2000, 2001)),
                    col("ss_sold_date_sk") == col("d_date_sk"))
                .group_by("ss_item_sk")
                .agg(F.count("*").alias("cnt"))
                .filter(col("cnt") > lit(4))
                .select(col("ss_item_sk").alias("freq_sk")))
    spenders = (t["store_sales"]
                .group_by("ss_customer_sk")
                .agg(F.sum(col("ss_quantity").cast("double")
                           * col("ss_sales_price")).alias("csales")))
    max_sales = (spenders.agg((F.max("csales") * lit(0.5))
                              .alias("tpcds_cmax")))
    best = (spenders.crossJoin(max_sales)
            .filter(col("csales") > col("tpcds_cmax"))
            .select(col("ss_customer_sk").alias("best_sk")))
    cs = (t["catalog_sales"]
          .join(t["date_dim"].filter((col("d_year") == lit(2000))
                                     & (col("d_moy") == lit(3))),
                col("cs_sold_date_sk") == col("d_date_sk"))
          .join(frequent, col("cs_item_sk") == col("freq_sk"),
                how="leftsemi")
          .join(best, col("cs_bill_customer_sk") == col("best_sk"),
                how="leftsemi")
          .select((col("cs_quantity").cast("double")
                   * col("cs_list_price")).alias("sales")))
    ws = (t["web_sales"]
          .join(t["date_dim"].filter((col("d_year") == lit(2000))
                                     & (col("d_moy") == lit(3)))
                .select(col("d_date_sk").alias("wd_sk")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .join(frequent, col("ws_item_sk") == col("freq_sk"),
                how="leftsemi")
          .join(best, col("ws_bill_customer_sk") == col("best_sk"),
                how="leftsemi")
          .select((col("ws_quantity").cast("double")
                   * col("ws_list_price")).alias("sales")))
    return cs.union(ws).agg(F.sum("sales").alias("total"))


def q24(t):
    """Customer net paid per color for same-state store customers."""
    ssales = (t["store_sales"]
              .join(t["store_returns"],
                    (col("ss_ticket_number") == col("sr_ticket_number"))
                    & (col("ss_item_sk") == col("sr_item_sk")))
              .join(t["store"].filter(col("s_market_id") <= lit(5)),
                    col("ss_store_sk") == col("s_store_sk"))
              .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
              .join(t["customer"],
                    col("ss_customer_sk") == col("c_customer_sk"))
              .filter(col("c_birth_country") != lit("Mexico"))
              .group_by("c_last_name", "c_first_name", "s_store_name",
                        "i_color")
              .agg(F.sum("ss_net_paid").alias("netpaid")))
    avg_paid = ssales.agg((F.avg("netpaid") * lit(0.05)).alias("thr"))
    return (ssales.crossJoin(avg_paid)
            .filter(col("netpaid") > col("thr"))
            .select("c_last_name", "c_first_name", "s_store_name",
                    "i_color", "netpaid")
            .sort("c_last_name", "c_first_name", "s_store_name",
                  "i_color")
            .limit(100))


def q25(t):
    """Store purchase -> return -> catalog repurchase profit chain.
    (Like-delta: wider month windows than the spec's 4..10 single year —
    dbgen-lite chains are sparse.)"""
    d1 = (t["date_dim"].filter((col("d_moy") <= lit(6))
                               & (col("d_year") == lit(2001)))
          .select(col("d_date_sk").alias("d1_sk")))
    d2 = (t["date_dim"].filter(col("d_year").isin(2001, 2002))
          .select(col("d_date_sk").alias("d2_sk")))
    d3 = (t["date_dim"].filter(col("d_year").isin(2001, 2002))
          .select(col("d_date_sk").alias("d3_sk")))
    return (t["store_sales"]
            .join(d1, col("ss_sold_date_sk") == col("d1_sk"))
            .join(t["store_returns"],
                  (col("ss_ticket_number") == col("sr_ticket_number"))
                  & (col("ss_item_sk") == col("sr_item_sk")))
            .join(d2, col("sr_returned_date_sk") == col("d2_sk"))
            .join(t["catalog_sales"],
                  (col("sr_customer_sk") == col("cs_bill_customer_sk"))
                  & (col("sr_item_sk") == col("cs_item_sk")))
            .join(d3, col("cs_sold_date_sk") == col("d3_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name")
            .agg(F.sum("ss_net_profit").alias("store_sales_profit"),
                 F.sum("sr_net_loss").alias("store_returns_loss"),
                 F.sum("cs_net_profit").alias("catalog_sales_profit"))
            .sort("i_item_id", "i_item_desc", "s_store_id",
                  "s_store_name")
            .limit(100))


def q26(t):
    """Catalog demographic/promo item averages (q7 catalog version)."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == lit("M"))
        & (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College")))
    promo = t["promotion"].filter(
        (col("p_channel_email") == lit("N"))
        | (col("p_channel_event") == lit("N")))
    return (t["catalog_sales"]
            .join(cd, col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("cs_sold_date_sk") == col("d_date_sk"))
            .join(promo, col("cs_promo_sk") == col("p_promo_sk"))
            .join(t["item"], col("cs_item_sk") == col("i_item_sk"))
            .group_by("i_item_id")
            .agg(F.avg("cs_quantity").alias("agg1"),
                 F.avg("cs_list_price").alias("agg2"),
                 F.avg("cs_coupon_amt").alias("agg3"),
                 F.avg("cs_sales_price").alias("agg4"))
            .sort("i_item_id")
            .limit(100))


def q27(t):
    """Store demographic item/state averages with rollup."""
    base = (t["store_sales"]
            .join(t["customer_demographics"].filter(
                (col("cd_gender") == lit("M"))
                & (col("cd_marital_status") == lit("S"))
                & (col("cd_education_status") == lit("College"))),
                col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"].filter(col("s_state").isin("TN", "CA")),
                  col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk")))

    def agg4(df):
        return df.agg(F.avg("ss_quantity").alias("agg1"),
                      F.avg("ss_list_price").alias("agg2"),
                      F.avg("ss_coupon_amt").alias("agg3"),
                      F.avg("ss_sales_price").alias("agg4"))

    lvl2 = (agg4(base.group_by("i_item_id", "s_state"))
            .select(col("i_item_id"), col("s_state"),
                    lit(0).alias("g_state"), col("agg1"), col("agg2"),
                    col("agg3"), col("agg4")))
    lvl1 = (agg4(base.group_by("i_item_id"))
            .select(col("i_item_id"),
                    lit(None).cast("string").alias("s_state"),
                    lit(1).alias("g_state"), col("agg1"), col("agg2"),
                    col("agg3"), col("agg4")))
    lvl0 = (agg4(base)
            .select(lit(None).cast("string").alias("i_item_id"),
                    lit(None).cast("string").alias("s_state"),
                    lit(1).alias("g_state"), col("agg1"), col("agg2"),
                    col("agg3"), col("agg4")))
    return (lvl2.union(lvl1).union(lvl0)
            .sort(col("i_item_id").asc_nulls_last(),
                  col("s_state").asc_nulls_last())
            .limit(100))


def q28(t):
    """Six price-bucket averages/distinct counts cross-joined."""
    buckets = [(0, 5, 11, 460, 14, 194), (6, 10, 91, 1430, 30, 864),
               (11, 15, 66, 1546, 28, 724), (16, 20, 142, 3636, 60, 932),
               (21, 25, 135, 3619, 53, 1136),
               (26, 30, 28, 2513, 45, 1006)]
    out = None
    for i, (qlo, qhi, lp_lo, _lp, cp_lo, wc_lo) in enumerate(buckets, 1):
        f = (t["store_sales"]
             .filter((col("ss_quantity") >= lit(qlo))
                     & (col("ss_quantity") <= lit(qhi))
                     & ((col("ss_list_price") >= lit(float(lp_lo)))
                        | (col("ss_coupon_amt") >= lit(float(cp_lo)))
                        | (col("ss_wholesale_cost")
                           >= lit(float(wc_lo))))))
        b = f.agg(F.avg("ss_list_price").alias(f"b{i}_lp"),
                  F.count("ss_list_price").alias(f"b{i}_cnt"))
        bd = (f.select("ss_list_price").distinct()
              .agg(F.count("*").alias(f"b{i}_cntd")))
        b = b.crossJoin(bd)
        out = b if out is None else out.crossJoin(b)
    return out


def q29(t):
    """q25 chain with quantity aggregates."""
    d1 = (t["date_dim"].filter((col("d_moy") == lit(4))
                               & (col("d_year") == lit(1999)))
          .select(col("d_date_sk").alias("d1_sk")))
    d2 = (t["date_dim"].filter((col("d_moy") >= lit(4))
                               & (col("d_moy") <= lit(7))
                               & (col("d_year") == lit(1999)))
          .select(col("d_date_sk").alias("d2_sk")))
    d3 = (t["date_dim"].filter(col("d_year").isin(1999, 2000, 2001))
          .select(col("d_date_sk").alias("d3_sk")))
    return (t["store_sales"]
            .join(d1, col("ss_sold_date_sk") == col("d1_sk"))
            .join(t["store_returns"],
                  (col("ss_ticket_number") == col("sr_ticket_number"))
                  & (col("ss_item_sk") == col("sr_item_sk")))
            .join(d2, col("sr_returned_date_sk") == col("d2_sk"))
            .join(t["catalog_sales"],
                  (col("sr_customer_sk") == col("cs_bill_customer_sk"))
                  & (col("sr_item_sk") == col("cs_item_sk")))
            .join(d3, col("cs_sold_date_sk") == col("d3_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_item_id", "i_item_desc", "s_store_id",
                      "s_store_name")
            .agg(F.sum("ss_quantity").alias("store_sales_quantity"),
                 F.sum("sr_return_quantity")
                 .alias("store_returns_quantity"),
                 F.sum("cs_quantity").alias("catalog_sales_quantity"))
            .sort("i_item_id", "i_item_desc", "s_store_id",
                  "s_store_name")
            .limit(100))


def q30(t):
    """Web customers returning >1.2x state average (q1 web version)."""
    ctr = (t["web_returns"]
           .join(t["date_dim"].filter(col("d_year") == lit(2002)),
                 col("wr_returned_date_sk") == col("d_date_sk"))
           .join(t["customer_address"],
                 col("wr_refunded_addr_sk") == col("ca_address_sk"))
           .group_by("wr_returning_customer_sk", "ca_state")
           .agg(F.sum("wr_return_amt").alias("ctr_total_return")))
    avg_ctr = (ctr.group_by("ca_state")
               .agg((F.avg("ctr_total_return") * lit(1.2)).alias("thr"))
               .select(col("ca_state").alias("avg_state"), col("thr")))
    return (ctr
            .join(avg_ctr, col("ca_state") == col("avg_state"))
            .filter(col("ctr_total_return") > col("thr"))
            .join(t["customer"],
                  col("wr_returning_customer_sk")
                  == col("c_customer_sk"))
            .select("c_customer_id", "c_salutation", "c_first_name",
                    "c_last_name", "c_preferred_cust_flag",
                    "c_birth_day", "c_birth_month", "c_birth_year",
                    "c_birth_country", "ctr_total_return")
            .sort("c_customer_id", "ctr_total_return")
            .limit(100))


def q31(t):
    """Counties where web growth outpaces store growth across quarters."""
    ss = (t["store_sales"]
          .join(t["customer_address"],
                col("ss_addr_sk") == col("ca_address_sk"))
          .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                col("ss_sold_date_sk") == col("d_date_sk"))
          .group_by("ca_county", "d_qoy")
          .agg(F.sum("ss_ext_sales_price").alias("store_sales")))
    ws = (t["web_sales"]
          .join(t["customer_address"].select(
              col("ca_address_sk").alias("wca_sk"),
              col("ca_county").alias("w_county")),
              col("ws_bill_addr_sk") == col("wca_sk"))
          .join(t["date_dim"].filter(col("d_year") == lit(2000))
                .select(col("d_date_sk").alias("wd_sk"),
                        col("d_qoy").alias("w_qoy")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .group_by("w_county", "w_qoy")
          .agg(F.sum("ws_ext_sales_price").alias("web_sales")))

    def pick(df, q, kc, vc, ka, va):
        return (df.filter(col(q[0]) == lit(q[1]))
                .select(col(kc).alias(ka), col(vc).alias(va)))

    ss1 = pick(ss, ("d_qoy", 1), "ca_county", "store_sales",
               "county_s1", "ss1")
    ss2 = pick(ss, ("d_qoy", 2), "ca_county", "store_sales",
               "county_s2", "ss2")
    ws1 = pick(ws, ("w_qoy", 1), "w_county", "web_sales",
               "county_w1", "ws1")
    ws2 = pick(ws, ("w_qoy", 2), "w_county", "web_sales",
               "county_w2", "ws2")
    return (ss1.join(ss2, col("county_s1") == col("county_s2"))
            .join(ws1, col("county_s1") == col("county_w1"))
            .join(ws2, col("county_s1") == col("county_w2"))
            .filter((col("ss1") > lit(0.0)) & (col("ws1") > lit(0.0))
                    & (col("ws2") / col("ws1")
                       > col("ss2") / col("ss1")))
            .select(col("county_s1").alias("ca_county"),
                    (col("ws2") / col("ws1")).alias("web_q1_q2_increase"),
                    (col("ss2") / col("ss1"))
                    .alias("store_q1_q2_increase"))
            .sort("ca_county"))


def q32(t):
    """Catalog excess discount: discount > 1.3x item 90-day average."""
    dd = t["date_dim"].filter((col("d_date") >= _d(2000, 1, 27))
                              & (col("d_date") <= _d(2000, 4, 26)))
    per_item = (t["catalog_sales"]
                .join(dd.select(col("d_date_sk").alias("ad_sk")),
                      col("cs_sold_date_sk") == col("ad_sk"))
                .group_by("cs_item_sk")
                .agg((F.avg("cs_ext_discount_amt") * lit(1.3))
                     .alias("thr"))
                .select(col("cs_item_sk").alias("avg_item_sk"),
                        col("thr")))
    return (t["catalog_sales"]
            .join(dd.select("d_date_sk"),
                  col("cs_sold_date_sk") == col("d_date_sk"))
            .join(t["item"].filter(col("i_manufact_id") == lit(77)),
                  col("cs_item_sk") == col("i_item_sk"))
            .join(per_item, col("cs_item_sk") == col("avg_item_sk"))
            .filter(col("cs_ext_discount_amt") > col("thr"))
            .agg(F.sum("cs_ext_discount_amt")
                 .alias("excess_discount_amount")))


def _by_manufact(t, sales, item_filter):
    """q33/q56/q60 helper: per-channel revenue for filtered items."""
    fact, date_k, item_k, addr_k, price = sales
    wanted = (t["item"].filter(item_filter)
              .select(col("i_manufact_id").alias("want_mid")).distinct())
    return (t[fact]
            .join(t["date_dim"].filter((col("d_year") == lit(1998))
                                       & (col("d_moy") == lit(5)))
                  .select(col("d_date_sk").alias(fact + "_d_sk")),
                  col(date_k) == col(fact + "_d_sk"))
            .join(t["customer_address"].filter(
                col("ca_gmt_offset") == lit(-5.0))
                .select(col("ca_address_sk").alias(fact + "_ca_sk")),
                col(addr_k) == col(fact + "_ca_sk"))
            .join(t["item"], col(item_k) == col("i_item_sk"))
            .join(wanted, col("i_manufact_id") == col("want_mid"),
                  how="leftsemi")
            .group_by("i_manufact_id")
            .agg(F.sum(price).alias("total_sales")))


def q33(t):
    """Manufacturer revenue across all three channels (category)."""
    filt = col("i_category") == lit("Electronics")
    ss = _by_manufact(t, ("store_sales", "ss_sold_date_sk",
                          "ss_item_sk", "ss_addr_sk",
                          "ss_ext_sales_price"), filt)
    cs = _by_manufact(t, ("catalog_sales", "cs_sold_date_sk",
                          "cs_item_sk", "cs_bill_addr_sk",
                          "cs_ext_sales_price"), filt)
    ws = _by_manufact(t, ("web_sales", "ws_sold_date_sk",
                          "ws_item_sk", "ws_bill_addr_sk",
                          "ws_ext_sales_price"), filt)
    return (ss.union(cs).union(ws)
            .group_by("i_manufact_id")
            .agg(F.sum("total_sales").alias("total_sales"))
            .sort(col("total_sales").asc(), col("i_manufact_id").asc())
            .limit(100))
