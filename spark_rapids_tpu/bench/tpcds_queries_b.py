"""TpcdsLike queries q34-q66 (DataFrame form).

Reference analog: integration_tests/.../tests/tpcds/TpcdsLikeSpark.scala.
Same rewrite conventions as tpcds_queries_a.py.
"""

from __future__ import annotations

import datetime as _dt

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

from spark_rapids_tpu.bench.tpcds_queries_a import _d, _stddev, \
    _year_total


def q34(t):
    """Households with 15-20 item tickets (q73 with wider dom windows)."""
    hd = t["household_demographics"].filter(
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > lit(0)))
    counts = (t["store_sales"]
              .join(t["date_dim"].filter(
                  (((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(3)))
                   | ((col("d_dom") >= lit(25))
                      & (col("d_dom") <= lit(28))))
                  & col("d_year").isin(1999, 2000, 2001)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
              .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
              .group_by("ss_ticket_number", "ss_customer_sk")
              .agg(F.count("*").alias("cnt"))
              .filter((col("cnt") >= lit(1)) & (col("cnt") <= lit(20))))
    return (counts
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .select("c_last_name", "c_first_name", "c_salutation",
                    "c_preferred_cust_flag", "ss_ticket_number", "cnt")
            .sort(col("c_last_name").asc_nulls_last(),
                  col("c_first_name").asc_nulls_last(),
                  col("c_salutation").asc_nulls_last(),
                  col("c_preferred_cust_flag").desc_nulls_first(),
                  col("ss_ticket_number").asc())
            .limit(100))


def q35(t):
    """q10 variant with per-demographic dependent-count statistics."""
    dd = t["date_dim"].filter((col("d_year") == lit(2002))
                              & (col("d_qoy") < lit(4)))
    ss_c = (t["store_sales"]
            .join(dd.select("d_date_sk"),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .select(col("ss_customer_sk").alias("act_sk")))
    ws_c = (t["web_sales"]
            .join(dd.select(col("d_date_sk").alias("wd_sk")),
                  col("ws_sold_date_sk") == col("wd_sk"))
            .select(col("ws_bill_customer_sk").alias("act_sk")))
    cs_c = (t["catalog_sales"]
            .join(dd.select(col("d_date_sk").alias("cd_sk")),
                  col("cs_sold_date_sk") == col("cd_sk"))
            .select(col("cs_bill_customer_sk").alias("act_sk")))
    c = (t["customer"]
         .join(ss_c, col("c_customer_sk") == col("act_sk"),
               how="leftsemi")
         .join(ws_c.union(cs_c), col("c_customer_sk") == col("act_sk"),
               how="leftsemi")
         .join(t["customer_address"],
               col("c_current_addr_sk") == col("ca_address_sk"))
         .join(t["customer_demographics"],
               col("c_current_cdemo_sk") == col("cd_demo_sk")))
    return (c.group_by("ca_state", "cd_gender", "cd_marital_status",
                       "cd_dep_count")
            .agg(F.count("*").alias("cnt1"),
                 F.min("cd_dep_count").alias("min_dep"),
                 F.max("cd_dep_count").alias("max_dep"),
                 F.avg("cd_dep_count").alias("avg_dep"))
            .sort(col("ca_state").asc_nulls_last(), col("cd_gender"),
                  col("cd_marital_status"), col("cd_dep_count"))
            .limit(100))


def q36(t):
    """Gross-margin ratio rollup over category/class with rank."""
    base = (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .join(t["store"].filter(col("s_state").isin("TN", "CA",
                                                        "TX", "NY")),
                  col("ss_store_sk") == col("s_store_sk")))

    def level(keys, lochierarchy):
        g = (base.group_by(*keys) if keys else base)
        a = g.agg(F.sum("ss_net_profit").alias("np"),
                  F.sum("ss_ext_sales_price").alias("esp"))
        sel = [col(k) for k in keys]
        sel += [lit(None).cast("string").alias(n)
                for n in ["i_category", "i_class"][len(keys):]]
        return a.select(
            (col("np") / col("esp")).alias("gross_margin"), *sel,
            lit(lochierarchy).alias("lochierarchy"))

    u = (level(["i_category", "i_class"], 0)
         .union(level(["i_category"], 1))
         .union(level([], 2)))
    rk = F.rank().over(
        Window.partition_by("lochierarchy")
        .order_by(col("gross_margin").asc()))
    return (u.select("gross_margin", "i_category", "i_class",
                     "lochierarchy", rk.alias("rank_within_parent"))
            .sort(col("lochierarchy").desc(),
                  col("i_category").asc_nulls_last(),
                  col("rank_within_parent").asc())
            .limit(100))


def q37(t):
    """Items with healthy inventory also sold by catalog in window."""
    inv = (t["inventory"]
           .join(t["date_dim"].filter(
               (col("d_date") >= _d(2000, 2, 1))
               & (col("d_date") <= _d(2000, 4, 1))),
               col("inv_date_sk") == col("d_date_sk"))
           .filter((col("inv_quantity_on_hand") >= lit(100))
                   & (col("inv_quantity_on_hand") <= lit(500)))
           .select(col("inv_item_sk").alias("inv_sk")))
    sold = t["catalog_sales"].select(col("cs_item_sk").alias("sold_sk"))
    return (t["item"]
            .filter((col("i_current_price") >= lit(10.0))
                    & (col("i_current_price") <= lit(60.0))
                    & col("i_manufact_id").isin(
                        *range(1, 200)))
            .join(inv, col("i_item_sk") == col("inv_sk"),
                  how="leftsemi")
            .join(sold, col("i_item_sk") == col("sold_sk"),
                  how="leftsemi")
            .group_by("i_item_id", "i_item_desc", "i_current_price")
            .agg(F.count("*").alias("_cnt"))
            .select("i_item_id", "i_item_desc", "i_current_price")
            .sort("i_item_id")
            .limit(100))


def q38(t):
    """Customers active in ALL three channels (INTERSECT chain)."""
    dd = t["date_dim"].filter((col("d_month_seq") >= lit(120))
                              & (col("d_month_seq") <= lit(131)))
    ss = (t["store_sales"]
          .join(dd.select("d_date_sk"),
                col("ss_sold_date_sk") == col("d_date_sk"))
          .select(col("ss_customer_sk").alias("sk")).distinct())
    cs = (t["catalog_sales"]
          .join(dd.select(col("d_date_sk").alias("cd_sk")),
                col("cs_sold_date_sk") == col("cd_sk"))
          .select(col("cs_bill_customer_sk").alias("csk")).distinct())
    ws = (t["web_sales"]
          .join(dd.select(col("d_date_sk").alias("wd_sk")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .select(col("ws_bill_customer_sk").alias("wsk")).distinct())
    return (ss.join(cs, col("sk") == col("csk"), how="leftsemi")
            .join(ws, col("sk") == col("wsk"), how="leftsemi")
            .agg(F.count("*").alias("cnt")))


def q39(t):
    """Inventory coefficient-of-variation pairs across months."""
    base = (t["inventory"]
            .join(t["item"], col("inv_item_sk") == col("i_item_sk"))
            .join(t["warehouse"],
                  col("inv_warehouse_sk") == col("w_warehouse_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                  col("inv_date_sk") == col("d_date_sk")))
    q = col("inv_quantity_on_hand").cast("double")
    g = (base.group_by("w_warehouse_name", "w_warehouse_sk",
                       "i_item_sk", "d_moy")
         .agg(F.count("*").alias("n"), F.sum(q).alias("s1"),
              F.sum(q * q).alias("s2"),
              F.avg("inv_quantity_on_hand").alias("mean")))
    g = (g.filter(col("mean") > lit(0.0))
         .select(col("w_warehouse_sk"), col("w_warehouse_name"),
                 col("i_item_sk"), col("d_moy"), col("mean"),
                 (_stddev(col("s2"), col("s1"), col("n"))
                  / col("mean")).alias("cov"))
         .filter(col("cov") > lit(0.5)))
    m1 = g.select(col("w_warehouse_sk").alias("wsk1"),
                  col("i_item_sk").alias("isk1"),
                  col("d_moy").alias("moy1"),
                  col("mean").alias("mean1"), col("cov").alias("cov1"))
    m2 = g.select(col("w_warehouse_sk").alias("wsk2"),
                  col("i_item_sk").alias("isk2"),
                  col("d_moy").alias("moy2"),
                  col("mean").alias("mean2"), col("cov").alias("cov2"))
    return (m1.join(m2, (col("wsk1") == col("wsk2"))
                    & (col("isk1") == col("isk2")))
            .filter(col("moy2") == col("moy1") + lit(1))
            .sort("wsk1", "isk1", "moy1")
            .limit(100))


def q40(t):
    """Catalog value shift around a date per warehouse/item, net of
    returns."""
    pivot = _d(2000, 3, 11)
    cr = t["catalog_returns"].select(
        col("cr_order_number").alias("cr_o"),
        col("cr_item_sk").alias("cr_i"),
        col("cr_refunded_cash").alias("refund"))
    j = (t["catalog_sales"]
         .join(cr, (col("cs_order_number") == col("cr_o"))
               & (col("cs_item_sk") == col("cr_i")), how="left")
         .join(t["warehouse"],
               col("cs_warehouse_sk") == col("w_warehouse_sk"))
         .join(t["item"].filter((col("i_current_price") >= lit(0.99))
                                & (col("i_current_price")
                                   <= lit(100.0))),
               col("cs_item_sk") == col("i_item_sk"))
         .join(t["date_dim"].filter(
             (col("d_date") >= _d(2000, 2, 10))
             & (col("d_date") <= _d(2000, 4, 10))),
             col("cs_sold_date_sk") == col("d_date_sk")))
    val = col("cs_sales_price") - F.coalesce(col("refund"), lit(0.0))
    return (j.group_by("w_state", "i_item_id")
            .agg(F.sum(F.when(col("d_date") < pivot, val)
                       .otherwise(lit(0.0))).alias("sales_before"),
                 F.sum(F.when(col("d_date") >= pivot, val)
                       .otherwise(lit(0.0))).alias("sales_after"))
            .sort("w_state", "i_item_id")
            .limit(100))


def q41(t):
    """Distinct product names of items matching manufact styles."""
    cond1 = ((col("i_category") == lit("Women"))
             & col("i_color").isin("red", "blue", "navy", "ivory")
             & col("i_units").isin("Each", "Dozen", "Oz", "Pound"))
    cond2 = ((col("i_category") == lit("Men"))
             & col("i_color").isin("green", "black", "white", "plum")
             & col("i_units").isin("Case", "Ton", "Pallet", "Each"))
    styled = (t["item"].filter(cond1 | cond2)
              .select(col("i_manufact").alias("want_m")).distinct())
    return (t["item"]
            .filter((col("i_manufact_id") >= lit(1))
                    & (col("i_manufact_id") <= lit(1000)))
            .join(styled, col("i_manufact") == col("want_m"),
                  how="leftsemi")
            .select("i_product_name")
            .distinct()
            .sort("i_product_name")
            .limit(100))


def q43(t):
    """Per-store weekday sales pivot for one year."""
    def day(nm):
        return F.sum(F.when(col("d_day_name") == lit(nm),
                            col("ss_sales_price"))
                     .otherwise(lit(None)))

    return (t["store_sales"]
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("s_store_name", "s_store_id")
            .agg(day("Sunday").alias("sun_sales"),
                 day("Monday").alias("mon_sales"),
                 day("Tuesday").alias("tue_sales"),
                 day("Wednesday").alias("wed_sales"),
                 day("Thursday").alias("thu_sales"),
                 day("Friday").alias("fri_sales"),
                 day("Saturday").alias("sat_sales"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


def q44(t):
    """Best and worst performing items by average revenue."""
    perf = (t["store_sales"].filter(col("ss_store_sk") == lit(1))
            .group_by("ss_item_sk")
            .agg(F.avg("ss_net_profit").alias("rank_col")))
    asc = (perf.select(
        col("ss_item_sk").alias("best_sk"),
        F.rank().over(Window.order_by(col("rank_col").asc()))
        .alias("rnk_a")).filter(col("rnk_a") < lit(11)))
    desc = (perf.select(
        col("ss_item_sk").alias("worst_sk"),
        F.rank().over(Window.order_by(col("rank_col").desc()))
        .alias("rnk_d")).filter(col("rnk_d") < lit(11)))
    i1 = t["item"].select(col("i_item_sk").alias("i1_sk"),
                          col("i_product_name").alias("best_performing"))
    i2 = t["item"].select(col("i_item_sk").alias("i2_sk"),
                          col("i_product_name")
                          .alias("worst_performing"))
    return (asc.join(desc, col("rnk_a") == col("rnk_d"))
            .join(i1, col("best_sk") == col("i1_sk"))
            .join(i2, col("worst_sk") == col("i2_sk"))
            .select(col("rnk_a").alias("rnk"), col("best_performing"),
                    col("worst_performing"))
            .sort("rnk"))


def q45(t):
    """Web sales by customer geography for selected zips/items."""
    return (t["web_sales"]
            .join(t["customer"],
                  col("ws_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["date_dim"].filter((col("d_qoy") == lit(2))
                                       & (col("d_year") == lit(2001))),
                  col("ws_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], col("ws_item_sk") == col("i_item_sk"))
            .filter(F.substring(col("ca_zip"), 1, 2)
                    .isin("85", "86", "88", "89", "80", "81", "30",
                          "31", "38", "98")
                    | col("i_item_id").isin(
                        "ITEM000000000002", "ITEM000000000003",
                        "ITEM000000000005", "ITEM000000000007",
                        "ITEM000000000011", "ITEM000000000013",
                        "ITEM000000000017", "ITEM000000000019",
                        "ITEM000000000023", "ITEM000000000029"))
            .group_by("ca_zip", "ca_city")
            .agg(F.sum("ws_sales_price").alias("total"))
            .sort("ca_zip", "ca_city")
            .limit(100))


def q46(t):
    """Ticket amounts for customers buying away from home city."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(4))
        | (col("hd_vehicle_count") == lit(3)))
    sales_ca = t["customer_address"].select(
        col("ca_address_sk").alias("sca_sk"),
        col("ca_city").alias("bought_city"))
    tickets = (t["store_sales"]
               .join(t["date_dim"].filter(
                   col("d_dow").isin(5, 6)
                   & col("d_year").isin(1999, 2000, 2001)),
                   col("ss_sold_date_sk") == col("d_date_sk"))
               .join(t["store"].filter(
                   col("s_city").isin("Midway", "Fairview")),
                   col("ss_store_sk") == col("s_store_sk"))
               .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(sales_ca, col("ss_addr_sk") == col("sca_sk"))
               .group_by("ss_ticket_number", "ss_customer_sk",
                         "bought_city")
               .agg(F.sum("ss_coupon_amt").alias("amt"),
                    F.sum("ss_net_profit").alias("profit")))
    return (tickets
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .filter(col("ca_city") != col("bought_city"))
            .select("c_last_name", "c_first_name", "ca_city",
                    "bought_city", "ss_ticket_number", "amt", "profit")
            .sort(col("c_last_name").asc_nulls_last(),
                  col("c_first_name").asc_nulls_last(),
                  col("ca_city").asc_nulls_last(),
                  col("bought_city").asc_nulls_last(),
                  col("ss_ticket_number").asc())
            .limit(100))


def q47(t):
    """Brand-store monthly sales deviating from the yearly average,
    with lag/lead context (v1_lag/v1_lead self-windows)."""
    base = (t["store_sales"]
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_category", "i_brand", "s_store_name",
                      "s_company_name", "d_year", "d_moy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    part = ["i_category", "i_brand", "s_store_name", "s_company_name"]
    w_avg = Window.partition_by(*part)
    w_seq = Window.partition_by(*part).order_by(col("d_moy").asc())
    v1 = base.select(
        *[col(c) for c in part], col("d_year"), col("d_moy"),
        col("sum_sales"),
        F.avg(col("sum_sales")).over(w_avg).alias("avg_monthly_sales"),
        F.lag(col("sum_sales"), 1).over(w_seq).alias("psum"),
        F.lead(col("sum_sales"), 1).over(w_seq).alias("nsum"))
    return (v1.filter((col("avg_monthly_sales") > lit(0.0))
                      & (F.abs(col("sum_sales")
                               - col("avg_monthly_sales"))
                         / col("avg_monthly_sales") > lit(0.1)))
            .select("i_category", "i_brand", "s_store_name", "d_year",
                    "d_moy", "sum_sales", "avg_monthly_sales", "psum",
                    "nsum")
            .sort((col("sum_sales") - col("avg_monthly_sales")).asc(),
                  col("s_store_name").asc(), col("d_moy").asc())
            .limit(100))


def q48(t):
    """Quantity sum under OR'd demographic/address conditions."""
    cd_ok = ((col("cd_marital_status") == lit("M"))
             & (col("cd_education_status") == lit("4 yr Degree"))
             & (col("ss_sales_price") >= lit(100.0))) | \
            ((col("cd_marital_status") == lit("D"))
             & (col("cd_education_status") == lit("Primary"))
             & (col("ss_sales_price") >= lit(50.0))) | \
            ((col("cd_marital_status") == lit("U"))
             & (col("cd_education_status") == lit("Advanced Degree")))
    ca_ok = (col("ca_state").isin("TX", "OH", "CA")
             | col("ca_state").isin("WA", "NY", "GA"))
    return (t["store_sales"]
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .join(t["customer_demographics"],
                  col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["customer_address"],
                  col("ss_addr_sk") == col("ca_address_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2001)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(cd_ok & ca_ok)
            .agg(F.sum("ss_quantity").alias("total_quantity")))


def _return_ratio(t, fact, prefix, ret, rprefix):
    """q49 helper: per-item return ratio + ranks for one channel."""
    s = (t[fact]
         .join(t["date_dim"].filter((col("d_year") == lit(2001))
                                    & (col("d_moy") == lit(12)))
               .select(col("d_date_sk").alias(prefix + "_dsk")),
               col(f"{prefix}_sold_date_sk") == col(prefix + "_dsk"))
         .filter(col(f"{prefix}_net_profit") > lit(1.0)))
    if prefix == "ss":
        join_cond = (col("ss_ticket_number") == col(f"{rprefix}_tick")) \
            & (col("ss_item_sk") == col(f"{rprefix}_isk"))
        r = t[ret].select(col("sr_ticket_number").alias("sr_tick"),
                          col("sr_item_sk").alias("sr_isk"),
                          col("sr_return_quantity").alias("ret_qty"),
                          col("sr_return_amt").alias("ret_amt"))
    else:
        join_cond = (col(f"{prefix}_order_number")
                     == col(f"{rprefix}_ord")) \
            & (col(f"{prefix}_item_sk") == col(f"{rprefix}_isk"))
        r = t[ret].select(
            col(f"{rprefix}_order_number").alias(f"{rprefix}_ord"),
            col(f"{rprefix}_item_sk").alias(f"{rprefix}_isk"),
            col(f"{rprefix}_return_quantity").alias("ret_qty"),
            col(f"{rprefix}_return_amt" if rprefix == "wr"
                else f"{rprefix}_return_amount").alias("ret_amt"))
    g = (s.join(r, join_cond, how="left")
         .group_by(f"{prefix}_item_sk")
         .agg(F.sum(F.coalesce(col("ret_qty"), lit(0))
                    .cast("double")).alias("rq"),
              F.sum(col(f"{prefix}_quantity").cast("double"))
              .alias("sq"),
              F.sum(F.coalesce(col("ret_amt"), lit(0.0))).alias("ra"),
              F.sum(col(f"{prefix}_net_paid")).alias("sa")))
    ratio = (col("rq") / col("sq")).alias("return_ratio")
    cratio = (col("ra") / col("sa")).alias("currency_ratio")
    v = g.select(col(f"{prefix}_item_sk").alias("item"), ratio, cratio)
    return (v.select(
        col("item"), col("return_ratio"), col("currency_ratio"),
        F.rank().over(Window.order_by(col("return_ratio").asc()))
        .alias("return_rank"),
        F.rank().over(Window.order_by(col("currency_ratio").asc()))
        .alias("currency_rank"))
        .filter((col("return_rank") <= lit(10))
                | (col("currency_rank") <= lit(10))))


def q49(t):
    """Worst return ratios across the three channels."""
    web = (_return_ratio(t, "web_sales", "ws", "web_returns", "wr")
           .select(lit("web").alias("channel"), col("item"),
                   col("return_ratio"), col("return_rank"),
                   col("currency_rank")))
    cat = (_return_ratio(t, "catalog_sales", "cs", "catalog_returns",
                         "cr")
           .select(lit("catalog").alias("channel"), col("item"),
                   col("return_ratio"), col("return_rank"),
                   col("currency_rank")))
    sto = (_return_ratio(t, "store_sales", "ss", "store_returns", "sr")
           .select(lit("store").alias("channel"), col("item"),
                   col("return_ratio"), col("return_rank"),
                   col("currency_rank")))
    return (web.union(cat).union(sto)
            .sort("channel", "return_rank", "currency_rank", "item")
            .limit(100))


def q50(t):
    """Sale-to-return lag buckets per store."""
    sr = t["store_returns"].select(
        col("sr_ticket_number").alias("r_tick"),
        col("sr_item_sk").alias("r_isk"),
        col("sr_customer_sk").alias("r_csk"),
        col("sr_returned_date_sk").alias("r_dsk"))
    d2 = (t["date_dim"].filter((col("d_year") == lit(2001))
                               & (col("d_moy") == lit(8)))
          .select(col("d_date_sk").alias("d2_sk")))
    lag = col("r_dsk") - col("ss_sold_date_sk")
    return (t["store_sales"]
            .join(sr, (col("ss_ticket_number") == col("r_tick"))
                  & (col("ss_item_sk") == col("r_isk"))
                  & (col("ss_customer_sk") == col("r_csk")))
            .join(d2, col("r_dsk") == col("d2_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("s_store_name", "s_store_id", "s_city", "s_state",
                      "s_zip")
            .agg(F.sum(F.when(lag <= lit(30), lit(1)).otherwise(lit(0)))
                 .alias("days_30"),
                 F.sum(F.when((lag > lit(30)) & (lag <= lit(60)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_31_60"),
                 F.sum(F.when((lag > lit(60)) & (lag <= lit(90)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_61_90"),
                 F.sum(F.when(lag > lit(90), lit(1)).otherwise(lit(0)))
                 .alias("days_over_90"))
            .sort("s_store_name", "s_store_id")
            .limit(100))


def q51(t):
    """Cumulative web vs store revenue crossover per item."""
    wd = (t["web_sales"]
          .join(t["date_dim"].filter((col("d_month_seq") >= lit(120))
                                     & (col("d_month_seq")
                                        <= lit(131))),
                col("ws_sold_date_sk") == col("d_date_sk"))
          .group_by("ws_item_sk", "d_month_seq")
          .agg(F.sum("ws_sales_price").alias("ws_mo"))
          .select(col("ws_item_sk").alias("w_item"),
                  col("d_month_seq").alias("w_mseq"),
                  F.sum(col("ws_mo")).over(
                      Window.partition_by("ws_item_sk")
                      .order_by(col("d_month_seq").asc())
                      .rows_between(Window.unbounded_preceding,
                                    Window.current_row))
                  .alias("web_cumulative")))
    sd = (t["store_sales"]
          .join(t["date_dim"].filter((col("d_month_seq") >= lit(120))
                                     & (col("d_month_seq")
                                        <= lit(131)))
                .select(col("d_date_sk").alias("sd_sk"),
                        col("d_month_seq").alias("s_mseq0")),
                col("ss_sold_date_sk") == col("sd_sk"))
          .group_by("ss_item_sk", "s_mseq0")
          .agg(F.sum("ss_sales_price").alias("ss_mo"))
          .select(col("ss_item_sk").alias("s_item"),
                  col("s_mseq0").alias("s_mseq"),
                  F.sum(col("ss_mo")).over(
                      Window.partition_by("ss_item_sk")
                      .order_by(col("s_mseq0").asc())
                      .rows_between(Window.unbounded_preceding,
                                    Window.current_row))
                  .alias("store_cumulative")))
    return (wd.join(sd, (col("w_item") == col("s_item"))
                    & (col("w_mseq") == col("s_mseq")))
            .filter(col("web_cumulative") > col("store_cumulative"))
            .select(col("w_item").alias("item_sk"),
                    col("w_mseq").alias("d_month_seq"),
                    col("web_cumulative"), col("store_cumulative"))
            .sort("item_sk", "d_month_seq")
            .limit(100))


def q53(t):
    """Manufacturer quarterly sales vs their average (iceberg)."""
    base = (t["store_sales"]
            .join(t["item"].filter(col("i_class").isin(
                "class01", "class02", "class03")),
                col("ss_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(col("d_month_seq").isin(
                *range(120, 132))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_manufact_id", "d_qoy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    v = base.select(
        col("i_manufact_id"), col("sum_sales"),
        F.avg(col("sum_sales")).over(
            Window.partition_by("i_manufact_id"))
        .alias("avg_quarterly_sales"))
    return (v.filter((col("avg_quarterly_sales") > lit(0.0))
                     & (F.abs(col("sum_sales")
                              - col("avg_quarterly_sales"))
                        / col("avg_quarterly_sales") > lit(0.1)))
            .sort(col("avg_quarterly_sales").asc(),
                  col("sum_sales").asc(), col("i_manufact_id").asc())
            .limit(100))


def q54(t):
    """Store revenue segments for cross-channel month customers."""
    month = (t["date_dim"].filter(col("d_moy").isin(11, 12)
                                  & (col("d_year") == lit(1998)))
             .select(col("d_date_sk").alias("m_dsk")))
    cs = (t["catalog_sales"]
          .select(col("cs_sold_date_sk").alias("sold_dsk"),
                  col("cs_item_sk").alias("sold_isk"),
                  col("cs_bill_customer_sk").alias("sold_csk")))
    ws = (t["web_sales"]
          .select(col("ws_sold_date_sk").alias("sold_dsk"),
                  col("ws_item_sk").alias("sold_isk"),
                  col("ws_bill_customer_sk").alias("sold_csk")))
    my_customers = (cs.union(ws)
                    .join(month, col("sold_dsk") == col("m_dsk"))
                    .join(t["item"].filter(
                        col("i_category") == lit("Women")),
                        col("sold_isk") == col("i_item_sk"))
                    .select(col("sold_csk").alias("my_csk"))
                    .distinct())
    revenue = (t["store_sales"]
               .join(my_customers,
                     col("ss_customer_sk") == col("my_csk"),
                     how="leftsemi")
               .join(t["date_dim"].filter(
                   (col("d_moy") <= lit(6))
                   & (col("d_year") == lit(1999))),
                   col("ss_sold_date_sk") == col("d_date_sk"))
               .group_by("ss_customer_sk")
               .agg(F.sum("ss_ext_sales_price").alias("revenue")))
    seg = (revenue.select(
        (F.floor(col("revenue") / lit(50.0))).cast("int")
        .alias("segment")))
    return (seg.group_by("segment")
            .agg(F.count("*").alias("num_customers"))
            .select(col("segment"), col("num_customers"),
                    (col("segment") * lit(50)).alias("segment_base"))
            .sort("segment", "num_customers")
            .limit(100))


def q56(t):
    """Per-item-id revenue across channels for colored items."""
    from spark_rapids_tpu.bench.tpcds_queries_a import _by_manufact  # noqa
    wanted = (t["item"].filter(col("i_color").isin(
        "red", "blue", "green", "navy"))
        .select(col("i_item_id").alias("want_id")).distinct())

    def chan(fact, date_k, item_k, addr_k, price):
        return (t[fact]
                .join(t["date_dim"].filter(
                    (col("d_year") == lit(2000))
                    & (col("d_moy") == lit(2)))
                    .select(col("d_date_sk").alias(fact + "_dsk")),
                    col(date_k) == col(fact + "_dsk"))
                .join(t["customer_address"].filter(
                    col("ca_gmt_offset") == lit(-5.0))
                    .select(col("ca_address_sk").alias(fact + "_csk")),
                    col(addr_k) == col(fact + "_csk"))
                .join(t["item"], col(item_k) == col("i_item_sk"))
                .join(wanted, col("i_item_id") == col("want_id"),
                      how="leftsemi")
                .group_by("i_item_id")
                .agg(F.sum(price).alias("total_sales")))

    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_addr_sk", col("ss_ext_sales_price"))
    cs = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              "cs_bill_addr_sk", col("cs_ext_sales_price"))
    ws = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_bill_addr_sk", col("ws_ext_sales_price"))
    return (ss.union(cs).union(ws)
            .group_by("i_item_id")
            .agg(F.sum("total_sales").alias("total_sales"))
            .sort(col("total_sales").asc(), col("i_item_id").asc())
            .limit(100))


def q57(t):
    """q47 for the catalog channel (call centers)."""
    base = (t["catalog_sales"]
            .join(t["item"], col("cs_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("cs_sold_date_sk") == col("d_date_sk"))
            .join(t["call_center"],
                  col("cs_call_center_sk") == col("cc_call_center_sk"))
            .group_by("i_category", "i_brand", "cc_name", "d_year",
                      "d_moy")
            .agg(F.sum("cs_sales_price").alias("sum_sales")))
    part = ["i_category", "i_brand", "cc_name"]
    v1 = base.select(
        *[col(c) for c in part], col("d_year"), col("d_moy"),
        col("sum_sales"),
        F.avg(col("sum_sales")).over(Window.partition_by(*part))
        .alias("avg_monthly_sales"),
        F.lag(col("sum_sales"), 1).over(
            Window.partition_by(*part).order_by(col("d_moy").asc()))
        .alias("psum"),
        F.lead(col("sum_sales"), 1).over(
            Window.partition_by(*part).order_by(col("d_moy").asc()))
        .alias("nsum"))
    return (v1.filter((col("avg_monthly_sales") > lit(0.0))
                      & (F.abs(col("sum_sales")
                               - col("avg_monthly_sales"))
                         / col("avg_monthly_sales") > lit(0.1)))
            .sort((col("sum_sales") - col("avg_monthly_sales")).asc(),
                  col("cc_name").asc(), col("d_moy").asc())
            .limit(100))


def q58(t):
    """Items with balanced revenue across all three channels in one
    period (Like-delta: month grain and a +/-50%% band — dbgen-lite's
    per-week per-channel item overlap is too sparse for the spec's
    single week / 10%% band)."""
    week = (t["date_dim"].filter(col("d_month_seq") == lit(110))
            .select(col("d_date_sk").alias("wk_dsk")))

    def chan(fact, date_k, item_k, price, nm):
        return (t[fact]
                .join(week, col(date_k) == col("wk_dsk"))
                .join(t["item"], col(item_k) == col("i_item_sk"))
                .group_by("i_item_id")
                .agg(F.sum(price).alias(nm))
                .select(col("i_item_id").alias(nm + "_id"), col(nm)))

    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              col("ss_ext_sales_price"), "ss_rev")
    cs = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              col("cs_ext_sales_price"), "cs_rev")
    ws = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
              col("ws_ext_sales_price"), "ws_rev")
    j = (ss.join(cs, col("ss_rev_id") == col("cs_rev_id"))
         .join(ws, col("ss_rev_id") == col("ws_rev_id")))
    avg3 = ((col("ss_rev") + col("cs_rev") + col("ws_rev"))
            / lit(3.0))
    lo, hi = avg3 * lit(0.5), avg3 * lit(1.5)
    return (j.filter((col("ss_rev") >= lo) & (col("ss_rev") <= hi)
                     & (col("cs_rev") >= lo) & (col("cs_rev") <= hi)
                     & (col("ws_rev") >= lo) & (col("ws_rev") <= hi))
            .select(col("ss_rev_id").alias("item_id"), col("ss_rev"),
                    col("cs_rev"), col("ws_rev"))
            .sort("item_id", "ss_rev")
            .limit(100))


def q59(t):
    """Store weekly sales year-over-year by weekday."""
    def day(nm):
        return F.sum(F.when(col("d_day_name") == lit(nm),
                            col("ss_sales_price"))
                     .otherwise(lit(None)))

    wss = (t["store_sales"]
           .join(t["date_dim"],
                 col("ss_sold_date_sk") == col("d_date_sk"))
           .group_by("d_week_seq", "ss_store_sk")
           .agg(day("Sunday").alias("sun_sales"),
                day("Monday").alias("mon_sales"),
                day("Tuesday").alias("tue_sales"),
                day("Wednesday").alias("wed_sales"),
                day("Thursday").alias("thu_sales"),
                day("Friday").alias("fri_sales"),
                day("Saturday").alias("sat_sales")))
    d = t["date_dim"].select("d_week_seq", "d_month_seq").distinct()
    y1 = (wss.join(d.filter((col("d_month_seq") >= lit(120))
                            & (col("d_month_seq") <= lit(131))),
                   on="d_week_seq")
          .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
          .select(col("s_store_name").alias("name1"),
                  col("s_store_id").alias("id1"),
                  col("d_week_seq").alias("wseq1"),
                  *[col(c).alias(c + "1")
                    for c in ["sun_sales", "mon_sales", "tue_sales",
                              "wed_sales", "thu_sales", "fri_sales",
                              "sat_sales"]]))
    y2 = (wss.join(d.filter((col("d_month_seq") >= lit(132))
                            & (col("d_month_seq") <= lit(143)))
                   .select(col("d_week_seq").alias("dw2"),
                           col("d_month_seq").alias("dm2")),
                   col("d_week_seq") == col("dw2"))
          .join(t["store"].select(col("s_store_sk").alias("ssk2"),
                                  col("s_store_id").alias("id2")),
                col("ss_store_sk") == col("ssk2"))
          .select(col("id2"), (col("dw2") - lit(52)).alias("wseq2"),
                  *[col(c).alias(c + "2")
                    for c in ["sun_sales", "mon_sales", "tue_sales",
                              "wed_sales", "thu_sales", "fri_sales",
                              "sat_sales"]]))
    j = y1.join(y2, (col("id1") == col("id2"))
                & (col("wseq1") == col("wseq2")))
    return (j.select(
        col("name1"), col("wseq1"),
        (col("sun_sales1") / col("sun_sales2")).alias("sun_r"),
        (col("mon_sales1") / col("mon_sales2")).alias("mon_r"),
        (col("tue_sales1") / col("tue_sales2")).alias("tue_r"),
        (col("wed_sales1") / col("wed_sales2")).alias("wed_r"),
        (col("thu_sales1") / col("thu_sales2")).alias("thu_r"),
        (col("fri_sales1") / col("fri_sales2")).alias("fri_r"),
        (col("sat_sales1") / col("sat_sales2")).alias("sat_r"))
        .sort("name1", "wseq1")
        .limit(100))


def q60(t):
    """Per-item-id revenue across channels for one category."""
    wanted = (t["item"].filter(col("i_category") == lit("Music"))
              .select(col("i_item_id").alias("want_id")).distinct())

    def chan(fact, date_k, item_k, addr_k, price):
        return (t[fact]
                .join(t["date_dim"].filter(
                    (col("d_year") == lit(1998))
                    & (col("d_moy") == lit(9)))
                    .select(col("d_date_sk").alias(fact + "_dsk")),
                    col(date_k) == col(fact + "_dsk"))
                .join(t["customer_address"].filter(
                    col("ca_gmt_offset") == lit(-5.0))
                    .select(col("ca_address_sk").alias(fact + "_csk")),
                    col(addr_k) == col(fact + "_csk"))
                .join(t["item"], col(item_k) == col("i_item_sk"))
                .join(wanted, col("i_item_id") == col("want_id"),
                      how="leftsemi")
                .group_by("i_item_id")
                .agg(F.sum(price).alias("total_sales")))

    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_addr_sk", col("ss_ext_sales_price"))
    cs = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              "cs_bill_addr_sk", col("cs_ext_sales_price"))
    ws = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_bill_addr_sk", col("ws_ext_sales_price"))
    return (ss.union(cs).union(ws)
            .group_by("i_item_id")
            .agg(F.sum("total_sales").alias("total_sales"))
            .sort("i_item_id", "total_sales")
            .limit(100))


def q61(t):
    """Promotional to total revenue ratio for a category/month."""
    base = (t["store_sales"]
            .join(t["date_dim"].filter((col("d_year") == lit(1998))
                                       & (col("d_moy") == lit(11))),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"].filter(col("s_gmt_offset") == lit(-5.0)),
                  col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"].filter(col("i_category") == lit("Jewelry")),
                  col("ss_item_sk") == col("i_item_sk"))
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"].filter(
                col("ca_gmt_offset") == lit(-5.0)),
                col("c_current_addr_sk") == col("ca_address_sk")))
    promos = (base.join(t["promotion"].filter(
        (col("p_channel_dmail") == lit("Y"))
        | (col("p_channel_email") == lit("Y"))
        | (col("p_channel_tv") == lit("Y"))),
        col("ss_promo_sk") == col("p_promo_sk"))
        .agg(F.sum("ss_ext_sales_price").alias("promotions")))
    total = base.agg(F.sum("ss_ext_sales_price").alias("total"))
    return (promos.crossJoin(total)
            .select(col("promotions"), col("total"),
                    (col("promotions").cast("double")
                     / col("total").cast("double") * lit(100.0))
                    .alias("pct")))


def q62(t):
    """Web shipping-lag day buckets by site/ship mode/warehouse."""
    lag = col("ws_ship_date_sk") - col("ws_sold_date_sk")
    return (t["web_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= lit(120))
                                       & (col("d_month_seq")
                                          <= lit(131))),
                  col("ws_ship_date_sk") == col("d_date_sk"))
            .join(t["web_site"],
                  col("ws_web_site_sk") == col("web_site_sk"))
            .join(t["ship_mode"],
                  col("ws_ship_mode_sk") == col("sm_ship_mode_sk"))
            .join(t["warehouse"],
                  col("ws_warehouse_sk") == col("w_warehouse_sk"))
            .group_by("w_warehouse_name", "sm_type", "web_name")
            .agg(F.sum(F.when(lag <= lit(30), lit(1)).otherwise(lit(0)))
                 .alias("days_30"),
                 F.sum(F.when((lag > lit(30)) & (lag <= lit(60)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_31_60"),
                 F.sum(F.when((lag > lit(60)) & (lag <= lit(90)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_61_90"),
                 F.sum(F.when((lag > lit(90)) & (lag <= lit(120)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_91_120"),
                 F.sum(F.when(lag > lit(120), lit(1))
                       .otherwise(lit(0))).alias("days_over_120"))
            .sort(col("w_warehouse_name").asc_nulls_last(),
                  col("sm_type").asc(), col("web_name").asc())
            .limit(100))


def q63(t):
    """Manager monthly sales vs average (q53 by manager)."""
    base = (t["store_sales"]
            .join(t["item"].filter(col("i_class").isin(
                "class01", "class02", "class03", "class04")),
                col("ss_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(col("d_month_seq").isin(
                *range(120, 132))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_manager_id", "d_moy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    v = base.select(
        col("i_manager_id"), col("sum_sales"),
        F.avg(col("sum_sales")).over(
            Window.partition_by("i_manager_id"))
        .alias("avg_monthly_sales"))
    return (v.filter((col("avg_monthly_sales") > lit(0.0))
                     & (F.abs(col("sum_sales")
                              - col("avg_monthly_sales"))
                        / col("avg_monthly_sales") > lit(0.1)))
            .sort("i_manager_id", col("avg_monthly_sales").asc(),
                  col("sum_sales").asc())
            .limit(100))


def q64(t):
    """Cross-channel repurchase chain with demographics (lite)."""
    cs_deals = (t["catalog_sales"]
                .join(t["catalog_returns"].select(
                    col("cr_order_number").alias("cr_o"),
                    col("cr_item_sk").alias("cr_i"),
                    col("cr_refunded_cash").alias("cr_cash")),
                    (col("cs_order_number") == col("cr_o"))
                    & (col("cs_item_sk") == col("cr_i")))
                .group_by("cs_item_sk")
                .agg(F.sum(col("cs_ext_list_price")).alias("sale"),
                     F.sum(col("cr_cash")).alias("refund"))
                .filter(col("sale") > lit(2.0) * col("refund"))
                .select(col("cs_item_sk").alias("deal_sk")))
    cross = (t["store_sales"]
             .join(t["store_returns"],
                   (col("ss_ticket_number") == col("sr_ticket_number"))
                   & (col("ss_item_sk") == col("sr_item_sk")))
             .join(cs_deals, col("ss_item_sk") == col("deal_sk"),
                   how="leftsemi")
             .join(t["date_dim"],
                   col("ss_sold_date_sk") == col("d_date_sk"))
             .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
             .join(t["item"].filter(col("i_color").isin(
                 "red", "blue", "green", "white", "black", "ivory")),
                 col("ss_item_sk") == col("i_item_sk"))
             .join(t["customer"],
                   col("ss_customer_sk") == col("c_customer_sk"))
             .join(t["household_demographics"],
                   col("c_current_hdemo_sk") == col("hd_demo_sk"))
             .join(t["income_band"],
                   col("hd_income_band_sk")
                   == col("ib_income_band_sk")))
    return (cross.group_by("i_product_name", "i_item_sk",
                           "s_store_name", "s_zip", "d_year")
            .agg(F.count("*").alias("cnt"),
                 F.sum("ss_wholesale_cost").alias("s1"),
                 F.sum("ss_list_price").alias("s2"),
                 F.sum("ss_coupon_amt").alias("s3"))
            .sort("i_product_name", "i_item_sk", "s_store_name",
                  "d_year")
            .limit(100))


def q65(t):
    """Store items selling at <= 10% of the store-average revenue."""
    sales = (t["store_sales"]
             .join(t["date_dim"].filter(
                 (col("d_month_seq") >= lit(120))
                 & (col("d_month_seq") <= lit(131))),
                 col("ss_sold_date_sk") == col("d_date_sk"))
             .group_by("ss_store_sk", "ss_item_sk")
             .agg(F.sum("ss_sales_price").alias("revenue")))
    avg_rev = (sales.group_by("ss_store_sk")
               .agg((F.avg("revenue") * lit(0.1)).alias("thr"))
               .select(col("ss_store_sk").alias("avg_ssk"),
                       col("thr")))
    return (sales
            .join(avg_rev, col("ss_store_sk") == col("avg_ssk"))
            .filter(col("revenue") <= col("thr"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
            .select("s_store_name", "i_item_desc", "revenue",
                    "i_current_price", "i_wholesale_cost", "i_brand")
            .sort(col("s_store_name").asc(),
                  col("i_item_desc").asc_nulls_last())
            .limit(100))


def q66(t):
    """Warehouse monthly shipping pivot for web+catalog, by time-of-day
    halves."""
    half = lit(43200)

    def chan(fact, prefix, qty, price):
        date_k = f"{prefix}_sold_date_sk"
        time_k = f"{prefix}_sold_time_sk"
        ship_k = f"{prefix}_ship_mode_sk"
        wh_k = f"{prefix}_warehouse_sk"
        night = F.sum(F.when(col("t_time") <= half,
                             price * qty.cast("double"))
                      .otherwise(lit(0.0)))
        day_ = F.sum(F.when(col("t_time") > half,
                            price * qty.cast("double"))
                     .otherwise(lit(0.0)))
        return (t[fact]
                .join(t["date_dim"].filter(col("d_year") == lit(2001))
                      .select(col("d_date_sk").alias(fact + "_dsk"),
                              col("d_moy").alias(fact + "_moy")),
                      col(date_k) == col(fact + "_dsk"))
                .join(t["time_dim"],
                      col(time_k) == col("t_time_sk"))
                .join(t["ship_mode"].filter(
                    col("sm_carrier").isin("UPS", "FEDEX"))
                    .select(col("sm_ship_mode_sk")
                            .alias(fact + "_smsk")),
                    col(ship_k) == col(fact + "_smsk"))
                .join(t["warehouse"],
                      col(wh_k) == col("w_warehouse_sk"))
                .group_by("w_warehouse_name", "w_warehouse_sq_ft",
                          "w_city", "w_county", "w_state", "w_country",
                          fact + "_moy")
                .agg(night.alias("night_val"), day_.alias("day_val"))
                .select(col("w_warehouse_name"),
                        col("w_warehouse_sq_ft"), col("w_city"),
                        col("w_county"), col("w_state"),
                        col("w_country"),
                        col(fact + "_moy").alias("moy"),
                        col("night_val"), col("day_val")))

    ws = chan("web_sales", "ws", col("ws_quantity"),
              col("ws_ext_sales_price"))
    cs = chan("catalog_sales", "cs", col("cs_quantity"),
              col("cs_ext_sales_price"))
    return (ws.union(cs)
            .group_by("w_warehouse_name", "w_warehouse_sq_ft", "w_city",
                      "w_county", "w_state", "w_country", "moy")
            .agg(F.sum("night_val").alias("night_total"),
                 F.sum("day_val").alias("day_total"))
            .sort(col("w_warehouse_name").asc_nulls_last(), col("moy"))
            .limit(100))
