"""Benchmark suites (reference analog: integration_tests/src/main/scala/
com/nvidia/spark/rapids/tests/{tpcds,tpch,tpcxbb} + tests/BenchmarkRunner).

``tpch`` holds a TpchLike suite: schema, dbgen-lite data generator, and all
22 queries expressed in the DataFrame API; ``runner`` holds the
BenchmarkRunner / CompareResults harness emitting JSON reports.
"""

from spark_rapids_tpu.bench import tpch  # noqa: F401
from spark_rapids_tpu.bench.runner import (  # noqa: F401
    BenchmarkRunner, CompareResults)
