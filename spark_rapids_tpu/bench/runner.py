"""BenchmarkRunner + CompareResults (reference analog:
``tests/.../BenchmarkRunner.scala`` and ``BenchUtils.scala`` /
``CompareResults`` — iterations with per-iteration timings collected into a
JSON report, plus a CPU-vs-accelerated result comparison with float
tolerance and optional row-order independence).
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import pyarrow as pa


@dataclass
class QueryReport:
    query: str
    iterations: List[float]          # seconds per iteration
    rows: int
    error: Optional[str] = None

    @property
    def best(self) -> float:
        return min(self.iterations) if self.iterations else math.nan

    @property
    def mean(self) -> float:
        return (sum(self.iterations) / len(self.iterations)
                if self.iterations else math.nan)


@dataclass
class BenchmarkReport:
    suite: str
    mode: str                        # "cpu" | "tpu"
    queries: List[QueryReport] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps({
            "suite": self.suite,
            "mode": self.mode,
            "queries": [{
                "query": q.query, "iterations": q.iterations,
                "rows": q.rows, "best_s": q.best, "mean_s": q.mean,
                "error": q.error,
            } for q in self.queries],
        }, indent=2)

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


class BenchmarkRunner:
    """Runs a suite's queries N times against one session and collects
    timings (reference: BenchmarkRunner "collect" mode)."""

    def __init__(self, session, tables: Dict[str, "object"],
                 queries: Dict[str, Callable], suite: str = "tpch",
                 mode: str = "tpu"):
        self.session = session
        self.tables = tables
        self.queries = queries
        self.suite = suite
        self.mode = mode

    def run(self, names: Optional[List[str]] = None, iterations: int = 1,
            warmup: int = 0) -> BenchmarkReport:
        report = BenchmarkReport(self.suite, self.mode)
        for name in (names or sorted(self.queries,
                                     key=lambda q: int(q[1:]))):
            fn = self.queries[name]
            try:
                for _ in range(warmup):
                    fn(self.tables).collect()
                times, rows = [], 0
                for _ in range(iterations):
                    t0 = time.perf_counter()
                    out = fn(self.tables).collect()
                    times.append(time.perf_counter() - t0)
                    rows = out.num_rows
                report.queries.append(QueryReport(name, times, rows))
            except Exception as e:  # noqa: BLE001 — keep benching
                report.queries.append(QueryReport(name, [], 0,
                                                  error=repr(e)))
        return report


class CompareResults:
    """Deep-compares two result tables (reference: BenchUtils.compareResults
    — epsilon floats, optional order independence)."""

    def __init__(self, epsilon: float = 1e-4,
                 ignore_ordering: bool = False):
        self.epsilon = epsilon
        self.ignore_ordering = ignore_ordering

    def _rows(self, t: pa.Table):
        rows = list(zip(*(t.column(i).to_pylist()
                          for i in range(t.num_columns))))
        if self.ignore_ordering:
            rows.sort(key=lambda r: tuple(
                (v is None, str(type(v)), v) for v in r))
        return rows

    def compare(self, expected: pa.Table, actual: pa.Table) -> List[str]:
        """Returns a list of mismatch descriptions (empty = equal)."""
        problems: List[str] = []
        if expected.num_rows != actual.num_rows:
            return [f"row count {expected.num_rows} != {actual.num_rows}"]
        if expected.num_columns != actual.num_columns:
            return [f"column count {expected.num_columns} != "
                    f"{actual.num_columns}"]
        for i, (er, ar) in enumerate(zip(self._rows(expected),
                                         self._rows(actual))):
            for j, (ev, av) in enumerate(zip(er, ar)):
                if not self._value_eq(ev, av):
                    problems.append(
                        f"row {i} col {expected.column_names[j]}: "
                        f"{ev!r} != {av!r}")
                    if len(problems) >= 10:
                        return problems
        return problems

    def _value_eq(self, ev, av) -> bool:
        if ev is None or av is None:
            return ev is None and av is None
        if isinstance(ev, float) and isinstance(av, float):
            if math.isnan(ev) or math.isnan(av):
                return math.isnan(ev) and math.isnan(av)
            scale = max(abs(ev), abs(av), 1.0)
            return abs(ev - av) <= self.epsilon * scale
        return ev == av
