"""TpcdsLike queries q67-q99 (DataFrame form).

Reference analog: integration_tests/.../tests/tpcds/TpcdsLikeSpark.scala.
Same rewrite conventions as tpcds_queries_a.py.
"""

from __future__ import annotations

import datetime as _dt

from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.window import Window

from spark_rapids_tpu.bench.tpcds_queries_a import _d, _year_total


def q67(t):
    """Top items per category by rolled-up sales with rank window."""
    base = (t["store_sales"]
            .join(t["date_dim"].filter(
                (col("d_month_seq") >= lit(120))
                & (col("d_month_seq") <= lit(131))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], col("ss_item_sk") == col("i_item_sk")))
    val = F.coalesce(col("ss_sales_price")
                     * col("ss_quantity").cast("double"), lit(0.0))
    full = (base.group_by("i_category", "i_class", "i_brand",
                          "i_product_name", "d_year", "d_qoy", "d_moy",
                          "s_store_id")
            .agg(F.sum(val).alias("sumsales")))
    cat = (base.group_by("i_category")
           .agg(F.sum(val).alias("sumsales"))
           .select(col("i_category"),
                   lit(None).cast("string").alias("i_class"),
                   lit(None).cast("string").alias("i_brand"),
                   lit(None).cast("string").alias("i_product_name"),
                   lit(None).cast("int").alias("d_year"),
                   lit(None).cast("int").alias("d_qoy"),
                   lit(None).cast("int").alias("d_moy"),
                   lit(None).cast("string").alias("s_store_id"),
                   col("sumsales")))
    u = full.select("i_category", "i_class", "i_brand",
                    "i_product_name", "d_year", "d_qoy", "d_moy",
                    "s_store_id", "sumsales").union(cat)
    rk = F.rank().over(Window.partition_by("i_category")
                       .order_by(col("sumsales").desc()))
    return (u.select("i_category", "i_class", "i_brand",
                     "i_product_name", "d_year", "d_qoy", "d_moy",
                     "s_store_id", "sumsales", rk.alias("rk"))
            .filter(col("rk") <= lit(100))
            .sort(col("i_category").asc_nulls_last(),
                  col("rk").asc(), col("sumsales").desc())
            .limit(100))


def q69(t):
    """Demographics of store customers inactive on web+catalog."""
    dd = t["date_dim"].filter((col("d_year") == lit(2001))
                              & (col("d_moy") >= lit(4))
                              & (col("d_moy") <= lit(6)))
    ss_c = (t["store_sales"]
            .join(dd.select("d_date_sk"),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .select(col("ss_customer_sk").alias("act_sk")))
    ws_c = (t["web_sales"]
            .join(dd.select(col("d_date_sk").alias("wd_sk")),
                  col("ws_sold_date_sk") == col("wd_sk"))
            .select(col("ws_bill_customer_sk").alias("act_sk")))
    cs_c = (t["catalog_sales"]
            .join(dd.select(col("d_date_sk").alias("cd_sk")),
                  col("cs_sold_date_sk") == col("cd_sk"))
            .select(col("cs_bill_customer_sk").alias("act_sk")))
    c = (t["customer"]
         .join(t["customer_address"].filter(
             col("ca_state").isin("CA", "TX", "NY", "OH", "WA", "GA")),
             col("c_current_addr_sk") == col("ca_address_sk"))
         .join(ss_c, col("c_customer_sk") == col("act_sk"),
               how="leftsemi")
         .join(ws_c, col("c_customer_sk") == col("act_sk"),
               how="leftanti")
         .join(cs_c, col("c_customer_sk") == col("act_sk"),
               how="leftanti")
         .join(t["customer_demographics"],
               col("c_current_cdemo_sk") == col("cd_demo_sk")))
    return (c.group_by("cd_gender", "cd_marital_status",
                       "cd_education_status", "cd_purchase_estimate",
                       "cd_credit_rating")
            .agg(F.count("*").alias("cnt1"))
            .sort("cd_gender", "cd_marital_status",
                  "cd_education_status", "cd_purchase_estimate",
                  "cd_credit_rating")
            .limit(100))


def q70(t):
    """Store net profit rollup over state/county for top-5 states."""
    base = (t["store_sales"]
            .join(t["date_dim"].filter(
                (col("d_month_seq") >= lit(120))
                & (col("d_month_seq") <= lit(131))),
                col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk")))
    state_rank = (base.group_by("s_state")
                  .agg(F.sum("ss_net_profit").alias("sp"))
                  .select(col("s_state").alias("rank_state"),
                          F.rank().over(
                              Window.order_by(col("sp").desc()))
                          .alias("r"))
                  .filter(col("r") <= lit(5)))
    top = base.join(state_rank, col("s_state") == col("rank_state"),
                    how="leftsemi")
    lvl2 = (top.group_by("s_state", "s_county")
            .agg(F.sum("ss_net_profit").alias("total_sum"))
            .select("total_sum", "s_state", "s_county",
                    lit(0).alias("lochierarchy")))
    lvl1 = (top.group_by("s_state")
            .agg(F.sum("ss_net_profit").alias("total_sum"))
            .select(col("total_sum"), col("s_state"),
                    lit(None).cast("string").alias("s_county"),
                    lit(1).alias("lochierarchy")))
    lvl0 = (top.agg(F.sum("ss_net_profit").alias("total_sum"))
            .select(col("total_sum"),
                    lit(None).cast("string").alias("s_state"),
                    lit(None).cast("string").alias("s_county"),
                    lit(2).alias("lochierarchy")))
    u = lvl2.union(lvl1).union(lvl0)
    rk = F.rank().over(Window.partition_by("lochierarchy")
                       .order_by(col("total_sum").desc()))
    return (u.select("total_sum", "s_state", "s_county", "lochierarchy",
                     rk.alias("rank_within_parent"))
            .sort(col("lochierarchy").desc(),
                  col("s_state").asc_nulls_last(),
                  col("rank_within_parent").asc())
            .limit(100))


def q71(t):
    """Brand revenue by meal-time hour across all three channels."""
    def chan(fact, prefix):
        return (t[fact]
                .join(t["date_dim"].filter(
                    (col("d_moy") == lit(11))
                    & (col("d_year") == lit(1999)))
                    .select(col("d_date_sk").alias(fact + "_dsk")),
                    col(f"{prefix}_sold_date_sk") == col(fact + "_dsk"))
                .select(col(f"{prefix}_ext_sales_price")
                        .alias("ext_price"),
                        col(f"{prefix}_item_sk").alias("sold_item_sk"),
                        col(f"{prefix}_sold_time_sk")
                        .alias("time_sk")))

    u = (chan("web_sales", "ws")
         .union(chan("catalog_sales", "cs"))
         .union(chan("store_sales", "ss")))
    return (u.join(t["item"].filter(col("i_manager_id") == lit(1)),
                   col("sold_item_sk") == col("i_item_sk"))
            .join(t["time_dim"].filter(
                col("t_meal_time").isin("breakfast", "dinner")),
                col("time_sk") == col("t_time_sk"))
            .group_by("i_brand_id", "i_brand", "t_hour", "t_minute")
            .agg(F.sum("ext_price").alias("ext_price"))
            .sort(col("ext_price").desc(), col("i_brand_id").asc(),
                  col("t_hour").asc())
            .limit(100))


def q72(t):
    """Catalog orders where inventory ran short, by item/warehouse."""
    d1 = (t["date_dim"].filter(col("d_year") == lit(2000))
          .select(col("d_date_sk").alias("d1_sk"),
                  col("d_week_seq").alias("d1_week"),
                  col("d_date").alias("d1_date")))
    d2 = t["date_dim"].select(col("d_date_sk").alias("d2_sk"),
                              col("d_week_seq").alias("d2_week"))
    d3 = t["date_dim"].select(col("d_date_sk").alias("d3_sk"),
                              col("d_date").alias("d3_date"))
    return (t["catalog_sales"]
            .join(t["household_demographics"].filter(
                col("hd_buy_potential") == lit(">10000")),
                col("cs_bill_hdemo_sk") == col("hd_demo_sk"))
            .join(d1, col("cs_sold_date_sk") == col("d1_sk"))
            .join(t["inventory"],
                  col("cs_item_sk") == col("inv_item_sk"))
            .join(d2, (col("inv_date_sk") == col("d2_sk")))
            .filter((col("d1_week") == col("d2_week"))
                    & (col("inv_quantity_on_hand") < col("cs_quantity")))
            .join(t["warehouse"],
                  col("inv_warehouse_sk") == col("w_warehouse_sk"))
            .join(d3, col("cs_ship_date_sk") == col("d3_sk"))
            .join(t["item"], col("cs_item_sk") == col("i_item_sk"))
            .group_by("i_item_desc", "w_warehouse_name", "d1_week")
            .agg(F.count("*").alias("no_promo"))
            .sort(col("no_promo").desc(), col("i_item_desc").asc(),
                  col("w_warehouse_name").asc_nulls_last(),
                  col("d1_week").asc())
            .limit(100))


def q74(t):
    """Customers with web growth above store growth (quantity q11)."""
    s1 = _year_total(t, "s", True).select(
        col("c_customer_id").alias("id_s1"),
        col("year_total").alias("t_s1"))
    s2 = _year_total(t, "s", False).select(
        col("c_customer_id").alias("id_s2"),
        col("year_total").alias("t_s2"))
    w1 = _year_total(t, "w", True).select(
        col("c_customer_id").alias("id_w1"),
        col("year_total").alias("t_w1"))
    w2 = _year_total(t, "w", False).select(
        col("c_customer_id").alias("id_w2"),
        col("year_total").alias("t_w2"))
    return (s1.join(s2, col("id_s1") == col("id_s2"))
            .join(w1, col("id_s1") == col("id_w1"))
            .join(w2, col("id_s1") == col("id_w2"))
            .filter(col("t_w2") / col("t_w1")
                    > col("t_s2") / col("t_s1"))
            .select(col("id_s1").alias("customer_id"))
            .sort("customer_id")
            .limit(100))


def q75(t):
    """Sales net of returns per brand/year; shrinking brands."""
    def chan(fact, prefix, ret, rpre, ret_amt):
        r = t[ret].select(
            col(f"{rpre}_order_number" if rpre != "sr"
                else "sr_ticket_number").alias("r_ord"),
            col(f"{rpre}_item_sk").alias("r_isk"),
            col(f"{rpre}_return_quantity").alias("r_qty"),
            col(ret_amt).alias("r_amt"))
        ord_k = f"{prefix}_order_number" if prefix != "ss" \
            else "ss_ticket_number"
        return (t[fact]
                .join(t["item"].filter(
                    col("i_category") == lit("Electronics")),
                    col(f"{prefix}_item_sk") == col("i_item_sk"))
                .join(t["date_dim"]
                      .select(col("d_date_sk").alias(fact + "_dsk"),
                              col("d_year").alias(fact + "_year")),
                      col(f"{prefix}_sold_date_sk")
                      == col(fact + "_dsk"))
                .join(r, (col(ord_k) == col("r_ord"))
                      & (col(f"{prefix}_item_sk") == col("r_isk")),
                      how="left")
                .select(col(fact + "_year").alias("d_year"),
                        col("i_brand_id"),
                        (col(f"{prefix}_quantity")
                         - F.coalesce(col("r_qty"), lit(0)))
                        .alias("sales_cnt"),
                        (col(f"{prefix}_ext_sales_price")
                         - F.coalesce(col("r_amt"), lit(0.0)))
                        .alias("sales_amt")))

    u = (chan("catalog_sales", "cs", "catalog_returns", "cr",
              "cr_return_amount")
         .union(chan("store_sales", "ss", "store_returns", "sr",
                     "sr_return_amt"))
         .union(chan("web_sales", "ws", "web_returns", "wr",
                     "wr_return_amt")))
    year_tot = (u.group_by("d_year", "i_brand_id")
                .agg(F.sum("sales_cnt").alias("sales_cnt"),
                     F.sum("sales_amt").alias("sales_amt")))
    cur = (year_tot.filter(col("d_year") == lit(2002))
           .select(col("i_brand_id").alias("b_cur"),
                   col("sales_cnt").alias("cnt_cur"),
                   col("sales_amt").alias("amt_cur")))
    prev = (year_tot.filter(col("d_year") == lit(2001))
            .select(col("i_brand_id").alias("b_prev"),
                    col("sales_cnt").alias("cnt_prev"),
                    col("sales_amt").alias("amt_prev")))
    return (cur.join(prev, col("b_cur") == col("b_prev"))
            .filter(col("cnt_cur").cast("double")
                    / col("cnt_prev").cast("double") < lit(0.9))
            .select(col("b_cur").alias("i_brand_id"), col("cnt_prev"),
                    col("cnt_cur"), col("amt_prev"), col("amt_cur"))
            .sort((col("cnt_cur") - col("cnt_prev")).asc(),
                  col("i_brand_id").asc())
            .limit(100))


def q76(t):
    """Sales rows with null keys per channel/year/quarter/category."""
    ss = (t["store_sales"].filter(F.isnull(col("ss_addr_sk")))
          .join(t["item"], col("ss_item_sk") == col("i_item_sk"))
          .join(t["date_dim"],
                col("ss_sold_date_sk") == col("d_date_sk"))
          .select(lit("store").alias("channel"),
                  lit("ss_addr_sk").alias("col_name"), col("d_year"),
                  col("d_qoy"), col("i_category"),
                  col("ss_ext_sales_price").alias("ext_sales_price")))
    ws = (t["web_sales"].filter(F.isnull(col("ws_ship_customer_sk")))
          .join(t["item"].select(col("i_item_sk").alias("wi_sk"),
                                 col("i_category").alias("wi_cat")),
                col("ws_item_sk") == col("wi_sk"))
          .join(t["date_dim"].select(col("d_date_sk").alias("wd_sk"),
                                     col("d_year").alias("w_year"),
                                     col("d_qoy").alias("w_qoy")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .select(lit("web").alias("channel"),
                  lit("ws_ship_customer_sk").alias("col_name"),
                  col("w_year").alias("d_year"),
                  col("w_qoy").alias("d_qoy"),
                  col("wi_cat").alias("i_category"),
                  col("ws_ext_sales_price").alias("ext_sales_price")))
    cs = (t["catalog_sales"].filter(F.isnull(col("cs_ship_addr_sk")))
          .join(t["item"].select(col("i_item_sk").alias("ci_sk"),
                                 col("i_category").alias("ci_cat")),
                col("cs_item_sk") == col("ci_sk"))
          .join(t["date_dim"].select(col("d_date_sk").alias("cd_sk"),
                                     col("d_year").alias("c_year"),
                                     col("d_qoy").alias("c_qoy")),
                col("cs_sold_date_sk") == col("cd_sk"))
          .select(lit("catalog").alias("channel"),
                  lit("cs_ship_addr_sk").alias("col_name"),
                  col("c_year").alias("d_year"),
                  col("c_qoy").alias("d_qoy"),
                  col("ci_cat").alias("i_category"),
                  col("cs_ext_sales_price").alias("ext_sales_price")))
    return (ss.union(ws).union(cs)
            .group_by("channel", "col_name", "d_year", "d_qoy",
                      "i_category")
            .agg(F.count("*").alias("sales_cnt"),
                 F.sum("ext_sales_price").alias("sales_amt"))
            .sort("channel", "col_name", "d_year", "d_qoy",
                  "i_category")
            .limit(100))


def q77(t):
    """Per-channel sales & returns totals with channel rollup."""
    dd = t["date_dim"].filter((col("d_date") >= _d(2000, 8, 3))
                              & (col("d_date") <= _d(2000, 10, 2)))

    ss = (t["store_sales"]
          .join(dd.select("d_date_sk"),
                col("ss_sold_date_sk") == col("d_date_sk"))
          .group_by("ss_store_sk")
          .agg(F.sum("ss_ext_sales_price").alias("sales"),
               F.sum("ss_net_profit").alias("profit"))
          .select(lit("store channel").alias("channel"),
                  col("ss_store_sk").cast("bigint").alias("id"),
                  col("sales"), col("profit")))
    sr = (t["store_returns"]
          .join(dd.select(col("d_date_sk").alias("srd_sk")),
                col("sr_returned_date_sk") == col("srd_sk"))
          .group_by("sr_store_sk")
          .agg(F.sum("sr_return_amt").alias("s_returns"),
               F.sum("sr_net_loss").alias("s_loss")))
    ssr = (ss.join(sr.select(col("sr_store_sk").alias("r_id"),
                             col("s_returns"), col("s_loss")),
                   col("id") == col("r_id"), how="left")
           .select(col("channel"), col("id"), col("sales"),
                   F.coalesce(col("s_returns"), lit(0.0))
                   .alias("returns_"),
                   (col("profit") - F.coalesce(col("s_loss"), lit(0.0)))
                   .alias("profit")))

    cs = (t["catalog_sales"]
          .join(dd.select(col("d_date_sk").alias("csd_sk")),
                col("cs_sold_date_sk") == col("csd_sk"))
          .group_by("cs_call_center_sk")
          .agg(F.sum("cs_ext_sales_price").alias("sales"),
               F.sum("cs_net_profit").alias("profit")))
    cr = (t["catalog_returns"]
          .join(dd.select(col("d_date_sk").alias("crd_sk")),
                col("cr_returned_date_sk") == col("crd_sk"))
          .agg(F.sum("cr_return_amount").alias("c_returns"),
               F.sum("cr_net_loss").alias("c_loss")))
    csr = (cs.crossJoin(cr)
           .select(lit("catalog channel").alias("channel"),
                   col("cs_call_center_sk").cast("bigint").alias("id"),
                   col("sales"), col("c_returns").alias("returns_"),
                   (col("profit") - col("c_loss")).alias("profit")))

    ws = (t["web_sales"]
          .join(dd.select(col("d_date_sk").alias("wsd_sk")),
                col("ws_sold_date_sk") == col("wsd_sk"))
          .group_by("ws_web_page_sk")
          .agg(F.sum("ws_ext_sales_price").alias("sales"),
               F.sum("ws_net_profit").alias("profit"))
          .select(lit("web channel").alias("channel"),
                  col("ws_web_page_sk").cast("bigint").alias("id"),
                  col("sales"), lit(0.0).alias("returns_"),
                  col("profit")))

    detail = ssr.union(csr).union(ws)
    per_channel = (detail.group_by("channel")
                   .agg(F.sum("sales").alias("sales"),
                        F.sum("returns_").alias("returns_"),
                        F.sum("profit").alias("profit"))
                   .select(col("channel"),
                           lit(None).cast("bigint").alias("id"),
                           col("sales"), col("returns_"),
                           col("profit")))
    total = (detail.agg(F.sum("sales").alias("sales"),
                        F.sum("returns_").alias("returns_"),
                        F.sum("profit").alias("profit"))
             .select(lit(None).cast("string").alias("channel"),
                     lit(None).cast("bigint").alias("id"),
                     col("sales"), col("returns_"), col("profit")))
    return (detail.union(per_channel).union(total)
            .sort(col("channel").asc_nulls_last(),
                  col("id").asc_nulls_last())
            .limit(100))


def q78(t):
    """Customer-item yearly sales ratios for unreturned sales."""
    ws = (t["web_sales"]
          .join(t["web_returns"].select(
              col("wr_order_number").alias("wr_o"),
              col("wr_item_sk").alias("wr_i")),
              (col("ws_order_number") == col("wr_o"))
              & (col("ws_item_sk") == col("wr_i")), how="leftanti")
          .join(t["date_dim"].select(col("d_date_sk").alias("wd_sk"),
                                     col("d_year").alias("w_year")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .filter(col("w_year") >= lit(1998))
          .group_by("ws_item_sk", "ws_bill_customer_sk")
          .agg(F.sum("ws_quantity").alias("ws_qty"),
               F.sum("ws_wholesale_cost").alias("ws_wc"),
               F.sum("ws_sales_price").alias("ws_sp"))
          .select(col("ws_item_sk").alias("w_isk"),
                  col("ws_bill_customer_sk").alias("w_csk"),
                  col("ws_qty"), col("ws_wc"), col("ws_sp")))
    ss = (t["store_sales"]
          .join(t["store_returns"].select(
              col("sr_ticket_number").alias("sr_t"),
              col("sr_item_sk").alias("sr_i")),
              (col("ss_ticket_number") == col("sr_t"))
              & (col("ss_item_sk") == col("sr_i")), how="leftanti")
          .join(t["date_dim"],
                col("ss_sold_date_sk") == col("d_date_sk"))
          .filter(col("d_year") >= lit(1998))
          .group_by("ss_item_sk", "ss_customer_sk")
          .agg(F.sum("ss_quantity").alias("ss_qty"),
               F.sum("ss_wholesale_cost").alias("ss_wc"),
               F.sum("ss_sales_price").alias("ss_sp")))
    return (ss.join(ws, (col("ss_item_sk") == col("w_isk"))
                    & (col("ss_customer_sk") == col("w_csk")))
            .filter(col("ws_qty") > lit(0))
            .select(col("ss_item_sk"), col("ss_customer_sk"),
                    col("ss_qty"), col("ws_qty"),
                    (col("ss_qty").cast("double")
                     / col("ws_qty").cast("double")).alias("ratio"))
            .sort(col("ratio").desc(), col("ss_item_sk").asc())
            .limit(100))


def q79(t):
    """Customer ticket profits in big stores on weekdays."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == lit(4))
        | (col("hd_vehicle_count") > lit(2)))
    tickets = (t["store_sales"]
               .join(t["date_dim"].filter(
                   (col("d_dow") == lit(1))
                   & col("d_year").isin(1999, 2000, 2001)),
                   col("ss_sold_date_sk") == col("d_date_sk"))
               .join(t["store"].filter(
                   col("s_number_employees") >= lit(200)),
                   col("ss_store_sk") == col("s_store_sk"))
               .join(hd, col("ss_hdemo_sk") == col("hd_demo_sk"))
               .group_by("ss_ticket_number", "ss_customer_sk",
                         "s_city")
               .agg(F.sum("ss_coupon_amt").alias("amt"),
                    F.sum("ss_net_profit").alias("profit")))
    return (tickets
            .join(t["customer"],
                  col("ss_customer_sk") == col("c_customer_sk"))
            .select("c_last_name", "c_first_name", "s_city", "profit",
                    "ss_ticket_number", "amt")
            .sort(col("c_last_name").asc_nulls_last(),
                  col("c_first_name").asc_nulls_last(),
                  col("profit").desc(), col("ss_ticket_number").asc())
            .limit(100))


def q80(t):
    """Promotion channel totals rollup across the three channels."""
    dd = t["date_dim"].filter((col("d_date") >= _d(2000, 8, 3))
                              & (col("d_date") <= _d(2000, 10, 2)))
    promo = t["promotion"].filter(col("p_channel_tv") == lit("N"))

    ss = (t["store_sales"]
          .join(dd.select("d_date_sk"),
                col("ss_sold_date_sk") == col("d_date_sk"))
          .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
          .join(promo.select(col("p_promo_sk").alias("sp_sk")),
                col("ss_promo_sk") == col("sp_sk"), how="leftsemi")
          .join(t["store_returns"].select(
              col("sr_ticket_number").alias("sr_t"),
              col("sr_item_sk").alias("sr_i"),
              col("sr_return_amt").alias("sret"),
              col("sr_net_loss").alias("sloss")),
              (col("ss_ticket_number") == col("sr_t"))
              & (col("ss_item_sk") == col("sr_i")), how="left")
          .group_by("s_store_id")
          .agg(F.sum("ss_ext_sales_price").alias("sales"),
               F.sum(F.coalesce(col("sret"), lit(0.0)))
               .alias("returns_"),
               F.sum(col("ss_net_profit")
                     - F.coalesce(col("sloss"), lit(0.0)))
               .alias("profit"))
          .select(lit("store channel").alias("channel"),
                  col("s_store_id").alias("id"), col("sales"),
                  col("returns_"), col("profit")))
    cs = (t["catalog_sales"]
          .join(dd.select(col("d_date_sk").alias("cd_sk")),
                col("cs_sold_date_sk") == col("cd_sk"))
          .join(t["catalog_page"],
                col("cs_catalog_page_sk") == col("cp_catalog_page_sk"))
          .join(promo.select(col("p_promo_sk").alias("cp_sk")),
                col("cs_promo_sk") == col("cp_sk"), how="leftsemi")
          .join(t["catalog_returns"].select(
              col("cr_order_number").alias("cr_o"),
              col("cr_item_sk").alias("cr_i"),
              col("cr_return_amount").alias("cret"),
              col("cr_net_loss").alias("closs")),
              (col("cs_order_number") == col("cr_o"))
              & (col("cs_item_sk") == col("cr_i")), how="left")
          .group_by("cp_catalog_page_id")
          .agg(F.sum("cs_ext_sales_price").alias("sales"),
               F.sum(F.coalesce(col("cret"), lit(0.0)))
               .alias("returns_"),
               F.sum(col("cs_net_profit")
                     - F.coalesce(col("closs"), lit(0.0)))
               .alias("profit"))
          .select(lit("catalog channel").alias("channel"),
                  col("cp_catalog_page_id").alias("id"), col("sales"),
                  col("returns_"), col("profit")))
    ws = (t["web_sales"]
          .join(dd.select(col("d_date_sk").alias("wd_sk")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .join(t["web_site"],
                col("ws_web_site_sk") == col("web_site_sk"))
          .join(promo.select(col("p_promo_sk").alias("wp_sk")),
                col("ws_promo_sk") == col("wp_sk"), how="leftsemi")
          .join(t["web_returns"].select(
              col("wr_order_number").alias("wr_o"),
              col("wr_item_sk").alias("wr_i"),
              col("wr_return_amt").alias("wret"),
              col("wr_net_loss").alias("wloss")),
              (col("ws_order_number") == col("wr_o"))
              & (col("ws_item_sk") == col("wr_i")), how="left")
          .group_by("web_site_id")
          .agg(F.sum("ws_ext_sales_price").alias("sales"),
               F.sum(F.coalesce(col("wret"), lit(0.0)))
               .alias("returns_"),
               F.sum(col("ws_net_profit")
                     - F.coalesce(col("wloss"), lit(0.0)))
               .alias("profit"))
          .select(lit("web channel").alias("channel"),
                  col("web_site_id").alias("id"), col("sales"),
                  col("returns_"), col("profit")))
    detail = ss.union(cs).union(ws)
    per_channel = (detail.group_by("channel")
                   .agg(F.sum("sales").alias("sales"),
                        F.sum("returns_").alias("returns_"),
                        F.sum("profit").alias("profit"))
                   .select(col("channel"),
                           lit(None).cast("string").alias("id"),
                           col("sales"), col("returns_"),
                           col("profit")))
    total = (detail.agg(F.sum("sales").alias("sales"),
                        F.sum("returns_").alias("returns_"),
                        F.sum("profit").alias("profit"))
             .select(lit(None).cast("string").alias("channel"),
                     lit(None).cast("string").alias("id"),
                     col("sales"), col("returns_"), col("profit")))
    return (detail.union(per_channel).union(total)
            .sort(col("channel").asc_nulls_last(),
                  col("id").asc_nulls_last())
            .limit(100))


def q81(t):
    """Catalog returners above 1.2x state average (q30 catalog)."""
    ctr = (t["catalog_returns"]
           .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                 col("cr_returned_date_sk") == col("d_date_sk"))
           .join(t["customer"].select(
               col("c_customer_sk").alias("rc_sk"),
               col("c_current_addr_sk").alias("rc_addr")),
               col("cr_returning_customer_sk") == col("rc_sk"))
           .join(t["customer_address"],
                 col("rc_addr") == col("ca_address_sk"))
           .group_by("cr_returning_customer_sk", "ca_state")
           .agg(F.sum("cr_refunded_cash").alias("ctr_total_return")))
    avg_ctr = (ctr.group_by("ca_state")
               .agg((F.avg("ctr_total_return") * lit(1.2)).alias("thr"))
               .select(col("ca_state").alias("avg_state"), col("thr")))
    return (ctr
            .join(avg_ctr, col("ca_state") == col("avg_state"))
            .filter(col("ctr_total_return") > col("thr"))
            .join(t["customer"],
                  col("cr_returning_customer_sk")
                  == col("c_customer_sk"))
            .select("c_customer_id", "c_salutation", "c_first_name",
                    "c_last_name", "ca_state", "ctr_total_return")
            .sort("c_customer_id", "ctr_total_return")
            .limit(100))


def q82(t):
    """q37 for the store channel."""
    inv = (t["inventory"]
           .join(t["date_dim"].filter(
               (col("d_date") >= _d(2000, 5, 25))
               & (col("d_date") <= _d(2000, 7, 24))),
               col("inv_date_sk") == col("d_date_sk"))
           .filter((col("inv_quantity_on_hand") >= lit(100))
                   & (col("inv_quantity_on_hand") <= lit(500)))
           .select(col("inv_item_sk").alias("inv_sk")))
    sold = t["store_sales"].select(col("ss_item_sk").alias("sold_sk"))
    return (t["item"]
            .filter((col("i_current_price") >= lit(30.0))
                    & (col("i_current_price") <= lit(90.0)))
            .join(inv, col("i_item_sk") == col("inv_sk"),
                  how="leftsemi")
            .join(sold, col("i_item_sk") == col("sold_sk"),
                  how="leftsemi")
            .group_by("i_item_id", "i_item_desc", "i_current_price")
            .agg(F.count("*").alias("_cnt"))
            .select("i_item_id", "i_item_desc", "i_current_price")
            .sort("i_item_id")
            .limit(100))


def q83(t):
    """Return quantities per item across all three channels.
    (Like-delta: multi-year window — single-quarter triple-channel item
    overlap is empty in dbgen-lite data.)"""
    dd = t["date_dim"].filter((col("d_date") >= _d(1998, 1, 1))
                              & (col("d_date") <= _d(2002, 12, 31)))

    sr = (t["store_returns"]
          .join(dd.select("d_date_sk"),
                col("sr_returned_date_sk") == col("d_date_sk"))
          .join(t["item"], col("sr_item_sk") == col("i_item_sk"))
          .group_by("i_item_id")
          .agg(F.sum("sr_return_quantity").alias("sr_qty"))
          .select(col("i_item_id").alias("sr_id"), col("sr_qty")))
    cr = (t["catalog_returns"]
          .join(dd.select(col("d_date_sk").alias("cd_sk")),
                col("cr_returned_date_sk") == col("cd_sk"))
          .join(t["item"].select(col("i_item_sk").alias("ci_sk"),
                                 col("i_item_id").alias("cr_id")),
                col("cr_item_sk") == col("ci_sk"))
          .group_by("cr_id")
          .agg(F.sum("cr_return_quantity").alias("cr_qty")))
    wr = (t["web_returns"]
          .join(dd.select(col("d_date_sk").alias("wd_sk")),
                col("wr_returned_date_sk") == col("wd_sk"))
          .join(t["item"].select(col("i_item_sk").alias("wi_sk"),
                                 col("i_item_id").alias("wr_id")),
                col("wr_item_sk") == col("wi_sk"))
          .group_by("wr_id")
          .agg(F.sum("wr_return_quantity").alias("wr_qty")))
    j = (sr.join(cr, col("sr_id") == col("cr_id"))
         .join(wr, col("sr_id") == col("wr_id")))
    total = (col("sr_qty") + col("cr_qty") + col("wr_qty")) \
        .cast("double")
    return (j.select(
        col("sr_id").alias("item_id"), col("sr_qty"),
        (col("sr_qty").cast("double") / total * lit(100.0))
        .alias("sr_dev"),
        col("cr_qty"),
        (col("cr_qty").cast("double") / total * lit(100.0))
        .alias("cr_dev"),
        col("wr_qty"),
        (col("wr_qty").cast("double") / total * lit(100.0))
        .alias("wr_dev"),
        (total / lit(3.0)).alias("average"))
        .sort("item_id", "sr_qty")
        .limit(100))


def q84(t):
    """Returning customers in one city within an income band."""
    return (t["customer"]
            .join(t["customer_address"].filter(
                col("ca_city").isin("Midway", "Fairview", "Oakland")),
                col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["household_demographics"],
                  col("c_current_hdemo_sk") == col("hd_demo_sk"))
            .join(t["income_band"].filter(
                (col("ib_lower_bound") >= lit(0))
                & (col("ib_upper_bound") <= lit(100000))),
                col("hd_income_band_sk") == col("ib_income_band_sk"))
            .join(t["customer_demographics"],
                  col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(t["store_returns"],
                  col("cd_demo_sk") == col("sr_cdemo_sk"),
                  how="leftsemi")
            .select(col("c_customer_id").alias("customer_id"),
                    F.concat(col("c_last_name"), lit(", "),
                             col("c_first_name")).alias("customername"))
            .sort("customer_id")
            .limit(100))


def q85(t):
    """Web return stats by reason with OR'd demographic conditions."""
    cd1 = t["customer_demographics"].select(
        col("cd_demo_sk").alias("cd1_sk"),
        col("cd_marital_status").alias("ms1"),
        col("cd_education_status").alias("es1"))
    cd2 = t["customer_demographics"].select(
        col("cd_demo_sk").alias("cd2_sk"),
        col("cd_marital_status").alias("ms2"),
        col("cd_education_status").alias("es2"))
    j = (t["web_sales"]
         .join(t["web_returns"],
               (col("ws_order_number") == col("wr_order_number"))
               & (col("ws_item_sk") == col("wr_item_sk")))
         .join(t["web_page"],
               col("ws_web_page_sk") == col("wp_web_page_sk"))
         .join(cd1, col("wr_refunded_cdemo_sk") == col("cd1_sk"))
         .join(cd2, col("wr_returning_cdemo_sk") == col("cd2_sk"))
         .join(t["customer_address"],
               col("wr_refunded_addr_sk") == col("ca_address_sk"))
         .join(t["date_dim"].filter(col("d_year") == lit(2000)),
               col("ws_sold_date_sk") == col("d_date_sk"))
         .join(t["reason"], col("wr_reason_sk") == col("r_reason_sk"))
         .filter((col("ms1") == col("ms2"))
                 & (col("es1") == col("es2"))))
    return (j.group_by("r_reason_desc")
            .agg(F.avg("ws_quantity").alias("avg_qty"),
                 F.avg("wr_refunded_cash").alias("avg_cash"),
                 F.avg("wr_fee").alias("avg_fee"))
            .sort("r_reason_desc")
            .limit(100))


def q86(t):
    """Web net profit rollup over the item hierarchy with rank."""
    base = (t["web_sales"]
            .join(t["date_dim"].filter(
                (col("d_month_seq") >= lit(120))
                & (col("d_month_seq") <= lit(131))),
                col("ws_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], col("ws_item_sk") == col("i_item_sk")))
    lvl2 = (base.group_by("i_category", "i_class")
            .agg(F.sum("ws_net_profit").alias("total_sum"))
            .select("total_sum", "i_category", "i_class",
                    lit(0).alias("lochierarchy")))
    lvl1 = (base.group_by("i_category")
            .agg(F.sum("ws_net_profit").alias("total_sum"))
            .select(col("total_sum"), col("i_category"),
                    lit(None).cast("string").alias("i_class"),
                    lit(1).alias("lochierarchy")))
    lvl0 = (base.agg(F.sum("ws_net_profit").alias("total_sum"))
            .select(col("total_sum"),
                    lit(None).cast("string").alias("i_category"),
                    lit(None).cast("string").alias("i_class"),
                    lit(2).alias("lochierarchy")))
    u = lvl2.union(lvl1).union(lvl0)
    rk = F.rank().over(Window.partition_by("lochierarchy")
                       .order_by(col("total_sum").desc()))
    return (u.select("total_sum", "i_category", "i_class",
                     "lochierarchy", rk.alias("rank_within_parent"))
            .sort(col("lochierarchy").desc(),
                  col("i_category").asc_nulls_last(),
                  col("rank_within_parent").asc())
            .limit(100))


def q87(t):
    """Store customers minus catalog minus web (EXCEPT chain)."""
    dd = t["date_dim"].filter((col("d_month_seq") >= lit(120))
                              & (col("d_month_seq") <= lit(131)))
    ss = (t["store_sales"]
          .join(dd.select("d_date_sk"),
                col("ss_sold_date_sk") == col("d_date_sk"))
          .select(col("ss_customer_sk").alias("sk")).distinct())
    cs = (t["catalog_sales"]
          .join(dd.select(col("d_date_sk").alias("cd_sk")),
                col("cs_sold_date_sk") == col("cd_sk"))
          .select(col("cs_bill_customer_sk").alias("csk")).distinct())
    ws = (t["web_sales"]
          .join(dd.select(col("d_date_sk").alias("wd_sk")),
                col("ws_sold_date_sk") == col("wd_sk"))
          .select(col("ws_bill_customer_sk").alias("wsk")).distinct())
    return (ss.join(cs, col("sk") == col("csk"), how="leftanti")
            .join(ws, col("sk") == col("wsk"), how="leftanti")
            .agg(F.count("*").alias("num_customers")))


def q88(t):
    """Half-hour sales counts through the day (8 cross-joined cells)."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") >= lit(0)))
    slots = [(8, 30), (9, 0), (9, 30), (10, 0), (10, 30), (11, 0),
             (11, 30), (12, 0)]
    out = None
    for i, (h, m) in enumerate(slots, 1):
        td = t["time_dim"].filter(
            (col("t_hour") == lit(h))
            & (col("t_minute") >= lit(m))
            & (col("t_minute") < lit(m + 30))).select(
            col("t_time_sk").alias(f"t{i}_sk"))
        cell = (t["store_sales"]
                .join(td, col("ss_sold_time_sk") == col(f"t{i}_sk"))
                .join(t["store"].filter(
                    col("s_store_name") == lit("store-1"))
                    .select(col("s_store_sk").alias(f"s{i}_sk")),
                    col("ss_store_sk") == col(f"s{i}_sk"))
                .agg(F.count("*").alias(f"h{i}")))
        out = cell if out is None else out.crossJoin(cell)
    return out


def q89(t):
    """Item-class monthly sales below their yearly average."""
    base = (t["store_sales"]
            .join(t["item"].filter(
                col("i_category").isin("Books", "Electronics",
                                       "Sports", "Men", "Jewelry",
                                       "Women")),
                col("ss_item_sk") == col("i_item_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(2000)),
                  col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], col("ss_store_sk") == col("s_store_sk"))
            .group_by("i_category", "i_class", "i_brand",
                      "s_store_name", "s_company_name", "d_moy")
            .agg(F.sum("ss_sales_price").alias("sum_sales")))
    v = base.select(
        "i_category", "i_class", "i_brand", "s_store_name",
        "s_company_name", "d_moy", "sum_sales",
        F.avg(col("sum_sales")).over(
            Window.partition_by("i_category", "i_brand",
                                "s_store_name", "s_company_name"))
        .alias("avg_monthly_sales"))
    return (v.filter(F.when(col("avg_monthly_sales") != lit(0.0),
                            F.abs(col("sum_sales")
                                  - col("avg_monthly_sales"))
                            / col("avg_monthly_sales"))
                     .otherwise(lit(None)) > lit(0.1))
            .sort((col("sum_sales") - col("avg_monthly_sales")).asc(),
                  col("s_store_name").asc(), col("d_moy").asc())
            .limit(100))


def q90(t):
    """AM to PM web sales ratio."""
    am = (t["web_sales"]
          .join(t["time_dim"].filter((col("t_hour") >= lit(8))
                                     & (col("t_hour") <= lit(9)))
                .select(col("t_time_sk").alias("am_sk")),
                col("ws_sold_time_sk") == col("am_sk"))
          .join(t["web_page"].filter((col("wp_char_count") >= lit(100))
                                     & (col("wp_char_count")
                                        <= lit(7000)))
                .select(col("wp_web_page_sk").alias("am_wp")),
                col("ws_web_page_sk") == col("am_wp"))
          .agg(F.count("*").alias("amc")))
    pm = (t["web_sales"]
          .join(t["time_dim"].filter((col("t_hour") >= lit(19))
                                     & (col("t_hour") <= lit(20)))
                .select(col("t_time_sk").alias("pm_sk")),
                col("ws_sold_time_sk") == col("pm_sk"))
          .join(t["web_page"].filter((col("wp_char_count") >= lit(100))
                                     & (col("wp_char_count")
                                        <= lit(7000)))
                .select(col("wp_web_page_sk").alias("pm_wp")),
                col("ws_web_page_sk") == col("pm_wp"))
          .agg(F.count("*").alias("pmc")))
    return (am.crossJoin(pm)
            .select((col("amc").cast("double")
                     / col("pmc").cast("double"))
                    .alias("am_pm_ratio")))


def q91(t):
    """Call-center catalog return losses by demographic group."""
    return (t["catalog_returns"]
            .join(t["call_center"],
                  col("cr_call_center_sk") == col("cc_call_center_sk"))
            .join(t["date_dim"].filter(col("d_year") == lit(1998)),
                  col("cr_returned_date_sk") == col("d_date_sk"))
            .join(t["customer"],
                  col("cr_returning_customer_sk")
                  == col("c_customer_sk"))
            .join(t["customer_demographics"].filter(
                col("cd_education_status").isin("Unknown",
                                                "Advanced Degree")),
                col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(t["household_demographics"].filter(
                col("hd_buy_potential").isin(">10000", "Unknown")),
                col("c_current_hdemo_sk") == col("hd_demo_sk"))
            .join(t["customer_address"],
                  col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by("cc_call_center_id", "cc_name", "cc_manager",
                      "cd_marital_status", "cd_education_status")
            .agg(F.sum("cr_net_loss").alias("returns_loss"))
            .sort(col("returns_loss").desc())
            .limit(100))


def q92(t):
    """Web excess discount (q32 web version)."""
    dd = t["date_dim"].filter((col("d_date") >= _d(2000, 1, 27))
                              & (col("d_date") <= _d(2000, 4, 26)))
    per_item = (t["web_sales"]
                .join(dd.select(col("d_date_sk").alias("ad_sk")),
                      col("ws_sold_date_sk") == col("ad_sk"))
                .group_by("ws_item_sk")
                .agg((F.avg("ws_ext_discount_amt") * lit(1.3))
                     .alias("thr"))
                .select(col("ws_item_sk").alias("avg_item_sk"),
                        col("thr")))
    return (t["web_sales"]
            .join(dd.select("d_date_sk"),
                  col("ws_sold_date_sk") == col("d_date_sk"))
            .join(t["item"].filter(col("i_manufact_id") <= lit(350)),
                  col("ws_item_sk") == col("i_item_sk"))
            .join(per_item, col("ws_item_sk") == col("avg_item_sk"))
            .filter(col("ws_ext_discount_amt") > col("thr"))
            .agg(F.sum("ws_ext_discount_amt")
                 .alias("excess_discount_amount")))


def q93(t):
    """Customer net sales after subtracting returned quantity value."""
    sr = (t["store_returns"]
          .join(t["reason"].filter(col("r_reason_desc")
                                   .startswith("reason 2")),
                col("sr_reason_sk") == col("r_reason_sk"))
          .select(col("sr_ticket_number").alias("r_t"),
                  col("sr_item_sk").alias("r_i"),
                  col("sr_return_quantity").alias("r_q")))
    act = F.when(F.isnull(col("r_q")),
                 col("ss_quantity").cast("double")
                 * col("ss_sales_price")) \
        .otherwise((col("ss_quantity") - col("r_q")).cast("double")
                   * col("ss_sales_price"))
    return (t["store_sales"]
            .join(sr, (col("ss_ticket_number") == col("r_t"))
                  & (col("ss_item_sk") == col("r_i")), how="left")
            .group_by("ss_customer_sk")
            .agg(F.sum(act).alias("sumsales"))
            .sort(col("sumsales").asc(),
                  col("ss_customer_sk").asc_nulls_last())
            .limit(100))


def q94(t):
    """Web orders shipped via multiple sites without returns."""
    ws1 = (t["web_sales"]
           .join(t["date_dim"].filter(
               (col("d_date") >= _d(1999, 2, 1))
               & (col("d_date") <= _d(1999, 4, 2))),
               col("ws_ship_date_sk") == col("d_date_sk"))
           .join(t["customer_address"].filter(
               col("ca_state").isin("IL", "CA", "TX", "NY", "WA")),
               col("ws_ship_addr_sk") == col("ca_address_sk"))
           .join(t["web_site"],
                 col("ws_web_site_sk") == col("web_site_sk")))
    multi = (t["web_sales"]
             .group_by("ws_order_number")
             .agg(F.count_distinct(col("ws_warehouse_sk"))
                  .alias("n_wh"))
             .filter(col("n_wh") > lit(1))
             .select(col("ws_order_number").alias("o2")))
    returned = t["web_returns"].select(
        col("wr_order_number").alias("ro"))
    base = (ws1.join(multi, col("ws_order_number") == col("o2"),
                     how="leftsemi")
            .join(returned, col("ws_order_number") == col("ro"),
                  how="leftanti"))
    dist = (base.select("ws_order_number").distinct()
            .agg(F.count("*").alias("order_count")))
    return (base.agg(F.sum("ws_ext_ship_cost")
                     .alias("total_shipping_cost"),
                     F.sum("ws_net_profit").alias("total_net_profit"))
            .crossJoin(dist)
            .select("order_count", "total_shipping_cost",
                    "total_net_profit"))


def q95(t):
    """Web orders that appear in returns AND ship multi-warehouse."""
    ws_wh = (t["web_sales"]
             .group_by("ws_order_number")
             .agg(F.count_distinct(col("ws_warehouse_sk"))
                  .alias("n_wh"))
             .filter(col("n_wh") > lit(1))
             .select(col("ws_order_number").alias("o2")))
    returned = t["web_returns"].select(
        col("wr_order_number").alias("ro"))
    base = (t["web_sales"]
            .join(t["date_dim"].filter(
                (col("d_date") >= _d(1999, 2, 1))
                & (col("d_date") <= _d(1999, 4, 2))),
                col("ws_ship_date_sk") == col("d_date_sk"))
            .join(t["customer_address"].filter(
                col("ca_state").isin("IL", "CA", "TX", "NY", "WA")),
                col("ws_ship_addr_sk") == col("ca_address_sk"))
            .join(t["web_site"],
                  col("ws_web_site_sk") == col("web_site_sk"))
            .join(ws_wh, col("ws_order_number") == col("o2"),
                  how="leftsemi")
            .join(returned, col("ws_order_number") == col("ro"),
                  how="leftsemi"))
    dist = (base.select("ws_order_number").distinct()
            .agg(F.count("*").alias("order_count")))
    return (base.agg(F.sum("ws_ext_ship_cost")
                     .alias("total_shipping_cost"),
                     F.sum("ws_net_profit").alias("total_net_profit"))
            .crossJoin(dist)
            .select("order_count", "total_shipping_cost",
                    "total_net_profit"))


def q97(t):
    """Store/catalog customer-item overlap counts."""
    dd = t["date_dim"].filter((col("d_month_seq") >= lit(120))
                              & (col("d_month_seq") <= lit(131)))
    ss = (t["store_sales"]
          .join(dd.select("d_date_sk"),
                col("ss_sold_date_sk") == col("d_date_sk"))
          .select(col("ss_customer_sk").alias("s_csk"),
                  col("ss_item_sk").alias("s_isk")).distinct())
    cs = (t["catalog_sales"]
          .join(dd.select(col("d_date_sk").alias("cd_sk")),
                col("cs_sold_date_sk") == col("cd_sk"))
          .select(col("cs_bill_customer_sk").alias("c_csk"),
                  col("cs_item_sk").alias("c_isk")).distinct())
    j = ss.join(cs, (col("s_csk") == col("c_csk"))
                & (col("s_isk") == col("c_isk")), how="full")
    return j.agg(
        F.sum(F.when(F.isnull(col("c_csk")), lit(1)).otherwise(lit(0)))
        .alias("store_only"),
        F.sum(F.when(F.isnull(col("s_csk")), lit(1)).otherwise(lit(0)))
        .alias("catalog_only"),
        F.sum(F.when((~F.isnull(col("s_csk")))
                     & (~F.isnull(col("c_csk"))), lit(1))
              .otherwise(lit(0))).alias("store_and_catalog"))


def q99(t):
    """Catalog shipping-lag buckets by call center/ship mode."""
    lag = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    return (t["catalog_sales"]
            .join(t["date_dim"].filter((col("d_month_seq") >= lit(120))
                                       & (col("d_month_seq")
                                          <= lit(131))),
                  col("cs_ship_date_sk") == col("d_date_sk"))
            .join(t["call_center"],
                  col("cs_call_center_sk") == col("cc_call_center_sk"))
            .join(t["ship_mode"],
                  col("cs_ship_mode_sk") == col("sm_ship_mode_sk"))
            .join(t["warehouse"],
                  col("cs_warehouse_sk") == col("w_warehouse_sk"))
            .group_by("w_warehouse_name", "sm_type", "cc_name")
            .agg(F.sum(F.when(lag <= lit(30), lit(1)).otherwise(lit(0)))
                 .alias("days_30"),
                 F.sum(F.when((lag > lit(30)) & (lag <= lit(60)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_31_60"),
                 F.sum(F.when((lag > lit(60)) & (lag <= lit(90)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_61_90"),
                 F.sum(F.when((lag > lit(90)) & (lag <= lit(120)),
                              lit(1)).otherwise(lit(0)))
                 .alias("days_91_120"),
                 F.sum(F.when(lag > lit(120), lit(1))
                       .otherwise(lit(0))).alias("days_over_120"))
            .sort(col("w_warehouse_name").asc_nulls_last(),
                  col("sm_type").asc(), col("cc_name").asc())
            .limit(100))
