"""Device-side Parquet decode: pages upload packed, decode runs in HBM.

TPU-native analog of the reference's core scan trick: CPU clips footers and
reassembles raw column chunks, then `Table.readParquet(hostBuffer)` decodes
**on device** (reference: GpuParquetScan.scala:456-620 host assembly,
:1022,1400,1536 device decode via libcudf's CUDA parquet kernels).

Here the CPU walks page headers and RLE/bit-packed run boundaries — O(pages
+ runs), not O(values) — and the O(values) work happens in XLA on TPU:

  * hybrid RLE/bit-pack expansion: `searchsorted` run lookup + 4-byte
    window gather + shift/mask (vectorized bit-unpack)
  * definition levels -> validity, then non-null value scatter via
    `cumsum(validity)` (the two-pass pattern of SURVEY.md §7 hard part #1)
  * dictionary gather in HBM (including string dictionaries as padded
    byte-matrix gathers)

Coverage: PLAIN + PLAIN_/RLE_DICTIONARY for INT32/INT64/FLOAT/DOUBLE/
BOOLEAN, dictionary-encoded BYTE_ARRAY (strings), flat schemas
(max_rep == 0, max_def <= 1), data pages v1 + v2, any Arrow-supported page
codec (host decompress — the nvcomp role stays host-side on TPU).  Anything
else falls back to Arrow host decode *per column*, so one exotic column
doesn't knock the whole scan off the device path (the reference's
per-operator fallback philosophy applied at column granularity).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             bucket_rows, from_arrow)
from spark_rapids_tpu.io import parquet_meta as pm
from spark_rapids_tpu.plan.logical import Schema

_MAX_W = 24  # 4-byte gather window supports shift(<=7) + w bits
# the dense phase-decomposed paths (io/parquet_fused.py and the Pallas
# kernel backend, kernels/decode.py) unpack any width up to a full
# 32-bit index word; plan_chunk admits those and the per-column XLA
# expansion falls back per column at decode time when w > _MAX_W
_MAX_W_DENSE = 32


# ---------------------------------------------------------------------------
# Host side: run walking (O(runs), not O(values))
# ---------------------------------------------------------------------------

@dataclass
class RunTable:
    """Hybrid RLE/bit-pack runs, concatenated across pages of a chunk.

    `bit_base` indexes into the shared `packed` byte buffer for bit-packed
    runs; `value` holds the repeated value for RLE runs."""

    counts: List[int]
    is_rle: List[bool]
    values: List[int]
    bit_bases: List[int]
    widths: List[int]

    @staticmethod
    def empty() -> "RunTable":
        return RunTable([], [], [], [], [])

    @property
    def total(self) -> int:
        return sum(self.counts)

    def trim_to(self, n: int) -> None:
        """Drop bit-pack padding so total == n (last runs clamp)."""
        excess = self.total - n
        while excess > 0 and self.counts:
            take = min(excess, self.counts[-1])
            self.counts[-1] -= take
            excess -= take
            if self.counts[-1] == 0:
                for lst in (self.counts, self.is_rle, self.values,
                            self.bit_bases, self.widths):
                    lst.pop()


def walk_hybrid(buf: bytes, start: int, end: int, w: int,
                packed: bytearray, runs: RunTable,
                max_values: Optional[int] = None) -> int:
    """Walk one page's hybrid stream appending runs; returns values seen.

    Bit-packed byte regions are appended to `packed` so the device sees one
    contiguous buffer per chunk."""
    pos = start
    vbytes = (w + 7) // 8
    seen = 0
    while pos < end and (max_values is None or seen < max_values):
        h = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            h |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if h & 1:  # bit-packed groups
            groups = h >> 1
            count = groups * 8
            nbytes = groups * w
            runs.counts.append(count)
            runs.is_rle.append(False)
            runs.values.append(0)
            runs.bit_bases.append(len(packed) * 8)
            runs.widths.append(w)
            packed += buf[pos:pos + nbytes]
            pos += nbytes
        else:  # RLE run
            count = h >> 1
            val = int.from_bytes(buf[pos:pos + vbytes], "little") \
                if vbytes else 0
            pos += vbytes
            runs.counts.append(count)
            runs.is_rle.append(True)
            runs.values.append(val)
            runs.bit_bases.append(0)
            runs.widths.append(w)
        seen += count
    return seen


def nonnull_count(runs: RunTable, packed: bytes, lo_run: int, hi_run: int,
                  n: int) -> int:
    """Host count of def-level==1 entries among the first n values of the
    run range [lo_run, hi_run) — popcount over bit-packed regions only."""
    remaining = n
    nn = 0
    for i in range(lo_run, hi_run):
        c = min(runs.counts[i], remaining)
        if c <= 0:
            break
        if runs.is_rle[i]:
            nn += c if runs.values[i] == 1 else 0
        else:
            base = runs.bit_bases[i] // 8
            nbytes = (c + 7) // 8
            bits = np.unpackbits(
                np.frombuffer(packed, dtype=np.uint8,
                              count=nbytes, offset=base),
                bitorder="little")[:c]
            nn += int(bits.sum())
        remaining -= c
    return nn


# ---------------------------------------------------------------------------
# Device side: jitted expansion kernels (static shapes per bucket)
# ---------------------------------------------------------------------------

def expand_runs_matrix(runs_mat: jnp.ndarray, packed: jnp.ndarray,
                       cap: int) -> jnp.ndarray:
    """Expand one hybrid-run stream to a [cap] uint32 vector (device,
    one pass).  ``runs_mat`` is [rcap, 5] with columns (cumulative end,
    is_rle, value, bit_base, width); int32 or int64.

    Used by the per-column decode path (this module) only; the fused
    whole-batch kernel (io/parquet_fused.py) uses a dense phase-
    decomposed unpack (_unpack_width + slice/scatter run expansion)
    instead — when touching bit math here, check that module too.
    """
    ends = runs_mat[:, 0]
    i = jnp.arange(cap, dtype=ends.dtype)
    # run id per element: scatter a marker at each run boundary and
    # cumsum (pure vector ops) — NOT searchsorted, whose ~log2(rcap)
    # binary-search steps are per-element random gathers (TPU gathers
    # run ~90M/s; this one change cut the fused decode 2.4x)
    # clamp sentinel/padding ends to cap BEFORE the scatter: a 2^62
    # sentinel wraps during the index-dtype conversion instead of being
    # dropped, landing a spurious bump at slot 0
    bump = jnp.zeros((cap,), jnp.int32).at[
        jnp.minimum(ends, cap)].add(1, mode="drop")
    rid = jnp.cumsum(bump)
    rid = jnp.clip(rid, 0, ends.shape[0] - 1)
    prev_end = jnp.where(rid > 0, jnp.take(ends, rid - 1), 0)
    local = i - prev_end
    w = jnp.take(runs_mat[:, 4], rid)
    bitpos = jnp.take(runs_mat[:, 3], rid) + local * w
    byte0 = bitpos >> 3
    sh = (bitpos & 7).astype(jnp.uint32)
    nb = packed.shape[0]
    g = lambda k: jnp.take(packed, jnp.clip(byte0 + k, 0, nb - 1)
                           ).astype(jnp.uint32)
    window = g(0) | (g(1) << 8) | (g(2) << 16) | (g(3) << 24)
    mask = ((jnp.uint32(1) << w.astype(jnp.uint32)) - 1)
    unpacked = (window >> sh) & mask
    return jnp.where(jnp.take(runs_mat[:, 1], rid) != 0,
                     jnp.take(runs_mat[:, 2], rid).astype(jnp.uint32),
                     unpacked)


@partial(jax.jit, static_argnames=("cap",))
def _expand_runs_packed(runs_mat: jnp.ndarray, packed: jnp.ndarray,
                        cap: int) -> jnp.ndarray:
    """Jitted wrapper over expand_runs_matrix (one upload per stream)."""
    return expand_runs_matrix(runs_mat, packed, cap)


@partial(jax.jit, static_argnames=("cap",))
def _def_expand(levels: jnp.ndarray, values: jnp.ndarray, n_rows,
                cap: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """validity + per-row values from def levels and non-null-compacted
    values (cumsum two-pass scatter; values may be 1-D or 2-D)."""
    row = jnp.arange(cap)
    valid = (levels == 1) & (row < n_rows)
    vidx = jnp.cumsum(valid.astype(jnp.int32)) - 1
    vidx = jnp.clip(vidx, 0, values.shape[0] - 1)
    out = jnp.take(values, vidx, axis=0)
    if out.ndim == 2:
        out = jnp.where(valid[:, None], out, 0)
    else:
        out = jnp.where(valid, out, jnp.zeros_like(out))
    return out, valid


@partial(jax.jit, static_argnames=("cap",))
def _dict_gather(indices: jnp.ndarray, dictionary: jnp.ndarray,
                 valid: jnp.ndarray, cap: int
                 ) -> jnp.ndarray:
    idx = jnp.clip(indices.astype(jnp.int32), 0, dictionary.shape[0] - 1)
    out = jnp.take(dictionary, idx, axis=0)
    if out.ndim == 2:
        return jnp.where(valid[:, None], out, 0)
    return jnp.where(valid, out, jnp.zeros_like(out))


def _pad_np(a: np.ndarray, cap: int, fill=0) -> np.ndarray:
    if a.shape[0] >= cap:
        return a[:cap]
    pad = np.full((cap - a.shape[0],) + a.shape[1:], fill, dtype=a.dtype)
    return np.concatenate([a, pad], axis=0)


def _upload_runs(runs: RunTable, packed: bytes):
    """Bucket + upload a run table as TWO device arrays (one [rcap, 5]
    int64 run matrix + the packed byte buffer) — minimizing host->device
    transfers, which dominate scan cost on remote/tunneled devices."""
    r = max(len(runs.counts), 1)
    rcap = bucket_rows(r, 8)
    ends = np.cumsum(np.asarray(runs.counts + [0], dtype=np.int64))[:r]
    n = len(runs.counts)
    mat = np.zeros((rcap, 5), dtype=np.int64)
    mat[:, 0] = _pad_np(ends, rcap, fill=np.int64(1) << 62)
    mat[:n, 1] = np.asarray(runs.is_rle, dtype=np.int64)
    mat[:n, 2] = np.asarray(runs.values, dtype=np.int64)
    mat[:n, 3] = np.asarray(runs.bit_bases, dtype=np.int64)
    mat[:n, 4] = np.asarray(runs.widths, dtype=np.int64)
    bcap = bucket_rows(max(len(packed), 4), 64)
    return dict(
        runs_mat=jnp.asarray(mat),
        packed=jnp.asarray(_pad_np(
            np.frombuffer(bytes(packed), dtype=np.uint8), bcap)))


# ---------------------------------------------------------------------------
# Per-chunk decode
# ---------------------------------------------------------------------------

_PLAIN_NP = {"INT32": np.dtype("<i4"), "INT64": np.dtype("<i8"),
             "FLOAT": np.dtype("<f4"), "DOUBLE": np.dtype("<f8")}


class UnsupportedChunk(Exception):
    pass


def _parse_plain_byte_array(buf: bytes, n: int) -> List[bytes]:
    out = []
    pos = 0
    for _ in range(n):
        ln = struct.unpack_from("<I", buf, pos)[0]
        pos += 4
        out.append(buf[pos:pos + ln])
        pos += ln
    return out


def _string_dict_matrix(vals: List[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    from spark_rapids_tpu.columnar.batch import _bucket_strlen
    max_len = _bucket_strlen(max((len(v) for v in vals), default=1))
    mat = np.zeros((max(len(vals), 1), max_len), dtype=np.uint8)
    lens = np.zeros((max(len(vals), 1),), dtype=np.int32)
    for i, v in enumerate(vals):
        mat[i, :len(v)] = np.frombuffer(v, dtype=np.uint8)
        lens[i] = len(v)
    return mat, lens


@dataclass
class ChunkPlan:
    """Host-side decode plan for one flat column chunk: run tables,
    packed bit regions, raw PLAIN bytes and dictionaries — everything
    the device expansion kernels need, produced by one O(pages+runs)
    host walk.  Shared by the per-column decode path (decode_chunk) and
    the fused whole-row-group kernel (io/parquet_fused.py)."""
    n_rows: int
    nullable: bool
    out_dtype: dt.DType
    mode: str                      # 'dict' | 'dict_str' | 'plain' | 'bool'
    def_runs: RunTable = None
    def_packed: bytes = b""
    val_runs: RunTable = None      # dict indices or bool bits
    val_packed: bytes = b""
    plain_np: np.ndarray = None    # PLAIN values (raw, non-null only)
    dict_np: np.ndarray = None
    dict_lens: np.ndarray = None
    page_segs: list = None         # per-page ('dict'|'plain', n_values)


def plan_chunk(chunk: pm.ChunkPages, out_dtype: dt.DType,
               allow_mixed: bool = False) -> ChunkPlan:
    """Host walk of one chunk's pages -> ChunkPlan (raises
    UnsupportedChunk for anything the device path doesn't cover).

    ``allow_mixed`` permits chunks whose dictionary overflowed mid-chunk
    (dict pages then PLAIN pages — pyarrow does this for high-cardinality
    columns); the fused path doesn't take them."""
    if chunk.max_rep > 0 or chunk.max_def > 1:
        raise UnsupportedChunk("nested column")
    ptype = chunk.physical_type
    if ptype not in _PLAIN_NP and ptype != "BOOLEAN" and \
            ptype != "BYTE_ARRAY":
        raise UnsupportedChunk(f"physical type {ptype}")
    lt = chunk.logical_type
    if "Decimal" in lt or "Time(" in lt or "isSigned=false" in lt or \
            ("Timestamp" in lt and "micro" not in lt):
        # value transforms the device path doesn't do (unit scaling,
        # unsigned reinterpretation, decimal) — host Arrow handles them
        raise UnsupportedChunk(f"logical type {lt}")

    # -- dictionary page (host parse; dictionaries are small) --------------
    dict_np = None
    dict_lens = None
    if chunk.dict_page is not None:
        dp = chunk.dict_page
        payload = pm.decompress(
            chunk.codec, chunk.data[dp.payload_off:
                                    dp.payload_off + dp.compressed_size],
            dp.uncompressed_size)
        if ptype == "BYTE_ARRAY":
            vals = _parse_plain_byte_array(payload, dp.num_values)
            dict_np, dict_lens = _string_dict_matrix(vals)
        else:
            dict_np = np.frombuffer(payload, dtype=_PLAIN_NP[ptype],
                                    count=dp.num_values).copy()
            if dict_np.shape[0] == 0:  # all-null chunk: empty dictionary
                dict_np = np.zeros((1,), dtype=_PLAIN_NP[ptype])

    nullable = chunk.max_def == 1
    def_runs = RunTable.empty()
    def_packed = bytearray()
    idx_runs = RunTable.empty()
    idx_packed = bytearray()
    plain_parts: List[bytes] = []   # PLAIN value byte regions
    bool_runs = RunTable.empty()    # BOOLEAN PLAIN == w=1 bit-pack runs
    bool_packed = bytearray()
    n_rows = 0
    n_nonnull_plain = 0
    idx_target = 0   # expected cumulative values in the index stream
    bool_target = 0
    any_dict = False
    any_plain = False
    page_segs: List[Tuple[str, int]] = []

    for page in chunk.data_pages:
        raw = chunk.data[page.payload_off:
                         page.payload_off + page.compressed_size]
        if page.page_type == pm.DATA_PAGE_V2:
            lvl = page.v2_rep_bytes + page.v2_def_bytes
            levels_buf = raw[:lvl]
            if page.v2_is_compressed:
                vals_buf = pm.decompress(chunk.codec, raw[lvl:],
                                         page.uncompressed_size - lvl)
            else:
                vals_buf = raw[lvl:]
            def_start, def_end = page.v2_rep_bytes, lvl
        else:
            payload = pm.decompress(chunk.codec, raw,
                                    page.uncompressed_size)
            levels_buf = payload
            if nullable:
                dlen = struct.unpack_from("<I", payload, 0)[0]
                def_start, def_end = 4, 4 + dlen
                vals_buf = payload[def_end:]
            else:
                def_start = def_end = 0
                vals_buf = payload

        lo = len(def_runs.counts)
        if nullable:
            walk_hybrid(levels_buf, def_start, def_end, 1,
                        def_packed, def_runs)
            def_runs.trim_to(n_rows + page.num_values)
            nn = nonnull_count(def_runs, bytes(def_packed), lo,
                               len(def_runs.counts), page.num_values)
        else:
            nn = page.num_values
        n_rows += page.num_values

        enc = page.encoding
        if enc in (pm.PLAIN_DICTIONARY, pm.RLE_DICTIONARY):
            if dict_np is None:
                raise UnsupportedChunk("dict-encoded page w/o dictionary")
            any_dict = True
            w = vals_buf[0]
            if w > _MAX_W_DENSE:
                raise UnsupportedChunk(f"dict bit width {w}")
            walk_hybrid(vals_buf, 1, len(vals_buf), w, idx_packed,
                        idx_runs)
            # trim this page's bit-pack group-of-8 padding
            idx_target += nn
            idx_runs.trim_to(idx_target)
            page_segs.append(("dict", nn))
        elif enc == pm.PLAIN:
            any_plain = True
            page_segs.append(("plain", nn))
            if ptype == "BOOLEAN":
                groups = (nn + 7) // 8
                bool_runs.counts.append(groups * 8)
                bool_runs.is_rle.append(False)
                bool_runs.values.append(0)
                bool_runs.bit_bases.append(len(bool_packed) * 8)
                bool_runs.widths.append(1)
                bool_packed += vals_buf[:groups]
                bool_target += nn
                bool_runs.trim_to(bool_target)
            elif ptype == "BYTE_ARRAY":
                raise UnsupportedChunk("PLAIN byte_array page")
            else:
                itemsize = _PLAIN_NP[ptype].itemsize
                plain_parts.append(vals_buf[:nn * itemsize])
            n_nonnull_plain += nn
        else:
            raise UnsupportedChunk(f"encoding {enc}")

    if any_dict and any_plain:
        # dictionary overflowed mid-chunk (pyarrow does this for
        # high-cardinality columns): dict-coded pages then PLAIN pages
        if not allow_mixed or out_dtype.is_string or \
                ptype == "BOOLEAN":
            raise UnsupportedChunk("mixed dict+plain pages")
        mode = "mixed"
    elif any_dict:
        mode = "dict_str" if out_dtype.is_string else "dict"
    elif ptype == "BOOLEAN":
        mode = "bool"
    else:
        mode = "plain"
    plain_np = None
    if mode in ("plain", "mixed"):
        raw = b"".join(plain_parts)
        plain_np = np.frombuffer(raw, dtype=_PLAIN_NP[ptype],
                                 count=n_nonnull_plain)
    return ChunkPlan(
        n_rows=n_rows, nullable=nullable, out_dtype=out_dtype, mode=mode,
        def_runs=def_runs, def_packed=bytes(def_packed),
        val_runs=idx_runs if any_dict else bool_runs,
        val_packed=bytes(idx_packed) if any_dict else bytes(bool_packed),
        plain_np=plain_np, dict_np=dict_np, dict_lens=dict_lens,
        page_segs=page_segs)


def decode_chunk(chunk: pm.ChunkPages, out_dtype: dt.DType,
                 cap: int) -> DeviceColumn:
    """Decode one flat column chunk into a DeviceColumn of capacity cap."""
    return decode_plan(plan_chunk(chunk, out_dtype, allow_mixed=True), cap)


def decode_plan(p: "ChunkPlan", cap: int,
                backend: Optional[str] = None) -> DeviceColumn:
    """Decode one host-walked ChunkPlan (possibly served by the scan
    -plan cache — io/scan_cache.py) into a DeviceColumn of capacity
    cap.  Treats the plan as immutable: plans are shared across
    queries and threads.

    ``backend`` selects the stream-expansion kernel per stream
    (``kernel.backend``): 'pallas' runs the dense phase-decomposed
    unpack (kernels/decode.py, ~1 gather/element, widths to 32),
    'xla'/None the window-gather path (~9 gathers/element, widths to
    ``_MAX_W``) — with per-stream fallback between them and the
    existing per-column host-Arrow fallback beneath both."""
    from spark_rapids_tpu.kernels import decode as kdec
    out_dtype = p.out_dtype
    n_rows = p.n_rows

    # -- device expansion ---------------------------------------------------
    vcap = bucket_rows(max(n_rows, 1))
    if p.nullable:
        levels = kdec.expand_stream(p.def_runs, p.def_packed, vcap,
                                    backend=backend)
    else:
        levels = None

    np_t = out_dtype.to_np() if not out_dtype.is_string else None

    if p.mode in ("dict", "dict_str"):
        indices = kdec.expand_stream(p.val_runs, p.val_packed, vcap,
                                     backend=backend)
        if p.nullable:
            indices, valid = _def_expand(levels, indices, n_rows, cap=vcap)
        else:
            valid = jnp.arange(vcap) < n_rows
        if out_dtype.is_string:
            d_mat = jnp.asarray(p.dict_np)
            d_len = jnp.asarray(p.dict_lens)
            data = _dict_gather(indices, d_mat, valid, cap=vcap)
            lengths = _dict_gather(indices, d_len, valid, cap=vcap)
            return _to_cap(DeviceColumn(out_dtype, data, valid,
                                        lengths.astype(jnp.int32)), cap)
        d_vals = jnp.asarray(p.dict_np.astype(np_t, copy=False))
        data = _dict_gather(indices, d_vals, valid, cap=vcap)
        return _to_cap(DeviceColumn(out_dtype, data, valid), cap)

    if p.mode == "bool":
        bits = kdec.expand_stream(p.val_runs, p.val_packed, vcap,
                                  backend=backend)
        vals = bits.astype(jnp.bool_)
    elif p.mode == "mixed":
        # merge dict-coded and PLAIN page segments in page order:
        # per-value source selectors built with vectorized numpy repeat
        indices = kdec.expand_stream(p.val_runs, p.val_packed, vcap,
                                     backend=backend)
        d_vals = jnp.take(
            jnp.asarray(p.dict_np.astype(np_t, copy=False)),
            jnp.clip(indices.astype(jnp.int32), 0,
                     p.dict_np.shape[0] - 1))
        p_vals = jnp.asarray(_pad_np(p.plain_np.astype(np_t, copy=True),
                                     vcap))
        kinds = np.array([k == "dict" for k, _ in p.page_segs])
        counts = np.array([c for _, c in p.page_segs], dtype=np.int64)
        sel = np.repeat(kinds, counts)
        di = np.cumsum(sel) - 1
        pi = np.cumsum(~sel) - 1
        sel_d = jnp.asarray(_pad_np(sel, vcap))
        di_d = jnp.asarray(_pad_np(di.astype(np.int32), vcap))
        pi_d = jnp.asarray(_pad_np(pi.astype(np.int32), vcap))
        vals = jnp.where(
            sel_d,
            jnp.take(d_vals, jnp.clip(di_d, 0, vcap - 1)),
            jnp.take(p_vals, jnp.clip(pi_d, 0, vcap - 1)))
    else:
        vals = jnp.asarray(_pad_np(p.plain_np.copy(), vcap))

    if p.nullable:
        data, valid = _def_expand(levels, vals, n_rows, cap=vcap)
    else:
        data, valid = vals, jnp.arange(vcap) < n_rows
        if data.ndim == 1:
            data = jnp.where(valid, data, jnp.zeros_like(data))
    data = data.astype(np_t)
    return _to_cap(DeviceColumn(out_dtype, data, valid), cap)


def _to_cap(col: DeviceColumn, cap: int) -> DeviceColumn:
    """Re-bucket a column to the batch capacity (jitted per shape)."""
    if col.capacity == cap:
        return col
    return _to_cap_jit(col, cap=cap)


@partial(jax.jit, static_argnames=("cap",))
def _to_cap_jit(col: DeviceColumn, cap: int) -> DeviceColumn:
    idx = jnp.arange(cap)
    valid_src = idx < col.capacity
    gidx = jnp.clip(idx, 0, col.capacity - 1)
    return col.gather(gidx, valid_src)


# ---------------------------------------------------------------------------
# File-level API
# ---------------------------------------------------------------------------


def leaf_index_map(pf) -> dict:
    """Top-level column name -> first leaf-column index.

    Leaf PATHS are ambiguous (a column literally named "a.b" collides
    with struct a.b), so map by walking the Arrow schema and counting
    leaves per top-level field instead."""
    def n_leaves(t):
        if pa.types.is_struct(t):
            return sum(n_leaves(f.type) for f in t)
        if pa.types.is_list(t) or pa.types.is_large_list(t):
            return n_leaves(t.value_type)
        if pa.types.is_map(t):
            return n_leaves(t.key_type) + n_leaves(t.item_type)
        return 1
    out = {}
    leaf = 0
    for f in pf.schema_arrow:
        out[f.name] = leaf
        leaf += n_leaves(f.type)
    return out


def leaf_map(pf) -> dict:
    """leaf_index_map with the cached-footer fast path (FooterInfo
    memoizes its map; a plain ParquetFile recomputes)."""
    if hasattr(pf, "leaf_of"):
        return pf.leaf_of()
    return leaf_index_map(pf)


def decode_row_group(path: str, row_group: int, schema: Schema,
                     columns: Optional[List[str]] = None,
                     parquet_file: Optional[papq.ParquetFile] = None,
                     source_key: Optional[tuple] = None,
                     metrics=None,
                     backend: Optional[str] = None
                     ) -> Tuple[DeviceBatch, List[str]]:
    """Decode one row group to a DeviceBatch.

    Returns (batch, fallback_columns) — fallback columns were host-decoded
    (Arrow) because their chunks use unsupported encodings/types.

    ``path`` may also be an in-memory parquet blob (bytes) — the cached
    -batch decode path (ParquetCachedBatchSerializer analog).

    ``source_key`` (io/scan_cache.source_key) enables the scan-plan
    cache for the flat-column page walks; pass None to force fresh
    walks.  ``parquet_file`` may be a real ParquetFile or a cached
    ``scan_cache.FooterInfo`` (only ``.metadata``/``.schema_arrow``/
    ``.read_row_group`` are used)."""
    from spark_rapids_tpu.io import scan_cache as sc
    if parquet_file is None and isinstance(path,
                                           (bytes, bytearray, memoryview)):
        parquet_file = sc.blob_footer(path)
    pf = parquet_file or papq.ParquetFile(path)
    md = pf.metadata
    leaf_of = leaf_map(pf)
    wanted = columns or [f.name for f in schema.fields]
    n_rows = md.row_group(row_group).num_rows
    cap = bucket_rows(max(n_rows, 1))

    cols: List[DeviceColumn] = []
    out_names: List[str] = []
    fallbacks: List[str] = []
    fb_pf = None    # one transient open shared by all fallback columns

    def _fb_reader():
        nonlocal fb_pf
        if fb_pf is None:
            fb_pf = papq.ParquetFile(path) \
                if isinstance(pf, sc.FooterInfo) else pf
        return fb_pf

    for name in wanted:
        f = schema.field(name)
        if name not in leaf_of:
            # partition or missing column: all-null
            if f.dtype.is_string:
                data = jnp.zeros((cap, 1), dtype=jnp.uint8)
                col = DeviceColumn(f.dtype, data,
                                   jnp.zeros((cap,), dtype=bool),
                                   jnp.zeros((cap,), dtype=jnp.int32))
            elif f.dtype.is_list:
                col = DeviceColumn(
                    f.dtype,
                    jnp.zeros((cap, 1), dtype=f.dtype.element.to_np()),
                    jnp.zeros((cap,), dtype=bool),
                    jnp.zeros((cap,), dtype=jnp.int32),
                    jnp.zeros((cap, 1), dtype=jnp.bool_))
            else:
                col = DeviceColumn(f.dtype,
                                   jnp.zeros((cap,), dtype=f.dtype.to_np()),
                                   jnp.zeros((cap,), dtype=bool))
            cols.append(col)
            out_names.append(name)
            continue
        ci = leaf_of[name]
        try:
            if f.dtype.is_list:
                # nested chunks aren't plan-cacheable (ChunkPlan covers
                # flat columns only): walk fresh
                chunk = pm.read_chunk_pages(path, row_group, ci,
                                            parquet_file=pf)
                col = decode_list_chunk(chunk, f.dtype, cap,
                                        f.nullable)
            else:
                plan = sc.get_chunk_plan(source_key, path, row_group,
                                         ci, f.dtype, True, pf,
                                         metrics=metrics)
                col = decode_plan(plan, cap, backend=backend)
        except Exception:
            # UnsupportedChunk or any malformed-page surprise: this column
            # decodes on host; the rest of the batch stays on device
            fallbacks.append(name)
            t = _fb_reader().read_row_group(row_group, columns=[name])
            sub = from_arrow(_cast_one(t, f), capacity=cap)
            col = sub.columns[0]
        cols.append(col)
        out_names.append(name)
    if fb_pf is not None and fb_pf is not pf:
        fb_pf.close()
    return DeviceBatch(out_names, cols, n_rows), fallbacks


def _cast_one(t: pa.Table, f) -> pa.Table:
    col = t.column(0).cast(f.dtype.to_arrow())
    return pa.Table.from_arrays(
        [col], schema=pa.schema([pa.field(f.name, f.dtype.to_arrow(),
                                          f.nullable)]))


# ---------------------------------------------------------------------------
# Nested (list) decode: max_rep == 1 (reference: GpuParquetScan.scala:1022
# handles nested via libcudf; here rep/def level STRUCTURE decodes with
# vectorized host numpy in O(levels) while element VALUES decode on
# device, then one scatter places elements into the [cap, L] list matrix)
# ---------------------------------------------------------------------------

def _expand_levels_host(runs: RunTable, packed: bytes) -> np.ndarray:
    """Hybrid runs -> numpy int32 levels (np.repeat / unpackbits per
    run — O(runs) Python, O(levels) vectorized C)."""
    parts = []
    pk = np.frombuffer(packed, np.uint8)
    for i in range(len(runs.counts)):
        c = runs.counts[i]
        if c <= 0:
            continue
        if runs.is_rle[i]:
            parts.append(np.full(c, runs.values[i], np.int32))
        else:
            w = runs.widths[i]
            base = runs.bit_bases[i]
            nbits = c * w
            b0 = base // 8
            off = base % 8
            nb = (off + nbits + 7) // 8
            bits = np.unpackbits(pk[b0:b0 + nb], bitorder="little")
            bits = bits[off:off + nbits].reshape(c, w)
            parts.append(
                (bits.astype(np.int32) *
                 (1 << np.arange(w, dtype=np.int32))).sum(axis=1))
    return np.concatenate(parts) if parts else np.zeros(0, np.int32)


def decode_list_chunk(chunk: pm.ChunkPages, out_dtype: dt.DType,
                      cap: int, outer_nullable: bool) -> DeviceColumn:
    """Decode a list<primitive> column chunk (max_rep == 1)."""
    if chunk.max_rep != 1:
        raise UnsupportedChunk("max_rep > 1")
    if not out_dtype.is_list or out_dtype.element is None or \
            out_dtype.element.is_string or out_dtype.element.is_nested:
        raise UnsupportedChunk("list element type")
    ptype = chunk.physical_type
    if ptype not in _PLAIN_NP and ptype != "BOOLEAN":
        raise UnsupportedChunk(f"list physical type {ptype}")
    max_def = chunk.max_def
    elem_nullable = (max_def - (1 if outer_nullable else 0)) == 2
    null_row_def = 0 if outer_nullable else -1
    slot_def = max_def - (1 if elem_nullable else 0)

    def_w = max(max_def.bit_length(), 1)
    rep_w = 1

    dict_np = None
    if chunk.dict_page is not None:
        dp = chunk.dict_page
        payload = pm.decompress(
            chunk.codec,
            chunk.data[dp.payload_off:dp.payload_off +
                       dp.compressed_size], dp.uncompressed_size)
        dict_np = np.frombuffer(payload, dtype=_PLAIN_NP[ptype],
                                count=dp.num_values).copy()
        if dict_np.shape[0] == 0:
            dict_np = np.zeros((1,), dtype=_PLAIN_NP[ptype])

    reps, defs = [], []
    idx_runs = RunTable.empty()
    idx_packed = bytearray()
    plain_parts: List[bytes] = []
    idx_target = 0
    any_dict = any_plain = False
    for page in chunk.data_pages:
        raw = chunk.data[page.payload_off:
                         page.payload_off + page.compressed_size]
        if page.page_type == pm.DATA_PAGE_V2:
            lvl = page.v2_rep_bytes + page.v2_def_bytes
            rep_buf = raw[:page.v2_rep_bytes]
            def_buf = raw[page.v2_rep_bytes:lvl]
            rep_s, rep_e = 0, len(rep_buf)
            def_s, def_e = 0, len(def_buf)
            if page.v2_is_compressed:
                vals_buf = pm.decompress(chunk.codec, raw[lvl:],
                                         page.uncompressed_size - lvl)
            else:
                vals_buf = raw[lvl:]
        else:
            payload = pm.decompress(chunk.codec, raw,
                                    page.uncompressed_size)
            rlen = struct.unpack_from("<I", payload, 0)[0]
            rep_buf = payload
            rep_s, rep_e = 4, 4 + rlen
            dlen = struct.unpack_from("<I", payload, rep_e)[0]
            def_buf = payload
            def_s, def_e = rep_e + 4, rep_e + 4 + dlen
            vals_buf = payload[def_e:]
        rt = RunTable.empty()
        rpk = bytearray()
        walk_hybrid(rep_buf, rep_s, rep_e, rep_w, rpk, rt)
        rt.trim_to(page.num_values)
        reps.append(_expand_levels_host(rt, bytes(rpk)))
        dtab = RunTable.empty()
        dpk = bytearray()
        walk_hybrid(def_buf, def_s, def_e, def_w, dpk, dtab)
        dtab.trim_to(page.num_values)
        page_defs = _expand_levels_host(dtab, bytes(dpk))
        defs.append(page_defs)
        nn = int((page_defs == max_def).sum())

        enc = page.encoding
        if enc in (pm.PLAIN_DICTIONARY, pm.RLE_DICTIONARY):
            if dict_np is None:
                raise UnsupportedChunk("dict page w/o dictionary")
            any_dict = True
            w = vals_buf[0]
            if w > _MAX_W:
                raise UnsupportedChunk(f"dict bit width {w}")
            walk_hybrid(vals_buf, 1, len(vals_buf), w, idx_packed,
                        idx_runs)
            idx_target += nn
            idx_runs.trim_to(idx_target)
        elif enc == pm.PLAIN:
            any_plain = True
            if ptype == "BOOLEAN":
                raise UnsupportedChunk("PLAIN boolean list")
            itemsize = _PLAIN_NP[ptype].itemsize
            plain_parts.append(vals_buf[:nn * itemsize])
        else:
            raise UnsupportedChunk(f"list encoding {enc}")
    if any_dict and any_plain:
        raise UnsupportedChunk("mixed dict+plain pages")

    rep = np.concatenate(reps) if reps else np.zeros(0, np.int32)
    dfl = np.concatenate(defs) if defs else np.zeros(0, np.int32)
    is_row = rep == 0
    n_rows = int(is_row.sum())
    row_id = np.cumsum(is_row) - 1
    is_slot = dfl >= slot_def
    has_val = dfl == max_def
    null_row = is_row & (dfl == null_row_def) if outer_nullable else \
        np.zeros_like(is_row)

    lengths = np.bincount(row_id[is_slot],
                          minlength=max(n_rows, 1)).astype(np.int32)
    if n_rows == 0:
        lengths = np.zeros(1, np.int32)
    from spark_rapids_tpu.columnar.batch import _bucket_strlen
    L = _bucket_strlen(int(lengths.max()) if lengths.size else 0)
    slot_rows = row_id[is_slot]
    prev = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    cols = np.arange(slot_rows.shape[0], dtype=np.int64) - \
        np.repeat(prev[:n_rows], lengths[:n_rows])
    flat_all = slot_rows.astype(np.int64) * L + cols
    flat_val = flat_all[has_val[is_slot]]

    npd = _PLAIN_NP[ptype] if ptype != "BOOLEAN" else np.dtype(bool)
    el_np = out_dtype.element.to_np()
    n_vals = int(has_val.sum())
    vcap = bucket_rows(max(n_vals, 1))
    if any_dict:
        dev = _upload_runs(idx_runs, bytes(idx_packed))
        indices = _expand_runs_packed(dev["runs_mat"], dev["packed"],
                                      cap=vcap)
        d_vals = jnp.asarray(dict_np.astype(el_np, copy=False))
        vals = jnp.take(d_vals,
                        jnp.clip(indices.astype(jnp.int32), 0,
                                 d_vals.shape[0] - 1))
    else:
        raw_v = b"".join(plain_parts)
        npvals = np.frombuffer(raw_v, dtype=npd, count=n_vals)
        vals = jnp.asarray(_pad_np(npvals.astype(el_np, copy=True),
                                   vcap))

    fcap = bucket_rows(max(flat_val.shape[0], 1))
    fidx = jnp.asarray(_pad_np(flat_val.astype(np.int64), fcap,
                               fill=cap * L))
    in_use = jnp.arange(fcap) < flat_val.shape[0]
    src = jnp.where(in_use, vals[:fcap] if vals.shape[0] >= fcap else
                    jnp.pad(vals, (0, fcap - vals.shape[0])),
                    jnp.zeros((), dtype=el_np))
    data = jnp.zeros((cap * L,), dtype=el_np).at[fidx].set(
        src, mode="drop").reshape(cap, L)

    acap = bucket_rows(max(flat_all.shape[0], 1))
    aidx = jnp.asarray(_pad_np(flat_all.astype(np.int64), acap,
                               fill=cap * L))
    ev_src = _pad_np(has_val[is_slot].astype(bool), acap)
    ev = jnp.zeros((cap * L,), dtype=jnp.bool_).at[aidx].set(
        jnp.asarray(ev_src), mode="drop").reshape(cap, L)

    validity = np.zeros(cap, dtype=bool)
    row_valid = ~null_row[is_row] if outer_nullable else \
        np.ones(n_rows, dtype=bool)
    validity[:n_rows] = row_valid
    lens_full = np.zeros(cap, dtype=np.int32)
    lens_full[:n_rows] = np.where(row_valid, lengths[:n_rows], 0)
    return DeviceColumn(out_dtype, data, jnp.asarray(validity),
                        jnp.asarray(lens_full), ev)
