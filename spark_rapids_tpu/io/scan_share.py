"""Shared-scan multicast: one device decode feeds every concurrent
subscriber of the same (file, row-group, column-set, stamp) work.

The scan-plan cache (io/scan_cache.py) already dedups the HOST half of
a warm scan — footer parses and page-header walks.  This module is the
missing device half: when N concurrent queries decode the SAME fused
scan group, exactly one of them (the *leader*) runs host prep + the
device decode, and the decoded ``DeviceBatch`` is multicast to every
*subscriber* that claimed the key while the flight was open.  A
subscriber pays zero page walks and zero decode dispatches — the
walk-count probe (io/parquet_meta.walk_count) and ``kernel.dispatches``
both prove it.

Identity is content-addressed, not connection-addressed::

    (sorted file_key stamps, (path, row-group) tuple, output schema
     signature, pushed-filter signature, partition values, backend)

``file_key`` is the scan-plan cache's (path, mtime_ns, size) stamp, so
a rewritten file can never serve another query's stale bytes — its key
simply never matches again and the old entry ages out of the window.

Lifecycle of one key::

    claim -> ("lead", e)   first claimant; runs prepare()+finish()
          -> ("join", e)   anyone else while the flight is open OR the
                           batch is still inside the retention window
    lead:  publish(e, batch)  settles the flight, enters the window
           fail(e, err)       (error/cancel/abandon) wakes subscribers
    join:  wait(e)            batch, or None when the leader failed --
                              the subscriber then decodes locally under
                              a FRESH claim (so a third query can still
                              share ITS decode)
    all:   release(e)         refcounted; the batch's HBM frees when the
                              last reference drops AND the retention
                              window has let go

The retention window is a byte-budget LRU (``scan.shared.windowBytes``)
over published batches, so a query arriving a moment after the flight
settled still shares the decode.  It registers as an auxiliary
pressure spiller (mem/spill.register_pressure_spiller): admission
pressure drops retained batches oldest-first before any query is made
to wait.  Refcounted release means a slow subscriber can never pin the
window — eviction only drops the WINDOW's pin; in-flight subscribers
keep their own reference until their stream drains.

Subscribers holding references to one batch is exactly why input-buffer
donation must not see shared scan batches: ``fused_stage.donate_ok``
bars donation for fused parquet scans whenever sharing is enabled (a
donated multicast batch would invalidate every other subscriber's
copy).  One-knob revert: ``scan.shared.enabled`` off restores the
private decode path AND scan-batch donation.

Counters (registry -> /metrics): ``scan.shared.subscribers`` (claims
that joined another query's flight or window entry),
``scan.shared.dedupedDecodes`` (joined claims actually served from the
shared batch), ``scan.shared.multicastBatches`` (published batches that
served more than one consumer).  Final release of a multicast batch
records a ``scan.multicastRelease`` event with its fan-out and size.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.sched import cancel as _cancel


class _Entry:
    """One keyed decode flight / retained batch."""

    __slots__ = ("key", "event", "batch", "error", "nbytes", "refs",
                 "joined", "served", "settled", "in_window",
                 "multicast_counted", "released")

    def __init__(self, key: Tuple):
        self.key = key
        self.event = threading.Event()
        self.batch = None
        self.error: Optional[BaseException] = None
        self.nbytes = 0
        self.refs = 1            # the leader's claim
        self.joined = 0          # subscribers beyond the leader
        self.served = 0          # joined claims actually delivered
        self.settled = False
        self.in_window = False
        self.multicast_counted = False
        self.released = False


class ScanShare:
    """Process-wide keyed single-flight + retention window (one
    instance, via :func:`get_share`)."""

    def __init__(self, window_bytes: int):
        self._lock = threading.Lock()
        self._window_bytes = int(window_bytes)
        self._inflight: dict = {}
        # key -> _Entry, LRU order (oldest first)
        self._window: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._window_total = 0

    def set_window_bytes(self, window_bytes: int) -> None:
        with self._lock:
            self._window_bytes = int(window_bytes)
            self._evict_locked()

    # -- claim / settle ----------------------------------------------------
    def claim(self, key: Tuple):
        """("lead", entry) for the first claimant of an open key,
        ("join", entry) for everyone arriving while the flight is open
        or the batch is retained.  Every claim (either role) owns one
        reference and MUST release it."""
        with self._lock:
            e = self._inflight.get(key)
            if e is None:
                e = self._window.get(key)
                if e is not None:
                    self._window.move_to_end(key)
            if e is None:
                e = _Entry(key)
                self._inflight[key] = e
                return "lead", e
            e.refs += 1
            e.joined += 1
        obsreg.get_registry().inc("scan.shared.subscribers")
        return "join", e

    def publish(self, e: _Entry, batch) -> None:
        """Leader settle: the decoded batch enters the retention window
        and every waiting subscriber wakes."""
        try:
            # DeviceBatch exposes nbytes(); pa.Table exposes the
            # property — the host-scan sharing path publishes Tables
            nb = batch.nbytes
            nb = int(nb() if callable(nb) else nb)
        except Exception:
            nb = 1 << 20
        with self._lock:
            e.batch = batch
            e.nbytes = nb
            e.settled = True
            if self._inflight.get(e.key) is e:
                del self._inflight[e.key]
            self._window[e.key] = e
            e.in_window = True
            self._window_total += nb
            self._evict_locked()
        e.event.set()

    def fail(self, e: _Entry, error: BaseException) -> None:
        """Leader settle on error/cancel/abandonment: subscribers wake
        and fall back to a local decode (no error propagation — the
        leader's cancellation is not the follower's failure)."""
        with self._lock:
            if e.settled:
                return
            e.error = error
            e.settled = True
            if self._inflight.get(e.key) is e:
                del self._inflight[e.key]
        e.event.set()

    # -- subscriber side ---------------------------------------------------
    def wait(self, e: _Entry):
        """Block (cancellably) until the flight settles.  Returns the
        shared batch, or None when the leader failed — the caller then
        decodes locally.  Never call while holding the TPU semaphore:
        the leader's decode needs a slot."""
        while not e.event.wait(0.05):
            _cancel.check_current()
        if e.batch is None:
            return None
        reg = obsreg.get_registry()
        reg.inc("scan.shared.dedupedDecodes")
        with self._lock:
            e.served += 1
            first_fanout = not e.multicast_counted
            e.multicast_counted = True
        if first_fanout:
            reg.inc("scan.shared.multicastBatches")
        return e.batch

    def release(self, e: _Entry) -> None:
        """Drop one claim's reference; the batch's memory frees once
        the last reference is gone and the window evicted the entry."""
        with self._lock:
            e.refs -= 1
            self._maybe_release_locked(e)

    def try_steal(self, e: _Entry) -> bool:
        """Withdraw a published batch from sharing so its ONLY holder
        may donate its buffers (the refcount-aware donation bar:
        exec/fused_stage dispatch calls this per batch at dispatch
        time).  Succeeds only when no other query ever received the
        batch (``joined == 0`` — a subscriber's pipeline may hold the
        object long after its claim released) and no claim is live
        (``refs == 0``): the entry leaves the window and the key
        re-opens, so a later claimant simply leads a fresh decode.
        False means the batch is (or was) multicast and must never be
        donated."""
        with self._lock:
            if e.joined > 0 or e.refs > 0 or e.released \
                    or not e.settled:
                return False
            if e.in_window:
                self._window.pop(e.key, None)
                self._window_total -= e.nbytes
                e.in_window = False
            # mark released WITHOUT dropping e.batch: the caller owns
            # the only reference and is about to consume it
            e.released = True
        obsreg.get_registry().inc("scan.shared.donationSteals")
        return True

    # -- retention window --------------------------------------------------
    def _evict_locked(self) -> None:
        while self._window_total > self._window_bytes and self._window:
            _key, e = self._window.popitem(last=False)
            self._window_total -= e.nbytes
            e.in_window = False
            self._maybe_release_locked(e)

    def _maybe_release_locked(self, e: _Entry) -> None:
        if e.refs > 0 or e.in_window or e.released:
            return
        e.released = True
        if e.batch is not None:
            nb, fanout = e.nbytes, e.served
            e.batch = None   # frees the decoded columns' HBM now
            obsrec.record_event("scan.multicastRelease",
                                subscribers=fanout, nbytes=nb)

    def pressure_spill(self, bytes_needed: int) -> int:
        """Admission-pressure hook (mem/spill): drop retained batches
        oldest-first.  In-flight subscribers keep their own references;
        only the window's pin releases here."""
        freed = 0
        with self._lock:
            for key in list(self._window.keys()):
                if freed >= bytes_needed:
                    break
                e = self._window[key]
                if e.refs > 0:
                    # live subscribers hold the batch: dropping the
                    # window's pin would free nothing, only lose the
                    # share point
                    continue
                del self._window[key]
                self._window_total -= e.nbytes
                e.in_window = False
                freed += e.nbytes
                self._maybe_release_locked(e)
        return freed

    def stats(self) -> dict:
        with self._lock:
            return {"inflight": len(self._inflight),
                    "window_entries": len(self._window),
                    "window_bytes": self._window_total}

    def clear(self) -> None:
        """Test hook: drop every retained batch (open flights keep
        settling through their leaders)."""
        with self._lock:
            while self._window:
                _key, e = self._window.popitem(last=False)
                self._window_total -= e.nbytes
                e.in_window = False
                self._maybe_release_locked(e)


_SHARE_LOCK = threading.Lock()
_SHARE: Optional[ScanShare] = None


def get_share(window_bytes: int) -> ScanShare:
    """The process-wide ScanShare, created on first use and registered
    as a pressure spiller; the byte budget follows the latest caller's
    conf (the scan_cache.configure last-caller-wins idiom)."""
    global _SHARE
    with _SHARE_LOCK:
        if _SHARE is None:
            _SHARE = ScanShare(window_bytes)
            from spark_rapids_tpu.mem import spill
            spill.register_pressure_spiller(_SHARE)
        else:
            _SHARE.set_window_bytes(window_bytes)
        return _SHARE


def peek_share() -> Optional[ScanShare]:
    """The singleton if one exists (tests / inspection), else None."""
    return _SHARE


def share_key(path_rgs, pv, schema_sig, pushed_sig,
              backend: str) -> Optional[Tuple]:
    """Content identity of one fused scan group, or None when any
    source can't be stamped (unstampable work is never shared)."""
    from spark_rapids_tpu.io import scan_cache as sc
    stamps = []
    for p in sorted({p for p, _rg in path_rgs}):
        k = sc.file_key(p)
        if k is None:
            return None
        stamps.append(k)
    return (tuple(stamps), tuple(path_rgs), tuple(schema_sig),
            pushed_sig, tuple(sorted(pv.items())), str(backend))
