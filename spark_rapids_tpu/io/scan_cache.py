"""Process-wide scan-plan cache: memoized host-prep artifacts.

The CPU half of the device parquet scan — footer parse, Thrift
page-header walks, RLE run-boundary tables (``ChunkPlan``) — is pure
O(pages+runs) host work that the engine redoes from scratch on every
``collect()``.  On the bench chip that host prep dominates the engine
end-to-end wall (BENCH_r05: 3.98 s host prep vs 149 ms device
pipeline).  This cache is the host-side sibling of
``exec/kernel_cache.py`` and the analog of the reference's footer
cache (reference: GpuParquetScan caches parsed footers per file so the
multi-file reader clips row groups without re-reading the tail):

  * entries key on ``(path, mtime_ns, size)`` for files — any rewrite
    of the file changes the stamp and invalidates every cached
    artifact for it — or on a content digest for in-memory parquet
    blobs (the ``df.cache()`` decode path);
  * per file the cache holds the parsed footer (``FooterInfo``) and
    every ``ChunkPlan`` walked so far, keyed by
    ``(row_group, leaf_index, out_dtype, allow_mixed)``;
  * unsupported chunks cache NEGATIVELY (the ``UnsupportedChunk`` is
    replayed) so a warm scan doesn't re-walk pages only to fall back
    to host Arrow again;
  * eviction is LRU at file granularity under a byte budget
    (``spark.rapids.tpu.sql.scan.metadataCache.maxBytes``) — run
    tables and packed buffers are the dominant cost and are accounted
    per plan.

Lookups stat the file every time (µs against ms-scale walks), so an
overwritten file is never served stale plans.  All entry points are
thread-safe: concurrent partition iterators and the host-prep thread
pool hit the cache simultaneously.  Plan computation runs OUTSIDE the
lock — two threads may race to walk the same chunk (both count as
misses; last insert wins), which is benign because plans are treated
as immutable after construction.
"""

from __future__ import annotations

import hashlib
import io as _io
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import pyarrow.parquet as papq

from spark_rapids_tpu.obs import registry as _obsreg

_LOCK = threading.RLock()
_ENABLED = True
_MAX_BYTES = 256 << 20

# skey -> _FileEntry, LRU order (oldest first)
_FILES: "OrderedDict[Tuple, _FileEntry]" = OrderedDict()
# abspath -> last skey (so a rewritten file's stale entry purges
# immediately instead of lingering until eviction)
_PATH_KEY: Dict[str, Tuple] = {}
_TOTAL_BYTES = 0

_HITS = 0
_MISSES = 0
_EVICTIONS = 0
_INVALIDATIONS = 0


class FooterInfo:
    """Cached parquet footer: standalone metadata + Arrow schema.

    Duck-types the slice of ``pyarrow.parquet.ParquetFile`` the scan
    paths use (``.metadata``, ``.schema_arrow``, ``.read_row_group``,
    ``.close``) WITHOUT holding an open file descriptor — a scan over
    thousands of files must not pin thousands of fds."""

    __slots__ = ("path", "metadata", "schema_arrow", "cache_key",
                 "_leaf_of")

    def __init__(self, path: str, metadata, schema_arrow,
                 cache_key: Optional[Tuple] = None):
        self.path = path
        self.metadata = metadata
        self.schema_arrow = schema_arrow
        # the (path, mtime, size) stamp this footer was parsed under —
        # chunk plans derived THROUGH this footer must key on it (a
        # re-stat at plan time could pick up a newer stamp and cache
        # plans built from stale byte offsets under the new key)
        self.cache_key = cache_key
        self._leaf_of: Optional[dict] = None

    @property
    def num_row_groups(self) -> int:
        return self.metadata.num_row_groups

    def leaf_of(self) -> dict:
        if self._leaf_of is None:
            from spark_rapids_tpu.io.device_parquet import leaf_index_map
            self._leaf_of = leaf_index_map(self)
        return self._leaf_of

    def read_row_group(self, rg: int, columns=None):
        """Host Arrow read for fallback columns (transient open)."""
        pf = papq.ParquetFile(self.path)
        try:
            return pf.read_row_group(rg, columns=columns)
        finally:
            pf.close()

    def close(self) -> None:  # ParquetFile-compatible no-op
        pass

    def nbytes(self) -> int:
        try:
            return int(self.metadata.serialized_size) + 4096
        except Exception:
            return 1 << 16


class _FileEntry:
    __slots__ = ("footer", "plans", "nbytes")

    def __init__(self):
        self.footer: Optional[FooterInfo] = None
        # (rg, leaf_idx, dtype_name, allow_mixed) -> ChunkPlan | Exception
        self.plans: Dict[Tuple, Any] = {}
        self.nbytes = 0


# ---------------------------------------------------------------------------
# Configuration / stats
# ---------------------------------------------------------------------------

def configure(enabled: bool, max_bytes: int) -> None:
    """Session bootstrap hook (api/session.py)."""
    global _ENABLED, _MAX_BYTES
    with _LOCK:
        _ENABLED = bool(enabled)
        _MAX_BYTES = int(max_bytes)
        if not _ENABLED:
            _clear_locked()
        else:
            _evict_locked()


def enabled() -> bool:
    return _ENABLED


def stats() -> Dict[str, int]:
    with _LOCK:
        return {"hits": _HITS, "misses": _MISSES,
                "evictions": _EVICTIONS,
                "invalidations": _INVALIDATIONS,
                "entries": len(_FILES), "bytes": _TOTAL_BYTES}


def clear() -> None:
    with _LOCK:
        _clear_locked()


def _clear_locked() -> None:
    global _TOTAL_BYTES
    _FILES.clear()
    _PATH_KEY.clear()
    _TOTAL_BYTES = 0


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------

def file_key(path: str) -> Optional[Tuple]:
    """Cache key of an on-disk file: (abspath, mtime_ns, size) — the
    spark-rapids footer-cache invalidation contract.  None when the
    path can't be stat'ed (the caller skips caching)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return ("file", os.path.abspath(path), st.st_mtime_ns, st.st_size)


def blob_key(blob) -> Optional[Tuple]:
    """Cache key of an in-memory parquet blob (df.cache() path):
    content digest, so a re-materialized relation with identical bytes
    still hits and freed-and-reused ids can never alias."""
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        return None
    return ("blob", hashlib.sha1(blob).hexdigest(), len(blob))


def source_key(src) -> Optional[Tuple]:
    """file_key for paths, blob_key for byte blobs."""
    if isinstance(src, str):
        return file_key(src)
    return blob_key(src)


def source_stamps(paths) -> Optional[Tuple[Tuple, ...]]:
    """Current content stamps for a set of scan sources: the sorted
    tuple of ``file_key`` stamps — the same (path, mtime_ns, size)
    invalidation contract the scan-plan cache keys on, exposed so the
    serving tier's result-set cache can key whole query results on it
    (serve/result_cache.py).  None when any path can't be stat'ed: a
    result derived from an unstampable source must not be cached."""
    out = []
    for p in paths:
        k = file_key(p)
        if k is None:
            return None
        out.append(k)
    return tuple(sorted(out))


class StampDelta:
    """Classification of an (old, new) source-stamp-set pair — the
    incremental result-maintenance admissibility verdict
    (exec/incremental.py).  ``kind`` is one of:

      * ``unchanged`` — identical stamp sets;
      * ``append``    — every old file's (path, mtime_ns, size) stamp
        holds verbatim and >= 1 new path appeared: the ONLY drift shape
        whose delta can be recomputed from the new files alone;
      * ``rewrite``   — some old path's stamp moved (size grew, shrank,
        or an mtime-only touch: content equality is unknowable from the
        stamp, so a touch classifies conservatively as a rewrite);
      * ``shrink``    — some old path vanished from the new set (file
        deleted or renamed away);
      * ``mixed``     — both rewrites/shrinks AND appends at once.

    Per-file attribution rides along so fallback counters and the
    /resultcache inspection can say WHICH file broke incrementality."""

    __slots__ = ("kind", "appended", "rewritten", "deleted")

    def __init__(self, kind: str, appended, rewritten, deleted):
        self.kind = kind
        self.appended = tuple(appended)
        self.rewritten = tuple(rewritten)
        self.deleted = tuple(deleted)

    def __repr__(self) -> str:
        return (f"StampDelta({self.kind}, +{len(self.appended)} "
                f"~{len(self.rewritten)} -{len(self.deleted)})")


def classify_stamp_delta(old_stamps, new_stamps) -> StampDelta:
    """Classify drift between two ``source_stamps`` tuples (see
    :class:`StampDelta`).  Both arguments are iterables of
    ("file", abspath, mtime_ns, size) stamps; paths, not live files,
    are compared — a deleted file shows up as a missing path here, it
    never re-raises the ``os.stat`` failure (the caller obtained the
    new stamps through :func:`source_stamps`, whose contract is None on
    any unstatable path)."""
    old_by_path = {s[1]: s for s in old_stamps}
    new_by_path = {s[1]: s for s in new_stamps}
    appended = sorted(p for p in new_by_path if p not in old_by_path)
    deleted = sorted(p for p in old_by_path if p not in new_by_path)
    rewritten = sorted(p for p, s in old_by_path.items()
                       if p in new_by_path and new_by_path[p] != s)
    if not appended and not deleted and not rewritten:
        return StampDelta("unchanged", (), (), ())
    if appended and not deleted and not rewritten:
        return StampDelta("append", appended, (), ())
    if appended:
        return StampDelta("mixed", appended, rewritten, deleted)
    if deleted:
        return StampDelta("shrink", (), rewritten, deleted)
    return StampDelta("rewrite", (), rewritten, ())


def handle_key(pf, src) -> Optional[Tuple]:
    """Plan-cache key for chunks walked through the open handle ``pf``:
    the stamp captured when the footer was parsed (FooterInfo), NOT a
    fresh stat — so a file rewritten mid-scan can never get plans built
    from the stale footer's offsets cached under the new file's key.
    Handles without a pinned stamp (a plain ParquetFile, an uncached
    FooterInfo) return None: their open-time stamp is unknowable, and
    caching under a fresh stat could poison a newer stamp with plans
    derived from the handle's older footer."""
    return getattr(pf, "cache_key", None)


# ---------------------------------------------------------------------------
# Entry management
# ---------------------------------------------------------------------------

def _purge_stale_locked(skey: Tuple) -> None:
    """Drop a previous-stamp entry for the same path (file rewritten).

    Only a FRESHER stamp may purge/repoint: a scan still pinned to an
    older footer (handle_key) must not evict the rewritten file's new
    entry — old- and new-stamp entries coexist until the old one ages
    out of the LRU."""
    global _TOTAL_BYTES, _INVALIDATIONS
    if skey[0] != "file":
        return
    prev = _PATH_KEY.get(skey[1])
    if prev is None or prev == skey:
        _PATH_KEY[skey[1]] = skey
        return
    if skey[2] < prev[2]:     # incoming mtime_ns older than recorded
        return
    entry = _FILES.pop(prev, None)
    if entry is not None:
        _TOTAL_BYTES -= entry.nbytes
        _INVALIDATIONS += 1
    _PATH_KEY[skey[1]] = skey


def _probe_locked(skey: Tuple) -> Optional["_FileEntry"]:
    """Lookup WITHOUT creating: a miss that then fails to parse/walk
    must leave no empty entry behind (they would accumulate for every
    corrupt/vanished file stamp)."""
    _purge_stale_locked(skey)
    entry = _FILES.get(skey)
    if entry is not None:
        _FILES.move_to_end(skey)
    return entry


def _entry_locked(skey: Tuple) -> "_FileEntry":
    entry = _probe_locked(skey)
    if entry is None:
        entry = _FileEntry()
        _FILES[skey] = entry
    return entry


def _evict_locked() -> None:
    global _TOTAL_BYTES, _EVICTIONS
    while _TOTAL_BYTES > _MAX_BYTES and len(_FILES) > 1:
        old_key, old = _FILES.popitem(last=False)
        _TOTAL_BYTES -= old.nbytes
        _EVICTIONS += 1
        if old_key[0] == "file" and _PATH_KEY.get(old_key[1]) == old_key:
            del _PATH_KEY[old_key[1]]


def _account_locked(entry: "_FileEntry", delta: int) -> None:
    global _TOTAL_BYTES
    entry.nbytes += delta
    _TOTAL_BYTES += delta
    _evict_locked()


def _plan_nbytes(plan) -> int:
    """Byte cost of one cached ChunkPlan (packed streams + value
    buffers dominate; run-table python lists cost ~40 B/run)."""
    if isinstance(plan, Exception):
        return 256
    n = 512
    for b in (plan.def_packed, plan.val_packed):
        n += len(b or b"")
    for a in (plan.plain_np, plan.dict_np, plan.dict_lens):
        if a is not None:
            n += int(a.nbytes)
    for rt in (plan.def_runs, plan.val_runs):
        if rt is not None:
            n += 40 * len(rt.counts)
    return n


# ---------------------------------------------------------------------------
# Public lookups
# ---------------------------------------------------------------------------

def _count(metrics, key: str) -> None:
    if metrics is not None:
        metrics.add_extra(key, 1)


def get_footer(path: str, metrics=None) -> FooterInfo:
    """Parsed footer for ``path``, cached on (path, mtime, size).

    Falls through to a direct parse (uncached) when the cache is off
    or the file can't be stat'ed."""
    skey = file_key(path) if _ENABLED else None
    if skey is not None:
        with _LOCK:
            entry = _probe_locked(skey)
            if entry is not None and entry.footer is not None:
                _bump_hits(metrics)
                return entry.footer
    md = papq.read_metadata(path)
    footer = FooterInfo(path, md, md.schema.to_arrow_schema(),
                        cache_key=skey)
    if skey is not None:
        _bump_misses(metrics)
        with _LOCK:
            entry = _entry_locked(skey)
            if entry.footer is None:
                entry.footer = footer
                _account_locked(entry, footer.nbytes())
            else:
                footer = entry.footer
    return footer


def get_chunk_plan(skey: Optional[Tuple], src, rg: int, leaf_idx: int,
                   out_dtype, allow_mixed: bool, pf, metrics=None):
    """ChunkPlan for one (source, row_group, leaf column), cached.

    ``src`` is a path or parquet blob; ``pf`` anything exposing
    ``.metadata`` (a ParquetFile or FooterInfo).  Re-raises a cached
    ``UnsupportedChunk`` without re-walking pages.  With the cache off
    or ``skey`` None the walk runs uncached."""
    from spark_rapids_tpu.io import parquet_meta as pm
    from spark_rapids_tpu.io.device_parquet import (UnsupportedChunk,
                                                    plan_chunk)

    pkey = (rg, leaf_idx, out_dtype.name, bool(allow_mixed))
    use_cache = _ENABLED and skey is not None
    if use_cache:
        with _LOCK:
            entry = _probe_locked(skey)
            cached = entry.plans.get(pkey) if entry is not None else None
        if cached is not None:
            _bump_hits(metrics)
            if isinstance(cached, Exception):
                # fresh instance per raise: the cached one is shared
                raise type(cached)(*cached.args)
            return cached
        _bump_misses(metrics)
    try:
        chunk = pm.read_chunk_pages(src, rg, leaf_idx, parquet_file=pf)
        plan = plan_chunk(chunk, out_dtype, allow_mixed=allow_mixed)
    except UnsupportedChunk as e:
        # negative-cache ONLY the deterministic verdict, stripped of
        # its traceback (frames pin the whole compressed chunk bytes,
        # and concurrent re-raises would race on __traceback__);
        # transient IO/parse errors must stay uncached and retryable
        if use_cache:
            neg = UnsupportedChunk(*e.args)
            with _LOCK:
                entry = _entry_locked(skey)
                if pkey not in entry.plans:
                    entry.plans[pkey] = neg
                    _account_locked(entry, _plan_nbytes(neg))
        raise
    if use_cache:
        with _LOCK:
            entry = _entry_locked(skey)
            if pkey not in entry.plans:
                entry.plans[pkey] = plan
                _account_locked(entry, _plan_nbytes(plan))
            else:
                got = entry.plans[pkey]
                if not isinstance(got, Exception):
                    plan = got
    return plan


def _bump_hits(metrics) -> None:
    global _HITS
    with _LOCK:
        _HITS += 1
    _count(metrics, "scan.planCacheHits")
    # mirrored into the unified metrics registry: the scan-cache
    # counters were one of the three disjoint stat channels the obs
    # layer folds together (obs/registry.py)
    _obsreg.get_registry().inc("scan.planCacheHits")


def _bump_misses(metrics) -> None:
    global _MISSES
    with _LOCK:
        _MISSES += 1
    _count(metrics, "scan.planCacheMisses")
    _obsreg.get_registry().inc("scan.planCacheMisses")


def open_source(path: str, metrics=None):
    """Footer-backed handle for a scan source: the cached FooterInfo
    when the cache is on, else a real ParquetFile (caller must close)."""
    if _ENABLED and file_key(path) is not None:
        return get_footer(path, metrics=metrics)
    return papq.ParquetFile(path)


def blob_footer(blob) -> papq.ParquetFile:
    """ParquetFile over an in-memory blob (footers for blobs are cheap
    enough to re-parse; the expensive page walks cache via blob_key)."""
    return papq.ParquetFile(_io.BytesIO(blob))
