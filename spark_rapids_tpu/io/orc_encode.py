"""Device-side ORC write encode.

Reference analog: ``GpuOrcFileFormat`` encodes batches on device via
``Table.writeORCChunked`` (reference: GpuOrcFileFormat.scala:103,
docs/FAQ.md:69-75 "GPU can encode Parquet and ORC much faster than the
CPU").  Same TPU-first split as the parquet encoder
(io/parquet_encode.py): the O(rows) data movement — per-column null
compaction — runs on device as one cached kernel and the result crosses
the wire in the engine's single packed download; the byte-twiddling the
TPU does badly (RLEv1 varints, protobuf metadata) runs in vectorized
numpy on host.

Output is a standard ORC file (version 0.12, compression NONE,
rowIndexStride=0 so no row-index streams are required): one stripe per
batch, DIRECT column encodings, PRESENT byte-RLE bitmaps, RLEv1 integer
streams — readable by any ORC reader (pyarrow round-trip tested).

Coverage: BOOLEAN/INT/LONG/FLOAT/DOUBLE/STRING/DATE.  Timestamps,
lists and structs fall back to the host Arrow writer (io/writers.py).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, _dispatch_pack,
                                             _download_batch)
from spark_rapids_tpu.io.parquet_encode import _compact_for_encode

# orc_proto.proto enums
_KIND = {"boolean": 0, "byte": 1, "short": 2, "int": 3, "long": 4,
         "float": 5, "double": 6, "string": 7, "date": 15,
         "struct": 12}
_STREAM_PRESENT = 0
_STREAM_DATA = 1
_STREAM_LENGTH = 2
_ENC_DIRECT = 0
_COMP_NONE = 0


def _orc_kind(d: dt.DType) -> str:
    if d.is_string:
        return "string"
    if d.is_bool:
        return "boolean"
    if d.id == dt.TypeId.DATE32:
        return "date"
    if d.id == dt.TypeId.TIMESTAMP_US:
        # ORC timestamps are (seconds-from-2015, nanos) stream pairs —
        # host Arrow writer handles them
        raise ValueError("timestamp: host fallback")
    npd = np.dtype(d.to_np())
    return {np.dtype("int32"): "int", np.dtype("int64"): "long",
            np.dtype("float32"): "float",
            np.dtype("float64"): "double"}[npd]


def supported(schema_fields) -> bool:
    try:
        for f in schema_fields:
            _orc_kind(f.dtype)
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Protobuf writer (wire format: varint tags, length-delimited messages)
# ---------------------------------------------------------------------------

class _PB:
    def __init__(self):
        self.out = bytearray()

    def varint(self, v: int) -> "_PB":
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return self

    def field_varint(self, fid: int, v: int) -> "_PB":
        self.varint((fid << 3) | 0)
        self.varint(v)
        return self

    def field_bytes(self, fid: int, b: bytes) -> "_PB":
        self.varint((fid << 3) | 2)
        self.varint(len(b))
        self.out += b
        return self

    def field_msg(self, fid: int, msg: "_PB") -> "_PB":
        return self.field_bytes(fid, bytes(msg.out))

    def field_packed_u32(self, fid: int, vals: Sequence[int]) -> "_PB":
        body = _PB()
        for v in vals:
            body.varint(v)
        return self.field_bytes(fid, bytes(body.out))


# ---------------------------------------------------------------------------
# ORC stream encoders (vectorized numpy)
# ---------------------------------------------------------------------------

def _byte_rle_literal(data: bytes) -> bytes:
    """Byte-RLE, literal runs only: header (256 - n) then n raw bytes,
    n <= 128.  Used for PRESENT bitmaps and boolean DATA."""
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        take = min(128, n - pos)
        out.append(256 - take)
        out += data[pos:pos + take]
        pos += take
    return bytes(out)


def _zigzag64(v: np.ndarray) -> np.ndarray:
    x = v.astype(np.int64)
    return ((x << 1) ^ (x >> 63)).astype(np.uint64)


def _varints(vals: np.ndarray) -> bytes:
    """Vectorized base-128 varint encoding of uint64 values."""
    if vals.size == 0:
        return b""
    v = vals.astype(np.uint64)
    # bytes needed per value: ceil(bit_length / 7), min 1
    bl = np.zeros(v.shape, dtype=np.int64)
    tmp = v.copy()
    for shift in (32, 16, 8, 4, 2, 1):
        big = tmp >= (np.uint64(1) << np.uint64(shift))
        bl = np.where(big, bl + shift, bl)
        tmp = np.where(big, tmp >> np.uint64(shift), tmp)
    nb = np.maximum((bl + 7) // 7, 1)
    total = int(nb.sum())
    out = np.zeros(total, dtype=np.uint8)
    starts = np.concatenate([[0], np.cumsum(nb)[:-1]])
    # up to 10 groups of 7 bits
    max_nb = int(nb.max())
    for k in range(max_nb):
        sel = nb > k
        chunk = ((v[sel] >> np.uint64(7 * k)) &
                 np.uint64(0x7F)).astype(np.uint8)
        more = (nb[sel] > k + 1)
        out[starts[sel] + k] = chunk | (more.astype(np.uint8) << 7)
    return out.tobytes()


def _rle_v1_literal(vals: np.ndarray, signed: bool) -> bytes:
    """RLEv1, literal runs only: header byte -(n) then n varints."""
    if vals.size == 0:
        return b""
    u = _zigzag64(vals) if signed else vals.astype(np.uint64)
    out = bytearray()
    pos = 0
    n = u.shape[0]
    while pos < n:
        take = min(128, n - pos)
        out.append(256 - take)
        out += _varints(u[pos:pos + take])
        pos += take
    return bytes(out)


def _present_stream(valid: np.ndarray) -> bytes:
    bits = np.packbits(valid.astype(bool))      # MSB-first per ORC spec
    return _byte_rle_literal(bits.tobytes())


# ---------------------------------------------------------------------------
# File assembly
# ---------------------------------------------------------------------------

def encode_batch(batch: DeviceBatch) -> bytes:
    """Encode one DeviceBatch into a complete one-stripe ORC file blob
    (device compaction + single packed download + host stream/protobuf
    assembly)."""
    comp = _compact_for_encode(batch)
    packed = _dispatch_pack(comp)
    n, host_cols = _download_batch(comp, packed)

    fields = [(name, c.dtype) for name, c in zip(batch.names,
                                                 batch.columns)]
    out = bytearray(b"ORC")
    stripe_start = len(out)

    streams: List[Tuple[int, int, int]] = []   # (column_id, kind, length)
    data = bytearray()
    for ci, ((name, d), (col_data, validity, lengths, _ev)) in \
            enumerate(zip(fields, host_cols)):
        col = ci + 1        # column 0 is the struct root
        valid = validity[:n].astype(bool)
        n_valid = int(valid.sum())
        has_nulls = n_valid < n
        if has_nulls:
            ps = _present_stream(valid)
            streams.append((col, _STREAM_PRESENT, len(ps)))
            data += ps
        kind = _orc_kind(d)
        if kind == "string":
            lens = lengths[:n_valid].astype(np.int64)
            mask = np.arange(col_data.shape[1])[None, :] < lens[:, None]
            ds = np.ascontiguousarray(col_data[:n_valid])[mask].tobytes()
            streams.append((col, _STREAM_DATA, len(ds)))
            data += ds
            ls = _rle_v1_literal(lens, signed=False)
            streams.append((col, _STREAM_LENGTH, len(ls)))
            data += ls
        elif kind == "boolean":
            bits = np.packbits(col_data[:n_valid].astype(bool))
            bs = _byte_rle_literal(bits.tobytes())
            streams.append((col, _STREAM_DATA, len(bs)))
            data += bs
        elif kind in ("int", "long", "date"):
            vs = _rle_v1_literal(col_data[:n_valid].astype(np.int64),
                                 signed=True)
            streams.append((col, _STREAM_DATA, len(vs)))
            data += vs
        else:   # float / double: IEEE little-endian raw
            npd = np.dtype(d.to_np()).newbyteorder("<")
            ds = np.ascontiguousarray(col_data[:n_valid]).astype(
                npd, copy=False).tobytes()
            streams.append((col, _STREAM_DATA, len(ds)))
            data += ds

    out += data

    # stripe footer
    sf = _PB()
    for col, skind, length in streams:
        s = _PB()
        s.field_varint(1, skind)
        s.field_varint(2, col)
        s.field_varint(3, length)
        sf.field_msg(1, s)
    for _ in range(len(fields) + 1):           # root + each column
        enc = _PB()
        enc.field_varint(1, _ENC_DIRECT)
        sf.field_msg(2, enc)
    sf_bytes = bytes(sf.out)
    out += sf_bytes

    data_len = len(data)
    stripe_footer_len = len(sf_bytes)

    # file footer
    ft = _PB()
    ft.field_varint(1, 3)                      # headerLength ("ORC")
    ft.field_varint(2, len(out))               # contentLength
    stripe = _PB()
    stripe.field_varint(1, stripe_start)       # offset
    stripe.field_varint(2, 0)                  # indexLength
    stripe.field_varint(3, data_len)
    stripe.field_varint(4, stripe_footer_len)
    stripe.field_varint(5, n)                  # numberOfRows
    ft.field_msg(3, stripe)
    # types: root struct + children
    root = _PB()
    root.field_varint(1, _KIND["struct"])
    root.field_packed_u32(2, list(range(1, len(fields) + 1)))
    for name, _d in fields:
        root.field_bytes(3, name.encode("utf-8"))
    ft.field_msg(4, root)
    for _name, d in fields:
        tp = _PB()
        tp.field_varint(1, _KIND[_orc_kind(d)])
        ft.field_msg(4, tp)
    ft.field_varint(6, n)                      # numberOfRows
    ft.field_varint(8, 0)                      # rowIndexStride: no index
    ft_bytes = bytes(ft.out)
    out += ft_bytes

    # postscript
    ps = _PB()
    ps.field_varint(1, len(ft_bytes))          # footerLength
    ps.field_varint(2, _COMP_NONE)
    ps.field_varint(3, 0)                      # compressionBlockSize
    ps.field_packed_u32(4, [0, 12])            # version
    ps.field_varint(5, 0)                      # metadataLength
    ps.field_varint(6, 1)                      # writerVersion
    ps.field_bytes(8000, b"ORC")               # magic
    ps_bytes = bytes(ps.out)
    out += ps_bytes
    out.append(len(ps_bytes))
    return bytes(out)
