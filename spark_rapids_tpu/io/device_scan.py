"""TPU Parquet scan exec: the file scan whose decode runs on device.

Analog of ``GpuFileSourceScanExec`` + ``Table.readParquet`` (reference:
GpuFileSourceScanExec.scala:372, GpuParquetScan.scala:1022): the reader
uploads packed page bytes and decodes in HBM (io/device_parquet.py) instead
of decoding on host and uploading decoded columns.  One plan partition per
file (PERFILE); batches are emitted per row group — downstream
TpuCoalesceBatchesExec re-sizes them to the CoalesceGoal exactly as the
reference inserts GpuCoalesceBatches after scans.

Hive partition-value columns are appended as device constant columns
(ColumnarPartitionReaderWithPartitionValues analog)."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _bucket_strlen)
from spark_rapids_tpu.exec.base import TpuExec, timed
from spark_rapids_tpu.io import device_parquet as devpq
from spark_rapids_tpu.mem.device import tpu_semaphore
from spark_rapids_tpu.plan.logical import FileScan, Schema


def _const_column(dtype: dt.DType, raw: Optional[str], cap: int,
                  n_rows: int) -> DeviceColumn:
    """Device constant column for one partition value."""
    row_valid = jnp.arange(cap) < n_rows
    if raw is None:
        if dtype.is_string:
            return DeviceColumn(dtype, jnp.zeros((cap, 1), dtype=jnp.uint8),
                                jnp.zeros((cap,), dtype=bool),
                                jnp.zeros((cap,), dtype=jnp.int32))
        return DeviceColumn(dtype,
                            jnp.zeros((cap,), dtype=dtype.to_np()),
                            jnp.zeros((cap,), dtype=bool))
    if dtype.is_string:
        b = raw.encode("utf-8")
        ml = _bucket_strlen(len(b))
        row = np.zeros((ml,), dtype=np.uint8)
        row[:len(b)] = np.frombuffer(b, dtype=np.uint8)
        data = jnp.broadcast_to(jnp.asarray(row), (cap, ml))
        lens = jnp.where(row_valid, np.int32(len(b)), 0)
        return DeviceColumn(dtype, data, row_valid, lens)
    val = np.asarray(raw, dtype=dtype.to_np()) if dtype.to_np().kind != "i" \
        else np.asarray(int(raw), dtype=dtype.to_np())
    data = jnp.where(row_valid, jnp.asarray(val),
                     jnp.zeros((), dtype=dtype.to_np()))
    return DeviceColumn(dtype, data, row_valid)


def _group_label(srcs) -> str:
    """Short source id for one coalesced scan group — the prefetcher
    stamps it into prefetch/stall span args so a trace names WHICH
    file/row-group the consumer starved on."""
    import os as _os
    if not srcs:
        return ""
    path, rg = srcs[0]
    label = f"{_os.path.basename(str(path))}#rg{rg}"
    if len(srcs) > 1:
        label += f"+{len(srcs) - 1}"
    return label


class TpuParquetScanExec(TpuExec):
    """Device-decoding parquet scan (is_tpu — yields DeviceBatch)."""

    fmt = "parquet"

    def __init__(self, scan: FileScan, conf):
        super().__init__()
        self.scan = scan
        self.conf = conf
        self.columns = scan.options.get("columns")
        self._schema = scan.schema if not self.columns else Schema(
            [scan.schema.field(c) for c in self.columns])
        self.part_fields = dict(scan.options.get("part_fields") or [])
        # cleared by the planner when the plan reads input_file_name()
        # (the reference's coalescing reader bails out the same way:
        # GpuParquetScan.scala canUseCoalesceFilesReader)
        self.allow_fused = True
        self.metrics.extra["fallbackColumns"] = 0

    @property
    def schema(self) -> Schema:
        return self._schema

    def _file_part(self, file_index: int) -> Iterator[DeviceBatch]:
        from spark_rapids_tpu.exec.context import set_input_file
        path = self.scan.paths[file_index]
        try:
            for b in self._file_part_inner(file_index):
                # set right before the yield so the consumer evaluates
                # input_file_name() against THIS batch's file even when
                # two scans are drained interleaved
                set_input_file(path)
                yield b
        finally:
            set_input_file("")

    def _file_part_inner(self, file_index: int) -> Iterator[DeviceBatch]:
        path = self.scan.paths[file_index]
        pv_list = self.scan.options.get("part_values") or []
        pv = pv_list[file_index] if file_index < len(pv_list) else {}
        wanted = [f.name for f in self._schema.fields]
        part_cols = [c for c in wanted if c in self.part_fields]
        file_cols = [c for c in wanted if c not in self.part_fields]
        file_schema = Schema([self._schema.field(c) for c in file_cols])
        fctx = self._open(path)  # one open/footer parse per file
        for rg in range(self._num_chunks(fctx)):
            with tpu_semaphore(self.metrics):
                with timed(self.metrics, "scan.decode"):
                    batch, fallbacks = self._decode_chunk(
                        fctx, rg, file_schema, file_cols)
                self.metrics.add_extra("fallbackColumns",
                                       len(fallbacks))
                cap = batch.capacity
                names = list(batch.names)
                cols = list(batch.columns)
                for c in part_cols:
                    d = self.part_fields[c]
                    names.append(c)
                    cols.append(_const_column(d, pv.get(c), cap,
                                              int(batch.num_rows)))
                # restore requested column order
                order = [names.index(c) for c in wanted]
                out = DeviceBatch([names[i] for i in order],
                                  [cols[i] for i in order],
                                  batch.num_rows)
                self.metrics.num_output_rows += int(out.num_rows)
                self.metrics.add_batches()
                yield out

    def _open(self, path: str):
        from spark_rapids_tpu.io import scan_cache as sc
        return path, sc.open_source(path, metrics=self.metrics)

    def _num_chunks(self, fctx) -> int:
        return fctx[1].metadata.num_row_groups

    def _decode_chunk(self, fctx, idx: int, file_schema: Schema,
                      file_cols):
        from spark_rapids_tpu.io import scan_cache as sc
        from spark_rapids_tpu.kernels import backend as kb
        path, pf = fctx
        return devpq.decode_row_group(
            path, idx, file_schema, columns=file_cols,
            parquet_file=pf, source_key=sc.handle_key(pf, path),
            metrics=self.metrics,
            backend=kb.resolve(getattr(self, "_kernel_backend", None)))

    def execute(self) -> List[Iterator[DeviceBatch]]:
        if (self.fmt == "parquet" and self.allow_fused and
                self.conf.get(cfg.PARQUET_FUSED_DECODE)):
            return self._execute_fused()
        from spark_rapids_tpu.io.readers import scan_file_indices
        return [self._file_part(i) for i in scan_file_indices(self.scan)]

    # -- fused coalescing reader (one XLA program per batch) ---------------
    def _fused_groups(self):
        """Greedy grouping of (file, row-group) pairs: same partition
        values, bounded by reader batchSizeRows/Bytes (the coalescing
        goal; reference: MultiFileParquetPartitionReader's
        maxReadBatchSizeRows/Bytes).

        Files open only transiently here (footer metadata) and lazily
        again inside each group's iterator — a scan over thousands of
        files must not hold thousands of descriptors for the query."""
        from spark_rapids_tpu.io import scan_cache as sc
        from spark_rapids_tpu.io.readers import scan_file_indices
        max_rows = int(self.conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS))
        max_bytes = int(self.conf.get(cfg.MAX_READER_BATCH_SIZE_BYTES))
        pv_list = self.scan.options.get("part_values") or []
        groups = []
        cur, cur_rows, cur_bytes, cur_pv = [], 0, 0, None
        # a file_subset restriction (incremental delta scans) excludes
        # files HERE, before any footer opens: a restricted scan never
        # stats, walks, or uploads a byte of an excluded file
        for fi in scan_file_indices(self.scan):
            path = self.scan.paths[fi]
            pf = sc.open_source(path, metrics=self.metrics)
            pv = pv_list[fi] if fi < len(pv_list) else {}
            pv_key = tuple(sorted(pv.items()))
            md = pf.metadata
            n_rgs = md.num_row_groups
            sizes = [(md.row_group(rg).num_rows,
                      md.row_group(rg).total_byte_size)
                     for rg in range(n_rgs)]
            pf.close()
            for rg in range(n_rgs):
                rows, nbytes = sizes[rg]
                if cur and (pv_key != cur_pv or
                            cur_rows + rows > max_rows or
                            cur_bytes + nbytes > max_bytes):
                    groups.append((cur, dict(cur_pv)))
                    cur, cur_rows, cur_bytes = [], 0, 0
                cur_pv = pv_key
                cur.append((path, rg))
                cur_rows += rows
                cur_bytes += nbytes
        if cur:
            groups.append((cur, dict(cur_pv)))
        return groups

    def _execute_fused(self) -> List[Iterator[DeviceBatch]]:
        from spark_rapids_tpu.exec.scans import ScanPrefetcher
        from spark_rapids_tpu.io import parquet_fused as pqf
        from spark_rapids_tpu.io import scan_cache as sc
        from spark_rapids_tpu.kernels import backend as kb

        wanted = [f.name for f in self._schema.fields]
        part_cols = [c for c in wanted if c in self.part_fields]
        file_cols = [c for c in wanted if c not in self.part_fields]
        file_schema = Schema([self._schema.field(c) for c in file_cols])
        host_threads = max(1, int(self.conf.get(
            cfg.SCAN_HOST_PREP_THREADS)))
        depth = max(0, int(self.conf.get(cfg.SCAN_PREFETCH_DEPTH)))
        backend = kb.resolve(getattr(self, "_kernel_backend", None))
        # kernel 2: the consumer's condition the planner pushed down
        # (plan/overrides._push_scan_filters); ordinals index `wanted`
        pushed = getattr(self, "_pushed_filter", None)
        groups = self._fused_groups()

        # shared-scan multicast (io/scan_share): concurrent queries
        # decoding the same (stamps, row-groups, columns, filter)
        # group share ONE host prep + device decode
        share = None
        share_keys: List = []
        if bool(self.conf.get(cfg.SCAN_SHARED_ENABLED)):
            from spark_rapids_tpu.exec import kernel_cache as kc
            from spark_rapids_tpu.io import scan_share
            share = scan_share.get_share(
                int(self.conf.get(cfg.SCAN_SHARED_WINDOW_BYTES)))
            schema_sig = tuple((f.name, f.dtype.name)
                               for f in self._schema.fields)
            pushed_sig = kc.expr_sig(pushed)
            share_keys = [scan_share.share_key(srcs, pv, schema_sig,
                                               pushed_sig, backend)
                          for srcs, pv in groups]

        def prepare(path_rgs):
            """Host prep + packed-page upload for one batch (NO device
            read — safe on the prefetch thread)."""
            handles = {p: sc.open_source(p, metrics=self.metrics)
                       for p in {p for p, _ in path_rgs}}
            sources = [(handles[p], p, rg) for p, rg in path_rgs]
            try:
                return pqf.prepare_fused(
                    sources, file_schema, columns=file_cols,
                    host_threads=host_threads,
                    metrics=self.metrics, backend=backend,
                    pushed_filter=pushed,
                    scan_names=wanted), handles
            except BaseException:
                for h in handles.values():
                    h.close()
                raise

        def finish(prepared, pv) -> DeviceBatch:
            """Dispatch the prepared batch (caller holds the TPU
            semaphore)."""
            prep, handles = prepared
            try:
                with timed(self.metrics, "scan.dispatch"):
                    batch, fallbacks = pqf.finish_fused(prep)
                self.metrics.add_extra("fallbackColumns",
                                       len(fallbacks))
                cap = batch.capacity
                names = list(batch.names)
                cols = list(batch.columns)
                for c in part_cols:
                    d = self.part_fields[c]
                    names.append(c)
                    cols.append(_const_column(
                        d, pv.get(c), cap, int(batch.num_rows)))
                order = [names.index(c) for c in wanted]
                out = DeviceBatch([names[i] for i in order],
                                  [cols[i] for i in order],
                                  batch.num_rows)
                self.metrics.num_output_rows += int(out.num_rows)
                self.metrics.add_batches()
                return out
            finally:
                for h in handles.values():
                    h.close()

        def _prep(idx, path_rgs):
            """Prepare with a sharing claim: markers are ("solo"/"lead"/
            "join", entry, prepared).  A joined claim skips the host
            prep (and so the page walks) entirely."""
            if share is None or share_keys[idx] is None:
                return ("solo", None, prepare(path_rgs))
            role, entry = share.claim(share_keys[idx])
            if role == "join":
                return ("join", entry, None)
            try:
                return ("lead", entry, prepare(path_rgs))
            except BaseException as e:
                share.fail(entry, e)
                share.release(entry)
                raise

        def _finish_marker(marker, pv) -> DeviceBatch:
            """Dispatch one non-join marker's decode (caller holds the
            TPU semaphore); a lead marker settles its flight."""
            kind, entry, prepared = marker
            if kind == "solo":
                return finish(prepared, pv)
            try:
                out = finish(prepared, pv)
            except BaseException as e:
                share.fail(entry, e)
                share.release(entry)
                raise
            share.publish(entry, out)
            share.release(entry)
            # host-side share stamp (tree_flatten drops it): downstream
            # donation checks the entry's live refcount at dispatch
            # time (fused_stage dispatch -> ScanShare.try_steal)
            out._scan_share_entry = entry
            return out

        def _resolve(marker, idx, path_rgs, pv) -> DeviceBatch:
            """Marker -> decoded batch.  Takes the semaphore only for
            real decode work — never while waiting on another query's
            flight (the leader's decode needs a slot)."""
            while True:
                kind, entry, _prepared = marker
                if kind != "join":
                    with tpu_semaphore(self.metrics):
                        return _finish_marker(marker, pv)
                try:
                    out = share.wait(entry)
                finally:
                    share.release(entry)
                if out is not None:
                    # decode skipped: account this exec's output so the
                    # query profile still shows the rows it consumed
                    self.metrics.num_output_rows += int(out.num_rows)
                    self.metrics.add_batches()
                    # a joined claim's batch is multicast by definition
                    # (entry.joined > 0 bars the donation steal)
                    out._scan_share_entry = entry
                    return out
                # the leader failed or abandoned its flight: decode
                # locally under a FRESH claim, so a later subscriber
                # can still share this decode
                marker = _prep(idx, path_rgs)

        def _cleanup(marker) -> None:
            kind, entry, prepared = marker
            if prepared is not None:
                for h in prepared[1].values():
                    h.close()
            if kind == "lead":
                share.fail(entry,
                           RuntimeError("scan flight abandoned"))
                share.release(entry)
            elif kind == "join":
                share.release(entry)

        prefetcher = None
        if depth > 0 and len(groups) > 1:
            # bounded look-ahead: host prep + upload of batch k+1
            # overlaps the dispatch-only decode of batch k
            prefetcher = ScanPrefetcher(
                [(lambda i=i, prgs=srcs: _prep(i, prgs))
                 for i, (srcs, _pv) in enumerate(groups)],
                depth=depth, metrics=self.metrics,
                cleanup=_cleanup,
                labels=[_group_label(srcs) for srcs, _pv in groups])

        def group_part(idx, path_rgs, pv) -> Iterator[DeviceBatch]:
            from spark_rapids_tpu.exec.context import set_input_file
            try:
                if prefetcher is not None:
                    marker = prefetcher.get(idx)
                    out = _resolve(marker, idx, path_rgs, pv)
                else:
                    # no pipelining: the whole prep+upload+dispatch runs
                    # under the semaphore, preserving the pre-prefetch
                    # concurrent-device-work bound (a joined claim waits
                    # OUTSIDE the semaphore instead)
                    out = None
                    with tpu_semaphore(self.metrics):
                        marker = _prep(idx, path_rgs)
                        if marker[0] != "join":
                            out = _finish_marker(marker, pv)
                    if out is None:
                        out = _resolve(marker, idx, path_rgs, pv)
                paths = {p for p, _ in path_rgs}
                # set right before the yield so the consumer evaluates
                # input_file_name() against THIS batch's file
                set_input_file(paths.pop() if len(paths) == 1 else "")
                yield out
            finally:
                set_input_file("")
                if prefetcher is not None:
                    # once every partition has finished (or failed),
                    # unconsumed prepared batches release immediately
                    prefetcher.part_done()

        return [group_part(i, srcs, pv)
                for i, (srcs, pv) in enumerate(groups)]

    def simple_string(self) -> str:
        return (f"{type(self).__name__}"
                f"(files={len(self.scan.paths)}, deviceDecode)")


class TpuOrcScanExec(TpuParquetScanExec):
    """Device-decoding ORC scan: stripe streams expand in HBM
    (GpuOrcScan analog, reference: GpuOrcScan.scala:206+).  One batch
    per stripe; shares the partition-column and fallback machinery."""

    fmt = "orc"

    def _open(self, path: str):
        from spark_rapids_tpu.io import device_orc as dorc
        with open(path, "rb") as f:
            raw = f.read()
        return path, raw, dorc.read_meta(raw)

    def _num_chunks(self, fctx) -> int:
        return len(fctx[2].stripes)

    def _decode_chunk(self, fctx, idx: int, file_schema: Schema,
                      file_cols):
        from spark_rapids_tpu.io import device_orc as dorc
        path, raw, meta = fctx
        return dorc.decode_stripe(path, idx, file_schema,
                                  columns=file_cols, raw=raw, meta=meta)


class TpuCsvScanExec(TpuExec):
    """Device-decoding CSV scan: ONE byte-tensor kernel per file scans
    delimiters and parses fields in HBM (GpuBatchScanExec Table.readCSV
    analog, reference: GpuBatchScanExec.scala:465).  Unsupported
    dialects (quotes, ragged rows, exotic numerics) fall back to the
    Arrow reader per file/column."""

    def __init__(self, scan: FileScan, conf):
        super().__init__()
        self.scan = scan
        self.conf = conf
        self.columns = scan.options.get("columns")
        self._schema = scan.schema if not self.columns else Schema(
            [scan.schema.field(c) for c in self.columns])
        self.metrics.extra["fallbackColumns"] = 0
        self.metrics.extra["fallbackFiles"] = 0

    @property
    def schema(self) -> Schema:
        return self._schema

    def _file_part(self, path: str):
        from spark_rapids_tpu.exec.context import set_input_file
        from spark_rapids_tpu.io import device_csv as dcsv
        from spark_rapids_tpu.io.readers import _read_csv, _normalize
        from spark_rapids_tpu.columnar.batch import from_arrow
        wanted = [f.name for f in self._schema.fields]
        opts = self.scan.options
        try:
            with tpu_semaphore(self.metrics):
                with timed(self.metrics, "scan.csvDecode"):
                    try:
                        batch, fallbacks = dcsv.decode_csv(
                            path, self.scan.schema, columns=wanted,
                            sep=opts.get("sep", ","),
                            header=bool(opts.get("header", True)))
                        self.metrics.add_extra("fallbackColumns",
                                               len(fallbacks))
                    except dcsv.UnsupportedCsv:
                        # whole-file host fallback
                        self.metrics.add_extra("fallbackFiles", 1)
                        t = _normalize(_read_csv(path, opts),
                                       self.scan.schema,
                                       permissive=True)
                        batch = from_arrow(t.select(wanted))
                    self.metrics.num_output_rows += int(batch.num_rows)
                    self.metrics.add_batches()
                    set_input_file(path)
                    yield batch
        finally:
            set_input_file("")

    def execute(self):
        from spark_rapids_tpu.io.readers import scan_file_indices
        return [self._file_part(self.scan.paths[i])
                for i in scan_file_indices(self.scan)]

    def simple_string(self) -> str:
        return (f"{type(self).__name__}"
                f"(files={len(self.scan.paths)}, deviceDecode)")
