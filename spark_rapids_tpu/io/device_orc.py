"""Device-side ORC decode: stripe streams upload packed, expand in HBM.

TPU-native analog of the reference's device ORC scan
(reference: GpuOrcScan.scala:206+ — CPU walks stripe footers, libcudf
decodes on GPU).  Mirrors io/device_parquet.py's architecture:

  host (O(runs), not O(values)):
    * hand-parsed protobuf postscript/footer/stripe-footer (ORC metadata
      is plain proto wire format; no generated code needed)
    * RLEv2 run walking — SHORT_REPEAT -> RLE runs, DIRECT -> big-endian
      bit-pack runs; DELTA materializes via vectorized numpy (base +
      cumsum); PATCHED_BASE falls the column back to host Arrow
    * boolean/PRESENT byte-RLE expanded with numpy (n/8 bytes)

  device (O(values), jitted per bucket):
    * big-endian bit-pack expansion (the MSB-first twin of parquet's
      run expansion), zigzag decode, PRESENT scatter via the shared
      ``_def_expand`` two-pass pattern, string dictionary gathers

Coverage: int8/16/32/64, date32, float32/64, boolean, strings
(DICTIONARY_V2 gathers in HBM; DIRECT_V2 builds the byte matrix on
host), flat schemas, NONE/ZLIB/ZSTD/SNAPPY(if available)/LZ4-frame
stream compression.  Anything else falls back to host Arrow *per
column*, same philosophy as the parquet decoder.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.orc as paorc

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _bucket_strlen, bucket_rows,
                                             from_arrow)
from spark_rapids_tpu.io.device_parquet import (RunTable, _def_expand,
                                                _dict_gather, _pad_np,
                                                _string_dict_matrix,
                                                _to_cap, _upload_runs)
from spark_rapids_tpu.plan.logical import Schema

_MAX_W = 24  # device window supports shift(<=7) + w bits in 4 bytes

# stream kinds
PRESENT, DATA, LENGTH, DICTIONARY_DATA, SECONDARY = 0, 1, 2, 3, 5
# column encodings
ENC_DIRECT, ENC_DICTIONARY, ENC_DIRECT_V2, ENC_DICTIONARY_V2 = 0, 1, 2, 3


class UnsupportedOrc(Exception):
    pass


# ---------------------------------------------------------------------------
# protobuf-lite: ORC metadata is plain proto2 wire format
# ---------------------------------------------------------------------------

def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over one message."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _varint(buf, pos)
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise UnsupportedOrc(f"proto wire type {wt}")
        yield fnum, wt, v


@dataclass
class StripeInfo:
    offset: int = 0
    index_len: int = 0
    data_len: int = 0
    footer_len: int = 0
    num_rows: int = 0


@dataclass
class OrcMeta:
    compression: int = 0           # 0 none, 1 zlib, 2 snappy, 4 lz4, 5 zstd
    block_size: int = 262144
    stripes: List[StripeInfo] = field(default_factory=list)
    kinds: List[int] = field(default_factory=list)       # per type id
    field_names: List[str] = field(default_factory=list)  # of the root


def read_meta(raw: bytes) -> OrcMeta:
    ps_len = raw[-1]
    ps = raw[-1 - ps_len:-1]
    m = OrcMeta()
    footer_len = 0
    for fnum, _, v in _fields(ps):
        if fnum == 1:
            footer_len = v
        elif fnum == 2:
            m.compression = v
        elif fnum == 3:
            m.block_size = v
    footer_raw = _decompress(m, raw[-1 - ps_len - footer_len:-1 - ps_len])
    for fnum, _, v in _fields(footer_raw):
        if fnum == 3:  # StripeInformation
            si = StripeInfo()
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    si.offset = v2
                elif f2 == 2:
                    si.index_len = v2
                elif f2 == 3:
                    si.data_len = v2
                elif f2 == 4:
                    si.footer_len = v2
                elif f2 == 5:
                    si.num_rows = v2
            m.stripes.append(si)
        elif fnum == 4:  # Type
            kind = 0
            names: List[str] = []
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    kind = v2
                elif f2 == 3:
                    names.append(v2.decode("utf-8"))
            m.kinds.append(kind)
            if not m.field_names and names:
                m.field_names = names
    return m


def _decompress(m: OrcMeta, buf: bytes) -> bytes:
    """ORC stream decompression: 3-byte chunk headers (len << 1 | raw)."""
    if m.compression == 0:
        return buf
    out = bytearray()
    pos = 0
    while pos + 3 <= len(buf):
        h = buf[pos] | (buf[pos + 1] << 8) | (buf[pos + 2] << 16)
        pos += 3
        ln = h >> 1
        chunk = buf[pos:pos + ln]
        pos += ln
        if h & 1:  # original (uncompressed) chunk
            out += chunk
        elif m.compression == 1:
            out += zlib.decompress(chunk, wbits=-15)
        elif m.compression == 5:
            import zstandard
            out += zstandard.ZstdDecompressor().decompress(
                chunk, max_output_size=m.block_size)
        elif m.compression == 4:
            import lz4.frame
            out += lz4.frame.decompress(chunk)
        elif m.compression == 2:
            try:
                import snappy
                out += snappy.decompress(chunk)
            except ImportError:
                raise UnsupportedOrc("snappy codec not available")
        else:
            raise UnsupportedOrc(f"orc compression {m.compression}")
    return bytes(out)


@dataclass
class StreamInfo:
    kind: int
    column: int
    length: int
    offset: int = 0  # absolute file offset


def read_stripe_footer(raw: bytes, m: OrcMeta, si: StripeInfo
                       ) -> Tuple[List[StreamInfo], List[Tuple[int, int]]]:
    foot = _decompress(m, raw[si.offset + si.index_len + si.data_len:
                              si.offset + si.index_len + si.data_len
                              + si.footer_len])
    streams: List[StreamInfo] = []
    encodings: List[Tuple[int, int]] = []  # (kind, dict_size) per column
    for fnum, _, v in _fields(foot):
        if fnum == 1:
            s = StreamInfo(0, 0, 0)
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    s.kind = v2
                elif f2 == 2:
                    s.column = v2
                elif f2 == 3:
                    s.length = v2
            streams.append(s)
        elif fnum == 2:
            kind = 0
            dsz = 0
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    kind = v2
                elif f2 == 2:
                    dsz = v2
            encodings.append((kind, dsz))
    # streams are laid out back to back from the stripe start (index
    # streams first, then data streams) in footer order
    off = si.offset
    for s in streams:
        s.offset = off
        off += s.length
    return streams, encodings


# ---------------------------------------------------------------------------
# RLEv2 host walking
# ---------------------------------------------------------------------------

_FBS = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
        19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48, 56, 64]


def _zigzag_np(u: np.ndarray) -> np.ndarray:
    return (u >> 1) ^ -(u & 1)


def _svarint(buf: bytes, pos: int) -> Tuple[int, int]:
    u, pos = _varint(buf, pos)
    return (u >> 1) ^ -(u & 1), pos


def walk_rlev2(buf: bytes, n_values: int, signed: bool,
               runs: RunTable, packed: bytearray
               ) -> Optional[np.ndarray]:
    """Walk an RLEv2 stream into device-expandable runs.

    SHORT_REPEAT and DIRECT (w <= 24) append to the shared run table
    (bit-pack regions are BIG-endian — the device expander's BE twin
    reads them in place).  DELTA sub-streams are materialized into a
    numpy overlay (vectorized cumsum) returned alongside; a non-None
    return means "use the overlay for the whole stream" (mixed
    run/overlay streams keep runs for non-delta spans with the overlay
    filled only where delta runs landed — simplest correct form:
    materialize EVERYTHING into the overlay once any delta run exists).
    PATCHED_BASE raises (column falls back).
    """
    pos = 0
    seen = 0
    # lazy: materialize host values ONLY if a delta run shows up (the
    # device expands short-repeat/direct runs; re-deriving them on host
    # for nothing would be O(values) host work)
    descs: List[Tuple] = []
    vals: List[np.ndarray] = []
    any_delta = False

    def _materialize_pending():
        for d in descs:
            if d[0] == "rle":
                vals.append(np.full(d[1], d[2], dtype=np.int64))
            else:
                _, cnt_, w_, region_ = d
                bits_ = np.unpackbits(
                    np.frombuffer(region_, dtype=np.uint8))
                u_ = _bits_be_to_uint(bits_, cnt_, w_)
                vals.append(_zigzag_np(u_.astype(np.int64)) if signed
                            else u_.astype(np.int64))
        descs.clear()

    while seen < n_values and pos < len(buf):
        h = buf[pos]
        enc = h >> 6
        if enc == 0:  # SHORT_REPEAT
            w = ((h >> 3) & 7) + 1
            cnt = (h & 7) + 3
            val = int.from_bytes(buf[pos + 1:pos + 1 + w], "big")
            pos += 1 + w
            if signed:
                val = (val >> 1) ^ -(val & 1)
            runs.counts.append(cnt)
            runs.is_rle.append(True)
            runs.values.append(val)
            runs.bit_bases.append(0)
            runs.widths.append(0)
            descs.append(("rle", cnt, val))
            seen += cnt
        elif enc == 1:  # DIRECT
            w = _FBS[(h >> 1) & 0x1F]
            cnt = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            nbytes = (cnt * w + 7) // 8
            region = buf[pos:pos + nbytes]
            pos += nbytes
            if w > _MAX_W:
                raise UnsupportedOrc(f"direct width {w}")
            runs.counts.append(cnt)
            runs.is_rle.append(False)
            runs.values.append(1 if signed else 0)  # zigzag flag
            runs.bit_bases.append(len(packed) * 8)
            runs.widths.append(w)
            packed += region
            descs.append(("bits", cnt, w, region))
            seen += cnt
        elif enc == 3:  # DELTA
            any_delta = True
            _materialize_pending()
            w_code = (h >> 1) & 0x1F
            w = 0 if w_code == 0 else _FBS[w_code]
            cnt = (((h & 1) << 8) | buf[pos + 1]) + 1
            pos += 2
            if signed:
                base, pos = _svarint(buf, pos)
            else:
                base, pos = _varint(buf, pos)
            delta0, pos = _svarint(buf, pos)
            out = np.empty(cnt, dtype=np.int64)
            out[0] = base
            if cnt > 1:
                out[1] = base + delta0
            if cnt > 2:
                if w == 0:
                    deltas = np.full(cnt - 2, delta0, dtype=np.int64)
                else:
                    nbytes = ((cnt - 2) * w + 7) // 8
                    region = buf[pos:pos + nbytes]
                    pos += nbytes
                    bits = np.unpackbits(
                        np.frombuffer(region, dtype=np.uint8))
                    mags = _bits_be_to_uint(bits, cnt - 2, w).astype(
                        np.int64)
                    deltas = np.where(delta0 < 0, -mags, mags)
                out[2:] = out[1] + np.cumsum(deltas)
            vals.append(out)
            seen += cnt
        else:
            raise UnsupportedOrc("PATCHED_BASE run")
    if any_delta:
        _materialize_pending()
        return np.concatenate(vals)[:n_values] if vals else \
            np.zeros(0, np.int64)
    return None


def _bits_be_to_uint(bits: np.ndarray, cnt: int, w: int) -> np.ndarray:
    """MSB-first bit array -> cnt w-bit unsigned values (host numpy)."""
    need = cnt * w
    b = bits[:need].reshape(cnt, w).astype(np.uint64)
    weights = (np.uint64(1) << np.arange(w - 1, -1, -1,
                                         dtype=np.uint64))
    return (b * weights).sum(axis=1, dtype=np.uint64)


def decode_bool_rle(buf: bytes, n_bits: int) -> np.ndarray:
    """ORC byte-RLE over a bit stream -> bool[n_bits] (host, n/8 bytes)."""
    arr = decode_byte_rle(buf, (n_bits + 7) // 8)
    return np.unpackbits(arr, bitorder="big")[:n_bits].astype(bool)


def decode_byte_rle(buf: bytes, n: int) -> np.ndarray:
    """ORC byte-RLE -> uint8[n] (PRESENT/bool bits, tinyint DATA)."""
    out = bytearray()
    pos = 0
    while pos < len(buf) and len(out) < n:
        h = buf[pos]
        pos += 1
        if h < 128:
            out += bytes([buf[pos]]) * (h + 3)
            pos += 1
        else:
            lit = 256 - h
            out += buf[pos:pos + lit]
            pos += lit
    return np.frombuffer(bytes(out[:n]), dtype=np.uint8)


# ---------------------------------------------------------------------------
# Device expansion (big-endian twin of device_parquet._expand_runs)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cap",))
def _expand_runs_be(runs_mat: jnp.ndarray, packed: jnp.ndarray,
                    cap: int) -> jnp.ndarray:
    """Expand SHORT_REPEAT/DIRECT runs; DIRECT regions are MSB-first.

    runs_mat columns: (end, is_rle, value_or_zigzag_flag, bit_base,
    width).  For bit-pack runs the value column carries the zigzag flag
    (1 = signed zigzag decode after unpack).  Values are int64.
    """
    run_ends = runs_mat[:, 0]
    run_is_rle = runs_mat[:, 1] != 0
    run_value = runs_mat[:, 2]
    run_bit_base = runs_mat[:, 3]
    run_w = runs_mat[:, 4]
    i = jnp.arange(cap, dtype=jnp.int64)
    rid = jnp.searchsorted(run_ends, i, side="right")
    rid = jnp.clip(rid, 0, run_ends.shape[0] - 1)
    prev_end = jnp.where(rid > 0, jnp.take(run_ends, rid - 1), 0)
    local = i - prev_end
    w = jnp.take(run_w, rid)
    bitpos = jnp.take(run_bit_base, rid) + local * w
    byte0 = bitpos >> 3
    sh = (bitpos & 7).astype(jnp.uint32)
    nb = packed.shape[0]
    g = lambda k: jnp.take(packed, jnp.clip(byte0 + k, 0, nb - 1)
                           ).astype(jnp.uint32)
    # big-endian 32-bit window starting at byte0
    window = (g(0) << 24) | (g(1) << 16) | (g(2) << 8) | g(3)
    wu = w.astype(jnp.uint32)
    shift = jnp.uint32(32) - sh - wu
    mask = ((jnp.uint32(1) << wu) - 1)
    unpacked = ((window >> shift) & mask).astype(jnp.int64)
    zig = jnp.take(run_value, rid) != 0
    dezig = (unpacked >> 1) ^ -(unpacked & 1)
    vals = jnp.where(zig, dezig, unpacked)
    return jnp.where(jnp.take(run_is_rle, rid),
                     jnp.take(run_value, rid), vals)


# ---------------------------------------------------------------------------
# Column decode
# ---------------------------------------------------------------------------

# ORC type kinds
K_BOOL, K_BYTE, K_SHORT, K_INT, K_LONG, K_FLOAT, K_DOUBLE, K_STRING, \
    K_BINARY, K_TIMESTAMP, K_LIST, K_MAP, K_STRUCT, K_UNION, K_DECIMAL, \
    K_DATE = range(16)

_INT_KINDS = {K_SHORT, K_INT, K_LONG, K_DATE}


def _expand_ints(runs: RunTable, packed: bytes,
                 overlay: Optional[np.ndarray], nn: int,
                 vcap: int) -> jnp.ndarray:
    """Non-null value vector (int64) from runs or a host overlay."""
    if overlay is not None:
        return jnp.asarray(_pad_np(overlay[:nn], vcap))
    dev = _upload_runs(runs, bytes(packed))
    return _expand_runs_be(dev["runs_mat"], dev["packed"], cap=vcap)


def decode_column(kind: int, enc: Tuple[int, int],
                  streams: Dict[int, bytes], out_dtype: dt.DType,
                  n_rows: int, cap: int) -> DeviceColumn:
    """Decode one flat column of a stripe into a DeviceColumn."""
    enc_kind, dict_size = enc
    present = streams.get(PRESENT)
    if present is not None:
        validity_np = decode_bool_rle(present, n_rows)
        nn = int(validity_np.sum())
    else:
        validity_np = np.ones(n_rows, dtype=bool)
        nn = n_rows
    vcap = bucket_rows(max(n_rows, 1))
    validity = jnp.asarray(_pad_np(validity_np, vcap))
    levels = validity.astype(jnp.uint32)

    def def_scatter(vals):
        if present is None:
            data = vals
            return data, jnp.arange(vcap) < n_rows
        return _def_expand(levels, vals, n_rows, cap=vcap)

    if kind in _INT_KINDS:
        if enc_kind != ENC_DIRECT_V2:
            raise UnsupportedOrc(f"int encoding {enc_kind}")
        runs = RunTable.empty()
        packed = bytearray()
        overlay = walk_rlev2(streams[DATA], nn, True, runs, packed)
        vals = _expand_ints(runs, packed, overlay, nn, vcap)
        data, valid = def_scatter(vals)
        return _to_cap(DeviceColumn(
            out_dtype, data.astype(out_dtype.to_np()), valid), cap)

    if kind == K_BYTE:
        vals = jnp.asarray(_pad_np(
            decode_byte_rle(streams[DATA], nn).astype(np.int64), vcap))
        data, valid = def_scatter(vals)
        return _to_cap(DeviceColumn(
            out_dtype, data.astype(out_dtype.to_np()), valid), cap)

    if kind in (K_FLOAT, K_DOUBLE):
        npdt = np.dtype("<f4") if kind == K_FLOAT else np.dtype("<f8")
        vals_np = np.frombuffer(streams[DATA], dtype=npdt, count=nn)
        vals = jnp.asarray(_pad_np(vals_np.copy(), vcap))
        data, valid = def_scatter(vals)
        return _to_cap(DeviceColumn(
            out_dtype, data.astype(out_dtype.to_np()), valid), cap)

    if kind == K_BOOL:
        bits = decode_bool_rle(streams[DATA], nn)
        vals = jnp.asarray(_pad_np(bits, vcap))
        data, valid = def_scatter(vals)
        return _to_cap(DeviceColumn(out_dtype, data, valid), cap)

    if kind == K_STRING:
        if enc_kind == ENC_DICTIONARY_V2:
            # dict lengths + blob on host (dictionaries are small),
            # per-row indices expand + gather on device
            lruns = RunTable.empty()
            lpacked = bytearray()
            lover = walk_rlev2(streams[LENGTH], dict_size, False, lruns,
                               lpacked)
            if lover is None:
                dev = _upload_runs(lruns, bytes(lpacked))
                lens64 = np.asarray(_expand_runs_be(
                    dev["runs_mat"], dev["packed"],
                    cap=bucket_rows(max(dict_size, 1))))[:dict_size]
            else:
                lens64 = lover[:dict_size]
            blob = streams.get(DICTIONARY_DATA, b"")
            offs = np.concatenate([[0], np.cumsum(lens64)])
            entries = [blob[offs[i]:offs[i + 1]]
                       for i in range(dict_size)]
            dmat, dlens = _string_dict_matrix(entries)
            iruns = RunTable.empty()
            ipacked = bytearray()
            iover = walk_rlev2(streams[DATA], nn, False, iruns, ipacked)
            idx = _expand_ints(iruns, ipacked, iover, nn, vcap)
            data_idx, valid = def_scatter(idx)
            mat = _dict_gather(data_idx, jnp.asarray(dmat), valid,
                               cap=vcap)
            lens = _dict_gather(data_idx, jnp.asarray(dlens), valid,
                                cap=vcap)
            return _to_cap(DeviceColumn(out_dtype, mat, valid,
                                        lens.astype(jnp.int32)), cap)
        if enc_kind == ENC_DIRECT_V2:
            lruns = RunTable.empty()
            lpacked = bytearray()
            lover = walk_rlev2(streams[LENGTH], nn, False, lruns,
                               lpacked)
            if lover is None:
                dev = _upload_runs(lruns, bytes(lpacked))
                lens64 = np.asarray(_expand_runs_be(
                    dev["runs_mat"], dev["packed"],
                    cap=bucket_rows(max(nn, 1))))[:nn]
            else:
                lens64 = lover[:nn]
            blob = np.frombuffer(streams.get(DATA, b""), dtype=np.uint8)
            max_len = _bucket_strlen(int(lens64.max()) if nn else 0)
            offs = np.concatenate([[0], np.cumsum(lens64)]).astype(
                np.int64)
            mat_np = np.zeros((max(nn, 1), max_len), dtype=np.uint8)
            colidx = np.arange(max_len)[None, :]
            src = offs[:nn, None] + colidx
            ok = colidx < lens64[:nn, None]
            mat_np[:nn][ok] = blob[src[ok]]
            mat = jnp.asarray(_pad_np(mat_np, vcap))
            lens = jnp.asarray(_pad_np(lens64[:nn].astype(np.int32),
                                       vcap))
            data, valid = def_scatter(mat)
            lens_s, _ = def_scatter(lens)
            return _to_cap(DeviceColumn(out_dtype, data, valid,
                                        lens_s.astype(jnp.int32)), cap)
        raise UnsupportedOrc(f"string encoding {enc_kind}")

    raise UnsupportedOrc(f"orc kind {kind}")


# ---------------------------------------------------------------------------
# Stripe-level API (decode_row_group twin)
# ---------------------------------------------------------------------------

def decode_stripe(path: str, stripe: int, schema: Schema,
                  columns: Optional[List[str]] = None,
                  raw: Optional[bytes] = None,
                  meta: Optional[OrcMeta] = None
                  ) -> Tuple[DeviceBatch, List[str]]:
    """Decode one ORC stripe to a DeviceBatch.

    Returns (batch, fallback_columns); fallback columns host-decode via
    Arrow so one exotic column doesn't knock the stripe off device.
    Pass ``meta`` (from ``read_meta``) to skip the O(stripes) redundant
    footer re-parse when decoding many stripes of one file."""
    if raw is None:
        with open(path, "rb") as f:
            raw = f.read()
    if meta is None:
        meta = read_meta(raw)
    wanted = columns or [f.name for f in schema.fields]
    # flat-schema guard: nested types shift ORC column ids (each subtree
    # claims a contiguous id range) — decoding by field position would
    # silently read the WRONG column's streams; whole stripe falls back
    if any(k in (K_LIST, K_MAP, K_STRUCT, K_UNION)
           for k in meta.kinds[1:]):
        import io as _io
        t = paorc.ORCFile(_io.BytesIO(raw)).read_stripe(
            stripe, columns=wanted)
        t = pa.Table.from_batches([t]) if not isinstance(t, pa.Table) \
            else t
        cast = pa.Table.from_arrays(
            [_cast_one(t.select([c]), schema.field(c)).column(0)
             for c in wanted], names=wanted)
        return from_arrow(cast), list(wanted)
    si = meta.stripes[stripe]
    streams, encodings = read_stripe_footer(raw, meta, si)
    n_rows = si.num_rows
    cap = bucket_rows(max(n_rows, 1))
    names = meta.field_names

    cols: List[DeviceColumn] = []
    out_names: List[str] = []
    fallbacks: List[str] = []
    orc_file = None
    for name in wanted:
        f = schema.field(name)
        if name not in names:
            npd = f.dtype.to_np() if not f.dtype.is_string else np.uint8
            if f.dtype.is_string:
                col = DeviceColumn(f.dtype,
                                   jnp.zeros((cap, 1), dtype=jnp.uint8),
                                   jnp.zeros((cap,), dtype=bool),
                                   jnp.zeros((cap,), dtype=jnp.int32))
            else:
                col = DeviceColumn(f.dtype,
                                   jnp.zeros((cap,), dtype=npd),
                                   jnp.zeros((cap,), dtype=bool))
            cols.append(col)
            out_names.append(name)
            continue
        # ORC column ids: 0 is the root struct; field i is column i+1
        cid = names.index(name) + 1
        try:
            kind = meta.kinds[cid]
            sdata: Dict[int, bytes] = {}
            for s in streams:
                if s.column == cid and s.kind in (PRESENT, DATA, LENGTH,
                                                  DICTIONARY_DATA):
                    sdata[s.kind] = _decompress(
                        meta, raw[s.offset:s.offset + s.length])
            col = decode_column(kind, encodings[cid], sdata, f.dtype,
                                n_rows, cap)
        except Exception:
            fallbacks.append(name)
            if orc_file is None:
                import io as _io
                orc_file = paorc.ORCFile(_io.BytesIO(raw))
            t = orc_file.read_stripe(stripe, columns=[name])
            t = pa.Table.from_batches([t]) if not isinstance(
                t, pa.Table) else t
            sub = from_arrow(_cast_one(t, f), capacity=cap)
            col = sub.columns[0]
        cols.append(col)
        out_names.append(name)
    return DeviceBatch(out_names, cols, n_rows), fallbacks


def _cast_one(t: pa.Table, f) -> pa.Table:
    col = t.column(0).cast(f.dtype.to_arrow())
    return pa.Table.from_arrays(
        [col], schema=pa.schema([pa.field(f.name, f.dtype.to_arrow(),
                                          f.nullable)]))


def num_stripes(path: str) -> int:
    return paorc.ORCFile(path).nstripes
