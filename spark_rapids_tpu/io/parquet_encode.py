"""Device-side Parquet write encode: the mirror image of device decode.

Reference analog: ``GpuParquetFileFormat`` encodes batches on device via
``Table.writeParquetChunked`` into a host buffer and streams bytes out
(reference: GpuParquetFileFormat.scala:281, ColumnarOutputWriter.scala);
the FAQ headlines "GPU can encode Parquet and ORC much faster than CPU"
(reference: docs/FAQ.md:69-75).

TPU-first split of the same work, following the measured device cost
model (PERF.md): the O(rows) DATA MOVEMENT — per-column null compaction
of values to the front — runs on device as one cached kernel, and the
whole result crosses the wire in the engine's single packed download
(columnar/batch._dispatch_pack).  The byte-twiddling the TPU does badly
(bit-packing levels, varint/thrift headers, page compression) runs in
vectorized numpy / Arrow codecs on host.  Output is a standard
Parquet v1 file: one row group per batch, one PLAIN data page per
column, RLE/bit-packed definition levels, snappy/zstd/uncompressed
codecs — readable by any Parquet reader (pyarrow round-trip tested).

Coverage: BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY (strings),
DATE32 and TIMESTAMP_US logical annotations.  Lists/structs fall back
to the host Arrow writer (io/writers.py).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _dispatch_pack,
                                             _download_batch)

# parquet.thrift enums
_TYPE = {"BOOLEAN": 0, "INT32": 1, "INT64": 2, "FLOAT": 4, "DOUBLE": 5,
         "BYTE_ARRAY": 6}
_ENC_PLAIN = 0
_ENC_RLE = 3
_CODEC = {"none": 0, "uncompressed": 0, "snappy": 1, "gzip": 2,
          "zstd": 6}
_CT_UTF8 = 0
_CT_DATE = 6
_CT_TS_MICROS = 10


# ---------------------------------------------------------------------------
# Thrift compact-protocol writer (mirror of parquet_meta._Reader)
# ---------------------------------------------------------------------------

_CT_BOOL_TRUE = 1
_CT_BOOL_FALSE = 2
_CT_I32 = 5
_CT_I64 = 6
_CT_BINARY = 8
_CT_LIST = 9
_CT_STRUCT = 12


class _TW:
    """Just enough TCompactProtocol writing for Parquet metadata."""

    def __init__(self):
        self.out = bytearray()
        self._last_fid = [0]

    def _varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def _zigzag(self, v: int) -> None:
        self._varint((v << 1) ^ (v >> 63))

    def _field(self, fid: int, ctype: int) -> None:
        delta = fid - self._last_fid[-1]
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ctype)
        else:
            self.out.append(ctype)
            self._varint((fid << 1) ^ (fid >> 15))
        self._last_fid[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self._field(fid, _CT_I32)
        self._zigzag(v)

    def i64(self, fid: int, v: int) -> None:
        self._field(fid, _CT_I64)
        self._zigzag(v)

    def string(self, fid: int, s: str) -> None:
        self._field(fid, _CT_BINARY)
        b = s.encode("utf-8")
        self._varint(len(b))
        self.out += b

    def struct_begin(self, fid: int) -> None:
        self._field(fid, _CT_STRUCT)
        self._last_fid.append(0)

    def struct_end(self) -> None:
        self.out.append(0)
        self._last_fid.pop()

    def list_begin(self, fid: int, size: int, elem_ctype: int) -> None:
        self._field(fid, _CT_LIST)
        if size < 15:
            self.out.append((size << 4) | elem_ctype)
        else:
            self.out.append(0xF0 | elem_ctype)
            self._varint(size)

    def elem_i32(self, v: int) -> None:
        self._zigzag(v)

    def elem_string(self, s: str) -> None:
        b = s.encode("utf-8")
        self._varint(len(b))
        self.out += b

    def elem_struct_begin(self) -> None:
        self._last_fid.append(0)

    def elem_struct_end(self) -> None:
        self.out.append(0)
        self._last_fid.pop()


def _page_header(n_values: int, uncompressed: int,
                 compressed: int) -> bytes:
    w = _TW()
    w.i32(1, 0)                  # type = DATA_PAGE
    w.i32(2, uncompressed)
    w.i32(3, compressed)
    w.struct_begin(5)            # data_page_header
    w.i32(1, n_values)
    w.i32(2, _ENC_PLAIN)         # encoding
    w.i32(3, _ENC_RLE)           # definition_level_encoding
    w.i32(4, _ENC_RLE)           # repetition_level_encoding
    w.struct_end()
    w.out.append(0)              # end PageHeader struct
    return bytes(w.out)


def _schema_elements(w: _TW, fields: Sequence[Tuple[str, dt.DType]]
                     ) -> None:
    w.list_begin(2, len(fields) + 1, _CT_STRUCT)
    # root
    w.elem_struct_begin()
    w.string(4, "schema")
    w.i32(5, len(fields))
    w.elem_struct_end()
    for name, d in fields:
        w.elem_struct_begin()
        w.i32(1, _TYPE[_physical(d)])
        w.i32(3, 1)              # repetition = OPTIONAL
        w.string(4, name)
        ct = _converted(d)
        if ct is not None:
            w.i32(6, ct)
        w.elem_struct_end()


def _physical(d: dt.DType) -> str:
    if d.is_string:
        return "BYTE_ARRAY"
    if d.is_bool:
        return "BOOLEAN"
    if d.id == dt.TypeId.DATE32:
        return "INT32"
    if d.id == dt.TypeId.TIMESTAMP_US:
        return "INT64"
    npd = d.to_np()
    return {np.dtype("int32"): "INT32", np.dtype("int64"): "INT64",
            np.dtype("float32"): "FLOAT",
            np.dtype("float64"): "DOUBLE"}[np.dtype(npd)]


def _converted(d: dt.DType) -> Optional[int]:
    if d.is_string:
        return _CT_UTF8
    if d.id == dt.TypeId.DATE32:
        return _CT_DATE
    if d.id == dt.TypeId.TIMESTAMP_US:
        return _CT_TS_MICROS
    return None


def supported(schema_fields) -> bool:
    try:
        for f in schema_fields:
            _physical(f.dtype)
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Device kernel: per-column null compaction
# ---------------------------------------------------------------------------

def _compact_for_encode(batch: DeviceBatch) -> DeviceBatch:
    """Per column: move non-null values to the front (cumsum+scatter),
    keeping the ORIGINAL validity (the host derives def levels from it).
    One cached kernel per schema; the result rides the engine's single
    packed download."""
    cap = batch.capacity
    exists = batch.row_mask()
    cols = []
    for c in batch.columns:
        keep = c.validity & exists
        dest = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1,
                         cap)
        data = jnp.zeros_like(c.data).at[dest].set(c.data, mode="drop")
        lengths = None
        if c.lengths is not None:
            lengths = jnp.zeros_like(c.lengths).at[dest].set(
                jnp.where(keep, c.lengths, 0), mode="drop")
        cols.append(DeviceColumn(c.dtype, data, keep, lengths,
                                 c.elem_validity))
    return DeviceBatch(batch.names, cols, batch.num_rows)


# ---------------------------------------------------------------------------
# Host assembly
# ---------------------------------------------------------------------------

def _rle_def_levels(valid: np.ndarray) -> bytes:
    """max_def=1 definition levels, RLE/bit-packed hybrid, with the
    4-byte length prefix of DataPage v1."""
    n = valid.shape[0]
    if n and valid.all():
        # one RLE run covering all n values (varint header + level byte)
        out = bytearray()
        h = n << 1
        while True:
            b = h & 0x7F
            h >>= 7
            if h:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        out.append(1)            # the repeated level value (1 byte, w=1)
        body = bytes(out)
    else:
        groups = (n + 7) // 8
        out = bytearray()
        h = (groups << 1) | 1
        while True:
            b = h & 0x7F
            h >>= 7
            if h:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        out += np.packbits(valid, bitorder="little").tobytes()
        body = bytes(out)
    return struct.pack("<I", len(body)) + body


def _plain_values(d: dt.DType, data: np.ndarray, lengths, n_valid: int
                  ) -> bytes:
    if d.is_string:
        lens = lengths[:n_valid].astype(np.int64)
        total = int(lens.sum()) + 4 * n_valid
        out = np.zeros(total, dtype=np.uint8)
        starts = 4 * np.arange(1, n_valid + 1) + np.concatenate(
            [[0], np.cumsum(lens)[:-1]])
        # 4-byte little-endian length prefixes
        lb = lens.astype("<u4").view(np.uint8).reshape(n_valid, 4)
        lpos = (starts - 4)[:, None] + np.arange(4)[None, :]
        out[lpos.reshape(-1)] = lb.reshape(-1)
        # value bytes
        mask = np.arange(data.shape[1])[None, :] < lens[:, None]
        flat = np.ascontiguousarray(data[:n_valid])[mask]
        idx = np.repeat(starts, lens) + _intra(lens)
        out[idx] = flat
        return out.tobytes()
    if d.is_bool:
        return np.packbits(data[:n_valid].astype(bool),
                           bitorder="little").tobytes()
    npd = np.dtype(d.to_np()).newbyteorder("<")
    return np.ascontiguousarray(data[:n_valid]).astype(
        npd, copy=False).tobytes()


def _intra(lens: np.ndarray) -> np.ndarray:
    """[0..l0), [0..l1), ... concatenated (empty runs skipped)."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    prev = np.concatenate([[0], np.cumsum(lens)[:-1]])
    return np.arange(total, dtype=np.int64) - np.repeat(prev, lens)


def _compress(codec: str, payload: bytes) -> bytes:
    if codec in ("none", "uncompressed"):
        return payload
    return pa.Codec(codec if codec != "gzip" else "gzip"
                    ).compress(payload, asbytes=True)


def encode_batch(batch: DeviceBatch, codec: str = "snappy") -> bytes:
    """Encode one DeviceBatch into a complete single-row-group Parquet
    file blob (device compaction + single packed download + host page
    assembly)."""
    comp = _compact_for_encode(batch)
    packed = _dispatch_pack(comp)
    n, host_cols = _download_batch(comp, packed)

    fields = [(name, c.dtype) for name, c in zip(batch.names,
                                                 batch.columns)]
    out = bytearray(b"PAR1")
    col_meta = []
    for (name, d), (data, validity, lengths, _ev) in zip(fields,
                                                         host_cols):
        valid = validity[:n]
        n_valid = int(valid.sum())
        levels = _rle_def_levels(valid)
        values = _plain_values(d, data, lengths, n_valid)
        payload = levels + values
        compressed = _compress(codec, payload)
        header = _page_header(n, len(payload), len(compressed))
        offset = len(out)
        out += header
        out += compressed
        col_meta.append(dict(
            name=name, dtype=d, offset=offset, num_values=n,
            uncompressed=len(payload) + len(header),
            compressed=len(compressed) + len(header)))

    # footer
    w = _TW()
    w.elem_struct_begin()
    w.i32(1, 1)                               # version
    _schema_elements(w, fields)
    w.i64(3, n)                               # num_rows
    w.list_begin(4, 1, _CT_STRUCT)            # row_groups
    w.elem_struct_begin()
    w.list_begin(1, len(col_meta), _CT_STRUCT)   # columns
    for cm in col_meta:
        w.elem_struct_begin()
        w.i64(2, cm["offset"])                # file_offset
        w.struct_begin(3)                     # meta_data
        w.i32(1, _TYPE[_physical(cm["dtype"])])
        w.list_begin(2, 2, _CT_I32)           # encodings
        w.elem_i32(_ENC_PLAIN)
        w.elem_i32(_ENC_RLE)
        w.list_begin(3, 1, _CT_BINARY)        # path_in_schema
        w.elem_string(cm["name"])
        w.i32(4, _CODEC[codec])
        w.i64(5, cm["num_values"])
        w.i64(6, cm["uncompressed"])
        w.i64(7, cm["compressed"])
        w.i64(9, cm["offset"])                # data_page_offset
        w.struct_end()
        w.elem_struct_end()
    w.i64(2, sum(cm["uncompressed"] for cm in col_meta))
    w.i64(3, n)                               # row group num_rows
    w.elem_struct_end()
    w.string(6, "spark-rapids-tpu parquet encoder")
    w.elem_struct_end()

    footer = bytes(w.out)
    out += footer
    out += struct.pack("<I", len(footer))
    out += b"PAR1"
    return bytes(out)
