"""File write layer: Parquet / CSV / ORC writers with Hive-style
partitioned output.

Reference analog: L8 write path (SURVEY.md) — ``GpuParquetFileFormat`` /
``GpuOrcFileFormat`` encode on device via ``Table.writeParquetChunked``
into a host buffer, then Hadoop FS output
(GpuParquetFileFormat.scala:270-281, ColumnarOutputWriter.scala,
GpuFileFormatWriter.scala:338, GpuFileFormatDataWriter.scala:419 for
partitioned/dynamic-partition writes).  Here encode runs on host via Arrow
C++ behind the same writer interface (the device-encode swap-in point),
with per-partition part files and Hive ``key=value`` directory layout for
partitionBy, plus basic write-stats (BasicColumnarWriteStatsTracker
analog).
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


@dataclass
class WriteStats:
    """numFiles/numBytes/numRows (reference: BasicColumnarWriteStatsTracker)."""

    num_files: int = 0
    num_bytes: int = 0
    num_rows: int = 0
    partitions: List[str] = field(default_factory=list)


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "errorifexists"
        self._partition_by: List[str] = []
        self._options: Dict[str, str] = {}

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m.lower()
        return self

    def option(self, k: str, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partition_by(self, *cols) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    # -- formats -----------------------------------------------------------
    def parquet(self, path: str) -> WriteStats:
        return self._write(path, "parquet")

    def csv(self, path: str, header: bool = True) -> WriteStats:
        self._options.setdefault("header", header)
        return self._write(path, "csv")

    def orc(self, path: str) -> WriteStats:
        return self._write(path, "orc")

    # -- core --------------------------------------------------------------
    def _prepare_dir(self, path: str) -> None:
        if os.path.exists(path):
            if self._mode == "overwrite":
                import shutil
                shutil.rmtree(path)
            elif self._mode in ("errorifexists", "error"):
                raise FileExistsError(
                    f"path {path} already exists (mode=errorifexists)")
            elif self._mode == "ignore":
                return
        os.makedirs(path, exist_ok=True)

    def _write_one(self, table: pa.Table, path: str, fmt: str) -> int:
        if fmt == "parquet":
            papq.write_table(table, path,
                             compression=self._options.get(
                                 "compression", "snappy"))
        elif fmt == "csv":
            opts = pacsv.WriteOptions(
                include_header=bool(self._options.get("header", True)))
            pacsv.write_csv(table, path, opts)
        elif fmt == "orc":
            paorc.write_table(table, path)
        return os.path.getsize(path)

    def _write(self, path: str, fmt: str) -> WriteStats:
        if self._mode == "ignore" and os.path.exists(path):
            return WriteStats()
        self._prepare_dir(path)
        stats = WriteStats()
        job_id = uuid.uuid4().hex[:8]
        ext = {"parquet": "parquet", "csv": "csv", "orc": "orc"}[fmt]

        result = self.df.session._plan_physical(self.df.plan)
        if fmt in ("parquet", "orc") and \
                self._device_encode_ok(result.plan, fmt):
            return self._write_device(result.plan, path, job_id, stats,
                                      fmt)
        part_iters = result.plan.execute()
        for pid, it in enumerate(part_iters):
            tables = [t for t in it if t.num_rows > 0]
            if not tables:
                continue
            table = pa.concat_tables(tables)
            if self._partition_by:
                self._write_partitioned(table, path, fmt, pid, job_id, ext,
                                        stats)
            else:
                fname = os.path.join(
                    path, f"part-{pid:05d}-{job_id}.{ext}")
                stats.num_bytes += self._write_one(table, fname, fmt)
                stats.num_files += 1
                stats.num_rows += table.num_rows
        # _SUCCESS marker like Hadoop committers
        open(os.path.join(path, "_SUCCESS"), "w").close()
        return stats

    # -- device encode (parquet + ORC) ------------------------------------
    def _device_encode_ok(self, plan, fmt: str) -> bool:
        from spark_rapids_tpu import config as cfg
        from spark_rapids_tpu.exec.tpu_basic import DeviceToHostExec
        if self._partition_by:
            return False
        if fmt == "parquet":
            from spark_rapids_tpu.io import parquet_encode as enc
            key = cfg.PARQUET_DEVICE_ENCODE
        else:
            from spark_rapids_tpu.io import orc_encode as enc
            key = cfg.ORC_DEVICE_ENCODE
        if not self.df.session.conf.get(key):
            return False
        return isinstance(plan, DeviceToHostExec) and \
            enc.supported(plan.schema.fields)

    def _write_device(self, plan, path: str, job_id: str,
                      stats: WriteStats, fmt: str) -> WriteStats:
        """Device-encode path (GpuParquetFileFormat / GpuOrcFileFormat
        analog): per-column null compaction on device, one packed
        download per batch, host page/stripe assembly
        (io/parquet_encode.py, io/orc_encode.py)."""
        from spark_rapids_tpu.columnar.batch import concat_batches
        if fmt == "parquet":
            from spark_rapids_tpu.io import parquet_encode as pqe
            codec = self._options.get("compression", "snappy")
            encode = lambda b: pqe.encode_batch(b, codec=codec)  # noqa
        else:
            from spark_rapids_tpu.io import orc_encode as oce
            encode = oce.encode_batch
        inner = plan.children[0]
        for pid, it in enumerate(inner.execute()):
            batches = [b for b in it if int(b.num_rows)]
            if not batches:
                continue
            whole = concat_batches(batches) if len(batches) > 1 \
                else batches[0]
            blob = encode(whole)
            fname = os.path.join(path,
                                 f"part-{pid:05d}-{job_id}.{fmt}")
            with open(fname, "wb") as f:
                f.write(blob)
            stats.num_bytes += len(blob)
            stats.num_files += 1
            stats.num_rows += int(whole.num_rows)
        open(os.path.join(path, "_SUCCESS"), "w").close()
        return stats

    def _write_partitioned(self, table: pa.Table, path: str, fmt: str,
                           pid: int, job_id: str, ext: str,
                           stats: WriteStats) -> None:
        """Hive key=value layout (dynamic partitioning analog,
        reference: GpuFileFormatDataWriter dynamic partition writer)."""
        import pyarrow.compute as pc
        keys = self._partition_by
        data_cols = [c for c in table.column_names if c not in keys]
        combos = table.select(keys).group_by(keys).aggregate([])
        for row in range(combos.num_rows):
            mask = None
            parts = []
            for k in keys:
                v = combos.column(k)[row]
                cond = pc.is_null(table.column(k)) if not v.is_valid else \
                    pc.equal(table.column(k), v)
                mask = cond if mask is None else pc.and_(mask, cond)
                sval = "__HIVE_DEFAULT_PARTITION__" if not v.is_valid \
                    else str(v.as_py())
                parts.append(f"{k}={sval}")
            sub = table.filter(mask).select(data_cols)
            subdir = os.path.join(path, *parts)
            os.makedirs(subdir, exist_ok=True)
            fname = os.path.join(subdir,
                                 f"part-{pid:05d}-{job_id}.{ext}")
            stats.num_bytes += self._write_one(sub, fname, fmt)
            stats.num_files += 1
            stats.num_rows += sub.num_rows
            pdir = "/".join(parts)
            if pdir not in stats.partitions:
                stats.partitions.append(pdir)
