"""CPU-side Parquet page metadata: footers via Arrow, page headers via a
minimal Thrift compact-protocol reader.

Reference analog: the reference parses footers and clips row groups on CPU
(`GpuParquetFileFilterHandler`, reference: GpuParquetScan.scala:239,456-620),
then hands raw page bytes to the device decoder (`Table.readParquet`,
GpuParquetScan.scala:1022).  This module is that CPU half for the TPU build:
it walks each column chunk's page stream and returns page descriptors +
payload byte ranges that `io/device_parquet.py` decodes in HBM.

Only the PageHeader struct needs Thrift parsing (chunk offsets, types,
codecs all come from pyarrow's footer metadata), so the reader below
implements just enough of TCompactProtocol: varints, zigzag, field headers,
and recursive skip of unknown fields.
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import pyarrow.parquet as papq

# page-walk probe: every chunk whose page headers are actually walked
# bumps this counter, so tests (and the scan-plan cache acceptance
# criterion) can assert a warm scan performs ZERO walks
_WALK_LOCK = threading.Lock()
_WALK_COUNT = 0


def walk_count() -> int:
    with _WALK_LOCK:
        return _WALK_COUNT


def _note_walk() -> None:
    global _WALK_COUNT
    with _WALK_LOCK:
        _WALK_COUNT += 1

# Thrift compact type nibbles
_T_BOOL_TRUE = 1
_T_BOOL_FALSE = 2
_T_BYTE = 3
_T_I16 = 4
_T_I32 = 5
_T_I64 = 6
_T_DOUBLE = 7
_T_BINARY = 8
_T_LIST = 9
_T_SET = 10
_T_MAP = 11
_T_STRUCT = 12

# Parquet page types
DATA_PAGE = 0
DICTIONARY_PAGE = 2
DATA_PAGE_V2 = 3

# Parquet encodings
PLAIN = 0
PLAIN_DICTIONARY = 2
RLE = 3
BIT_PACKED = 4
DELTA_BINARY_PACKED = 5
RLE_DICTIONARY = 8
BYTE_STREAM_SPLIT = 9


class _Reader:
    """Cursor over a bytes buffer with Thrift compact primitives."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        result = 0
        shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def skip(self, ttype: int) -> None:
        if ttype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
            return
        if ttype == _T_BYTE:
            self.pos += 1
        elif ttype in (_T_I16, _T_I32, _T_I64):
            self.varint()
        elif ttype == _T_DOUBLE:
            self.pos += 8
        elif ttype == _T_BINARY:
            n = self.varint()
            self.pos += n
        elif ttype in (_T_LIST, _T_SET):
            h = self.byte()
            size = h >> 4
            etype = h & 0x0F
            if size == 15:
                size = self.varint()
            for _ in range(size):
                self.skip(etype)
        elif ttype == _T_MAP:
            size = self.varint()
            if size > 0:
                kv = self.byte()
                for _ in range(size):
                    self.skip(kv >> 4)
                    self.skip(kv & 0x0F)
        elif ttype == _T_STRUCT:
            self.read_struct()
        else:
            raise ValueError(f"unknown thrift type {ttype}")

    def read_struct(self) -> Dict[int, object]:
        """Parse a struct into {field_id: value}; unknown types skipped.

        Values: bools, ints, bytes, nested dicts for structs."""
        out: Dict[int, object] = {}
        fid = 0
        while True:
            b = self.byte()
            if b == 0:
                return out
            delta = b >> 4
            ttype = b & 0x0F
            if delta == 0:
                fid = self.zigzag()
            else:
                fid += delta
            if ttype == _T_BOOL_TRUE:
                out[fid] = True
            elif ttype == _T_BOOL_FALSE:
                out[fid] = False
            elif ttype == _T_BYTE:
                out[fid] = self.byte()
            elif ttype in (_T_I16, _T_I32, _T_I64):
                out[fid] = self.zigzag()
            elif ttype == _T_DOUBLE:
                out[fid] = struct.unpack("<d", self.buf[self.pos:
                                                        self.pos + 8])[0]
                self.pos += 8
            elif ttype == _T_BINARY:
                n = self.varint()
                out[fid] = self.buf[self.pos:self.pos + n]
                self.pos += n
            elif ttype == _T_STRUCT:
                out[fid] = self.read_struct()
            else:
                self.skip(ttype)
        return out


@dataclass
class PageInfo:
    """One page inside a column chunk (offsets relative to chunk bytes)."""

    page_type: int
    num_values: int
    encoding: int
    payload_off: int              # start of (possibly compressed) payload
    compressed_size: int
    uncompressed_size: int
    # v2-only: def levels live *outside* the compressed region
    v2_def_bytes: int = 0
    v2_rep_bytes: int = 0
    v2_num_nulls: int = 0
    v2_num_rows: int = 0
    v2_is_compressed: bool = True


@dataclass
class ChunkPages:
    """All pages of one column chunk + the raw chunk bytes."""

    column: str
    physical_type: str            # INT32/INT64/FLOAT/DOUBLE/BOOLEAN/...
    logical_type: str             # pyarrow's logical-type repr ("" if none)
    codec: str                    # UNCOMPRESSED/SNAPPY/...
    max_def: int                  # 0 = required, 1 = flat optional
    max_rep: int
    num_values: int
    data: bytes                   # raw chunk bytes (headers + payloads)
    dict_page: Optional[PageInfo]
    data_pages: List[PageInfo] = field(default_factory=list)


def parse_page_header(buf: bytes, pos: int) -> Tuple[PageInfo, int]:
    """Parse one PageHeader at `pos`; returns (info, payload_start)."""
    r = _Reader(buf, pos)
    h = r.read_struct()
    ptype = h.get(1)
    uncomp = h.get(2, 0)
    comp = h.get(3, 0)
    if ptype == DATA_PAGE:
        dph = h.get(5) or {}
        info = PageInfo(DATA_PAGE, dph.get(1, 0), dph.get(2, PLAIN),
                        r.pos, comp, uncomp)
    elif ptype == DICTIONARY_PAGE:
        dph = h.get(7) or {}
        info = PageInfo(DICTIONARY_PAGE, dph.get(1, 0),
                        dph.get(2, PLAIN), r.pos, comp, uncomp)
    elif ptype == DATA_PAGE_V2:
        dph = h.get(8) or {}
        info = PageInfo(DATA_PAGE_V2, dph.get(1, 0), dph.get(4, PLAIN),
                        r.pos, comp, uncomp,
                        v2_def_bytes=dph.get(5, 0),
                        v2_rep_bytes=dph.get(6, 0),
                        v2_num_nulls=dph.get(2, 0),
                        v2_num_rows=dph.get(3, 0),
                        v2_is_compressed=dph.get(7, True))
    else:
        # index page etc. — record and let the caller skip it
        info = PageInfo(int(ptype or -1), 0, PLAIN, r.pos, comp, uncomp)
    return info, r.pos


def read_chunk_pages(path: str, row_group: int, col_idx: int,
                    parquet_file: Optional[papq.ParquetFile] = None
                    ) -> ChunkPages:
    """Read one column chunk's raw bytes and index its pages on CPU."""
    _note_walk()
    pf = parquet_file or papq.ParquetFile(path)
    md = pf.metadata
    cc = md.row_group(row_group).column(col_idx)
    start = cc.dictionary_page_offset
    if start is None or (cc.data_page_offset and
                         cc.data_page_offset < start):
        start = cc.data_page_offset
    total = cc.total_compressed_size
    # byte-walk accounting: global counter + tenant ledger with the
    # same n (prefetch threads carry no token and bill unattributed)
    from spark_rapids_tpu.obs import accounting as _acct
    from spark_rapids_tpu.obs import registry as _obsreg
    _obsreg.get_registry().inc("scan.bytesWalked", int(total))
    _acct.charge("scan.bytesWalked", int(total))
    if isinstance(path, (bytes, bytearray, memoryview)):
        # in-memory parquet blob (cached-batch path)
        data = bytes(path[start:start + total])
    else:
        with open(path, "rb") as f:
            f.seek(start)
            data = f.read(total)

    pq_schema = md.schema
    col_schema = pq_schema.column(col_idx)
    max_def = 1 if col_schema.max_definition_level is None else \
        col_schema.max_definition_level
    max_rep = 0 if col_schema.max_repetition_level is None else \
        col_schema.max_repetition_level

    chunk = ChunkPages(
        column=cc.path_in_schema,
        physical_type=cc.physical_type,
        logical_type=str(col_schema.logical_type or ""),
        codec=cc.compression,
        max_def=max_def,
        max_rep=max_rep,
        num_values=cc.num_values,
        data=data,
        dict_page=None,
    )
    pos = 0
    seen = 0
    while pos < len(data) and seen < cc.num_values:
        info, payload_start = parse_page_header(data, pos)
        pos = payload_start + info.compressed_size
        if info.page_type == DICTIONARY_PAGE:
            chunk.dict_page = info
        elif info.page_type in (DATA_PAGE, DATA_PAGE_V2):
            chunk.data_pages.append(info)
            seen += info.num_values
        # anything else (index pages): skip
    return chunk


def decompress(codec: str, payload: bytes, uncompressed_size: int) -> bytes:
    """Host decompression of one page payload (nvcomp-role on host; device
    codecs aren't available on TPU — see SURVEY.md §2h nvcomp row)."""
    codec = codec.upper()
    if codec == "UNCOMPRESSED":
        return payload
    import pyarrow as pa
    return pa.Codec(codec.lower()).decompress(
        payload, decompressed_size=uncompressed_size).to_pybytes()
