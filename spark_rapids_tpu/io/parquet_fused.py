"""Fused multi-row-group Parquet decode: ONE XLA program per scan batch.

The per-column decode path (io/device_parquet.py) issues ~5 device
dispatches and ~4 uploads per column per row group — hundreds per query.
On any runtime that's dispatch overhead; on a tunneled/remote device it
dominates the whole query (measured: r2's q6 bench spent >90% of wall
clock on per-op round trips).  This module is the TPU-first answer to
the reference's one-kernel-per-buffer decode (`Table.readParquet`,
reference: GpuParquetScan.scala:1022 — one libcudf call decodes every
column of the assembled buffer):

  * the HOST walks pages for every column of every row group in the
    batch (O(pages+runs), reusing device_parquet.plan_chunk),
  * all run tables pack into ONE [streams, rcap, 5] int32 matrix, all
    bit-packed regions into ONE uint8 buffer, PLAIN values and
    dictionaries into ONE buffer per wire dtype — ≤8 uploads total,
  * ONE jitted program expands runs, applies definition levels, gathers
    dictionaries and stitches row groups, emitting the whole batch.

Every data-dependent number (row counts, buffer offsets, dictionary
sizes) travels as a traced int32 operand; only power-of-two shape
buckets are static — so the compile cache hits across files, queries
and processes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _bucket_strlen, bucket_rows,
                                             from_arrow)
from spark_rapids_tpu.io import parquet_meta as pm
from spark_rapids_tpu.io.device_parquet import (ChunkPlan, UnsupportedChunk,
                                                _cast_one, _pad_np,
                                                leaf_index_map, plan_chunk)
from spark_rapids_tpu.plan.logical import Schema

_END_SENTINEL = np.int32(1 << 30)


# ---------------------------------------------------------------------------
# Host assembly
# ---------------------------------------------------------------------------

@dataclass
class _SegSpec:
    """Static decode recipe for one (column, row-group) segment.

    Only bucketed shapes live here (it is part of the kernel cache key);
    exact offsets/counts are traced operands in the meta vector."""
    mode: str             # 'dict' | 'dict_str' | 'plain' | 'bool' | 'null'
    nullable: bool
    def_stream: int = -1  # index into runs_mat, -1 = none
    val_stream: int = -1
    plain_key: str = ""   # wire dtype of the plain buffer
    dcap: int = 0         # bucketed dictionary rows
    dlen: int = 0         # bucketed string dict max_len
    # traced meta slots (positions in the meta vector)
    m_plain_off: int = -1
    m_dict_off: int = -1
    m_dict_size: int = -1
    m_dlen_off: int = -1


@dataclass
class _FusedPlan:
    """Everything decode_row_groups_fused assembled on host."""
    key: Tuple            # kernel cache key (static spec)
    specs: List[List[_SegSpec]]      # [col][rg]
    out_dtypes: List[dt.DType]
    names: List[str]
    arrays: Dict[str, np.ndarray]    # upload set
    n_rows: List[int]
    cap: int
    vcap: int


def _runs_to_rows(runs, packed_off_bits: int, rcap: int) -> np.ndarray:
    """One stream's RunTable -> [rcap, 5] int32 row block."""
    r = len(runs.counts)
    mat = np.full((rcap, 5), 0, dtype=np.int32)
    ends = np.cumsum(np.asarray(runs.counts, dtype=np.int64))
    if np.any(ends > (1 << 30)):
        raise UnsupportedChunk("stream too long for fused decode")
    mat[:, 0] = _END_SENTINEL
    mat[:r, 0] = ends.astype(np.int32)
    mat[:r, 1] = np.asarray(runs.is_rle, dtype=np.int32)
    mat[:r, 2] = np.asarray(runs.values, dtype=np.int32)
    bases = np.asarray(runs.bit_bases, dtype=np.int64) + packed_off_bits
    if np.any(bases + 32 > (np.int64(1) << 31)):
        raise UnsupportedChunk("packed buffer too long for fused decode")
    mat[:r, 3] = bases.astype(np.int32)
    mat[:r, 4] = np.asarray(runs.widths, dtype=np.int32)
    return mat


def assemble(plans: List[List[Optional[ChunkPlan]]],
             out_dtypes: List[dt.DType], names: List[str],
             n_rows: List[int]) -> _FusedPlan:
    """Pack every segment's host structures into the fused upload set.

    plans[col][rg] is a ChunkPlan, or None for a column missing from
    that file (emitted as all-null rows for that segment)."""
    K = len(n_rows)
    streams: List[Tuple[Any, bytes]] = []   # (RunTable, packed)
    plain_parts: Dict[str, List[np.ndarray]] = {}
    plain_sizes: Dict[str, int] = {}
    dict_parts: Dict[str, List[np.ndarray]] = {}
    dict_sizes: Dict[str, int] = {}
    meta: List[int] = []
    specs: List[List[_SegSpec]] = []

    def add_meta(v: int) -> int:
        meta.append(int(v))
        return len(meta) - 1

    for ci, col_plans in enumerate(plans):
        col_specs: List[_SegSpec] = []
        for r, p in enumerate(col_plans):
            if p is None:
                col_specs.append(_SegSpec(mode="null", nullable=True))
                continue
            s = _SegSpec(mode=p.mode, nullable=p.nullable)
            if p.nullable:
                s.def_stream = len(streams)
                streams.append((p.def_runs, p.def_packed))
            if p.mode in ("dict", "dict_str", "bool"):
                s.val_stream = len(streams)
                streams.append((p.val_runs, p.val_packed))
            if p.mode == "plain":
                key = str(p.plain_np.dtype)
                s.plain_key = key
                off = plain_sizes.get(key, 0)
                s.m_plain_off = add_meta(off)
                plain_parts.setdefault(key, []).append(p.plain_np)
                plain_sizes[key] = off + p.plain_np.shape[0]
            if p.mode == "dict":
                d = p.dict_np
                key = str(d.dtype)
                s.plain_key = key
                off = dict_sizes.get(key, 0)
                s.m_dict_off = add_meta(off)
                s.m_dict_size = add_meta(d.shape[0])
                s.dcap = bucket_rows(d.shape[0], 8)
                dict_parts.setdefault(key, []).append(d)
                dict_sizes[key] = off + d.shape[0]
            if p.mode == "dict_str":
                mat, lens = p.dict_np, p.dict_lens
                s.dlen = _bucket_strlen(mat.shape[1])
                s.dcap = bucket_rows(mat.shape[0], 8)
                off = dict_sizes.get("u8str", 0)
                s.m_dict_off = add_meta(off)
                s.m_dict_size = add_meta(mat.shape[0])
                dict_parts.setdefault("u8str", []).append(
                    mat.reshape(-1).astype(np.uint8))
                dict_sizes["u8str"] = off + mat.size
                loff = dict_sizes.get("strlens", 0)
                s.m_dlen_off = add_meta(loff)
                dict_parts.setdefault("strlens", []).append(
                    lens.astype(np.int32))
                dict_sizes["strlens"] = loff + lens.shape[0]
                # record the un-bucketed row stride for the flat matrix
                s.plain_key = str(mat.shape[1])  # exact L (static)
            col_specs.append(s)
        specs.append(col_specs)

    rcap = bucket_rows(max((len(rt.counts) for rt, _ in streams),
                           default=1), 8)
    S = max(len(streams), 1)
    runs_mat = np.full((S, rcap, 5), 0, dtype=np.int32)
    runs_mat[:, :, 0] = _END_SENTINEL
    packed_chunks: List[bytes] = []
    packed_off = 0
    for si, (rt, pk) in enumerate(streams):
        runs_mat[si] = _runs_to_rows(rt, packed_off * 8, rcap)
        packed_chunks.append(pk)
        packed_off += len(pk)
    packed = b"".join(packed_chunks)
    bcap = bucket_rows(max(len(packed), 4), 64)

    arrays: Dict[str, np.ndarray] = {
        "runs": runs_mat,
        "packed": _pad_np(np.frombuffer(packed, dtype=np.uint8), bcap),
        "nrows": np.asarray(n_rows, dtype=np.int32),
        "meta": np.asarray(meta or [0], dtype=np.int32),
    }
    vcap = bucket_rows(max(max(n_rows, default=1), 1))
    for key, parts in plain_parts.items():
        buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
        # slack so a dynamic_slice of size vcap never walks off the end
        arrays["plain_" + key] = _pad_np(
            buf, bucket_rows(buf.shape[0] + vcap, 64))
    for key, parts in dict_parts.items():
        buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
        pad = max((s.dcap * max(s.dlen, 1)
                   for row in specs for s in row), default=64)
        arrays["dict_" + key] = _pad_np(
            buf, bucket_rows(buf.shape[0] + pad, 64))

    total = sum(n_rows)
    cap = bucket_rows(max(total, 1))
    key = ("pq_fused", tuple(names),
           tuple(d.name for d in out_dtypes), K, rcap, bcap, vcap, cap,
           tuple((a, arrays[a].shape, str(arrays[a].dtype))
                 for a in sorted(arrays)),
           tuple(tuple((s.mode, s.nullable, s.def_stream, s.val_stream,
                        s.plain_key, s.dcap, s.dlen, s.m_plain_off,
                        s.m_dict_off, s.m_dict_size, s.m_dlen_off)
                       for s in row) for row in specs))
    return _FusedPlan(key=key, specs=specs, out_dtypes=out_dtypes,
                      names=names, arrays=arrays, n_rows=list(n_rows),
                      cap=cap, vcap=vcap)


# ---------------------------------------------------------------------------
# Device kernel (traced once per _FusedPlan.key)
# ---------------------------------------------------------------------------

def _expand_stream(runs_row: jnp.ndarray, packed: jnp.ndarray,
                   vcap: int) -> jnp.ndarray:
    """Expand one stream's [rcap, 5] runs to [vcap] uint32 values —
    delegates to the single shared bit-unpack implementation."""
    from spark_rapids_tpu.io.device_parquet import expand_runs_matrix
    return expand_runs_matrix(runs_row, packed, vcap)


def _def_apply(levels: Optional[jnp.ndarray], values: jnp.ndarray,
               n_r: jnp.ndarray, vcap: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Definition levels -> (per-row values, validity) for one segment."""
    row = jnp.arange(vcap, dtype=jnp.int32)
    if levels is None:
        valid = row < n_r
        return values, valid
    valid = (levels == 1) & (row < n_r)
    vidx = jnp.cumsum(valid.astype(jnp.int32)) - 1
    vidx = jnp.clip(vidx, 0, values.shape[0] - 1)
    return jnp.take(values, vidx, axis=0), valid


def _make_kernel(plan_key: Tuple, specs, out_dtypes, names, K: int,
                 rcap: int, vcap: int, cap: int):
    """Build the fused decode program for one static spec.

    Compile-size discipline: segments (column x row-group) are grouped
    by (mode, nullable, wire dtype, string stride) and each group is
    processed with ONE vmapped subgraph — so the HLO scales with the
    number of distinct segment SHAPES (a handful), not with columns x
    row groups (which made cold compiles take minutes)."""

    # group segments by identical processing recipe
    groups: Dict[Tuple, List[Tuple[int, int]]] = {}
    for ci, col_specs in enumerate(specs):
        for r, s in enumerate(col_specs):
            if s.mode == "null":
                continue
            sig = (s.mode, s.nullable, s.plain_key, s.dlen)
            groups.setdefault(sig, []).append((ci, r))

    def kernel(arrays: Dict[str, jnp.ndarray]):
        runs = arrays["runs"]
        packed = arrays["packed"]
        nrows = arrays["nrows"]
        meta = arrays["meta"]
        # ONE batched expansion for every stream (def levels, dict
        # indices, bool bits)
        expanded = jax.vmap(_expand_stream, in_axes=(0, None, None))(
            runs, packed, vcap)                      # [S, vcap] uint32
        cum = jnp.cumsum(nrows)
        total = cum[-1]
        out_row = jnp.arange(cap, dtype=jnp.int32)
        seg_of_row = jnp.searchsorted(cum, out_row, side="right")
        seg_of_row = jnp.clip(seg_of_row, 0, K - 1)
        prev = jnp.where(seg_of_row > 0,
                         jnp.take(cum, seg_of_row - 1), 0)
        local = out_row - prev
        flat_idx = seg_of_row * vcap + local
        row_exists = out_row < total

        # -- pass 1: one vmapped subgraph per group ------------------------
        # group results: (ci, r) -> (data, valid[, lens])
        seg_out: Dict[Tuple[int, int], Tuple] = {}
        for sig, members in groups.items():
            mode, nullable, pkey, dlen = sig
            s0 = specs[members[0][0]][members[0][1]]
            specs_m = [specs[ci][r] for ci, r in members]
            n_m = nrows[jnp.asarray([r for _, r in members])]
            if nullable:
                lv_m = expanded[
                    jnp.asarray([s.def_stream for s in specs_m])
                ].astype(jnp.int32)
            else:
                lv_m = None

            if mode in ("dict", "dict_str"):
                idx_m = expanded[
                    jnp.asarray([s.val_stream for s in specs_m])
                ].astype(jnp.int32)
                doff_m = meta[jnp.asarray(
                    [s.m_dict_off for s in specs_m])]
                dsize_m = meta[jnp.asarray(
                    [s.m_dict_size for s in specs_m])]
                if mode == "dict":
                    dbuf = arrays["dict_" + pkey]

                    def one_dict(idx, lv, n_r, doff, dsize):
                        idx, valid = _def_apply(lv, idx, n_r, vcap)
                        idx = jnp.clip(idx, 0,
                                       jnp.maximum(dsize - 1, 0))
                        vals = jnp.take(dbuf, doff + idx)
                        return jnp.where(valid, vals, 0), valid

                    in_ax = (0, 0 if nullable else None, 0, 0, 0)
                    data_m, valid_m = jax.vmap(
                        one_dict, in_axes=in_ax)(idx_m, lv_m, n_m,
                                                 doff_m, dsize_m)
                    for (ci, r), d, v in zip(members, data_m, valid_m):
                        seg_out[(ci, r)] = (d, v)
                else:
                    L = int(pkey)
                    dbuf = arrays["dict_u8str"]
                    lbuf = arrays["dict_strlens"]
                    loff_m = meta[jnp.asarray(
                        [s.m_dlen_off for s in specs_m])]

                    def one_str(idx, lv, n_r, doff, dsize, loff):
                        idx, valid = _def_apply(lv, idx, n_r, vcap)
                        idx = jnp.clip(idx, 0,
                                       jnp.maximum(dsize - 1, 0))
                        byte_idx = ((doff + idx * L)[:, None] +
                                    jnp.arange(dlen)[None, :])
                        in_range = jnp.arange(dlen)[None, :] < L
                        mat = jnp.take(dbuf,
                                       jnp.clip(byte_idx, 0,
                                                dbuf.shape[0] - 1))
                        mat = jnp.where(valid[:, None] & in_range,
                                        mat, 0)
                        lens = jnp.take(lbuf, loff + idx)
                        return (mat, jnp.where(valid, lens,
                                               0).astype(jnp.int32),
                                valid)

                    in_ax = (0, 0 if nullable else None, 0, 0, 0, 0)
                    mat_m, lens_m, valid_m = jax.vmap(
                        one_str, in_axes=in_ax)(idx_m, lv_m, n_m,
                                                doff_m, dsize_m,
                                                loff_m)
                    for (ci, r), d, ln, v in zip(members, mat_m,
                                                 lens_m, valid_m):
                        seg_out[(ci, r)] = (d, v, ln)
            elif mode == "bool":
                bits_m = expanded[
                    jnp.asarray([s.val_stream for s in specs_m])
                ].astype(jnp.bool_)

                def one_bool(bits, lv, n_r):
                    data, valid = _def_apply(lv, bits, n_r, vcap)
                    return data & valid, valid

                data_m, valid_m = jax.vmap(
                    one_bool, in_axes=(0, 0 if nullable else None, 0)
                )(bits_m, lv_m, n_m)
                for (ci, r), d, v in zip(members, data_m, valid_m):
                    seg_out[(ci, r)] = (d, v)
            else:  # plain
                pbuf = arrays["plain_" + pkey]
                off_m = meta[jnp.asarray(
                    [s.m_plain_off for s in specs_m])]

                def one_plain(off, lv, n_r):
                    vals = jax.lax.dynamic_slice(pbuf, (off,), (vcap,))
                    data, valid = _def_apply(lv, vals, n_r, vcap)
                    return jnp.where(valid, data, 0), valid

                data_m, valid_m = jax.vmap(
                    one_plain, in_axes=(0, 0 if nullable else None, 0)
                )(off_m, lv_m, n_m)
                for (ci, r), d, v in zip(members, data_m, valid_m):
                    seg_out[(ci, r)] = (d, v)

        # -- pass 2: stitch row groups per column --------------------------
        cols: List[DeviceColumn] = []
        for ci, col_specs in enumerate(specs):
            odt = out_dtypes[ci]
            np_t = odt.to_np() if not odt.is_string else None
            col_L = max((s.dlen for s in col_specs), default=1) \
                if odt.is_string else 0
            seg_data, seg_valid, seg_lens = [], [], []
            for r, s in enumerate(col_specs):
                if s.mode == "null":
                    if odt.is_string:
                        seg_data.append(jnp.zeros((vcap, col_L),
                                                  dtype=jnp.uint8))
                        seg_lens.append(jnp.zeros((vcap,),
                                                  dtype=jnp.int32))
                    else:
                        seg_data.append(jnp.zeros((vcap,), dtype=np_t))
                    seg_valid.append(jnp.zeros((vcap,),
                                               dtype=jnp.bool_))
                    continue
                out = seg_out[(ci, r)]
                if odt.is_string:
                    d = out[0]
                    if d.shape[1] < col_L:
                        d = jnp.pad(d, ((0, 0), (0, col_L - d.shape[1])))
                    seg_data.append(d)
                    seg_valid.append(out[1])
                    seg_lens.append(out[2])
                else:
                    seg_data.append(out[0].astype(np_t))
                    seg_valid.append(out[1])

            stacked = jnp.stack(seg_data)          # [K, vcap(, L)]
            stackedv = jnp.stack(seg_valid)        # [K, vcap]
            if odt.is_string:
                data = jnp.take(stacked.reshape(K * vcap, col_L),
                                flat_idx, axis=0)
                data = jnp.where(row_exists[:, None], data, 0)
                lens = jnp.take(jnp.stack(seg_lens).reshape(-1),
                                flat_idx)
                lens = jnp.where(row_exists, lens, 0)
                valid = jnp.take(stackedv.reshape(-1),
                                 flat_idx) & row_exists
                cols.append(DeviceColumn(odt, data, valid, lens))
            else:
                data = jnp.take(stacked.reshape(K * vcap), flat_idx)
                data = jnp.where(row_exists, data,
                                 jnp.zeros((), dtype=np_t))
                valid = jnp.take(stackedv.reshape(-1),
                                 flat_idx) & row_exists
                cols.append(DeviceColumn(odt, data, valid))
        return tuple(cols), total

    return kernel


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------

def _fused_list_column(sources, f, n_rows) -> Optional[DeviceColumn]:
    """Device list decode per row group + device concat for the fused
    batch; None -> host fallback."""
    from spark_rapids_tpu.columnar.batch import concat_batches
    from spark_rapids_tpu.io.device_parquet import decode_list_chunk
    try:
        per = []
        for (pf, path, rg), nr in zip(sources, n_rows):
            leaf_of = leaf_index_map(pf)
            if f.name not in leaf_of:
                return None
            chunk = pm.read_chunk_pages(path, rg, leaf_of[f.name],
                                        parquet_file=pf)
            col = decode_list_chunk(chunk, f.dtype,
                                    bucket_rows(max(nr, 1)),
                                    f.nullable)
            per.append(DeviceBatch([f.name], [col], nr))
        merged = concat_batches(per)
        return merged.columns[0]
    except Exception:
        return None


def decode_row_groups_fused(sources: Sequence[Tuple[Any, str, int]],
                            schema: Schema,
                            columns: Optional[List[str]] = None
                            ) -> Tuple[DeviceBatch, List[str]]:
    """Decode several (parquet_file, path, row_group) sources into ONE
    DeviceBatch with one fused kernel (+ a host-decoded column merge for
    anything the device path can't cover).

    Returns (batch, fallback_column_names)."""
    wanted = columns or [f.name for f in schema.fields]
    out_dtypes = [schema.field(c).dtype for c in wanted]
    n_rows = [pf.metadata.row_group(rg).num_rows
              for pf, _, rg in sources]

    plans: List[List[Optional[ChunkPlan]]] = []
    fallbacks: List[str] = []
    list_cols: Dict[str, DeviceColumn] = {}
    for c in wanted:
        f = schema.field(c)
        if f.dtype.is_list:
            # list columns decode per row group via the dedicated
            # rep/def path and concatenate on device
            col = _fused_list_column(sources, f, n_rows)
            if col is not None:
                list_cols[c] = col
            else:
                fallbacks.append(c)
            plans.append(None)
            continue
        col_plans: List[Optional[ChunkPlan]] = []
        try:
            for pf, path, rg in sources:
                leaf_of = leaf_index_map(pf)
                if c not in leaf_of:
                    col_plans.append(None)
                    continue
                chunk = pm.read_chunk_pages(path, rg, leaf_of[c],
                                            parquet_file=pf)
                col_plans.append(plan_chunk(chunk, f.dtype))
        except Exception:
            fallbacks.append(c)
            col_plans = None
        plans.append(col_plans)

    dev_cols = [c for c, p in zip(wanted, plans) if p is not None]
    dev_dtypes = [d for d, p in zip(out_dtypes, plans) if p is not None]
    dev_plans = [p for p in plans if p is not None]

    total = sum(n_rows)
    cap = bucket_rows(max(total, 1))

    cols_by_name: Dict[str, DeviceColumn] = dict(list_cols)
    if dev_plans:
        fp = assemble(dev_plans, dev_dtypes, dev_cols, n_rows)
        from spark_rapids_tpu.exec import kernel_cache as kc
        kern = kc.get_kernel(
            fp.key,
            lambda: _make_kernel(fp.key, fp.specs, fp.out_dtypes,
                                 fp.names, len(fp.n_rows),
                                 fp.arrays["runs"].shape[1], fp.vcap,
                                 fp.cap))
        dev_arrays = {k: jnp.asarray(v) for k, v in fp.arrays.items()}
        out_cols, _ = kern(dev_arrays)
        for name, col in zip(dev_cols, out_cols):
            cols_by_name[name] = col

    if fallbacks:
        tables = []
        for pf, path, rg in sources:
            leaf_of2 = leaf_index_map(pf)
            present = [c for c in fallbacks if c in leaf_of2]
            t = pf.read_row_group(rg, columns=present) if present \
                else pa.table({})
            arrs = []
            for c in fallbacks:
                f = schema.field(c)
                if c in present:
                    arrs.append(_cast_one(t.select([c]), f).column(0))
                else:
                    arrs.append(pa.nulls(t.num_rows if present
                                         else pf.metadata.row_group(rg)
                                         .num_rows,
                                         type=f.dtype.to_arrow()))
            tables.append(pa.Table.from_arrays(
                arrs, names=list(fallbacks)))
        merged = pa.concat_tables(tables)
        fb = from_arrow(merged, capacity=cap)
        for name, col in zip(fb.names, fb.columns):
            cols_by_name[name] = col

    out = DeviceBatch(
        wanted, [cols_by_name[c] for c in wanted], total)
    return out, fallbacks
