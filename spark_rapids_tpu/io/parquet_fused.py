"""Fused multi-row-group Parquet decode: ONE XLA program per scan batch.

The per-column decode path (io/device_parquet.py) issues ~5 device
dispatches and ~4 uploads per column per row group — hundreds per query.
On any runtime that's dispatch overhead; on a tunneled/remote device it
dominates the whole query.  This module is the TPU-first answer to the
reference's one-kernel-per-buffer decode (`Table.readParquet`,
reference: GpuParquetScan.scala:1022 — one libcudf call decodes every
column of the assembled buffer):

  * the HOST walks pages for every column of every row group in the
    batch (O(pages+runs), reusing device_parquet.plan_chunk),
  * ONE jitted program expands runs, applies definition levels, gathers
    dictionaries and stitches row groups, emitting the whole batch.

The round-4 kernel is a DENSE PHASE DECOMPOSITION — TPU gathers run at
~90M lookups/s while dense vector ops stream at HBM bandwidth, so every
per-element gather the round-3 kernel did (4-byte window reads + ~5
run-metadata takes per element) is reformulated as dense work:

  phase 0  bit-unpack: all bit-packed regions of one width concatenate
           into one byte buffer; unpack is a reshape + shift/mask +
           weighted-sum — O(bits) elementwise, ZERO gathers.  The
           per-width value streams concatenate into ONE dense value
           array (`dense_all`).
  phase 1  run expansion:
           - streams with few runs (the common case: pyarrow emits ~1
             hybrid run per page) unroll as `dynamic_slice`s of
             dense_all masked per run — dense copies, ZERO gathers;
           - many-run streams use delta-scatter + cumsum to broadcast
             per-run metadata (A = value-base − run-start, C =
             value·2+is_rle) to elements, then ONE gather/element into
             dense_all.
  phase 2  definition levels: chunks whose def stream is all-valid
           (no nulls — detected on host from the run table) skip level
           expansion AND the null-scatter compaction entirely; only
           truly-nullable segments pay the cumsum + take.
  phase 3  dictionary gather — the one irreducible gather (the analog
           of libcudf's dictionary decode).
  phase 4  row-group stitching: sequential `dynamic_update_slice`
           writes per segment (dense copies; segment k's padding tail
           is overwritten by segment k+1's write) replace the round-3
           per-column stitch gather.

Every data-dependent number (row counts, buffer offsets, dictionary
sizes) travels as a traced int32 operand; only power-of-two shape
buckets are static — so the compile cache hits across files, queries
and processes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _VBIT_BUCKETS, _bucket_strlen,
                                             bits_for_range, bucket_rows,
                                             from_arrow)
from spark_rapids_tpu.io import parquet_meta as pm
from spark_rapids_tpu.io.device_parquet import (ChunkPlan, RunTable,
                                                UnsupportedChunk, _cast_one,
                                                _pad_np, leaf_map,
                                                plan_chunk)
from spark_rapids_tpu.plan.logical import Schema

_BIG = np.int32(1 << 30)
# streams with at most this many hybrid runs expand as unrolled masked
# dynamic_slices (dense); above it, the delta-scatter+cumsum general
# path with one gather/element takes over
_SLICE_MAX_RUNS = 8


# ---------------------------------------------------------------------------
# Host assembly
# ---------------------------------------------------------------------------

@dataclass
class _SegSpec:
    """Static decode recipe for one (column, row-group) segment.

    Only bucketed shapes live here (it is part of the kernel cache key);
    exact offsets/counts are traced operands in the meta vector."""
    mode: str             # 'dict' | 'dict_str' | 'plain' | 'bool' | 'null'
    nullable: bool        # EFFECTIVE: False when def levels are all-valid
    def_stream: int = -1  # global stream index, -1 = none
    val_stream: int = -1
    plain_key: str = ""   # wire dtype of the plain buffer
    dcap: int = 0         # bucketed dictionary rows
    dlen: int = 0         # bucketed string dict max_len
    # traced meta slots (positions in the meta vector)
    m_plain_off: int = -1
    m_dict_off: int = -1
    m_dict_size: int = -1
    m_dlen_off: int = -1
    # kernel-2 deferral: keep this dict segment as CODES through
    # stitching; the dictionary gather runs predicated on the pushed
    # filter's mask AFTER condition evaluation (kernels/filter_decode)
    defer: bool = False


@dataclass
class _FusedPlan:
    """Everything decode_row_groups_fused assembled on host."""
    key: Tuple            # kernel cache key (static spec)
    specs: List[List[_SegSpec]]      # [col][rg]
    out_dtypes: List[dt.DType]
    names: List[str]
    arrays: Dict[str, np.ndarray]    # upload set
    n_rows: List[int]
    cap: int
    vcap: int
    # per global stream: ('slice', row in sruns) | ('general', row in gruns)
    stream_path: List[Tuple[str, int]] = field(default_factory=list)
    nslcap: int = 1       # unroll count of the slice path
    widths: Tuple[Tuple[int, int], ...] = ()   # (width, Ncap) sorted
    # per-column static value-range hint (DeviceColumn.vbits) computed
    # from host-known dictionary pages / PLAIN buffers; None = unknown
    col_vbits: Tuple[Optional[int], ...] = ()
    # kernel backend for phase 0 (dense unpack) and the kernel-2
    # deferred dictionary gather; folded into ``key``
    backend: str = "xla"
    # tile budget stamped at assemble time (pallas only; also in
    # ``key``): _make_kernel's tiled gathers read THIS value, never
    # the live process knob, so a concurrent session reconfiguring
    # kernel.pallas.tileBytes between assemble and first trace cannot
    # build a kernel that disagrees with the eligibility gate or key
    tile_bytes: Optional[int] = None
    # kernel 2: (condition expr, scan output-name order, deferred
    # column names) when the pushed filter is active, else None
    pushed: Optional[Tuple] = None


def _column_vbits(out_dtype: dt.DType,
                  col_plans: List[Optional[ChunkPlan]]) -> Optional[int]:
    """Host-known value range of one fused column: dictionary pages
    hold every referenceable value, PLAIN buffers hold every stored
    value — min/max over them bounds all VALID decoded values (null
    slots store nothing in either encoding).  The result is re-bucketed
    to the shape-erased ABI's coarse hint table (kernel_abi) before it
    reaches the pq_fused6 kernel key and the decoded columns — precise
    per-file ranges were minting one scan program per value range."""
    if out_dtype.is_string or out_dtype.is_floating or out_dtype.is_bool:
        return None
    if not np.issubdtype(np.dtype(out_dtype.to_np()), np.integer):
        return None
    lo, hi = 0, 0
    seen = False
    for p in col_plans:
        if p is None or p.mode == "null":
            continue   # all-null segment: no value constraint
        if p.mode == "dict":
            buf = p.dict_np
        elif p.mode == "plain":
            buf = p.plain_np
        else:
            return None
        if buf is None or not np.issubdtype(buf.dtype, np.integer):
            return None
        if buf.shape[0]:
            lo = min(lo, int(buf.min())) if seen else int(buf.min())
            hi = max(hi, int(buf.max())) if seen else int(buf.max())
            seen = True
    from spark_rapids_tpu.exec import kernel_abi
    if not seen:
        return kernel_abi.bucket_vbits(_VBIT_BUCKETS[0])
    return kernel_abi.bucket_vbits(bits_for_range(lo, hi))


def _all_valid(runs: RunTable) -> bool:
    """True when a def-level stream encodes zero nulls (every run is an
    RLE run of 1) — pyarrow writes exactly this for null-free pages."""
    return all(r and v == 1
               for r, v in zip(runs.is_rle, runs.values))


def _stream_quads(runs: RunTable, packed: bytes,
                  add_region) -> List[Tuple[int, int, int, int]]:
    """Per-run (start, end, A, C) for one stream.

    A = dense_all index of the run's first value minus the run's start
    (so element i of the run reads dense_all[A + i]); C packs the RLE
    value and flag as value*2+is_rle.  ``add_region(w, bytes) -> value
    offset`` appends a bit-packed byte region to the width-w buffer and
    returns its value offset within that buffer (resolved to a global
    dense_all offset later via a per-width base)."""
    n = len(runs.counts)
    bp = [i for i in range(n) if not runs.is_rle[i]]
    region_end = {}
    for j, i in enumerate(bp):
        b1 = runs.bit_bases[bp[j + 1]] // 8 if j + 1 < len(bp) \
            else len(packed)
        region_end[i] = b1
    quads = []
    pos = 0
    for i in range(n):
        c = runs.counts[i]
        start, end = pos, pos + c
        pos = end
        if runs.is_rle[i]:
            # A is irrelevant for RLE elements; carry 0 markers — the
            # delta chain re-telescopes through whatever value we pick,
            # and the slice path never reads A when C's flag is set
            quads.append((start, end, None, (runs.values[i] << 1) | 1))
        else:
            w = runs.widths[i]
            b0 = runs.bit_bases[i] // 8
            off = add_region(w, packed[b0:region_end[i]])
            quads.append((start, end, (w, off - start), 0))
    return quads


def assemble(plans: List[List[Optional[ChunkPlan]]],
             out_dtypes: List[dt.DType], names: List[str],
             n_rows: List[int], backend: str = "xla",
             pushed_filter=None,
             scan_names: Optional[List[str]] = None) -> _FusedPlan:
    """Pack every segment's host structures into the fused upload set.

    plans[col][rg] is a ChunkPlan, or None for a column missing from
    that file (emitted as all-null rows for that segment).

    ``backend`` selects the phase-0 unpack kernel (kernels/decode.py).
    ``pushed_filter`` (with ``scan_names``, the scan's full output-name
    order the condition's ordinals index) arms kernel 2: int-dictionary
    columns NOT referenced by the condition defer their dictionary
    gather until after the mask is known — per-column fallback reasons
    land in ``kernel.backend.pallas.fallbacks.scan.filterDecode.*``."""
    from spark_rapids_tpu.kernels import backend as kb
    from spark_rapids_tpu.kernels import filter_decode as kfd
    K = len(n_rows)
    vcap = bucket_rows(max(max(n_rows, default=1), 1))
    total = sum(n_rows)
    cap = bucket_rows(max(total, 1))

    # -- kernel-2 deferral candidates (decided before specs build) ----
    defer_cols: set = set()
    if pushed_filter is not None and backend == kb.PALLAS and \
            kb.pallas_available():
        from spark_rapids_tpu.expr import ir as _ir
        ref_names = {scan_names[b.ordinal] for b in _ir.collect(
            pushed_filter, lambda e: isinstance(e, _ir.BoundReference))}
        for ci, col_plans in enumerate(plans):
            modes = {p.mode for p in col_plans if p is not None}
            # int ('dict') and STRING ('dict_str') dictionary columns
            # both defer; mixed-mode columns decode eagerly
            if modes not in ({"dict"}, {"dict_str"}):
                continue
            if names[ci] in ref_names:
                kb.fallback("scan.filterDecode", "condition_column")
                continue
            if modes == {"dict"}:
                # every segment's dictionary must live in the SAME
                # wire-dtype buffer: phase 5 runs ONE gather over one
                # buffer, and doff offsets from a different buffer
                # would silently read the wrong dictionary (schema-
                # evolved multi-file groups can mix int32/int64 dict
                # pages per column).  String dictionaries are immune:
                # all of them share the one u8 matrix buffer and the
                # per-segment stride is static in the stitched codes.
                pkeys = {str(p.dict_np.dtype) for p in col_plans
                         if p is not None}
                if len(pkeys) != 1:
                    kb.fallback("scan.filterDecode", "mixed_dict_dtypes")
                    continue
            defer_cols.add(ci)
        if not defer_cols:
            kb.fallback("scan.filterDecode", "no_dict_columns")

    width_bytes: Dict[int, List[bytes]] = {}
    width_vals: Dict[int, int] = {}

    def add_region(w: int, b: bytes) -> int:
        off = width_vals.get(w, 0)
        width_bytes.setdefault(w, []).append(b)
        width_vals[w] = off + len(b) * 8 // w
        return off

    stream_quads: List[List[Tuple]] = []
    meta: List[int] = []
    specs: List[List[_SegSpec]] = []

    def add_meta(v: int) -> int:
        meta.append(int(v))
        return len(meta) - 1

    plain_parts: Dict[str, List[np.ndarray]] = {}
    plain_sizes: Dict[str, int] = {}
    dict_parts: Dict[str, List[np.ndarray]] = {}
    dict_sizes: Dict[str, int] = {}

    for ci, col_plans in enumerate(plans):
        col_specs: List[_SegSpec] = []
        for r, p in enumerate(col_plans):
            if p is None:
                col_specs.append(_SegSpec(mode="null", nullable=True))
                continue
            nullable = p.nullable and not _all_valid(p.def_runs)
            s = _SegSpec(mode=p.mode, nullable=nullable,
                         defer=(ci in defer_cols and
                                p.mode in ("dict", "dict_str")))
            if nullable:
                s.def_stream = len(stream_quads)
                stream_quads.append(_stream_quads(
                    p.def_runs, p.def_packed, add_region))
            if p.mode in ("dict", "dict_str", "bool"):
                s.val_stream = len(stream_quads)
                stream_quads.append(_stream_quads(
                    p.val_runs, p.val_packed, add_region))
            if p.mode == "plain":
                key = str(p.plain_np.dtype)
                s.plain_key = key
                off = plain_sizes.get(key, 0)
                s.m_plain_off = add_meta(off)
                plain_parts.setdefault(key, []).append(p.plain_np)
                plain_sizes[key] = off + p.plain_np.shape[0]
            if p.mode == "dict":
                d = p.dict_np
                key = str(d.dtype)
                s.plain_key = key
                off = dict_sizes.get(key, 0)
                s.m_dict_off = add_meta(off)
                s.m_dict_size = add_meta(d.shape[0])
                s.dcap = bucket_rows(d.shape[0], 8)
                dict_parts.setdefault(key, []).append(d)
                dict_sizes[key] = off + d.shape[0]
            if p.mode == "dict_str":
                mat, lens = p.dict_np, p.dict_lens
                s.dlen = _bucket_strlen(mat.shape[1])
                s.dcap = bucket_rows(mat.shape[0], 8)
                off = dict_sizes.get("u8str", 0)
                s.m_dict_off = add_meta(off)
                s.m_dict_size = add_meta(mat.shape[0])
                dict_parts.setdefault("u8str", []).append(
                    mat.reshape(-1).astype(np.uint8))
                dict_sizes["u8str"] = off + mat.size
                loff = dict_sizes.get("strlens", 0)
                s.m_dlen_off = add_meta(loff)
                dict_parts.setdefault("strlens", []).append(
                    lens.astype(np.int32))
                dict_sizes["strlens"] = loff + lens.shape[0]
                # record the un-bucketed row stride for the flat matrix
                s.plain_key = str(mat.shape[1])  # exact L (static)
            col_specs.append(s)
        specs.append(col_specs)

    # -- width layout: one dense value array, front-padded by vcap so a
    # -- run's slice start (A >= dense_off - start >= vcap - vcap) is
    # -- never negative
    widths = tuple(sorted(width_vals))
    w_caps = []
    dense_off: Dict[int, int] = {}
    off = vcap
    for w in widths:
        ncap = bucket_rows(width_vals[w], 16)   # multiple of 8
        dense_off[w] = off
        off += ncap
        w_caps.append((w, ncap))
    # tail pad of vcap: a run near the end of the last width section has
    # A up to ~dense_len, and its dynamic_slice must fit un-clamped
    dense_len = off + vcap
    if dense_len > int(_BIG):
        raise UnsupportedChunk("packed streams too long for fused decode")

    # -- resolve stream runs to (start, end, A, C) with global A, and
    # -- split into the slice path and the general path
    stream_path: List[Tuple[str, int]] = []
    sruns_rows: List[np.ndarray] = []
    gruns_rows: List[np.ndarray] = []
    max_slice_runs = 1
    max_gen_runs = 1
    resolved: List[List[Tuple[int, int, int, int]]] = []
    for quads in stream_quads:
        rs = []
        a_carry = 0
        for (start, end, pv, c) in quads:
            if pv is not None:
                w, rel = pv
                a_carry = dense_off[w] + rel
            rs.append((start, end, a_carry, c))
        resolved.append(rs)
        if len(rs) <= _SLICE_MAX_RUNS:
            stream_path.append(("slice", len(sruns_rows)))
            sruns_rows.append(None)   # placeholder, filled below
            max_slice_runs = max(max_slice_runs, len(rs) or 1)
        else:
            stream_path.append(("general", len(gruns_rows)))
            gruns_rows.append(None)
            max_gen_runs = max(max_gen_runs, len(rs))

    nslcap = _bucket_strlen(max_slice_runs)
    rcap = bucket_rows(max_gen_runs, 8)
    for si, rs in enumerate(resolved):
        path, idx = stream_path[si]
        if path == "slice":
            mat = np.zeros((nslcap, 4), dtype=np.int32)
            mat[:, 0] = _BIG        # empty range: start == end == BIG
            mat[:, 1] = _BIG
            for r, (st, en, a, c) in enumerate(rs):
                mat[r] = (st, en, a, c)
            sruns_rows[idx] = mat
        else:
            mat = np.zeros((rcap, 3), dtype=np.int32)
            mat[:, 0] = _BIG        # scatter target past vcap: dropped
            prev_a = prev_c = 0
            for r, (st, en, a, c) in enumerate(rs):
                mat[r] = (st, a - prev_a, c - prev_c)
                prev_a, prev_c = a, c
            gruns_rows[idx] = mat

    arrays: Dict[str, np.ndarray] = {
        "nrows": np.asarray(n_rows, dtype=np.int32),
        "meta": np.asarray(meta or [0], dtype=np.int32),
        "sruns": np.stack(sruns_rows) if sruns_rows else
        np.zeros((1, nslcap, 4), dtype=np.int32),
        "gruns": np.stack(gruns_rows) if gruns_rows else
        np.zeros((1, rcap, 3), dtype=np.int32),
    }
    for w, ncap in w_caps:
        buf = np.frombuffer(b"".join(width_bytes[w]), dtype=np.uint8)
        arrays[f"bits_{w}"] = _pad_np(buf, ncap * w // 8)
    for key, parts in plain_parts.items():
        buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
        # slack so a dynamic_slice of size vcap never walks off the end
        arrays["plain_" + key] = _pad_np(
            buf, bucket_rows(buf.shape[0] + vcap, 64))
    for key, parts in dict_parts.items():
        buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
        pad = max((s.dcap * max(s.dlen, 1)
                   for row in specs for s in row), default=64)
        arrays["dict_" + key] = _pad_np(
            buf, bucket_rows(buf.shape[0] + pad, 64))

    # -- kernel-2 shape gate (the old 16 MiB dict_too_large residency
    # -- gate is gone — oversized dictionaries stream tile-wise
    # -- instead of falling back).  ``tileb`` below is the one
    # -- tile-budget read this plan ever makes: gate, cache key, and
    # -- trace-time kernels all share it.
    tileb = kb.tile_bytes() if backend == kb.PALLAS else None
    for ci in sorted(defer_cols):
        s0 = next(s for s in specs[ci] if s.mode in ("dict", "dict_str"))
        if s0.mode == "dict":
            ok, reason = kfd.supported(cap)
        else:
            col_L = max(s.dlen for s in specs[ci]
                        if s.mode == "dict_str")
            ok, reason = kfd.str_supported(cap, col_L,
                                           tile_bytes=tileb)
            if ok:
                # the post-filter lengths recover via the 1-D gather
                ok, reason = kfd.supported(cap)
        if not ok:
            kb.fallback("scan.filterDecode", reason)
            for s in specs[ci]:
                s.defer = False
    defer_names = tuple(
        names[ci] for ci in range(len(specs))
        if any(s.defer for s in specs[ci]))
    pushed = None
    pushed_sig = None
    if defer_names:
        from spark_rapids_tpu.exec import kernel_cache as kc
        pushed = (pushed_filter, tuple(scan_names), defer_names)
        pushed_sig = (kc.expr_sig(pushed_filter), tuple(scan_names),
                      defer_names)

    col_vbits = tuple(_column_vbits(out_dtypes[ci], plans[ci])
                      for ci in range(len(plans)))
    # interpret mode is part of the executable's identity whenever the
    # backend embeds pallas calls: flipping kernel.pallas.interpret
    # in-process must not serve a stale interpreter-mode kernel — and
    # so is the tile budget (``tileb``, read ONCE above), which shapes
    # every embedded kernel's grid
    interp = kb.interpret() if backend == kb.PALLAS else None
    key = ("pq_fused6", tuple(names),
           tuple(d.name for d in out_dtypes), K, vcap, cap,
           nslcap, rcap, tuple(stream_path), tuple(w_caps), col_vbits,
           backend, interp, tileb, pushed_sig,
           tuple((a, arrays[a].shape, str(arrays[a].dtype))
                 for a in sorted(arrays)),
           tuple(tuple((s.mode, s.nullable, s.def_stream, s.val_stream,
                        s.plain_key, s.dcap, s.dlen, s.m_plain_off,
                        s.m_dict_off, s.m_dict_size, s.m_dlen_off,
                        s.defer)
                       for s in row) for row in specs))
    return _FusedPlan(key=key, specs=specs, out_dtypes=out_dtypes,
                      names=names, arrays=arrays, n_rows=list(n_rows),
                      cap=cap, vcap=vcap, stream_path=stream_path,
                      nslcap=nslcap, widths=tuple(w_caps),
                      col_vbits=col_vbits, backend=backend,
                      tile_bytes=tileb, pushed=pushed)


# ---------------------------------------------------------------------------
# Device kernel (traced once per _FusedPlan.key)
# ---------------------------------------------------------------------------

def _unpack_width(bytes_arr: jnp.ndarray, w: int, ncap: int) -> jnp.ndarray:
    """Phase 0: dense bit-unpack of one width's byte buffer to [ncap]
    uint32 values, no gathers.

    Parquet packs LSB-first (bit k of the stream is byte[k>>3]>>(k&7)),
    and hybrid bit-packed runs always hold multiples of 8 values, so
    the byte regions concatenate into one value-aligned bitstring.

    Fast path: 32 consecutive values span exactly w little-endian u32
    words, so reshaping the words to [ncap/32, w] makes every value j
    in a group a STATIC (word, shift) slot — w vectorized shift/or ops
    over [ncap/32] lanes, ~10x less memory traffic than expanding to
    one byte per bit.

    (Implementation moved to kernels/decode.py so the Pallas backend
    shares one definition; this alias is the XLA path.)"""
    from spark_rapids_tpu.kernels.decode import _unpack_xla
    return _unpack_xla(bytes_arr, w, ncap)


def _expand_slice_stream(sruns_row: jnp.ndarray, dense_all: jnp.ndarray,
                         vcap: int, nsl: int) -> jnp.ndarray:
    """Phase 1, few-runs path: per run, one dynamic_slice of dense_all
    (element i of a bit-packed run lives at dense_all[A + i]) masked to
    the run's [start, end) range — dense copies, zero gathers."""
    i = jnp.arange(vcap, dtype=jnp.int32)
    out = jnp.zeros((vcap,), jnp.uint32)
    hi = dense_all.shape[0] - vcap
    for r in range(nsl):
        start, end = sruns_row[r, 0], sruns_row[r, 1]
        a, c = sruns_row[r, 2], sruns_row[r, 3]
        shifted = jax.lax.dynamic_slice(
            dense_all, (jnp.clip(a, 0, hi),), (vcap,))
        vals = jnp.where((c & 1) != 0, (c >> 1).astype(jnp.uint32),
                         shifted)
        out = jnp.where((i >= start) & (i < end), vals, out)
    return out


def _expand_general(gruns: jnp.ndarray, dense_all: jnp.ndarray,
                    vcap: int) -> jnp.ndarray:
    """Phase 1, many-runs path: broadcast per-run metadata to elements
    with delta-scatter + cumsum (A and C step functions), then ONE
    gather/element into dense_all."""
    def one(g):
        starts = jnp.minimum(g[:, 0], vcap)   # padding rows drop
        a = jnp.zeros((vcap,), jnp.int32).at[starts].add(
            g[:, 1], mode="drop")
        c = jnp.zeros((vcap,), jnp.int32).at[starts].add(
            g[:, 2], mode="drop")
        a = jnp.cumsum(a)
        c = jnp.cumsum(c)
        i = jnp.arange(vcap, dtype=jnp.int32)
        idx = jnp.clip(a + i, 0, dense_all.shape[0] - 1)
        vals = jnp.take(dense_all, idx)
        return jnp.where((c & 1) != 0, (c >> 1).astype(jnp.uint32),
                         vals)
    return jax.vmap(one)(gruns)


def _def_apply(levels: Optional[jnp.ndarray], values: jnp.ndarray,
               n_r: jnp.ndarray, vcap: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Definition levels -> (per-row values, validity) for one segment.
    Segments with no nulls pass levels=None and skip the compaction."""
    row = jnp.arange(vcap, dtype=jnp.int32)
    if levels is None:
        valid = row < n_r
        return values, valid
    valid = (levels == 1) & (row < n_r)
    vidx = jnp.cumsum(valid.astype(jnp.int32)) - 1
    vidx = jnp.clip(vidx, 0, values.shape[0] - 1)
    return jnp.take(values, vidx, axis=0), valid


def _make_kernel(fp: _FusedPlan):
    """Build the fused decode program for one static spec.

    Compile-size discipline: segments (column x row-group) are grouped
    by (mode, nullable, wire dtype, string stride) and each group is
    processed with ONE vmapped subgraph — so the HLO scales with the
    number of distinct segment SHAPES (a handful), not with columns x
    row groups."""
    specs = fp.specs
    out_dtypes = fp.out_dtypes
    K = len(fp.n_rows)
    vcap, cap = fp.vcap, fp.cap
    stream_path = fp.stream_path
    nslcap = fp.nslcap
    w_caps = fp.widths

    # group segments by identical processing recipe
    groups: Dict[Tuple, List[Tuple[int, int]]] = {}
    for ci, col_specs in enumerate(specs):
        for r, s in enumerate(col_specs):
            if s.mode == "null":
                continue
            sig = (s.mode, s.nullable, s.plain_key, s.dlen, s.defer)
            groups.setdefault(sig, []).append((ci, r))

    def kernel(arrays: Dict[str, jnp.ndarray]):
        from spark_rapids_tpu.kernels import decode as kdec
        nrows = arrays["nrows"]
        meta = arrays["meta"]

        # -- phase 0: dense per-width unpack -> one value array --------
        dense_parts = [jnp.zeros((vcap,), jnp.uint32)]   # front pad
        for w, ncap in w_caps:
            dense_parts.append(
                kdec.unpack_bits(arrays[f"bits_{w}"], w, ncap,
                                 backend=fp.backend))
        dense_parts.append(jnp.zeros((vcap,), jnp.uint32))  # tail pad
        dense_all = jnp.concatenate(dense_parts)

        # -- phase 1: expand every stream to [vcap] uint32 -------------
        outs: List[Optional[jnp.ndarray]] = [None] * len(stream_path)
        gen_rows = [idx for (p, idx) in stream_path if p == "general"]
        gen_out = _expand_general(arrays["gruns"], dense_all, vcap) \
            if gen_rows else None
        for si, (path, idx) in enumerate(stream_path):
            if path == "slice":
                outs[si] = _expand_slice_stream(
                    arrays["sruns"][idx], dense_all, vcap, nslcap)
            else:
                outs[si] = gen_out[idx]
        expanded = jnp.stack(outs) if outs else \
            jnp.zeros((1, vcap), jnp.uint32)

        cum = jnp.cumsum(nrows)
        total = cum[-1]
        prevs = cum - nrows                        # [K] traced starts

        # -- phases 2-3: one vmapped subgraph per group ----------------
        seg_out: Dict[Tuple[int, int], Tuple] = {}
        for sig, members in groups.items():
            mode, nullable, pkey, dlen, defer = sig
            specs_m = [specs[ci][r] for ci, r in members]
            n_m = nrows[jnp.asarray([r for _, r in members])]
            if nullable:
                lv_m = expanded[
                    jnp.asarray([s.def_stream for s in specs_m])
                ].astype(jnp.int32)
            else:
                lv_m = None

            if mode in ("dict", "dict_str"):
                idx_m = expanded[
                    jnp.asarray([s.val_stream for s in specs_m])
                ].astype(jnp.int32)
                doff_m = meta[jnp.asarray(
                    [s.m_dict_off for s in specs_m])]
                dsize_m = meta[jnp.asarray(
                    [s.m_dict_size for s in specs_m])]
                if mode == "dict" and defer:
                    # kernel 2: keep CODES (global dictionary index);
                    # the gather runs predicated on the pushed mask in
                    # phase 5 — filtered-out rows never decode
                    def one_codes(idx, lv, n_r, doff, dsize):
                        idx, valid = _def_apply(lv, idx, n_r, vcap)
                        idx = jnp.clip(idx, 0,
                                       jnp.maximum(dsize - 1, 0))
                        return doff + idx, valid

                    in_ax = (0, 0 if nullable else None, 0, 0, 0)
                    codes_m, valid_m = jax.vmap(
                        one_codes, in_axes=in_ax)(idx_m, lv_m, n_m,
                                                  doff_m, dsize_m)
                    for (ci, r), d, v in zip(members, codes_m, valid_m):
                        seg_out[(ci, r)] = (d, v)
                elif mode == "dict_str" and defer:
                    # kernel 2, strings: stitch three int32 code
                    # streams — byte base into the shared u8 matrix
                    # buffer, index into the lengths buffer, and the
                    # segment's static row stride — and gather bytes +
                    # lengths tile-wise in phase 5 once the pushed
                    # mask is known (kernels/filter_decode)
                    L = int(pkey)
                    loff_m = meta[jnp.asarray(
                        [s.m_dlen_off for s in specs_m])]

                    def one_str_codes(idx, lv, n_r, doff, dsize, loff):
                        idx, valid = _def_apply(lv, idx, n_r, vcap)
                        idx = jnp.clip(idx, 0,
                                       jnp.maximum(dsize - 1, 0))
                        bb = doff + idx * L
                        li = loff + idx
                        lw = jnp.where(valid, jnp.int32(L),
                                       jnp.int32(0))
                        return bb, li, lw, valid

                    in_ax = (0, 0 if nullable else None, 0, 0, 0, 0)
                    bb_m, li_m, lw_m, valid_m = jax.vmap(
                        one_str_codes, in_axes=in_ax)(idx_m, lv_m, n_m,
                                                      doff_m, dsize_m,
                                                      loff_m)
                    for (ci, r), b3, l3, w3, v in zip(
                            members, bb_m, li_m, lw_m, valid_m):
                        seg_out[(ci, r)] = (b3, l3, w3, v)
                elif mode == "dict":
                    dbuf = arrays["dict_" + pkey]

                    def one_dict(idx, lv, n_r, doff, dsize):
                        idx, valid = _def_apply(lv, idx, n_r, vcap)
                        idx = jnp.clip(idx, 0,
                                       jnp.maximum(dsize - 1, 0))
                        vals = jnp.take(dbuf, doff + idx)
                        return jnp.where(valid, vals, 0), valid

                    in_ax = (0, 0 if nullable else None, 0, 0, 0)
                    data_m, valid_m = jax.vmap(
                        one_dict, in_axes=in_ax)(idx_m, lv_m, n_m,
                                                 doff_m, dsize_m)
                    for (ci, r), d, v in zip(members, data_m, valid_m):
                        seg_out[(ci, r)] = (d, v)
                else:
                    L = int(pkey)
                    dbuf = arrays["dict_u8str"]
                    lbuf = arrays["dict_strlens"]
                    loff_m = meta[jnp.asarray(
                        [s.m_dlen_off for s in specs_m])]

                    def one_str(idx, lv, n_r, doff, dsize, loff):
                        idx, valid = _def_apply(lv, idx, n_r, vcap)
                        idx = jnp.clip(idx, 0,
                                       jnp.maximum(dsize - 1, 0))
                        byte_idx = ((doff + idx * L)[:, None] +
                                    jnp.arange(dlen)[None, :])
                        in_range = jnp.arange(dlen)[None, :] < L
                        mat = jnp.take(dbuf,
                                       jnp.clip(byte_idx, 0,
                                                dbuf.shape[0] - 1))
                        mat = jnp.where(valid[:, None] & in_range,
                                        mat, 0)
                        lens = jnp.take(lbuf, loff + idx)
                        return (mat, jnp.where(valid, lens,
                                               0).astype(jnp.int32),
                                valid)

                    in_ax = (0, 0 if nullable else None, 0, 0, 0, 0)
                    mat_m, lens_m, valid_m = jax.vmap(
                        one_str, in_axes=in_ax)(idx_m, lv_m, n_m,
                                                doff_m, dsize_m,
                                                loff_m)
                    for (ci, r), d, ln, v in zip(members, mat_m,
                                                 lens_m, valid_m):
                        seg_out[(ci, r)] = (d, v, ln)
            elif mode == "bool":
                bits_m = expanded[
                    jnp.asarray([s.val_stream for s in specs_m])
                ].astype(jnp.bool_)

                def one_bool(bits, lv, n_r):
                    data, valid = _def_apply(lv, bits, n_r, vcap)
                    return data & valid, valid

                data_m, valid_m = jax.vmap(
                    one_bool, in_axes=(0, 0 if nullable else None, 0)
                )(bits_m, lv_m, n_m)
                for (ci, r), d, v in zip(members, data_m, valid_m):
                    seg_out[(ci, r)] = (d, v)
            else:  # plain
                pbuf = arrays["plain_" + pkey]
                off_m = meta[jnp.asarray(
                    [s.m_plain_off for s in specs_m])]

                def one_plain(off, lv, n_r):
                    vals = jax.lax.dynamic_slice(pbuf, (off,), (vcap,))
                    data, valid = _def_apply(lv, vals, n_r, vcap)
                    return jnp.where(valid, data, 0), valid

                data_m, valid_m = jax.vmap(
                    one_plain, in_axes=(0, 0 if nullable else None, 0)
                )(off_m, lv_m, n_m)
                for (ci, r), d, v in zip(members, data_m, valid_m):
                    seg_out[(ci, r)] = (d, v)

        # -- phase 4: stitch row groups per column ---------------------
        # sequential dynamic_update_slice per segment: write k's padding
        # tail [n_k, vcap) lands in [prevs[k]+n_k, prevs[k]+vcap), which
        # write k+1 (starting at prevs[k]+n_k) fully overwrites; the
        # last segment's tail is invalid-masked zeros by construction
        cap_pad = cap + vcap

        def stitch(parts, fill):
            out = jnp.full((cap_pad,) + parts[0].shape[1:], fill,
                           dtype=parts[0].dtype)
            for k in range(K):
                start = (prevs[k],) + \
                    (jnp.int32(0),) * (parts[k].ndim - 1)
                out = jax.lax.dynamic_update_slice(out, parts[k], start)
            return out[:cap]

        cols: List[Optional[DeviceColumn]] = []
        # ci -> ('int', codes, valid) | ('str', bb, li, lw, valid, L)
        deferred_info: Dict[int, Tuple] = {}
        for ci, col_specs in enumerate(specs):
            odt = out_dtypes[ci]
            np_t = odt.to_np() if not odt.is_string else None
            col_defer = any(s.defer for s in col_specs)
            str_defer = col_defer and odt.is_string
            col_L = max((s.dlen for s in col_specs), default=1) \
                if odt.is_string else 0
            seg_data, seg_valid, seg_lens = [], [], []
            seg_li, seg_lw = [], []   # string-defer code streams
            for r, s in enumerate(col_specs):
                if s.mode == "null":
                    if col_defer:
                        seg_data.append(jnp.zeros((vcap,),
                                                  dtype=jnp.int32))
                        if str_defer:
                            seg_li.append(jnp.zeros((vcap,),
                                                    dtype=jnp.int32))
                            seg_lw.append(jnp.zeros((vcap,),
                                                    dtype=jnp.int32))
                    elif odt.is_string:
                        seg_data.append(jnp.zeros((vcap, col_L),
                                                  dtype=jnp.uint8))
                        seg_lens.append(jnp.zeros((vcap,),
                                                  dtype=jnp.int32))
                    else:
                        seg_data.append(jnp.zeros((vcap,), dtype=np_t))
                    seg_valid.append(jnp.zeros((vcap,),
                                               dtype=jnp.bool_))
                    continue
                out = seg_out[(ci, r)]
                if str_defer:
                    seg_data.append(out[0].astype(jnp.int32))  # bytebase
                    seg_li.append(out[1].astype(jnp.int32))
                    seg_lw.append(out[2].astype(jnp.int32))
                    seg_valid.append(out[3])
                elif col_defer:
                    seg_data.append(out[0].astype(jnp.int32))
                    seg_valid.append(out[1])
                elif odt.is_string:
                    d = out[0]
                    if d.shape[1] < col_L:
                        d = jnp.pad(d, ((0, 0), (0, col_L - d.shape[1])))
                    seg_data.append(d)
                    seg_valid.append(out[1])
                    seg_lens.append(out[2])
                else:
                    seg_data.append(out[0].astype(np_t))
                    seg_valid.append(out[1])

            valid = stitch(seg_valid, False)
            vb = fp.col_vbits[ci] if fp.col_vbits else None
            nn = all(not s.nullable and s.mode != "null"
                     for s in col_specs)
            if str_defer:
                deferred_info[ci] = ("str", stitch(seg_data, np.int32(0)),
                                     stitch(seg_li, np.int32(0)),
                                     stitch(seg_lw, np.int32(0)),
                                     valid, col_L)
                cols.append(None)
            elif col_defer:
                # kernel 2: hold global dictionary codes; decoded in
                # phase 5 once the pushed filter's mask is known
                deferred_info[ci] = ("int", stitch(seg_data, np.int32(0)),
                                     valid)
                cols.append(None)
            elif odt.is_string:
                data = stitch(seg_data, np.uint8(0))
                lens = stitch(seg_lens, np.int32(0))
                cols.append(DeviceColumn(odt, data, valid, lens,
                                         nonnull=nn))
            else:
                data = stitch(seg_data, np.zeros((), np_t)[()])
                cols.append(DeviceColumn(odt, data, valid, vbits=vb,
                                         nonnull=nn))

        # -- phase 5 (kernel 2): pushed-filter mask, then PREDICATED
        # -- dictionary gathers for the deferred columns --------------
        if deferred_info:
            from spark_rapids_tpu.expr import eval_tpu
            from spark_rapids_tpu.kernels import filter_decode as kfd
            cond, scan_names_t, _dn = fp.pushed
            by_name = {nm: c for nm, c in zip(fp.names, cols)
                       if c is not None}
            # placeholder for names the condition can't reference
            # (deferred / partition / fallback columns — barred by the
            # prepare-time eligibility gates)
            ph = DeviceColumn(dt.INT32, jnp.zeros((cap,), jnp.int32),
                              jnp.zeros((cap,), jnp.bool_))
            eval_batch = DeviceBatch(
                list(scan_names_t),
                [by_name.get(nm, ph) for nm in scan_names_t], total)
            cv = eval_tpu.evaluate(cond, eval_batch)
            keep = cv.data.astype(jnp.bool_) & cv.validity & \
                (jnp.arange(cap) < total)
            for ci, dinfo in deferred_info.items():
                odt = out_dtypes[ci]
                nn = all(not s.nullable and s.mode != "null"
                         for s in specs[ci])
                if dinfo[0] == "str":
                    _k, bb, li, lw, valid, col_L = dinfo
                    keepv = keep & valid
                    mat = kfd.decode_str_pallas(
                        arrays["dict_u8str"], bb, lw, keepv, col_L,
                        tile_bytes=fp.tile_bytes)
                    lens = kfd.decode_pallas(
                        arrays["dict_strlens"], li, keepv,
                        tile_bytes=fp.tile_bytes)
                    cols[ci] = DeviceColumn(
                        odt, mat, valid, lens.astype(jnp.int32),
                        nonnull=nn)
                    continue
                _k, codes, valid = dinfo
                np_t = odt.to_np()
                s0 = next(s for s in specs[ci] if s.defer)
                dbuf = arrays["dict_" + s0.plain_key]
                vals = kfd.decode_pallas(dbuf, codes, keep & valid,
                                         tile_bytes=fp.tile_bytes)
                cols[ci] = DeviceColumn(
                    odt, vals.astype(np_t), valid,
                    vbits=fp.col_vbits[ci] if fp.col_vbits else None,
                    nonnull=nn)
        return tuple(cols), total

    return kernel


# ---------------------------------------------------------------------------
# Public entry: host-prep (prepare) split from device dispatch (finish)
# so a prefetching scan can run batch k+1's footer/page walks + packed
# -page uploads while batch k's decode program is being dispatched
# ---------------------------------------------------------------------------

def _fused_list_column(sources, f, n_rows) -> Optional[DeviceColumn]:
    """Device list decode per row group + device concat for the fused
    batch; None -> host fallback."""
    from spark_rapids_tpu.columnar.batch import concat_batches
    from spark_rapids_tpu.io.device_parquet import decode_list_chunk
    try:
        per = []
        for (pf, path, rg), nr in zip(sources, n_rows):
            leaf_of = leaf_map(pf)
            if f.name not in leaf_of:
                return None
            chunk = pm.read_chunk_pages(path, rg, leaf_of[f.name],
                                        parquet_file=pf)
            col = decode_list_chunk(chunk, f.dtype,
                                    bucket_rows(max(nr, 1)),
                                    f.nullable)
            per.append(DeviceBatch([f.name], [col], nr))
        merged = concat_batches(per)
        return merged.columns[0]
    except Exception:
        return None


@dataclass
class PreparedScan:
    """Everything a fused scan batch needs EXCEPT the decode dispatch:
    assembled plan, device-resident upload set, list columns (already
    dispatch-only device work) and host-decoded fallback columns
    (already uploaded).  ``finish_fused`` turns it into a DeviceBatch
    with one kernel call — no device->host read anywhere."""
    wanted: List[str]
    total: int
    cap: int
    fp: Optional[_FusedPlan]
    dev_arrays: Optional[Dict[str, Any]]
    dev_cols: List[str]
    extra_cols: Dict[str, DeviceColumn]
    fallbacks: List[str]


def _collect_plans(sources, schema, wanted, host_threads: int,
                   metrics=None) -> Tuple[List, List[str],
                                          Dict[str, DeviceColumn]]:
    """Walk (or cache-fetch) every flat column chunk's ChunkPlan, the
    parallel host-prep stage: a thread pool of ``host_threads`` walks
    page headers / run boundaries across (column, row-group) pairs
    concurrently.  Page reads and codec decompression release the GIL,
    so the walks genuinely overlap."""
    from spark_rapids_tpu.io import scan_cache as sc

    # key on the stamp each footer was PARSED under (handle_key), not a
    # fresh stat: a file rewritten mid-scan must never cache plans built
    # from the stale footer's offsets under its new (mtime, size) key
    skeys = {path: sc.handle_key(pf, path)
             for pf, path, _ in sources}

    flat_cols = [c for c in wanted if not schema.field(c).dtype.is_list]

    def plan_one(c, si):
        pf, path, rg = sources[si]
        leaf_of = leaf_map(pf)
        if c not in leaf_of:
            return None
        return sc.get_chunk_plan(skeys[path], path, rg, leaf_of[c],
                                 schema.field(c).dtype, False, pf,
                                 metrics=metrics)

    def run(item):
        c, si = item
        try:
            return plan_one(c, si)
        except Exception as e:
            return e

    tasks = [(c, si) for c in flat_cols for si in range(len(sources))]
    results: Dict[Tuple[str, int], Any] = {}
    if host_threads > 1 and len(tasks) > 1:
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(
                max_workers=min(host_threads, len(tasks)),
                thread_name_prefix="scan-hostprep") as pool:
            outs = list(pool.map(run, tasks))
    else:
        outs = [run(t) for t in tasks]
    for (c, si), out in zip(tasks, outs):
        results[(c, si)] = out

    n_rows = [pf.metadata.row_group(rg).num_rows
              for pf, _, rg in sources]
    plans: List[Optional[List[Optional[ChunkPlan]]]] = []
    fallbacks: List[str] = []
    list_cols: Dict[str, DeviceColumn] = {}
    for c in wanted:
        f = schema.field(c)
        if f.dtype.is_list:
            # list columns decode per row group via the dedicated
            # rep/def path and concatenate on device
            col = _fused_list_column(sources, f, n_rows)
            if col is not None:
                list_cols[c] = col
            else:
                fallbacks.append(c)
            plans.append(None)
            continue
        col_plans = [results[(c, si)] for si in range(len(sources))]
        if any(isinstance(p, Exception) for p in col_plans):
            fallbacks.append(c)
            plans.append(None)
        else:
            plans.append(col_plans)
    return plans, fallbacks, list_cols


def prepare_fused(sources: Sequence[Tuple[Any, str, int]],
                  schema: Schema,
                  columns: Optional[List[str]] = None,
                  host_threads: int = 1,
                  metrics=None,
                  backend: Optional[str] = None,
                  pushed_filter=None,
                  scan_names: Optional[List[str]] = None
                  ) -> PreparedScan:
    """Host half of the fused decode: footer/page walks (through the
    scan-plan cache when enabled), fused-plan assembly, packed-page
    upload, and the host-Arrow fallback decode.  Safe to run on a
    prefetch thread: it never reads device memory.

    ``backend`` picks the kernel backend (``kernel.backend``) for the
    decode program; ``pushed_filter``/``scan_names`` arm the kernel-2
    deferred dictionary-decode+filter (see ``assemble``) — an
    optimization hint with per-batch eligibility checks here, never a
    contract: any ineligibility simply decodes everything as before."""
    import contextlib
    from spark_rapids_tpu.columnar.batch import from_arrow as _fa
    from spark_rapids_tpu.exec.base import timed_extra
    from spark_rapids_tpu.kernels import backend as kb

    def phase(key):
        return timed_extra(metrics, key) if metrics is not None \
            else contextlib.nullcontext()

    wanted = columns or [f.name for f in schema.fields]
    out_dtypes = [schema.field(c).dtype for c in wanted]
    n_rows = [pf.metadata.row_group(rg).num_rows
              for pf, _, rg in sources]
    bk = kb.resolve(backend)

    with phase("scan.hostPrepTime"):
        plans, fallbacks, list_cols = _collect_plans(
            sources, schema, wanted, host_threads, metrics=metrics)

        dev_cols = [c for c, p in zip(wanted, plans) if p is not None]
        dev_dtypes = [d for d, p in zip(out_dtypes, plans)
                      if p is not None]
        dev_plans = [p for p in plans if p is not None]

        total = sum(n_rows)
        cap = bucket_rows(max(total, 1))

        pushed = None
        if pushed_filter is not None and bk == kb.PALLAS:
            # every column the condition reads must be device-decoded
            # in THIS batch (a fallback/list/partition operand would
            # evaluate against a placeholder) — ineligible batches keep
            # the ordinary decode, per-kernel-fallback style
            from spark_rapids_tpu.expr import ir as _ir
            ref_names = {scan_names[b.ordinal] for b in _ir.collect(
                pushed_filter,
                lambda e: isinstance(e, _ir.BoundReference))}
            if ref_names <= set(dev_cols):
                pushed = pushed_filter
            else:
                kb.fallback("scan.filterDecode", "condition_columns")

        fp = assemble(dev_plans, dev_dtypes, dev_cols, n_rows,
                      backend=bk, pushed_filter=pushed,
                      scan_names=scan_names) \
            if dev_plans else None
        if fp is not None and fp.pushed is not None:
            kb.hit("scan.filterDecode")

    with phase("scan.uploadTime"):
        dev_arrays = {k: jnp.asarray(v) for k, v in fp.arrays.items()} \
            if fp is not None else None
        if fp is not None:
            # upload-byte accounting: global counter + tenant ledger,
            # same n (the exactness invariant)
            from spark_rapids_tpu.obs import accounting as _acct
            from spark_rapids_tpu.obs import registry as _obsreg
            up = sum(int(getattr(v, "nbytes", 0))
                     for v in fp.arrays.values())
            if up:
                _obsreg.get_registry().inc("scan.bytesUploaded", up)
                _acct.charge("scan.bytesUploaded", up)

        extra_cols: Dict[str, DeviceColumn] = dict(list_cols)
        if fallbacks:
            import pyarrow.parquet as papq
            from spark_rapids_tpu.io import scan_cache as sc
            opened: Dict[str, Any] = {}

            def reader(pf, path):
                # one transient open per path for the whole fallback
                # merge (FooterInfo.read_row_group re-opens per call)
                if isinstance(pf, sc.FooterInfo):
                    if path not in opened:
                        opened[path] = papq.ParquetFile(path)
                    return opened[path]
                return pf
            try:
                tables = []
                for pf, path, rg in sources:
                    leaf_of2 = leaf_map(pf)
                    present = [c for c in fallbacks if c in leaf_of2]
                    t = reader(pf, path).read_row_group(
                        rg, columns=present) if present else pa.table({})
                    arrs = []
                    for c in fallbacks:
                        f = schema.field(c)
                        if c in present:
                            arrs.append(
                                _cast_one(t.select([c]), f).column(0))
                        else:
                            arrs.append(
                                pa.nulls(t.num_rows if present
                                         else pf.metadata.row_group(rg)
                                         .num_rows,
                                         type=f.dtype.to_arrow()))
                    tables.append(pa.Table.from_arrays(
                        arrs, names=list(fallbacks)))
            finally:
                for f in opened.values():
                    f.close()
            merged = pa.concat_tables(tables)
            fb = _fa(merged, capacity=cap)
            for name, col in zip(fb.names, fb.columns):
                extra_cols[name] = col

    return PreparedScan(wanted=wanted, total=total, cap=cap, fp=fp,
                        dev_arrays=dev_arrays, dev_cols=dev_cols,
                        extra_cols=extra_cols, fallbacks=fallbacks)


def finish_fused(prep: PreparedScan) -> Tuple[DeviceBatch, List[str]]:
    """Device half: ONE fused kernel dispatch over the prepared upload
    set (dispatch-only — the terminal collect barrier does the read)."""
    cols_by_name: Dict[str, DeviceColumn] = dict(prep.extra_cols)
    if prep.fp is not None:
        from spark_rapids_tpu.exec import kernel_cache as kc
        fp = prep.fp
        kern = kc.get_kernel(fp.key, lambda: _make_kernel(fp),
                             backend=fp.backend)
        out_cols, _ = kern(prep.dev_arrays)
        for name, col in zip(prep.dev_cols, out_cols):
            cols_by_name[name] = col
    out = DeviceBatch(
        prep.wanted, [cols_by_name[c] for c in prep.wanted], prep.total)
    return out, prep.fallbacks


def decode_row_groups_fused(sources: Sequence[Tuple[Any, str, int]],
                            schema: Schema,
                            columns: Optional[List[str]] = None,
                            host_threads: int = 1,
                            metrics=None
                            ) -> Tuple[DeviceBatch, List[str]]:
    """Decode several (parquet_file, path, row_group) sources into ONE
    DeviceBatch with one fused kernel (+ a host-decoded column merge for
    anything the device path can't cover).

    Returns (batch, fallback_column_names)."""
    return finish_fused(prepare_fused(sources, schema, columns=columns,
                                      host_threads=host_threads,
                                      metrics=metrics))
