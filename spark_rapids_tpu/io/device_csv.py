"""Device-side CSV decode: byte-tensor delimiter scan in HBM.

Reference analog: ``GpuBatchScanExec`` decodes CSV on device via
``Table.readCSV`` (reference: GpuBatchScanExec.scala:465, libcudf's CUDA
CSV parser).  The TPU formulation keeps the O(bytes) work in vector
ops:

  * the raw file bytes upload ONCE as a uint8 tensor,
  * ONE kernel finds every delimiter/newline with an elementwise
    compare, ranks them with a cumsum, and scatters their positions
    into a [rows, cols] boundary matrix (no sort, no per-byte host
    work),
  * per column, a static-width byte window gathers the field and a
    fixed-step fold (v = v*10 + digit) parses ints/floats exactly —
    per-row Python never runs.

The host does an O(bytes) vectorized numpy prescan only to SIZE the
static shapes (row count, per-column width buckets) and to detect
dialects the kernel doesn't do (quoted fields, ragged rows, exotic
numerics) — those fall back to the Arrow CSV reader per file, the same
per-operator fallback philosophy as the parquet path.

Coverage: int32/int64/float32/float64 (fixed-point, optional sign,
optional fraction; NaN/Inf/exponent fall back), bool (true/false),
strings, empty-string nulls, trailing ``\\r`` (CRLF), header skip.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import (DeviceBatch, DeviceColumn,
                                             _bucket_strlen, bucket_rows)
from spark_rapids_tpu.plan.logical import Schema


class UnsupportedCsv(Exception):
    pass


def prescan(raw: bytes, n_cols: int, sep: bytes = b",",
            header: bool = True):
    """Vectorized host prescan: row count, per-column width buckets,
    dialect checks.  O(bytes) numpy, no per-field work."""
    a = np.frombuffer(raw, dtype=np.uint8)
    if header:
        # strip the header BEFORE the quote check: writers commonly
        # quote column names while leaving data unquoted
        first_nl = int(np.argmax(a == 0x0A)) if 0x0A in a[:1 << 20] \
            else -1
        if first_nl < 0:
            raise UnsupportedCsv("no header newline")
        a = a[first_nl + 1:]
    if np.any(a == ord('"')):
        raise UnsupportedCsv("quoted fields")
    if a.shape[0] and a[-1] != 0x0A:
        a = np.concatenate([a, np.array([0x0A], np.uint8)])
    is_nl = a == 0x0A
    n_rows = int(is_nl.sum())
    if n_rows == 0:
        return a, 0, [1] * n_cols
    is_delim = (a == sep[0]) | is_nl
    pos = np.flatnonzero(is_delim)
    if pos.shape[0] != n_rows * n_cols:
        raise UnsupportedCsv("ragged rows")
    bounds = pos.reshape(n_rows, n_cols)
    starts = np.empty_like(bounds)
    starts[:, 1:] = bounds[:, :-1] + 1
    starts[0, 0] = 0
    starts[1:, 0] = bounds[:-1, -1] + 1
    widths = (bounds - starts).max(axis=0)
    return a, n_rows, [max(int(w), 1) for w in widths]


@partial(jax.jit, static_argnames=("n_cols", "cap", "widths",
                                   "dtypes_key", "sep", "parse_cols"))
def _decode_kernel(raw: jnp.ndarray, n_rows, n_cols: int, cap: int,
                   widths: Tuple[int, ...], dtypes_key: Tuple[str, ...],
                   sep: int, parse_cols: Tuple[int, ...]):
    """ONE program: delimiter scan -> boundary matrix -> per-column
    parse.  Shapes are static buckets only; the exact row count is a
    traced operand so the compile cache hits across files."""
    nb = raw.shape[0]
    is_nl = raw == jnp.uint8(0x0A)
    is_delim = (raw == jnp.uint8(sep)) | is_nl
    # rank every delimiter and scatter its byte position
    did = jnp.cumsum(is_delim.astype(jnp.int32)) - 1
    tgt = jnp.where(is_delim, did, cap * n_cols)
    bounds = jnp.full((cap * n_cols + 1,), nb,
                      dtype=jnp.int32).at[tgt].set(
        jnp.arange(nb, dtype=jnp.int32), mode="drop")[:-1]
    bounds = bounds.reshape(cap, n_cols)
    starts = jnp.concatenate(
        [jnp.concatenate([jnp.zeros((1, 1), jnp.int32),
                          bounds[:-1, -1:] + 1]),
         bounds[:, :-1] + 1], axis=1)
    lens = bounds - starts
    # strip trailing \r (CRLF) from the LAST field of each row
    last_byte = jnp.take(
        raw, jnp.clip(bounds[:, -1] - 1, 0, nb - 1))
    lens = lens.at[:, -1].add(
        jnp.where((last_byte == 0x0D) & (lens[:, -1] > 0), -1, 0))

    row_pad = jnp.arange(cap) < n_rows
    out = []
    # column pruning: the delimiter scan covers every column, but the
    # gather+parse runs only for requested ones
    for c in parse_cols:
        F = widths[c]
        st = jnp.where(row_pad, starts[:, c], 0)
        ln = jnp.where(row_pad, lens[:, c], 0)
        idx = st[:, None] + jnp.arange(F, dtype=jnp.int32)[None, :]
        in_field = jnp.arange(F)[None, :] < ln[:, None]
        mat = jnp.where(
            in_field & row_pad[:, None],
            jnp.take(raw, jnp.clip(idx, 0, nb - 1)), 0)
        out.append(_parse_column(mat, ln, row_pad, dtypes_key[c], F))
    return tuple(out)


def _parse_column(mat: jnp.ndarray, ln: jnp.ndarray,
                  row_pad: jnp.ndarray, dkey: str, F: int):
    """(data, validity[, lengths, ok]) for one column; `ok` is a scalar
    False when a field used syntax the kernel doesn't parse."""
    empty = ln == 0
    if dkey == "string":
        valid = row_pad & ~empty
        return (jnp.where(valid[:, None], mat, 0), valid,
                jnp.where(valid, ln, 0).astype(jnp.int32),
                jnp.bool_(True))
    if dkey == "bool":
        def word(wd: bytes):
            m = ln == len(wd)
            for j, byte in enumerate(wd):
                if j < F:
                    m = m & ((mat[:, j] | 0x20) == (byte | 0x20))
                else:
                    m = jnp.zeros_like(m)
            return m
        is_t = word(b"true")
        is_f = word(b"false")
        valid = row_pad & ~empty & (is_t | is_f)
        ok = jnp.all(~row_pad | empty | is_t | is_f)
        return is_t & valid, valid, None, ok

    # numeric: [-]digits[.digits]
    neg = mat[:, 0] == ord("-")
    digit = mat - ord("0")
    is_digit = (digit >= 0) & (digit <= 9)
    is_dot = mat == ord(".")
    pos_in = jnp.arange(F)[None, :]
    in_field = pos_in < ln[:, None]
    legal = ~in_field | is_digit | is_dot | \
        ((pos_in == 0) & neg[:, None])
    ok = jnp.all(legal | ~row_pad[:, None])
    one_dot = jnp.sum((is_dot & in_field).astype(jnp.int32),
                      axis=1) <= 1
    ok = ok & jnp.all(one_dot | ~row_pad)

    dot_pos = jnp.min(jnp.where(is_dot & in_field, pos_in,
                                jnp.int32(F)), axis=1)
    int_v = jnp.zeros(mat.shape[0], dtype=jnp.int64)
    frac_v = jnp.zeros(mat.shape[0], dtype=jnp.int64)
    frac_n = jnp.zeros(mat.shape[0], dtype=jnp.int32)
    n_dig = jnp.zeros(mat.shape[0], dtype=jnp.int32)
    for i in range(F):
        d = digit[:, i].astype(jnp.int64)
        take_int = is_digit[:, i] & (i < ln) & (i < dot_pos)
        take_frac = is_digit[:, i] & (i < ln) & (i > dot_pos)
        int_v = jnp.where(take_int, int_v * 10 + d, int_v)
        frac_v = jnp.where(take_frac, frac_v * 10 + d, frac_v)
        frac_n = frac_n + take_frac.astype(jnp.int32)
        n_dig = n_dig + (take_int | take_frac).astype(jnp.int32)
    # a bare '-' / '.' is NOT a number, and >18 digits would silently
    # wrap the int64 fold — both host-fallback instead
    ok = ok & jnp.all((n_dig >= 1) | empty | ~row_pad)
    ok = ok & jnp.all((n_dig <= 18) | ~row_pad)
    valid = row_pad & ~empty
    if dkey in ("int32", "int64"):
        # a '.' in an integer column falls back
        ok = ok & jnp.all(dot_pos >= jnp.where(row_pad, ln, 0))
        v = jnp.where(neg, -int_v, int_v)
        v = jnp.where(valid, v, 0)
        if dkey == "int32":
            # the 18-digit guard only protects the int64 fold; values
            # outside int32 range would silently wrap on the device cast
            # — route them to the host fallback like other unsupported
            # numerics
            in_range = (v >= jnp.int64(-2**31)) & (v <= jnp.int64(2**31 - 1))
            ok = ok & jnp.all(in_range | ~row_pad)
            v = v.astype(jnp.int32)
        return v, valid, None, ok
    v = int_v.astype(jnp.float64) + \
        frac_v.astype(jnp.float64) / (10.0 ** frac_n.astype(jnp.float64))
    v = jnp.where(neg, -v, v)
    v = jnp.where(valid, v, 0.0)
    if dkey == "float32":
        v = v.astype(jnp.float32)
    return v, valid, None, ok


_DKEY = {dt.TypeId.INT32: "int32", dt.TypeId.INT64: "int64",
         dt.TypeId.FLOAT32: "float32", dt.TypeId.FLOAT64: "float64",
         dt.TypeId.BOOL: "bool", dt.TypeId.STRING: "string"}


def decode_csv(path: str, schema: Schema,
               columns: Optional[List[str]] = None, sep: str = ",",
               header: bool = True) -> Tuple[DeviceBatch, List[str]]:
    """Decode one CSV file to a DeviceBatch (raises UnsupportedCsv for
    dialects the kernel doesn't cover — caller falls back to Arrow).

    Returns (batch, fallback_columns): columns whose runtime content
    used unsupported numeric syntax are re-decoded on host."""
    wanted = columns or [f.name for f in schema.fields]
    all_names = [f.name for f in schema.fields]
    for f in schema.fields:
        if f.dtype.id not in _DKEY:
            raise UnsupportedCsv(f"dtype {f.dtype.name}")
    with open(path, "rb") as fh:
        raw = fh.read()
    a, n_rows, widths = prescan(raw, len(all_names),
                                sep.encode(), header)
    cap = bucket_rows(max(n_rows, 1))
    bcap = bucket_rows(max(a.shape[0], 64), 64)
    dev_raw = jnp.asarray(np.concatenate(
        [a, np.zeros(bcap - a.shape[0], np.uint8)]))
    widths_b = tuple(_bucket_strlen(w) for w in widths)
    dkeys = tuple(_DKEY[f.dtype.id] for f in schema.fields)
    parse_cols = tuple(i for i, nme in enumerate(all_names)
                       if nme in wanted)
    outs = _decode_kernel(dev_raw, jnp.int32(n_rows),
                          n_cols=len(all_names), cap=cap,
                          widths=widths_b, dtypes_key=dkeys,
                          sep=ord(sep), parse_cols=parse_cols)
    out_by_idx = dict(zip(parse_cols, outs))

    # one tiny read for the per-column ok flags
    oks = [bool(x) for x in np.asarray(
        jnp.stack([o[3] for o in outs]))]
    fallbacks = [all_names[i] for i, okf in zip(parse_cols, oks)
                 if not okf]
    host_cols = {}
    if fallbacks:
        from spark_rapids_tpu.io.readers import _normalize, _read_csv
        fb_schema = Schema([schema.field(n) for n in fallbacks])
        t = _normalize(_read_csv(path, {"header": header, "sep": sep}),
                       fb_schema, permissive=True)
        from spark_rapids_tpu.columnar.batch import from_arrow
        sub = from_arrow(t, capacity=cap)
        host_cols = dict(zip(sub.names, sub.columns))

    cols, names = [], []
    for i, (name, f) in enumerate(zip(all_names, schema.fields)):
        if name not in wanted:
            continue
        o = out_by_idx[i]
        if name in host_cols:
            cols.append(host_cols[name])
        elif f.dtype.is_string:
            cols.append(DeviceColumn(f.dtype, o[0], o[1],
                                     o[2]))
        else:
            cols.append(DeviceColumn(f.dtype, o[0], o[1], None))
        names.append(name)
    return DeviceBatch(names, cols, n_rows), fallbacks
